from automodel_tpu.models.llama_bidirectional.model import (
    LlamaBidirectionalConfig,
    LlamaBidirectionalModel,
)

__all__ = ["LlamaBidirectionalConfig", "LlamaBidirectionalModel"]
