"""Backend switchboard (reference BackendConfig, models/common/utils.py:139).

The reference toggles between TE/flex/SDPA attention, Triton/gmm experts, fused losses.
On TPU the choices collapse to: XLA einsum vs Pallas kernels, and how to rematerialize.
One config object threads through every model family.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = ["BackendConfig"]

# policy name -> jax.checkpoint policy ("full" = no remat; None = remat everything)
_REMAT_POLICIES = {
    "none": None,
    "dots": jax.checkpoint_policies.checkpoint_dots,
    "dots_no_batch": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    # save ONLY the two fat MLP projections (gate/up): their matmuls are ~half a
    # layer's forward FLOPs, so keeping just them cuts the backward replay almost
    # as much as "dots" at a fraction of its footprint. The middle ground between
    # "none" (replay everything, minimal memory) and "dots" (replay nothing,
    # ~2.8x the activation footprint).
    "mlp_dots": jax.checkpoint_policies.save_only_these_names("mlp_gate", "mlp_up"),
    # half of mlp_dots: fits alongside losses that still materialize logits
    "mlp_gate_dot": jax.checkpoint_policies.save_only_these_names("mlp_gate"),
    "mlp_gate_attn": jax.checkpoint_policies.save_only_these_names("mlp_gate", "attn_out"),
    # save only the post-activation (tokens*K, I) expert tensor — HALF of
    # mlp_gate_dot's (tokens*K, 2I) footprint for gated experts. The down-proj
    # backward reads it saved; only the gate_up GEMM + activation replay. The
    # MoE-tuned rung: with the Pallas grouped GEMM (custom VJP, no saved
    # intermediates of its own) this is the cheapest save that still skips the
    # fattest recompute, so the tuner can trade it against dots/none.
    "mlp_act_dot": jax.checkpoint_policies.save_only_these_names("mlp_act"),
    # additionally keep k/v + the attention output: replay shrinks to the q
    # projection + elementwise (q is recomputed for the flash backward; saving it
    # too was measured 20MB over the 15.75G HBM line at the 1B bench shape)
    "mlp_attn_dots": jax.checkpoint_policies.save_only_these_names(
        "mlp_gate", "mlp_up", "attn_k", "attn_v", "attn_out"
    ),
    "full": "full",
}


@dataclasses.dataclass
class BackendConfig:
    """Compute-backend knobs shared by all model families.

    attention:    "xla" (einsum softmax) | "flash" (Pallas, TPU only)
    remat_policy: "none" | "dots" | "dots_no_batch" | "full"
    scan_layers:  stack layer params and lax.scan over them (fast compiles, PP-friendly)
    dtype:        activation/param compute dtype (bf16 default; optimizer keeps fp32 master)
    """

    attention: str = "xla"
    # pass segment ids into the attention mask. True is always correct; False is
    # a fast path for RIGHT-PADDED UNPACKED batches, where causal masking alone
    # already stops real tokens from attending to pads (pads sit after every
    # real token; pad rows' outputs are loss-masked). Packed sequences NEED it
    # on — the recipe guards that combination.
    attention_segments: bool = True
    # "allgather": rely on XLA SPMD to gather k/v across the cp axis (always
    # correct). "ring": ppermute ring attention over cp (overlaps comm with
    # compute; full/causal GQA attention without sinks/soft-cap/traced windows)
    context_parallel: str = "allgather"
    # "default" (einsum) | "fp8" (e4m3/e5m2 dynamic scaling). fp8 covers the dense
    # attention/MLP projections; MoE expert GEMMs keep their own experts_backend.
    linear: str = "default"
    remat_policy: str = "none"
    scan_layers: bool = True
    dtype: str = "bfloat16"
    # MoE knobs (used by MoE families only). "ragged_dot" is XLA's native ragged
    # matmul (the megablocks/gmm equivalent); "pallas" routes the same sorted
    # layout through the blocked Pallas grouped GEMM (ops/pallas/grouped_gemm.py:
    # hand-scheduled tiles, fused custom-VJP backward, per-shape ragged_dot
    # fallback); "dense" is the GShard one-hot einsum path.
    experts_backend: str = "ragged_dot"  # "ragged_dot" | "pallas" | "dense"
    dispatcher: str = "dense"  # "dense" (GSPMD ragged/one-hot) | "a2a" (EP all_to_all)
    # a2a only: per-destination-rank send capacity = ep_capacity_factor * T * K / ep.
    # Overflow copies are dropped AND reported (stats["dropped_token_frac"]).
    ep_capacity_factor: float = 1.5
    # a2a only: split dispatch/combine into this many capacity slices so chunk
    # i's expert GEMM overlaps chunk i+1's all_to_all (XLA's latency-hiding
    # scheduler overlaps them once the dependency graph allows it). 1 = one
    # monolithic a2a. Token selection and dropped_frac are EXACT under any
    # chunk count (routing/capacity math happens before slicing).
    a2a_chunks: int = 1
    fake_balanced_gate: bool = False  # benchmark mode: uniform routing, no gate math
    fake_gate_noise: float = 0.0

    def __post_init__(self):
        if self.linear not in ("default", "fp8"):
            raise ValueError(f"unknown linear backend {self.linear!r} (default | fp8)")
        if self.context_parallel not in ("allgather", "ring"):
            raise ValueError(
                f"unknown context_parallel {self.context_parallel!r} (allgather | ring)"
            )
        if self.experts_backend not in ("ragged_dot", "pallas", "dense"):
            raise ValueError(
                f"unknown experts_backend {self.experts_backend!r} "
                "(ragged_dot | pallas | dense)"
            )
        if self.dispatcher not in ("dense", "a2a"):
            raise ValueError(f"unknown dispatcher {self.dispatcher!r} (dense | a2a)")
        if int(self.a2a_chunks) < 1:
            raise ValueError(f"a2a_chunks must be >= 1, got {self.a2a_chunks}")

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    def layer_remat(self, fn):
        """Wrap a layer fn with jax.checkpoint per the policy."""
        if self.remat_policy not in _REMAT_POLICIES:
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r} (choose from {list(_REMAT_POLICIES)})"
            )
        policy = _REMAT_POLICIES[self.remat_policy]
        if policy == "full":
            return fn
        return jax.checkpoint(fn, policy=policy)
