"""Unified trace timeline: one Chrome-trace JSON for a run's whole life.

Per-step metrics answer "how fast"; the timeline answers "what happened when".
Every durable phase (compile, step, eval, checkpoint, rollback) becomes a
complete event and every async incident (stall, preemption, resilience events)
an instant event, all in ``out_dir/timeline.json`` using the Chrome
trace-event format — drop the file into Perfetto (ui.perfetto.dev) or
``chrome://tracing`` and the run is one picture.

Timestamps are microseconds of ``time.perf_counter`` relative to timeline
construction; ``pid`` is the JAX process index so multi-host traces merge into
one view. The writer is bounded (``max_events``, drops counted, never raises)
and atomic (tmp + rename), so a mid-run copy of the file always parses.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from typing import Any

logger = logging.getLogger(__name__)

__all__ = ["TraceTimeline"]


def _jsonable_args(args: dict[str, Any]) -> dict[str, Any]:
    out = {}
    for k, v in args.items():
        if isinstance(v, (int, str, bool)) or v is None:
            out[k] = v
        elif isinstance(v, float):
            out[k] = v if v == v and abs(v) != float("inf") else None
        else:
            out[k] = str(v)
    return out


class TraceTimeline:
    """Bounded, atomically-written Chrome trace-event collector.

    ``path=None`` (non-main processes) degrades every method to a no-op, the
    same contract MetricLogger uses.
    """

    def __init__(self, path: str | None, pid: int = 0,
                 max_events: int = 20000, flush_every: int = 256):
        self.path = path
        self.pid = int(pid)
        self.max_events = int(max_events)
        self.flush_every = int(flush_every)
        self.dropped = 0
        self._events: list[dict[str, Any]] = []
        self._t0 = time.perf_counter()
        self._since_flush = 0
        if path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    @property
    def enabled(self) -> bool:
        return self.path is not None

    def now(self) -> float:
        """Seconds since timeline start — pair with ``complete(start_s=...)``."""
        return time.perf_counter() - self._t0

    # ------------------------------------------------------------------ emit
    def complete(self, name: str, cat: str, start_s: float, dur_s: float,
                 tid: int = 0, **args: Any) -> None:
        """A span with explicit start/duration (Chrome phase "X")."""
        self._push({
            "name": name, "cat": cat, "ph": "X",
            "ts": round(start_s * 1e6, 1), "dur": round(max(dur_s, 0.0) * 1e6, 1),
            "pid": self.pid, "tid": tid,
            "args": _jsonable_args(args),
        })

    def instant(self, name: str, cat: str = "event", tid: int = 0, **args: Any) -> None:
        """A zero-duration incident marker (Chrome phase "i", process scope)."""
        self._push({
            "name": name, "cat": cat, "ph": "i", "s": "p",
            "ts": round(self.now() * 1e6, 1),
            "pid": self.pid, "tid": tid,
            "args": _jsonable_args(args),
        })

    def counter(self, name: str, tid: int = 0, **values: Any) -> None:
        """A counter sample (Chrome phase "C"): Perfetto renders each series in
        ``values`` as a stacked track over time — how hbm_gib_in_use/peak
        become a picture instead of a column of numbers."""
        self._push({
            "name": name, "cat": "counter", "ph": "C",
            "ts": round(self.now() * 1e6, 1),
            "pid": self.pid, "tid": tid,
            "args": _jsonable_args(values),
        })

    def counters_from_flat(self, flat: dict[str, Any], prefix: str = "dynamics",
                           tid: int = 0) -> None:
        """Fan a flat ``<prefix>/<group>/<metric>`` row into per-metric counter
        tracks: one Chrome counter per metric, one series per group — so
        ``dynamics/layers.mlp/grad_norm`` and its siblings render as a stacked
        ``dynamics/grad_norm`` track with a line per layer bucket."""
        by_metric: dict[str, dict[str, Any]] = {}
        for key, val in flat.items():
            parts = key.split("/")
            if len(parts) != 3 or parts[0] != prefix:
                continue
            by_metric.setdefault(parts[2], {})[parts[1]] = val
        for metric, series in by_metric.items():
            self.counter(f"{prefix}/{metric}", tid=tid, **series)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "phase", tid: int = 0, **args: Any):
        """Context manager emitting a complete event for the wrapped block."""
        t0 = self.now()
        try:
            yield
        finally:
            self.complete(name, cat, t0, self.now() - t0, tid=tid, **args)

    def _push(self, event: dict[str, Any]) -> None:
        if self.path is None:
            return
        if len(self._events) >= self.max_events:
            self.dropped += 1
            return
        self._events.append(event)
        self._since_flush += 1
        if self._since_flush >= self.flush_every:
            self.write()

    # ----------------------------------------------------------------- output
    def write(self) -> None:
        """Atomic snapshot of everything collected so far; safe to call anytime."""
        if self.path is None:
            return
        self._since_flush = 0
        doc = {"traceEvents": list(self._events), "displayTimeUnit": "ms"}
        if self.dropped:
            doc["droppedEventCount"] = self.dropped
        tmp = f"{self.path}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
        except Exception:
            logger.exception("timeline write failed (run continues)")

    def close(self) -> None:
        self.write()
