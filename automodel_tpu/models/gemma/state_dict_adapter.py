"""Gemma 2/3 HF key/layout mapping (llama-style projections + sandwich norms)."""

from __future__ import annotations

from automodel_tpu.models.common.state_dict import Entry, MappingAdapter
from automodel_tpu.models.gemma.model import GemmaConfig
from automodel_tpu.models.llama.state_dict_adapter import (
    _o_in,
    _o_out,
    _proj_in,
    _proj_out,
    _t,
)

__all__ = ["GemmaStateDictAdapter"]


class GemmaStateDictAdapter(MappingAdapter):
    """Maps bare text-model keys; :meth:`from_hf` also accepts multimodal
    Gemma3ForConditionalGeneration checkpoints by stripping the language-model
    prefix (both the pre- and post-4.52 transformers layouts) and dropping the
    vision tower/projector tensors — the text backbone loads, vision does not."""

    _MM_PREFIXES = ("language_model.model.", "model.language_model.")

    def from_hf(self, tensors, dtype=None) -> dict:
        if "model.embed_tokens.weight" not in tensors and any(
            k.startswith(p) for k in tensors for p in self._MM_PREFIXES
        ):
            remapped = {}
            for k, v in tensors.items():
                for p in self._MM_PREFIXES:
                    if k.startswith(p):
                        remapped["model." + k[len(p):]] = v
                        break
                else:
                    if k in ("language_model.lm_head.weight", "lm_head.weight"):
                        remapped["lm_head.weight"] = v
                    # else: vision tower / multi_modal_projector — dropped
            tensors = remapped
        return super().from_hf(tensors, dtype)

    def __init__(self, cfg: GemmaConfig, scan_layers: bool = True):
        n, k, h = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        pre = "model.layers.{i}"
        entries = [
            Entry("model.embed_tokens.weight", "embed"),
            Entry("model.norm.weight", "final_norm"),
            Entry(f"{pre}.input_layernorm.weight", "layers.attn_norm"),
            Entry(f"{pre}.post_attention_layernorm.weight", "layers.post_attn_norm"),
            Entry(f"{pre}.pre_feedforward_layernorm.weight", "layers.pre_ffn_norm"),
            Entry(f"{pre}.post_feedforward_layernorm.weight", "layers.post_ffn_norm"),
            Entry(f"{pre}.self_attn.q_proj.weight", "layers.wq", _proj_in(n, h), _proj_out(n, h)),
            Entry(f"{pre}.self_attn.k_proj.weight", "layers.wk", _proj_in(k, h), _proj_out(k, h)),
            Entry(f"{pre}.self_attn.v_proj.weight", "layers.wv", _proj_in(k, h), _proj_out(k, h)),
            Entry(f"{pre}.self_attn.o_proj.weight", "layers.wo", _o_in(n, h), _o_out(n, h)),
            Entry(f"{pre}.mlp.gate_proj.weight", "layers.w_gate", _t, _t),
            Entry(f"{pre}.mlp.up_proj.weight", "layers.w_up", _t, _t),
            Entry(f"{pre}.mlp.down_proj.weight", "layers.w_down", _t, _t),
        ]
        if cfg.qk_norm:
            entries += [
                Entry(f"{pre}.self_attn.q_norm.weight", "layers.q_norm"),
                Entry(f"{pre}.self_attn.k_norm.weight", "layers.k_norm"),
            ]
        if not cfg.tie_word_embeddings:
            entries.append(Entry("lm_head.weight", "lm_head", _t, _t))
        super().__init__(entries, cfg.num_hidden_layers, scan_layers)
