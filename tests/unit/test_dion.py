"""Dion optimizer: orthonormal low-rank updates, mixed grouping, descent."""

import numpy as np

import jax
import jax.numpy as jnp
import optax

from automodel_tpu.optim.dion import build_dion_optimizer, dion


class TestDion:
    def test_update_is_orthonormal_low_rank(self):
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(32, 16).astype(np.float32))
        g = jnp.asarray(rng.randn(32, 16).astype(np.float32))
        tx = dion(0.1, rank_fraction=0.5)
        state = tx.init({"w": w})
        upd, state = tx.update({"w": g}, state)
        u = np.asarray(upd["w"]) / -0.1 / np.sqrt(32 / 16)
        # u = P Q^T with P orthonormal (rows x r), Q col-normalized -> rank <= r
        r = 8
        s = np.linalg.svd(u, compute_uv=False)
        assert (s[r:] < 1e-4).all()

    def test_stacked_leaves_vmapped(self):
        rng = np.random.RandomState(1)
        w = jnp.asarray(rng.randn(4, 16, 8).astype(np.float32))  # (layers, m, n)
        tx = dion(0.1)
        state = tx.init({"w": w})
        upd, _ = tx.update({"w": w}, state)
        assert upd["w"].shape == (4, 16, 8)

    def test_mixed_groups_descend(self):
        """Tiny regression: dion on the matrix, adamw on bias/embedding — loss drops."""
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(64, 8).astype(np.float32))
        w_true = rng.randn(8, 4).astype(np.float32)
        y = x @ jnp.asarray(w_true)  # realizable: optimum loss ~0
        params = {
            "w_proj": jnp.asarray(rng.randn(8, 4).astype(np.float32) * 0.1),
            "bias": jnp.zeros((4,), jnp.float32),
            "embed": jnp.asarray(rng.randn(10, 8).astype(np.float32) * 0.1),
        }
        sched = optax.constant_schedule(0.02)
        tx = build_dion_optimizer(sched, rank_fraction=1.0, max_grad_norm=1.0)
        state = tx.init(params)

        def loss_fn(p):
            pred = x @ p["w_proj"] + p["bias"] + p["embed"][:4].sum() * 0
            return ((pred - y) ** 2).mean()

        @jax.jit
        def step(p, s):
            l, g = jax.value_and_grad(loss_fn)(p)
            u, s = tx.update(g, s, p)
            return optax.apply_updates(p, u), s, l

        losses = []
        for _ in range(80):
            params, state, l = step(params, state)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.5

    def test_head_split_projection_uses_full_matrix(self):
        """wq (L, D, N, H) must orthonormalize the (D, N*H) matmul matrix per layer,
        not per-(layer, embed-row) (H, dh) blocks — the update of each layer slice
        must be low-rank as a (D, N*H) matrix."""
        rng = np.random.RandomState(3)
        L, D, N, H = 2, 24, 4, 4
        g = jnp.asarray(rng.randn(L, D, N, H).astype(np.float32))
        tx = dion(0.1, rank_fraction=0.5)
        state = tx.init({"layers": {"wq": g}})
        # q factor lives in the flattened geometry: (L, N*H, r)
        assert state.q["layers"]["wq"].shape == (L, N * H, 8)
        upd, _ = tx.update({"layers": {"wq": g}}, state)
        assert upd["layers"]["wq"].shape == (L, D, N, H)
        u0 = np.asarray(upd["layers"]["wq"][0]).reshape(D, N * H)
        s = np.linalg.svd(u0, compute_uv=False)
        assert (s[8:] < 1e-4).all(), "per-layer update must be rank<=r over (D, N*H)"

    def test_wo_projection_flattens_leading_heads(self):
        rng = np.random.RandomState(4)
        L, N, H, D = 2, 4, 4, 24
        g = jnp.asarray(rng.randn(L, N, H, D).astype(np.float32))
        tx = dion(0.1, rank_fraction=0.5)
        state = tx.init({"layers": {"wo": g}})
        assert state.q["layers"]["wo"].shape == (L, D, 8)
        upd, _ = tx.update({"layers": {"wo": g}}, state)
        assert upd["layers"]["wo"].shape == (L, N, H, D)

    def test_square_stacked_projection_untouched(self):
        """A vision-tower style wq stored already-flattened as (L, d, d) must be
        treated as a per-layer (d, d) matrix — NOT have its layer dim fused in."""
        from automodel_tpu.optim.dion import _canon_shape

        assert _canon_shape((), (4, 8, 8)) == (4, 8, 8)

    def test_axes_driven_canonicalization(self):
        """logical_axes grouping: MLA wq_b (L, r, N, H) -> (L, r, N*H); DeltaNet
        wqkvz (L, D, Hk, M) -> (L, D, Hk*M); 3-way layouts fall back to AdamW."""
        from automodel_tpu.optim.dion import _axes_canon_shape

        # no stack prefix -> three matrix dims -> ambiguous
        assert _axes_canon_shape((2, 6, 4, 8), (None, None, "heads", "head_dim")) is None
        assert _axes_canon_shape(
            (2, 6, 4, 8), ("layers", None, "heads", "head_dim")
        ) == (2, 6, 32)
        assert _axes_canon_shape(
            (2, 16, 4, 8), ("layers", "embed", "kv_heads", "head_dim")
        ) == (2, 16, 32)
        assert _axes_canon_shape(
            (2, 4, 8, 16), ("layers", "heads", "head_dim", "embed")
        ) == (2, 32, 16)
        # per-head bias (L, N, H) -> single merged dim -> not a matrix
        assert _axes_canon_shape((2, 4, 8), ("layers", "heads", "head_dim")) is None
        # three distinct matrix dims: ambiguous, AdamW
        assert _axes_canon_shape((2, 4, 8, 16), ("layers", "a", "b", "c")) is None

    def test_build_with_logical_axes_mla(self):
        """build_dion_optimizer(logical_axes=...) orthonormalizes wq_b over the
        full (r, N*H) matrix per layer."""
        rng = np.random.RandomState(5)
        L, r_lat, N, H = 2, 12, 4, 4
        params = {"layers": {"wq_b": jnp.asarray(rng.randn(L, r_lat, N, H).astype(np.float32))}}
        axes = {"layers": {"wq_b": ("layers", None, "heads", "head_dim")}}
        tx = build_dion_optimizer(0.1, rank_fraction=0.5, logical_axes=axes)
        state = tx.init(params)
        q = state.inner_states["dion"].inner_state[0].q["layers"]["wq_b"]
        assert q.shape == (L, N * H, 6)
        upd, _ = tx.update(jax.tree.map(jnp.ones_like, params), state, params)
        u0 = np.asarray(upd["layers"]["wq_b"][0]).reshape(r_lat, N * H)
        s = np.linalg.svd(u0, compute_uv=False)
        assert (s[6:] < 1e-4).all()

    def test_dense_decoder_param_tree(self):
        """End-to-end over a real dense-decoder tree: labels route per-head biases
        to adamw, and the jitted dion+adamw step runs over every leaf."""
        from automodel_tpu.models.common.backend import BackendConfig
        from automodel_tpu.models.llama.model import LlamaConfig, LlamaForCausalLM
        from automodel_tpu.optim.dion import _is_matrix_path

        import jax.tree_util as jtu

        cfg = LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=48,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            attention_bias=True,
        )
        model = LlamaForCausalLM(cfg, BackendConfig(dtype="float32"))
        params = model.init(jax.random.key(0), jnp.float32)
        labels = jtu.tree_map_with_path(
            lambda p, l: "dion" if _is_matrix_path(p, l) else "adamw", params
        )
        layer_labels = labels["layers"]
        for name in ("bq", "bk", "bv"):
            if name in layer_labels:
                assert layer_labels[name] == "adamw", f"{name} must not be orthonormalized"
        assert layer_labels["wq"] == "dion"
        assert layer_labels["w_down"] == "dion"

        tx = build_dion_optimizer(optax.constant_schedule(1e-3), max_grad_norm=1.0)
        state = tx.init(params)
        grads = jax.tree.map(jnp.ones_like, params)
        upd, _ = jax.jit(tx.update)(grads, state, params)
        chex_shapes = jax.tree.map(lambda u, p: u.shape == p.shape, upd, params)
        assert all(jax.tree.leaves(chex_shapes))

    def test_grouping_labels(self):
        from automodel_tpu.optim.dion import _is_matrix_path

        import jax.tree_util as jtu

        params = {
            "embed": jnp.zeros((10, 4)),
            "layers": {"wq": jnp.zeros((2, 4, 4)), "attn_norm": jnp.zeros((2, 4))},
            "lm_head": jnp.zeros((4, 10)),
        }
        labels = jtu.tree_map_with_path(
            lambda p, l: "dion" if _is_matrix_path(p, l) else "adamw", params
        )
        assert labels["embed"] == "adamw"
        assert labels["lm_head"] == "adamw"
        assert labels["layers"]["wq"] == "dion"
        assert labels["layers"]["attn_norm"] == "adamw"
