"""mistral-common tokenizer adapter
(reference tokenization/tokenization_mistral_common.py:169 MistralCommonBackend +
tokenization/registry.py).

Mistral ships its official tokenizers (tekken.json / tokenizer.model.v*) through
the ``mistral_common`` package rather than HF tokenizer.json files; several
Mistral repos have no (or stale) HF tokenizer artifacts. This adapter wraps a
``mistral_common`` tokenizer in the minimal HF-compatible surface the recipes
use (encode / decode / __call__ / apply_chat_template / special-token ids), and
the registry decides per checkpoint dir whether to route to it.

``mistral_common`` is an optional dependency (gated import, like wandb/mlflow):
with it absent, Mistral repos that still carry HF tokenizer files fall back to
``transformers.AutoTokenizer`` as before; repos without them raise with an
actionable message.
"""

from __future__ import annotations

import os

__all__ = ["MistralCommonTokenizer", "find_mistral_tokenizer_file", "mistral_common_available"]

# the file names mistral_common knows how to load, in preference order
# (registry.py probes the same set). Deliberately NOT the bare "tokenizer.model":
# that name is generic sentencepiece (llama-2, gemma, ...) and would mis-route
# ordinary HF checkpoints here.
_TOKENIZER_FILES = (
    "tekken.json",
    "tokenizer.model.v11",
    "tokenizer.model.v7",
    "tokenizer.model.v3",
    "tokenizer.model.v2",
    "tokenizer.model.v1",
)


def mistral_common_available() -> bool:
    try:
        import mistral_common  # noqa: F401

        return True
    except ImportError:
        return False


def find_mistral_tokenizer_file(path: str) -> str | None:
    """The mistral-common tokenizer file in a checkpoint dir, if any."""
    if not os.path.isdir(path):
        return None
    for name in _TOKENIZER_FILES:
        fp = os.path.join(path, name)
        if os.path.isfile(fp):
            return fp
    return None


class MistralCommonTokenizer:
    """HF-shaped wrapper over mistral_common's MistralTokenizer.

    Covers the contract the data pipeline relies on: ``encode(text,
    add_special_tokens=...)``, ``decode``, ``apply_chat_template(messages)``,
    ``bos/eos/pad_token_id``, ``vocab_size``/``__len__``. Instruct-style
    tokenization goes through mistral_common's own ChatCompletionRequest
    encoding, which is the entire point of using the official tokenizer
    (reference MistralCommonBackend.apply_chat_template)."""

    def __init__(self, mistral_tokenizer):
        self._mt = mistral_tokenizer
        self._inner = mistral_tokenizer.instruct_tokenizer.tokenizer

    @classmethod
    def from_pretrained(cls, path: str) -> "MistralCommonTokenizer":
        try:
            from mistral_common.tokens.tokenizers.mistral import MistralTokenizer
        except ImportError as exc:  # pragma: no cover - env without the extra
            raise ImportError(
                "this checkpoint ships a mistral-common tokenizer "
                f"({find_mistral_tokenizer_file(path)}); install the "
                "`mistral-common` extra to load it"
            ) from exc
        fp = find_mistral_tokenizer_file(path)
        if fp is None:
            raise FileNotFoundError(f"no mistral tokenizer file under {path!r}")
        return cls(MistralTokenizer.from_file(fp))

    # ---- special tokens -------------------------------------------------
    @property
    def bos_token_id(self) -> int:
        return self._inner.bos_id

    @property
    def eos_token_id(self) -> int:
        return self._inner.eos_id

    @property
    def pad_token_id(self) -> int:
        # mistral pads with its dedicated pad id when present, else eos
        pad = getattr(self._inner, "pad_id", None)
        if pad is None or pad < 0:
            return self.eos_token_id
        return pad

    @property
    def unk_token_id(self) -> int | None:
        unk = getattr(self._inner, "unk_id", None)
        return None if unk is None or unk < 0 else unk

    @property
    def vocab_size(self) -> int:
        return self._inner.n_words

    def __len__(self) -> int:
        return self.vocab_size

    # ---- text path ------------------------------------------------------
    def encode(self, text: str, add_special_tokens: bool = True, **_) -> list[int]:
        return list(self._inner.encode(text, bos=add_special_tokens, eos=False))

    def decode(self, ids, skip_special_tokens: bool = True, **_) -> str:
        ids = [int(i) for i in ids]
        if skip_special_tokens:
            special = {self.bos_token_id, self.eos_token_id, self.pad_token_id}
            ids = [i for i in ids if i not in special]
        return self._inner.decode(ids)

    def __call__(self, text, **kwargs):
        if isinstance(text, str):
            ids = self.encode(text, add_special_tokens=kwargs.get("add_special_tokens", True))
            return {"input_ids": ids, "attention_mask": [1] * len(ids)}
        out = [self.encode(t, add_special_tokens=kwargs.get("add_special_tokens", True)) for t in text]
        return {"input_ids": out, "attention_mask": [[1] * len(o) for o in out]}

    # ---- chat -----------------------------------------------------------
    def apply_chat_template(self, messages, tokenize: bool = True,
                            add_generation_prompt: bool = False, **_):
        """Official instruct encoding via ChatCompletionRequest (the reason this
        adapter exists: HF chat templates for Mistral drift from the real one)."""
        from mistral_common.protocol.instruct.messages import (
            AssistantMessage, SystemMessage, UserMessage,
        )
        from mistral_common.protocol.instruct.request import ChatCompletionRequest

        roles = {"system": SystemMessage, "user": UserMessage, "assistant": AssistantMessage}
        ms = [roles[m["role"]](content=m["content"]) for m in messages]
        tokenized = self._mt.encode_chat_completion(ChatCompletionRequest(messages=ms))
        if tokenize:
            return list(tokenized.tokens)
        return tokenized.text
