"""Generation recipe — sample from a trained/finetuned checkpoint in-framework.

The reference points users at vLLM/transformers for sampling after export; here
the KV-cache decode path (generation/__init__.py) is native, so ``automodel
generate llm -c cfg.yaml`` closes the finetune -> sample loop without leaving
the framework (and without exporting first).

.. code-block:: yaml

    model:
      pretrained_model_name_or_path: /path/to/hf_or_exported_dir
    generation:
      max_new_tokens: 64
      temperature: 0.7        # 0 = greedy
      top_k: 50
      top_p: 0.95
      seed: 0
    prompts:                  # or prompts_file: one prompt per line
      - "The capital of France is"
    output_file: completions.jsonl   # optional; stdout always
"""

from __future__ import annotations

import json
import logging

import jax.numpy as jnp
import numpy as np

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.config.cli_overrides import parse_args_and_load_config
from automodel_tpu.models.auto import AutoModelForCausalLM
from automodel_tpu.models.auto_tokenizer import AutoTokenizer
from automodel_tpu.models.common.backend import BackendConfig

logger = logging.getLogger(__name__)

__all__ = ["GenerationRecipe", "main"]


class GenerationRecipe:
    def __init__(self, cfg: ConfigNode):
        self.cfg = cfg

    def setup(self):
        cfg = self.cfg
        path = cfg.get("model.pretrained_model_name_or_path")
        if path is None:
            raise ValueError("generate recipe needs model.pretrained_model_name_or_path")
        backend_cfg = (cfg.get("backend") or ConfigNode()).to_dict()
        backend = BackendConfig(**backend_cfg)
        self.model, self.params = AutoModelForCausalLM.from_pretrained(
            path, backend=backend, dtype=backend.jnp_dtype
        )
        tok_cfg = cfg.get("tokenizer")
        if tok_cfg and "_target_" in tok_cfg:
            self.tokenizer = tok_cfg.instantiate()
        else:
            tok_path = (tok_cfg or ConfigNode()).get(
                "pretrained_model_name_or_path") or path
            self.tokenizer = AutoTokenizer.from_pretrained(tok_path)
        return self

    def _prompts(self) -> list[str]:
        prompts = self.cfg.get("prompts")
        if prompts is not None:
            return list(prompts)
        pf = self.cfg.get("prompts_file")
        if pf is None:
            raise ValueError("generate recipe needs prompts: [...] or prompts_file")
        with open(pf) as f:
            return [line.rstrip("\n") for line in f if line.strip()]

    def run(self) -> list[dict]:
        cfg = self.cfg
        prompts = self._prompts()
        if not prompts:
            raise ValueError("generate recipe got an empty prompt list "
                             "(prompts: [] or a blank prompts_file)")
        tok = self.tokenizer
        encoded = [tok.encode(p) for p in prompts]
        max_len = max(len(e) for e in encoded)
        pad_id = getattr(tok, "pad_token_id", None) or 0
        ids = np.full((len(encoded), max_len), pad_id, np.int32)
        mask = np.zeros((len(encoded), max_len), np.int32)
        for i, e in enumerate(encoded):  # right-padded (generation contract)
            ids[i, : len(e)] = e
            mask[i, : len(e)] = 1
        g = (cfg.get("generation") or ConfigNode()).to_dict()
        out = self.model.generate(
            self.params, ids,
            attention_mask=mask,
            max_new_tokens=int(g.get("max_new_tokens", 64)),
            temperature=float(g.get("temperature", 0.0)),
            top_k=g.get("top_k"),
            top_p=g.get("top_p"),
            eos_token_id=getattr(tok, "eos_token_id", None),
            pad_token_id=pad_id,
            seed=int(g.get("seed", 0)),
            cache_dtype=jnp.bfloat16 if g.get("cache_dtype", "bfloat16") == "bfloat16"
            else jnp.float32,
        )
        results = []
        for i, p in enumerate(prompts):
            n = int(out["lengths"][i])
            completion = tok.decode(np.asarray(out["tokens"][i][:n]).tolist())
            results.append({"prompt": p, "completion": completion, "new_tokens": n})
            print(f"=== {p!r}\n{completion}\n")
        of = cfg.get("output_file")
        if of:
            with open(of, "w") as f:
                for r in results:
                    f.write(json.dumps(r) + "\n")
            logger.info("wrote %d completions to %s", len(results), of)
        return results


def main(cfg: ConfigNode | None = None, argv=None):
    if cfg is None:
        cfg = parse_args_and_load_config(argv)
    recipe = GenerationRecipe(cfg).setup()
    return recipe.run()


if __name__ == "__main__":
    main()
