"""Mock datasets isolating compute perf from data noise
(reference datasets/llm/mock_iterable_dataset.py:19, mock.py — used by every benchmark
config, SURVEY.md §4 fixtures)."""

from __future__ import annotations

import time
from typing import Any

import numpy as np

__all__ = ["MockSFTDataset"]


class MockSFTDataset:
    """Deterministic synthetic examples; loss over the whole sequence.

    pattern="random": i.i.d. uniform tokens — incompressible, the right fixture for
    benchmarks (loss stays at ln(vocab), isolating compute perf from learning).
    pattern="arith": per-sample arithmetic progressions mod vocab — highly learnable,
    the right fixture for loss-decreases tests.
    """

    def __init__(
        self,
        vocab_size: int,
        seq_len: int,
        num_samples: int = 1024,
        seed: int = 0,
        pattern: str = "random",
        item_delay_s: float = 0.0,
    ):
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.num_samples = num_samples
        self.seed = seed
        if pattern not in ("random", "arith"):
            raise ValueError(f"unknown pattern {pattern!r}")
        self.pattern = pattern
        # simulated host-side input cost (tokenize/augment/pack): the perf
        # smoke uses it to make data_wait visible so the overlapped pipeline
        # has something to hide
        self.item_delay_s = float(item_delay_s)

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, i: int) -> dict[str, Any]:
        if self.item_delay_s:
            time.sleep(self.item_delay_s)
        rng = np.random.RandomState(self.seed * 100003 + i)
        # seq_len + 1 so the next-token shift still yields seq_len targets
        if self.pattern == "arith":
            step = rng.randint(1, 8)
            start = rng.randint(0, self.vocab_size)
            ids = (start + step * np.arange(self.seq_len + 1)) % self.vocab_size
        else:
            ids = rng.randint(0, self.vocab_size, size=self.seq_len + 1)
        return {"input_ids": ids.tolist(), "prompt_len": 0}
