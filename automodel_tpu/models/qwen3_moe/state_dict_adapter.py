"""Qwen3-MoE HF key/layout mapping (reference models/qwen3_moe/state_dict_adapter.py).

HF stores one tensor per expert (``mlp.experts.{e}.gate_proj.weight`` etc.); ours are
expert-stacked with gate|up merged: gate_up_proj (L, E, D, 2I), down_proj (L, E, I, D).
"""

from __future__ import annotations

import numpy as np

from automodel_tpu.models.common.state_dict import Entry, MappingAdapter
from automodel_tpu.models.llama.state_dict_adapter import (
    _bias_in,
    _bias_out,
    _o_in,
    _o_out,
    _proj_in,
    _proj_out,
    _t,
)
from automodel_tpu.models.common.moe_transformer import MoEDecoderConfig

__all__ = ["Qwen3MoeStateDictAdapter", "moe_expert_entries", "attention_entries"]


def _gate_up_in(gate: np.ndarray, up: np.ndarray) -> np.ndarray:
    """HF gate (I, D) + up (I, D) -> ours (D, 2I) with [gate | up] concat."""
    return np.concatenate([gate.T, up.T], axis=-1)


def _gate_up_out(w: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    inter = w.shape[1] // 2
    return np.ascontiguousarray(w[:, :inter].T), np.ascontiguousarray(w[:, inter:].T)


def moe_expert_entries(prefix: str, ours_prefix: str, layer_range=None) -> list[Entry]:
    """Per-expert gate/up/down HF tensors -> stacked gate_up/down (DSv3/Qwen3-MoE style)."""
    return [
        Entry(
            (f"{prefix}.experts.{{e}}.gate_proj.weight", f"{prefix}.experts.{{e}}.up_proj.weight"),
            f"{ours_prefix}.experts.gate_up_proj",
            _gate_up_in,
            _gate_up_out,
            layer_range=layer_range,
        ),
        Entry(
            f"{prefix}.experts.{{e}}.down_proj.weight",
            f"{ours_prefix}.experts.down_proj",
            _t,
            _t,
            layer_range=layer_range,
        ),
    ]


def attention_entries(cfg, ours_prefix: str = "layers", layer_range=None) -> list[Entry]:
    """GQA attention + norms, shared by every non-MLA family."""
    n, k, h = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    pre = "model.layers.{i}"
    entries = [
        Entry(f"{pre}.input_layernorm.weight", f"{ours_prefix}.attn_norm", layer_range=layer_range),
        Entry(f"{pre}.post_attention_layernorm.weight", f"{ours_prefix}.mlp_norm", layer_range=layer_range),
        Entry(f"{pre}.self_attn.q_proj.weight", f"{ours_prefix}.wq", _proj_in(n, h), _proj_out(n, h), layer_range=layer_range),
        Entry(f"{pre}.self_attn.k_proj.weight", f"{ours_prefix}.wk", _proj_in(k, h), _proj_out(k, h), layer_range=layer_range),
        Entry(f"{pre}.self_attn.v_proj.weight", f"{ours_prefix}.wv", _proj_in(k, h), _proj_out(k, h), layer_range=layer_range),
        Entry(f"{pre}.self_attn.o_proj.weight", f"{ours_prefix}.wo", _o_in(n, h), _o_out(n, h), layer_range=layer_range),
    ]
    if cfg.attention_bias:
        entries += [
            Entry(f"{pre}.self_attn.q_proj.bias", f"{ours_prefix}.bq", _bias_in(n, h), _bias_out(n, h), layer_range=layer_range),
            Entry(f"{pre}.self_attn.k_proj.bias", f"{ours_prefix}.bk", _bias_in(k, h), _bias_out(k, h), layer_range=layer_range),
            Entry(f"{pre}.self_attn.v_proj.bias", f"{ours_prefix}.bv", _bias_in(k, h), _bias_out(k, h), layer_range=layer_range),
        ]
    if getattr(cfg, "attention_out_bias", False):
        entries.append(Entry(f"{pre}.self_attn.o_proj.bias", f"{ours_prefix}.bo", layer_range=layer_range))
    if getattr(cfg, "attention_sinks", False):
        entries.append(Entry(f"{pre}.self_attn.sinks", f"{ours_prefix}.sinks", layer_range=layer_range))
    if cfg.qk_norm:
        entries += [
            Entry(f"{pre}.self_attn.q_norm.weight", f"{ours_prefix}.q_norm", layer_range=layer_range),
            Entry(f"{pre}.self_attn.k_norm.weight", f"{ours_prefix}.k_norm", layer_range=layer_range),
        ]
    return entries


class Qwen3MoeStateDictAdapter(MappingAdapter):
    def __init__(self, cfg: MoEDecoderConfig, scan_layers: bool = True):
        k = cfg.first_k_dense_replace
        L = cfg.num_hidden_layers
        moe_range = (k, L)
        entries = [
            Entry("model.embed_tokens.weight", "embed"),
            Entry("model.norm.weight", "final_norm"),
            *attention_entries(cfg, "moe_layers", layer_range=moe_range),
            Entry("model.layers.{i}.mlp.gate.weight", "moe_layers.moe.gate.weight", layer_range=moe_range),
            *moe_expert_entries("model.layers.{i}.mlp", "moe_layers.moe", layer_range=moe_range),
        ]
        if k > 0:
            entries += [
                *attention_entries(cfg, "dense_layers", layer_range=(0, k)),
                Entry("model.layers.{i}.mlp.gate_proj.weight", "dense_layers.w_gate", _t, _t, layer_range=(0, k)),
                Entry("model.layers.{i}.mlp.up_proj.weight", "dense_layers.w_up", _t, _t, layer_range=(0, k)),
                Entry("model.layers.{i}.mlp.down_proj.weight", "dense_layers.w_down", _t, _t, layer_range=(0, k)),
            ]
        if not cfg.tie_word_embeddings:
            entries.append(Entry("lm_head.weight", "lm_head", _t, _t))
        super().__init__(entries, L, scan_layers, num_experts=cfg.moe.n_routed_experts)
