from automodel_tpu.models.nemotron_parse.model import (
    NemotronParseConfig,
    NemotronParseForConditionalGeneration,
)

__all__ = ["NemotronParseConfig", "NemotronParseForConditionalGeneration"]
