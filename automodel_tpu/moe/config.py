"""MoE architecture configuration (reference MoEConfig, components/moe/config.py:39)."""

from __future__ import annotations

import dataclasses

__all__ = ["MoEConfig"]


@dataclasses.dataclass
class MoEConfig:
    """Architecture knobs for one MoE block, shared by all MoE model families.

    Field semantics mirror the reference (components/moe/config.py:39-66):

    - ``score_func``: "softmax" (Qwen/Mixtral-style) or "sigmoid" (DeepSeek-V3 noaux-tc).
    - ``gate_bias_update_factor``: >0 enables the DeepSeek-V3 loss-free balancing
      correction bias (e_score_correction_bias), updated once per optimizer step from
      accumulated expert load.
    - ``n_expert_groups`` / ``n_limited_groups``: group-limited routing (DeepSeek-V3
      device-limited gating) — scores are grouped, only top ``n_limited_groups`` groups
      stay candidates.
    - ``expert_activation``: "swiglu" | "quick_geglu" (gpt-oss, with clamp ``activation_limit``
      and sigmoid slope ``activation_alpha`` and +1 linear offset on up) | "relu2".
    - ``norm_topk_prob``: renormalize top-k weights to sum to 1 (Qwen3-MoE style).
    """

    n_routed_experts: int
    n_activated_experts: int
    dim: int
    moe_inter_dim: int
    n_shared_experts: int = 0
    n_expert_groups: int = 1
    n_limited_groups: int = 1
    train_gate: bool = True
    gate_bias_update_factor: float = 0.0
    aux_loss_coeff: float = 0.0
    score_func: str = "softmax"
    route_scale: float = 1.0
    norm_topk_prob: bool = False
    softmax_before_topk: bool = False
    router_bias: bool = False
    expert_bias: bool = False
    expert_activation: str = "swiglu"
    activation_alpha: float = 1.702
    activation_limit: float = 7.0
    shared_expert_gate: bool = False
    shared_expert_inter_dim: int | None = None
    shared_expert_activation: str = "swiglu"
    force_score_correction_bias: bool = False  # create the buffer for HF ckpt compat

    def __post_init__(self):
        if self.score_func not in ("softmax", "sigmoid"):
            raise ValueError(f"score_func must be softmax|sigmoid, got {self.score_func!r}")
        if self.expert_activation not in ("swiglu", "quick_geglu", "relu2"):
            raise ValueError(f"unknown expert_activation {self.expert_activation!r}")
        if self.shared_expert_activation not in ("swiglu", "relu2"):
            raise ValueError(f"unknown shared_expert_activation {self.shared_expert_activation!r}")
        if self.n_routed_experts % self.n_expert_groups != 0:
            raise ValueError("n_routed_experts must divide evenly into n_expert_groups")

    @property
    def has_correction_bias(self) -> bool:
        return self.gate_bias_update_factor > 0 or self.force_score_correction_bias

    @property
    def gated(self) -> bool:
        return self.expert_activation in ("swiglu", "quick_geglu")

    @property
    def shared_inter_dim(self) -> int:
        return self.n_shared_experts * (self.shared_expert_inter_dim or self.moe_inter_dim)
