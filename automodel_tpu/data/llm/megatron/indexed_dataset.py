"""Megatron MMapIndexedDataset format — reader and writer
(reference megatron/indexed_dataset.py).

The on-disk format is the public Megatron-LM layout, kept bit-compatible so corpora
tokenized for GPU training load here unchanged (the same day-0 interop argument as HF
safetensors):

``.idx``: magic ``MMIDIDX\\x00\\x00`` | u64 version=1 | u8 dtype code |
          u64 sequence_count | u64 document_count |
          i32 sizes[sequence_count] | i64 pointers[sequence_count] |
          i64 doc_idx[document_count+1]
``.bin``: raw token values, row-major.

dtype codes follow Megatron: 1=u8 2=i8 3=i16 4=i32 5=i64 6=f32 7=f64 8=u16.
"""

from __future__ import annotations

import os
import struct

import numpy as np

__all__ = ["MMapIndexedDataset", "MMapIndexedDatasetBuilder", "DTYPE_CODES"]

_MAGIC = b"MMIDIDX\x00\x00"
DTYPE_CODES = {
    1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
    5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16,
}
_CODE_FOR = {np.dtype(v): k for k, v in DTYPE_CODES.items()}


def _idx_path(prefix: str) -> str:
    return prefix + ".idx"


def _bin_path(prefix: str) -> str:
    return prefix + ".bin"


class MMapIndexedDataset:
    """Zero-copy reader: tokens stay in the OS page cache via np.memmap."""

    def __init__(self, path_prefix: str):
        self.path_prefix = path_prefix
        with open(_idx_path(path_prefix), "rb") as f:
            magic = f.read(9)
            if magic != _MAGIC:
                raise ValueError(f"{_idx_path(path_prefix)}: bad magic {magic!r}")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != 1:
                raise ValueError(f"unsupported index version {version}")
            (code,) = struct.unpack("<B", f.read(1))
            self.dtype = np.dtype(DTYPE_CODES[code])
            (seq_count,) = struct.unpack("<Q", f.read(8))
            (doc_count,) = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        idx_buffer = np.memmap(_idx_path(path_prefix), mode="r", order="C")
        self.sizes = np.frombuffer(idx_buffer, np.int32, count=seq_count, offset=offset)
        offset += seq_count * 4
        self.pointers = np.frombuffer(idx_buffer, np.int64, count=seq_count, offset=offset)
        offset += seq_count * 8
        self.document_indices = np.frombuffer(idx_buffer, np.int64, count=doc_count + 1, offset=offset)
        self._bin = np.memmap(_bin_path(path_prefix), mode="r", order="C")

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, idx: int) -> np.ndarray:
        return self.get(idx)

    def get(self, idx: int, offset: int = 0, length: int | None = None) -> np.ndarray:
        """Tokens of sequence ``idx`` starting at ``offset`` (in tokens)."""
        size = int(self.sizes[idx]) - offset
        if length is not None:
            size = min(size, length)
        byte_start = int(self.pointers[idx]) + offset * self.dtype.itemsize
        return np.frombuffer(self._bin, self.dtype, count=size, offset=byte_start)

    @property
    def num_tokens(self) -> int:
        return int(self.sizes.sum())

    @staticmethod
    def exists(path_prefix: str) -> bool:
        return os.path.exists(_idx_path(path_prefix)) and os.path.exists(_bin_path(path_prefix))


class MMapIndexedDatasetBuilder:
    """Streaming writer; ``add_document`` per tokenized doc, then ``finalize``."""

    def __init__(self, path_prefix: str, dtype=np.int32):
        self.path_prefix = path_prefix
        self.dtype = np.dtype(dtype)
        self._bin = open(_bin_path(path_prefix), "wb")
        self.sizes: list[int] = []
        self.doc_indices: list[int] = [0]
        self._offset = 0

    def add_document(self, tokens: np.ndarray) -> None:
        arr = np.ascontiguousarray(tokens, dtype=self.dtype)
        self._bin.write(arr.tobytes(order="C"))
        self.sizes.append(len(arr))
        self.doc_indices.append(len(self.sizes))
        self._offset += arr.nbytes

    def finalize(self) -> None:
        self._bin.close()
        sizes = np.asarray(self.sizes, np.int32)
        pointers = np.zeros(len(sizes), np.int64)
        np.cumsum(sizes[:-1].astype(np.int64) * self.dtype.itemsize, out=pointers[1:])
        with open(_idx_path(self.path_prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", _CODE_FOR[self.dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(self.doc_indices) - 1))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(np.asarray(self.doc_indices, np.int64).tobytes(order="C"))
