"""Qwen3-Omni-MoE thinker: full logits parity vs HF with audio + image inputs."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.models.auto import AutoModelForImageTextToText
from automodel_tpu.models.common.backend import BackendConfig

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from transformers.models.qwen3_omni_moe.configuration_qwen3_omni_moe import (
    Qwen3OmniMoeThinkerConfig as HFThinkerConfig,
)
from transformers.models.qwen3_omni_moe.modeling_qwen3_omni_moe import (
    Qwen3OmniMoeThinkerForConditionalGeneration as HFThinker,
)

AUDIO, IMG, VSTART = 120, 121, 123


def tiny_cfg():
    return HFThinkerConfig(
        audio_config=dict(
            d_model=32, encoder_layers=2, encoder_attention_heads=4, encoder_ffn_dim=48,
            num_mel_bins=32, n_window=8, n_window_infer=32, downsample_hidden_size=16,
            output_dim=64, conv_chunksize=500,
        ),
        vision_config=dict(
            depth=3, hidden_size=32, intermediate_size=48, num_heads=4, patch_size=4,
            spatial_merge_size=2, temporal_patch_size=2, out_hidden_size=64,
            num_position_embeddings=16, deepstack_visual_indexes=[0, 2], in_channels=3,
        ),
        text_config=dict(
            vocab_size=128, hidden_size=64, intermediate_size=96, moe_intermediate_size=32,
            num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            num_experts=8, num_experts_per_tok=2, max_position_embeddings=128,
            rope_scaling={"rope_type": "default", "mrope_section": [4, 2, 2], "mrope_interleaved": True},
        ),
        audio_token_id=AUDIO, image_token_id=IMG, video_token_id=122,
        vision_start_token_id=VSTART, audio_start_token_id=124,
    )


def _fp32_backend():
    return BackendConfig(dtype="float32", remat_policy="full")


def _build(tmp_path, hf):
    d = str(tmp_path / "hf")
    hf.save_pretrained(d, safe_serialization=True)
    return AutoModelForImageTextToText.from_pretrained(d, dtype=jnp.float32, backend=_fp32_backend())


class TestOmniThinkerParity:
    def test_logits_match_hf_audio_and_image(self, tmp_path):
        torch.manual_seed(0)
        hf = HFThinker(tiny_cfg()).eval()
        model, params = _build(tmp_path, hf)

        rng = np.random.RandomState(0)
        seq = 40
        ids = rng.randint(0, 100, (1, seq))
        # audio span: 23 mel frames -> _get_feat_extract_output_lengths = 3 tokens
        audio_T = 23
        n_audio_tok = 3
        ids[0, 2 : 2 + n_audio_tok] = AUDIO
        # image span: (1, 8, 8) grid -> 16 merged tokens
        ids[0, 10] = VSTART
        ids[0, 11:27] = IMG
        grid = np.array([[1, 8, 8]])
        pixels = rng.randn(64, 3 * 2 * 4 * 4).astype(np.float32)
        mel = rng.randn(32, audio_T).astype(np.float32)

        with torch.no_grad():
            theirs = hf(
                input_ids=torch.tensor(ids),
                attention_mask=torch.ones_like(torch.tensor(ids)),
                input_features=torch.tensor(mel)[None],
                feature_attention_mask=torch.ones(1, audio_T, dtype=torch.long),
                pixel_values=torch.tensor(pixels),
                image_grid_thw=torch.tensor(grid),
            ).logits.float().numpy()

        vin = {k: jnp.asarray(v) for k, v in model.prepare_vision_inputs(grid).items()}
        vcoords = tuple(jnp.asarray(c) for c in model.visual_token_coords(ids))
        ain = model.prepare_audio_inputs([mel])
        acoords = tuple(jnp.asarray(c) for c in model.audio_token_coords(ids))
        pos3 = jnp.asarray(model.get_mrope_positions(ids, grid))
        ours, _ = model(
            params, jnp.asarray(ids),
            pixel_values=jnp.asarray(pixels), vision_inputs=vin, visual_coords=vcoords,
            audio_chunks=jnp.asarray(ain["chunks"]),
            audio_inputs={k: jnp.asarray(v) for k, v in ain.items()},
            audio_coords=acoords, positions3=pos3, training=False,
        )
        np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-3, rtol=1e-3)

    def test_rope_index_matches_hf_with_audio(self, tmp_path):
        torch.manual_seed(1)
        hf = HFThinker(tiny_cfg())
        model, _ = _build(tmp_path, hf)
        rng = np.random.RandomState(1)
        ids = rng.randint(0, 100, (1, 20))
        ids[0, 2:5] = AUDIO  # 3 audio tokens (text-like positions)
        theirs, _ = hf.get_rope_index(
            torch.tensor(ids), attention_mask=torch.ones_like(torch.tensor(ids)),
            audio_seqlens=torch.tensor([23]),
        )
        ours = model.get_mrope_positions(ids, None)
        np.testing.assert_array_equal(ours, theirs.numpy())

    def test_adapter_key_parity(self, tmp_path):
        torch.manual_seed(2)
        hf = HFThinker(tiny_cfg())
        model, params = _build(tmp_path, hf)
        hf_dict = model.state_dict_adapter().to_hf(params)
        theirs = {k for k in hf.state_dict() if "rotary" not in k}
        assert set(hf_dict) == theirs

    def test_rope_index_matches_hf_timestamp_video(self, tmp_path):
        """Omni video: one contiguous t*gh*gw span with timestamp-scaled t-index
        (position_id_per_seconds x second_per_grid)."""
        torch.manual_seed(6)
        hf = HFThinker(tiny_cfg())
        model, _ = _build(tmp_path, hf)
        t, h, w = 3, 4, 4
        n_tok = t * (h // 2) * (w // 2)
        ids = np.random.RandomState(6).randint(0, 100, (1, 30))
        ids[0, 2] = VSTART
        ids[0, 3 : 3 + n_tok] = 122  # video tokens, contiguous span
        grid = np.array([[t, h, w]])
        theirs, _ = hf.get_rope_index(
            torch.tensor(ids), attention_mask=torch.ones_like(torch.tensor(ids)),
            video_grid_thw=torch.tensor(grid),
            second_per_grids=torch.tensor([2.0]),
        )
        ours = model.get_mrope_positions(
            ids, None, video_grid_thw=grid, second_per_grids=np.array([2.0])
        )
        np.testing.assert_array_equal(ours, theirs.numpy())


class TestOmniPPHidden:
    def test_pp_hidden_matches_forward_with_audio(self, cpu_devices):
        """Omni under pp (VERDICT r3 #5 follow-through): the inherited
        make_pp_hidden path with audio embeds riding the per-microbatch
        prologue must reproduce the unpipelined hidden states exactly."""
        import jax
        import jax.numpy as jnp

        from automodel_tpu.data.vlm.collate_fns import qwen3_omni_collate
        from automodel_tpu.models.auto import AutoModelForImageTextToText
        from automodel_tpu.models.common.backend import BackendConfig
        from automodel_tpu.parallel.mesh import MeshContext, default_sharding_rules
        from tests.unit.test_datasets_llm import WordTokenizer

        hf = {
            "architectures": ["Qwen3OmniMoeForConditionalGeneration"],
            "audio_config": {
                "d_model": 32, "encoder_layers": 2, "encoder_attention_heads": 4,
                "encoder_ffn_dim": 48, "num_mel_bins": 32, "n_window": 8,
                "n_window_infer": 32, "downsample_hidden_size": 16, "output_dim": 64,
                "conv_chunksize": 500,
            },
            "vision_config": {
                "depth": 2, "hidden_size": 32, "intermediate_size": 48, "num_heads": 4,
                "patch_size": 4, "spatial_merge_size": 2, "temporal_patch_size": 2,
                "out_hidden_size": 64, "num_position_embeddings": 16,
                "deepstack_visual_indexes": [0, 1], "in_channels": 3,
            },
            "text_config": {
                "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
                "moe_intermediate_size": 32, "num_hidden_layers": 2,
                "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
                "num_experts": 8, "num_experts_per_tok": 2,
                "max_position_embeddings": 256,
                "rope_scaling": {"rope_type": "default", "mrope_section": [4, 2, 2],
                                 "mrope_interleaved": True},
            },
            "audio_token_id": 123, "image_token_id": 120, "video_token_id": 122,
            "vision_start_token_id": 121, "audio_start_token_id": 124,
        }
        model = AutoModelForImageTextToText.from_config(hf, BackendConfig(dtype="float32"))
        rng = np.random.RandomState(0)
        exs = [{"prompt": "<audio> transcribe", "answer": "hello",
                "audio_features": rng.randn(32, 24).astype(np.float32)}]
        batch = qwen3_omni_collate(exs, WordTokenizer(), model, seq_len=64)

        ctx = MeshContext(pp=2, dp_shard=1, world_size=2)
        mesh = ctx.build_mesh(jax.devices()[:2])
        rules = default_sharding_rules().with_mesh(mesh)
        with mesh:
            shardings = rules.tree_sharding(model.logical_axes())
            params = jax.jit(lambda k: model.init(k, jnp.float32),
                             out_shardings=shardings)(jax.random.key(0))
            ref_h, _ = model(
                params, jnp.asarray(batch["input_ids"]),
                audio_chunks=jnp.asarray(batch["audio_chunks"]),
                audio_inputs={k: jnp.asarray(v) for k, v in batch["audio_inputs"].items()},
                audio_coords=(jnp.asarray(batch["audio_coords_b"]),
                              jnp.asarray(batch["audio_coords_s"])),
                positions3=jnp.asarray(batch["positions3"]),
                segment_ids=jnp.asarray(batch["segment_ids"]),
                token_mask=jnp.asarray(batch["segment_ids"]) != 0,
                training=True, return_hidden=True,
            )
            hidden_fn = model.make_pp_hidden(mesh, rules, seq_len_hint=64)
            stack = jax.tree.map(lambda *xs: np.stack(xs), batch, batch)  # n_micro=2
            n = int((np.asarray(batch["labels"]) != -100).sum()) * 2
            h_stack, aux_loss, extras = jax.jit(hidden_fn, static_argnums=())(
                params, stack, n)
        # final norm applies in __call__'s return_hidden but NOT in hidden_fn?
        # both return pre-head hidden AFTER final_norm in __call__; hidden_fn
        # returns the raw layer-stack output — compare via the head-side norm
        from automodel_tpu.ops.norms import rms_norm

        cfg_t = model.config.text
        got = rms_norm(h_stack[0], np.asarray(params["final_norm"]).astype(np.float32),
                       cfg_t.rms_norm_eps)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref_h),
                                   rtol=2e-5, atol=2e-5)
        assert extras["expert_load"].shape[-1] == 8
