from automodel_tpu.models.deepseek_v3.model import DeepseekV3Config, DeepseekV3ForCausalLM

__all__ = ["DeepseekV3Config", "DeepseekV3ForCausalLM"]
