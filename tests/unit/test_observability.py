"""Timers + experiment-logger tests (reference tests for training/timers.py and
loggers/)."""

import time

import jax.numpy as jnp
import pytest

from automodel_tpu.loggers.experiment_loggers import (
    MLflowLogger,
    WandbLogger,
    build_experiment_loggers,
)
from automodel_tpu.training.timers import Timer, Timers


class TestTimers:
    def test_basic_timing(self):
        timers = Timers()
        with timers("work"):
            time.sleep(0.01)
        s = timers.summary()
        assert 0.005 < s["work"] < 1.0

    def test_mean_over_calls(self):
        timers = Timers()
        for _ in range(3):
            with timers("x"):
                time.sleep(0.002)
        assert timers("x").count == 3
        assert timers("x").mean < timers("x").elapsed_total

    def test_sync_blocks_on_result(self):
        t = Timer("d", sync=True)
        t.start()
        out = jnp.ones((256, 256)) @ jnp.ones((256, 256))
        dt = t.stop(out)
        assert dt > 0

    def test_double_start_raises(self):
        t = Timer("x")
        t.start()
        with pytest.raises(RuntimeError, match="already started"):
            t.start()

    def test_summary_reset(self):
        timers = Timers()
        with timers("a"):
            pass
        timers.summary(reset=True)
        assert timers.summary() == {}


class TestExperimentLoggers:
    def test_missing_packages_degrade_gracefully(self):
        # wandb/mlflow are not installed in this image: loggers become no-ops
        w = WandbLogger(project="x", mode="offline")
        w.log(1, loss=1.0)
        w.close()
        m = MLflowLogger(tracking_uri="file:/tmp/nope")
        m.log(1, loss=1.0)
        m.close()

    def test_build_from_config(self):
        from automodel_tpu.config.loader import ConfigNode

        cfg = ConfigNode({"wandb": {"project": "p", "mode": "offline"}})
        loggers = build_experiment_loggers(cfg)
        assert len(loggers) == 1
        cfg2 = ConfigNode({})
        assert build_experiment_loggers(cfg2) == []
