"""End-to-end MoE training on the virtual 8-device mesh: EP-sharded experts, aux loss,
gate-bias loss-free balancing, load-balance metrics in the JSONL stream."""

import json
import textwrap

import numpy as np
import pytest

from automodel_tpu.config.loader import load_config
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction
from automodel_tpu.utils import jax_compat

# see tests/unit/test_pipeline.py: pre-0.5 jax + XLA CPU cannot lower the
# PartitionId the pp ring's axis_index produces under partial-manual shard_map
pp_partial_manual_compiles = pytest.mark.skipif(
    jax_compat.SHIMMED,
    reason="jax<0.5 XLA CPU cannot lower PartitionId under partial-manual "
    "shard_map (pp ring axis_index)",
)


def _write_cfg(tmp_path, arch="Qwen3MoeForCausalLM", extra_model="", extra="", max_steps=6):
    cfg = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
        architectures: [{arch}]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 96
        moe_intermediate_size: 32
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        head_dim: 16
        max_position_embeddings: 128
        {extra_model}
    distributed:
      dp_shard: 2
      ep: 2
      tp: 2
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 256
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: 2
      max_steps: {max_steps}
      num_epochs: 10
      handle_sigterm: false
      ckpt_every_steps: 0
    optimizer:
      lr: 1.0e-2
      weight_decay: 0.0
      max_grad_norm: 1.0
    lr_scheduler:
      lr_warmup_steps: 2
    checkpoint:
      enabled: false
    {extra}
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg))
    return p


def _read_jsonl(path):
    from tests.functional.jsonl import metric_rows

    return metric_rows(path)


class TestMoERecipeE2E:
    def test_qwen3_moe_loss_decreases(self, tmp_path, cpu_devices):
        cfg = load_config(_write_cfg(
            tmp_path,
            extra_model="num_experts: 8\n        num_experts_per_tok: 2\n        "
                        "norm_topk_prob: true\n        router_aux_loss_coef: 0.01",
        ))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        losses = [r["loss"] for r in rows]
        assert losses[0] > 4.0
        assert losses[-1] < losses[0] - 0.3
        # MoE load-balance metrics flow into the metric stream
        assert "moe_load/max_util_mean" in rows[0]
        assert rows[0]["moe_load/max_util_mean"] >= 1.0

    @pp_partial_manual_compiles
    def test_qwen3_moe_pp_loss_decreases(self, tmp_path, cpu_devices):
        """PP x EP x DP composition: 4 moe layers pipelined over pp=2."""
        cfg = load_config(_write_cfg(
            tmp_path,
            extra_model="num_experts: 8\n        num_experts_per_tok: 2\n        "
                        "norm_topk_prob: true",
            max_steps=6,
        ))
        cfg.set_by_path("model.config.num_hidden_layers", 4)
        cfg.set_by_path("distributed.pp", 2)
        cfg.set_by_path("distributed.tp", 1)
        cfg.set_by_path("step_scheduler.grad_acc_steps", 4)
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        losses = [r["loss"] for r in rows]
        assert losses[0] > 4.0
        assert losses[-1] < losses[0] - 0.3
        assert "moe_load/max_util_mean" in rows[0]
        # moe layer params actually pp-sharded: 4 layers over pp=2 -> 2 local
        wq = recipe.params["moe_layers"]["wq"]
        assert wq.sharding.shard_shape(wq.shape)[0] == 2

    @pp_partial_manual_compiles
    def test_dsv3_pp_gate_bias_updates(self, tmp_path, cpu_devices):
        """MLA + PP: dense prefix replicated, moe stack pipelined, bias balancing on."""
        cfg = load_config(_write_cfg(
            tmp_path,
            arch="DeepseekV3ForCausalLM",
            extra_model=(
                "q_lora_rank: 24\n        kv_lora_rank: 32\n        qk_nope_head_dim: 16\n"
                "        qk_rope_head_dim: 8\n        v_head_dim: 16\n"
                "        n_routed_experts: 8\n        num_experts_per_tok: 2\n"
                "        n_shared_experts: 1\n        norm_topk_prob: true\n"
                "        first_k_dense_replace: 1"
            ),
            max_steps=4,
        ))
        cfg.set_by_path("model.config.num_hidden_layers", 5)  # 1 dense + 4 moe
        cfg.set_by_path("distributed.pp", 2)
        cfg.set_by_path("distributed.tp", 1)
        cfg.set_by_path("step_scheduler.grad_acc_steps", 4)
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        bias0 = np.asarray(
            recipe.params["moe_layers"]["moe"]["gate"]["score_correction_bias"]
        ).copy()
        recipe.run_train_validation_loop()
        bias1 = np.asarray(recipe.params["moe_layers"]["moe"]["gate"]["score_correction_bias"])
        assert np.abs(bias1 - bias0).max() > 0
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        assert np.isfinite([r["loss"] for r in rows]).all()

    def test_dsv3_gate_bias_updates(self, tmp_path, cpu_devices):
        cfg = load_config(_write_cfg(
            tmp_path,
            arch="DeepseekV3ForCausalLM",
            extra_model=(
                "q_lora_rank: 24\n        kv_lora_rank: 32\n        qk_nope_head_dim: 16\n"
                "        qk_rope_head_dim: 8\n        v_head_dim: 16\n"
                "        n_routed_experts: 8\n        num_experts_per_tok: 2\n"
                "        n_shared_experts: 1\n        n_group: 2\n        topk_group: 1\n"
                "        routed_scaling_factor: 1.0\n        norm_topk_prob: true\n"
                "        first_k_dense_replace: 1"
            ),
            max_steps=4,
        ))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        bias0 = np.asarray(
            recipe.params["moe_layers"]["moe"]["gate"]["score_correction_bias"]
        ).copy()
        recipe.run_train_validation_loop()
        bias1 = np.asarray(recipe.params["moe_layers"]["moe"]["gate"]["score_correction_bias"])
        # loss-free balancing must have moved the correction bias (factor 0.001/step)
        assert np.abs(bias1 - bias0).max() > 0
        assert bias1.dtype == np.float32
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        assert np.isfinite([r["loss"] for r in rows]).all()


class TestPPAuxLoss:
    @pp_partial_manual_compiles
    def test_pp_aux_loss_balancing(self, tmp_path, cpu_devices):
        """pp + router aux-loss (a round-1 fence): the aux term now rides the
        pipeline's per-stage accumulators and joins the loss; trajectory stays
        finite and falls with balancing on."""
        cfg = load_config(_write_cfg(
            tmp_path,
            extra_model="num_experts: 8\n        num_experts_per_tok: 2\n        "
                        "norm_topk_prob: true\n        router_aux_loss_coef: 0.01",
            max_steps=6,
        ))
        cfg.set_by_path("model.config.num_hidden_layers", 4)
        cfg.set_by_path("distributed.pp", 2)
        cfg.set_by_path("distributed.tp", 1)
        cfg.set_by_path("step_scheduler.grad_acc_steps", 4)
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        losses = [r["loss"] for r in rows]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] - 0.3
