"""NemotronParse — TPU-native seq2seq OCR family (reference
models/nemotron_parse/model.py:431 NemotronParseForConditionalGeneration).

Encoder–decoder: a RADIO vision trunk (external trust_remote_code model in the
reference too, :375) feeds a native *neck* — 1x1 conv (linear) -> LayerNorm ->
(1,4)-stride conv merging 4 horizontal patches -> LayerNorm, plus a projected
summary token appended — whose output cross-attends into an mBART-style decoder.
The decoder is MBartDecoder minus positional embeddings (reference :212-243
creates no embed_positions): scaled word embeddings, pre-norm layers with
self-attention, cross-attention and GELU FFN, embedding/final LayerNorms.

The vision trunk is pluggable: pass ``encoder_features (B, N, 1280)`` and
``summary (B, 3840)`` (RADIO outputs) and the native neck runs on device. The
``extra_heads``/``extra_proj`` linears exist for checkpoint compatibility (the
reference creates but never calls them in forward)."""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.transformer import _constrain
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import layer_norm

__all__ = ["NemotronParseConfig", "NemotronParseForConditionalGeneration"]


@dataclasses.dataclass
class NemotronParseConfig:
    vocab_size: int = 250027
    d_model: int = 1024
    decoder_layers: int = 12
    decoder_attention_heads: int = 16
    decoder_ffn_dim: int = 4096
    activation_function: str = "gelu"
    scale_embedding: bool = True
    num_extra_heads: int = 0
    # neck geometry (reference RadioWithNeck :366-407)
    radio_feature_dim: int = 1280
    radio_summary_dim: int = 3840
    neck_dim: int = 1024
    neck_merge: int = 4  # (1, 4) stride conv merges 4 horizontal patches
    pad_token_id: int = 1
    decoder_start_token_id: int = 2
    initializer_range: float = 0.02

    @property
    def head_dim(self) -> int:
        return self.d_model // self.decoder_attention_heads

    def __post_init__(self):
        if self.num_extra_heads:
            # reference creates but never calls these heads (model.py:448-460);
            # checkpoints with them are not yet supported
            raise NotImplementedError("num_extra_heads > 0 is not supported")

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "NemotronParseConfig":
        dec = hf.get("decoder", hf)
        return cls(
            vocab_size=dec.get("vocab_size", 250027),
            d_model=dec.get("d_model", 1024),
            decoder_layers=dec.get("decoder_layers", 12),
            decoder_attention_heads=dec.get("decoder_attention_heads", 16),
            decoder_ffn_dim=dec.get("decoder_ffn_dim", 4096),
            activation_function=dec.get("activation_function", "gelu"),
            scale_embedding=dec.get("scale_embedding", True),
            num_extra_heads=hf.get("num_extra_heads", 0),
            pad_token_id=hf.get("pad_token_id", dec.get("pad_token_id", 1)),
            decoder_start_token_id=hf.get("decoder_start_token_id", 2),
            initializer_range=dec.get("init_std", 0.02),
        )

    def shift_tokens_right(self, labels):
        """Host/device helper mirroring transformers shift_tokens_right (mBART):
        decoder inputs = labels rolled right with the start token prepended and
        ignore(-100) replaced by pad."""
        import numpy as np

        labels = np.asarray(labels)
        shifted = np.zeros_like(labels)
        shifted[:, 1:] = labels[:, :-1]
        shifted[:, 0] = self.decoder_start_token_id
        shifted[shifted == -100] = self.pad_token_id
        return shifted


def _attn_shapes(cfg: NemotronParseConfig, prefix: str) -> dict:
    d, H, dh = cfg.d_model, cfg.decoder_attention_heads, cfg.head_dim
    return {
        f"{prefix}_wq": (d, H, dh), f"{prefix}_bq": (H, dh),
        f"{prefix}_wk": (d, H, dh), f"{prefix}_bk": (H, dh),
        f"{prefix}_wv": (d, H, dh), f"{prefix}_bv": (H, dh),
        f"{prefix}_wo": (H, dh, d), f"{prefix}_bo": (d,),
        f"{prefix}_ln_w": (d,), f"b_{prefix}_ln": (d,),
    }


def _layer_shapes(cfg: NemotronParseConfig) -> dict:
    d, f = cfg.d_model, cfg.decoder_ffn_dim
    return (
        _attn_shapes(cfg, "self")
        | _attn_shapes(cfg, "cross")
        | {
            "fc1": (d, f), "b_fc1": (f,),
            "fc2": (f, d), "b_fc2": (d,),
            "final_ln_w": (d,), "b_final_ln": (d,),
        }
    )


class NemotronParseForConditionalGeneration:
    """Functional model: holds config + backend, operates on param pytrees."""

    config_class = NemotronParseConfig
    hf_architectures = ("NemotronParseForConditionalGeneration",)

    def __init__(self, config: NemotronParseConfig, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()

    # ---- params ----

    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        cfg = self.config
        std = cfg.initializer_range
        d, L = cfg.d_model, cfg.decoder_layers
        keys = iter(jax.random.split(key, 16))

        def w(shape):
            return (jax.random.normal(next(keys), shape, jnp.float32) * std).astype(dtype)

        shapes = _layer_shapes(cfg)
        ks = jax.random.split(next(keys), len(shapes))
        layers = {}
        for j, (name, shape) in enumerate(shapes.items()):
            if name.endswith("ln_w"):
                layers[name] = jnp.ones((L, *shape), dtype)
            elif name.startswith("b_") or "_b" in name:
                layers[name] = jnp.zeros((L, *shape), dtype)
            else:
                layers[name] = (jax.random.normal(ks[j], (L, *shape), jnp.float32) * std).astype(dtype)

        nd = cfg.neck_dim
        params: dict = {
            "embed": w((cfg.vocab_size, d)),
            "emb_ln_w": jnp.ones((d,), dtype), "b_emb_ln": jnp.zeros((d,), dtype),
            "final_ln_w": jnp.ones((d,), dtype), "b_final_ln": jnp.zeros((d,), dtype),
            "layers": layers,
            "lm_head": w((d, cfg.vocab_size)),
            "neck": {
                "conv1_w": w((cfg.radio_feature_dim, nd)), "b_conv1": jnp.zeros((nd,), dtype),
                "ln1_w": jnp.ones((nd,), dtype), "b_ln1": jnp.zeros((nd,), dtype),
                "conv2_w": w((cfg.neck_merge * nd, nd)),  # (1,4) conv, no bias
                "ln2_w": jnp.ones((nd,), dtype), "b_ln2": jnp.zeros((nd,), dtype),
                "sum_w": w((cfg.radio_summary_dim, nd)), "b_sum": jnp.zeros((nd,), dtype),
                "ln3_w": jnp.ones((nd,), dtype), "b_ln3": jnp.zeros((nd,), dtype),
            },
        }
        return params

    def abstract_params(self, dtype=jnp.bfloat16) -> dict:
        return jax.eval_shape(lambda k: self.init(k, dtype), jax.random.key(0))

    def logical_axes(self) -> dict:
        cfg = self.config
        ax = {"embed": ("vocab", "embed"), "emb_ln_w": ("norm",), "b_emb_ln": ("norm",),
              "final_ln_w": ("norm",), "b_final_ln": ("norm",), "lm_head": ("embed", "vocab")}
        layer_ax = {}
        for name, shape in _layer_shapes(cfg).items():
            if len(shape) == 3:
                layer_ax[name] = ("layers", "embed", "heads", "head_dim")[: len(shape) + 1]
            elif len(shape) == 2:
                kind = ("embed", "mlp") if name in ("fc1",) else (
                    ("mlp", "embed") if name == "fc2" else ("heads", "head_dim")
                )
                layer_ax[name] = ("layers",) + kind
            elif name == "b_fc1":
                layer_ax[name] = ("layers", "mlp")
            else:
                layer_ax[name] = ("layers", "norm")
        # fix 3-d projections explicitly
        for p in ("self", "cross"):
            layer_ax[f"{p}_wq"] = ("layers", "embed", "heads", "head_dim")
            layer_ax[f"{p}_wk"] = ("layers", "embed", "heads", "head_dim")
            layer_ax[f"{p}_wv"] = ("layers", "embed", "heads", "head_dim")
            layer_ax[f"{p}_wo"] = ("layers", "heads", "head_dim", "embed")
        ax["layers"] = layer_ax
        ax["neck"] = {
            "conv1_w": ("embed", "mlp"), "b_conv1": ("norm",),
            "ln1_w": ("norm",), "b_ln1": ("norm",),
            "conv2_w": ("embed", "mlp"),
            "ln2_w": ("norm",), "b_ln2": ("norm",),
            "sum_w": ("embed", "mlp"), "b_sum": ("norm",),
            "ln3_w": ("norm",), "b_ln3": ("norm",),
        }
        return ax

    # ---- forward ----

    def encode(self, params, encoder_features, summary, grid_hw):
        """Neck: RADIO features (B, N, 1280) with N = h*w patches -> tokens
        (B, h*(w//4) + 1, neck_dim); summary (B, 3840) appended last."""
        cfg = self.config
        dtype = self.backend.jnp_dtype
        np_ = params["neck"]
        np_ = jax.tree.map(lambda a: a.astype(dtype), np_)
        h, w = grid_hw
        B = encoder_features.shape[0]
        x = encoder_features.astype(dtype) @ np_["conv1_w"] + np_["b_conv1"]
        x = layer_norm(x, np_["ln1_w"], np_["b_ln1"], 1e-6)
        # (1, merge)-stride conv == reshape merge horizontal neighbours + matmul
        x = x.reshape(B, h * (w // cfg.neck_merge), cfg.neck_merge * cfg.neck_dim) @ np_["conv2_w"]
        x = layer_norm(x, np_["ln2_w"], np_["b_ln2"], 1e-6)
        s = summary.astype(dtype) @ np_["sum_w"] + np_["b_sum"]
        s = layer_norm(s, np_["ln3_w"], np_["b_ln3"], 1e-6)
        return jnp.concatenate([x, s[:, None, :]], axis=1)

    def __call__(
        self,
        params,
        decoder_input_ids,  # (B, S)
        encoder_hidden_states=None,  # (B, N, d_model) pre-necked tokens
        encoder_features=None,  # (B, N_patches, 1280) raw RADIO features
        summary=None,  # (B, 3840) RADIO summary
        grid_hw=None,  # (h, w) patch grid for the neck reshape
        segment_ids=None,
        rules=None,
        training=True,
    ):
        cfg = self.config
        dtype = self.backend.jnp_dtype
        backend = self.backend
        d, H, dh = cfg.d_model, cfg.decoder_attention_heads, cfg.head_dim
        scale = d**0.5 if cfg.scale_embedding else 1.0

        if encoder_hidden_states is None and encoder_features is not None:
            encoder_hidden_states = self.encode(params, encoder_features, summary, grid_hw)

        h = params["embed"].astype(dtype)[decoder_input_ids] * jnp.asarray(scale, dtype)
        h = layer_norm(h, params["emb_ln_w"].astype(dtype), params["b_emb_ln"].astype(dtype))
        h = _constrain(h, rules, ("batch", "act_seq", "act_embed"))
        enc = None if encoder_hidden_states is None else encoder_hidden_states.astype(dtype)

        def mha(lp, p, xq, xkv, causal):
            q = jnp.einsum("bsd,dnh->bsnh", xq, lp[f"{p}_wq"]) + lp[f"{p}_bq"]
            k = jnp.einsum("bsd,dnh->bsnh", xkv, lp[f"{p}_wk"]) + lp[f"{p}_bk"]
            v = jnp.einsum("bsd,dnh->bsnh", xkv, lp[f"{p}_wv"]) + lp[f"{p}_bv"]
            out = dot_product_attention(
                q, k, v, causal=causal,
                segment_ids_q=segment_ids if causal else None,
                backend=backend.attention,
            )
            return jnp.einsum("bsnh,nhd->bsd", out, lp[f"{p}_wo"]) + lp[f"{p}_bo"]

        def layer_fn(hh, lp):
            lp = jax.tree.map(lambda a: a.astype(dtype), lp)
            x = layer_norm(hh, lp["self_ln_w"], lp["b_self_ln"])
            hh = hh + mha(lp, "self", x, x, causal=True)
            if enc is not None:
                x = layer_norm(hh, lp["cross_ln_w"], lp["b_cross_ln"])
                hh = hh + mha(lp, "cross", x, enc, causal=False)
            x = layer_norm(hh, lp["final_ln_w"], lp["b_final_ln"])
            act = jax.nn.gelu(x @ lp["fc1"] + lp["b_fc1"], approximate=False)
            hh = hh + (act @ lp["fc2"] + lp["b_fc2"])
            return _constrain(hh, rules, ("batch", "act_seq", "act_embed")), None

        if backend.scan_layers:
            h, _ = jax.lax.scan(backend.layer_remat(layer_fn), h, params["layers"])
        else:
            for i in range(cfg.decoder_layers):
                lp = jax.tree.map(lambda a: a[i], params["layers"])
                h, _ = backend.layer_remat(layer_fn)(h, lp)

        h = layer_norm(h, params["final_ln_w"].astype(dtype), params["b_final_ln"].astype(dtype))
        logits = jnp.einsum("bsd,dv->bsv", h, params["lm_head"].astype(dtype))
        return logits, {}

    # ---- interop ----

    def state_dict_adapter(self):
        from automodel_tpu.models.nemotron_parse.state_dict_adapter import (
            NemotronParseStateDictAdapter,
        )

        return NemotronParseStateDictAdapter(self.config)

    @classmethod
    def from_config(cls, config, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = NemotronParseConfig.from_hf(config)
        return cls(config, backend)
