"""Pytest entry for bench matrix resilience (tools/supervisor_smoke.py
``matrix`` phase + a forced-hang cell, docs/observability.md "Resumable matrix
& cell isolation").

Marked ``slow`` (real bench cells compile); run with ``pytest -m slow`` or
``-m ""``. The matrix phase drives the acceptance scenario end to end:
a poisoned cell still yields a schema-valid artifact naming the absent cell,
``bench_gate`` exits 2 naming it, ``--allow-incomplete`` gates the cells that
ran, and ``--resume`` re-runs only the incomplete cell while replaying the
completed entries byte-identically.

The forced-hang case runs here (not in the smoke) because a hung cell must
burn its whole ``--cell-timeout`` wall budget — the test keeps that budget
tiny. Fast stub-runner coverage of the same retry/skip logic lives in
tests/unit/test_bench_cells.py.
"""

import json
import os
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))


@pytest.mark.slow
def test_matrix_survives_poisoned_cell_and_resumes(tmp_path, cpu_devices):
    import supervisor_smoke

    assert supervisor_smoke.main(str(tmp_path), phase="matrix") == 0


@pytest.mark.slow
def test_hung_cell_times_out_as_watchdog(tmp_path, cpu_devices):
    """A wedged cell costs its wall budget and nothing else: one real
    ``bench.py --cell`` child hangs via the chaos hook (which fires before
    any compilation), the harness kills it at the budget, and the ledger
    records status=timeout/taxonomy=watchdog with a single attempt even
    though retries are allowed (timeouts are never retried)."""
    from automodel_tpu.resilience.harness import (
        CellLedger, run_cells, validate_cell_report,
    )

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONHASHSEED": "0",
        "AUTOMODEL_BENCH_CHAOS": json.dumps({"hang": ["dense_s2048"]}),
    })
    spec = {"id": "dense_s2048", "kind": "dense", "seq_len": 2048, "cpu": True}
    argv = [sys.executable, str(REPO / "bench.py"), "--cell", "dense:2048",
            "--cpu"]
    ledger = CellLedger(str(tmp_path / "ledger.json"))
    counts = run_cells([spec], argv_for=lambda s: argv, ledger=ledger,
                       timeout_s=45.0, retries=3, env=env)
    assert counts["timeout"] == 1 and counts["ran"] == 0
    assert validate_cell_report(ledger.doc) == []
    out = ledger.entry("dense_s2048")["outcome"]
    assert out["status"] == "timeout" and out["taxonomy"] == "watchdog"
    assert out["attempts"] == 1, "timeouts must not be retried"
