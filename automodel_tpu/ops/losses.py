"""Loss functions (reference components/loss/).

All losses return an *unreduced sum* over valid tokens plus the valid-token count, and
the recipe divides by the *global* ``num_label_tokens`` after a psum over the data axes —
the same normalization contract as the reference (every loss normalizes by global label
tokens, loss/masked_ce.py:22).

- ``masked_cross_entropy``: fp32 log-softmax CE with ignore_index masking
  (reference MaskedCrossEntropy, loss/masked_ce.py:22).
- ``chunked_cross_entropy``: vocab-chunked CE that never materializes the full
  (tokens, vocab) fp32 tensor at once (reference ChunkedCrossEntropy, chunked_ce.py:43).
- ``linear_cross_entropy``: fused hidden->logits->CE that takes the hidden states and
  the unembedding matrix and computes CE blockwise over the sequence, so the full logits
  tensor never exists (reference FusedLinearCrossEntropy via cut-cross-entropy,
  loss/linear_ce.py:119). XLA fuses each block's matmul+softmax; a Pallas variant can
  slot in underneath without changing the signature.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

__all__ = [
    "masked_cross_entropy", "chunked_cross_entropy", "linear_cross_entropy",
    "fused_linear_ce_tokens", "pallas_linear_ce_supported", "kd_loss",
]

IGNORE_INDEX = -100


def _ce_sum(logits: jnp.ndarray, labels: jnp.ndarray, ignore_index: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sum of token CE over valid labels + count of valid labels. fp32 math."""
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logits32 = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits32, axis=-1)
    gold = jnp.take_along_axis(logits32, safe_labels[..., None], axis=-1)[..., 0]
    tok_loss = jnp.where(valid, logz - gold, 0.0)
    return tok_loss.sum(), valid.sum()


def masked_cross_entropy(
    logits: jnp.ndarray,  # (..., vocab)
    labels: jnp.ndarray,  # (...,) int, ignore_index = masked
    num_label_tokens: jnp.ndarray | int | None = None,
    ignore_index: int = IGNORE_INDEX,
) -> jnp.ndarray:
    """Mean CE over valid tokens; denominator overridable with the global token count."""
    total, count = _ce_sum(logits, labels, ignore_index)
    denom = count if num_label_tokens is None else num_label_tokens
    return total / jnp.maximum(denom, 1).astype(jnp.float32)


def chunked_cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    num_label_tokens: jnp.ndarray | int | None = None,
    ignore_index: int = IGNORE_INDEX,
    num_chunks: int = 8,
) -> jnp.ndarray:
    """CE computed over sequence chunks to bound the fp32 logits working set."""
    v = logits.shape[-1]
    flat_logits = logits.reshape(-1, v)
    flat_labels = labels.reshape(-1)
    n = flat_labels.shape[0]
    pad = (-n) % num_chunks
    if pad:
        flat_logits = jnp.pad(flat_logits, ((0, pad), (0, 0)))
        flat_labels = jnp.pad(flat_labels, (0, pad), constant_values=ignore_index)
    flat_logits = flat_logits.reshape(num_chunks, -1, v)
    flat_labels = flat_labels.reshape(num_chunks, -1)

    def body(carry, chunk):
        logits_c, labels_c = chunk
        # per-chunk sums ride as stacked outputs, not carries: a zero-init carry
        # would clash with shard_map's varying-axis tracking inside manual regions
        return carry, _ce_sum(logits_c, labels_c, ignore_index)

    _, (sums, counts) = jax.lax.scan(body, (), (flat_logits, flat_labels))
    total, count = sums.sum(), counts.sum()
    denom = count if num_label_tokens is None else num_label_tokens
    return total / jnp.maximum(denom, 1).astype(jnp.float32)


def fused_linear_ce_tokens(
    hidden2d: jnp.ndarray,  # (N, embed)
    unembed: jnp.ndarray,  # (embed, vocab_local)
    labels: jnp.ndarray,  # (N,) GLOBAL label ids
    ignore_index: int = IGNORE_INDEX,
    vocab_offset: jnp.ndarray | int = 0,
    interpret: bool | None = None,
    filter_eps: float | None = 1e-7,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Pallas fused projection+CE partials: per-token (z, gold), logits never in HBM.

    Vocab-shard aware: with ``unembed`` a vocab shard and ``vocab_offset`` its
    global start, combine across shards with ``logsumexp(z)`` / ``sum(gold)``
    before forming ``loss = z - gold`` (reference te_cross_entropy.py:113).
    Returns None-equivalent is not provided — callers must check
    :func:`pallas_linear_ce_supported` first.
    """
    from automodel_tpu.ops.pallas.linear_ce import fused_logsumexp, gold_logits, pick_blocks

    n, e = hidden2d.shape
    block_n, block_v = pick_blocks(e, unembed.shape[1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    local_labels = labels.astype(jnp.int32) - vocab_offset
    gold = gold_logits(hidden2d, unembed, local_labels)
    pad = (-n) % block_n
    h_pad = jnp.pad(hidden2d, ((0, pad), (0, 0))) if pad else hidden2d
    z = fused_logsumexp(h_pad, unembed, block_n, block_v, interpret, filter_eps)
    return z[:n], gold


def pallas_linear_ce_supported(embed: int, vocab_local: int) -> bool:
    """True only when BOTH the forward and backward kernels can tile the shape.

    The backward adds an f32 accumulator to the VMEM budget, so some shapes
    (e.g. embed>=12288 with 128k vocab) tile forward but not backward; checking
    only the forward would run training straight into the backward's fallback
    (or, before it existed, a trace-time crash)."""
    from automodel_tpu.ops.pallas.linear_ce import pick_blocks, pick_bwd_blocks

    fwd = pick_blocks(embed, vocab_local)
    if fwd is None:
        return False
    return pick_bwd_blocks(embed, vocab_local, fwd[1], None) is not None


def linear_cross_entropy(
    hidden: jnp.ndarray,  # (..., embed)
    unembed: jnp.ndarray,  # (embed, vocab)
    labels: jnp.ndarray,  # (...,)
    num_label_tokens: jnp.ndarray | int | None = None,
    ignore_index: int = IGNORE_INDEX,
    block_size: int = 1024,
    impl: str = "auto",  # auto | pallas | xla
    filter_eps: float | None = 1e-7,
) -> jnp.ndarray:
    """Fused projection+CE: logits exist only one (block, vocab) tile at a time.

    ``impl="pallas"`` (or auto on TPU) routes to the Pallas kernel pair with a
    manual VJP — logits live only as a VMEM tile even in the backward. The XLA
    path is the blockwise-remat scan; it is also the fallback for shapes the
    kernel can't tile. NOTE: the pallas path assumes an unsharded (replicated)
    ``unembed``; under tensor-parallel vocab sharding use
    :func:`fused_linear_ce_tokens` inside shard_map instead.
    """
    e = hidden.shape[-1]
    use_pallas = impl == "pallas" or (impl == "auto" and jax.default_backend() == "tpu")
    if use_pallas and pallas_linear_ce_supported(e, unembed.shape[-1]):
        flat_h = hidden.reshape(-1, e)
        flat_labels = labels.reshape(-1)
        z, gold = fused_linear_ce_tokens(
            flat_h, unembed, flat_labels, ignore_index,
            interpret=None if impl == "auto" else (jax.default_backend() != "tpu"),
            filter_eps=filter_eps,
        )
        valid = flat_labels != ignore_index
        total = jnp.where(valid, z - gold, 0.0).sum()
        count = valid.sum()
        denom = count if num_label_tokens is None else num_label_tokens
        return total / jnp.maximum(denom, 1).astype(jnp.float32)
    flat_h = hidden.reshape(-1, e)
    flat_labels = labels.reshape(-1)
    n = flat_h.shape[0]
    pad = (-n) % block_size
    if pad:
        flat_h = jnp.pad(flat_h, ((0, pad), (0, 0)))
        flat_labels = jnp.pad(flat_labels, (0, pad), constant_values=ignore_index)
    blocks_h = flat_h.reshape(-1, block_size, e)
    blocks_l = flat_labels.reshape(-1, block_size)

    @jax.checkpoint
    def body(carry, blk):
        # remat: the (block, vocab) logits tile is recomputed in backward instead of
        # saved per scan step — without this the scan residuals re-materialize the
        # full logits tensor and the fusion saves nothing (cut-cross-entropy trick).
        # Sums ride as stacked outputs, not carries (shard_map varying-axis safety).
        h_b, l_b = blk
        logits_b = h_b.astype(jnp.float32) @ unembed.astype(jnp.float32)
        return carry, _ce_sum(logits_b, l_b, ignore_index)

    _, (sums, counts) = jax.lax.scan(body, (), (blocks_h, blocks_l))
    total, count = sums.sum(), counts.sum()
    denom = count if num_label_tokens is None else num_label_tokens
    return total / jnp.maximum(denom, 1).astype(jnp.float32)


def kd_loss(
    student_logits: jnp.ndarray,
    teacher_logits: jnp.ndarray,
    labels: jnp.ndarray,
    temperature: float = 1.0,
    ignore_index: int = IGNORE_INDEX,
    num_label_tokens: jnp.ndarray | int | None = None,
    divergence: str = "forward_kl",
) -> jnp.ndarray:
    """Distillation divergence on valid tokens (reference loss/kd_loss.py:21 is
    forward-KL; reverse-KL and symmetric JS ship as config options on top).

    - ``forward_kl``: KL(teacher || student) — mode-covering, the reference's loss.
    - ``reverse_kl``: KL(student || teacher) — mode-seeking, the MiniLLM-style
      objective for generative students.
    - ``js``: Jensen-Shannon, symmetric middle ground.
    """
    valid = labels != ignore_index
    t = jax.nn.log_softmax(teacher_logits.astype(jnp.float32) / temperature, axis=-1)
    s = jax.nn.log_softmax(student_logits.astype(jnp.float32) / temperature, axis=-1)
    if divergence == "forward_kl":
        per_tok = (jnp.exp(t) * (t - s)).sum(-1)
    elif divergence == "reverse_kl":
        per_tok = (jnp.exp(s) * (s - t)).sum(-1)
    elif divergence == "js":
        m = jnp.logaddexp(t, s) - jnp.log(2.0)
        per_tok = 0.5 * ((jnp.exp(t) * (t - m)).sum(-1) + (jnp.exp(s) * (s - m)).sum(-1))
    else:
        raise ValueError(
            f"unknown kd divergence {divergence!r} (forward_kl | reverse_kl | js)"
        )
    kl = per_tok * (temperature**2)
    total = jnp.where(valid, kl, 0.0).sum()
    denom = valid.sum() if num_label_tokens is None else num_label_tokens
    return total / jnp.maximum(denom, 1).astype(jnp.float32)
