"""EP-dispatch microbench: dense GSPMD path vs explicit a2a (VERDICT r3 #6).

Single chip, ep=1 degenerate mesh: the all_to_all is a self-copy, so the delta
between the two dispatchers is exactly the a2a path's bucketing overhead — the
one-hot-cumsum queue positions + (ep, cap, D) scatter layout — with zero real
ICI traffic in either. Run on the TPU via `python tools/bench_a2a_dispatch.py`;
prints one JSON line per (dispatcher, shape).
"""

from __future__ import annotations

import json
import time

import numpy as np


def measure(dispatcher: str, *, seq_len=2048, micro_batch=4, n_steps=10):
    import jax
    import jax.numpy as jnp
    import optax

    from automodel_tpu.models.auto import AutoModelForCausalLM
    from automodel_tpu.models.common.backend import BackendConfig
    from automodel_tpu.ops.losses import masked_cross_entropy
    from automodel_tpu.parallel.mesh import MeshContext, default_sharding_rules
    from automodel_tpu.training.train_step import make_train_step

    ctx = MeshContext(ep=1, dp_shard=1, world_size=1)
    mesh = ctx.build_mesh(jax.devices()[:1])
    rules = default_sharding_rules().with_mesh(mesh)
    # qwen3-moe-A3B-ish proxy scaled to one 16GB chip
    hf_cfg = {
        "architectures": ["Qwen3MoeForCausalLM"],
        "vocab_size": 32000, "hidden_size": 1024, "intermediate_size": 3072,
        "moe_intermediate_size": 384, "num_hidden_layers": 12,
        "num_attention_heads": 16, "num_key_value_heads": 4, "head_dim": 64,
        "num_experts": 32, "num_experts_per_tok": 4, "norm_topk_prob": True,
        "max_position_embeddings": seq_len,
    }
    backend = BackendConfig(dtype="bfloat16", attention="flash",
                            remat_policy="mlp_attn_dots", dispatcher=dispatcher)
    model = AutoModelForCausalLM.from_config(hf_cfg, backend)
    with mesh:
        params = model.init(jax.random.key(0), jnp.bfloat16)
        optimizer = optax.chain(optax.scale_by_factored_rms(), optax.scale(-1e-5))
        opt_state = jax.jit(optimizer.init)(params)

        def forward_loss(p, batch, n):
            out, stats = model(
                p, batch["input_ids"], positions=batch["positions"],
                segment_ids=batch["segment_ids"],
                token_mask=batch["segment_ids"] != 0,
                rules=rules if mesh.size > 1 else None, training=True,
            )
            return masked_cross_entropy(out, batch["labels"], n), {
                "expert_load": stats["expert_load"]}

        step = jax.jit(make_train_step(forward_loss, optimizer), donate_argnums=(0, 1))
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 32000, (1, micro_batch, seq_len)).astype(np.int32)
        batch = {
            "input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids),
            "positions": jnp.broadcast_to(jnp.arange(seq_len, dtype=jnp.int32), ids.shape),
            "segment_ids": jnp.ones_like(jnp.asarray(ids)),
        }
        for _ in range(3):  # warmup + compile
            params, opt_state, m = step(params, opt_state, batch)
        float(m["loss"])  # sync through the tunnel (block_until_ready doesn't)
        t0 = time.perf_counter()
        for _ in range(n_steps):
            params, opt_state, m = step(params, opt_state, batch)
        float(m["loss"])
        dt = (time.perf_counter() - t0) / n_steps
    tokens = micro_batch * seq_len
    return {"dispatcher": dispatcher, "seq_len": seq_len,
            "step_time_ms": round(dt * 1e3, 2),
            "tokens_per_sec": round(tokens / dt, 1)}


if __name__ == "__main__":
    for disp in ("dense", "a2a"):
        print(json.dumps(measure(disp)))
