"""GPT-2 HF key mapping. HF Conv1D weights are stored (in, out) — our orientation —
so transforms are identity; only the tied lm_head and the ``transformer.`` prefix
need handling."""

from __future__ import annotations

from automodel_tpu.models.common.state_dict import Entry, MappingAdapter

__all__ = ["GPT2StateDictAdapter"]


class GPT2StateDictAdapter(MappingAdapter):
    def __init__(self, cfg, scan_layers: bool = True):
        pre = "transformer.h.{i}"
        entries = [
            Entry("transformer.wte.weight", "wte"),
            Entry("transformer.wpe.weight", "wpe"),
            Entry("transformer.ln_f.weight", "lnf_w"),
            Entry("transformer.ln_f.bias", "lnf_b"),
            Entry(f"{pre}.ln_1.weight", "layers.ln1_w"),
            Entry(f"{pre}.ln_1.bias", "layers.ln1_b"),
            Entry(f"{pre}.attn.c_attn.weight", "layers.c_attn"),
            Entry(f"{pre}.attn.c_attn.bias", "layers.c_attn_b"),
            Entry(f"{pre}.attn.c_proj.weight", "layers.c_proj"),
            Entry(f"{pre}.attn.c_proj.bias", "layers.c_proj_b"),
            Entry(f"{pre}.ln_2.weight", "layers.ln2_w"),
            Entry(f"{pre}.ln_2.bias", "layers.ln2_b"),
            Entry(f"{pre}.mlp.c_fc.weight", "layers.c_fc"),
            Entry(f"{pre}.mlp.c_fc.bias", "layers.c_fc_b"),
            Entry(f"{pre}.mlp.c_proj.weight", "layers.c_proj2"),
            Entry(f"{pre}.mlp.c_proj.bias", "layers.c_proj2_b"),
        ]
        super().__init__(entries, cfg.n_layer, scan_layers)
