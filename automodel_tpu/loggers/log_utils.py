"""Rank-aware logging setup (reference components/loggers/log_utils.py)."""

from __future__ import annotations

import logging
import sys

import jax

__all__ = ["setup_logging", "rank_prefix"]


def rank_prefix() -> str:
    try:
        return f"[p{jax.process_index()}]"
    except RuntimeError:
        return "[p?]"


def setup_logging(level: int | str = logging.INFO, main_process_only: bool = True) -> None:
    """Configure root logging; non-main hosts log warnings+ only by default."""
    if isinstance(level, str):
        level = getattr(logging, level.upper())
    try:
        is_main = jax.process_index() == 0
    except RuntimeError:
        is_main = True
    effective = level if (is_main or not main_process_only) else logging.WARNING
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            fmt=f"%(asctime)s {rank_prefix()} %(levelname)s %(name)s: %(message)s",
            datefmt="%H:%M:%S",
        )
    )
    root = logging.getLogger()
    root.handlers = [handler]
    root.setLevel(effective)
