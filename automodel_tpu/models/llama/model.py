"""Llama family (Llama 2/3/3.x) — TPU-native (reference models/llama/model.py).

Also serves Qwen2 (attention_bias=True) and Qwen3 (qk_norm=True, head_dim override)
through config, the way the reference's optimized TP plans treat these families as one
lineage (distributed/optimized_tp_plans.py:406).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.transformer import (
    DenseDecoderConfig,
    decoder_forward,
    dense_decoder_logical_axes,
    init_dense_decoder_params,
)

__all__ = ["LlamaConfig", "LlamaForCausalLM"]


def _is_olmo2(hf: dict) -> bool:
    archs = "".join(hf.get("architectures", []))
    return "Olmo2" in archs or "Olmo3" in archs


def _cohere2_layer_types(hf: dict) -> list:
    """Cohere2's per-layer pattern: explicit layer_types, or derived from the
    original R7B config format's integer sliding_window_pattern the way
    Cohere2Config's BC branch does (every pattern-th layer is full attention)."""
    if hf.get("layer_types"):
        return hf["layer_types"]
    p = int(hf.get("sliding_window_pattern", 4))
    return ["sliding_attention" if (i + 1) % p else "full_attention"
            for i in range(hf["num_hidden_layers"])]


def _no_rope_layers(hf: dict) -> list | None:
    """Per-layer rope enable (1 = rope ON); None when every layer uses rope.

    - SmolLM3: explicit no_rope_layers list, or derived from
      no_rope_layer_interval (every interval-th layer is NoPE)
    - Cohere2: rope applies ONLY on sliding_attention layers (transformers
      Cohere2Attention gates rotary on self.sliding_window)"""
    layers = hf.get("no_rope_layers")
    if layers is None and hf.get("no_rope_layer_interval"):
        k = int(hf["no_rope_layer_interval"])
        layers = [int((i + 1) % k != 0) for i in range(hf["num_hidden_layers"])]
    if layers is None and "Cohere2" in "".join(hf.get("architectures", [])):
        layers = [int(t == "sliding_attention") for t in _cohere2_layer_types(hf)]
    if layers is not None and all(layers):
        return None
    return layers


@dataclasses.dataclass
class LlamaConfig(DenseDecoderConfig):
    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "LlamaConfig":
        """Build from an HF config.json dict (llama/qwen2/qwen3/mistral compatible)."""
        archs = "".join(hf.get("architectures", []))
        is_cohere = "Cohere" in archs
        is_glm4 = "Glm4" in archs  # dense glm4 only (Glm4Moe routes to its own family)
        is_glm = "Glm" in archs  # old GLM + Glm4: both use interleaved partial rope
        is_arcee = "Arcee" in archs  # ungated relu^2 MLP
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            num_attention_heads=hf["num_attention_heads"],
            num_key_value_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            head_dim=hf.get("head_dim"),
            max_position_embeddings=hf.get("max_position_embeddings", 4096),
            rope_theta=hf.get("rope_theta", 10000.0),
            rope_scaling=hf.get("rope_scaling"),
            partial_rotary_factor=hf.get("partial_rotary_factor", 1.0),
            rms_norm_eps=hf.get("rms_norm_eps", hf.get("layer_norm_eps", 1e-5)),
            tie_word_embeddings=hf.get("tie_word_embeddings", is_cohere),
            attention_bias=hf.get("attention_bias", hf.get("qkv_bias", False)),
            qk_norm="Qwen3" in archs or (is_cohere and hf.get("use_qk_norm", False)),
            # Olmo2/3: post-sublayer norms + whole-projection qk-RMSNorm
            qk_norm_whole=_is_olmo2(hf),
            norm_placement=("post" if _is_olmo2(hf)
                            else "sandwich" if is_glm4 else "pre"),
            # Cohere: mean-centered LN, parallel attn||mlp block, interleaved
            # rope, and a MULTIPLicative logit_scale (== dividing by its inverse)
            norm_type="layernorm" if is_cohere else "rms",
            parallel_block=is_cohere,
            mlp_gated=not is_arcee,
            mlp_act="relu2" if is_arcee else "silu",
            rope_interleaved=is_cohere or is_glm,
            sliding_window=hf.get("sliding_window") if hf.get("use_sliding_window", True) else None,
            layer_types=(_cohere2_layer_types(hf) if "Cohere2" in archs
                         else hf.get("layer_types")),
            no_rope_layers=_no_rope_layers(hf),
            initializer_range=hf.get("initializer_range", 0.02),
            # granite mup-style scalars (identity for every other family)
            embedding_multiplier=hf.get("embedding_multiplier", 1.0),
            residual_multiplier=hf.get("residual_multiplier", 1.0),
            attention_multiplier=hf.get("attention_multiplier"),
            logits_scaling=(1.0 / hf["logit_scale"]
                            if is_cohere and hf.get("logit_scale")
                            else hf.get("logits_scaling", 1.0)),
        )


class LlamaForCausalLM:
    """Functional model: holds config + backend, operates on param pytrees."""

    config_class = LlamaConfig
    hf_architectures = (
        "LlamaForCausalLM",
        "Qwen2ForCausalLM",
        "Qwen3ForCausalLM",
        "MistralForCausalLM",
    )

    def __init__(self, config: LlamaConfig, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()

    # -- params -------------------------------------------------------------
    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        return init_dense_decoder_params(self.config, key, dtype, self.backend.scan_layers)

    def logical_axes(self) -> dict:
        return dense_decoder_logical_axes(self.config, self.backend.scan_layers)

    def abstract_params(self, dtype=jnp.bfloat16) -> dict:
        """Shape/dtype skeleton without allocating (reference meta-device init,
        auto_model.py:235-242) — feed to jax.eval_shape / checkpoint restore."""
        return jax.eval_shape(lambda k: self.init(k, dtype), jax.random.key(0))

    # -- forward ------------------------------------------------------------
    def __call__(self, params, input_ids, positions=None, segment_ids=None, rules=None,
                 return_hidden=False, cache=None):
        return decoder_forward(
            self.config, self.backend, params, input_ids,
            positions=positions, segment_ids=segment_ids, rules=rules,
            return_hidden=return_hidden, cache=cache,
        )

    def generate(self, params, input_ids, **kw):
        """Sample from the model with a KV cache (see :func:`automodel_tpu.generation.generate`)."""
        from automodel_tpu.generation import generate

        return generate(self, params, input_ids, **kw)

    # -- HF interop ---------------------------------------------------------
    def state_dict_adapter(self):
        from automodel_tpu.models.llama.state_dict_adapter import LlamaStateDictAdapter

        return LlamaStateDictAdapter(self.config, scan_layers=self.backend.scan_layers)

    @classmethod
    def from_config(cls, config: LlamaConfig | dict, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = LlamaConfig.from_hf(config)
        return cls(config, backend)
