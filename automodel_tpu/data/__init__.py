from automodel_tpu.data.loader import DataLoader
from automodel_tpu.data.collate import sft_collate, stack_batches

__all__ = ["DataLoader", "sft_collate", "stack_batches"]
