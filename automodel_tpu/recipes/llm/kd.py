"""Knowledge-distillation recipe (reference KnowledgeDistillationRecipeForNextTokenPrediction,
recipes/llm/kd.py:145).

A teacher model runs forward-only next to the student; the loss blends hard-label CE
with forward-KL to the teacher's temperature-softened distribution:

    loss = (1 - kd_ratio) * CE(student, labels) + kd_ratio * KL(teacher || student)

The teacher rides through the jitted step as a *frozen* pytree argument (the same
``with_frozen`` path PEFT uses) — no gradients, no optimizer state, donated nothing.

YAML adds two sections to the finetune contract:

.. code-block:: yaml

    teacher_model:
      pretrained_model_name_or_path: /path/to/teacher   # or config: {...}
    kd: {temperature: 1.0, kd_ratio: 0.5}
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.config.cli_overrides import parse_args_and_load_config
from automodel_tpu.models.auto import AutoModelForCausalLM, load_hf_config
from automodel_tpu.ops.losses import kd_loss, masked_cross_entropy
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction
from automodel_tpu.training.train_step import count_label_tokens, make_train_step

logger = logging.getLogger(__name__)

__all__ = ["KnowledgeDistillationRecipe", "main"]


class KnowledgeDistillationRecipe(TrainFinetuneRecipeForNextTokenPrediction):
    def _build_teacher(self):
        cfg = self.cfg
        t_cfg = cfg.get("teacher_model")
        if t_cfg is None:
            raise ValueError("kd recipe needs a teacher_model section")
        pretrained = t_cfg.get("pretrained_model_name_or_path")
        with self.mesh:
            if pretrained:
                self.teacher, self.teacher_params = AutoModelForCausalLM.from_pretrained(
                    pretrained, backend=self.backend, dtype=jnp.float32, rules=self.rules
                )
            else:
                model_cfg = t_cfg.get("config")
                if model_cfg is None:
                    raise ValueError("teacher_model needs pretrained_model_name_or_path or config")
                hf = model_cfg.to_dict() if isinstance(model_cfg, ConfigNode) else dict(model_cfg)
                self.teacher = AutoModelForCausalLM.from_config(hf, backend=self.backend)
                shardings = self.rules.tree_sharding(self.teacher.logical_axes())
                init_fn = jax.jit(lambda k: self.teacher.init(k, jnp.float32), out_shardings=shardings)
                self.teacher_params = init_fn(self.rng.key("teacher_init"))
        n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.teacher_params))
        logger.info("teacher: %s (%.1fM params)", type(self.teacher).__name__, n / 1e6)

    def _build_train_step(self):
        self._build_teacher()
        temperature = float(self.cfg.get("kd.temperature", 1.0))
        kd_ratio = float(self.cfg.get("kd.kd_ratio", 0.5))
        divergence = str(self.cfg.get("kd.divergence", "forward_kl"))
        self._static_log_fields = {"kd_ratio": kd_ratio, "temperature": temperature,
                                   "kd_divergence": divergence}
        logger.info("kd: ratio=%s T=%s divergence=%s", kd_ratio, temperature, divergence)
        if self.mesh_ctx.pp > 1:
            return self._build_pp_train_step(temperature, kd_ratio, divergence)

        teacher_is_moe = (getattr(self.teacher.config, "moe", None) is not None
                          or getattr(getattr(self.teacher.config, "text", None),
                                     "moe", None) is not None)

        def kd_core(student_params, teacher_params, batch, num_label_tokens):
            s_kw = ({"token_mask": batch["segment_ids"] != 0, "training": True}
                    if self._moe_config is not None else {})
            out = self.model(
                student_params, batch["input_ids"], positions=batch["positions"],
                segment_ids=batch["segment_ids"], rules=self.rules, **s_kw,
            )
            # MoE students return (logits, stats) — same contract train_ft's
            # _forward_loss consumes; expert_load flows to metrics/gate-bias
            student_logits, stats = out if isinstance(out, tuple) else (out, None)
            t_kw = ({"token_mask": batch["segment_ids"] != 0, "training": False}
                    if teacher_is_moe else {})
            t_out = self.teacher(
                teacher_params, batch["input_ids"], positions=batch["positions"],
                segment_ids=batch["segment_ids"], rules=self.rules, **t_kw,
            )
            teacher_logits = jax.lax.stop_gradient(
                t_out[0] if isinstance(t_out, tuple) else t_out
            )
            ce = masked_cross_entropy(student_logits, batch["labels"], num_label_tokens)
            kd = kd_loss(
                student_logits, teacher_logits, batch["labels"],
                temperature=temperature, num_label_tokens=num_label_tokens,
                divergence=divergence,
            )
            loss = (1.0 - kd_ratio) * ce + kd_ratio * kd
            if stats is None:
                return loss
            aux = {"expert_load": stats["expert_load"]}
            if "dropped_token_frac" in stats:
                aux["dropped_token_frac"] = stats["dropped_token_frac"]
            if stats["aux_loss"] is not None:
                mb_tokens = count_label_tokens(batch["labels"]).astype(jnp.float32)
                loss = loss + self._moe_config.aux_loss_coeff * stats["aux_loss"] * (
                    mb_tokens / num_label_tokens
                )
            return loss, aux

        use_dropout = self.peft is not None and self.peft.dropout > 0.0
        if self.peft is not None:
            # kd + peft (reference composes them, infrastructure.py:303): the
            # frozen slot carries BOTH the teacher and the student's lora base
            from automodel_tpu.peft.lora import lora_merged_loss

            kd_forward = lora_merged_loss(
                lambda merged, fr, b, n: kd_core(merged, fr["teacher"], b, n),
                lambda fr: fr["base"], self.peft, use_dropout,
            )
        else:
            def kd_forward(params, frozen, batch, num_label_tokens):
                return kd_core(params, frozen["teacher"], batch, num_label_tokens)

        self._step_needs_rng = use_dropout
        post_update = (self._post_update()
                       if (self._moe_config is not None and self.peft is None) else None)
        step = make_train_step(kd_forward, self.optimizer, with_frozen=True,
                               guard_nonfinite=self._check_nan_grads,
                               pass_rng=use_dropout, post_update=post_update)
        return jax.jit(step, donate_argnums=(0, 1))

    def _build_pp_train_step(self, temperature: float, kd_ratio: float,
                             divergence: str = "forward_kl"):
        """kd x pp (reference composes them through its one sequencing path,
        infrastructure.py:303): the STUDENT's layer stack pipelines over pp and
        yields final hidden states outside the manual region; the student head,
        the teacher forward, and the blended CE+KL loss then run per microbatch
        in plain GSPMD (lax.map — one microbatch's logits pair live at a time).
        The teacher is not pipelined: its layer stacks stay sharded by the rules
        (the pp axis acts as an extra FSDP axis for it), gathered per layer
        during its forward-only pass."""
        from automodel_tpu.models.common.transformer import embed_lookup
        from automodel_tpu.parallel.pipeline import (
            make_dense_decoder_pp_hidden, make_head_logits, make_moe_pp_hidden,
        )
        from automodel_tpu.training.train_step import make_pp_train_step

        cfg, backend = self.model.config, self.model.backend
        dtype = backend.jnp_dtype
        virtual = int(self.cfg.get("distributed.pp_virtual_stages", 1))
        head_logits = make_head_logits(cfg, dtype)
        is_moe = self._moe_config is not None
        teacher_is_moe = (getattr(self.teacher.config, "moe", None) is not None
                          or getattr(getattr(self.teacher.config, "text", None),
                                     "moe", None) is not None)
        if is_moe:
            # MoE students ride the same pipelined hidden-state path train_ft's
            # MoE pp loss is built on (make_moe_pp_loss); expert_load flows to
            # the gate-bias post-update exactly as in the non-KD recipe
            layers_key = "moe_layers"
            student_hidden = make_moe_pp_hidden(
                self.model, self.mesh, self.rules, seq_len_hint=self.seq_len,
                circular_repeats=virtual,
            )
        else:
            layers_key = "layers"
            dense_hidden = make_dense_decoder_pp_hidden(
                cfg, backend, self.mesh, circular_repeats=virtual
            )

            def student_hidden(params, batch_stack, n):
                other = {k: v for k, v in params.items() if k != "layers"}
                x_stack = {
                    "h": embed_lookup(other["embed"], batch_stack["input_ids"],
                                      dtype, self.rules,
                                      scale=getattr(cfg, "embedding_multiplier", 1.0)),
                    "positions": batch_stack["positions"],
                    "segment_ids": batch_stack["segment_ids"],
                }
                return dense_hidden(params["layers"], x_stack), 0.0, {}

        def kd_pp_core(student_params, teacher_params, batch_stack, n):
            h_stack, aux_loss, extras = student_hidden(student_params, batch_stack, n)
            other = {k: v for k, v in student_params.items() if k != layers_key}

            def mb_loss(args):
                h_mb, mb = args
                s_logits = head_logits(other, h_mb)
                t_kw = ({"token_mask": mb["segment_ids"] != 0, "training": False}
                        if teacher_is_moe else {})
                t_out = self.teacher(
                    teacher_params, mb["input_ids"], positions=mb["positions"],
                    segment_ids=mb["segment_ids"], rules=self.rules, **t_kw,
                )
                t_logits = jax.lax.stop_gradient(
                    t_out[0] if isinstance(t_out, tuple) else t_out
                )
                ce = masked_cross_entropy(s_logits, mb["labels"], n)
                kd = kd_loss(s_logits, t_logits, mb["labels"],
                             temperature=temperature, num_label_tokens=n,
                             divergence=divergence)
                return (1.0 - kd_ratio) * ce + kd_ratio * kd

            loss = jax.lax.map(mb_loss, (h_stack, batch_stack)).sum() + aux_loss
            return (loss, extras) if is_moe else loss

        use_dropout = self.peft is not None and self.peft.dropout > 0.0
        if self.peft is not None:
            from automodel_tpu.peft.lora import lora_merged_loss

            kd_forward = lora_merged_loss(
                lambda merged, fr, bs, n: kd_pp_core(merged, fr["teacher"], bs, n),
                lambda fr: fr["base"], self.peft, use_dropout,
            )
        else:
            def kd_forward(params, frozen, batch_stack, n):
                return kd_pp_core(params, frozen["teacher"], batch_stack, n)

        self._step_needs_rng = use_dropout
        post_update = self._post_update() if (is_moe and self.peft is None) else None
        step = make_pp_train_step(kd_forward, self.optimizer, with_frozen=True,
                                  guard_nonfinite=self._check_nan_grads,
                                  post_update=post_update, pass_rng=use_dropout)
        return jax.jit(step, donate_argnums=(0, 1))

    @property
    def _kd_frozen_arg(self):
        frozen = {"teacher": self.teacher_params}
        if self.peft is not None:
            frozen["base"] = self.params
        return frozen

    def run_train_validation_loop(self):
        # thread the teacher (and, under peft, the student base) through the
        # frozen slot; the base loop's peft extra is replaced by _kd_frozen_arg
        # but its trailing dropout rng (when _step_needs_rng) passes through
        jitted = self._train_step
        self._train_step = lambda p, o, stack, *extra: jitted(
            p, o, stack, self._kd_frozen_arg,
            *((extra[-1],) if self._step_needs_rng else ()),
        )
        super().run_train_validation_loop()


def main(cfg: ConfigNode | None = None, argv=None):
    if cfg is None:
        cfg = parse_args_and_load_config(argv)
    recipe = KnowledgeDistillationRecipe(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()
    return recipe


if __name__ == "__main__":
    main()
