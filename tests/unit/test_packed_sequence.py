"""Packed-sequence tests (reference tests for packed_sequence.py / thd_utils.py).

The crucial property: a model forward over a pack must produce, at each sample's
token positions, the same logits as running that sample alone — segment-id masking
plus per-sample position restart is a complete THD replacement."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.data.llm.packed import pack_dataset, packed_collate
from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.llama.model import LlamaForCausalLM

IGNORE = -100


def _samples(lengths, vocab=97, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(1, vocab, size=n + 1).tolist()} for n in lengths]


class TestPackDataset:
    def test_greedy_fill_and_shapes(self):
        ds = pack_dataset(_samples([7, 7, 7]), packed_sequence_size=16)
        # 7-token samples (8 ids -> 7 after shift): two fit per 16-pack
        assert len(ds) == 2
        p = ds[0]
        assert p["input_ids"].shape == (16,)
        np.testing.assert_array_equal(np.unique(p["segment_ids"]), [0, 1, 2])
        # positions restart at each sample
        seg2_pos = p["positions"][p["segment_ids"] == 2]
        np.testing.assert_array_equal(seg2_pos, np.arange(7))

    def test_shift_is_within_sample(self):
        sample = {"input_ids": [10, 11, 12, 13]}
        ds = pack_dataset([sample, sample], packed_sequence_size=8)
        p = ds[0]
        # inputs [10,11,12][10,11,12] + pad; labels [11,12,13][11,12,13]
        np.testing.assert_array_equal(p["input_ids"][:6], [10, 11, 12, 10, 11, 12])
        np.testing.assert_array_equal(p["labels"][:6], [11, 12, 13, 11, 12, 13])
        # no label crosses the boundary: label at last token of sample 1 is 13 (its
        # own next token), not 10 (the next sample's first token)

    def test_prompt_masking(self):
        ds = pack_dataset(
            [{"input_ids": [1, 2, 3, 4, 5], "prompt_len": 3}], packed_sequence_size=8
        )
        labels = ds[0]["labels"]
        np.testing.assert_array_equal(labels[:4], [IGNORE, IGNORE, 4, 5])

    def test_long_sample_raises_or_drops(self):
        with pytest.raises(ValueError, match="too long"):
            pack_dataset(_samples([20]), packed_sequence_size=8)
        ds = pack_dataset(_samples([20, 4]), packed_sequence_size=8, drop_long_samples=True)
        assert len(ds) == 1

    def test_max_packs(self):
        ds = pack_dataset(_samples([7] * 10), packed_sequence_size=8, max_packs=3)
        assert len(ds) == 3

    def test_collate_stacks(self):
        ds = pack_dataset(_samples([7, 7, 7, 7]), packed_sequence_size=8)
        batch = packed_collate([ds[0], ds[1]])
        assert batch["input_ids"].shape == (2, 8)


class TestPackedForwardEquivalence:
    def test_packed_logits_match_unpacked(self):
        cfg = {
            "architectures": ["LlamaForCausalLM"],
            "vocab_size": 97,
            "hidden_size": 32,
            "intermediate_size": 64,
            "num_hidden_layers": 2,
            "num_attention_heads": 4,
            "num_key_value_heads": 2,
            "max_position_embeddings": 64,
        }
        model = LlamaForCausalLM.from_config(cfg, BackendConfig(dtype="float32"))
        params = model.init(jax.random.key(0), jnp.float32)
        samples = _samples([10, 5], vocab=97, seed=3)
        ds = pack_dataset(samples, packed_sequence_size=16)
        pack = packed_collate([ds[0]])
        packed_logits = np.asarray(
            model(
                params,
                jnp.asarray(pack["input_ids"]),
                positions=jnp.asarray(pack["positions"]),
                segment_ids=jnp.asarray(pack["segment_ids"]),
            )
        )
        for seg, sample in enumerate(samples, start=1):
            ids = np.asarray(sample["input_ids"][:-1], np.int32)[None]
            solo = np.asarray(model(params, jnp.asarray(ids)))
            sel = pack["segment_ids"][0] == seg
            np.testing.assert_allclose(
                packed_logits[0, sel], solo[0], rtol=2e-4, atol=2e-5,
                err_msg=f"segment {seg} logits leak across pack boundary",
            )
