"""FP8 matmul with dynamic tensorwise scaling (reference components/quantization/fp8.py,
which delegates to torchao Float8Linear; here it is a ~60-line custom_vjp over XLA's
native fp8 dot support).

Recipe (the standard "tensorwise dynamic" float8 training scheme):
- forward: x, w quantized to e4m3 with per-tensor amax scaling; accumulate in fp32
- backward: the incoming gradient is quantized to e5m2 (wider range, less precision —
  gradients tolerate it), weights/activations reuse e4m3

On TPU the MXU consumes fp8 pairs natively; off-TPU XLA emulates, so tests run
anywhere. The first/last layers (embed, lm_head) stay high-precision, matching the
reference's filter_fqns default.
"""

from __future__ import annotations

import collections

import jax
import jax.numpy as jnp

__all__ = ["AmaxHistory", "E4M3_MAX", "E5M2_MAX", "fp8_matmul", "project"]

# representable maxima of the two training formats; public so the dynamics
# telemetry (observability/dynamics.py) can count grad values past the point
# where the e4m3 fwd / e5m2 bwd quantizers would saturate
E4M3_MAX = 448.0
E5M2_MAX = 57344.0
_E4M3_MAX = E4M3_MAX
_E5M2_MAX = E5M2_MAX


class AmaxHistory:
    """Host-side rolling amax window (the delayed-scaling bookkeeping shape,
    torchao Float8 history semantics): ``update(amax)`` folds one grad-path
    amax sample and returns the ``dynamics/num/*`` row fields — the window
    max (what a delayed-scaling recipe would derive its scale from) and the
    current sample's headroom to e5m2 saturation in doublings."""

    def __init__(self, window: int = 16):
        self._window: collections.deque = collections.deque(maxlen=max(int(window), 1))

    def update(self, amax: float) -> dict[str, float]:
        import math

        out: dict[str, float] = {}
        a = float(amax)
        if math.isfinite(a):
            self._window.append(a)
        if not self._window:
            return out
        hist_max = max(self._window)
        out["dynamics/num/amax_hist_max"] = round(hist_max, 6)
        if hist_max > 0:
            out["dynamics/num/e5m2_margin_log2"] = round(
                math.log2(E5M2_MAX / hist_max), 3)
        return out


def _quant(x: jnp.ndarray, dtype, fmax: float) -> tuple[jnp.ndarray, jnp.ndarray]:
    amax = jnp.max(jnp.abs(x)).astype(jnp.float32)
    scale = jnp.maximum(amax, 1e-12) / fmax
    q = jnp.clip(x.astype(jnp.float32) / scale, -fmax, fmax).astype(dtype)
    return q, scale


@jax.custom_vjp
def fp8_matmul(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """x (..., K) @ w (K, N) in e4m3 with fp32 accumulation."""
    out, _ = _fp8_fwd(x, w)
    return out


def _fp8_fwd(x, w):
    xq, sx = _quant(x, jnp.float8_e4m3fn, _E4M3_MAX)
    wq, sw = _quant(w, jnp.float8_e4m3fn, _E4M3_MAX)
    out = jnp.matmul(xq, wq, preferred_element_type=jnp.float32) * (sx * sw)
    # zero-size dtype markers: custom_vjp residuals must be arrays, and the
    # cotangents must land in each primal's own dtype (x and w may differ)
    markers = (jnp.zeros((0,), x.dtype), jnp.zeros((0,), w.dtype))
    return out.astype(x.dtype), (xq, sx, wq, sw, markers)


def _fp8_bwd(res, g):
    xq, sx, wq, sw, (xm, wm) = res
    gq, sg = _quant(g, jnp.float8_e5m2, _E5M2_MAX)
    # dx = g @ w.T ; dw = x.T @ g — both fp8 x fp8 -> fp32
    dx = jnp.matmul(gq, wq.T, preferred_element_type=jnp.float32) * (sg * sw)
    xq2 = xq.reshape(-1, xq.shape[-1])
    gq2 = gq.reshape(-1, gq.shape[-1])
    dw = jnp.matmul(xq2.T, gq2, preferred_element_type=jnp.float32) * (sx * sg)
    return dx.astype(xm.dtype), dw.astype(wm.dtype)


fp8_matmul.defvjp(_fp8_fwd, _fp8_bwd)


def project(x: jnp.ndarray, w: jnp.ndarray, n_in: int, linear_backend: str = "default") -> jnp.ndarray:
    """Contract x's trailing dims with w's first ``n_in`` dims (the generic form of
    every transformer projection: wq (d,n,h) n_in=1, wo (n,h,d) n_in=2, ...).

    ``linear_backend="fp8"`` routes the flattened 2-D matmul through
    :func:`fp8_matmul`; "default" is a plain einsum XLA fuses as usual.
    """
    in_shape = w.shape[:n_in]
    out_shape = w.shape[n_in:]
    k = 1
    for s in in_shape:
        k *= s
    x2 = x.reshape(*x.shape[: x.ndim - n_in], k) if n_in > 1 else x
    w2 = w.reshape(k, -1)
    if linear_backend == "fp8":
        out = fp8_matmul(x2, w2)
    else:
        out = jnp.matmul(x2, w2)
    return out.reshape(*x2.shape[:-1], *out_shape)
