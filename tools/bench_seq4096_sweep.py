"""seq-4096 MFU experiments (VERDICT r3 #2): one variant per invocation.

Usage: python tools/bench_seq4096_sweep.py <variant>
Variants:
  base          current bench recipe at seq 4096 (control)
  noseg         backend.attention_segments=False (right-padded fast path)
  bwdq256/512/1024   dkv kernel q-block via AUTOMODEL_FLASH_BWD_Q_BLOCK
  blk2048x1024  flash forward/dq blocks (2048, 1024)
  blk1024x512   flash blocks (1024, 512)
  mb4           micro_batch 4 + noseg (memory freed may admit it; mb4 OOMs with segs)

Each prints one JSON line. Run variants SEQUENTIALLY (one TPU process at a time).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

SEQ = 4096
MICRO_BATCH = 2  # bench.py's seq-4096 condition (mb 4 OOMs 16GB)
STEPS = 10


def measure(attention_segments=True, block_q=None, block_kv=None, micro_batch=MICRO_BATCH):
    import jax
    import jax.numpy as jnp
    import optax

    from automodel_tpu.models.common.backend import BackendConfig
    from automodel_tpu.models.llama.model import LlamaConfig, LlamaForCausalLM
    from automodel_tpu.ops.losses import masked_cross_entropy
    from automodel_tpu.training.train_step import make_train_step
    import bench

    cfg = LlamaConfig(
        vocab_size=128256, hidden_size=2048, intermediate_size=8192,
        num_hidden_layers=16, num_attention_heads=32, num_key_value_heads=8,
        max_position_embeddings=8192, rope_theta=500000.0,
    )
    backend = BackendConfig(dtype="bfloat16", remat_policy="mlp_attn_dots",
                            attention="flash", attention_segments=attention_segments)
    if block_q is not None:
        # patch the flash defaults (flash_attention._pick targets) for the sweep
        import functools

        from automodel_tpu.ops.pallas import flash_attention as fa

        orig = fa.flash_attention
        fa.flash_attention = functools.partial(orig, block_q=block_q, block_k=block_kv)
        import automodel_tpu.ops.attention as attn_mod

        # attention.py imports inside the function, so patching the module
        # attribute is enough
        assert attn_mod is not None
    model = LlamaForCausalLM(cfg, backend)
    params = model.init(jax.random.key(0), jnp.bfloat16)
    optimizer = optax.chain(optax.scale_by_factored_rms(), optax.scale(-1e-5))
    opt_state = jax.jit(optimizer.init)(params)

    def forward_loss(p, batch, n):
        logits = model(p, batch["input_ids"], positions=batch["positions"],
                       segment_ids=batch["segment_ids"])
        return masked_cross_entropy(logits, batch["labels"], n)

    step = jax.jit(make_train_step(forward_loss, optimizer), donate_argnums=(0, 1))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (1, micro_batch, SEQ)).astype(np.int32)
    batch = {
        "input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids),
        "positions": jnp.broadcast_to(jnp.arange(SEQ, dtype=jnp.int32), ids.shape),
        "segment_ids": jnp.ones_like(jnp.asarray(ids)),
    }
    for _ in range(3):
        params, opt_state, m = step(params, opt_state, batch)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(STEPS):
        params, opt_state, m = step(params, opt_state, batch)
    float(m["loss"])
    dt = (time.perf_counter() - t0) / STEPS
    tps = micro_batch * SEQ / dt
    fpt = bench.llama_flops_per_token(cfg, SEQ)
    peak = 197e12
    return {"tokens_per_sec": round(tps, 1), "mfu": round(tps * fpt / peak, 4),
            "step_time_ms": round(dt * 1e3, 1)}


if __name__ == "__main__":
    variant = sys.argv[1]
    kw = {}
    if variant == "noseg":
        kw = {"attention_segments": False}
    elif variant.startswith("bwdq"):
        os.environ["AUTOMODEL_FLASH_BWD_Q_BLOCK"] = variant[4:]
    elif variant == "blk2048x1024":
        kw = {"block_q": 2048, "block_kv": 1024}
    elif variant == "blk1024x512":
        kw = {"block_q": 1024, "block_kv": 512}
    elif variant == "mb4":
        kw = {"attention_segments": False, "micro_batch": 4}
    elif variant != "base":
        raise SystemExit(f"unknown variant {variant}")
    out = measure(**kw)
    out["variant"] = variant
    print(json.dumps(out))
