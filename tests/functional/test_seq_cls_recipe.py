"""Seq-cls recipe end-to-end: synthetic learnable classification, loss falls
below chance (reference L2 seq-cls scenario)."""

import json
import textwrap

import numpy as np

from automodel_tpu.config.loader import load_config
from tests.functional.jsonl import losses as jl_losses, metric_rows
from automodel_tpu.recipes.llm.train_seq_cls import TrainSeqClsRecipe


class ParityDataset:
    """label = last_token % 2 — learnable directly at the pooled position."""

    def __init__(self, vocab_size=64, seq_len=12, num_samples=256, seed=0):
        rng = np.random.default_rng(seed)
        self.rows = []
        for _ in range(num_samples):
            n = int(rng.integers(4, seq_len))
            ids = rng.integers(3, vocab_size, size=n)
            self.rows.append({"input_ids": ids.tolist(), "label": int(ids[-1]) % 2})

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, i):
        return self.rows[i]


def test_seq_cls_loss_decreases(tmp_path, cpu_devices):
    cfg_text = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      num_labels: 2
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 64
        hidden_size: 32
        intermediate_size: 64
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 64
    distributed:
      dp_shard: 4
      tp: 2
    backend:
      dtype: float32
    dataset:
      _target_: tests.functional.test_seq_cls_recipe.ParityDataset
      num_samples: 256
    micro_batch_size: 16
    seq_len: 16
    step_scheduler:
      grad_acc_steps: 1
      max_steps: 15
      num_epochs: 10
      handle_sigterm: false
    optimizer:
      lr: 1.0e-2
      max_grad_norm: 1.0
    lr_scheduler:
      lr_warmup_steps: 2
    checkpoint:
      enabled: false
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg_text))
    recipe = TrainSeqClsRecipe(load_config(p)).setup()
    recipe.run_train_validation_loop()
    rows = metric_rows(tmp_path / "out" / "training.jsonl")
    losses = [r["loss"] for r in rows]
    assert 0.5 < losses[0] < 1.2  # ~ln(2) at init
    assert losses[-1] < 0.45  # learns the parity rule
