from automodel_tpu.training.rng import ScopedRNG, StatefulRNG
from automodel_tpu.training.step_scheduler import StepScheduler

__all__ = ["ScopedRNG", "StatefulRNG", "StepScheduler"]
