"""Autotune end-to-end smoke (docs/observability.md "Autotuning & the perf
lab"): ``bench.py --tune`` completes a pruned search on the CPU smoke cell
with a schema-valid resumable ledger, the tuned yaml is accepted by the
finetune recipe with provenance in the run header, and the winning cell gates
through tools/bench_gate.py against the merged baseline.

Marked ``slow`` + ``perf`` (out of tier-1): run with ``pytest -m perf``."""

import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.perf]

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
BENCH = os.path.join(REPO, "bench.py")
GATE = os.path.join(REPO, "tools", "bench_gate.py")


@pytest.fixture(scope="module")
def tune_run(tmp_path_factory):
    """One ``bench.py --tune --cpu`` search shared by the assertions below."""
    tmp = tmp_path_factory.mktemp("autotune")
    baseline = tmp / "BASELINE.json"
    shutil.copy(os.path.join(REPO, "BASELINE.json"), baseline)
    out_dir = tmp / "tuned"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    result = subprocess.run(
        [sys.executable, BENCH, "--tune", "--cpu",
         "--tune-dir", str(out_dir), "--tune-baseline", str(baseline)],
        capture_output=True, text=True, timeout=1500, env=env, cwd=REPO)
    assert result.returncode == 0, result.stdout + result.stderr
    (tmp / "stdout.jsonl").write_text(result.stdout)
    return tmp


def test_tune_completes_pruned_search_with_auditable_ledger(tune_run):
    from automodel_tpu.tuning.runner import validate_report

    lines = [json.loads(ln) for ln in
             (tune_run / "stdout.jsonl").read_text().splitlines() if ln.strip()]
    summary = lines[-1]
    assert summary["ok"], summary
    tuner = summary["tuner"]
    assert tuner["counts"]["ran"] > 0 and tuner["counts"]["pruned"] > 0

    # per-trial rows ride stdout with the tuner/* keys under contract
    rows = [ln for ln in lines if ln.get("tuner_row")]
    assert len(rows) == tuner["counts"]["total"] + 1  # + the winner row
    assert {"tuner/trial", "tuner/digest", "tuner/outcome"} <= set(rows[0])
    assert rows[-1]["tuner/winner"] == tuner["winner"]

    # the ledger: schema-valid, every trial has an outcome, winner attribution
    # cites signal keys that really exist in the winner's metrics
    doc = json.load(open(tune_run / "tuned" / "tuner_report.json"))
    assert validate_report(doc) == []
    assert all(e["outcome"]["status"] in ("pruned", "ran", "failed")
               for e in doc["trials"])
    winner = next(e for e in doc["trials"]
                  if e["digest"] == doc["winner"]["digest"])
    attribution = doc["winner"]["attribution"]
    for key in attribution["signal_keys"]:
        assert key in winner["outcome"]["metrics"]
    # pruned trials never compiled: their reason cites the memory-plan verdict
    pruned = [e for e in doc["trials"] if e["outcome"]["status"] == "pruned"]
    assert all("mem_plan/fits=false" in e["outcome"]["reason"] for e in pruned)

    # a trial span per trial on the Chrome-trace timeline
    timeline = json.load(open(tune_run / "tuned" / "tuner_timeline.json"))
    events = timeline["traceEvents"] if isinstance(timeline, dict) else timeline
    spans = [e for e in events if str(e.get("name", "")).startswith("tuner/")]
    assert len(spans) == tuner["counts"]["total"] - tuner["counts"].get(
        "skipped_resume", 0)


def test_winning_cell_lands_in_baseline_and_gates_green(tune_run):
    summary = json.loads(
        (tune_run / "stdout.jsonl").read_text().splitlines()[-1])
    cell = summary["tuner"]["cell"]
    base = json.load(open(tune_run / "BASELINE.json"))
    assert f"tuned/{cell}/tps" in base["metrics"]
    assert base["metrics_meta"]["tuner"]["winner"] == summary["tuner"]["winner"]

    gate = subprocess.run(
        [sys.executable, GATE, "--run", str(tune_run / "stdout.jsonl"),
         "--baseline", str(tune_run / "BASELINE.json"),
         "--only", f"tuned/{cell}/tps", "--only", f"tuned/{cell}/hbm_gib_peak",
         "--tolerance", "default=0.5", "--require", f"tuned/{cell}/tps"],
        capture_output=True, text=True, timeout=120)
    assert gate.returncode == 0, gate.stdout + gate.stderr
    assert "[gate] PASS" in gate.stdout


def test_train_ft_accepts_tuned_config_with_header_provenance(
        tune_run, tmp_path, cpu_devices):
    from automodel_tpu.config.loader import load_config
    from automodel_tpu.recipes.llm.train_ft import (
        TrainFinetuneRecipeForNextTokenPrediction,
    )

    summary = json.loads(
        (tune_run / "stdout.jsonl").read_text().splitlines()[-1])
    tuned_yaml = tune_run / "tuned" / f"{summary['tuner']['cell']}.yaml"
    assert tuned_yaml.exists()
    cfg_text = f"""
    seed: 7
    output_dir: {tmp_path}/out
    tuned_config: {tuned_yaml}
    model:
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 128
        num_hidden_layers: 2
        num_attention_heads: 8
        num_key_value_heads: 4
        max_position_embeddings: 256
    distributed:
      # dp degree 2: must divide the tuned winner's micro_batch_size (2)
      dp_shard: 2
      tp: 4
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 64
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: 1
      max_steps: 2
      num_epochs: 1
      handle_sigterm: false
    optimizer:
      lr: 1.0e-2
    checkpoint:
      enabled: false
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg_text))
    cfg = load_config(p)
    recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
    recipe.run_train_validation_loop()

    overrides = json.load(open(
        tune_run / "tuned" / "tuner_report.json"))["winner"]
    winner_entry = next(
        e for e in json.load(open(tune_run / "tuned" / "tuner_report.json"))["trials"]
        if e["digest"] == overrides["digest"])
    # the tuned knobs actually shaped the run
    assert cfg.get("backend.remat_policy") == (
        winner_entry["trial"]["backend.remat_policy"])
    assert int(cfg.get("micro_batch_size")) == (
        winner_entry["trial"]["micro_batch_size"])
    # provenance rides the run header
    rows = [json.loads(line) for line in open(tmp_path / "out" / "training.jsonl")]
    header = next(r for r in rows if r.get("run_header"))
    assert header["tuned_config"] == str(tuned_yaml)
    assert header["tuned_cell"] == summary["tuner"]["cell"]
    assert header["tuned_digest"] == summary["tuner"]["winner"]
