"""Unified trace timeline (observability/events.py): Chrome trace-event JSON
that Perfetto/chrome://tracing loads — field validity, span/instant/complete
forms, the event cap, and mid-run readability."""

import json

import pytest

from automodel_tpu.observability.events import TraceTimeline

REQUIRED_FIELDS = {"name", "cat", "ph", "ts", "pid", "tid"}


def _load(path):
    doc = json.load(open(path))
    assert doc["displayTimeUnit"] == "ms"
    assert isinstance(doc["traceEvents"], list)
    for ev in doc["traceEvents"]:
        assert REQUIRED_FIELDS <= set(ev), ev
        assert ev["ph"] in ("X", "i")
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
    return doc


class TestTraceEvents:
    def test_complete_span_instant_roundtrip(self, tmp_path):
        p = tmp_path / "timeline.json"
        tl = TraceTimeline(str(p))
        with tl.span("checkpoint", cat="phase"):
            pass
        tl.complete("step", "step", tl.now(), 0.25, step=7, loss=1.5)
        tl.instant("stall", step=7, stall_s=12.0)
        tl.close()
        doc = _load(p)
        by_name = {e["name"]: e for e in doc["traceEvents"]}
        assert set(by_name) == {"checkpoint", "step", "stall"}
        assert by_name["step"]["ph"] == "X"
        assert by_name["step"]["dur"] == pytest.approx(0.25e6, rel=1e-6)
        assert by_name["step"]["args"]["step"] == 7
        assert by_name["stall"]["ph"] == "i"
        assert by_name["stall"]["s"] == "p"  # process-scoped instant

    def test_timestamps_are_microseconds_since_construction(self, tmp_path):
        p = tmp_path / "t.json"
        tl = TraceTimeline(str(p))
        tl.complete("a", "x", 1.0, 0.5)
        tl.close()
        ev = _load(p)["traceEvents"][0]
        assert ev["ts"] == pytest.approx(1e6, rel=1e-6)
        assert ev["dur"] == pytest.approx(0.5e6, rel=1e-6)

    def test_nonscalar_and_nonfinite_args_sanitized(self, tmp_path):
        p = tmp_path / "t.json"
        tl = TraceTimeline(str(p))
        tl.instant("e", bad=float("nan"), obj={"k": 1}, ok=3)
        tl.close()
        args = _load(p)["traceEvents"][0]["args"]
        assert args["bad"] is None
        assert isinstance(args["obj"], str)
        assert args["ok"] == 3

    def test_event_cap_records_drop_count(self, tmp_path):
        p = tmp_path / "t.json"
        tl = TraceTimeline(str(p), max_events=10)
        for i in range(25):
            tl.instant("e", i=i)
        tl.close()
        doc = _load(p)
        assert len(doc["traceEvents"]) == 10
        assert doc["droppedEventCount"] == 15

    def test_file_is_valid_mid_run(self, tmp_path):
        """Periodic flushes must leave a loadable file before close()."""
        p = tmp_path / "t.json"
        tl = TraceTimeline(str(p), flush_every=2)
        for i in range(5):
            tl.instant("e", i=i)
        assert p.exists()
        doc = _load(p)  # parse WITHOUT close
        assert len(doc["traceEvents"]) >= 2
        tl.close()
        assert len(_load(p)["traceEvents"]) == 5

    def test_none_path_noops(self):
        tl = TraceTimeline(None)  # non-proc-0 hosts
        tl.instant("e")
        with tl.span("x"):
            pass
        tl.close()  # nothing written, nothing raised

    def test_per_host_pid(self, tmp_path):
        p = tmp_path / "t.json"
        tl = TraceTimeline(str(p), pid=3)
        tl.instant("e")
        tl.close()
        assert _load(p)["traceEvents"][0]["pid"] == 3
