"""Qwen3-Next HF key/layout mapping (reference models/qwen3_next/state_dict_adapter.py).

Hybrid layer streams: HF indexes layers 0..L-1 with interleaved linear/full attention;
ours stacks each stream separately, so every per-layer entry pins explicit
``layer_indices``. The fused HF projections (in_proj_qkvz, in_proj_ba, q_proj with its
output gate) stay fused as single leaves — transforms are pure transposes/reshapes.
"""

from __future__ import annotations

import numpy as np

from automodel_tpu.models.common.state_dict import Entry, MappingAdapter
from automodel_tpu.models.llama.state_dict_adapter import _o_in, _o_out, _proj_in, _proj_out, _t
from automodel_tpu.models.qwen3_moe.state_dict_adapter import moe_expert_entries

__all__ = ["Qwen3NextStateDictAdapter"]


def _fused_in(heads: int):
    """HF (heads*M, D) -> ours (D, heads, M)."""

    def f(w: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(w.T).reshape(w.shape[1], heads, -1)

    return f


def _fused_out(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w.reshape(w.shape[0], -1).T)


def _conv_in(w: np.ndarray) -> np.ndarray:
    return w[:, 0, :]  # (C, 1, K) -> (C, K)


def _conv_out(w: np.ndarray) -> np.ndarray:
    return w[:, None, :]


class Qwen3NextStateDictAdapter(MappingAdapter):
    def __init__(self, cfg):
        self.cfg = cfg
        lin_idx, full_idx = cfg.linear_layer_indices, cfg.full_layer_indices
        Hk = cfg.linear_num_key_heads
        H, Hkv, dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        pre = "model.layers.{i}"

        entries = [
            Entry("model.embed_tokens.weight", "embed"),
            Entry("model.norm.weight", "final_norm"),
        ]
        if not cfg.tie_word_embeddings:
            entries.append(Entry("lm_head.weight", "lm_head", _t, _t))

        def stream(ours_prefix: str, idx: tuple[int, ...]) -> list[Entry]:
            out = [
                Entry(f"{pre}.input_layernorm.weight", f"{ours_prefix}.attn_norm", layer_indices=idx),
                Entry(f"{pre}.post_attention_layernorm.weight", f"{ours_prefix}.mlp_norm", layer_indices=idx),
                Entry(f"{pre}.mlp.gate.weight", f"{ours_prefix}.moe.gate.weight", layer_indices=idx),
                Entry(f"{pre}.mlp.shared_expert.gate_proj.weight",
                      f"{ours_prefix}.moe.shared_experts.w_gate", _t, _t, layer_indices=idx),
                Entry(f"{pre}.mlp.shared_expert.up_proj.weight",
                      f"{ours_prefix}.moe.shared_experts.w_up", _t, _t, layer_indices=idx),
                Entry(f"{pre}.mlp.shared_expert.down_proj.weight",
                      f"{ours_prefix}.moe.shared_experts.w_down", _t, _t, layer_indices=idx),
                Entry(f"{pre}.mlp.shared_expert_gate.weight",
                      f"{ours_prefix}.moe.shared_expert_gate", _t, _t, layer_indices=idx),
            ]
            for e in moe_expert_entries(f"{pre}.mlp", f"{ours_prefix}.moe"):
                out.append(Entry(e.hf, e.ours, e.to_ours, e.to_hf, layer_indices=idx))
            return out

        if lin_idx:
            entries += stream("linear_layers", lin_idx)
            entries += [
                Entry(f"{pre}.linear_attn.in_proj_qkvz.weight", "linear_layers.wqkvz",
                      _fused_in(Hk), _fused_out, layer_indices=lin_idx),
                Entry(f"{pre}.linear_attn.in_proj_ba.weight", "linear_layers.wba",
                      _fused_in(Hk), _fused_out, layer_indices=lin_idx),
                Entry(f"{pre}.linear_attn.conv1d.weight", "linear_layers.conv_w",
                      _conv_in, _conv_out, layer_indices=lin_idx),
                Entry(f"{pre}.linear_attn.dt_bias", "linear_layers.dt_bias", layer_indices=lin_idx),
                # decay logs stay fp32 like init() (bf16 rounding perturbs every step
                # of the recurrence; same precedent as DSv3's score_correction_bias)
                Entry(f"{pre}.linear_attn.A_log", "linear_layers.a_log",
                      to_ours=lambda x: x.astype(np.float32),
                      keep_dtype=True, layer_indices=lin_idx),
                Entry(f"{pre}.linear_attn.norm.weight", "linear_layers.norm", layer_indices=lin_idx),
                Entry(f"{pre}.linear_attn.out_proj.weight", "linear_layers.wo",
                      _o_in(cfg.linear_num_value_heads, cfg.linear_value_head_dim),
                      _o_out(cfg.linear_num_value_heads, cfg.linear_value_head_dim),
                      layer_indices=lin_idx),
            ]
        if full_idx:
            entries += stream("full_layers", full_idx)
            entries += [
                Entry(f"{pre}.self_attn.q_proj.weight", "full_layers.wq",
                      _fused_in(H), _fused_out, layer_indices=full_idx),
                Entry(f"{pre}.self_attn.k_proj.weight", "full_layers.wk",
                      _proj_in(Hkv, dh), _proj_out(Hkv, dh), layer_indices=full_idx),
                Entry(f"{pre}.self_attn.v_proj.weight", "full_layers.wv",
                      _proj_in(Hkv, dh), _proj_out(Hkv, dh), layer_indices=full_idx),
                Entry(f"{pre}.self_attn.o_proj.weight", "full_layers.wo",
                      _o_in(H, dh), _o_out(H, dh), layer_indices=full_idx),
                Entry(f"{pre}.self_attn.q_norm.weight", "full_layers.q_norm", layer_indices=full_idx),
                Entry(f"{pre}.self_attn.k_norm.weight", "full_layers.k_norm", layer_indices=full_idx),
            ]

        super().__init__(entries, cfg.num_hidden_layers, num_experts=cfg.moe.n_routed_experts)
