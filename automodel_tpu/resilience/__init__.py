"""Fault-tolerant training: anomaly rollback, checkpoint integrity + fallback
restore, coordinated preemption, elastic topology (mesh-shape-agnostic resume),
transient-fault retry, process supervision (heartbeat hang detection, failure
taxonomy, bounded auto-restart), and a deterministic fault-injection harness
(docs/resilience.md)."""

from automodel_tpu.resilience.anomaly import AnomalyDetector, RecoveryPolicy, Verdict
from automodel_tpu.resilience.chaos import ChaosConfig, ChaosInjector, FlakyIO
from automodel_tpu.resilience.config import (
    AnomalyConfig, ElasticConfig, PreemptionConfig, ResilienceConfig,
    RollbackConfig,
)
from automodel_tpu.resilience.elastic import (
    ElasticTopologyChange, merge_host_states, plan_warmup_micro_counts,
    repartition_dataloader_state,
)
from automodel_tpu.resilience.manager import ResilienceManager
from automodel_tpu.resilience.supervisor import (
    HeartbeatWriter, Supervisor, SupervisorConfig, classify_error_text,
    classify_failure, read_heartbeat,
)

__all__ = [
    "AnomalyConfig",
    "AnomalyDetector",
    "ChaosConfig",
    "ChaosInjector",
    "ElasticConfig",
    "ElasticTopologyChange",
    "FlakyIO",
    "HeartbeatWriter",
    "PreemptionConfig",
    "RecoveryPolicy",
    "ResilienceConfig",
    "ResilienceManager",
    "RollbackConfig",
    "Supervisor",
    "SupervisorConfig",
    "Verdict",
    "classify_error_text",
    "classify_failure",
    "merge_host_states",
    "plan_warmup_micro_counts",
    "repartition_dataloader_state",
    "read_heartbeat",
]
