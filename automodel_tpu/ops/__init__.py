from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import apply_rope, rope_frequencies
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.losses import masked_cross_entropy

__all__ = [
    "rms_norm",
    "apply_rope",
    "rope_frequencies",
    "dot_product_attention",
    "masked_cross_entropy",
]
