"""bench.py's failure contract: the LAST stdout line is ALWAYS parseable JSON.

The driver reads exactly one thing from a bench run — the final stdout line —
so every escape path (BaseException through run_cli, backend faults routed to
the CPU fallback, code bugs reported as ``{"ok": false}``) must end stdout
with a machine-parseable line. Round 5 lost its data point to a canary-level
backend death that printed a raw traceback; these tests pin the seams that
prevent a repeat: the run_cli BaseException guard, the canary → fallback
routing, the backend-marker routing, and the fallback child's row re-emission.
"""

from __future__ import annotations

import json
import subprocess
import types

import pytest

import bench


def _stdout_docs(capsys):
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert lines, "bench printed nothing to stdout"
    return lines, [json.loads(ln) for ln in lines]


def _fake_backend(monkeypatch, name="tpu"):
    """Make bench.main think a non-CPU accelerator is attached."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: name)


class TestRunCliGuard:
    def test_baseexception_still_ends_with_json_line(self, monkeypatch, capsys):
        def boom(argv=None):
            raise KeyboardInterrupt("ctrl-c mid-bench")

        monkeypatch.setattr(bench, "main", boom)
        rc = bench.run_cli([])
        lines, docs = _stdout_docs(capsys)
        assert rc == 1
        assert docs[-1]["ok"] is False
        assert "KeyboardInterrupt" in docs[-1]["error"]

    def test_systemexit_from_library_is_caught(self, monkeypatch, capsys):
        monkeypatch.setattr(
            bench, "main", lambda argv=None: (_ for _ in ()).throw(SystemExit(3))
        )
        rc = bench.run_cli([])
        _, docs = _stdout_docs(capsys)
        assert rc == 1
        assert docs[-1]["ok"] is False

    def test_clean_run_passes_through_rc(self, monkeypatch, capsys):
        monkeypatch.setattr(bench, "main", lambda argv=None: 0)
        assert bench.run_cli([]) == 0


class TestCanaryRouting:
    def test_canary_failure_routes_to_cpu_fallback(self, monkeypatch, capsys):
        _fake_backend(monkeypatch)
        monkeypatch.setattr(
            bench, "_canary_dispatch",
            lambda: (_ for _ in ()).throw(RuntimeError("wedged chip")),
        )
        calls = []

        def fake_fallback(reason, extra_args=()):
            calls.append((reason, extra_args))
            print(json.dumps({"ok": True, "extra": {"fallback": "cpu"}}))
            return 0

        monkeypatch.setattr(bench, "_spawn_cpu_fallback", fake_fallback)
        rc = bench.main([])
        _, docs = _stdout_docs(capsys)
        assert rc == 0
        assert len(calls) == 1
        assert "wedged chip" in calls[0][0]
        assert docs[-1]["ok"] is True

    def test_canary_failure_carries_matrix_flag_to_fallback(self, monkeypatch, capsys):
        _fake_backend(monkeypatch)
        monkeypatch.setattr(
            bench, "_canary_dispatch",
            lambda: (_ for _ in ()).throw(RuntimeError("wedged chip")),
        )
        calls = []

        def fake_fallback(reason, extra_args=()):
            calls.append(extra_args)
            print(json.dumps({"ok": True}))
            return 0

        monkeypatch.setattr(bench, "_spawn_cpu_fallback", fake_fallback)
        assert bench.main(["--matrix"]) == 0
        assert calls == [("--matrix",)]

    def test_backend_marker_in_bench_error_routes_to_fallback(self, monkeypatch, capsys):
        _fake_backend(monkeypatch)
        monkeypatch.setattr(bench, "_canary_dispatch", lambda: None)
        monkeypatch.setattr(
            bench, "_full_bench",
            lambda **kw: (_ for _ in ()).throw(RuntimeError("libtpu crashed late")),
        )
        monkeypatch.setattr(
            bench, "_spawn_cpu_fallback",
            lambda reason, extra_args=(): (print(json.dumps({"ok": True})), 0)[1],
        )
        rc = bench.main([])
        _, docs = _stdout_docs(capsys)
        assert rc == 0
        assert docs[-1]["ok"] is True

    def test_code_bug_is_reported_not_masked_by_fallback(self, monkeypatch, capsys):
        _fake_backend(monkeypatch)
        monkeypatch.setattr(bench, "_canary_dispatch", lambda: None)
        monkeypatch.setattr(
            bench, "_full_bench",
            lambda **kw: (_ for _ in ()).throw(ValueError("shape mismatch in our code")),
        )

        def no_fallback(reason, extra_args=()):  # pragma: no cover - must not run
            raise AssertionError("code bugs must not be laundered through the CPU fallback")

        monkeypatch.setattr(bench, "_spawn_cpu_fallback", no_fallback)
        rc = bench.main([])
        _, docs = _stdout_docs(capsys)
        assert rc == 1
        assert docs[-1]["ok"] is False
        assert "shape mismatch" in docs[-1]["error"]

    def test_cpu_mode_error_keeps_json_contract(self, monkeypatch, capsys):
        monkeypatch.setattr(
            bench, "_cpu_fallback_bench",
            lambda **kw: (_ for _ in ()).throw(RuntimeError("tiny bench died")),
        )
        rc = bench.main(["--cpu"])
        _, docs = _stdout_docs(capsys)
        assert rc == 1
        assert docs[-1]["ok"] is False


class TestFallbackChildReemission:
    def _fake_child(self, monkeypatch, stdout, returncode=0):
        def fake_run(cmd, **kwargs):
            return types.SimpleNamespace(stdout=stdout, stderr="", returncode=returncode)

        monkeypatch.setattr(subprocess, "run", fake_run)

    def test_matrix_rows_reemitted_before_final_doc(self, monkeypatch, capsys):
        row = {"matrix_row": True, "model": "dense", "seq_len": 2048,
               "prefetch": True, "tokens_per_sec_per_chip": 10.0}
        final = {"ok": True, "matrix": [row], "extra": {"fallback": "cpu"}}
        self._fake_child(
            monkeypatch,
            "noise line, not json\n" + json.dumps(row) + "\n" + json.dumps(final) + "\n",
        )
        rc = bench._spawn_cpu_fallback("canary died", extra_args=("--matrix",))
        lines, docs = _stdout_docs(capsys)
        assert rc == 0
        assert docs[0]["matrix_row"] is True
        assert docs[-1]["ok"] is True
        assert docs[-1]["extra"]["fallback_reason"] == "canary died"

    def test_child_with_no_json_is_a_reported_failure(self, monkeypatch, capsys):
        self._fake_child(monkeypatch, "traceback only, no json\n", returncode=1)
        rc = bench._spawn_cpu_fallback("backend gone")
        _, docs = _stdout_docs(capsys)
        assert rc == 1
        assert docs[-1]["ok"] is False
        assert "backend gone" in docs[-1]["error"]


class TestMatrixRowShape:
    def test_matrix_summary_doc_flattens_for_the_gate(self):
        from automodel_tpu.observability.regression import load_run_metrics

        rows = [
            {"matrix_row": True, "model": "dense", "seq_len": 2048,
             "prefetch": False, "tokens_per_sec_per_chip": 100.0},
            {"matrix_row": True, "model": "moe", "seq_len": 4096,
             "prefetch": True, "tokens_per_sec_per_chip": 80.0,
             "moe/tokens_per_sec_per_chip": 640.0, "a2a_byte_share": 0.2},
        ]
        doc = {"ok": True, "metric": "m", "value": 100.0, "matrix": rows}
        import json as _json

        for text, label in [
            (_json.dumps(doc), "summary doc"),
            ("\n".join(_json.dumps(r) for r in rows) + "\n" + _json.dumps(doc),
             "stdout capture"),
        ]:
            import tempfile

            with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
                f.write(text)
                path = f.name
            got = load_run_metrics(path)
            assert got["matrix/dense_s2048_pfoff/tps"] == 100.0, label
            assert got["matrix/moe_s4096_pfon/tps"] == 80.0, label
            assert got["matrix/moe_s4096_pfon/moe_tps"] == 640.0, label
            assert "matrix/moe_s4096_pfon/a2a_share" not in got, label


class TestProfiledCellStep:
    """bench.py --profile: one traced step per cell -> measured_* row keys and
    a schema-valid signals cell; any failure degrades to empty, never raises."""

    def test_measured_keys_and_signals_cell(self):
        import jax
        import jax.numpy as jnp

        def step(params, opt_state, batch):
            loss = jnp.sum((batch @ params) ** 2) + opt_state
            return params, opt_state, {"loss": loss}

        params = jnp.ones((16, 16), jnp.float32)
        opt_state = jnp.float32(0.0)
        batch = jnp.ones((8, 16), jnp.float32)
        compiled = jax.jit(step).lower(params, opt_state, batch).compile()
        hlo = compiled.as_text()

        measured, cell = bench._profile_cell_step(
            compiled, params, opt_state, batch, hlo,
            {"model": "dense", "seq_len": 2048})
        assert measured, "profiled step produced no measured keys"
        assert measured["measured_step_time_s"] > 0
        assert 0.0 <= measured["overlap_frac"] <= 1.0
        assert measured["measured_bound"] in (
            "compute", "comms", "moe_a2a", "input")
        for key in ("measured_frac_compute", "measured_frac_comm",
                    "measured_frac_moe_a2a", "measured_frac_host"):
            assert key in measured, key

        from automodel_tpu.observability.signals import (
            build_signals,
            validate_signals,
        )

        assert cell is not None
        assert validate_signals(build_signals([cell])) == []
        assert cell["cell"]["seq_len"] == 2048
        assert cell["measured"] is not None

    def test_failure_degrades_to_empty(self, capsys):
        measured, cell = bench._profile_cell_step(
            None, None, None, None, None, {"model": "x", "seq_len": 1})
        assert measured == {} and cell is None
        assert "measured_* keys" in capsys.readouterr().err
