"""Real VLM dataset loaders (reference datasets/vlm/datasets.py:24-140).

Each loader returns a list of rows in this repo's collate contract —
``{"prompt": str (with <image>/<audio> placeholders), "answer": str,
"image": (H, W, 3) array | "audio": 16kHz float waveform}`` — instead of the
reference's nested chat-conversation format: the per-model collators
(data/vlm/collate.py, collate_fns.py) expand placeholders into the model's
native media-token spans and mask labels to the answer span, so the flat
prompt/answer shape carries the same information with less ceremony.

``path_or_dataset`` accepts an HF hub id, a local ``datasets.save_to_disk``
directory, or any path ``datasets.load_dataset`` understands — the local
forms are what the functional suite (and any air-gapped machine) uses.
"""

from __future__ import annotations

import json
import logging
import os
import random

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "json2token",
    "make_rdr_dataset",
    "make_cord_v2_dataset",
    "make_cv17_dataset",
]


def json2token(obj, sort_json_key: bool = True) -> str:
    """Donut-style JSON flattening: ``{"k": v}`` -> ``<s_k>v</s_k>``, lists
    join with ``<sep/>`` (reference datasets/vlm/utils.py:33 — the CORD
    receipt-parsing output convention)."""
    if isinstance(obj, dict):
        keys = sorted(obj.keys()) if sort_json_key else obj.keys()
        return "".join(
            f"<s_{k}>{json2token(obj[k], sort_json_key)}</s_{k}>" for k in keys
        )
    if isinstance(obj, list):
        return "<sep/>".join(json2token(v, sort_json_key) for v in obj)
    return str(obj)


def _load(path_or_dataset: str, split: str):
    import datasets

    if os.path.isdir(path_or_dataset):
        loaded = datasets.load_from_disk(path_or_dataset)
        if isinstance(loaded, datasets.DatasetDict):
            return loaded[split]
        if split != "train":
            # a bare save_to_disk dir carries no split structure: the caller
            # asked for a specific split we cannot select — say so instead of
            # silently serving whatever rows were saved
            logger.warning(
                "%s is a single-split on-disk dataset; requested split %r "
                "cannot be selected and ALL saved rows are used",
                path_or_dataset, split)
        return loaded
    return datasets.load_dataset(path_or_dataset, split=split)


def _image_array(img) -> np.ndarray:
    """PIL image | array -> (H, W, 3) uint8/float array."""
    arr = np.asarray(img)
    if arr.ndim == 2:  # grayscale
        arr = np.stack([arr] * 3, axis=-1)
    if arr.shape[-1] == 4:  # RGBA
        arr = arr[..., :3]
    return arr


def make_rdr_dataset(path_or_dataset: str = "quintend/rdr-items",
                     split: str = "train", limit: int | None = None):
    """Image-captioning rows (reference make_rdr_dataset, datasets.py:24):
    image + "Describe this image." -> caption text."""
    rows = []
    for ex in _load(path_or_dataset, split):
        rows.append({
            "prompt": "<image>Describe this image.",
            "answer": ex["text"],
            "image": _image_array(ex["image"]),
        })
        if limit and len(rows) >= limit:
            break
    return rows


def make_cord_v2_dataset(path_or_dataset: str = "naver-clova-ix/cord-v2",
                         split: str = "train", limit: int | None = None,
                         seed: int = 0):
    """CORD-v2 receipt parsing (reference make_cord_v2_dataset,
    datasets.py:58): the ground-truth JSON parse flattens to the Donut token
    string; multiple gt_parses pick one at random (seeded — the reference uses
    bare random.choice, which breaks dataloader-state resume)."""
    rng = random.Random(seed)
    rows = []
    for ex in _load(path_or_dataset, split):
        gt = json.loads(ex["ground_truth"])
        if "gt_parses" in gt:
            parses = list(gt["gt_parses"])
        else:
            parses = [gt["gt_parse"]]
        text = rng.choice([json2token(p, sort_json_key=True) for p in parses])
        rows.append({
            "prompt": "<image>Describe this image.",
            "answer": text,
            "image": _image_array(ex["image"]),
        })
        if limit and len(rows) >= limit:
            break
    return rows


def _resample_to_16k(wave: np.ndarray, sr: int) -> np.ndarray:
    """Linear-interp resample to the 16kHz the audio towers expect."""
    wave = np.asarray(wave, np.float32)
    if sr == 16000 or len(wave) == 0:
        return wave
    n_out = max(1, int(round(len(wave) * 16000 / sr)))
    return np.interp(
        np.linspace(0.0, len(wave) - 1.0, n_out), np.arange(len(wave)), wave
    ).astype(np.float32)


def make_cv17_dataset(path_or_dataset: str = "ysdede/commonvoice_17_tr_fixed",
                      split: str = "train", limit: int | None = None):
    """CommonVoice-17 speech transcription (reference make_cv17_dataset,
    datasets.py:120): audio clip -> transcription; waveforms land as raw
    16kHz float arrays (the omni collate's "audio" contract)."""
    rows = []
    for ex in _load(path_or_dataset, split):
        audio = ex["audio"]
        wave, sr = np.asarray(audio["array"], np.float32), int(audio["sampling_rate"])
        rows.append({
            "prompt": "<audio>Transcribe the audio clip.",
            "answer": ex["transcription"],
            "audio": _resample_to_16k(wave, sr),
        })
        if limit and len(rows) >= limit:
            break
    return rows
