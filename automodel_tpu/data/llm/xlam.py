"""xLAM function-calling dataset (reference datasets/llm/xlam.py make_xlam_dataset).

Rows carry ``query`` / ``answers`` (tool calls) / ``tools`` (schemas), possibly as
JSON strings. Tools convert to OpenAI function schemas fed to the chat template; the
assistant turn carries the tool calls, and only it takes loss.
"""

from __future__ import annotations

import json
from typing import Any

from automodel_tpu.data.llm.column_mapped import _load_rows
from automodel_tpu.data.llm.formatting import IGNORE_INDEX, format_chat_messages

__all__ = ["XlamDataset", "make_xlam_dataset"]


def _json_load_if_str(v):
    return json.loads(v) if isinstance(v, str) else v


def convert_tools(raw_tools: list[dict]) -> list[dict]:
    """Dataset tool specs -> OpenAI function schema (reference _convert_tools)."""
    tools = []
    for tool in raw_tools or []:
        params_raw = _json_load_if_str(tool.get("parameters")) or {}
        properties = {}
        for name, p in params_raw.items():
            p = p or {}
            properties[name] = {
                "type": p.get("type", "string"),
                "description": p.get("description", ""),
            }
        tools.append(
            {
                "type": "function",
                "function": {
                    "name": tool.get("name", ""),
                    "description": tool.get("description", ""),
                    "parameters": {"type": "object", "properties": properties},
                },
            }
        )
    return tools


def convert_tool_calls(raw_calls: list[dict]) -> list[dict]:
    """answers -> OpenAI tool_calls with JSON-string arguments."""
    calls = []
    for i, call in enumerate(raw_calls or []):
        args = call.get("arguments", {})
        calls.append(
            {
                "id": f"call_{i}",
                "type": "function",
                "function": {
                    "name": call.get("name", ""),
                    "arguments": args if isinstance(args, str) else json.dumps(args),
                },
            }
        )
    return calls


class XlamDataset:
    def __init__(
        self,
        tokenizer,
        path_or_dataset_id: str = "Salesforce/xlam-function-calling-60k",
        split: str = "train",
        limit_dataset_samples: int | None = None,
    ):
        self.rows = _load_rows(path_or_dataset_id, split)
        if limit_dataset_samples:
            self.rows = self.rows[:limit_dataset_samples]
        self.tokenizer = tokenizer

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, i: int) -> dict[str, Any]:
        row = self.rows[i]
        tools = convert_tools(_json_load_if_str(row.get("tools")))
        calls = convert_tool_calls(_json_load_if_str(row.get("answers")))
        messages = [
            {"role": "user", "content": str(row.get("query", ""))},
            {"role": "assistant", "content": "", "tool_calls": calls},
        ]
        if hasattr(self.tokenizer, "apply_chat_template") and self.tokenizer.chat_template:
            full = list(
                self.tokenizer.apply_chat_template(messages, tools=tools, tokenize=True)
            )
            prefix = list(
                self.tokenizer.apply_chat_template(
                    messages[:1], tools=tools, tokenize=True, add_generation_prompt=True
                )
            )
            labels = [IGNORE_INDEX] * len(full)
            lo = min(len(prefix), len(full))
            labels[lo:] = full[lo:]
            return {"input_ids": full, "labels": labels}
        # templateless fallback: serialize calls as JSON in the assistant turn
        messages[-1] = {"role": "assistant", "content": json.dumps(calls)}
        return format_chat_messages(self.tokenizer, messages)


def make_xlam_dataset(tokenizer, **kwargs) -> XlamDataset:
    return XlamDataset(tokenizer, **kwargs)
