"""Run supervisor: heartbeat contract, failure taxonomy, bounded restart
(automodel_tpu/resilience/supervisor.py, docs/resilience.md "Supervised runs").

The Supervisor tests drive REAL subprocesses (tiny ``python -c`` children) so
the poll/kill/reap loop is exercised for real — with poll intervals and hang
timeouts shrunk to keep each case under a second. The full training-loop
chaos scenario (SIGKILL + silent hang + torn save) lives in
tests/functional/test_supervisor_chaos.py (``pytest -m chaos``).
"""

import json
import os
import signal
import sys
import time

from automodel_tpu.resilience.supervisor import (
    HEARTBEAT_ENV,
    HeartbeatWriter,
    Supervisor,
    SupervisorConfig,
    classify_error_text,
    classify_failure,
    read_heartbeat,
)
from automodel_tpu.utils.retry import RetryConfig


# ---------------------------------------------------------------- taxonomy
class TestClassifier:
    def test_oom_wins_over_everything(self):
        text = "RESOURCE_EXHAUSTED while lowering; Unable to initialize backend"
        assert classify_error_text(text) == ("oom", False)

    def test_lowering_error_is_not_backend_init(self):
        # BENCH_r05: a convert_element_type lowering failure whose message
        # contains init-looking text must NOT classify as a retryable
        # backend-unavailable — retrying re-runs the same deterministic error
        text = ("setup/compile error: INVALID_ARGUMENT: convert_element_type "
                "... UNAVAILABLE: Unable to initialize backend")
        assert classify_error_text(text) == ("compile", False)

    def test_backend_init_is_transient(self):
        assert classify_error_text("failed to connect to libtpu") == (
            "backend-init", True)
        assert classify_error_text("PJRT plugin UNAVAILABLE") == (
            "backend-init", True)

    def test_numerics_preemption_data_unknown(self):
        assert classify_error_text("loss=nan at step 12") == ("numerics", False)
        assert classify_error_text("SIGTERM received; exiting") == (
            "preemption", True)
        assert classify_error_text("DataLoader worker crashed") == ("data", False)
        assert classify_error_text("something else entirely") == (
            "unknown", False)

    def test_hang_beats_everything(self):
        v = classify_failure(returncode=-9, stderr_tail="RESOURCE_EXHAUSTED",
                             hang=True)
        assert v["taxonomy"] == "watchdog" and v["transient"]

    def test_signal_deaths(self):
        assert classify_failure(returncode=-signal.SIGTERM)["taxonomy"] == \
            "preemption"
        v = classify_failure(returncode=-signal.SIGKILL)
        assert v["taxonomy"] == "crash" and v["transient"]
        assert classify_failure(returncode=3)["taxonomy"] == "unknown"

    def test_forensics_artifacts_mtime_gated(self, tmp_path):
        oom = tmp_path / "oom_report.json"
        oom.write_text("{}")
        stale_cutoff = os.path.getmtime(oom) + 10  # report predates episode
        v = classify_failure(returncode=1, out_dir=str(tmp_path),
                             since=stale_cutoff)
        assert v["taxonomy"] == "unknown"
        v = classify_failure(returncode=1, out_dir=str(tmp_path),
                             since=os.path.getmtime(oom) - 10)
        assert v["taxonomy"] == "oom" and v["evidence"] == str(oom)


# ---------------------------------------------------------------- heartbeat
class TestHeartbeat:
    def test_roundtrip_and_throttle(self, tmp_path):
        p = str(tmp_path / "hb.json")
        w = HeartbeatWriter(p, min_interval_s=60.0)
        w.beat(3)
        doc = read_heartbeat(p)
        assert doc["step"] == 3 and doc["pid"] == os.getpid()
        os.unlink(p)
        w.beat(3)  # same step inside the interval: throttled, no rewrite
        assert read_heartbeat(p) is None
        w.beat(4)  # step change always writes
        assert read_heartbeat(p)["step"] == 4

    def test_from_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv(HEARTBEAT_ENV, raising=False)
        assert HeartbeatWriter.from_env() is None
        monkeypatch.setenv(HEARTBEAT_ENV, str(tmp_path / "hb.json"))
        w = HeartbeatWriter.from_env()
        assert w is not None and w.path == str(tmp_path / "hb.json")

    def test_unreadable_heartbeat_is_none(self, tmp_path):
        p = tmp_path / "hb.json"
        p.write_text("{torn")
        assert read_heartbeat(str(p)) is None


# ---------------------------------------------------------------- supervisor
def _cfg(**over):
    over.setdefault("poll_interval_s", 0.02)
    over.setdefault("grace_s", 0.5)
    over.setdefault("backoff", RetryConfig(base_delay_s=0.0, jitter=0.0))
    return SupervisorConfig(**over)


def _run(tmp_path, child_src, *child_args, **cfg_over):
    sup = Supervisor(
        [sys.executable, "-c", child_src, *child_args],
        str(tmp_path / "out"), config=_cfg(**cfg_over),
        sleep=lambda s: None,
    )
    rc = sup.run()
    return rc, sup


class TestSupervisor:
    def test_clean_exit_completes_first_episode(self, tmp_path):
        rc, sup = _run(tmp_path, "pass")
        assert rc == 0
        report = json.load(open(sup.report_path))
        assert report["status"] == "completed"
        assert report["restarts"] == 0 and len(report["episodes"]) == 1
        rows = [json.loads(ln) for ln in
                open(os.path.join(sup.out_dir, "supervisor.jsonl"))]
        assert rows[-1]["supervisor/returncode"] == 0

    def test_crash_once_then_success_restarts(self, tmp_path):
        marker = str(tmp_path / "second_run")
        src = ("import os,sys\n"
               "p=sys.argv[1]\n"
               "if os.path.exists(p): sys.exit(0)\n"
               "open(p,'w').write('x')\n"
               "sys.stderr.write('boom\\n'); sys.exit(1)\n")
        rc, sup = _run(tmp_path, src, marker, max_restarts=2)
        assert rc == 0
        report = json.load(open(sup.report_path))
        assert report["status"] == "completed" and report["restarts"] == 1
        assert report["episodes"][0]["taxonomy"] == "unknown"
        assert "boom" in report["episodes"][0]["stderr_tail"]
        assert report["episodes"][1]["returncode"] == 0

    def test_budget_exhausted_aborts_with_reason(self, tmp_path):
        rc, sup = _run(tmp_path, "import sys; sys.exit(3)", max_restarts=1)
        assert rc == 3
        report = json.load(open(sup.report_path))
        assert report["status"] == "aborted"
        assert "restart budget exhausted" in report["abort_reason"]
        assert len(report["episodes"]) == 2  # initial + 1 restart

    def test_stale_heartbeat_is_killed_as_watchdog(self, tmp_path):
        src = ("import json,os,time\n"
               "p=os.environ['AUTOMODEL_HEARTBEAT_FILE']\n"
               "open(p,'w').write(json.dumps("
               "{'step':1,'time':time.time(),'pid':os.getpid()}))\n"
               "time.sleep(60)\n")
        t0 = time.monotonic()
        rc, sup = _run(tmp_path, src, max_restarts=0, hang_timeout_s=0.5)
        assert time.monotonic() - t0 < 30, "hang detector never fired"
        assert rc != 0
        report = json.load(open(sup.report_path))
        ep = report["episodes"][0]
        assert ep["hang"] and ep["taxonomy"] == "watchdog"
        assert ep["heartbeat_step"] == 1

    def test_silent_uninstrumented_child_is_not_a_hang(self, tmp_path):
        # no heartbeat ever written: the detector must stay disarmed and let
        # the child finish (sleep longer than hang_timeout_s)
        rc, sup = _run(tmp_path, "import time; time.sleep(1.2)",
                       max_restarts=0, hang_timeout_s=0.4)
        assert rc == 0
        report = json.load(open(sup.report_path))
        assert report["status"] == "completed"
        assert not report["episodes"][0]["hang"]

    def test_heartbeat_env_exported_and_timeline_written(self, tmp_path):
        src = ("import os,sys\n"
               "sys.exit(0 if os.environ.get('AUTOMODEL_HEARTBEAT_FILE') "
               "else 7)\n")
        rc, sup = _run(tmp_path, src)
        assert rc == 0, "child did not see the heartbeat env var"
        timeline = json.load(open(
            os.path.join(sup.out_dir, "supervisor_timeline.json")))
        names = {e.get("name") for e in timeline["traceEvents"]}
        assert "supervisor/episode_0" in names

    def test_episode_env_exported_with_index_and_run_id(self, tmp_path):
        # the child sees {"index", "run_id"} and the index advances per episode
        marker = str(tmp_path / "second_run")
        src = ("import json,os,sys\n"
               "ep=json.loads(os.environ['AUTOMODEL_EPISODE'])\n"
               "assert isinstance(ep['index'],int) and ep['run_id']\n"
               "p=sys.argv[1]\n"
               "if os.path.exists(p): sys.exit(0 if ep['index']==1 else 7)\n"
               "open(p,'w').write('x')\n"
               "sys.exit(1 if ep['index']==0 else 7)\n")
        rc, sup = _run(tmp_path, src, marker, max_restarts=2)
        # episode 0 dies after asserting its index; the restarted child only
        # exits 0 when it sees index 1 — rc==0 proves the stamp advanced
        assert rc == 0
        assert len(json.load(open(sup.report_path))["episodes"]) == 2

    def test_report_v2_has_run_identity_and_episode_starts(self, tmp_path):
        rc, sup = _run(tmp_path, "import sys; sys.exit(3)", max_restarts=1)
        report = json.load(open(sup.report_path))
        assert report["version"] == 2
        assert report["run_id"] == sup.run_id
        assert report["started"] > 0
        starts = [ep["started"] for ep in report["episodes"]]
        assert len(starts) == 2 and starts[0] <= starts[1]

    def test_run_ledger_written_from_child_metric_stream(self, tmp_path):
        # end to end: the child stamps its episode into training.jsonl via the
        # real MetricLogger env contract, dies once, and the supervisor's
        # ledger counts the re-trained step + a finite crash recovery time
        src = (
            "import json,os,sys,time\n"
            "ep=json.loads(os.environ['AUTOMODEL_EPISODE'])['index']\n"
            "steps=[1,2,3] if ep==0 else [3,4,5]\n"
            "with open(os.path.join(sys.argv[1],'training.jsonl'),'a') as f:\n"
            "    for s in steps:\n"
            "        f.write(json.dumps({'step':s,'ts':time.time(),"
            "'episode':ep,'loss':1.0})+'\\n')\n"
            "    f.write(json.dumps({'step':steps[-1],'ts':time.time(),"
            "'episode':ep,'loss':1.0,'goodput_wall_s':0.2,"
            "'goodput/device_step':1.0})+'\\n')\n"
            "sys.exit(9 if ep==0 else 0)\n")
        rc, sup = _run(tmp_path, src, str(tmp_path / "out"), max_restarts=2)
        assert rc == 0
        from automodel_tpu.observability import runledger
        ledger = runledger.load_ledger(sup.out_dir)
        assert runledger.validate_ledger(ledger) == []
        assert ledger["wasted_steps"] == 1  # step 3 re-trained after the crash
        assert ledger["restarts"] == 1
        assert ledger["run_id"] == sup.run_id
        ep0 = ledger["episodes"][0]
        assert ep0["taxonomy"] == "unknown"
        assert ep0["recovery_s"] is not None and ep0["recovery_s"] >= 0.0
        assert ledger["recovery"]["unknown"]["count"] == 1
        # the supervisor metric stream carries the flat ledger row
        rows = [json.loads(ln) for ln in
                open(os.path.join(sup.out_dir, "supervisor.jsonl"))]
        ledger_rows = [r for r in rows if "ledger/goodput_e2e" in r]
        assert ledger_rows, "no ledger/* row emitted"
        assert ledger_rows[-1]["ledger/episodes"] == 2
        assert "badput/idle" in ledger_rows[-1]
        # badput spans land on the terminal timeline
        timeline = json.load(open(
            os.path.join(sup.out_dir, "supervisor_timeline.json")))
        names = {e.get("name") for e in timeline["traceEvents"]}
        assert "badput/wasted_steps" in names
        assert "goodput_e2e" in names
