"""Real VLM dataset loaders (data/vlm/datasets.py) against tiny on-disk HF
fixtures — offline versions of the reference's rdr/cord-v2/cv17 loaders
(reference datasets/vlm/datasets.py:24,58,120)."""

import json

import numpy as np
import pytest

datasets = pytest.importorskip("datasets")

from automodel_tpu.data.vlm.datasets import (
    json2token, make_cord_v2_dataset, make_cv17_dataset, make_rdr_dataset,
)


def _img(seed):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 255, size=(32, 48, 3), dtype=np.uint8)


class TestJson2Token:
    def test_dict_list_scalar(self):
        obj = {"menu": [{"nm": "latte", "price": "5"}, {"nm": "tea", "price": "3"}]}
        got = json2token(obj)
        assert got == ("<s_menu><s_nm>latte</s_nm><s_price>5</s_price><sep/>"
                       "<s_nm>tea</s_nm><s_price>3</s_price></s_menu>")

    def test_sort_key_off_preserves_order(self):
        assert json2token({"b": "1", "a": "2"}, sort_json_key=False) == \
            "<s_b>1</s_b><s_a>2</s_a>"


class TestRdr:
    def test_rows_from_disk(self, tmp_path):
        ds = datasets.Dataset.from_dict(
            {"image": [_img(0), _img(1)], "text": ["a red mug", "a blue bowl"]},
            features=datasets.Features(
                {"image": datasets.Image(), "text": datasets.Value("string")}
            ),
        )
        ds.save_to_disk(str(tmp_path / "rdr"))
        rows = make_rdr_dataset(str(tmp_path / "rdr"))
        assert len(rows) == 2
        assert rows[0]["prompt"].startswith("<image>")
        assert rows[0]["answer"] == "a red mug"
        assert rows[0]["image"].shape == (32, 48, 3)


class TestCordV2:
    def test_gt_parse_flattens(self, tmp_path):
        gt = json.dumps({"gt_parse": {"total": {"price": "12.00"}}})
        ds = datasets.Dataset.from_dict(
            {"image": [_img(2)], "ground_truth": [gt]},
            features=datasets.Features(
                {"image": datasets.Image(), "ground_truth": datasets.Value("string")}
            ),
        )
        ds.save_to_disk(str(tmp_path / "cord"))
        rows = make_cord_v2_dataset(str(tmp_path / "cord"))
        assert rows[0]["answer"] == "<s_total><s_price>12.00</s_price></s_total>"

    def test_multi_parse_seeded_choice(self, tmp_path):
        gt = json.dumps({"gt_parses": [{"a": "1"}, {"b": "2"}]})
        ds = datasets.Dataset.from_dict(
            {"image": [_img(3)], "ground_truth": [gt]},
            features=datasets.Features(
                {"image": datasets.Image(), "ground_truth": datasets.Value("string")}
            ),
        )
        ds.save_to_disk(str(tmp_path / "cord2"))
        a = make_cord_v2_dataset(str(tmp_path / "cord2"), seed=0)
        b = make_cord_v2_dataset(str(tmp_path / "cord2"), seed=0)
        assert a[0]["answer"] == b[0]["answer"]  # resume-deterministic


class TestCv17:
    def test_audio_resamples_to_16k(self, tmp_path):
        wave = np.sin(np.linspace(0, 100, 8000)).astype(np.float32)
        # plain nested columns, not the datasets.Audio feature — encoding that
        # feature needs torchcodec, which this image doesn't ship; the loader
        # only reads ex["audio"]["array"]/["sampling_rate"] either way
        ds = datasets.Dataset.from_list(
            [{"audio": {"array": wave.tolist(), "sampling_rate": 8000},
              "transcription": "merhaba"}]
        )
        ds.save_to_disk(str(tmp_path / "cv"))
        rows = make_cv17_dataset(str(tmp_path / "cv"))
        assert rows[0]["prompt"].startswith("<audio>")
        assert rows[0]["answer"] == "merhaba"
        assert abs(len(rows[0]["audio"]) - 16000) < 10  # 1s at 16kHz
