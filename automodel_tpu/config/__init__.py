from automodel_tpu.config.loader import ConfigNode, instantiate, load_config
from automodel_tpu.config.cli_overrides import parse_args_and_load_config

__all__ = ["ConfigNode", "instantiate", "load_config", "parse_args_and_load_config"]
