"""Per-compile HBM attribution + an analytic fit-before-run memory plan.

The compute/comms pillar (:mod:`hlo_costs`) explains every second of step
time; this module is its memory twin — it explains every byte of HBM, twice:

1. **Analytically, before any compile.** Params and optimizer state exist as
   sharded arrays the moment setup finishes, so their exact per-shard bytes
   are known; the batch stack's bytes follow from the config, and the live
   activation working set is estimated from the model dims (one microbatch is
   live at a time under the scan-based grad accumulation). The resulting
   :class:`MemoryPlan` carries a ``hbm_headroom_gib`` / ``fits`` verdict
   usable *before execution* — the fit-before-run primitive that deciding
   whether a resharded checkpoint fits a new mesh shape needs (ROADMAP #3).
   The plan's flat ``mem_plan/*`` keys ride the run_header.

2. **Exactly, at the first compile.** ``Compiled.memory_analysis()`` reports
   XLA's own argument/output/temp/generated-code byte totals for the
   per-device program. :func:`compiled_memory_attribution` flattens those
   into ``mem/*`` keys for the ``compile_costs`` event row, and
   :func:`reconcile` checks the analytic argument total against XLA's within
   a documented tolerance (:data:`RECON_TOLERANCE`) — if the analytic model
   drifts from what the compiler actually allocates, the reconciliation row
   says so before an OOM does.

Reconciliation contract: the *argument* bytes are compared (params +
optimizer state + batch stack — all concrete, exactly sharded inputs). The
activation estimate is deliberately NOT gated against ``temp_size``:
temporaries also hold fusion workspace and collective buffers, so the plan
reports the ratio (``mem_plan/act_vs_temp``) as a diagnostic instead of
pretending the coarse model is exact. Arguments reconcile within
``RECON_TOLERANCE`` (10%) on real programs; padding and replicated small
leaves account for the slack.

Per-chip HBM capacity resolves in priority order: explicit override
(``observability.memory.hbm_limit_gib`` — also how CPU tests exercise the
verdict) > the runtime's ``memory_stats()['bytes_limit']`` > the
:class:`~automodel_tpu.observability.hlo_costs.DeviceSpec` capacity table >
unknown (``None``: headroom/fits keys stay absent rather than guessing).
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Any

logger = logging.getLogger(__name__)

__all__ = [
    "ACTIVATION_BYTES_PER_TOKEN_LAYER",
    "RECON_TOLERANCE",
    "MemoryPlan",
    "tree_shard_bytes",
    "resolve_hbm_limit_bytes",
    "build_memory_plan",
    "compiled_memory_attribution",
    "reconcile",
]

# Live fp32 activation tensors per (token, layer, hidden-unit) during the
# backward of one pre-norm transformer block: attn in/q/k/v/attn-out/post,
# mlp in/gate/up/act/down plus the residual stream — ~14 hidden-sized
# tensors. Remat ladders shrink this; the estimate is a ceiling for the
# default no-remat path and is labeled an estimate everywhere it appears.
ACTIVATION_BYTES_PER_TOKEN_LAYER = 14

# documented reconciliation tolerance: analytic argument bytes vs XLA's
# argument_size_in_bytes (padding + replicated small leaves + host-side
# scalar args account for the slack)
RECON_TOLERANCE = 0.10

_GIB = float(2**30)


def _gib(nbytes: float | int | None) -> float | None:
    # 6 decimals = ~1 KiB resolution: test-sized programs (a few KiB of
    # arguments) must not round to an indistinguishable 0.0
    return None if nbytes is None else round(float(nbytes) / _GIB, 6)


def _leaf_shard_bytes(leaf: Any) -> int:
    """Per-device bytes of one array(-like): the shard shape when sharded,
    the full shape otherwise. Works for concrete jax.Arrays and abstract
    ShapeDtypeStructs alike — only shape/dtype/sharding are touched."""
    import numpy as np

    shape = getattr(leaf, "shape", None)
    if shape is None:
        return 0
    try:
        itemsize = np.dtype(leaf.dtype).itemsize
    except Exception:
        return 0
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None:
        try:
            shape = sharding.shard_shape(tuple(shape))
        except Exception:
            pass  # unsupported sharding kind: count the full (replicated) size
    return int(math.prod(shape)) * itemsize


def tree_shard_bytes(tree: Any) -> int:
    """Sum of per-device bytes over every array leaf of a pytree."""
    import jax

    return sum(_leaf_shard_bytes(leaf) for leaf in jax.tree.leaves(tree))


@dataclasses.dataclass
class MemoryPlan:
    """The analytic per-device HBM budget, in bytes (GiB only at the edges)."""

    params_bytes: int
    opt_bytes: int
    batch_bytes: int
    act_est_bytes: int
    hbm_limit_bytes: int | None = None
    # filled in at the first compile from memory_analysis(); None until then
    measured_peak_bytes: int | None = None

    @property
    def total_bytes(self) -> int:
        return self.params_bytes + self.opt_bytes + self.batch_bytes + self.act_est_bytes

    @property
    def headroom_bytes(self) -> int | None:
        if self.hbm_limit_bytes is None:
            return None
        # once XLA has spoken, its peak beats the analytic estimate
        used = self.measured_peak_bytes if self.measured_peak_bytes is not None else self.total_bytes
        return self.hbm_limit_bytes - used

    @property
    def fits(self) -> bool | None:
        head = self.headroom_bytes
        return None if head is None else head >= 0

    def header_row(self) -> dict[str, Any]:
        """Flat ``mem_plan/*`` keys for the run_header (and the OOM report)."""
        out: dict[str, Any] = {
            "mem_plan/params_gib": _gib(self.params_bytes),
            "mem_plan/opt_gib": _gib(self.opt_bytes),
            "mem_plan/batch_gib": _gib(self.batch_bytes),
            "mem_plan/act_est_gib": _gib(self.act_est_bytes),
            "mem_plan/total_gib": _gib(self.total_bytes),
        }
        if self.hbm_limit_bytes is not None:
            out["mem_plan/hbm_limit_gib"] = _gib(self.hbm_limit_bytes)
            out["mem_plan/hbm_headroom_gib"] = _gib(self.headroom_bytes)
            out["mem_plan/fits"] = self.fits
        return out


def resolve_hbm_limit_bytes(override_gib: float | None = None,
                            devices: Any = None) -> int | None:
    """Per-chip HBM capacity; None when genuinely unknown (CPU, no override)."""
    if override_gib is not None:
        return int(float(override_gib) * _GIB)
    import jax

    devs = list(devices) if devices is not None else jax.local_devices()
    limits: list[int] = []
    for d in devs:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and stats.get("bytes_limit"):
            limits.append(int(stats["bytes_limit"]))
    if limits:
        return min(limits)  # the tightest chip is the one that OOMs first
    if devs and getattr(devs[0], "platform", None) == "tpu":
        from automodel_tpu.observability.hlo_costs import device_specs

        spec = device_specs(devs[0].device_kind)
        if spec.known and spec.hbm_gib:
            return int(spec.hbm_gib * _GIB)
    return None


def _text_config(model_config: Any) -> Any:
    """The text-stack dims (VLM configs nest them under ``.text``)."""
    if model_config is None:
        return None
    return getattr(model_config, "text", model_config)


def build_memory_plan(
    params: Any,
    opt_state: Any,
    *,
    micro_batch_size: int,
    seq_len: int,
    grad_acc_steps: int = 1,
    dp_degree: int = 1,
    batch_streams: int = 4,
    model_config: Any = None,
    activation_itemsize: int = 4,
    hbm_limit_override_gib: float | None = None,
    devices: Any = None,
) -> MemoryPlan:
    """Analytic per-device plan from the concrete sharded state + config dims.

    ``batch_streams``: int32 token streams per stack entry (input_ids, labels,
    positions, segment_ids). ``dp_degree`` divides the batch dimension —
    the stack shards over every data axis (dp_replicate, dp_shard, ep).
    Activations assume ONE live microbatch (scan-based grad accumulation
    keeps exactly one in flight); the batch stack itself holds all
    ``grad_acc_steps`` microbatches on device.
    """
    params_bytes = tree_shard_bytes(params)
    opt_bytes = tree_shard_bytes(opt_state)
    shard_batch = max(int(micro_batch_size) // max(int(dp_degree), 1), 1)
    batch_bytes = int(grad_acc_steps) * shard_batch * int(seq_len) * 4 * int(batch_streams)

    act_bytes = 0
    tcfg = _text_config(model_config)
    hidden = getattr(tcfg, "hidden_size", None) if tcfg is not None else None
    layers = getattr(tcfg, "num_hidden_layers", None) if tcfg is not None else None
    if isinstance(tcfg, dict):
        hidden = tcfg.get("hidden_size")
        layers = tcfg.get("num_hidden_layers")
    if hidden and layers:
        tokens_per_shard = shard_batch * int(seq_len)
        act_bytes = (tokens_per_shard * int(hidden) * int(layers)
                     * ACTIVATION_BYTES_PER_TOKEN_LAYER * int(activation_itemsize))

    return MemoryPlan(
        params_bytes=params_bytes,
        opt_bytes=opt_bytes,
        batch_bytes=batch_bytes,
        act_est_bytes=act_bytes,
        hbm_limit_bytes=resolve_hbm_limit_bytes(hbm_limit_override_gib, devices),
    )


def compiled_memory_attribution(compiled: Any) -> dict[str, int] | None:
    """Raw byte totals from ``Compiled.memory_analysis()``, or None.

    ``peak_est`` is the classic XLA accounting identity: arguments + outputs
    + temporaries + generated code − aliased (donated inputs alias outputs,
    so their bytes must not be double-counted).
    """
    try:
        ma = compiled.memory_analysis()
    except Exception:
        logger.debug("memory_analysis unavailable on this backend", exc_info=True)
        return None
    if ma is None:
        return None
    try:
        out = {
            "args": int(ma.argument_size_in_bytes),
            "out": int(ma.output_size_in_bytes),
            "temp": int(ma.temp_size_in_bytes),
            "code": int(ma.generated_code_size_in_bytes),
            "alias": int(ma.alias_size_in_bytes),
        }
    except AttributeError:
        logger.debug("memory_analysis missing expected fields", exc_info=True)
        return None
    out["peak_est"] = out["args"] + out["out"] + out["temp"] + out["code"] - out["alias"]
    return out


def reconcile(plan: MemoryPlan, attribution: dict[str, int]) -> dict[str, Any]:
    """Compare the analytic plan against XLA's measured attribution.

    Returns flat log-row keys: ``mem/*_gib`` (the measured side),
    ``mem_plan/recon_rel_err`` (analytic vs measured *argument* bytes — the
    gated comparison, tolerance :data:`RECON_TOLERANCE`) and
    ``mem_plan/act_vs_temp`` (activation estimate / temp bytes, a diagnostic
    ratio, never gated). Also refines the plan's headroom in place with the
    measured peak.
    """
    row: dict[str, Any] = {
        f"mem/{k}_gib": _gib(v) for k, v in attribution.items()
    }
    analytic_args = plan.params_bytes + plan.opt_bytes + plan.batch_bytes
    measured_args = attribution.get("args", 0)
    if measured_args > 0:
        rel = abs(analytic_args - measured_args) / measured_args
        row["mem_plan/recon_rel_err"] = round(rel, 4)
        if rel > RECON_TOLERANCE:
            logger.warning(
                "memory plan reconciliation off by %.1f%% (analytic args %.3f GiB "
                "vs compiled %.3f GiB) — the analytic model may be stale for "
                "this config", rel * 100, analytic_args / _GIB, measured_args / _GIB)
    temp = attribution.get("temp", 0)
    if temp > 0 and plan.act_est_bytes:
        row["mem_plan/act_vs_temp"] = round(plan.act_est_bytes / temp, 3)
    plan.measured_peak_bytes = attribution.get("peak_est")
    if plan.hbm_limit_bytes is not None:
        row["mem_plan/hbm_headroom_gib"] = _gib(plan.headroom_bytes)
        row["mem_plan/fits"] = plan.fits
    return row
