"""Stall watchdog: turn a silent multi-host hang into a diagnosable event.

A wedged collective (one host lost, a deadlocked checkpoint barrier, a stuck
data worker) freezes the train loop with no output at all — the worst failure
mode a long run has. The watchdog is a daemon thread fed heartbeats from the
loop; after ``threshold_s`` of silence it dumps every thread's stack to the
run dir and reports a structured stall event, then re-arms on the next
heartbeat (so a recovered stall and a second stall are both visible).

The dump is pure-Python (``sys._current_frames``) rather than ``faulthandler``
so it lands in a named file with thread names attached, and so a custom
``on_stall`` sink can route the event into the metric stream.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
import traceback
from typing import Any, Callable

logger = logging.getLogger(__name__)

__all__ = ["StallWatchdog"]


class StallWatchdog:
    """Daemon thread that fires when heartbeats stop arriving.

    ``on_stall`` (optional) receives ``{"event": "stall", "stall_s": float,
    "step": int | None, "stack_dump": path}``; exceptions in the sink are
    swallowed — diagnostics must never take the run down themselves.

    ``context_fn`` (optional) is called at fire time and its dict merged into
    the event — the manager passes the goodput snapshot so a stack dump can be
    correlated with what the run was doing (last-completed step rides in
    ``step`` already).
    """

    def __init__(
        self,
        threshold_s: float,
        dump_dir: str,
        on_stall: Callable[[dict[str, Any]], None] | None = None,
        poll_interval_s: float | None = None,
        context_fn: Callable[[], dict[str, Any]] | None = None,
    ):
        if threshold_s <= 0:
            raise ValueError(f"threshold_s must be > 0, got {threshold_s}")
        self.threshold_s = float(threshold_s)
        self.dump_dir = str(dump_dir)
        self.on_stall = on_stall
        self.context_fn = context_fn
        self._poll = poll_interval_s if poll_interval_s else min(max(threshold_s / 4, 0.01), 60.0)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._last_beat: float | None = None
        self._last_step: int | None = None
        self._fired = False
        self._thread: threading.Thread | None = None
        self.stall_count = 0

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "StallWatchdog":
        if self.running:
            return self
        with self._lock:
            self._last_beat = time.monotonic()
            self._fired = False
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="stall-watchdog", daemon=True)
        self._thread.start()
        return self

    def heartbeat(self, step: int | None = None) -> None:
        with self._lock:
            self._last_beat = time.monotonic()
            self._last_step = step
            self._fired = False  # re-arm: a later second stall fires again

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------------ internals
    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            with self._lock:
                if self._last_beat is None or self._fired:
                    continue
                silence = time.monotonic() - self._last_beat
                if silence < self.threshold_s:
                    continue
                self._fired = True  # once per silence window
                step = self._last_step
            self._fire(silence, step)

    def _fire(self, silence: float, step: int | None) -> None:
        self.stall_count += 1
        try:
            path = self.dump_stacks(silence, step)
        except Exception:
            logger.exception("stall watchdog failed to write stack dump")
            path = None
        logger.error(
            "STALL: no train-loop heartbeat for %.1fs (threshold %.1fs, last step %s); "
            "all-thread stacks -> %s", silence, self.threshold_s, step, path,
        )
        if self.on_stall is not None:
            event: dict[str, Any] = {
                "event": "stall",
                "stall_s": round(silence, 1),
                "step": step,
                "stack_dump": path,
            }
            if self.context_fn is not None:
                try:
                    event.update(self.context_fn() or {})
                except Exception:
                    logger.exception("stall watchdog context_fn raised")
            try:
                self.on_stall(event)
            except Exception:
                logger.exception("stall watchdog on_stall sink raised")

    def dump_stacks(self, silence: float, step: int | None = None) -> str:
        """Write every thread's stack to ``dump_dir``; returns the file path."""
        os.makedirs(self.dump_dir, exist_ok=True)
        path = os.path.join(self.dump_dir, f"stall_{self.stall_count:03d}_{int(time.time())}.txt")
        names = {t.ident: t.name for t in threading.enumerate()}
        with open(path, "w") as f:
            f.write(
                f"stall after {silence:.1f}s of silence (threshold {self.threshold_s}s, "
                f"last step {step})\n"
            )
            for tid, frame in sys._current_frames().items():
                f.write(f"\n--- thread {names.get(tid, '?')} (ident {tid}) ---\n")
                f.write("".join(traceback.format_stack(frame)))
        return path
