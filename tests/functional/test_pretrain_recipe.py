"""Megatron-data pretraining through the full recipe (reference llm_pretrain
functional scenario): build a real .bin/.idx corpus, train via the YAML path,
loss must fall."""

import json
import textwrap

import numpy as np

from automodel_tpu.config.loader import load_config
from tests.functional.jsonl import losses as jl_losses, metric_rows
from automodel_tpu.data.llm.megatron.indexed_dataset import MMapIndexedDatasetBuilder
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction


def _build_corpus(tmp_path, vocab=128, n_docs=200, seed=0):
    """Learnable synthetic corpus: token t+1 = (t*3+1) mod vocab within a doc."""
    prefix = str(tmp_path / "corpus")
    rng = np.random.default_rng(seed)
    builder = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    for _ in range(n_docs):
        n = int(rng.integers(20, 60))
        start = int(rng.integers(0, vocab))
        doc = np.empty(n, np.int32)
        doc[0] = start
        for i in range(1, n):
            doc[i] = (doc[i - 1] * 3 + 1) % vocab
        builder.add_document(doc)
    builder.finalize()
    return prefix


def test_megatron_pretrain_loss_decreases(tmp_path, cpu_devices):
    prefix = _build_corpus(tmp_path)
    cfg_text = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 128
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    distributed:
      dp_shard: 8
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.megatron.MegatronPretraining
      paths: [{prefix}]
      seq_length: 32
      split: "80,10,10"
      split_name: train
      num_samples: 512
      index_mapping_dir: {tmp_path}/idx
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: 1
      max_steps: 12
      num_epochs: 4
      handle_sigterm: false
    optimizer:
      lr: 3.0e-2
      max_grad_norm: 1.0
    lr_scheduler:
      lr_warmup_steps: 2
    checkpoint:
      enabled: false
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg_text))
    recipe = TrainFinetuneRecipeForNextTokenPrediction(load_config(p)).setup()
    recipe.run_train_validation_loop()
    rows = metric_rows(tmp_path / "out" / "training.jsonl")
    losses = [r["loss"] for r in rows]
    assert losses[0] > 4.0
    # the corpus is a deterministic affine map: a 2-layer model learns it fast
    assert losses[-1] < losses[0] - 1.0
