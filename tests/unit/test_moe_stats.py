"""MoE telemetry: load-balance edge cases, the moe/* row family, hot-expert flag.

Locks three contracts the MoE observability stack leans on: (1)
``compute_load_balance_metrics`` stays well-defined on degenerate loads
(all-zero layers, a single expert, detailed mode) because a telemetry helper
that NaNs on an all-padding microbatch poisons the JSONL stream; (2) the
``moe/*`` rows from :mod:`automodel_tpu.observability.moe_stats` survive the
MetricLogger's strict-JSON encoding (non-finite → null + ``*_nonfinite``);
(3) the cross-host aggregator's ``hot_expert_host`` flag fires exactly like
``straggler_host`` does, on the MoE wire format only.
"""

from __future__ import annotations

import json
import math

import numpy as np
import pytest

from automodel_tpu.loggers.metric_logger import MetricsSample
from automodel_tpu.moe.metrics import compute_load_balance_metrics
from automodel_tpu.observability.aggregate import (
    HOST_KEYS,
    MOE_HOST_KEYS,
    CrossHostAggregator,
)
from automodel_tpu.observability.moe_stats import (
    MoEStats,
    local_expert_max_util,
    moe_step_metrics,
    routing_entropy,
)


class TestLoadBalanceEdgeCases:
    def test_all_zero_loads_are_finite(self):
        m = compute_load_balance_metrics(np.zeros((3, 8)))
        assert all(math.isfinite(v) for v in m.values())
        # zero ideal → utilization defined as 1.0 (balanced vacuously)
        assert m["moe_load/max_util_mean"] == 1.0
        assert m["moe_load/min_util_mean"] == 1.0
        assert m["moe_load/util_std_mean"] == 0.0
        assert m["moe_load/zero_expert_frac"] == 1.0

    def test_single_expert_is_perfectly_balanced(self):
        m = compute_load_balance_metrics(np.array([[64.0]]))
        assert m["moe_load/max_util_mean"] == 1.0
        assert m["moe_load/zero_expert_frac"] == 0.0
        # top/bottom-k collapses to the one expert
        assert m["moe_load/top0_expert0_util"] == 1.0
        assert m["moe_load/bottom0_expert0_util"] == 1.0

    def test_1d_input_promotes_to_single_layer(self):
        flat = compute_load_balance_metrics(np.array([4.0, 0.0, 4.0, 0.0]))
        stacked = compute_load_balance_metrics(np.array([[4.0, 0.0, 4.0, 0.0]]))
        assert flat == stacked
        assert flat["moe_load/zero_expert_frac"] == 0.5

    def test_detailed_mode_adds_per_layer_rows(self):
        loads = np.array([[8.0, 0.0], [4.0, 4.0]])
        brief = compute_load_balance_metrics(loads, mode="brief")
        detailed = compute_load_balance_metrics(loads, mode="detailed")
        assert "moe_load/layer0/max_util" not in brief
        assert detailed["moe_load/layer0/max_util"] == 2.0
        assert detailed["moe_load/layer1/max_util"] == 1.0
        assert detailed["moe_load/layer0/min_util"] == 0.0
        # brief keys are a subset of detailed
        assert set(brief) <= set(detailed)

    def test_prefix_is_respected(self):
        m = compute_load_balance_metrics(np.ones((2, 4)), prefix="moe")
        assert all(k.startswith("moe/") for k in m)


class TestRoutingEntropy:
    def test_uniform_routing_is_one(self):
        mean, mn = routing_entropy(np.full((3, 8), 16.0))
        assert mean == pytest.approx(1.0)
        assert mn == pytest.approx(1.0)

    def test_collapse_is_zero_and_min_names_worst_layer(self):
        loads = np.array([[10.0, 10.0], [20.0, 0.0]])  # balanced, collapsed
        mean, mn = routing_entropy(loads)
        assert mn == pytest.approx(0.0)
        assert mean == pytest.approx(0.5)

    def test_zero_total_layer_counts_as_uniform(self):
        mean, mn = routing_entropy(np.zeros((2, 4)))
        assert mean == 1.0 and mn == 1.0

    def test_single_expert_degenerate(self):
        assert routing_entropy(np.array([[7.0]])) == (1.0, 1.0)


class TestMoeStepMetricsRow:
    def test_row_keys_and_throughput(self):
        loads = np.array([[6.0, 2.0], [4.0, 4.0]])
        row = moe_step_metrics(loads, dropped_token_frac=0.01, aux_loss=0.5,
                               aux_loss_ema=0.4, step_time_s=2.0, device_count=8)
        assert row["moe/dropped_token_frac"] == 0.01
        assert row["moe/aux_loss"] == 0.5
        assert row["moe/aux_loss_trend"] == pytest.approx(0.1)
        # 16 routed copies / 2s / 8 chips
        assert row["moe/tokens_per_sec_per_chip"] == 1.0
        assert row["moe/max_util_mean"] == pytest.approx((1.5 + 1.0) / 2)
        assert "moe/routing_entropy" in row and "moe/routing_entropy_min" in row

    def test_optional_fields_stay_absent(self):
        row = moe_step_metrics(np.ones((1, 4)))
        assert "moe/dropped_token_frac" not in row
        assert "moe/aux_loss" not in row
        assert "moe/tokens_per_sec_per_chip" not in row

    def test_row_is_strict_json_safe(self):
        row = moe_step_metrics(np.ones((2, 8)), dropped_token_frac=0.0,
                               aux_loss=1.25, aux_loss_ema=1.0,
                               step_time_s=1.0, device_count=1)
        rec = json.loads(MetricsSample(step=3, metrics=row).to_json())
        assert rec["step"] == 3
        assert rec["moe/aux_loss"] == 1.25
        assert not any(k.endswith("_nonfinite") for k in rec)

    def test_nonfinite_aux_loss_becomes_null_plus_flag(self):
        row = moe_step_metrics(np.ones((1, 4)), aux_loss=float("nan"),
                               aux_loss_ema=1.0)
        rec = json.loads(MetricsSample(step=1, metrics=row).to_json())
        assert rec["moe/aux_loss"] is None
        assert rec["moe/aux_loss_nonfinite"] is True
        assert rec["moe/aux_loss_trend"] is None  # nan - ema propagates


class TestMoEStatsState:
    def test_rows_empty_without_expert_load(self):
        assert MoEStats().rows({"loss": 1.0}) == {}

    def test_ema_seeds_then_smooths(self):
        stats = MoEStats(ema_decay=0.5)
        first = stats.rows({"expert_load": np.ones((1, 4)), "moe_aux_loss": 2.0})
        assert first["moe/aux_loss_trend"] == 0.0  # seeded: ema == aux
        second = stats.rows({"expert_load": np.ones((1, 4)), "moe_aux_loss": 4.0})
        # ema = 0.5*2 + 0.5*4 = 3; trend = 4 - 3
        assert second["moe/aux_loss_ema"] == pytest.approx(3.0)
        assert second["moe/aux_loss_trend"] == pytest.approx(1.0)

    def test_nonfinite_aux_does_not_corrupt_ema(self):
        stats = MoEStats(ema_decay=0.5)
        stats.rows({"expert_load": np.ones((1, 4)), "moe_aux_loss": 2.0})
        stats.rows({"expert_load": np.ones((1, 4)), "moe_aux_loss": float("nan")})
        assert stats.aux_loss_ema == 2.0

    def test_dropped_frac_divided_by_grad_acc(self):
        row = MoEStats().rows(
            {"expert_load": np.ones((1, 4)), "dropped_token_frac": 0.4},
            grad_acc_steps=4,
        )
        assert row["moe/dropped_token_frac"] == pytest.approx(0.1)

    def test_bad_ema_decay_rejected(self):
        with pytest.raises(ValueError):
            MoEStats(ema_decay=1.0)


class TestLocalExpertMaxUtil:
    def test_none_without_ep(self):
        assert local_expert_max_util(np.ones((1, 8)), None, 1) is None
        assert local_expert_max_util(np.ones((1, 8)), [0], 1) is None

    def test_picks_this_hosts_shard(self):
        # E=4, ep=2: host with coord 0 owns experts {0,1}, coord 1 owns {2,3}
        loads = np.array([[4.0, 0.0, 1.0, 3.0]])  # ideal = 2 → util 2,0,.5,1.5
        assert local_expert_max_util(loads, [0], 2) == pytest.approx(2.0)
        assert local_expert_max_util(loads, [1], 2) == pytest.approx(1.5)

    def test_indivisible_expert_count_is_none(self):
        assert local_expert_max_util(np.ones((1, 6)), [0], 4) is None


class TestHotExpertAggregation:
    def _agg(self, table, keys=MOE_HOST_KEYS, factor=2.0):
        return CrossHostAggregator(
            straggler_factor=factor, keys=keys,
            allgather_fn=lambda vec: table, process_count=len(table),
        )

    def test_hot_expert_host_flagged(self):
        # hosts: (step_time, data_wait, hbm, headroom, moe_max_util)
        table = [[1.0, 0.0, 1.0, 8.0, 1.1], [1.0, 0.0, 1.0, 8.0, 1.0], [1.0, 0.0, 1.0, 8.0, 3.0]]
        out = self._agg(table).aggregate(
            {"step_time_s": 1.0, "data_wait_s": 0.0, "hbm_gib_peak": 1.0,
             "moe_max_util": 1.1},
        )
        assert out["hot_expert_host"] == 2
        assert out["hot_expert_ratio"] == pytest.approx(3.0 / 1.1, abs=1e-3)
        assert "straggler_host" not in out
        assert out["host/moe_max_util_max"] == 3.0

    def test_balanced_pod_has_no_flag(self):
        table = [[1.0, 0.0, 1.0, 8.0, 1.2], [1.0, 0.0, 1.0, 8.0, 1.1]]
        out = self._agg(table).aggregate(
            {"step_time_s": 1.0, "data_wait_s": 0.0, "hbm_gib_peak": 1.0,
             "moe_max_util": 1.2},
        )
        assert "hot_expert_host" not in out

    def test_dense_wire_format_never_flags_hot_expert(self):
        # legacy HOST_KEYS table: no moe_max_util column, flag must not appear
        table = [[1.0, 0.0, 1.0, 8.0], [5.0, 0.0, 1.0, 8.0], [1.0, 0.0, 1.0, 8.0]]
        out = self._agg(table, keys=HOST_KEYS).aggregate(
            {"step_time_s": 1.0, "data_wait_s": 0.0, "hbm_gib_peak": 1.0},
        )
        assert out["straggler_host"] == 1
        assert "hot_expert_host" not in out

    def test_missing_moe_sample_travels_as_nan(self):
        table = [[1.0, 0.0, 1.0, 8.0, math.nan], [1.0, 0.0, 1.0, 8.0, math.nan]]
        out = self._agg(table).aggregate(
            {"step_time_s": 1.0, "data_wait_s": 0.0, "hbm_gib_peak": 1.0,
             "moe_max_util": None},
        )
        assert "hot_expert_host" not in out
        assert "host/moe_max_util_max" not in out
