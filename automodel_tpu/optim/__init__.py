from automodel_tpu.optim.scheduler import OptimizerParamScheduler, build_lr_schedule
from automodel_tpu.optim.builder import build_optimizer

__all__ = ["OptimizerParamScheduler", "build_lr_schedule", "build_optimizer"]
