from automodel_tpu.models.qwen3_5_moe.model import Qwen3_5MoeConfig, Qwen3_5MoeForCausalLM

__all__ = ["Qwen3_5MoeConfig", "Qwen3_5MoeForCausalLM"]
