"""Perf-regression gate smoke (tools/bench_gate.py) + the bench JSON contract.

Marked ``perf`` (and ``slow``, out of tier-1): run with ``pytest -m perf``.
Drives the real CLI through a subprocess the way CI would: train once on CPU,
write a baseline from the run, gate the same run (exit 0), then gate a
synthetically 10%-slower run (exit non-zero)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.perf]

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
GATE = os.path.join(REPO, "tools", "bench_gate.py")


def _gate(*args):
    return subprocess.run([sys.executable, GATE, *args],
                          capture_output=True, text=True, timeout=120)


@pytest.fixture(scope="module")
def train_run(tmp_path_factory, cpu_devices):
    """One tiny CPU training run shared by the gate scenarios."""
    from automodel_tpu.config.loader import load_config
    from automodel_tpu.recipes.llm.train_ft import (
        TrainFinetuneRecipeForNextTokenPrediction,
    )

    tmp_path = tmp_path_factory.mktemp("perf_gate")
    cfg_text = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 128
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    distributed:
      dp_shard: 4
      tp: 2
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 256
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: 1
      max_steps: 8
      num_epochs: 10
      handle_sigterm: false
    optimizer:
      lr: 1.0e-2
    checkpoint:
      enabled: false
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg_text))
    recipe = TrainFinetuneRecipeForNextTokenPrediction(load_config(p)).setup()
    recipe.run_train_validation_loop()
    return tmp_path


def test_gate_passes_on_matching_run_and_fails_on_10pct_regression(train_run):
    run = str(train_run / "out" / "training.jsonl")
    baseline = str(train_run / "baseline.json")

    wrote = _gate("--run", run, "--baseline", baseline, "--write-baseline")
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    base = json.load(open(baseline))
    assert "tps" in base["metrics"]

    same = _gate("--run", run, "--baseline", baseline)
    assert same.returncode == 0, same.stdout + same.stderr
    assert "[gate] PASS" in same.stdout

    # synthetic regression: scale every row's tps down 10%
    slower = str(train_run / "regressed.jsonl")
    with open(run) as src, open(slower, "w") as dst:
        for line in src:
            row = json.loads(line)
            if row.get("tps") is not None:
                row["tps"] *= 0.9
            dst.write(json.dumps(row) + "\n")
    bad = _gate("--run", slower, "--baseline", baseline)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "REGRESSION" in bad.stdout and "tps" in bad.stdout


def test_mem_plan_keys_ride_run_header(train_run):
    """The memory-plan smoke: a real recipe run's header must carry the full
    ``mem_plan/*`` budget, and its compile_costs row the measured ``mem/*``
    attribution — the keys the memory gate and OOM report build on."""
    rows = [json.loads(line)
            for line in open(train_run / "out" / "training.jsonl")]
    h = [r for r in rows if r.get("run_header")][0]
    for key in ("mem_plan/params_gib", "mem_plan/opt_gib", "mem_plan/batch_gib",
                "mem_plan/act_est_gib", "mem_plan/total_gib"):
        assert h[key] > 0, key
    c = [r for r in rows if r.get("event") == "compile_costs"][0]
    assert c["mem/args_gib"] > 0 and c["mem/peak_est_gib"] > 0
    assert c["mem_plan/recon_rel_err"] is not None


def test_gate_memory_keys_direction(tmp_path):
    """hbm_gib_peak gates lower-is-better through the real CLI — including
    matrix-namespaced cells, which resolve direction by basename."""
    baseline = tmp_path / "b.json"
    baseline.write_text(json.dumps({"metrics": {
        "tps": 1000.0, "hbm_gib_peak": 10.0,
        "matrix/dense_s2048_pfon/hbm_gib_peak": 3.0,
    }}))
    ok_run = tmp_path / "ok.json"
    ok_run.write_text(json.dumps({"metrics": {
        "tps": 1010.0, "hbm_gib_peak": 9.5,
        "matrix/dense_s2048_pfon/hbm_gib_peak": 2.9,
    }}))
    assert _gate("--run", str(ok_run), "--baseline", str(baseline)).returncode == 0

    bad_run = tmp_path / "bad.json"
    bad_run.write_text(json.dumps({"metrics": {
        "tps": 1010.0, "hbm_gib_peak": 12.0,  # footprint GREW 20%
        "matrix/dense_s2048_pfon/hbm_gib_peak": 2.9,
    }}))
    bad = _gate("--run", str(bad_run), "--baseline", str(baseline))
    assert bad.returncode == 1
    assert "hbm_gib_peak" in bad.stdout


def test_gate_reads_bench_json_line(train_run, tmp_path):
    """The gate accepts bench.py's one-line JSON as the run artifact."""
    line = {"ok": True, "metric": "tok/s", "value": 14380.0, "unit": "tokens/s/chip",
            "vs_baseline": 1.4, "extra": {"mfu": 0.6}}
    run = tmp_path / "bench_line.json"
    run.write_text(json.dumps(line))
    baseline = tmp_path / "b.json"
    baseline.write_text(json.dumps({"metrics": {"tps": 14000.0, "mfu": 0.58}}))
    ok = _gate("--run", str(run), "--baseline", str(baseline))
    assert ok.returncode == 0, ok.stdout + ok.stderr


def test_bench_cpu_fallback_prints_parseable_json(tmp_path):
    """bench.py on a TPU-less host: exit 0, final stdout line is JSON with
    ok=true and extra.fallback=cpu (the driver's failure contract)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 8 virtual devices would slow the tiny bench
    result = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                            capture_output=True, text=True, timeout=600, env=env)
    assert result.returncode == 0, result.stderr[-2000:]
    doc = json.loads(result.stdout.strip().splitlines()[-1])
    assert doc["ok"] is True
    assert doc["value"] > 0
    assert doc["extra"]["fallback"] == "cpu"
