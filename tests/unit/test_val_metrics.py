"""Validation-metric correctness: biencoder rank tie-breaking and the
cross-host val-loss aggregation (f32-exact hi/lo transport)."""

from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.recipes.biencoder.train_biencoder import positive_ranks
from automodel_tpu.recipes.llm.train_ft import (
    TrainFinetuneRecipeForNextTokenPrediction,
)


class TestPositiveRanks:
    def test_distinct_scores(self):
        scores = jnp.asarray([[0.1, 0.9, 0.5], [0.7, 0.2, 0.3]])
        labels = jnp.asarray([1, 0])
        assert positive_ranks(scores, labels).tolist() == [1, 1]
        assert positive_ranks(scores, jnp.asarray([0, 1])).tolist() == [3, 3]

    def test_ties_break_by_first_occurrence(self):
        # positive at col 2 ties with cols 0 and 3; only col 0 precedes it
        scores = jnp.asarray([[1.0, 0.5, 1.0, 1.0]])
        assert int(positive_ranks(scores, jnp.asarray([2]))[0]) == 2
        assert int(positive_ranks(scores, jnp.asarray([0]))[0]) == 1
        assert int(positive_ranks(scores, jnp.asarray([3]))[0]) == 3

    def test_all_tied_is_column_order(self):
        """In-batch duplicate passages: every column ties. The old
        strict-wins rank scored ALL of them rank 1 (acc@1 = 100% on a
        degenerate batch); first-occurrence gives the honest column order."""
        scores = jnp.zeros((4, 4))
        labels = jnp.asarray([0, 1, 2, 3])
        assert positive_ranks(scores, labels).tolist() == [1, 2, 3, 4]

    def test_matches_numpy_argsort_on_random(self):
        rng = np.random.default_rng(0)
        scores = rng.choice([0.0, 0.25, 0.5, 1.0], size=(16, 12))
        labels = rng.integers(0, 12, size=16)
        got = positive_ranks(jnp.asarray(scores), jnp.asarray(labels))
        # stable argsort descending == first-occurrence ranking
        order = np.argsort(-scores, axis=-1, kind="stable")
        want = [int(np.where(order[i] == labels[i])[0][0]) + 1
                for i in range(16)]
        assert got.tolist() == want


class _CapturingLogger:
    def __init__(self):
        self.rows = []

    def log(self, step, **kw):
        self.rows.append((step, kw))


def _bare_recipe():
    rec = TrainFinetuneRecipeForNextTokenPrediction.__new__(
        TrainFinetuneRecipeForNextTokenPrediction)
    rec.val_metric_logger = _CapturingLogger()
    rec.experiment_loggers = []
    rec.checkpointer = SimpleNamespace(config=SimpleNamespace(enabled=False))
    return rec


class TestValLossAggregation:
    def test_single_host_plain_division(self):
        rec = _bare_recipe()
        rec._log_val_loss(5, 12.0, 4.0, extra_sums={"val_acc1": 2.0})
        ((step, row),) = rec.val_metric_logger.rows
        assert step == 5
        assert row["val_loss"] == pytest.approx(3.0)
        assert row["val_acc1"] == pytest.approx(0.5)

    def test_multihost_sum_is_f64_exact(self, monkeypatch):
        """The per-host sums cross the allgather as f32 hi/lo pairs and are
        rebuilt in np.float64: a value f32 can't represent (2^25 + 1) must
        survive the trip bit-exactly. The old jnp.float64 transport silently
        downcast to f32 (x64 is disabled) and lost the +1."""
        from jax.experimental import multihost_utils

        host_b = np.asarray([1.0, 1.0], np.float64)  # total=1, count=1

        def fake_allgather(x):
            mine = np.asarray(x)  # [2, K] hi/lo from this "host"
            theirs = np.stack([host_b.astype(np.float32),
                               (host_b - host_b.astype(np.float32)
                                .astype(np.float64)).astype(np.float32)])
            return np.stack([mine, theirs])

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(multihost_utils, "process_allgather",
                            fake_allgather)
        rec = _bare_recipe()
        rec._log_val_loss(1, float(2**25 + 1), 1.0)
        ((_, row),) = rec.val_metric_logger.rows
        # (2^25 + 1 + 1) / 2 == 16777217.0 exactly; an f32 round-trip of the
        # total would have produced 16777216.5
        assert row["val_loss"] == 16777217.0

    def test_multihost_extra_sums_share_denominator(self, monkeypatch):
        from jax.experimental import multihost_utils

        def fake_allgather(x):
            mine = np.asarray(x)
            return np.stack([mine, mine])  # both hosts identical

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(multihost_utils, "process_allgather",
                            fake_allgather)
        rec = _bare_recipe()
        rec._log_val_loss(2, 6.0, 3.0, extra_sums={"val_mrr": 1.5})
        ((_, row),) = rec.val_metric_logger.rows
        assert row["val_loss"] == pytest.approx(2.0)  # 12 / 6
        assert row["val_mrr"] == pytest.approx(0.5)  # 3 / 6
