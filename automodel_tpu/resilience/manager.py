"""The resilience manager a recipe holds (docs/resilience.md).

Glues the anomaly detector, the recovery policy, the checkpoint integrity
layer, coordinated preemption, and the chaos harness behind a handful of
hooks, mirroring how ``Observability`` wraps its pillars:

- ``on_step(step, loss, grad_norm, nonfinite)`` -> action
  (``ok``/``skip_update``/``rollback``/``abort``), emitting a structured
  ``resilience/*`` event for every non-ok verdict;
- ``rollback_target()`` -> the pod-agreed newest verifiable checkpoint step;
- ``record_checkpoint(step)`` marks saves that happened on a clean trajectory;
- ``skip_consolidated_export(elapsed_s)`` -> the pod-agreed preemption
  decision to drop the HF export when the grace window is short.

The manager never touches params itself — the recipe owns the restore
(train_ft.py ``_perform_rollback``) because params/optimizer/rng/dataloader
live there; the manager owns *deciding* and *accounting*.
"""

from __future__ import annotations

import logging
from typing import Any, Callable

from automodel_tpu.resilience.anomaly import (
    ABORT, OK, ROLLBACK, SKIP_UPDATE, AnomalyDetector, RecoveryPolicy,
)
from automodel_tpu.resilience.chaos import ChaosConfig, ChaosInjector
from automodel_tpu.resilience.config import ResilienceConfig

logger = logging.getLogger(__name__)

__all__ = ["ResilienceManager"]


class ResilienceManager:
    def __init__(
        self,
        config: ResilienceConfig,
        checkpointer: Any = None,
        metric_sink: Callable[..., None] | None = None,
    ):
        self.config = config
        self.checkpointer = checkpointer
        self._sink = metric_sink
        self.detector = AnomalyDetector(config.anomaly)
        self.policy = RecoveryPolicy(config.rollback, config.max_skipped_updates)
        chaos_cfg = ChaosConfig.from_dict(config.chaos)
        self.chaos: ChaosInjector | None = (
            ChaosInjector(chaos_cfg) if config.enabled and chaos_cfg.enabled else None
        )
        self.last_good_step: int | None = None
        self.last_verdict = None  # most recent Verdict (layer attribution rides it)
        self.events = 0

    @classmethod
    def from_config(cls, raw: Any, checkpointer: Any = None,
                    metric_sink: Callable[..., None] | None = None) -> "ResilienceManager":
        return cls(ResilienceConfig.from_dict(raw), checkpointer, metric_sink)

    # ------------------------------------------------------------------ state
    @property
    def active(self) -> bool:
        """Anomaly handling on: the loop pulls loss/grad-norm every step (one
        scalar device->host sync — the price of same-step detection) and the
        jitted step must guard non-finite updates."""
        return bool(self.config.enabled and self.config.anomaly.enabled)

    @property
    def guards_updates(self) -> bool:
        return self.active

    def emit(self, step: int, event: str, **fields: Any) -> None:
        """Structured ``resilience/*`` event into the metric fan-out."""
        self.events += 1
        logger.warning("resilience: %s at step %d %s", event, step, fields or "")
        if self._sink is not None:
            self._sink(step, **{"resilience/event": event,
                                **{f"resilience/{k}": v for k, v in fields.items()}})

    # ------------------------------------------------------------------ steps
    def on_step(self, step: int, loss: float, grad_norm: float,
                nonfinite: bool = False, layer: str | None = None) -> str:
        """Classify the step's training signal and decide the action.

        ``layer`` is the dynamics pillar's per-layer attribution for this step
        (nonfinite provenance, or the EMA-excursion suspect) — when set, every
        non-ok event and the eventual rollback verdict cite it.
        """
        if not self.active:
            return OK
        verdict = self.detector.observe(step, float(loss), float(grad_norm),
                                        bool(nonfinite), layer=layer)
        self.last_verdict = verdict
        action = self.policy.decide(verdict)
        if action != OK:
            fields: dict[str, Any] = dict(
                reason=verdict.kind,
                loss=verdict.loss,
                grad_norm=verdict.grad_norm,
                zscore=verdict.zscore,
                consecutive_skips=self.policy.consecutive_skips,
                rollbacks_used=self.policy.rollbacks_used,
            )
            if verdict.layer is not None:
                fields["layer"] = verdict.layer
            self.emit(step, action, **fields)
        return action

    def record_checkpoint(self, step: int) -> None:
        """A save on a clean trajectory: the preferred rollback destination."""
        self.last_good_step = step

    # ------------------------------------------------------------------ rollback
    def rollback_target(self) -> int | None:
        """Pod-agreed newest verifiable checkpoint step (collective on
        multi-host — every host must reach this call together)."""
        if self.checkpointer is None or not self.checkpointer.config.enabled:
            return None
        return self.checkpointer.agreed_restore_step()

    def note_rollback(self, from_step: int, to_step: int, skipped_steps: int,
                      layer: str | None = None) -> None:
        self.policy.on_rollback()
        self.detector.reset()
        fields: dict[str, Any] = dict(
            from_step=from_step, to_step=to_step, skipped_steps=skipped_steps,
            rollbacks_used=self.policy.rollbacks_used,
        )
        if layer is None and self.last_verdict is not None:
            layer = self.last_verdict.layer
        if layer is not None:
            fields["layer"] = layer
        self.emit(from_step, "rollback_done", **fields)

    # ------------------------------------------------------------------ preemption
    def skip_consolidated_export(self, elapsed_since_sigterm_s: float) -> bool:
        """Pod-agreed: drop the consolidated HF export from the preemption save
        when the remaining grace window is short. Any host being short makes
        EVERY host skip — the export's per-tensor gathers are collectives, so
        the decision must be uniform or the pod deadlocks mid-export."""
        from automodel_tpu.parallel.init import any_process_flag

        p = self.config.preemption
        remaining = float(p.grace_period_s) - float(elapsed_since_sigterm_s)
        short = remaining < float(p.export_min_grace_s)
        agreed = any_process_flag(short)
        if agreed:
            self.emit(
                0, "preemption_skip_export",
                remaining_grace_s=round(max(remaining, 0.0), 1),
                export_min_grace_s=p.export_min_grace_s,
            )
        return agreed

    # ------------------------------------------------------------------ client state
    def state_dict(self) -> dict:
        return {
            "detector": self.detector.state_dict(),
            "rollbacks_used": self.policy.rollbacks_used,
            "last_anomaly_step": self.policy.last_anomaly_step,
        }

    def load_state_dict(self, state: dict) -> None:
        self.detector.load_state_dict(state.get("detector", {}))
        self.policy.rollbacks_used = int(state.get("rollbacks_used", 0))
        las = state.get("last_anomaly_step")
        self.policy.last_anomaly_step = None if las is None else int(las)
