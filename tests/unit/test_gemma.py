"""Gemma 2/3 logit parity vs HF transformers (torch CPU) + adapter roundtrip."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.models.auto import AutoModelForCausalLM
from automodel_tpu.models.common.backend import BackendConfig

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _fp32_backend(**kw):
    return BackendConfig(dtype="float32", remat_policy="full", **kw)


def _compare(hf_model, tmp_path, atol=5e-3, seq=12):
    hf_model.eval()
    d = str(tmp_path / "hf")
    hf_model.save_pretrained(d, safe_serialization=True)
    model, params = AutoModelForCausalLM.from_pretrained(
        d, dtype=jnp.float32, backend=_fp32_backend()
    )
    rng = np.random.RandomState(0)
    ids = rng.randint(0, hf_model.config.vocab_size, (2, seq))
    ours = model(params, jnp.asarray(ids))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=atol, rtol=1e-3)
    return model, params


def tiny_gemma3_cfg(**kw):
    base = dict(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        query_pre_attn_scalar=16.0, sliding_window=8,
        layer_types=["sliding_attention", "sliding_attention", "full_attention"],
        rope_theta=1_000_000.0, rope_local_base_freq=10_000.0,
        max_position_embeddings=64, pad_token_id=0, bos_token_id=1, eos_token_id=2,
        tie_word_embeddings=True,
    )
    base.update(kw)
    return transformers.Gemma3TextConfig(**base)


def tiny_gemma2_cfg(**kw):
    base = dict(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        query_pre_attn_scalar=16.0, sliding_window=8,
        layer_types=["sliding_attention", "full_attention"],
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        max_position_embeddings=64, pad_token_id=0, bos_token_id=1, eos_token_id=2,
        tie_word_embeddings=True,
    )
    base.update(kw)
    return transformers.Gemma2Config(**base)


class TestGemma3Parity:
    def test_logits_match_hf(self, tmp_path):
        torch.manual_seed(0)
        hf = transformers.Gemma3ForCausalLM(tiny_gemma3_cfg())
        _compare(hf, tmp_path)

    def test_roundtrip_and_key_parity(self, tmp_path):
        torch.manual_seed(1)
        hf = transformers.Gemma3ForCausalLM(tiny_gemma3_cfg())
        d = str(tmp_path / "hf")
        hf.save_pretrained(d, safe_serialization=True)
        model, params = AutoModelForCausalLM.from_pretrained(
            d, dtype=jnp.float32, backend=_fp32_backend()
        )
        adapter = model.state_dict_adapter()
        hf_dict = adapter.to_hf(params)
        theirs = {k for k in hf.state_dict() if "rotary_emb" not in k and k != "lm_head.weight"}
        assert set(hf_dict) == theirs
        params2 = adapter.from_hf(hf_dict)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params, jax.tree.map(jnp.asarray, params2),
        )

    def test_sharded_init_and_grad(self, cpu_devices):
        from automodel_tpu.parallel.mesh import MeshContext, default_sharding_rules

        ctx = MeshContext(dp_shard=4, tp=2, world_size=8)
        mesh = ctx.build_mesh(cpu_devices)
        rules = default_sharding_rules().with_mesh(mesh)
        model = AutoModelForCausalLM.from_config(
            {"architectures": ["Gemma3ForCausalLM"], "vocab_size": 128,
             "hidden_size": 64, "intermediate_size": 96, "num_hidden_layers": 2,
             "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
             "query_pre_attn_scalar": 16.0, "max_position_embeddings": 64},
            _fp32_backend(),
        )
        with mesh:
            shardings = rules.tree_sharding(model.logical_axes())
            params = jax.jit(lambda k: model.init(k, jnp.float32),
                             out_shardings=shardings)(jax.random.key(0))
            ids = jnp.zeros((4, 8), jnp.int32)

            def loss(p):
                lg = model(p, ids, rules=rules)
                return (lg.astype(jnp.float32) ** 2).mean()

            g = jax.jit(jax.grad(loss))(params)
        assert np.isfinite(np.asarray(g["embed"])).all()


class TestGemma2Parity:
    def test_logits_match_hf_with_softcaps(self, tmp_path):
        torch.manual_seed(2)
        hf = transformers.Gemma2ForCausalLM(tiny_gemma2_cfg())
        model, _ = _compare(hf, tmp_path)
        assert model.config.attn_logit_softcapping == 50.0
        assert model.config.qk_norm is False


class TestGemma3MultimodalCheckpointLoad:
    def test_prefixed_text_backbone_loads(self, tmp_path):
        """Gemma3ForConditionalGeneration checkpoints prefix text weights
        (language_model.model.* pre-4.52, model.language_model.* after); the
        adapter strips either and drops the vision tower."""
        torch.manual_seed(3)
        hf = transformers.Gemma3ForCausalLM(tiny_gemma3_cfg())
        d = str(tmp_path / "hf")
        hf.save_pretrained(d, safe_serialization=True)
        model, params = AutoModelForCausalLM.from_pretrained(
            d, dtype=jnp.float32, backend=_fp32_backend()
        )
        adapter = model.state_dict_adapter()
        flat = adapter.to_hf(params)
        for prefix in ("language_model.model.", "model.language_model."):
            wrapped = {prefix + k[len("model."):]: v for k, v in flat.items()
                       if k.startswith("model.")}
            wrapped["vision_tower.encoder.layer0.weight"] = np.zeros((2, 2), np.float32)
            wrapped["multi_modal_projector.mm_input_projection_weight"] = np.zeros(
                (2, 2), np.float32)
            params2 = adapter.from_hf(wrapped, dtype=np.float32)
            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
                params, jax.tree.map(jnp.asarray, params2),
            )


class TestGemmaDecode:
    def test_cache_matches_full_recompute(self):
        """Greedy cache decode == full recompute, across the sliding/full mix
        and through the sliding window boundary."""
        model = AutoModelForCausalLM.from_config(
            {"architectures": ["Gemma3ForCausalLM"], "vocab_size": 128,
             "hidden_size": 64, "intermediate_size": 96, "num_hidden_layers": 3,
             "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
             "query_pre_attn_scalar": 16.0, "sliding_window": 4,
             "layer_types": ["sliding_attention", "sliding_attention", "full_attention"],
             "max_position_embeddings": 64},
            _fp32_backend(),
        )
        params = model.init(jax.random.key(7), jnp.float32)
        rng = np.random.RandomState(8)
        prompts = rng.randint(0, 128, (2, 6)).astype(np.int32)

        def full(row, n_new):
            ids = list(row)
            for _ in range(n_new):
                x = jnp.asarray([ids], jnp.int32)
                logits = model(params, x, segment_ids=jnp.ones_like(x))
                ids.append(int(np.asarray(logits)[0, -1].argmax()))
            return ids[len(row):]

        want = np.asarray([full(r, 6) for r in prompts], np.int32)
        out = model.generate(params, prompts, max_new_tokens=6, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)
