"""HF Hub resolution: accept ``org/name`` repo ids anywhere a local HF
directory is accepted (reference pre-downloads on rank 0,
_transformers/model_init.py:194, so ``pretrained_model_name_or_path:
meta-llama/Llama-3.2-1B`` just works day-0).

Multi-host protocol: process 0 downloads first while every other process
waits at a cross-host barrier, then the others resolve — a no-op cache hit
when the HF cache is on a shared filesystem, an uncontended per-host download
when it is not (TPU pods usually have per-host local disk; either topology
works, and the barrier prevents N processes thundering the Hub for the same
blobs)."""

from __future__ import annotations

import logging
import os
import re

logger = logging.getLogger(__name__)

__all__ = ["resolve_pretrained_path", "looks_like_repo_id"]

# org/name or bare name: hub id segments are [\w.-]+, at most one slash, and a
# path that exists on disk always wins over the hub interpretation
_REPO_ID_RE = re.compile(r"^[A-Za-z0-9][\w.-]*(/[\w.-]+)?$")

# config + weights + tokenizer assets; skips .bin/.pt duplicates, images, etc.
_DEFAULT_PATTERNS = ("*.json", "*.safetensors", "*.model", "*.txt",
                     "tokenizer*", "*.tiktoken")
# tokenizer-only resolution must not pull the weight shards
TOKENIZER_PATTERNS = ("*.json", "*.model", "*.txt", "tokenizer*", "*.tiktoken")


def looks_like_repo_id(path_or_id: str) -> bool:
    return bool(_REPO_ID_RE.match(path_or_id)) and not os.path.exists(path_or_id)


def resolve_pretrained_path(path_or_id: str, *, revision: str | None = None,
                            allow_patterns=_DEFAULT_PATTERNS) -> str:
    """Local directory -> itself; HF repo id -> local snapshot directory."""
    if os.path.isdir(path_or_id):
        return path_or_id
    if not looks_like_repo_id(path_or_id):
        raise FileNotFoundError(
            f"{path_or_id!r} is neither a local HF model directory nor a "
            "hub repo id (expected 'org/name')"
        )
    # id-shaped AND path-like: 'checkpoints/model' where checkpoints/ exists
    # locally is almost always a typo'd local path (missing file, wrong cwd),
    # and silently treating it as org='checkpoints' would surface as a
    # baffling hub 404. Refuse and name both readings instead of guessing.
    first_seg, sep, _ = path_or_id.partition("/")
    if sep and os.path.isdir(first_seg):
        raise FileNotFoundError(
            f"{path_or_id!r} is ambiguous: it parses as hub repo id "
            f"'{path_or_id}', but {first_seg!r} is also a local directory "
            f"(and {path_or_id!r} itself does not exist). If you meant a "
            f"local path, fix it so the full path exists; if you meant the "
            f"hub repo, rename or move the local {first_seg!r} directory "
            "or run from a different working directory."
        )
    return _download(path_or_id, revision=revision, allow_patterns=allow_patterns)


def _snapshot_download(repo_id: str, revision=None, allow_patterns=None) -> str:
    try:
        from huggingface_hub import snapshot_download
    except ImportError as exc:  # pragma: no cover - hub ships with transformers
        raise ImportError(
            f"loading {repo_id!r} from the HF Hub needs huggingface_hub; "
            "pass a local directory instead"
        ) from exc
    from automodel_tpu.utils.retry import with_retry

    # transient hub/network blips retry with backoff (utils/retry.py); a 401/404
    # or corrupt blob is not transient and raises immediately
    return with_retry(
        snapshot_download, repo_id, revision=revision, allow_patterns=allow_patterns,
        description=f"snapshot_download({repo_id!r})",
    )


def _download(repo_id: str, *, revision, allow_patterns) -> str:
    """main_process_first (parallel/init.py) is the whole protocol: process 0
    fetches before the rest proceed, its barrier is reached even when the
    download raises (so an error can't strand peers in sync_global_devices),
    and the others then hit the shared-fs cache or fetch per-host uncontended.

    Caveat: the topology comes from ``jax.process_count()``, so on multi-host
    this must run AFTER ``jax.distributed.initialize`` (the recipes do) — a
    bare script calling from_pretrained pre-init sees one process per host and
    every host downloads concurrently (correct, just uncoordinated)."""
    import jax

    from automodel_tpu.parallel.init import main_process_first

    try:
        jax.process_count()  # probe: raises when no backend can initialize
    except RuntimeError:
        # pure-host tooling, or a TPU already locked by a running job —
        # degrade to a plain single-process download
        return _snapshot_download(
            repo_id, revision=revision, allow_patterns=allow_patterns
        )
    with main_process_first(f"hub_download:{repo_id}") as is_main:
        if is_main:
            logger.info("downloading %s from the HF Hub", repo_id)
        return _snapshot_download(
            repo_id, revision=revision, allow_patterns=allow_patterns
        )
