"""Qwen3-VL vision tower — TPU-native (HF Qwen3VLMoeVisionModel,
transformers modeling_qwen3_vl_moe.py:617; the reference reuses the HF tower and
swaps only the text stack, reference models/qwen3_vl_moe/model.py:101).

TPU-first contract: all data-dependent bookkeeping — 2D rope position ids, bilinear
pos-embed interpolation indices/weights, per-frame attention segment ids — is computed
host-side by ``prepare_vision_inputs`` (numpy, from ``grid_thw``), so the device
function sees only static-shaped arrays. The Conv3D patch embed collapses to one
matmul (kernel == stride), and per-frame varlen attention becomes segment-id masking
in the shared ``dot_product_attention``.

Token order is the Qwen processor's merge-unit order: (t, block_row, block_col,
intra_row, intra_col), so the spatial mergers are plain reshapes.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import layer_norm
from automodel_tpu.ops.rope import apply_rope_angles, rope_frequencies

__all__ = ["Qwen3VLVisionConfig", "init_vision_params", "vision_logical_axes",
           "vision_forward", "prepare_vision_inputs"]


@dataclasses.dataclass
class Qwen3VLVisionConfig:
    depth: int = 27
    hidden_size: int = 1152
    intermediate_size: int = 4304
    num_heads: int = 16
    in_channels: int = 3
    patch_size: int = 16
    spatial_merge_size: int = 2
    temporal_patch_size: int = 2
    out_hidden_size: int = 3584
    num_position_embeddings: int = 2304
    deepstack_visual_indexes: tuple[int, ...] = (8, 16, 24)
    hidden_act: str = "gelu_pytorch_tanh"
    initializer_range: float = 0.02

    def __post_init__(self):
        # the segmented forward scan taps deepstack features in index order
        if list(self.deepstack_visual_indexes) != sorted(self.deepstack_visual_indexes):
            raise ValueError("deepstack_visual_indexes must be sorted ascending")
        if self.deepstack_visual_indexes and self.deepstack_visual_indexes[-1] >= self.depth:
            raise ValueError("deepstack_visual_indexes out of range")

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "Qwen3VLVisionConfig":
        keys = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in hf.items() if k in keys}
        if "deepstack_visual_indexes" in kwargs:
            kwargs["deepstack_visual_indexes"] = tuple(kwargs["deepstack_visual_indexes"])
        return cls(**kwargs)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def patch_dim(self) -> int:
        return self.in_channels * self.temporal_patch_size * self.patch_size**2

    @property
    def merge_unit(self) -> int:
        return self.spatial_merge_size**2

    @property
    def num_grid_per_side(self) -> int:
        return int(self.num_position_embeddings**0.5)


def init_vision_params(cfg: Qwen3VLVisionConfig, key: jax.Array, dtype=jnp.float32) -> dict:
    std = cfg.initializer_range
    d, i = cfg.hidden_size, cfg.intermediate_size
    dm = d * cfg.merge_unit
    keys = iter(jax.random.split(key, 16))

    def w(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * std).astype(dtype)

    def block_stack(L):
        ks = jax.random.split(next(keys), 4)
        mk = lambda kk, shape: (jax.random.normal(kk, (L, *shape), jnp.float32) * std).astype(dtype)
        return {
            "ln1_w": jnp.ones((L, d), dtype), "b_ln1": jnp.zeros((L, d), dtype),
            "ln2_w": jnp.ones((L, d), dtype), "b_ln2": jnp.zeros((L, d), dtype),
            "qkv_w": mk(ks[0], (d, 3 * d)), "b_qkv": jnp.zeros((L, 3 * d), dtype),
            "proj_w": mk(ks[1], (d, d)), "b_proj": jnp.zeros((L, d), dtype),
            "fc1_w": mk(ks[2], (d, i)), "b_fc1": jnp.zeros((L, i), dtype),
            "fc2_w": mk(ks[3], (i, d)), "b_fc2": jnp.zeros((L, d), dtype),
        }

    def merger(norm_dim):
        return {
            "norm_w": jnp.ones((norm_dim,), dtype), "b_norm": jnp.zeros((norm_dim,), dtype),
            "fc1_w": w((dm, dm)), "b_fc1": jnp.zeros((dm,), dtype),
            "fc2_w": w((dm, cfg.out_hidden_size)), "b_fc2": jnp.zeros((cfg.out_hidden_size,), dtype),
        }

    n_ds = len(cfg.deepstack_visual_indexes)
    return {
        "patch_w": w((cfg.patch_dim, d)),
        "b_patch": jnp.zeros((d,), dtype),
        "pos_embed": w((cfg.num_position_embeddings, d)),
        "blocks": block_stack(cfg.depth),
        "merger": merger(d),
        "ds_mergers": jax.tree.map(
            lambda *xs: jnp.stack(xs), *[merger(dm) for _ in range(n_ds)]
        ) if n_ds else {},
    }


def vision_logical_axes(cfg: Qwen3VLVisionConfig) -> dict:
    blocks = {
        "ln1_w": ("layers", "norm"), "b_ln1": ("layers", "norm"),
        "ln2_w": ("layers", "norm"), "b_ln2": ("layers", "norm"),
        "qkv_w": ("layers", "embed", "heads"), "b_qkv": ("layers", "heads"),
        "proj_w": ("layers", "heads", "embed"), "b_proj": ("layers", "norm"),
        "fc1_w": ("layers", "embed", "mlp"), "b_fc1": ("layers", "mlp"),
        "fc2_w": ("layers", "mlp", "embed"), "b_fc2": ("layers", "norm"),
    }
    merger = {"norm_w": ("norm",), "b_norm": ("norm",),
              "fc1_w": ("embed", "mlp"), "b_fc1": ("mlp",),
              "fc2_w": ("mlp", "embed"), "b_fc2": ("norm",)}
    axes = {
        "patch_w": (None, "embed"), "b_patch": ("norm",),
        "pos_embed": (None, "embed"),
        "blocks": blocks,
        "merger": merger,
    }
    if cfg.deepstack_visual_indexes:
        axes["ds_mergers"] = {k: ("layers",) + v for k, v in merger.items()}
    return axes


def prepare_vision_inputs(grid_thw: np.ndarray, cfg: Qwen3VLVisionConfig) -> dict[str, np.ndarray]:
    """Host-side bookkeeping from ``grid_thw (n_images, 3)``: rope angles' position
    pairs, bilinear pos-embed gather indices/weights, per-frame segment ids —
    everything data-dependent, so the device fn stays static-shaped.

    Mirrors HF rot_pos_emb (:656) and fast_pos_embed_interpolate (:695); all outputs
    follow the processor's merge-unit token order.
    """
    ms = cfg.spatial_merge_size
    side = cfg.num_grid_per_side
    pos_pairs, idx4, w4, seg = [], [[] for _ in range(4)], [[] for _ in range(4)], []
    seg_id = 0
    for t, h, w in np.asarray(grid_thw):
        t, h, w = int(t), int(h), int(w)
        # --- rope coords in merge-unit order ---
        bh, bw = h // ms, w // ms
        row = (np.arange(bh)[:, None, None, None] * ms + np.arange(ms)[None, None, :, None])
        col = (np.arange(bw)[None, :, None, None] * ms + np.arange(ms)[None, None, None, :])
        row = np.broadcast_to(row, (bh, bw, ms, ms)).reshape(-1)
        col = np.broadcast_to(col, (bh, bw, ms, ms)).reshape(-1)
        coords = np.stack([row, col], axis=-1)
        pos_pairs.append(np.tile(coords, (t, 1)))
        # --- bilinear pos-embed interpolation (row-major), then merge-unit permute ---
        h_idx = np.linspace(0, side - 1, h, dtype=np.float32)
        w_idx = np.linspace(0, side - 1, w, dtype=np.float32)
        hf_, wf_ = h_idx.astype(np.int32), w_idx.astype(np.int32)
        hc_, wc_ = np.clip(hf_ + 1, None, side - 1), np.clip(wf_ + 1, None, side - 1)
        dh, dw = h_idx - hf_, w_idx - wf_
        corner_idx = [
            (hf_[:, None] * side + wf_[None, :]),
            (hf_[:, None] * side + wc_[None, :]),
            (hc_[:, None] * side + wf_[None, :]),
            (hc_[:, None] * side + wc_[None, :]),
        ]
        corner_w = [
            (1 - dh)[:, None] * (1 - dw)[None, :],
            (1 - dh)[:, None] * dw[None, :],
            dh[:, None] * (1 - dw)[None, :],
            dh[:, None] * dw[None, :],
        ]
        # row-major (h, w) -> (t, bh, bw, ms, ms) merge-unit order
        perm = (
            np.arange(h * w)
            .reshape(bh, ms, bw, ms)
            .transpose(0, 2, 1, 3)
            .reshape(-1)
        )
        for j in range(4):
            flat_i = corner_idx[j].reshape(-1)[perm]
            flat_w = corner_w[j].reshape(-1)[perm]
            idx4[j].append(np.tile(flat_i, t))
            w4[j].append(np.tile(flat_w, t))
        # --- per-frame attention segments (HF cu_seqlens repeat_interleave h*w, t) ---
        for _ in range(t):
            seg.append(np.full((h * w,), seg_id, dtype=np.int32))
            seg_id += 1
    return {
        "pos_pairs": np.concatenate(pos_pairs).astype(np.int32),  # (Tv, 2)
        "pos_idx": np.stack([np.concatenate(x) for x in idx4]).astype(np.int32),  # (4, Tv)
        "pos_w": np.stack([np.concatenate(x) for x in w4]).astype(np.float32),  # (4, Tv)
        "segment_ids": np.concatenate(seg),  # (Tv,)
    }


def vision_forward(
    cfg: Qwen3VLVisionConfig,
    backend: BackendConfig,
    params: dict,
    patches: jnp.ndarray,  # (Tv, patch_dim) processor-flattened pixels
    pos_pairs: jnp.ndarray,  # (Tv, 2) from prepare_vision_inputs
    pos_idx: jnp.ndarray,  # (4, Tv)
    pos_w: jnp.ndarray,  # (4, Tv)
    segment_ids: jnp.ndarray,  # (Tv,)
):
    """Returns ``(merged (Tv/merge_unit, out_hidden), deepstack (n_ds, Tv/mu, out))``."""
    dtype = backend.jnp_dtype
    d = cfg.hidden_size
    H, dh = cfg.num_heads, cfg.head_dim
    mu = cfg.merge_unit
    approx = cfg.hidden_act == "gelu_pytorch_tanh"

    p = jax.tree.map(lambda a: a.astype(dtype) if a.dtype != jnp.int32 else a, params)

    h = patches.astype(dtype) @ p["patch_w"] + p["b_patch"]
    pos = (p["pos_embed"][pos_idx] * pos_w[..., None].astype(dtype)).sum(0)
    h = h + pos

    # 2D rope: per-token angles [row*(inv_freq), col*(inv_freq)] over head_dim/2
    inv_freq = rope_frequencies(dh // 2)
    angles = (pos_pairs[:, :, None].astype(jnp.float32) * inv_freq).reshape(h.shape[0], -1)
    angles = angles[None]  # (1, Tv, dh/2)

    seg = segment_ids[None]

    def merger_apply(mp, x, post_shuffle):
        if post_shuffle:
            x = x.reshape(-1, d * mu)
            x = layer_norm(x, mp["norm_w"], mp["b_norm"], 1e-6)
        else:
            x = layer_norm(x, mp["norm_w"], mp["b_norm"], 1e-6).reshape(-1, d * mu)
        x = jax.nn.gelu(x @ mp["fc1_w"] + mp["b_fc1"], approximate=False)
        return x @ mp["fc2_w"] + mp["b_fc2"]

    def block_fn(hh, lp):
        x = layer_norm(hh, lp["ln1_w"], lp["b_ln1"], 1e-6)
        qkv = (x @ lp["qkv_w"] + lp["b_qkv"]).reshape(1, -1, 3, H, dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = apply_rope_angles(q, angles)
        k = apply_rope_angles(k, angles)
        attn = dot_product_attention(
            q, k, v, causal=False, segment_ids_q=seg, segment_ids_kv=seg,
            backend=backend.attention,
        )[0].reshape(-1, d)
        hh = hh + (attn @ lp["proj_w"] + lp["b_proj"])
        x = layer_norm(hh, lp["ln2_w"], lp["b_ln2"], 1e-6)
        hh = hh + (jax.nn.gelu(x @ lp["fc1_w"] + lp["b_fc1"], approximate=approx) @ lp["fc2_w"] + lp["b_fc2"])
        return hh, None

    body = backend.layer_remat(block_fn)

    # scan the contiguous segments between deepstack taps (compile time ~ #taps)
    deepstack = []
    bounds = [i + 1 for i in cfg.deepstack_visual_indexes]
    start = 0
    for j, end in enumerate([*bounds, cfg.depth]):
        if end > start:
            seg_params = jax.tree.map(lambda a: a[start:end], p["blocks"])
            h, _ = jax.lax.scan(body, h, seg_params)
        if j < len(bounds):
            mp = jax.tree.map(lambda a: a[j], p["ds_mergers"])
            deepstack.append(merger_apply(mp, h, post_shuffle=True))
        start = end

    merged = merger_apply(p["merger"], h, post_shuffle=False)
    ds = jnp.stack(deepstack) if deepstack else jnp.zeros((0, merged.shape[0], cfg.out_hidden_size), dtype)
    return merged, ds
