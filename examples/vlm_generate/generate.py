"""Image-conditioned generation from a finetuned LLaVA checkpoint.

TPU-native analogue of the reference's examples/vlm_generate/generate.py (which
loads a torch checkpoint and calls HF .generate): here the checkpoint loads
through the safetensors adapter and decode is the framework's own jitted
KV-cache loop (automodel_tpu.generation) — finetune -> sample without leaving
the framework.

Usage:
    python examples/vlm_generate/generate.py \
        --checkpoint-path /path/to/hf_or_exported_checkpoint \
        --prompt "<image> What is shown here?" --image photo.jpg \
        --max-new-tokens 64 --temperature 0.7
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--checkpoint-path", required=True,
                    help="HF-format LLaVA checkpoint (pretrained or exported by "
                         "checkpoint.save_hf after finetuning)")
    ap.add_argument("--prompt", default="<image> Describe this image.")
    ap.add_argument("--image", default=None, help="path to an image file")
    ap.add_argument("--max-new-tokens", type=int, default=64)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-p", type=float, default=None)
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import jax.numpy as jnp

    from automodel_tpu.models.auto import AutoModelForImageTextToText
    from automodel_tpu.models.auto_tokenizer import AutoTokenizer

    model, params = AutoModelForImageTextToText.from_pretrained(args.checkpoint_path)
    tokenizer = AutoTokenizer.from_pretrained(args.checkpoint_path)

    cfg = model.config
    n_img = cfg.num_image_tokens if args.image else 0
    text = args.prompt.replace("<image>", "")
    ids = tokenizer.encode(text, add_special_tokens=True)
    # image placeholders go up front (processor layout: media, then text)
    input_ids = np.asarray([[cfg.image_token_index] * n_img + ids], np.int32)

    pixels = None
    if args.image:
        from PIL import Image

        size = cfg.vision.image_size
        img = Image.open(args.image).convert("RGB").resize((size, size))
        x = np.asarray(img, np.float32) / 255.0
        x = (x - 0.5) / 0.5  # CLIP-style normalize
        pixels = jnp.asarray(x.transpose(2, 0, 1)[None])  # (1, 3, H, W)

    out = model.generate(
        params, input_ids, pixel_values=pixels,
        max_new_tokens=args.max_new_tokens, temperature=args.temperature,
        top_p=args.top_p, top_k=args.top_k,
        eos_token_id=getattr(tokenizer, "eos_token_id", None), seed=args.seed,
    )
    tokens = np.asarray(out["tokens"])[0][: int(out["lengths"][0])]
    print(tokenizer.decode(tokens.tolist()))


if __name__ == "__main__":
    main()
