"""Pipeline parallelism over the ``pp`` mesh axis (SPMD collective pipelining).

TPU-native replacement for torch.distributed.pipelining (reference AutoPipeline,
distributed/pipelining/autopipeline.py:46 + functional.py:289,490): instead of
FQN-slicing a module tree into per-rank stage graphs with explicit P2P send/recv and a
hand-built 1F1B schedule, the layer-stacked param layout makes stage slicing a
*sharding*: layer dim -> ``pp`` axis. Every rank runs the same jitted program; a
``lax.scan`` over pipeline ticks moves activations stage->stage with ``ppermute``
(neighbor ICI hops). Reverse-mode AD differentiates through the scan + ppermute,
yielding the mirrored backward pipeline automatically — no schedule code, no shape
inference, no stage graphs.

Schedule: GPipe-style (all-forward then all-backward per optimizer step) with
bubble fraction (pp-1)/(n_micro+pp-1); the reference's 1F1B/interleaved/zero-bubble
schedules trade that bubble for explicit per-microbatch scheduling — a later
optimization (interleaving = assigning non-contiguous layer blocks per rank, which
this layout also supports by reshaping the layer dim).

Composition: shard_map is manual over ``pp`` only; FSDP/TP shardings on other mesh
axes stay GSPMD-managed inside (same partial-manual pattern as moe.dispatch).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_spmd", "make_pipeline_forward"]


def pipeline_spmd(
    stage_params,  # pytree; leaves (L_local, ...) — this rank's layer slice
    x_stack,  # pytree; leaves (n_micro, ...) — stage-0 inputs (already embedded)
    layer_apply: Callable,  # (stage_params, x) -> y; runs this rank's layers
    *,
    axis: str = "pp",
):
    """Run the pipeline; returns an x_stack-like pytree of outputs, valid on the
    LAST stage (other ranks hold garbage — mask with axis_index == pp-1).

    ``x_stack`` may be a pytree (e.g. {"h": ..., "positions": ..., "segment_ids":
    ...}) — side inputs like positions ride along with the activation through the
    ring so each stage sees its microbatch's metadata. Call inside shard_map manual
    over ``axis``.
    """
    pp = jax.lax.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    leaves = jax.tree.leaves(x_stack)
    n_micro = leaves[0].shape[0]
    steps = n_micro + pp - 1
    # stage s -> s+1; the wraparound edge (pp-1 -> 0) carries only garbage, which
    # stage 0 immediately overwrites with fresh microbatch input.
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def tick(carry, t):
        outputs, state = carry
        mb = jnp.clip(t, 0, n_micro - 1)
        feed = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(a, mb, 0, keepdims=False), x_stack
        )
        x = jax.tree.map(lambda f, s: jnp.where(idx == 0, f, s), feed, state)
        y = layer_apply(stage_params, x)
        # last stage finishes microbatch t-(pp-1) at tick t; earlier ticks write
        # garbage into slot 0 which the t = pp-1 tick overwrites (writes are in
        # time order, so the final write per slot is the correct one)
        out_slot = jnp.clip(t - (pp - 1), 0, n_micro - 1)
        outputs = jax.tree.map(
            lambda o, yl: jax.lax.dynamic_update_index_in_dim(o, yl, out_slot, 0),
            outputs, y,
        )
        state = jax.tree.map(lambda yl: jax.lax.ppermute(yl, axis, perm), y)
        return (outputs, state), None

    # mark the carries pp-varying (the body's ppermute/axis_index make them so)
    def _vary(x):
        return jax.lax.pcast(x, (axis,), to="varying")

    outputs = jax.tree.map(lambda a: _vary(jnp.zeros_like(a)), x_stack)
    state = jax.tree.map(lambda a: _vary(jnp.zeros_like(a[0])), x_stack)
    (outputs, _), _ = jax.lax.scan(tick, (outputs, state), jnp.arange(steps))
    return outputs


def make_pipeline_forward(mesh: Mesh, *, pp_axis: str = "pp"):
    """Wrap (embed, layer_apply, head_loss) into a pp-pipelined loss function.

    Returns ``fn(layer_params, other_params, batch_stack, embed_fn, layer_apply,
    head_loss_fn)`` where:
      - ``embed_fn(params, microbatch) -> x`` (stage-0 work, cheap enough to run
        everywhere: replicated compute beats a broadcast)
      - ``layer_apply(stage_layer_params, x) -> y`` scans this rank's layer slice
      - ``head_loss_fn(params, y, microbatch) -> scalar`` final-norm + head + loss
        (additive across microbatches)

    Layer params must be stacked (L, ...) with the layer dim sharded over ``pp``
    (sharding rule "layers" -> pp); all other params replicated over pp.
    """
    pp = mesh.shape[pp_axis]

    def fn(layer_params, other_params, batch_stack, embed_fn, layer_apply, head_loss_fn):
        def body(layer_params, other_params, batch_stack):
            x_stack = jax.vmap(
                lambda mb: embed_fn(other_params, mb), in_axes=0
            )(batch_stack)
            outs = pipeline_spmd(
                layer_params, x_stack, layer_apply, axis=pp_axis
            )
            is_last = jax.lax.axis_index(pp_axis) == pp - 1
            # sequential over microbatches: only one microbatch's logits live at a
            # time (vmap would materialize n_micro full logits tensors at once,
            # forfeiting exactly the peak-memory win pipelining exists for)
            losses = jax.lax.map(
                lambda ymb: head_loss_fn(other_params, ymb[0], ymb[1]),
                (outs, batch_stack),
            )
            loss = jnp.where(is_last, losses.sum(), 0.0)
            return jax.lax.psum(loss, pp_axis)

        # Replicate non-layer params (embed/head/final-norm) before entering the
        # partial-manual region: a gather whose operand carries tp shardings trips
        # XLA's SpmdPartitioner (ExpandDeviceGroupsWithIota check) when pp is
        # manual. Embed/head tp-sharding inside the pp loop is a later optimization.
        from jax.sharding import NamedSharding

        other_params = jax.lax.with_sharding_constraint(
            other_params, NamedSharding(mesh, P())
        )
        layer_specs = jax.tree.map(lambda _: P(pp_axis), layer_params)
        other_specs = jax.tree.map(lambda _: P(), other_params)
        batch_specs = jax.tree.map(lambda _: P(), batch_stack)
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(layer_specs, other_specs, batch_specs),
            out_specs=P(),
            axis_names={pp_axis},
        )(layer_params, other_params, batch_stack)

    return fn


def make_dense_decoder_pp_loss(model, mesh: Mesh, rules=None, loss_name: str = "masked_ce"):
    """Pipelined forward+loss for Llama-lineage models (the reference's PP covers HF
    decoder LMs the same way: embed on first stage, head+loss on last,
    recipes/llm/train_ft.py:1234-1242).

    Returns ``forward_loss(params, batch_stack, num_label_tokens)`` where
    ``batch_stack`` leaves are (n_micro, ...) — the pipeline consumes all
    microbatches in one call (grad accum *is* the pipeline schedule).
    """
    from automodel_tpu.models.common.transformer import apply_layer_stack
    from automodel_tpu.ops.losses import masked_cross_entropy
    from automodel_tpu.ops.norms import rms_norm

    cfg, backend = model.config, model.backend
    dtype = backend.jnp_dtype
    pipeline = make_pipeline_forward(mesh)

    def embed_fn(other, mb):
        h = other["embed"].astype(dtype)[mb["input_ids"]]
        return {"h": h, "positions": mb["positions"], "segment_ids": mb["segment_ids"]}

    # NB: no sharding-constraint rules inside the pp-manual region —
    # with_sharding_constraint over the full mesh clashes with manual pp axes;
    # GSPMD propagates dp/tp activation shardings from the params instead.
    del rules

    def layer_apply(stage, x):
        lp, sliding = stage
        return apply_layer_stack(cfg, backend, lp, sliding, x, None)

    def head_loss(other, y, mb):
        h = rms_norm(y["h"], other["final_norm"].astype(dtype), cfg.rms_norm_eps)
        unembed = other.get("lm_head")
        if unembed is None:
            unembed = other["embed"].T
        logits = jnp.einsum("bsd,dv->bsv", h, jnp.asarray(unembed).astype(dtype))
        # additive (sum/num) microbatch losses, same contract as make_train_step
        return masked_cross_entropy(logits, mb["labels"], 1.0)

    if loss_name != "masked_ce":
        raise NotImplementedError(f"pp loss {loss_name!r} (use masked_ce)")

    def forward_loss(params, batch_stack, num_label_tokens):
        sliding = jnp.asarray(cfg.sliding_flags, jnp.int32)
        layer_params = (params["layers"], sliding)
        other = {k: v for k, v in params.items() if k != "layers"}
        total = pipeline(layer_params, other, batch_stack,
                         embed_fn, layer_apply, head_loss)
        return total / num_label_tokens

    return forward_loss
