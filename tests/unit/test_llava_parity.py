"""LLaVA VLM logit parity vs transformers (tiny CLIP + tiny Llama, offline)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.models.auto import AutoModelForImageTextToText
from automodel_tpu.models.common.backend import BackendConfig

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

IMAGE_TOKEN = 120


def tiny_llava(tmp_path):
    cfg = transformers.LlavaConfig(
        vision_config=transformers.CLIPVisionConfig(
            hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, image_size=28, patch_size=14,
        ),
        text_config=transformers.LlamaConfig(
            vocab_size=128, hidden_size=48, intermediate_size=96, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        ),
        image_token_index=IMAGE_TOKEN,
        vision_feature_layer=-2,
        vision_feature_select_strategy="default",
    )
    hf_model = transformers.LlavaForConditionalGeneration(cfg).eval()
    d = str(tmp_path / "hf")
    hf_model.save_pretrained(d, safe_serialization=True)
    return hf_model, d


class TestLlavaParity:
    def test_logits_match_hf(self, tmp_path):
        hf_model, d = tiny_llava(tmp_path)
        model, params = AutoModelForImageTextToText.from_pretrained(
            d, dtype=jnp.float32, backend=BackendConfig(dtype="float32")
        )
        # 28/14 -> 2x2 patches = 4 image tokens per image
        assert model.config.num_image_tokens == 4
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 100, (2, 12))
        ids[:, 2:6] = IMAGE_TOKEN
        pixels = rng.randn(2, 3, 28, 28).astype(np.float32)
        ours = np.asarray(model(params, jnp.asarray(ids), pixel_values=jnp.asarray(pixels)))
        with torch.no_grad():
            theirs = hf_model(
                input_ids=torch.tensor(ids), pixel_values=torch.tensor(pixels)
            ).logits.float().numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-3, rtol=1e-3)

    def test_text_only_forward(self, tmp_path):
        _, d = tiny_llava(tmp_path)
        model, params = AutoModelForImageTextToText.from_pretrained(
            d, dtype=jnp.float32, backend=BackendConfig(dtype="float32")
        )
        ids = jnp.arange(10).reshape(1, 10) % 100
        logits = model(params, ids)
        assert logits.shape == (1, 10, 128)

    def test_adapter_roundtrip(self, tmp_path):
        _, d = tiny_llava(tmp_path)
        model, params = AutoModelForImageTextToText.from_pretrained(
            d, dtype=jnp.float32, backend=BackendConfig(dtype="float32")
        )
        adapter = model.state_dict_adapter()
        tensors = adapter.to_hf(jax.tree.map(np.asarray, params))
        assert "vision_tower.vision_model.embeddings.patch_embedding.weight" in tensors
        params2 = adapter.from_hf(tensors, dtype=np.float32)
        ids = jnp.arange(8).reshape(1, 8) % 100
        np.testing.assert_allclose(
            np.asarray(model(params, ids)), np.asarray(model(jax.tree.map(jnp.asarray, params2), ids)),
            atol=1e-5,
        )
