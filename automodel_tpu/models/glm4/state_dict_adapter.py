"""GLM-4 HF key mapping: llama table (incl. sandwich norms) + fused gate_up
split/merge (transformers Glm4MLP packs gate|up into mlp.gate_up_proj.weight;
the shared FusedTensorMixin owns the machinery)."""

from __future__ import annotations

from automodel_tpu.models.common.state_dict import FusedTensorMixin
from automodel_tpu.models.common.transformer import DenseDecoderConfig
from automodel_tpu.models.llama.state_dict_adapter import LlamaStateDictAdapter

__all__ = ["Glm4StateDictAdapter"]


class Glm4StateDictAdapter(FusedTensorMixin, LlamaStateDictAdapter):
    _fused = [("mlp.gate_up_proj.weight",
               ["mlp.gate_proj.weight", "mlp.up_proj.weight"])]

    def __init__(self, cfg: DenseDecoderConfig, scan_layers: bool = True):
        super().__init__(cfg, scan_layers)
        self._fused_splits = {"mlp.gate_up_proj.weight": [cfg.intermediate_size]}
