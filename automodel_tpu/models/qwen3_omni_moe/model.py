"""Qwen3-Omni-MoE thinker — TPU-native (reference models/qwen3_omni_moe/model.py:177;
the reference swaps only the thinker text stack and keeps HF towers — here the
audio tower (models/audio/qwen3_omni_audio.py) and vision tower
(models/vision/qwen3_vl_vit.py — identical math to the omni tower, only merger key
names differ) are native too).

Composition = Qwen3-VL-MoE (deepstack vision + interleaved mrope text) plus audio:
encoded audio tokens replace the embedding rows at ``audio_token_id`` positions.
Audio tokens take text-like (all-axes-equal) mrope positions, which the inherited
``get_mrope_positions`` walk already produces for non-vision tokens
(HF get_rope_index audio branch, modeling_qwen3_omni_moe.py:333-344).

Video spans use omni timestamp semantics: one contiguous placeholder run whose
t-indices are floor(frame * second_per_grid * position_id_per_seconds)
(HF-pinned). Interleaved audio-in-video position ids are not yet supported."""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

import jax.numpy as jnp

from automodel_tpu.models.audio.qwen3_omni_audio import (
    Qwen3OmniAudioConfig,
    audio_forward,
    audio_logical_axes,
    init_audio_params,
    prepare_audio_inputs,
)
from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.qwen3_vl_moe.model import (
    Qwen3VLMoeConfig,
    Qwen3VLMoeForConditionalGeneration,
)

__all__ = ["Qwen3OmniMoeThinkerConfig", "Qwen3OmniMoeThinkerForConditionalGeneration"]


@dataclasses.dataclass
class Qwen3OmniMoeThinkerConfig(Qwen3VLMoeConfig):
    audio: Qwen3OmniAudioConfig = None
    audio_token_id: int = 151646
    position_id_per_seconds: int = 25

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "Qwen3OmniMoeThinkerConfig":
        hf = hf.get("thinker_config", hf)
        base = Qwen3VLMoeConfig.from_hf(hf)
        return cls(
            **{f.name: getattr(base, f.name) for f in dataclasses.fields(Qwen3VLMoeConfig)},
            audio=Qwen3OmniAudioConfig.from_hf(hf.get("audio_config", {})),
            audio_token_id=hf.get("audio_token_id", 151646),
            position_id_per_seconds=hf.get("position_id_per_seconds", 25),
        )


class Qwen3OmniMoeThinkerForConditionalGeneration(Qwen3VLMoeForConditionalGeneration):
    config_class = Qwen3OmniMoeThinkerConfig
    hf_architectures = (
        "Qwen3OmniMoeThinkerForConditionalGeneration",
        "Qwen3OmniMoeForConditionalGeneration",
    )
    # the layer walk is inherited from Qwen3VLMoe, so the pipelined hidden path
    # works as-is once the audio embeds ride the per-microbatch prologue:
    def _pp_extra_embeds(self, params, mb):
        if "audio_chunks" not in mb:
            return None
        ai = mb["audio_inputs"]
        tokens = audio_forward(
            self.config.audio, self.backend, params["audio"],
            mb["audio_chunks"], ai["gather_idx"], ai["segment_ids"],
        )
        return ((mb["audio_coords_b"], mb["audio_coords_s"]), tokens)

    # ---- params ----

    def init(self, key, dtype=jnp.float32):
        import jax

        k_base, k_audio = jax.random.split(jax.random.fold_in(key, 0))
        params = super().init(k_base, dtype)
        params["audio"] = init_audio_params(self.config.audio, k_audio, dtype)
        return params

    def logical_axes(self):
        axes = super().logical_axes()
        axes["audio"] = audio_logical_axes(self.config.audio)
        return axes

    # ---- host-side helpers ----

    def prepare_audio_inputs(self, features) -> dict[str, np.ndarray]:
        return prepare_audio_inputs(features, self.config.audio)

    def audio_token_coords(self, input_ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        b, s = np.where(input_ids == self.config.audio_token_id)
        return b.astype(np.int32), s.astype(np.int32)

    def get_mrope_positions(
        self,
        input_ids,
        grid_thw,
        attention_mask=None,
        video_grid_thw=None,
        second_per_grids=None,  # (n_videos,) seconds per temporal grid (default 1.0)
    ):
        """Omni mrope: audio spans take text-like positions (inherited walk);

        NOTE: this forks the Qwen3VLMoe walk (qwen3_vl_moe/model.py
        get_mrope_positions) because omni videos are ONE contiguous t*gh*gw span
        with timestamp t-indices while VL splits them into per-frame t=1 spans —
        fixes to the parent walk's cursor/mask handling must be mirrored here.
        video spans are ONE contiguous run of t*gh*gw placeholders whose t-index is
        timestamp-scaled — floor(frame * second_per_grid * position_id_per_seconds)
        (HF get_rope_index video branch + get_llm_pos_ids_for_vision). Interleaved
        audio-in-video is not supported."""
        cfg = self.config
        vids = None if video_grid_thw is None else np.asarray(video_grid_thw)
        if vids is None or not (vids[:, 0] > 1).any():
            return super().get_mrope_positions(
                input_ids, grid_thw, attention_mask=attention_mask, video_grid_thw=video_grid_thw
            )
        if second_per_grids is None:
            second_per_grids = np.ones((len(vids),), np.float32)
        ms = cfg.vision.spatial_merge_size
        B, S = input_ids.shape
        pos = np.zeros((3, B, S), dtype=np.int64)
        img_idx, vid_idx = 0, 0
        for b in range(B):
            valid = np.ones((S,), bool) if attention_mask is None else attention_mask[b].astype(bool)
            ids = input_ids[b][valid]
            out = np.zeros((3, len(ids)), dtype=np.int64)
            st, cursor = 0, 0
            is_img = ids == cfg.image_token_id
            is_vid = ids == cfg.video_token_id
            while st < len(ids):
                if not (is_img[st] or is_vid[st]):
                    out[:, st] = cursor
                    cursor += 1
                    st += 1
                    continue
                if is_vid[st]:
                    t, h, w = (int(x) for x in vids[vid_idx])
                    spg = float(second_per_grids[vid_idx])
                    vid_idx += 1
                    t_index = np.floor(
                        np.arange(t) * spg * cfg.position_id_per_seconds
                    ).astype(np.int64)
                else:
                    t, h, w = (int(x) for x in grid_thw[img_idx])
                    img_idx += 1
                    t_index = np.arange(t)
                gh, gw = h // ms, w // ms
                n = t * gh * gw
                span = is_vid[st : st + n] if is_vid[st] else is_img[st : st + n]
                if len(span) < n:
                    raise ValueError(
                        f"vision span truncated: expected {n} placeholder tokens for "
                        f"grid ({t},{h},{w}) but the sequence ends after {len(span)}"
                    )
                if not span.all():
                    # use_audio_in_video interleaves audio tokens per frame inside
                    # the video span — those position ids are not implemented, and
                    # assigning grid coordinates blindly would silently desync
                    raise NotImplementedError(
                        "non-contiguous vision span (audio-in-video interleaving is "
                        "not supported; check grid/token alignment otherwise)"
                    )
                out[0, st : st + n] = np.repeat(t_index, gh * gw) + cursor
                out[1, st : st + n] = np.tile(np.repeat(np.arange(gh), gw), t) + cursor
                out[2, st : st + n] = np.tile(np.arange(gw), t * gh) + cursor
                cursor = int(out[:, st : st + n].max()) + 1
                st += n
            pos[:, b, valid] = out
        return pos

    # ---- forward ----

    def __call__(
        self,
        params,
        input_ids,
        pixel_values=None,
        vision_inputs=None,
        visual_coords=None,
        audio_chunks=None,  # (N, mel, chunk_len)
        audio_inputs=None,  # dict from prepare_audio_inputs
        audio_coords=None,  # (b_idx, s_idx) of audio placeholder tokens
        positions3=None,
        segment_ids=None,
        token_mask=None,
        rules=None,
        return_hidden=False,
        training=True,
    ):
        extra_embeds = None
        if audio_chunks is not None:
            ai = audio_inputs
            audio_tokens = audio_forward(
                self.config.audio, self.backend, params["audio"],
                audio_chunks, ai["gather_idx"], ai["segment_ids"],
            )
            extra_embeds = (audio_coords, audio_tokens)
        return super().__call__(
            params, input_ids,
            pixel_values=pixel_values, vision_inputs=vision_inputs,
            visual_coords=visual_coords, positions3=positions3,
            segment_ids=segment_ids, token_mask=token_mask, rules=rules,
            return_hidden=return_hidden, training=training,
            extra_embeds=extra_embeds,
        )

    # ---- interop ----

    def state_dict_adapter(self):
        from automodel_tpu.models.qwen3_omni_moe.state_dict_adapter import (
            Qwen3OmniMoeThinkerStateDictAdapter,
        )

        return Qwen3OmniMoeThinkerStateDictAdapter(self.config)

    @classmethod
    def from_config(cls, config, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = Qwen3OmniMoeThinkerConfig.from_hf(config)
        return cls(config, backend)
