"""Measured-profile attribution: read the XPlane traces jax.profiler writes.

Every performance number the rest of this package reasons about is *analytic*
(hlo_costs.py derives rooflines from ``cost_analysis()`` and assumes zero
compute/comms overlap), while the profiler traces PR 11 captures were dumped
for humans only. This module machine-reads them: a minimal vendored protobuf
varint/field walker (NO tensorboard/tensorflow dependency) decodes the
``*.xplane.pb`` file, device op events are classified against the compiled
module's named scopes (utils/tracing.scope_blocks: attention/mlp/moe_dispatch/
moe_combine/...) and collective-kind patterns, and interval-union math turns
them into measured per-category time per step — compute, ``moe_a2a``,
per-mesh-axis collectives, host/input gaps — plus an **overlap fraction**
(collective time concurrent with compute), the one number the analytic
roofline cannot produce.

Wire format (the subset of tsl/profiler/protobuf/xplane.proto we read)::

    XSpace        planes=1
    XPlane        id=1 name=2 lines=3 event_metadata=4(map) stat_metadata=5(map)
    XLine         id=1 name=2 timestamp_ns=3 events=4 duration_ps=9 display_name=11
    XEvent        metadata_id=1 offset_ps=2 duration_ps=3 stats=4
    XEventMetadata / XStatMetadata   id=1 name=2
    XStat         metadata_id=1  double=2 uint64=3 int64=4 str=5 bytes=6 ref=7
    map entries   key=1 value=2

Classification correlates trace event names ("fusion.3", "all-reduce.5",
"dot.4") with the compiled HLO text the manager already fetched at
compile_step: instruction names match event names, their ``op_name`` metadata
carries the named-scope path, and replica-group sizes attribute collectives to
mesh axes (same rules as hlo_costs.collective_bytes_by_axis). With no HLO text
the classifier degrades to event-name prefix patterns (collective kinds are
still separated from compute; scopes and axes go unattributed).

Category accounting is exact by construction: ``compute_s`` and ``comm_s`` are
interval *unions* (concurrent executor threads don't double-count),
``overlap_s = |union(comm) ∩ union(compute)|``, and the host/input gap is the
analysis window minus the union of all device-op intervals — so
``compute + comm - overlap + host == window`` identically and the per-step
categories always sum to the measured wall step time.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import struct
from typing import Any, Iterable, Iterator

from automodel_tpu.observability.hlo_costs import (
    COLLECTIVE_OPS,
    MOE_DISPATCH_SCOPES,
    _group_size,
    _OP_RE,
    _OPNAME_RE,
)

logger = logging.getLogger(__name__)

__all__ = [
    "DEFAULT_SCOPES",
    "InstrInfo",
    "TraceEvent",
    "TraceLine",
    "TracePlane",
    "TraceReport",
    "analyze_trace",
    "build_instruction_index",
    "find_xplane_files",
    "intersection_total",
    "merge_intervals",
    "read_xspace",
    "reconcile_with_roofline",
    "union_total",
]

# the named-scope labels the models emit (utils/tracing.scope_blocks tables
# plus the explicit named_scope sites in moe/); innermost match wins, so
# listing both "moe" and its sub-phases is safe
DEFAULT_SCOPES = (
    "attention", "mla_attention", "mlp", "moe_gate", "moe_shared_experts",
    "moe_experts", "ep_experts", "moe",
) + MOE_DISPATCH_SCOPES


# ------------------------------------------------------------- wire walking
def _uvarint(buf: bytes, pos: int) -> tuple[int, int]:
    """Decode one base-128 varint; returns (value, next_pos)."""
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint longer than 10 bytes")


def _fields(buf: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yield (field_number, wire_type, raw_value) over one serialized message.

    Varints come back as ints, length-delimited fields as bytes slices,
    fixed32/64 as bytes — the per-message readers interpret them.
    """
    pos, n = 0, len(buf)
    while pos < n:
        tag, pos = _uvarint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            val, pos = _uvarint(buf, pos)
        elif wt == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:
            ln, pos = _uvarint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:  # groups (3/4) died with proto1; xplane never writes them
            raise ValueError(f"unsupported wire type {wt} at byte {pos}")
        yield field, wt, val


def _signed(val: int) -> int:
    """Two's-complement interpretation of a varint read as unsigned."""
    return val - (1 << 64) if val >= (1 << 63) else val


class _Ref(int):
    """An XStat ref_value: an index into the plane's stat_metadata table."""


def _stat(buf: bytes) -> tuple[int, Any]:
    """One XStat -> (metadata_id, value); refs resolve at the plane level."""
    meta_id, value = 0, None
    for f, _wt, v in _fields(buf):
        if f == 1:
            meta_id = v
        elif f == 2:
            value = struct.unpack("<d", v)[0]
        elif f == 3:
            value = v
        elif f == 4:
            value = _signed(v)
        elif f == 5:
            value = v.decode("utf-8", errors="replace")
        elif f == 6:
            value = v
        elif f == 7:
            value = _Ref(v)
    return meta_id, value


def _metadata_entry(buf: bytes) -> tuple[int, str]:
    """One map<int64, X{Event,Stat}Metadata> entry -> (id, name)."""
    key, name = 0, ""
    for f, _wt, v in _fields(buf):
        if f == 1:
            key = v
        elif f == 2:
            for mf, _mwt, mv in _fields(v):
                if mf == 1:
                    key = key or mv
                elif mf == 2:
                    name = mv.decode("utf-8", errors="replace")
    return key, name


@dataclasses.dataclass
class TraceEvent:
    name: str
    start_ps: int  # absolute: line timestamp_ns * 1000 + offset_ps
    dur_ps: int
    stats: dict[str, Any]

    @property
    def end_ps(self) -> int:
        return self.start_ps + self.dur_ps


@dataclasses.dataclass
class TraceLine:
    name: str
    timestamp_ns: int
    events: list[TraceEvent]


@dataclasses.dataclass
class TracePlane:
    name: str
    lines: list[TraceLine]


def _parse_event(buf: bytes, line_t0_ps: int, event_names: dict[int, str],
                 stat_names: dict[int, str]) -> TraceEvent:
    meta_id, offset_ps, dur_ps = 0, 0, 0
    raw_stats: list[tuple[int, Any]] = []
    for f, _wt, v in _fields(buf):
        if f == 1:
            meta_id = v
        elif f == 2:
            offset_ps = _signed(v)
        elif f == 3:
            dur_ps = _signed(v)
        elif f == 4:
            raw_stats.append(_stat(v))
    stats = {}
    for sid, value in raw_stats:
        key = stat_names.get(sid, str(sid))
        if isinstance(value, _Ref):
            value = stat_names.get(int(value), str(int(value)))
        stats[key] = value
    return TraceEvent(event_names.get(meta_id, str(meta_id)),
                      line_t0_ps + offset_ps, max(int(dur_ps), 0), stats)


def _parse_line(buf: bytes, event_names: dict[int, str],
                stat_names: dict[int, str]) -> TraceLine:
    name, ts_ns = "", 0
    raw_events: list[bytes] = []
    for f, _wt, v in _fields(buf):
        if f == 2 and not name:
            name = v.decode("utf-8", errors="replace")
        elif f == 11:
            name = v.decode("utf-8", errors="replace") or name
        elif f == 3:
            ts_ns = _signed(v)
        elif f == 4:
            raw_events.append(v)
    t0_ps = ts_ns * 1000
    return TraceLine(name, ts_ns,
                     [_parse_event(e, t0_ps, event_names, stat_names)
                      for e in raw_events])


def _parse_plane(buf: bytes) -> TracePlane:
    name = ""
    raw_lines: list[bytes] = []
    event_names: dict[int, str] = {}
    stat_names: dict[int, str] = {}
    for f, _wt, v in _fields(buf):
        if f == 2:
            name = v.decode("utf-8", errors="replace")
        elif f == 3:
            raw_lines.append(v)
        elif f == 4:
            key, meta_name = _metadata_entry(v)
            event_names[key] = meta_name
        elif f == 5:
            key, meta_name = _metadata_entry(v)
            stat_names[key] = meta_name
    return TracePlane(name, [_parse_line(ln, event_names, stat_names)
                             for ln in raw_lines])


def read_xspace(source: str | bytes) -> list[TracePlane]:
    """Decode one serialized XSpace (path or bytes) into planes/lines/events."""
    buf = source if isinstance(source, bytes) else open(source, "rb").read()
    return [_parse_plane(v) for f, _wt, v in _fields(buf) if f == 1]


def find_xplane_files(trace_dir: str) -> list[str]:
    """The ``<host>.xplane.pb`` files under one jax.profiler trace directory."""
    out = []
    for root, _dirs, files in os.walk(trace_dir):
        out.extend(os.path.join(root, f) for f in files
                   if f.endswith(".xplane.pb"))
    return sorted(out)


# -------------------------------------------------------------- interval math
def merge_intervals(intervals: Iterable[tuple[int, int]]) -> list[tuple[int, int]]:
    """Sorted disjoint union of half-open intervals (the canonical form)."""
    ivs = sorted((s, e) for s, e in intervals if e > s)
    out: list[tuple[int, int]] = []
    for s, e in ivs:
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def union_total(intervals: Iterable[tuple[int, int]]) -> int:
    """Total covered length of a set of (possibly overlapping) intervals."""
    return sum(e - s for s, e in merge_intervals(intervals))


def intersection_total(a: Iterable[tuple[int, int]],
                       b: Iterable[tuple[int, int]]) -> int:
    """Length of the intersection of two interval sets (merged two-pointer)."""
    ma, mb = merge_intervals(a), merge_intervals(b)
    i = j = total = 0
    while i < len(ma) and j < len(mb):
        lo = max(ma[i][0], mb[j][0])
        hi = min(ma[i][1], mb[j][1])
        if hi > lo:
            total += hi - lo
        if ma[i][1] <= mb[j][1]:
            i += 1
        else:
            j += 1
    return total


# ------------------------------------------------------------ classification
@dataclasses.dataclass
class InstrInfo:
    """What the compiled HLO says about one instruction name."""

    collective: str | None = None  # collective kind, None for compute
    axis: str | None = None  # mesh axis the collective runs over
    moe: bool = False  # MoE dispatch/combine traffic
    scope: str | None = None  # innermost named-scope label


_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=")


def build_instruction_index(hlo_text: str, mesh_axes: dict | None = None,
                            scopes: tuple[str, ...] = DEFAULT_SCOPES,
                            ) -> dict[str, InstrInfo]:
    """instruction name -> InstrInfo for every instruction in the module text.

    Trace event names on the device op lines are HLO instruction names, so
    this index is the whole correlation: collective kind + replica-group ->
    mesh axis (hlo_costs rules), ``op_name`` metadata -> innermost named
    scope, MOE_DISPATCH_SCOPES membership -> the ``moe_a2a`` flag.
    """
    axes = {str(k): int(v) for k, v in (mesh_axes or {}).items()}
    index: dict[str, InstrInfo] = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        info = InstrInfo()
        m_name = _OPNAME_RE.search(line)
        op_name = m_name.group(1) if m_name else ""
        matches = [(op_name.rfind(s), s) for s in scopes if s in op_name]
        if matches:
            info.scope = max(matches)[1]
        cm = _OP_RE.search(line)
        if cm:
            info.collective = cm.group(2)
            info.moe = any(s in op_name for s in MOE_DISPATCH_SCOPES)
            g = _group_size(line)
            candidates = [ax for ax, size in axes.items() if size == g and size > 1]
            if len(candidates) == 1:
                info.axis = candidates[0]
                if info.axis == "ep" and info.collective == "all-to-all":
                    info.moe = True
            elif info.moe and "ep" in axes:
                info.axis = "ep"
        index[m.group(1)] = info
    return index


def _classify(name: str, index: dict[str, InstrInfo] | None) -> InstrInfo:
    """Event name -> InstrInfo, degrading to name-prefix patterns."""
    if index:
        info = index.get(name)
        if info is None and "." in name:
            # async halves land as `all-reduce-start.5` / `-done.5` events
            # while the index holds the `-start` instruction; retry the stem
            info = index.get(name.replace("-done.", "-start."))
        if info is not None:
            return info
    for kind in COLLECTIVE_OPS:
        if name.startswith(kind):
            return InstrInfo(collective=kind, moe=(kind == "all-to-all"))
    return InstrInfo()


def _is_op_line(line: TraceLine) -> bool:
    """Device-op timing lines: TPU planes call theirs "XLA Ops"; the CPU
    thunk executor's per-op events ride ``tf_XLATfrtCpuClient/...`` threads
    and are recognized by their hlo stats instead (see _is_op_event)."""
    return line.name.strip() == "XLA Ops"


_OP_EVENT_STATS = ("hlo_op", "hlo_category", "hlo_module", "program_id")


def _is_op_event(ev: TraceEvent) -> bool:
    return any(k in ev.stats for k in _OP_EVENT_STATS)


def _op_events(planes: list[TracePlane]) -> list[TraceEvent]:
    out: list[TraceEvent] = []
    for plane in planes:
        for line in plane.lines:
            if _is_op_line(line):
                out.extend(ev for ev in line.events if ev.dur_ps > 0)
            else:
                out.extend(ev for ev in line.events
                           if ev.dur_ps > 0 and _is_op_event(ev))
    return out


# ------------------------------------------------------------------ analysis
_PS = 1e-12  # picoseconds -> seconds


@dataclasses.dataclass
class TraceReport:
    """Measured per-step category attribution for one captured trace.

    All ``*_s`` category fields are **per step** (window totals divided by the
    estimated step count); ``window_s`` is the whole analysis window. The
    identity ``compute_s + comm_s - overlap_s + host_s == step_time_s`` holds
    exactly (see module docstring).
    """

    trace_path: str
    num_events: int
    module: str  # dominant hlo_module (most device time)
    steps: int  # estimated executions inside the window
    steps_hint: int | None  # caller-provided count, when given
    window_s: float
    step_time_s: float  # window_s / steps
    compute_s: float
    comm_s: float
    moe_a2a_s: float
    host_s: float
    overlap_s: float
    overlap_frac: float  # overlap_s / comm_s; 0.0 when no collectives ran
    comm_axis_s: dict[str, float]
    scope_s: dict[str, float]  # summed device-op time per named scope
    measured_bound: str  # compute | comms | moe_a2a | input

    def summary_row(self) -> dict[str, Any]:
        """Flat metric-row keys (the ``trace_summary`` event row contract)."""
        row: dict[str, Any] = {
            "trace/steps": self.steps,
            "trace/events": self.num_events,
            "trace/window_s": round(self.window_s, 6),
            "measured_step_time_s": round(self.step_time_s, 6),
            "measured_t_compute_s": round(self.compute_s, 6),
            "measured_t_comm_s": round(self.comm_s, 6),
            "measured_t_moe_a2a_s": round(self.moe_a2a_s, 6),
            "measured_t_host_s": round(self.host_s, 6),
            "measured_t_overlap_s": round(self.overlap_s, 6),
            "overlap_frac": round(self.overlap_frac, 4),
            "measured_bound": self.measured_bound,
        }
        denom = self.step_time_s or 1.0
        for cat, val in (("compute", self.compute_s), ("comm", self.comm_s),
                         ("moe_a2a", self.moe_a2a_s), ("host", self.host_s)):
            row[f"measured_frac_{cat}"] = round(val / denom, 4)
        for ax, s in sorted(self.comm_axis_s.items()):
            row[f"measured_comm_axis_{ax}_s"] = round(s, 6)
        for scope, s in sorted(self.scope_s.items()):
            row[f"trace/scope/{scope}_s"] = round(s, 6)
        return row

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def _estimate_steps(events: list[TraceEvent]) -> int:
    """Executions of the dominant module inside the window.

    Each execution replays every instruction once (scan/while bodies replay
    more, rare one-shot ops less), so the *median* multiplicity over distinct
    event names is a robust execution count.
    """
    counts: dict[str, int] = {}
    for ev in events:
        counts[ev.name] = counts.get(ev.name, 0) + 1
    if not counts:
        return 1
    mult = sorted(counts.values())
    return max(int(mult[len(mult) // 2]), 1)


def _measured_bound(compute_s: float, comm_s: float, moe_a2a_s: float,
                    host_frac: float, input_bound_frac: float = 0.25) -> str:
    """Mirror of hlo_costs.diagnose_bound on measured numbers. The trace
    cannot split compute-bound from memory-bound (both are device-busy), so
    "memory" never appears here; reconciliation maps the analytic "memory"
    onto measured "compute" for the agree/disagree verdict."""
    if host_frac > input_bound_frac:
        return "input"
    if comm_s > compute_s:
        if comm_s > 0 and moe_a2a_s > 0.5 * comm_s:
            return "moe_a2a"
        return "comms"
    return "compute"


def analyze_trace(trace: str, hlo_text: str | None = None,
                  mesh_axes: dict | None = None,
                  scopes: tuple[str, ...] = DEFAULT_SCOPES,
                  steps_hint: int | None = None) -> TraceReport | None:
    """One trace directory (or ``.xplane.pb`` path) -> a :class:`TraceReport`.

    Returns None when the trace holds no device op events (e.g. an empty
    window); raises only on unreadable/corrupt input. Multi-host traces
    contain one xplane file per host — this host's view is the first sorted
    file, which is the right one for per-host diagnosis under SPMD.
    """
    if os.path.isdir(trace):
        files = find_xplane_files(trace)
        if not files:
            logger.warning("no .xplane.pb under %s", trace)
            return None
        path = files[0]
    else:
        path = trace
    planes = read_xspace(path)
    events = _op_events(planes)
    if not events:
        logger.warning("trace %s has no device op events", path)
        return None

    index = (build_instruction_index(hlo_text, mesh_axes, scopes)
             if hlo_text else None)

    # dominant module = the step program; auxiliary executables (metric
    # pulls, eval helpers) stay in the category accounting but not in the
    # window/step estimation
    by_module: dict[str, list[TraceEvent]] = {}
    for ev in events:
        key = str(ev.stats.get("hlo_module") or ev.stats.get("program_id")
                  or "unknown")
        by_module.setdefault(key, []).append(ev)
    module = max(by_module, key=lambda k: sum(e.dur_ps for e in by_module[k]))
    step_events = by_module[module]
    w0 = min(e.start_ps for e in step_events)
    w1 = max(e.end_ps for e in step_events)
    if w1 <= w0:
        return None
    steps = steps_hint or _estimate_steps(step_events)

    compute_iv: list[tuple[int, int]] = []
    comm_iv: list[tuple[int, int]] = []
    moe_iv: list[tuple[int, int]] = []
    axis_iv: dict[str, list[tuple[int, int]]] = {}
    scope_ps: dict[str, int] = {}
    for ev in events:
        s, e = max(ev.start_ps, w0), min(ev.end_ps, w1)
        if e <= s:
            continue
        info = _classify(ev.name, index)
        if info.collective:
            comm_iv.append((s, e))
            if info.moe:
                moe_iv.append((s, e))
            if info.axis:
                axis_iv.setdefault(info.axis, []).append((s, e))
        else:
            compute_iv.append((s, e))
        if info.scope:
            scope_ps[info.scope] = scope_ps.get(info.scope, 0) + (e - s)

    window_ps = w1 - w0
    compute_ps = union_total(compute_iv)
    comm_ps = union_total(comm_iv)
    overlap_ps = intersection_total(compute_iv, comm_iv)
    busy_ps = union_total(compute_iv + comm_iv)
    host_ps = window_ps - busy_ps
    moe_ps = union_total(moe_iv)
    per_step = _PS / steps
    host_frac = host_ps / window_ps

    return TraceReport(
        trace_path=str(path),
        num_events=len(events),
        module=module,
        steps=steps,
        steps_hint=steps_hint,
        window_s=window_ps * _PS,
        step_time_s=window_ps * per_step,
        compute_s=compute_ps * per_step,
        comm_s=comm_ps * per_step,
        moe_a2a_s=moe_ps * per_step,
        host_s=host_ps * per_step,
        overlap_s=overlap_ps * per_step,
        overlap_frac=(overlap_ps / comm_ps) if comm_ps else 0.0,
        comm_axis_s={ax: union_total(iv) * per_step
                     for ax, iv in sorted(axis_iv.items())},
        scope_s={sc: ps * per_step for sc, ps in sorted(scope_ps.items())},
        measured_bound=_measured_bound(
            compute_ps, comm_ps, moe_ps, host_frac),
    )


# -------------------------------------------------------------- reconciliation
# the trace can't separate compute-bound from memory-bound (both are
# device-busy time), and the measured "input" diagnosis corresponds to the
# analytic data-wait one
_ANALYTIC_TO_MEASURED = {"compute": "compute", "memory": "compute",
                         "comms": "comms", "moe_a2a": "moe_a2a",
                         "input": "input"}


def reconcile_with_roofline(report: TraceReport,
                            roofline: dict[str, Any] | None) -> dict[str, Any]:
    """Measured-vs-analytic verdict keys for the ``trace_summary`` row.

    ``trace/bound_agrees`` is the headline: False means the analytic roofline
    is diagnosing the wrong resource and should not be trusted for this
    config (exactly the disagreement signal the ROADMAP-4 autotuner needs).
    """
    out: dict[str, Any] = {}
    if not roofline:
        return out
    analytic = roofline.get("roofline_bound")
    if not analytic:
        return out
    mapped = _ANALYTIC_TO_MEASURED.get(str(analytic), str(analytic))
    agrees = mapped == report.measured_bound
    out["trace/analytic_bound"] = str(analytic)
    out["trace/bound_agrees"] = agrees
    out["trace/verdict"] = (
        "agree" if agrees
        else f"disagree analytic={analytic} measured={report.measured_bound}")
    expected = roofline.get("roofline_step_time_s")
    if expected and report.step_time_s > 0:
        # >1 would mean the device beat its own roofline — a modeling error
        out["trace/roofline_vs_measured"] = round(
            float(expected) / report.step_time_s, 6)
    return out
