"""HF-checkpoint <-> param-pytree state-dict adapters (reference per-family
state_dict_adapter.py files + checkpoint/state_dict_adapter.py).

This is the day-0 HF value proposition: read HF safetensors into our stacked,
sharding-friendly layout, and write checkpoints back out HF-loadable. Adapters are
declarative tables of :class:`Entry` — an HF key template, a dotted path into the
param tree, and a pair of transforms — so new families are data, not code.

Transforms run in numpy on one tensor at a time (host RAM bounded by the largest
tensor, not the model), and layer stacking/unstacking happens here so models always
see the scan-ready (L, ...) layout.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable, Mapping

import numpy as np

__all__ = ["Entry", "MappingAdapter", "get_path", "set_path"]

Transform = Callable[[np.ndarray], np.ndarray]


def _identity(x: np.ndarray) -> np.ndarray:
    return x


@dataclasses.dataclass
class Entry:
    """One HF tensor -> one (possibly per-layer) slot in the param tree."""

    hf: str  # e.g. "model.layers.{i}.self_attn.q_proj.weight"
    ours: str  # e.g. "layers.wq"
    to_ours: Transform = _identity
    to_hf: Transform = _identity
    optional: bool = False

    @property
    def per_layer(self) -> bool:
        return "{i}" in self.hf


def get_path(tree: dict, path: str) -> Any:
    node = tree
    for part in path.split("."):
        node = node[part]
    return node


def set_path(tree: dict, path: str, value: Any) -> None:
    parts = path.split(".")
    node = tree
    for part in parts[:-1]:
        node = node.setdefault(part, {})
    node[parts[-1]] = value


class MappingAdapter:
    """Applies an Entry table in either direction, handling layer stacking."""

    def __init__(self, entries: Iterable[Entry], num_layers: int, scan_layers: bool = True):
        self.entries = list(entries)
        self.num_layers = num_layers
        self.scan_layers = scan_layers

    def from_hf(self, tensors: Mapping[str, np.ndarray], dtype=None) -> dict:
        """HF flat dict -> our nested param tree (layers stacked when scan_layers)."""
        params: dict = {}
        for e in self.entries:
            if e.per_layer:
                per = []
                missing = False
                for i in range(self.num_layers):
                    key = e.hf.format(i=i)
                    if key not in tensors:
                        if e.optional:
                            missing = True
                            break
                        raise KeyError(f"missing tensor {key!r} in checkpoint")
                    per.append(e.to_ours(np.asarray(tensors[key])))
                if missing:
                    continue
                # models consume the stacked (L, ...) layout whether or not they scan
                stacked = np.stack(per, axis=0)
                set_path(params, e.ours, stacked if dtype is None else stacked.astype(dtype))
            else:
                if e.hf not in tensors:
                    if e.optional:
                        continue
                    raise KeyError(f"missing tensor {e.hf!r} in checkpoint")
                t = e.to_ours(np.asarray(tensors[e.hf]))
                set_path(params, e.ours, t if dtype is None else t.astype(dtype))
        return params

    def to_hf(self, params: dict, dtype=None) -> dict[str, np.ndarray]:
        """Our param tree -> HF flat dict (unstacking layers)."""
        out: dict[str, np.ndarray] = {}
        for e in self.entries:
            try:
                value = get_path(params, e.ours)
            except KeyError:
                if e.optional:
                    continue
                raise
            value = np.asarray(value)
            if e.per_layer:
                for i in range(self.num_layers):
                    t = e.to_hf(value[i])
                    out[e.hf.format(i=i)] = t if dtype is None else t.astype(dtype)
            else:
                t = e.to_hf(value)
                out[e.hf] = t if dtype is None else t.astype(dtype)
        return out
