"""Timers + experiment-logger tests (reference tests for training/timers.py and
loggers/)."""

import time

import jax.numpy as jnp
import pytest

from automodel_tpu.loggers.experiment_loggers import (
    MLflowLogger,
    WandbLogger,
    build_experiment_loggers,
)
from automodel_tpu.training.timers import Timer, Timers


class TestTimers:
    def test_basic_timing(self):
        timers = Timers()
        with timers("work"):
            time.sleep(0.01)
        s = timers.summary()
        assert 0.005 < s["work"] < 1.0

    def test_mean_over_calls(self):
        timers = Timers()
        for _ in range(3):
            with timers("x"):
                time.sleep(0.002)
        assert timers("x").count == 3
        assert timers("x").mean < timers("x").elapsed_total

    def test_sync_blocks_on_result(self):
        t = Timer("d", sync=True)
        t.start()
        out = jnp.ones((256, 256)) @ jnp.ones((256, 256))
        dt = t.stop(out)
        assert dt > 0

    def test_double_start_raises(self):
        t = Timer("x")
        t.start()
        with pytest.raises(RuntimeError, match="already started"):
            t.start()

    def test_summary_reset(self):
        timers = Timers()
        with timers("a"):
            pass
        timers.summary(reset=True)
        assert timers.summary() == {}


class TestExperimentLoggers:
    def test_missing_packages_degrade_gracefully(self):
        # wandb/mlflow are not installed in this image: loggers become no-ops
        w = WandbLogger(project="x", mode="offline")
        w.log(1, loss=1.0)
        w.close()
        m = MLflowLogger(tracking_uri="file:/tmp/nope")
        m.log(1, loss=1.0)
        m.close()

    def test_build_from_config(self):
        from automodel_tpu.config.loader import ConfigNode

        cfg = ConfigNode({"wandb": {"project": "p", "mode": "offline"}})
        loggers = build_experiment_loggers(cfg)
        assert len(loggers) == 1
        cfg2 = ConfigNode({})
        assert build_experiment_loggers(cfg2) == []


class TestNamedScopes:
    """Profiler scope labels (autonvtx parity): block/region names must survive
    into the lowered program's metadata so trace viewers can group ops."""

    def test_moe_block_scopes_in_lowered_text(self):
        import jax

        from automodel_tpu.moe.config import MoEConfig
        from automodel_tpu.moe.layers import init_moe_params, moe_forward

        cfg = MoEConfig(n_routed_experts=4, n_activated_experts=2, dim=16,
                        moe_inter_dim=32, n_shared_experts=1)
        p = init_moe_params(cfg, jax.random.key(0))
        x = jnp.ones((4, 16))
        txt = jax.jit(lambda p, x: moe_forward(cfg, p, x)[0]).lower(p, x).as_text(
            debug_info=True
        )
        for scope in ("moe_gate", "moe_experts", "moe_shared_experts"):
            assert scope in txt, scope

    def test_hybrid_family_block_scopes(self):
        import jax
        import numpy as np

        from automodel_tpu.models.common.backend import BackendConfig
        from automodel_tpu.models.nemotron_v3.model import NemotronHForCausalLM, NemotronV3Config
        from automodel_tpu.moe.config import MoEConfig

        cfg = NemotronV3Config(
            vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=4,
            layers_block_type=("mamba", "attention", "mlp", "moe"),
            num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            mamba_num_heads=4, mamba_head_dim=8, ssm_state_size=16, n_groups=2,
            chunk_size=16, conv_kernel=4,
            moe=MoEConfig(
                n_routed_experts=4, n_activated_experts=2, dim=64, moe_inter_dim=32,
                score_func="sigmoid", expert_activation="relu2",
            ),
        )
        model = NemotronHForCausalLM(cfg, BackendConfig(dtype="float32", remat_policy="full"))
        params = model.init(jax.random.key(0), jnp.float32)
        ids = jnp.asarray(np.zeros((1, 8), np.int32))
        txt = jax.jit(lambda p, i: model(p, i)[0]).lower(params, ids).as_text(
            debug_info=True
        )
        for scope in ("mamba", "attention", "mlp"):
            assert scope in txt, scope

    def test_scoped_wrapper_preserves_fn(self):
        from automodel_tpu.utils.tracing import scope_blocks, scoped

        f = scoped("thing", lambda a, b: a + b)
        assert f(1, 2) == 3
        table = scope_blocks({"x": lambda v: v * 2})
        assert table["x"](4) == 8
