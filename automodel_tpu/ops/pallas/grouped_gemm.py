"""Blocked grouped (ragged) expert GEMM Pallas kernels for TPU.

The MoE hot path multiplies a sorted-by-expert token matrix against a stack of
per-expert weights: row blocks are contiguous per expert, but expert boundaries
fall anywhere inside a block. ``jax.lax.ragged_dot`` handles this in XLA; this
module is the hand-scheduled equivalent (the megablocks/gmm analogue the
reference reaches via torch grouped_gemm / DeepEP+gmm / TE GroupedLinear,
components/moe/experts.py:158,478,661) with the schedule under our control:

- **Tile schedule, not one-hot masking.** A static-length tile list is
  precomputed in XLA from ``group_sizes``: one (row-block, expert) tile per
  overlap, so each grid step runs exactly one MXU matmul against exactly one
  expert's weights. Rows of other experts inside a boundary block are zero-
  masked (boundary tiles only); interior blocks are full-rate MXU work. The
  schedule rides in as a scalar-prefetch SMEM array — index maps read it to
  pick the x/w blocks per step, costing nothing in the kernel body.
- **bf16 operands, f32 accumulate.** Partial products accumulate in an f32
  VMEM scratch across the tiles of a row block (forward) or of an expert
  (dW), cast to the output dtype once on the final tile of the run.
- **Fused custom VJP.** The backward is two kernels over the same schedule:
  dX is the forward kernel with per-expert transposed weights; dW accumulates
  x_e^T @ dout_e per expert run. Residuals are just (x, w, group_sizes) — no
  saved intermediates, so the kernel composes with every remat rung.
- **Interpret mode.** ``interpret=True`` runs the identical kernel logic on
  CPU (any shape, no Mosaic tiling constraints) — the parity tests diff it
  against ``ragged_dot`` bit-for-bit-ish (bf16 rel err <= 1e-2) including
  grads, empty experts, and ragged boundary blocks.
- **XLA fallback.** Shapes whose tiles don't fit the VMEM budget, or whose
  dims break Mosaic lane alignment, fall back to ``jax.lax.ragged_dot``
  (forward AND backward), so ``backend.experts_backend="pallas"`` is always
  safe to enable.

Contract: ``sum(group_sizes) == x.shape[0]`` (every row belongs to a group) —
both call sites guarantee it via ``jnp.bincount`` over all rows. Rows the
wrapper pads (to a block multiple) belong to no group and are sliced off.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["grouped_matmul", "pick_grouped_blocks"]

LANES = 128


def pick_grouped_blocks(d_in: int, d_out: int, n: int | None = None) -> tuple[int, int] | None:
    """Largest (block_n, block_out) tile fitting the VMEM budget, or None.

    Same ~9.8MB modeled budget as linear_ce.pick_blocks (Mosaic's scoped-vmem
    use runs ~30-40% above the model; this keeps compiled kernels under the
    16MB limit). ``d_in`` is the contraction dim (untiled: the whole x row and
    w column strip sit in VMEM); ``d_out`` must divide into a candidate tile.
    ``n=None`` skips the row-divisibility constraint (the wrapper pads rows).
    """
    if d_in % LANES or d_out % LANES:
        return None
    budget = 9_800_000
    best = None
    for bn in (512, 256, 128, 64, 32, 16, 8):
        for bo in (1024, 512, 256, 128):
            if d_out % bo:
                continue
            if n is not None and n % bn:
                continue
            used = (
                2 * bn * d_in * 2      # x tile, double-buffered bf16
                + 2 * d_in * bo * 2    # w tile, double-buffered bf16
                + bn * bo * 4          # out tile
                + max(bn * bo, d_in * bo) * 4  # f32 accumulator (fwd or dW)
            )
            if used <= budget and (best is None or bn * bo > best[0] * best[1]):
                best = (bn, bo)
    return best


def _tile_schedule(group_sizes: jnp.ndarray, num_bn: int, block_n: int) -> jnp.ndarray:
    """(4, S) int32 tile list, S = num_bn + E static: rows are (row_block,
    expert, row_start, row_end) per tile, row range relative to the block.

    One tile per (row-block, expert) overlap; empty experts get one empty-range
    tile (so their dW block is still written — with zeros); tail padding tiles
    repeat the last valid (row_block, expert) with an empty range so they
    extend the final accumulation runs instead of opening new ones. Both the
    row_block and expert columns are non-decreasing, which is what the kernels'
    run-boundary detection (init on change, flush before change) relies on.
    """
    E = group_sizes.shape[0]
    S = num_bn + E
    gs = group_sizes.astype(jnp.int32)
    ends = jnp.cumsum(gs)
    starts = ends - gs
    nonempty = gs > 0
    # empty experts tile the block their (zero-width) range points at, keeping
    # the row_block column monotone — a 0-index fallback would reopen (and
    # zero-flush) an already-written out block mid-schedule
    fb = jnp.clip(starts // block_n, 0, num_bn - 1)
    lb = jnp.where(nonempty, jnp.clip((ends - 1) // block_n, 0, num_bn - 1), fb)
    ntiles = jnp.where(nonempty, lb - fb + 1, 1)
    tile_end = jnp.cumsum(ntiles)
    tile_start = tile_end - ntiles
    total = tile_end[-1]

    s = jnp.arange(S, dtype=jnp.int32)
    eid = jnp.clip(jnp.searchsorted(tile_end, s, side="right"), 0, E - 1).astype(jnp.int32)
    rb = fb[eid] + (s - tile_start[eid])
    blk0 = rb * block_n
    rs = jnp.clip(starts[eid] - blk0, 0, block_n)
    re = jnp.clip(ends[eid] - blk0, 0, block_n)

    valid = s < total
    last = total - 1
    rb = jnp.where(valid, rb, jnp.take(rb, last))
    eid = jnp.where(valid, eid, jnp.take(eid, last))
    rs = jnp.where(valid, rs, 0)
    re = jnp.where(valid, re, 0)
    return jnp.stack([rb, eid, rs, re]).astype(jnp.int32)


def _gmm_kernel(sched_ref, x_ref, w_ref, o_ref, acc_ref, *, block_n, num_s):
    """out[rb] = sum over this row block's tiles of masked_x @ w[expert]."""
    s = pl.program_id(1)
    rb = sched_ref[0, s]
    rs = sched_ref[2, s]
    re = sched_ref[3, s]

    @pl.when((s == 0) | (sched_ref[0, jnp.maximum(s - 1, 0)] != rb))
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(re > rs)
    def _compute():
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_n, 1), 0)
        xm = jnp.where((rows >= rs) & (rows < re), x_ref[...], 0).astype(x_ref.dtype)
        acc_ref[:] += jax.lax.dot_general(
            xm, w_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when((s == num_s - 1) | (sched_ref[0, jnp.minimum(s + 1, num_s - 1)] != rb))
    def _flush():
        o_ref[...] = acc_ref[:].astype(o_ref.dtype)


def _tgmm_kernel(sched_ref, x_ref, g_ref, dw_ref, acc_ref, *, block_n, num_s):
    """dw[e] = sum over this expert's tiles of masked_x^T @ dout."""
    s = pl.program_id(1)
    e = sched_ref[1, s]
    rs = sched_ref[2, s]
    re = sched_ref[3, s]

    @pl.when((s == 0) | (sched_ref[1, jnp.maximum(s - 1, 0)] != e))
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(re > rs)
    def _compute():
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_n, 1), 0)
        xm = jnp.where((rows >= rs) & (rows < re), x_ref[...], 0).astype(x_ref.dtype)
        acc_ref[:] += jax.lax.dot_general(
            xm, g_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when((s == num_s - 1) | (sched_ref[1, jnp.minimum(s + 1, num_s - 1)] != e))
    def _flush():
        dw_ref[0] = acc_ref[:].astype(dw_ref.dtype)


def _pad_rows(x, block_n):
    n = x.shape[0]
    n_pad = -(-n // block_n) * block_n
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
    return x, n_pad


def _gmm_call(x, w, group_sizes, block_n, block_o, interpret):
    n = x.shape[0]
    e_, d, f = w.shape
    xp, n_pad = _pad_rows(x, block_n)
    num_bn = n_pad // block_n
    sched = _tile_schedule(group_sizes, num_bn, block_n)
    num_s = num_bn + e_
    out = pl.pallas_call(
        functools.partial(_gmm_kernel, block_n=block_n, num_s=num_s),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(f // block_o, num_s),
            in_specs=[
                pl.BlockSpec((block_n, d), lambda fi, s, sd: (sd[0, s], 0)),
                pl.BlockSpec((1, d, block_o), lambda fi, s, sd: (sd[1, s], 0, fi)),
            ],
            out_specs=pl.BlockSpec((block_n, block_o), lambda fi, s, sd: (sd[0, s], fi)),
            scratch_shapes=[pltpu.VMEM((block_n, block_o), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, f), x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(sched, xp, w)
    return out[:n]


def _tgmm_call(x, g, group_sizes, block_n, block_o, interpret, e_, d, f):
    xp, n_pad = _pad_rows(x, block_n)
    gp, _ = _pad_rows(g, block_n)
    num_bn = n_pad // block_n
    sched = _tile_schedule(group_sizes, num_bn, block_n)
    num_s = num_bn + e_
    return pl.pallas_call(
        functools.partial(_tgmm_kernel, block_n=block_n, num_s=num_s),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(f // block_o, num_s),
            in_specs=[
                pl.BlockSpec((block_n, d), lambda fi, s, sd: (sd[0, s], 0)),
                pl.BlockSpec((block_n, block_o), lambda fi, s, sd: (sd[0, s], fi)),
            ],
            out_specs=pl.BlockSpec((1, d, block_o), lambda fi, s, sd: (sd[1, s], 0, fi)),
            scratch_shapes=[pltpu.VMEM((d, block_o), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((e_, d, f), g.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(sched, xp, gp)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _grouped_mm(x, w, group_sizes, block_n, block_o, interpret):
    return _gmm_call(x, w, group_sizes, block_n, block_o, interpret)


def _fwd_rule(x, w, group_sizes, block_n, block_o, interpret):
    out = _gmm_call(x, w, group_sizes, block_n, block_o, interpret)
    return out, (x, w, group_sizes)


def _bwd_rule(block_n, block_o, interpret, res, dout):
    x, w, group_sizes = res
    e_, d, f = w.shape
    # dX sweeps the transposed weights (contraction dim f); dW accumulates a
    # (d, block) f32 tile per expert. Re-pick blocks per operand shape; a
    # non-fitting backward falls back to XLA for BOTH grads (ragged_dot's vjp)
    # so the gradient pair always comes from one implementation.
    dx_blocks = (block_n, d) if interpret else pick_grouped_blocks(f, d)
    dw_blocks = (block_n, f) if interpret else pick_grouped_blocks(d, f)
    if dx_blocks is None or dw_blocks is None:
        _, vjp = jax.vjp(lambda xx, ww: jax.lax.ragged_dot(xx, ww, group_sizes), x, w)
        dx, dw = vjp(dout)
    else:
        dx = _gmm_call(dout, jnp.swapaxes(w, 1, 2), group_sizes,
                       dx_blocks[0], dx_blocks[1], interpret)
        dw = _tgmm_call(x, dout, group_sizes, dw_blocks[0], dw_blocks[1],
                        interpret, e_, d, f)
    return dx, dw, np.zeros(group_sizes.shape, dtype=jax.dtypes.float0)


_grouped_mm.defvjp(_fwd_rule, _bwd_rule)


def grouped_matmul(
    x: jnp.ndarray,  # (N, D) rows sorted so each group's rows are contiguous
    w: jnp.ndarray,  # (E, D, F) per-group weights
    group_sizes: jnp.ndarray,  # (E,) int32, sum == N
    *,
    interpret: bool = False,
    block_n: int | None = None,
    block_o: int | None = None,
) -> jnp.ndarray:
    """``jax.lax.ragged_dot`` semantics via the blocked Pallas schedule.

    Differentiable w.r.t. x and w through the fused Pallas backward. Shapes the
    tile picker rejects (lane misalignment, VMEM overflow) silently use
    ``ragged_dot`` — callers opt into the kernel, never into a crash. In
    interpret mode (CPU tests) any shape runs; unspecified blocks default to
    small tiles that exercise multi-block schedules on test-sized inputs.
    """
    if interpret:
        bn = block_n or 8
        bo = block_o or w.shape[2]
    else:
        picked = pick_grouped_blocks(w.shape[1], w.shape[2])
        if picked is None:
            return jax.lax.ragged_dot(x, w, group_sizes)
        bn = block_n or picked[0]
        bo = block_o or picked[1]
    if w.shape[2] % bo:
        return jax.lax.ragged_dot(x, w, group_sizes)
    return _grouped_mm(x, w, group_sizes.astype(jnp.int32), bn, bo, interpret)
