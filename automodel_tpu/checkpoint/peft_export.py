"""HF PEFT-format adapter export: adapter_model.safetensors + adapter_config.json.

The consolidated export merges LoRA into the base weights; this writes the
ADAPTER ALONE in the layout the ``peft`` library loads
(``PeftModel.from_pretrained``), so a TPU finetune hands its adapter to any
torch/HF deployment without shipping base weights (reference PEFT checkpoint
addon, checkpoint/addons.py — its DCP save keeps adapter state separate the
same way).

Key mapping rides the model's state-dict Entry table: our LoRA tree mirrors the
param tree (e.g. ``layers.wq``), each matching single-key Entry names the HF
module (``model.layers.{i}.self_attn.q_proj``), and factors transpose to torch
Linear layout (A: (r, in_features), B: (out_features, r)).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["save_peft_adapter"]


def _hf_layer_ids(e, n_stack: int):
    """Stack index -> HF layer index, honoring Entry.layer_indices/layer_range
    (interleaved-hybrid and ranged entries: stack slot 2 may be HF layer 11)."""
    if e.layer_indices is not None:
        return list(e.layer_indices)
    if e.layer_range is not None:
        return list(range(*e.layer_range))
    return list(range(n_stack))


def save_peft_adapter(
    out_dir: str,
    lora_tree: Any,
    peft_cfg,
    entries,
    *,
    host_fn=np.asarray,
    base_model_name: str | None = None,
    write: bool = True,
) -> dict[str, np.ndarray]:
    """Write the HF PEFT adapter dir; returns the flat tensor dict.

    ``host_fn`` gathers a (possibly sharded) leaf to host — under multi-host
    meshes it is collective, so call on EVERY process with ``write`` true only
    on rank 0. Adapter factors are rank-r small, so a dense dict is fine."""
    from automodel_tpu.peft.lora import _flatten_lora

    by_ours = {}
    for e in entries:
        if isinstance(e.hf, str):
            by_ours[e.ours] = e

    tensors: dict[str, np.ndarray] = {}
    modules: set[str] = set()
    for path, leaf in sorted(_flatten_lora(lora_tree)):
        e = by_ours.get(path)
        if e is None:
            logger.warning(
                "peft export: no single-key HF mapping for %r (merged/tuple "
                "entries can't split a low-rank delta) — skipped", path,
            )
            continue
        module_tmpl = e.hf.removesuffix(".weight")
        a = host_fn(leaf["lora_a"])  # (*stack, fan_in, r)
        b = host_fn(leaf["lora_b"])  # (*stack, r, fan_out)
        mag = host_fn(leaf["magnitude"]) if "magnitude" in leaf else None
        n_stack = a.ndim - 2
        hf_ids = _hf_layer_ids(e, a.shape[0]) if n_stack >= 1 else [None]
        for li, i in enumerate(hf_ids):
            fmt = {"i": i} if i is not None else {}
            a_l = a[li] if i is not None else a
            b_l = b[li] if i is not None else b
            if a_l.ndim != 2:  # expert-stacked adapters: flatten extra stack dims out of scope
                logger.warning("peft export: %r has extra stack dims — skipped", path)
                break
            module = module_tmpl.format(**fmt)
            modules.add(module.rsplit(".", 1)[-1])
            key = f"base_model.model.{module}"
            # torch Linear layout: A.weight (r, in), B.weight (out, r)
            tensors[f"{key}.lora_A.weight"] = np.ascontiguousarray(a_l.T)
            tensors[f"{key}.lora_B.weight"] = np.ascontiguousarray(b_l.T)
            if mag is not None:
                m_l = mag[li] if i is not None else mag
                tensors[f"{key}.lora_magnitude_vector"] = np.ascontiguousarray(m_l)

    if write:
        from safetensors.numpy import save_file

        os.makedirs(out_dir, exist_ok=True)
        save_file(tensors, os.path.join(out_dir, "adapter_model.safetensors"),
                  metadata={"format": "pt"})
        cfg = {
            "peft_type": "LORA",
            "r": int(peft_cfg.dim),
            "lora_alpha": int(peft_cfg.alpha),
            "lora_dropout": float(peft_cfg.dropout),
            "use_dora": bool(peft_cfg.use_dora),
            "target_modules": sorted(modules),
            "bias": "none",
            "task_type": "CAUSAL_LM",
            "base_model_name_or_path": base_model_name or "",
            # our scaling is alpha/r (PeftConfig.scaling) — peft's non-rslora default
            "use_rslora": False,
        }
        with open(os.path.join(out_dir, "adapter_config.json"), "w") as f:
            json.dump(cfg, f, indent=2)
    return tensors
