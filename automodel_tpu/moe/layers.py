"""MoE block: gate + grouped experts + shared experts (reference MoE,
components/moe/layers.py:515).

The reference overlaps shared experts with the EP all-to-all on a separate CUDA stream
(layers.py:615-630); under XLA the scheduler overlaps independent ops inside one jit
program, so the block is just straight-line code.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.experts import (
    capacity_experts_apply,
    expert_logical_axes,
    grouped_experts_apply,
    init_expert_params,
)
from automodel_tpu.moe.gate import (
    fake_balanced_route,
    gate_logical_axes,
    init_gate_params,
    route,
)

__all__ = ["init_moe_params", "moe_logical_axes", "moe_forward", "cast_moe_compute_params"]


def cast_moe_compute_params(moe_params: dict, dtype) -> dict:
    """Cast MoE block params to the compute dtype, keeping the routing correction bias
    fp32 (bf16 rounding flips expert selection, reference layers.py:262-266)."""
    return {
        sub: {
            k: (v if sub == "gate" and k == "score_correction_bias" else v.astype(dtype))
            for k, v in leaves.items()
        }
        if isinstance(leaves, dict)
        else leaves.astype(dtype)
        for sub, leaves in moe_params.items()
    }


def init_moe_params(cfg: MoEConfig, key: jax.Array, dtype=jnp.float32, init_std: float = 0.02) -> dict:
    kg, ke, ks, ksg = jax.random.split(key, 4)
    params = {
        "gate": init_gate_params(cfg, kg, dtype, init_std),
        "experts": init_expert_params(cfg, ke, dtype, init_std),
    }
    if cfg.n_shared_experts > 0:
        D, I = cfg.dim, cfg.shared_inter_dim
        keys = jax.random.split(ks, 3)
        shared = {
            "w_up": (jax.random.normal(keys[0], (D, I), jnp.float32) * init_std).astype(dtype),
            "w_down": (jax.random.normal(keys[1], (I, D), jnp.float32) * init_std).astype(dtype),
        }
        if cfg.shared_expert_activation == "swiglu":
            shared["w_gate"] = (jax.random.normal(keys[2], (D, I), jnp.float32) * init_std).astype(dtype)
        params["shared_experts"] = shared
        if cfg.shared_expert_gate:
            params["shared_expert_gate"] = (
                jax.random.normal(ksg, (D, 1), jnp.float32) * init_std
            ).astype(dtype)
    return params


def moe_logical_axes(cfg: MoEConfig) -> dict:
    axes = {"gate": gate_logical_axes(cfg), "experts": expert_logical_axes(cfg)}
    if cfg.n_shared_experts > 0:
        shared = {"w_up": ("embed", "mlp"), "w_down": ("mlp", "embed")}
        if cfg.shared_expert_activation == "swiglu":
            shared["w_gate"] = ("embed", "mlp")
        axes["shared_experts"] = shared
        if cfg.shared_expert_gate:
            axes["shared_expert_gate"] = ("embed", None)
    return axes


def _shared_experts_forward(cfg: MoEConfig, params: dict, x: jnp.ndarray) -> jnp.ndarray:
    sp = params["shared_experts"]
    up = x @ sp["w_up"]
    if cfg.shared_expert_activation == "swiglu":
        act = jax.nn.silu(x @ sp["w_gate"]) * up
    else:  # relu2
        act = jnp.square(jax.nn.relu(up))
    z = act @ sp["w_down"]
    if "shared_expert_gate" in params:
        z = jax.nn.sigmoid(x @ params["shared_expert_gate"]) * z
    return z


def moe_forward(
    cfg: MoEConfig,
    params: dict,
    x: jnp.ndarray,  # (B, S, D) or (T, D)
    token_mask: jnp.ndarray | None = None,  # (B, S) or (T,) bool; True = valid
    *,
    training: bool = True,
    dispatcher: str = "ragged",  # "ragged" (dropless) | "capacity" (GShard one-hot)
    capacity_factor: float = 1.25,
    fake_balanced_gate: bool = False,
    fake_gate_noise: float = 0.0,
    experts_backend: str = "ragged_dot",  # "ragged_dot" | "pallas" (ragged only)
):
    """Returns ``(y, aux_loss|None, expert_load (E,))``; y has x's shape.

    aux_loss is *unscaled* — the recipe adds ``cfg.aux_loss_coeff * aux_loss``
    (x num-tokens correction) to the train loss, replacing the reference's autograd-hook
    scaler (megatron/moe_utils.py MoEAuxLossAutoScaler).
    """
    shape = x.shape
    x2 = x.reshape(-1, cfg.dim)
    mask = None if token_mask is None else token_mask.reshape(-1)

    # named scopes label the trace's routing vs expert-GEMM vs shared regions
    # (autonvtx parity for the MoE block internals)
    with jax.named_scope("moe_gate"):
        if fake_balanced_gate:
            weights, indices, aux_loss, expert_load = fake_balanced_route(
                cfg, x2, noise=fake_gate_noise
            )
        else:
            weights, indices, aux_loss, expert_load = route(
                cfg, params["gate"], x2, mask, training=training
            )

    with jax.named_scope("moe_experts"):
        if dispatcher == "capacity":
            y = capacity_experts_apply(
                cfg, params["experts"], x2, weights, indices, mask, capacity_factor=capacity_factor
            )
        else:
            y = grouped_experts_apply(cfg, params["experts"], x2, weights, indices, mask,
                                      experts_backend=experts_backend)

    if cfg.n_shared_experts > 0:
        with jax.named_scope("moe_shared_experts"):
            y = y + _shared_experts_forward(cfg, params, x2)

    return y.reshape(shape), aux_loss, expert_load
