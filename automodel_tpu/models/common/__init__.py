from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.transformer import (
    decoder_forward,
    init_dense_decoder_params,
    dense_decoder_logical_axes,
)

__all__ = [
    "BackendConfig",
    "decoder_forward",
    "init_dense_decoder_params",
    "dense_decoder_logical_axes",
]
