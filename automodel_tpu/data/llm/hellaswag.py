"""HellaSwag SFT dataset (reference datasets/llm/hellaswag.py behavior):
context -> prompt, gold ending -> answer; loss on the ending span only."""

from __future__ import annotations

import json
import os
from typing import Any

__all__ = ["HellaSwagDataset"]


class HellaSwagDataset:
    def __init__(
        self,
        path_or_dataset_id: str = "rowan/hellaswag",
        tokenizer=None,
        split: str = "train",
        limit_dataset_samples: int | None = None,
    ):
        if os.path.exists(path_or_dataset_id):
            rows = []
            with open(path_or_dataset_id) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
        else:
            import datasets as hf_datasets

            rows = list(hf_datasets.load_dataset(path_or_dataset_id, split=split))
        if limit_dataset_samples:
            rows = rows[:limit_dataset_samples]
        self.rows = rows
        self.tokenizer = tokenizer

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, i: int) -> dict[str, Any]:
        from automodel_tpu.data.tokenize import tokenize_sft_example

        row = self.rows[i]
        ending = row["endings"][int(row["label"])]
        return tokenize_sft_example(self.tokenizer, row["ctx"], ending)
