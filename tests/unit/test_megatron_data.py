"""Megatron pretraining data stack tests (reference tests for megatron/ + nanogpt).

Covers: .bin/.idx roundtrip, C++-vs-NumPy index builder parity, GPT sample
construction invariants, blending proportionality, split partitioning, nanogpt
shard streaming."""

import numpy as np
import pytest

from automodel_tpu.data.llm.megatron.blended import BlendedDataset, parse_blend
from automodel_tpu.data.llm.megatron.gpt_dataset import GPTDataset
from automodel_tpu.data.llm.megatron.helpers import (
    _sample_idx_numpy,
    build_blending_indices,
    build_exhaustive_blending_indices,
    build_sample_idx,
    native_available,
)
from automodel_tpu.data.llm.megatron.indexed_dataset import (
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
)
from automodel_tpu.data.llm.megatron.megatron_dataset import MegatronPretraining, parse_split
from automodel_tpu.data.llm.nanogpt_dataset import NanogptDataset, peek_num_tokens, write_shard


@pytest.fixture()
def corpus(tmp_path):
    """20 documents of varying lengths, tokens encode (doc_id, position)."""
    prefix = str(tmp_path / "corpus")
    builder = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    rng = np.random.default_rng(0)
    docs = []
    for d in range(20):
        n = int(rng.integers(5, 40))
        doc = (d * 1000 + np.arange(n)).astype(np.int32)
        docs.append(doc)
        builder.add_document(doc)
    builder.finalize()
    return prefix, docs


class TestIndexedDataset:
    def test_roundtrip(self, corpus):
        prefix, docs = corpus
        ds = MMapIndexedDataset(prefix)
        assert len(ds) == len(docs)
        for i in (0, 7, 19):
            np.testing.assert_array_equal(ds[i], docs[i])
        np.testing.assert_array_equal(ds.get(3, offset=2, length=4), docs[3][2:6])
        assert ds.num_tokens == sum(len(d) for d in docs)
        assert MMapIndexedDataset.exists(prefix)

    def test_bad_magic(self, tmp_path):
        p = tmp_path / "x.idx"
        p.write_bytes(b"NOTMAGIC!!")
        (tmp_path / "x.bin").write_bytes(b"")
        with pytest.raises(ValueError, match="bad magic"):
            MMapIndexedDataset(str(tmp_path / "x"))


class TestIndexHelpers:
    def test_native_builds(self):
        assert native_available(), "g++ should be present in this image"

    def test_sample_idx_native_matches_numpy(self):
        rng = np.random.default_rng(1)
        sizes = rng.integers(3, 50, size=30).astype(np.int32)
        doc_idx = rng.permutation(np.repeat(np.arange(30, dtype=np.int64), 3))
        got = build_sample_idx(sizes, doc_idx, seq_length=16, num_samples=40)
        want = _sample_idx_numpy(sizes, doc_idx, 16, 40)
        np.testing.assert_array_equal(got, want)

    def test_sample_idx_spans_cover_seq_length(self):
        sizes = np.asarray([10, 7, 25, 13], np.int32)
        doc_idx = np.asarray([2, 0, 3, 1, 2, 0], np.int64)
        seq = 8
        idx = build_sample_idx(sizes, doc_idx, seq, 5)
        # each consecutive pair spans exactly seq tokens (token-position arithmetic)
        cum = np.cumsum([0] + [int(sizes[d]) for d in doc_idx])
        for i in range(len(idx) - 1):
            t0 = cum[idx[i][0]] + idx[i][1]
            t1 = cum[idx[i + 1][0]] + idx[i + 1][1]
            assert t1 - t0 == seq

    def test_blending_tracks_weights(self):
        w = np.asarray([0.5, 0.3, 0.2])
        d_idx, s_idx = build_blending_indices(w, 1000)
        counts = np.bincount(d_idx, minlength=3)
        np.testing.assert_allclose(counts / 1000, w, atol=0.01)
        # sample indices are per-dataset sequential
        for d in range(3):
            np.testing.assert_array_equal(np.sort(s_idx[d_idx == d]), np.arange(counts[d]))

    def test_exhaustive_blending_exact(self):
        sizes = np.asarray([10, 5, 3], np.int64)
        d_idx, s_idx = build_exhaustive_blending_indices(sizes)
        assert len(d_idx) == 18
        np.testing.assert_array_equal(np.bincount(d_idx, minlength=3), sizes)

    def test_exhaustive_blending_skips_empty_components(self):
        # native and numpy paths must agree: empty datasets receive zero samples
        sizes = np.asarray([0, 5], np.int64)
        d_idx, _ = build_exhaustive_blending_indices(sizes)
        np.testing.assert_array_equal(d_idx, np.ones(5, np.int16))


class TestGPTDataset:
    def test_sample_shapes_and_determinism(self, corpus, tmp_path):
        prefix, _ = corpus
        ds1 = GPTDataset(prefix, seq_length=32, num_samples=50, seed=7)
        ds2 = GPTDataset(prefix, seq_length=32, num_samples=50, seed=7)
        assert len(ds1) >= 1
        for i in (0, len(ds1) - 1):
            s1, s2 = ds1[i], ds2[i]
            assert s1["input_ids"].shape == (33,)
            np.testing.assert_array_equal(s1["input_ids"], s2["input_ids"])
        ds3 = GPTDataset(prefix, seq_length=32, num_samples=50, seed=8)
        assert any(
            not np.array_equal(ds1[i]["input_ids"], ds3[i]["input_ids"]) for i in range(5)
        )

    def test_samples_are_contiguous_token_stream(self, corpus):
        """Tokens inside one sample follow document order: within a document the
        (doc*1000+pos) encoding increments by 1."""
        prefix, _ = corpus
        ds = GPTDataset(prefix, seq_length=16, num_samples=30, seed=3)
        s = ds[0]["input_ids"]
        diffs = np.diff(s)
        # either +1 (same doc) or a jump (document boundary)
        assert ((diffs == 1) | (np.abs(diffs) > 1)).all()
        assert (diffs == 1).sum() >= len(diffs) // 2  # mostly contiguous

    def test_index_cache(self, corpus, tmp_path):
        prefix, _ = corpus
        cache = str(tmp_path / "idxcache")
        ds1 = GPTDataset(prefix, seq_length=16, num_samples=20, seed=5, cache_dir=cache)
        ds2 = GPTDataset(prefix, seq_length=16, num_samples=20, seed=5, cache_dir=cache)
        np.testing.assert_array_equal(ds1[3]["input_ids"], ds2[3]["input_ids"])

    def test_cache_key_distinguishes_document_subsets(self, corpus, tmp_path):
        """Equal-length but different doc subsets must not share a cache entry
        (otherwise changed split strings silently serve stale documents)."""
        prefix, _ = corpus
        cache = str(tmp_path / "idxcache2")
        lo = GPTDataset(prefix, seq_length=8, num_samples=10, seed=5, cache_dir=cache,
                        documents=np.arange(0, 5, dtype=np.int64))
        hi = GPTDataset(prefix, seq_length=8, num_samples=10, seed=5, cache_dir=cache,
                        documents=np.arange(5, 10, dtype=np.int64))
        assert (hi[0]["input_ids"] >= 5000).all()  # docs 5+ encode tokens >= 5000
        assert (lo[0]["input_ids"] < 5000).all()

    def test_document_subset(self, corpus):
        prefix, _ = corpus
        docs = np.arange(0, 5, dtype=np.int64)
        ds = GPTDataset(prefix, seq_length=8, num_samples=10, documents=docs)
        for i in range(len(ds)):
            assert (ds[i]["input_ids"] < 5000).all()  # doc ids 0-4 encode < 5000


class TestBlendedAndSplits:
    def test_parse_blend(self):
        assert parse_blend(["/a", "/b"]) == ([1.0, 1.0], ["/a", "/b"])
        assert parse_blend([0.7, "/a", 0.3, "/b"]) == ([0.7, 0.3], ["/a", "/b"])

    def test_parse_split(self):
        assert parse_split("900,50,50") == [0.9, 0.05, 0.05]
        with pytest.raises(ValueError):
            parse_split("0,0,0")

    def test_blended_dataset(self, corpus, tmp_path):
        prefix, _ = corpus
        a = GPTDataset(prefix, seq_length=8, num_samples=20, seed=1)
        b = GPTDataset(prefix, seq_length=8, num_samples=20, seed=2)
        blend = BlendedDataset([a, b], weights=[0.75, 0.25], size=40)
        assert len(blend) == 40
        counts = np.bincount(blend.dataset_index, minlength=2)
        assert counts[0] > counts[1]
        assert blend[0]["input_ids"].shape == (9,)

    def test_megatron_pretraining_splits_disjoint(self, corpus, tmp_path):
        prefix, _ = corpus
        train = MegatronPretraining([prefix], seq_length=8, split="50,25,25",
                                    split_name="train", num_samples=20)
        val = MegatronPretraining([prefix], seq_length=8, split="50,25,25",
                                  split_name="validation", num_samples=10)
        train_docs = {int(t) // 1000 for i in range(len(train)) for t in train[i]["input_ids"]}
        val_docs = {int(t) // 1000 for i in range(len(val)) for t in val[i]["input_ids"]}
        assert train_docs.isdisjoint(val_docs)


class TestNanogpt:
    def test_shard_roundtrip_and_sampling(self, tmp_path):
        tokens = np.arange(1000, dtype=np.uint16)
        shard1 = str(tmp_path / "a_000.bin")
        shard2 = str(tmp_path / "a_001.bin")
        write_shard(shard1, tokens[:600])
        write_shard(shard2, tokens[600:])
        assert peek_num_tokens(shard1) == 600
        ds = NanogptDataset(str(tmp_path / "a_*.bin"), seq_len=64)
        assert len(ds) == (1000 - 1) // 64
        s0 = ds[0]["input_ids"]
        np.testing.assert_array_equal(s0, np.arange(65))
        # sample crossing the shard boundary reads both shards
        cross = ds[9]["input_ids"]  # tokens 576..640
        np.testing.assert_array_equal(cross, np.arange(9 * 64, 9 * 64 + 65))

    def test_bos_alignment(self, tmp_path):
        bos = 999
        toks = []
        for start in (0, 40, 100, 170):
            toks.append([bos])
            toks.append(list(range(1, 30)))
        flat = np.asarray([t for chunk in toks for t in chunk], np.uint16)
        shard = str(tmp_path / "b_000.bin")
        write_shard(shard, flat)
        ds = NanogptDataset(shard, seq_len=16, align_to_bos=True, bos_token=bos)
        s = ds[1]["input_ids"]
        assert s[0] == bos  # window snapped to a document start
