"""HostPrefetcher/DevicePrefetcher/InputPipeline: ordering, resume accounting,
error propagation, and shutdown — the contracts the train loop leans on."""

import queue
import threading
import time

import numpy as np
import pytest

from automodel_tpu.data.collate import stack_batches
from automodel_tpu.data.loader import DataLoader
from automodel_tpu.data.prefetch import (
    DevicePrefetcher,
    HostPrefetcher,
    InputPipeline,
    PrefetchConfig,
    StepBatch,
)
from automodel_tpu.training.step_scheduler import StepScheduler


def _dataset(n=32, width=4):
    return [{"x": np.full((width,), i, np.int32)} for i in range(n)]


def _collate(samples):
    return {"x": np.stack([s["x"] for s in samples])}


def _make(n=32, grad_acc=2, batch_size=2, num_epochs=1, max_steps=None, seed=3):
    dl = DataLoader(_dataset(n), batch_size=batch_size, collate_fn=_collate, seed=seed)
    sched = StepScheduler(
        grad_acc_steps=grad_acc, num_epochs=num_epochs, max_steps=max_steps,
        dataloader=dl, handle_sigterm=False,
    )
    return sched, dl


def _pipeline(sched, dl, enabled, put_fn=None, **cfg):
    return InputPipeline(
        scheduler=sched, dataloader=dl, stack_fn=stack_batches,
        put_fn=put_fn or (lambda s: s),
        config=PrefetchConfig(enabled=enabled, **cfg),
    )


def _drain(pipeline):
    out = []
    while True:
        item = pipeline.get()
        if item is None:
            return out
        out.append(item)


class TestDeterminism:
    @pytest.mark.parametrize("host_depth,device_depth", [(1, 1), (2, 2), (4, 3)])
    def test_same_batches_same_order_as_sync(self, host_depth, device_depth):
        ref = _drain(_pipeline(*_make(), enabled=False))
        pf = _pipeline(*_make(), enabled=True,
                       host_depth=host_depth, device_depth=device_depth)
        got = _drain(pf)
        pf.close()
        assert [b.step for b in got] == [b.step for b in ref]
        for g, r in zip(got, ref):
            np.testing.assert_array_equal(g.stack["x"], r.stack["x"])

    def test_multi_epoch_order_preserved(self):
        ref = _drain(_pipeline(*_make(num_epochs=3), enabled=False))
        pf = _pipeline(*_make(num_epochs=3), enabled=True, host_depth=3)
        got = _drain(pf)
        pf.close()
        assert len(got) == len(ref) and len(ref) > 0
        for g, r in zip(got, ref):
            assert (g.step, g.epoch) == (r.step, r.epoch)
            np.testing.assert_array_equal(g.stack["x"], r.stack["x"])

    def test_end_of_data_is_terminal(self):
        pf = _pipeline(*_make(max_steps=3), enabled=True)
        assert len(_drain(pf)) == 3
        assert pf.get() is None  # stays None, does not hang or raise
        pf.close()


class TestResumeAccounting:
    def test_client_states_track_consumed_not_produced(self):
        """With the worker running ahead, the live scheduler's counter exceeds
        the consumed step; the snapshot must match what was consumed."""
        sched, dl = _make(n=64, max_steps=10)
        pf = _pipeline(sched, dl, enabled=True, host_depth=4, device_depth=2)
        for want_step in (1, 2, 3):
            item = pf.get()
            assert item.step == want_step
            snap = pf.client_states()
            assert snap["step_scheduler"]["step"] == want_step
        # the producer meanwhile advanced past the consumer
        deadline = time.monotonic() + 5.0
        while sched.step <= 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sched.step > 3
        pf.close()

    def test_restoring_snapshot_replays_in_flight_batches(self):
        ref = _drain(_pipeline(*_make(n=64, max_steps=12), enabled=False))

        sched, dl = _make(n=64, max_steps=12)
        pf = _pipeline(sched, dl, enabled=True, host_depth=4, device_depth=2)
        consumed = [pf.get() for _ in range(5)]
        snap = pf.client_states()
        pf.close()  # in-flight items beyond step 5 are dropped here

        sched2, dl2 = _make(n=64, max_steps=12)
        sched2.load_state_dict(snap["step_scheduler"])
        dl2.load_state_dict(snap["dataloader"])
        resumed = _drain(_pipeline(sched2, dl2, enabled=True))

        replay = consumed + resumed
        assert [b.step for b in replay] == [b.step for b in ref]
        for g, r in zip(replay, ref):
            np.testing.assert_array_equal(g.stack["x"], r.stack["x"])

    def test_sync_mode_has_no_overrides(self):
        pipe = _pipeline(*_make(max_steps=4), enabled=False)
        pipe.get()
        assert pipe.client_states() == {}

    def test_client_states_before_first_get_is_construction_snapshot(self):
        """A save issued before the first consumed batch must not persist the
        live scheduler/dataloader — the worker starts advancing them the
        moment the pipeline is built."""
        sched, dl = _make(n=64, max_steps=10)
        base_sched = dict(sched.state_dict())
        base_dl = dict(dl.state_dict())
        pf = _pipeline(sched, dl, enabled=True, host_depth=4)
        # wait until the worker has provably advanced the live objects
        deadline = time.monotonic() + 5.0
        while sched.step == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sched.step > 0
        snap = pf.client_states()
        assert snap["step_scheduler"] == base_sched
        assert snap["dataloader"] == base_dl
        pf.close()


class TestErrorPropagation:
    def test_worker_exception_surfaces_at_same_position(self):
        class Boom(RuntimeError):
            pass

        def make_stack_fn():
            calls = {"n": 0}

            def stack_fn(batches):
                calls["n"] += 1
                if calls["n"] == 4:
                    raise Boom("stack 4")
                return stack_batches(batches)

            return stack_fn

        def run(enabled):
            sched, dl = _make(n=64, max_steps=10)
            pipe = InputPipeline(
                scheduler=sched, dataloader=dl, stack_fn=make_stack_fn(),
                put_fn=lambda s: s,
                config=PrefetchConfig(enabled=enabled, host_depth=3, device_depth=2),
            )
            got = []
            try:
                while True:
                    item = pipe.get()
                    if item is None:
                        return got, None
                    got.append(item.step)
            except Boom as e:
                return got, e
            finally:
                pipe.close()

        ref_steps, ref_err = run(enabled=False)
        pf_steps, pf_err = run(enabled=True)
        assert ref_err is not None and pf_err is not None
        assert pf_steps == ref_steps == [1, 2, 3]

    def test_put_fn_error_surfaces_at_same_position_as_sync(self):
        """A device_put failure for batch k+n is deferred until the buffered
        good batches k..k+n-1 are consumed — the sync path's raise position."""

        class Boom(RuntimeError):
            pass

        def make_put_fn():
            calls = {"n": 0}

            def put_fn(stack):
                calls["n"] += 1
                if calls["n"] == 4:
                    raise Boom("put 4")
                return stack

            return put_fn

        def run(enabled):
            sched, dl = _make(n=64, max_steps=10)
            pipe = InputPipeline(
                scheduler=sched, dataloader=dl, stack_fn=stack_batches,
                put_fn=make_put_fn(),
                config=PrefetchConfig(enabled=enabled, host_depth=4, device_depth=3),
            )
            got = []
            try:
                while True:
                    item = pipe.get()
                    if item is None:
                        return got, None
                    got.append(item.step)
            except Boom as e:
                return got, e
            finally:
                pipe.close()

        ref_steps, ref_err = run(enabled=False)
        pf_steps, pf_err = run(enabled=True)
        assert ref_err is not None and pf_err is not None
        assert pf_steps == ref_steps == [1, 2, 3]

    def test_error_is_terminal_and_rereadable(self):
        def bad_stack(batches):
            raise ValueError("always")

        sched, dl = _make(max_steps=4)
        host = HostPrefetcher(sched, dl, bad_stack, depth=2)
        with pytest.raises(ValueError):
            host.get()
        with pytest.raises(ValueError):  # sentinel re-queued, not lost
            host.get()
        host.close()


class TestShutdown:
    def test_close_unblocks_worker_stuck_on_full_queue(self):
        sched, dl = _make(n=64)
        host = HostPrefetcher(sched, dl, stack_batches, depth=1)
        deadline = time.monotonic() + 5.0
        while host.ready < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert host.ready == 1  # queue full, worker blocked in _put
        t0 = time.monotonic()
        host.close()
        assert time.monotonic() - t0 < 5.0
        assert not host._thread.is_alive()

    def test_close_is_idempotent(self):
        pipe = _pipeline(*_make(), enabled=True)
        pipe.get()
        pipe.close()
        pipe.close()
        assert not pipe.prefetching

    def test_close_without_any_get(self):
        pipe = _pipeline(*_make(), enabled=True)
        pipe.close()

    def test_final_items_survive_timeout_vs_worker_exit_race(self, monkeypatch):
        """The worker can enqueue its last StepBatch + _END and exit inside the
        window between get()'s queue timeout and the liveness check; get() must
        drain the (now race-free) queue before concluding end-of-data."""
        sched, dl = _make(max_steps=2)
        host = HostPrefetcher(sched, dl, stack_batches, depth=8)
        host._thread.join(timeout=5.0)  # everything produced, worker gone
        assert not host._thread.is_alive()
        # simulate the unlucky timeout: one blocking get() raises Empty even
        # though the dead worker's items already sit in the queue
        real_get = host._q.get
        spurious = {"left": 1}

        def flaky_get(*args, **kwargs):
            if kwargs.get("timeout") is not None and spurious["left"]:
                spurious["left"] -= 1
                raise queue.Empty
            return real_get(*args, **kwargs)

        monkeypatch.setattr(host._q, "get", flaky_get)
        got = []
        while True:
            item = host.get()
            if item is None:
                break
            got.append(item.step)
        assert got == [1, 2]  # nothing dropped
        host.close()

    def test_sigterm_stops_worker_without_collectives(self):
        """The worker iterates with collective_sigterm=False: setting the local
        flag stops production at the next step boundary, from any thread."""
        sched, dl = _make(n=256, num_epochs=8)
        host = HostPrefetcher(sched, dl, stack_batches, depth=2)
        assert isinstance(host.get(), StepBatch)
        sched._sigterm.set()
        # drain: the worker must terminate the stream promptly (no deadlock)
        deadline = time.monotonic() + 10.0
        while host.get() is not None:
            assert time.monotonic() < deadline, "worker ignored local SIGTERM"
        assert not host._thread.is_alive() or host.get() is None
        host.close()


class TestSigtermTruncation:
    """End-of-stream caused by the LOCAL flag is not end-of-data: the train
    loop needs to distinguish the two, or a signaled host exits the per-step
    collective rhythm while the rest of the pod keeps stepping."""

    def _truncate(self, sched, dl):
        pf = _pipeline(sched, dl, enabled=True)
        consumed = [pf.get().step]
        sched._sigterm.set()
        while True:
            item = pf.get()
            if item is None:
                return pf, consumed
            consumed.append(item.step)

    def test_truncated_with_data_remaining(self):
        sched, dl = _make(n=256, num_epochs=8)
        pf, _ = self._truncate(sched, dl)
        assert pf.truncated_by_local_sigterm()
        pf.close()

    def test_not_truncated_at_genuine_end_of_data(self):
        sched, dl = _make(max_steps=3)
        pf = _pipeline(sched, dl, enabled=True)
        assert len(_drain(pf)) == 3
        sched._sigterm.set()  # flag up, but the data really did end
        assert not pf.truncated_by_local_sigterm()
        pf.close()

    def test_sync_mode_never_truncates(self):
        sched, dl = _make(max_steps=2)
        pipe = _pipeline(sched, dl, enabled=False)
        sched._sigterm.set()
        assert not pipe.truncated_by_local_sigterm()

    def test_rebuild_after_truncation_resumes_at_next_step(self):
        """The train loop's recovery path: rebuild from the live scheduler
        position and keep the step rhythm — the fresh worker always yields at
        least one item (its flag check is post-yield), continuing exactly
        where truncation hit."""
        sched, dl = _make(n=256, num_epochs=8)
        pf, consumed = self._truncate(sched, dl)
        pf.close()
        pf2 = _pipeline(sched, dl, enabled=True)  # flag still set
        nxt = pf2.get()
        assert nxt is not None and nxt.step == consumed[-1] + 1
        pf2.close()


class TestDevicePrefetcher:
    def test_put_fn_applied_and_depth_respected(self):
        sched, dl = _make(n=64, max_steps=8)
        host = HostPrefetcher(sched, dl, stack_batches, depth=8)
        tagged = []

        def put_fn(stack):
            tagged.append(stack["x"].sum())
            return {"x": stack["x"] + 100}

        dev = DevicePrefetcher(host, put_fn, depth=2)
        first = dev.get()
        assert (first.stack["x"] >= 100).all()
        # transfers are issued ahead of consumption, bounded by depth
        assert 1 <= len(tagged) <= 3
        assert dev.ready <= 2
        host.close()

    def test_ready_depth_reports_buffered_items(self):
        pipe = _pipeline(*_make(n=64, max_steps=8), enabled=True,
                         host_depth=3, device_depth=2)
        assert pipe.ready_depth() >= 0
        pipe.get()
        deadline = time.monotonic() + 5.0
        while pipe.ready_depth() < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pipe.ready_depth() >= 1
        pipe.close()


class TestConfig:
    def test_from_config_none_disabled(self):
        cfg = PrefetchConfig.from_config(None)
        assert not cfg.enabled

    def test_from_config_dict(self):
        cfg = PrefetchConfig.from_config(
            {"enabled": True, "host_depth": 5, "device_depth": 3}
        )
        assert cfg.enabled and cfg.host_depth == 5 and cfg.device_depth == 3

    def test_invalid_depths_raise(self):
        with pytest.raises(ValueError):
            PrefetchConfig(host_depth=0)
        with pytest.raises(ValueError):
            PrefetchConfig(device_depth=-1)
