"""Ring attention over the ``cp`` mesh axis — long-context context parallelism.

TPU-native replacement for the reference's two CP mechanisms (SURVEY.md §5): torch
DTensor experimental ``context_parallel`` ring SDPA (distributed/cp_utils.py:68) and
TransformerEngine p2p ring attention (moe/parallelizer.py:267-285). Here: q/k/v arrive
sequence-sharded over ``cp``; k/v (+ their positions/segment ids) rotate around the
ring via ``lax.ppermute`` while each shard accumulates online-softmax partials in
fp32. ppermute rides ICI neighbor links, and XLA overlaps the permute with the
current chunk's attention math.

Causality is enforced by *global* positions (each shard's token positions travel with
it), so any seq-dim layout works — including the load-balanced interleave the
reference gets from THD round-robin sharding (cp_utils.py:296-321).

Two per-chunk implementations:

- ``flash`` (default): Pallas chunk kernels (ops/pallas/ring_chunk.py) carrying the
  online-softmax state (acc, m, l) across ring steps in VMEM — no per-chunk
  (Sq_local x Skv_local) score matrix ever reaches HBM, which is the whole point of
  CP at long context. The ring is a ``lax.fori_loop`` (O(1) HLO at any cp), wrapped
  in a custom VJP whose backward runs a second ring: dk/dv accumulators travel WITH
  their kv chunk and arrive home after cp rotations.
- ``dense``: the plain-XLA partial-attention path (materializes per-chunk scores;
  differentiable by plain AD through an unrolled ring). Kept as the fallback for
  shapes the kernels can't tile and as the parity oracle in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from automodel_tpu.ops.pallas.flash_attention import (
    LANES,
    NEG_INF,
    _kv_sublanes,
    _q_lanes,
)

__all__ = ["ring_attention_local", "make_ring_attention"]


def _partial_attention(q, k, v, allowed, scale):
    """Unnormalized blockwise attention; returns (acc, m, l) in fp32.

    q/k (B, S, N|K, D); v (B, Sk, K, Dv) — Dv may differ from D (MLA's v_head_dim,
    moe/parallelizer.py:267-285 runs ring CP through TE for MLA the same way);
    allowed (B, Sq, Sk) bool or None. acc (B, K, G, Sq, Dv), m/l (B, K, G, Sq).
    """
    b, sq, n, d = q.shape
    kh = k.shape[2]
    g = n // kh
    qf = q.astype(jnp.float32).reshape(b, sq, kh, g, d)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qf, k.astype(jnp.float32)) * scale
    if allowed is not None:
        logits = jnp.where(allowed[:, None, None], logits, NEG_INF)
    m = logits.max(-1)  # (b, kh, g, sq)
    p = jnp.exp(logits - m[..., None])
    if allowed is not None:
        # fully-masked rows would otherwise contribute exp(0)=1 per masked entry
        p = jnp.where(allowed[:, None, None], p, 0.0)
    l = p.sum(-1)
    acc = jnp.einsum("bkgqs,bskd->bkgqd", p, v.astype(jnp.float32))
    return acc, m, l


def _rotate(tree, axis, perm):
    return jax.tree.map(
        lambda x: jax.lax.ppermute(x, axis, perm) if x is not None else None,
        tree, is_leaf=lambda x: x is None,
    )


def _gqa_sum(g, groups):
    """(BN, S, d) per-q-head grads -> (BK, S, d) kv-row grads."""
    if groups == 1:
        return g
    return g.reshape(-1, groups, *g.shape[1:]).sum(1)


# cfg: (axis, causal, window, scale, block_q, block_k, groups, n_heads,
#       interpret, kv_chunk) — hashable, so it rides nondiff_argnums.
@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def _ring_flash(q, k, v, pq, pkv, sq, skv, cfg):
    out, _ = _ring_flash_fwd(q, k, v, pq, pkv, sq, skv, cfg)
    return out


def _ring_flash_fwd(q, k, v, pq, pkv, sq, skv, cfg):
    from automodel_tpu.ops.pallas.ring_chunk import chunk_attention_fwd

    axis, causal, window, scale, bq, bk, groups, nh, interp, _ = cfg
    cp = jax.lax.axis_size(axis)
    bn, sqlen, _ = q.shape
    dv = v.shape[-1]
    perm = [(j, (j + 1) % cp) for j in range(cp)]

    # pvary: the carry must be marked varying-over-cp like the pallas outputs
    # that replace it each iteration, or shard_map's vma check rejects the loop
    acc = jax.lax.pcast(jnp.zeros((bn, sqlen, dv), jnp.float32), axis, to='varying')
    m = jax.lax.pcast(jnp.full((bn, sqlen, LANES), NEG_INF, jnp.float32), axis, to='varying')
    l = jax.lax.pcast(jnp.zeros((bn, sqlen, LANES), jnp.float32), axis, to='varying')

    def body(_, carry):
        kv_bundle, acc, m, l = carry
        k_i, v_i, pkv_i, skv_i = kv_bundle
        acc, m, l = chunk_attention_fwd(
            q, k_i, v_i, pq, pkv_i, sq, skv_i, acc, m, l,
            scale=scale, causal=causal, window=window, groups=groups,
            n_heads=nh, block_q=bq, block_k=bk, interpret=interp,
            vma=frozenset({axis}),
        )
        # rotate every step: after cp rotations the bundle is home again, and
        # an unconditional rotate keeps the loop body collective-uniform
        return _rotate(kv_bundle, axis, perm), acc, m, l

    _, acc, m, l = jax.lax.fori_loop(0, cp, body, ((k, v, pkv, skv), acc, m, l))

    l0 = l[:, :, :1]
    out = (acc / jnp.where(l0 == 0.0, 1.0, l0)).astype(q.dtype)
    # save lse COMPACT (bn, sq, 1): every lane is identical by construction,
    # and the residual lives from fwd to bwd — a LANES-broadcast copy here
    # would 128x the per-layer activation memory at exactly the long-context
    # sizes CP exists for; the bwd re-broadcasts transiently
    lse = jnp.where(l0 == 0.0, NEG_INF,
                    m[:, :, :1] + jnp.log(jnp.where(l0 == 0.0, 1.0, l0)))
    return out, (q, k, v, pq, pkv, sq, skv, out, lse)


def _ring_flash_bwd(cfg, res, do):
    from automodel_tpu.ops.pallas.ring_chunk import chunk_attention_bwd

    axis, causal, window, scale, bq, bk, groups, nh, interp, kv_chunk = cfg
    q, k, v, pq, pkv, sq, skv, out, lse = res
    cp = jax.lax.axis_size(axis)
    perm = [(j, (j + 1) % cp) for j in range(cp)]
    skv_len = k.shape[1]
    lse = jnp.broadcast_to(lse, (*lse.shape[:2], LANES))  # compact -> lanes
    delta = _q_lanes((out.astype(jnp.float32) * do.astype(jnp.float32)).sum(-1))

    # bound the bwd kernel's full-(Skv, d) dk/dv scratch by sub-chunking kv;
    # each sub-chunk is an independent kernel call (dq partials sum, dk/dv
    # slices concatenate), so VMEM stays flat in sequence length. The chunk
    # must hold whole kernel blocks AND tile the local kv length — otherwise
    # fall back to one full-length chunk.
    kvc = max(bk, (kv_chunk // bk) * bk) if kv_chunk else skv_len
    if skv_len % kvc:
        kvc = skv_len

    def body(_, carry):
        bundle, dq = carry
        k_i, v_i, pkv_i, skv_i, dk_i, dv_i = bundle
        for c in range(skv_len // kvc):
            rows = slice(c * kvc, (c + 1) * kvc)
            dq_p, dk_c, dv_c = chunk_attention_bwd(
                q, k_i[:, rows], v_i[:, rows], pq, pkv_i[:, :, rows], sq,
                None if skv_i is None else skv_i[:, :, rows], do, lse, delta,
                scale=scale, causal=causal, window=window, groups=groups,
                n_heads=nh, block_q=bq, block_k=bk, interpret=interp,
                vma=frozenset({axis}),
            )
            dq = dq + dq_p
            dk_i = dk_i.at[:, rows].add(_gqa_sum(dk_c, groups))
            dv_i = dv_i.at[:, rows].add(_gqa_sum(dv_c, groups))
        # dk/dv travel WITH their kv chunk; after cp rotations they are home
        return _rotate((k_i, v_i, pkv_i, skv_i, dk_i, dv_i), axis, perm), dq

    dq0 = jax.lax.pcast(jnp.zeros(q.shape, jnp.float32), axis, to='varying')
    dk0 = jax.lax.pcast(jnp.zeros(k.shape, jnp.float32), axis, to='varying')
    dv0 = jax.lax.pcast(jnp.zeros(v.shape, jnp.float32), axis, to='varying')
    bundle, dq = jax.lax.fori_loop(
        0, cp, body, ((k, v, pkv, skv, dk0, dv0), dq0)
    )
    _, _, _, _, dk, dv = bundle
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None, None, None, None)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def _pick_block(seq, target):
    """Largest power-of-two block <= target dividing seq (>= 8); 0 if none."""
    b = 1 << (max(min(target, seq), 8).bit_length() - 1)
    while b > 8 and seq % b:
        b //= 2
    return b if seq % b == 0 else 0


def ring_attention_local(
    q: jnp.ndarray,  # (B, Sq_local, N, D)
    k: jnp.ndarray,  # (B, Skv_local, K, D)
    v: jnp.ndarray,
    positions_q: jnp.ndarray,  # (B, Sq_local) global positions
    positions_kv: jnp.ndarray,  # (B, Skv_local)
    segment_ids_q: jnp.ndarray | None = None,  # (B, Sq_local)
    segment_ids_kv: jnp.ndarray | None = None,
    *,
    axis: str = "cp",
    causal: bool = True,
    sliding_window: int | None = None,
    softmax_scale: float | None = None,
    impl: str | None = None,  # "flash" | "dense" | None = auto
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,  # None = auto (True off-TPU)
    kv_chunk: int = 4096,
) -> jnp.ndarray:
    """The per-shard body — call inside shard_map manual over ``axis``."""
    cp = jax.lax.axis_size(axis)
    b, sq, n, d = q.shape
    dv = v.shape[-1]
    kh = k.shape[2]
    g = n // kh
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    if impl not in (None, "flash", "dense"):
        raise ValueError(f"unknown ring impl {impl!r} (flash | dense | None=auto)")

    if impl is None or impl == "flash":
        bq = _pick_block(sq, block_q or 1024)
        bk = _pick_block(k.shape[1], block_k or 1024)
        flash_ok = bq > 0 and bk > 0
        if impl == "flash" and not flash_ok:
            raise ValueError(
                f"ring flash needs power-of-two-tileable local seqs, got "
                f"sq={sq}, skv={k.shape[1]}"
            )
        if flash_ok:
            if interpret is None:
                interpret = jax.default_backend() != "tpu"
            # rows: (B, S, H, D) -> (B*H, S, D); kv heads stay un-repeated
            qf = q.transpose(0, 2, 1, 3).reshape(b * n, sq, d)
            kf = k.transpose(0, 2, 1, 3).reshape(b * kh, k.shape[1], d)
            vf = v.transpose(0, 2, 1, 3).reshape(b * kh, v.shape[1], dv)
            pq = _q_lanes(positions_q.astype(jnp.int32))
            pkv = _kv_sublanes(positions_kv.astype(jnp.int32))
            sq_ids = skv_ids = None
            if segment_ids_q is not None or segment_ids_kv is not None:
                a = segment_ids_q if segment_ids_q is not None else segment_ids_kv
                c = segment_ids_kv if segment_ids_kv is not None else segment_ids_q
                sq_ids = _q_lanes(a.astype(jnp.int32))
                skv_ids = _kv_sublanes(c.astype(jnp.int32))
            cfg = (axis, causal, sliding_window, scale, bq, bk, g, n,
                   interpret, kv_chunk)
            o = _ring_flash(qf, kf, vf, pq, pkv, sq_ids, skv_ids, cfg)
            return o.reshape(b, n, sq, dv).transpose(0, 2, 1, 3)

    # dense fallback: plain-XLA partials, unrolled ring, plain AD
    perm = [(j, (j + 1) % cp) for j in range(cp)]
    acc = jnp.zeros((b, kh, g, sq, dv), jnp.float32)
    m = jnp.full((b, kh, g, sq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, kh, g, sq), jnp.float32)
    kv = (k, v, positions_kv, segment_ids_kv)

    for step in range(cp):
        k_i, v_i, pos_kv, seg_kv = kv
        allowed = None

        def _and(a, b):
            return b if a is None else jnp.logical_and(a, b)

        if causal:
            allowed = _and(allowed, positions_q[:, :, None] >= pos_kv[:, None, :])
        if sliding_window is not None:
            allowed = _and(
                allowed, positions_q[:, :, None] - pos_kv[:, None, :] < sliding_window
            )
        if segment_ids_q is not None:
            allowed = _and(
                allowed, segment_ids_q[:, :, None] == seg_kv[:, None, :]
            )

        acc_i, m_i, l_i = _partial_attention(q, k_i, v_i, allowed, scale)
        m_new = jnp.maximum(m, m_i)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_i - m_new)
        acc = acc * alpha[..., None] + acc_i * beta[..., None]
        l = l * alpha + l_i * beta
        m = m_new

        if step < cp - 1:
            kv = _rotate(kv, axis, perm)

    out = acc / jnp.where(l == 0.0, 1.0, l)[..., None]  # (b, kh, g, sq, dv)
    return out.transpose(0, 3, 1, 2, 4).reshape(b, sq, n, dv).astype(q.dtype)


def _flash_interpret_mode(global_seq: int, cp: int, impl: str | None,
                          block_q: int | None, block_k: int | None) -> bool:
    """True iff :func:`ring_attention_local` will run interpret-mode pallas.

    Mirrors the local body's decision: the flash path is taken when it isn't
    disabled (``impl="dense"``) and the per-shard seq lengths tile, and it
    interprets only off-TPU. Only that combination needs ``check_vma=False``
    on the enclosing shard_map (see make_ring_attention).
    """
    if impl == "dense" or jax.default_backend() == "tpu":
        return False
    sq = global_seq // cp
    return _pick_block(sq, block_q or 1024) > 0 and _pick_block(sq, block_k or 1024) > 0


def make_ring_attention(
    mesh: Mesh,
    *,
    cp_axis: str = "cp",
    causal: bool = True,
    sliding_window: int | None = None,
    softmax_scale: float | None = None,
    impl: str | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
):
    """Wrap :func:`ring_attention_local` in a partial-manual shard_map over ``cp``.

    Inputs are global arrays with the seq dim sharded over ``cp`` (other axes stay
    GSPMD-managed). Returns ``fn(q, k, v, positions, segment_ids=None) -> out``.
    """

    # jit: eager shard_map dispatch rejects partial-manual + check_vma=False;
    # the traced path (the only one models ever take) is fine
    @jax.jit
    def fn(q, k, v, positions, segment_ids=None):
        seq_spec = P(None, cp_axis)

        def body(q, k, v, positions, segment_ids):
            return ring_attention_local(
                q, k, v, positions, positions,
                segment_ids, segment_ids,
                axis=cp_axis, causal=causal,
                sliding_window=sliding_window, softmax_scale=softmax_scale,
                impl=impl, block_q=block_q, block_k=block_k,
            )

        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(
                P(None, cp_axis, None, None),
                P(None, cp_axis, None, None),
                P(None, cp_axis, None, None),
                seq_spec,
                None if segment_ids is None else seq_spec,
            ),
            out_specs=P(None, cp_axis, None, None),
            axis_names={cp_axis},
            # interpret-mode pallas lowering (the flash path off-TPU)
            # internally mixes varying and unvarying operands
            # (dynamic_slice), which the vma checker rejects; JAX's own
            # error message prescribes check_vma=False there. Real-TPU runs
            # (and the dense fallback anywhere) keep the varying-mesh-axes
            # consistency check — it's exactly the multi-chip configurations
            # that benefit from it
            check_vma=not _flash_interpret_mode(
                q.shape[1], mesh.shape[cp_axis], impl, block_q, block_k),
        )(q, k, v, positions, segment_ids)

    return fn
