"""Default run-output directory resolution.

Recipes write ``training.jsonl`` / ``benchmark.json`` / checkpoints under
``output_dir``.  When the YAML leaves it unset we put artifacts under
``runs/<recipe>-<timestamp>/`` instead of littering the CWD (reference keeps
run artifacts under an explicit log dir per recipe, e.g.
nemo_automodel/recipes/llm/train_ft.py log_dir handling).
"""

from __future__ import annotations

import os
import time


def default_output_dir(recipe: str) -> str:
    """Return ``runs/<recipe>-<YYYYmmdd-HHMMSS>`` (created), for unset output_dir."""
    stamp = time.strftime("%Y%m%d-%H%M%S")
    path = os.path.join("runs", f"{recipe}-{stamp}")
    os.makedirs(path, exist_ok=True)
    return path
