"""Streaming / iterable dataset variants
(reference datasets/llm/column_mapped_text_instruction_iterable_dataset.py +
mock_iterable_dataset.py behavior).

For corpora too large to index up front: rows stream from JSONL files or HF
streaming datasets, shard per process, and tokenize on the fly. The TPU
dataloader contract stays the same (dict SFT examples) — only __len__ is
unavailable, so drive training by ``step_scheduler.max_steps``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterator, Mapping

import numpy as np

__all__ = ["ColumnMappedTextInstructionIterableDataset", "MockIterableDataset"]


def _retrying_rows(ds, source: str) -> Iterator[dict]:
    """Pull rows off a (possibly HTTP-backed) stream, retrying transient
    failures per row so a mid-epoch network blip doesn't kill the run."""
    from automodel_tpu.utils.retry import with_retry

    it = iter(ds)
    sentinel = object()
    while True:
        row = with_retry(next, it, sentinel, description=f"stream row from {source!r}")
        if row is sentinel:
            return
        yield row


class ColumnMappedTextInstructionIterableDataset:
    """Streaming version of ColumnMappedTextInstructionDataset.

    ``shard(num_shards, index)`` and ``shuffle(buffer_size, seed)`` mirror the
    reference's surface; sharding is strided over the stream so every process
    sees a disjoint subset without indexing the corpus."""

    def __init__(
        self,
        path_or_dataset_id: str,
        column_mapping: Mapping[str, str],
        tokenizer=None,
        split: str | None = None,
        answer_only_loss_mask: bool = True,
    ):
        if "answer" not in column_mapping:
            raise ValueError("column_mapping must include an 'answer' role")
        self.source = path_or_dataset_id
        self.split = split
        self.mapping = dict(column_mapping)
        self.tokenizer = tokenizer
        self.answer_only = answer_only_loss_mask
        self._num_shards, self._index = 1, 0
        self._buffer_size, self._seed = 0, 0
        self._epoch = 0

    # reference surface ----------------------------------------------------
    def shard(self, num_shards: int, index: int) -> "ColumnMappedTextInstructionIterableDataset":
        self._num_shards, self._index = int(num_shards), int(index)
        return self

    def shuffle(self, buffer_size: int = 1000, seed: int | None = None
                ) -> "ColumnMappedTextInstructionIterableDataset":
        self._buffer_size = int(buffer_size)
        self._seed = int(seed or 0)
        return self

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)

    # stream ---------------------------------------------------------------
    def _raw_rows(self) -> Iterator[dict]:
        if os.path.exists(self.source):
            with open(self.source) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield json.loads(line)
            return
        import datasets as hf_datasets

        from automodel_tpu.utils.retry import with_retry

        # opening the stream touches the hub; transient failures retry with
        # backoff (utils/retry.py) instead of killing a long run at step 0
        ds = with_retry(
            hf_datasets.load_dataset, self.source, split=self.split or "train",
            streaming=True, description=f"load_dataset({self.source!r})",
        )
        yield from _retrying_rows(ds, self.source)

    def _format(self, row: Mapping[str, Any]) -> dict:
        from automodel_tpu.data.llm.column_mapped import format_and_tokenize

        return format_and_tokenize(row, self.mapping, self.tokenizer, self.answer_only)

    def __iter__(self) -> Iterator[dict]:
        rows = (
            r for i, r in enumerate(self._raw_rows())
            if i % self._num_shards == self._index
        )
        if not self._buffer_size:
            for r in rows:
                yield self._format(r)
            return
        # reservoir-style buffer shuffle (the reference delegates to HF's
        # buffer shuffle; same semantics: random within a sliding window)
        rng = np.random.default_rng(self._seed + self._epoch)
        buf: list[dict] = []
        for r in rows:
            if len(buf) < self._buffer_size:
                buf.append(r)
                continue
            j = int(rng.integers(0, self._buffer_size))
            yield self._format(buf[j])
            buf[j] = r
        rng.shuffle(buf)
        for r in buf:
            yield self._format(r)


class MockIterableDataset:
    """Unbounded synthetic SFT stream (reference mock_iterable_dataset.py):
    exercises the iterable path without a corpus."""

    def __init__(self, vocab_size: int = 128, seq_len: int = 32, seed: int = 0,
                 num_samples: int | None = None):
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.seed = seed
        self.num_samples = num_samples

    def __iter__(self) -> Iterator[dict]:
        rng = np.random.default_rng(self.seed)
        i = 0
        while self.num_samples is None or i < self.num_samples:
            ids = rng.integers(0, self.vocab_size, self.seq_len).astype(np.int32)
            yield {"input_ids": ids.tolist(), "prompt_len": self.seq_len // 2}
            i += 1
