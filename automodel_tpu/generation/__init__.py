"""KV-cache generation for every causal decoder stack.

The reference gets generation from the wrapped HF modules' ``.generate()``
(its factory returns torch models — see examples/vlm_generate/vlm_generate.py:1);
here decode is TPU-native: a static-shape KV cache pytree, one ``lax.scan`` over
decode steps inside a single jit (no per-token host round-trips — a host-driven
loop pays the device-sync latency every token), and position/validity-masked
attention so right-padded prompts of uneven length batch together.

Cache layout: ``k``/``v`` are (L, B, S_max, KH, D) stacked per layer — the same
stacked-stream convention as the layer params, so the layer scan consumes the
cache as scan-xs and emits the updated slices as scan-ys. ``positions`` /
``valid`` / ``write_idx`` are shared across layers and advanced by the loop
here, not by the model.

MLA families (DeepSeek-V3/V2, Kimi-K2, GLM4-MoE-Lite) decode through an
expanded-head cache (see :func:`init_kv_cache`). Hybrids (Qwen3-Next DeltaNet,
Nemotron Mamba2) build their own cache via ``model.init_decode_cache`` —
conv taps + recurrent state instead of per-position KV. DeepSeek-V3.2's sparse
indexer decodes through the same hook: each token's post-Hadamard indexer key
is cached per layer and the top-k bias is recomputed incrementally against the
cache (deepseek_v32.make_indexer_decode_fn). Cacheless external models raise
with a pointer at HF export.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["init_kv_cache", "generate", "sample_token"]


def init_kv_cache(cfg, batch_size: int, max_len: int, dtype=jnp.bfloat16) -> dict[str, Any]:
    """Zeroed cache for ``cfg.num_hidden_layers`` layers.

    GQA stacks: k/v are (L, B, S, kv_heads, head_dim). MLA stacks (marked by
    ``kv_lora_rank``): the EXPANDED per-head k/v — k head-dim is nope+rope while
    v head-dim is ``v_head_dim``, and every head caches (no GQA grouping).
    ``valid`` doubles as kv segment ids (0 = empty slot, masked); ``positions``
    feed the position-causal mask, so cache slot order never has to match
    position order.
    """
    L = cfg.num_hidden_layers
    if getattr(cfg, "kv_lora_rank", None) is not None:  # MLA
        kh = cfg.num_attention_heads
        dk = cfg.qk_nope_head_dim + cfg.qk_rope_head_dim
        dv = cfg.v_head_dim
    else:
        kh = cfg.num_key_value_heads
        dk = dv = cfg.head_dim
    return {
        "k": jnp.zeros((L, batch_size, max_len, kh, dk), dtype),
        "v": jnp.zeros((L, batch_size, max_len, kh, dv), dtype),
        "positions": jnp.zeros((batch_size, max_len), jnp.int32),
        "valid": jnp.zeros((batch_size, max_len), jnp.int32),
        "write_idx": jnp.zeros((batch_size,), jnp.int32),
    }


def sample_token(logits: jnp.ndarray, rng: jax.Array, *, temperature: float = 1.0,
                 top_k: int | None = None, top_p: float | None = None) -> jnp.ndarray:
    """One token per row from (B, V) logits. temperature==0 -> greedy."""
    if temperature == 0.0:
        return logits.argmax(-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass > top_p (the first token
        # always survives: cum - probs < top_p holds at index 0)
        keep_sorted = (cum - probs) < top_p
        cutoff = jnp.where(keep_sorted, sorted_logits, jnp.inf).min(-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits).astype(jnp.int32)


def generate(
    model,
    params,
    input_ids,  # (B, S_prompt) int32, right-padded
    *,
    attention_mask=None,  # (B, S_prompt) 1 = real token; default all-real
    max_new_tokens: int = 32,
    temperature: float = 0.0,  # 0 = greedy
    top_k: int | None = None,
    top_p: float | None = None,
    eos_token_id: int | None = None,
    pad_token_id: int = 0,
    seed: int = 0,
    inputs_embeds=None,  # (B, S_prompt, D) VLM path: pre-merged media embeddings
    cache_dtype=None,
    decode_config=None,  # cache-shape config override (VLM wrappers pass their text config)
):
    """Prefill + scan-decode; returns ``{"sequences", "tokens", "lengths"}``.

    ``sequences`` is (B, S_prompt + max_new_tokens) with the prompt's padding
    compacted away is NOT attempted — generated tokens start at each row's
    ``prompt_len`` slot in the cache but are returned densely in ``tokens``
    (B, max_new_tokens), ``pad_token_id``-filled after eos. The whole decode
    runs inside one jit (cache donated through the scan carry).
    """
    import inspect

    cfg = decode_config if decode_config is not None else model.config
    is_mla = getattr(cfg, "kv_lora_rank", None) is not None
    call_params = inspect.signature(model.__call__).parameters
    # a model either consumes the generic GQA/MLA cache or builds its own
    # (hybrids: conv taps + recurrent state via init_decode_cache); the
    # capability marker is whether the forward accepts a cache at all
    own_cache = hasattr(model, "init_decode_cache")
    if "cache" not in call_params or (
        not own_cache and not is_mla and not hasattr(cfg, "num_key_value_heads")
    ):
        raise NotImplementedError(
            "KV-cache decode covers the GQA, MLA, and hybrid (init_decode_cache) "
            "stacks; this model has no cache path yet — export to HF for "
            "generation instead"
        )
    input_ids = jnp.asarray(input_ids, jnp.int32)
    b, s_prompt = input_ids.shape
    mask = (jnp.ones_like(input_ids) if attention_mask is None
            else jnp.asarray(attention_mask, jnp.int32))
    if cache_dtype is None:
        cache_dtype = model.backend.jnp_dtype
    max_len = s_prompt + max_new_tokens
    prompt_lens = mask.sum(-1).astype(jnp.int32)

    accepts_training = "training" in call_params
    accepts_embeds = "inputs_embeds" in call_params

    def _model_call(p, ids, positions, segment_ids, cache, embeds=None):
        kw = dict(positions=positions, segment_ids=segment_ids, cache=cache)
        if embeds is not None:
            if not accepts_embeds:
                raise TypeError(f"{type(model).__name__} does not accept inputs_embeds")
            kw["inputs_embeds"] = embeds
        if accepts_training:  # MoE stacks: eval-mode gating (no exploration noise)
            kw["training"] = False
        return model(p, ids, **kw)

    def _run(params, input_ids, mask, prompt_lens, inputs_embeds, rng):
        rows = jnp.arange(b)
        cache = (model.init_decode_cache(b, max_len, cache_dtype) if own_cache
                 else init_kv_cache(cfg, b, max_len, cache_dtype))
        prefill_pos = jnp.broadcast_to(jnp.arange(s_prompt, dtype=jnp.int32), (b, s_prompt))
        cache["positions"] = cache["positions"].at[:, :s_prompt].set(prefill_pos)
        cache["valid"] = cache["valid"].at[:, :s_prompt].set(mask)
        # cache-mode forwards return next-token logits only, (B, 1, V)
        logits, cache = _model_call(params, input_ids, prefill_pos, mask, cache,
                                    inputs_embeds)
        last_logits = logits[:, 0]

        def step(carry, rng_t):
            cache, last_logits, cur_idx, cur_pos, done = carry
            tok = sample_token(last_logits, rng_t, temperature=temperature,
                               top_k=top_k, top_p=top_p)
            if eos_token_id is not None:
                tok = jnp.where(done, pad_token_id, tok)
                done = done | (tok == eos_token_id)
            else:
                done = jnp.zeros_like(done)
            cache = dict(
                cache,
                positions=cache["positions"].at[rows, cur_idx].set(cur_pos),
                valid=cache["valid"].at[rows, cur_idx].set(1),
                write_idx=cur_idx,
            )
            logits, cache = _model_call(
                params, tok[:, None], cur_pos[:, None],
                jnp.ones((b, 1), jnp.int32), cache,
            )
            return (cache, logits[:, 0], cur_idx + 1, cur_pos + 1, done), tok

        rngs = jax.random.split(rng, max_new_tokens)
        init = (cache, last_logits, prompt_lens, prompt_lens,
                jnp.zeros((b,), bool))
        (_, _, _, _, done), tokens = jax.lax.scan(step, init, rngs)
        tokens = tokens.T  # (B, max_new_tokens)
        if eos_token_id is not None:
            # pad everything after (and excluding) the first eos
            is_eos = jnp.asarray(tokens == eos_token_id, jnp.int32)
            after = (jnp.cumsum(is_eos, axis=1) - is_eos) > 0
            tokens = jnp.where(after, pad_token_id, tokens)
            lengths = (max_new_tokens - after.sum(-1)).astype(jnp.int32)
        else:
            lengths = jnp.full((b,), max_new_tokens, jnp.int32)
        return tokens, lengths

    # jit once per (model, shapes, sampling settings): a fresh jit per call
    # would recompile the whole prefill+decode program on EVERY generate()
    # (jax keys its cache on function identity)
    jit_key = (b, s_prompt, max_new_tokens, temperature, top_k, top_p,
               eos_token_id, pad_token_id, str(cache_dtype),
               inputs_embeds is not None, id(cfg))
    jit_cache = model.__dict__.setdefault("_generate_jit_cache", {})
    if jit_key not in jit_cache:
        jit_cache[jit_key] = jax.jit(_run)
    rng = jax.random.key(seed)
    tokens, lengths = jit_cache[jit_key](params, input_ids, mask, prompt_lens,
                                         inputs_embeds, rng)
    sequences = jnp.concatenate([input_ids, tokens], axis=1)
    return {"sequences": sequences, "tokens": tokens, "lengths": lengths}
