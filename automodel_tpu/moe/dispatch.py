"""Explicit expert-parallel token dispatch over the ``ep`` mesh axis.

TPU-native replacement for DeepEP fused dispatch/combine
(reference moe/megatron/fused_a2a.py:250,282 + MoEFlexTokenDispatcher,
token_dispatcher.py:339): NVSHMEM buffers + fused CUDA all-to-alls become
``lax.all_to_all`` collectives over ICI inside a partial-manual ``shard_map`` —
manual over ``ep`` only, so FSDP/TP sharding on other axes stays GSPMD-managed.

Protocol per ep-shard (capacity-bucketed, static shapes):
  route -> bucket token copies by destination rank (expert // E_local) with a fixed
  per-destination capacity -> all_to_all (dispatch) -> local grouped GEMM -> all_to_all
  (combine) -> weighted scatter-add at origin.
Copies beyond capacity are dropped (standard capacity-factor trade-off; DeepEP is
dropless, the dropless path here is ``grouped_experts_apply`` under plain GSPMD).
The dispatch *accounts* for every drop: it returns ``dropped_frac`` (dropped copies /
valid copies, globally summed) so a mis-set ``capacity_factor`` is visible in the
training metrics instead of silently changing the loss.

a2a/compute overlap (``n_chunks > 1``): the capacity dim is split into K slices
and the dispatch a2a / expert GEMM / combine a2a run as three software-pipelined
sweeps, so chunk *i*'s GEMM has no data dependence on chunk *i+1*'s all_to_all
and XLA's latency-hiding scheduler overlaps them (the DeepEP async-dispatch
discipline, expressed as graph structure instead of CUDA streams). Routing, the
capacity cutoff, and ``dropped_frac`` are computed globally BEFORE slicing, so
which copies survive — and the forward output, loss, and activation gradients —
are bit-exact under any chunk count (per-row GEMM results don't depend on which
rows share a chunk). The one numeric difference: expert WEIGHT grads accumulate
per-chunk partial sums, a float reassociation of the monolithic GEMM's reduction
(measured ~2e-7 relative on fp32).

The body (:func:`make_ep_dispatch_body`) is shard_map-free: it assumes it is
already inside a region manual over ``ep_axis``. :func:`make_ep_moe_forward`
wraps it in its own partial-manual shard_map (the standalone GSPMD path);
``parallel/pipeline.py`` calls it directly inside the flattened {pp, ep} manual
region (a2a x PP composition — a nested shard_map over ep would be rejected).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.experts import sorted_ragged_ffn
from automodel_tpu.moe.gate import fake_balanced_route, route
from automodel_tpu.moe.layers import _shared_experts_forward, moe_forward

__all__ = ["make_ep_dispatch_body", "make_ep_moe_forward", "make_moe_block_forward"]


def make_moe_block_forward(cfg: MoEConfig, backend, rules=None, *, training: bool = True,
                           ep_manual_axis: str | None = None):
    """Dispatcher-aware MoE block shared by every MoE model family.

    Returns ``fn(moe_params, x, token_mask) -> (y, aux_loss, expert_load, dropped_frac)``
    with ``x`` (B, S, D). ``backend.dispatcher``:

    - ``"a2a"``: explicit EP all-to-all dispatch over the mesh's ``ep`` axis
      (:func:`make_ep_moe_forward`; the DeepEP deployment shape, reference
      fused_a2a.py:250). ``dropped_frac`` reports capacity overflow.
    - ``"dense"`` (default): GSPMD-managed :func:`moe_forward` — ``ragged_dot``
      sorted path is dropless, so ``dropped_frac`` is a constant 0.

    ``ep_manual_axis``: set when the caller is ALREADY inside a manual region
    over that axis (the pp pipeline's flattened {pp, ep} region). The a2a body
    then runs directly — no nested shard_map, no sharding constraints (which
    clash with manual axes) — with ``x`` already carrying the per-ep-shard
    batch slice and expert params the local expert shard.
    """
    if backend.dispatcher == "a2a" and ep_manual_axis is not None:
        def manual_fn(moe_params, x, token_mask=None):
            if token_mask is None:
                token_mask = jnp.ones(x.shape[:2], bool)
            # axis_size is static inside the manual region; the body builder is
            # a cheap closure, so deriving ep at trace time costs nothing
            ep = jax.lax.axis_size(ep_manual_axis)
            body = make_ep_dispatch_body(
                cfg, ep,
                capacity_factor=backend.ep_capacity_factor,
                training=training,
                fake_balanced_gate=backend.fake_balanced_gate,
                fake_gate_noise=backend.fake_gate_noise,
                ep_axis=ep_manual_axis,
                n_chunks=backend.a2a_chunks,
                experts_backend=backend.experts_backend,
            )
            return body(moe_params, x, token_mask)

        return manual_fn

    if backend.dispatcher == "a2a":
        mesh = getattr(rules, "mesh", None)
        if mesh is None or "ep" not in mesh.axis_names:
            raise ValueError(
                "backend.dispatcher='a2a' requires sharding rules bound to a mesh "
                f"with an 'ep' axis (MeshContext(ep=...)); got mesh={mesh!r}"
            )
        if mesh.shape["ep"] == 1:
            import logging

            # measured (tools/bench_a2a_dispatch.py): at ep=1 the all_to_all is
            # a self-copy, so the delta is pure bucketing overhead (one-hot-
            # cumsum queue positions + (ep, cap, D) scatter layout) — a2a was
            # 2.25x slower than dense on a v5e chip (577ms vs 257ms/step).
            # With real expert parallelism (--ep 4 --devices 8, virtual mesh)
            # the explicit a2a measured ~2.05x FASTER than the dense GSPMD
            # path (1.77s vs 3.63s/step) — which is what it exists for.
            logging.getLogger(__name__).warning(
                "dispatcher='a2a' with ep=1: measured ~2.3x slower than the "
                "default dense dispatcher on one chip; use dispatcher='dense' "
                "unless ep > 1"
            )
        ep_fn = make_ep_moe_forward(
            cfg,
            mesh,
            capacity_factor=backend.ep_capacity_factor,
            training=training,
            fake_balanced_gate=backend.fake_balanced_gate,
            fake_gate_noise=backend.fake_gate_noise,
            n_chunks=backend.a2a_chunks,
            experts_backend=backend.experts_backend,
        )
        act_sharding = rules.sharding(("batch", "act_seq", "act_embed"))

        def pinned(moe_params, x, token_mask=None):
            # pin the activation sharding at the shard_map boundary: the
            # partial-manual region leaves the auto dims unconstrained, and
            # GSPMD otherwise invents a carry sharding for the layer scan that
            # forces a replicate-then-repartition in the backward
            x = jax.lax.with_sharding_constraint(x, act_sharding)
            y, aux, load, dropped = ep_fn(moe_params, x, token_mask)
            y = jax.lax.with_sharding_constraint(y, act_sharding)
            return y, aux, load, dropped

        return pinned

    def fn(moe_params, x, token_mask=None):
        y, aux, load = moe_forward(
            cfg, moe_params, x, token_mask,
            training=training,
            dispatcher="capacity" if backend.experts_backend == "dense" else "ragged",
            fake_balanced_gate=backend.fake_balanced_gate,
            fake_gate_noise=backend.fake_gate_noise,
            experts_backend=backend.experts_backend,
        )
        return y, aux, load, jnp.float32(0)

    return fn


def _local_grouped_gemm(cfg: MoEConfig, expert_params: dict, x, expert_ids,
                        n_local_experts, experts_backend: str = "ragged_dot"):
    """Sorted grouped GEMM over the local expert shard; x (N, D), expert_ids (N,)."""
    sort_idx = jnp.argsort(expert_ids)
    group_sizes = jnp.bincount(expert_ids, length=n_local_experts).astype(jnp.int32)
    out = sorted_ragged_ffn(cfg, expert_params, x[sort_idx], expert_ids[sort_idx],
                            group_sizes, experts_backend=experts_backend)
    # unsort back to slot order
    return jnp.zeros_like(out).at[sort_idx].set(out)


def make_ep_dispatch_body(
    cfg: MoEConfig,
    ep: int,
    *,
    capacity_factor: float = 1.5,
    capacity: int | None = None,
    training: bool = True,
    fake_balanced_gate: bool = False,
    fake_gate_noise: float = 0.0,
    ep_axis: str = "ep",
    n_chunks: int = 1,
    experts_backend: str = "ragged_dot",
):
    """The per-shard a2a dispatch protocol, assuming a manual region over
    ``ep_axis`` is already open. Returns ``shard_fn(params, x, token_mask) ->
    (y, aux_loss, expert_load, dropped_frac)`` with ``x`` (B_local, S, D).
    """
    if cfg.n_routed_experts % ep != 0:
        raise ValueError(f"n_routed_experts {cfg.n_routed_experts} not divisible by ep {ep}")
    n_local = cfg.n_routed_experts // ep
    nch = max(1, int(n_chunks))

    def shard_fn(params, x, token_mask):
        B, S, D = x.shape  # B already divided by ep (manual), auto-sharded over dp
        x2 = x.reshape(-1, D)
        mask = token_mask.reshape(-1)
        T = x2.shape[0]
        K = cfg.n_activated_experts

        if fake_balanced_gate:
            weights, indices, aux_loss, expert_load = fake_balanced_route(
                cfg, x2, noise=fake_gate_noise
            )
        else:
            weights, indices, aux_loss, expert_load = route(
                cfg, params["gate"], x2, mask, training=training
            )

        cap = capacity if capacity is not None else max(1, int(capacity_factor * T * K / ep))
        # send buffers pad the capacity dim up to a chunk multiple; the cutoff
        # itself stays `cap`, so which copies survive — and dropped_frac — are
        # EXACT under any chunk count (the pad slots are never filled)
        cap_pad = -(-cap // nch) * nch
        cc = cap_pad // nch

        dest = (indices // n_local).reshape(-1)  # (T*K,) destination ep rank
        local_eid = (indices % n_local).reshape(-1)
        tok = jnp.arange(T * K) // K
        # Masked (padding) copies go to rank `ep` (out of bounds): they neither
        # consume capacity (all-zero one_hot row) nor get scattered (drop mode).
        valid_copy = mask[tok]
        dest = jnp.where(valid_copy, dest, ep)

        # Queue position of each copy within its destination bucket.
        oh = jax.nn.one_hot(dest, ep, dtype=jnp.int32)
        pos = ((jnp.cumsum(oh, axis=0) - oh) * oh).sum(-1)
        keep = (pos < cap) & valid_copy
        slot = jnp.where(keep, pos, cap_pad)  # cap_pad is out-of-bounds -> scatter drops it

        send_x = jnp.zeros((ep, cap_pad, D), x.dtype).at[dest, slot].set(x2[tok], mode="drop")
        send_eid = jnp.zeros((ep, cap_pad), jnp.int32).at[dest, slot].set(local_eid, mode="drop")
        sx = send_x.reshape(ep, nch, cc, D)
        se = send_eid.reshape(ep, nch, cc)

        # Three software-pipelined sweeps: chunk i's GEMM depends only on chunk
        # i's dispatch, so the scheduler runs it under chunk i+1's all_to_all
        # (and chunk i's combine under chunk i+1's GEMM). With nch=1 this is
        # the original monolithic dispatch -> GEMM -> combine.
        recvs = []
        for i in range(nch):
            with jax.named_scope("ep_dispatch"):
                rx = jax.lax.all_to_all(sx[:, i], ep_axis, split_axis=0, concat_axis=0)
                rid = jax.lax.all_to_all(se[:, i], ep_axis, split_axis=0, concat_axis=0)
            recvs.append((rx, rid))

        outs = []
        for rx, rid in recvs:
            with jax.named_scope("ep_experts"):
                outs.append(
                    _local_grouped_gemm(
                        cfg, params["experts"], rx.reshape(ep * cc, D), rid.reshape(-1),
                        n_local, experts_backend,
                    ).reshape(ep, cc, D)
                )

        backs = []
        for out in outs:
            with jax.named_scope("ep_combine"):
                backs.append(jax.lax.all_to_all(out, ep_axis, split_axis=0, concat_axis=0))
        back = jnp.stack(backs, axis=1).reshape(ep, cap_pad, D)

        # Combine at origin: gather each copy's result, weight it, drop overflow.
        gathered = back[dest, jnp.minimum(slot, cap_pad - 1)]  # (T*K, D)
        w = (weights.reshape(-1) * keep).astype(jnp.float32)
        y = jnp.zeros((T, D), jnp.float32).at[tok].add(gathered.astype(jnp.float32) * w[:, None])
        y = y.astype(x.dtype)

        if cfg.n_shared_experts > 0:
            y = y + _shared_experts_forward(cfg, params, x2)

        if aux_loss is not None:
            aux_loss = jax.lax.pmean(aux_loss, ep_axis)
        expert_load = jax.lax.psum(expert_load, ep_axis)
        n_valid = jax.lax.psum(valid_copy.sum().astype(jnp.float32), ep_axis)
        n_dropped = jax.lax.psum(
            (valid_copy & ~keep).sum().astype(jnp.float32), ep_axis
        )
        dropped_frac = n_dropped / jnp.maximum(n_valid, 1.0)
        return y.reshape(B, S, D), aux_loss, expert_load, dropped_frac

    return shard_fn


def make_ep_moe_forward(
    cfg: MoEConfig,
    mesh: Mesh,
    *,
    capacity_factor: float = 1.5,
    capacity: int | None = None,
    training: bool = True,
    fake_balanced_gate: bool = False,
    fake_gate_noise: float = 0.0,
    ep_axis: str = "ep",
    n_chunks: int = 1,
    experts_backend: str = "ragged_dot",
):
    """Build ``fn(params, x, token_mask) -> (y, aux_loss, expert_load, dropped_frac)``
    with explicit EP a2a dispatch. ``x`` is (B, S, D) with batch sharded over data axes
    (incl. ep); expert params are sharded over ``ep`` on their leading dim.
    ``dropped_frac`` is a global fp32 scalar: token copies dropped over capacity /
    valid token copies — exact regardless of ``n_chunks``.
    """
    ep = mesh.shape[ep_axis]
    shard_fn = make_ep_dispatch_body(
        cfg, ep,
        capacity_factor=capacity_factor, capacity=capacity, training=training,
        fake_balanced_gate=fake_balanced_gate, fake_gate_noise=fake_gate_noise,
        ep_axis=ep_axis, n_chunks=n_chunks, experts_backend=experts_backend,
    )

    # Manual specs cover only the ep axis; everything else stays auto/GSPMD.
    def param_specs(params):
        return {
            "gate": jax.tree.map(lambda _: P(), params["gate"]),
            "experts": jax.tree.map(lambda _: P(ep_axis), params["experts"]),
            **(
                {"shared_experts": jax.tree.map(lambda _: P(), params["shared_experts"])}
                if "shared_experts" in params
                else {}
            ),
            **(
                {"shared_expert_gate": P()}
                if "shared_expert_gate" in params
                else {}
            ),
        }

    def fn(params, x, token_mask=None):
        if token_mask is None:
            token_mask = jnp.ones(x.shape[:2], bool)
        aux_spec = P() if (cfg.aux_loss_coeff > 0 and training and not fake_balanced_gate) else None
        out_specs = (P(ep_axis), aux_spec, P(), P())
        mapped = jax.shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(param_specs(params), P(ep_axis), P(ep_axis)),
            out_specs=out_specs,
            axis_names={ep_axis},
        )
        return mapped(params, x, token_mask)

    return fn
