"""Phi-3 HF key/layout mapping: llama table + fused-tensor split/merge.

HF Phi-3 packs q|k|v into ``self_attn.qkv_proj.weight`` and gate|up into
``mlp.gate_up_proj.weight`` (transformers Phi3Attention/Phi3MLP). The adapter
splits them into the llama-table's virtual q/k/v/gate/up keys on the way in and
re-fuses on the way out, so the model tree stays identical to llama's.
"""

from __future__ import annotations

import numpy as np

from automodel_tpu.models.common.state_dict import LazyHFTensor
from automodel_tpu.models.common.transformer import DenseDecoderConfig
from automodel_tpu.models.llama.state_dict_adapter import LlamaStateDictAdapter

__all__ = ["Phi3StateDictAdapter"]

_FUSED = (
    # (fused HF suffix, [unfused llama-table suffixes])
    ("self_attn.qkv_proj.weight",
     ["self_attn.q_proj.weight", "self_attn.k_proj.weight", "self_attn.v_proj.weight"]),
    ("mlp.gate_up_proj.weight", ["mlp.gate_proj.weight", "mlp.up_proj.weight"]),
)


class Phi3StateDictAdapter(LlamaStateDictAdapter):
    def __init__(self, cfg: DenseDecoderConfig, scan_layers: bool = True):
        super().__init__(cfg, scan_layers)
        q = cfg.num_attention_heads * cfg.head_dim
        kv = cfg.num_key_value_heads * cfg.head_dim
        # split offsets along HF's out_features dim 0
        self._splits = {"self_attn.qkv_proj.weight": [q, q + kv],
                        "mlp.gate_up_proj.weight": [cfg.intermediate_size]}

    def _keys(self, i: int, fused: str, parts: "list[str]"):
        pre = f"model.layers.{i}."
        return pre + fused, [pre + p for p in parts]

    def from_hf(self, tensors, dtype=None) -> dict:
        t = dict(tensors)
        for i in range(self.num_layers):
            for fused, parts in _FUSED:
                fk, pks = self._keys(i, fused, parts)
                if fk not in t:
                    continue
                for pk, arr in zip(pks, np.split(np.asarray(t.pop(fk)), self._splits[fused], axis=0)):
                    t[pk] = arr
        return super().from_hf(t, dtype)

    def to_hf(self, params, dtype=None) -> dict:
        out = super().to_hf(params, dtype)
        for i in range(self.num_layers):
            for fused, parts in _FUSED:
                fk, pks = self._keys(i, fused, parts)
                out[fk] = np.concatenate([out.pop(pk) for pk in pks], axis=0)
        return out

    def to_hf_lazy(self, params, dtype=None, host_fn=None) -> dict:
        out = super().to_hf_lazy(params, dtype, host_fn)
        for i in range(self.num_layers):
            for fused, parts in _FUSED:
                fk, pks = self._keys(i, fused, parts)
                lazies = [out.pop(pk) for pk in pks]
                out[fk] = LazyHFTensor(
                    (lambda ls=lazies: np.concatenate([x.materialize() for x in ls], axis=0)),
                    sum(x.nbytes for x in lazies),
                )
        return out
