"""Qwen3.5-MoE (text stack) — TPU-native (reference models/qwen3_5_moe/model.py:359).

Qwen3-Next-style hybrid decoder (gated DeltaNet linear attention + gated full
attention + MoE) whose HF checkpoint stores the DeltaNet projections *separately*
(``in_proj_qkv`` / ``in_proj_z`` / ``in_proj_b`` / ``in_proj_a``, reference
model.py:71-99) and the experts packed as ``gate_up_proj (E, 2I, D)`` /
``down_proj (E, D, I)`` (reference state_dict_adapter.py:19-25). Compute reuses the
qwen3_next machinery unchanged — the adapter re-interleaves the separate projections
into the fused per-key-head layout at load time.

Like the reference (which gates this family on a transformers build that ships
``qwen3_5_moe``), only the text stack is supported here; the VL tower keys under
``model.visual.*`` are not yet mapped."""

from __future__ import annotations

import dataclasses
from typing import Any

from automodel_tpu.models.qwen3_next.model import Qwen3NextConfig, Qwen3NextForCausalLM

__all__ = ["Qwen3_5MoeConfig", "Qwen3_5MoeForCausalLM"]


@dataclasses.dataclass
class Qwen3_5MoeConfig(Qwen3NextConfig):
    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "Qwen3_5MoeConfig":
        t = hf.get("text_config", hf)
        base = Qwen3NextConfig.from_hf(t)
        return cls(**{f.name: getattr(base, f.name) for f in dataclasses.fields(base)})


class Qwen3_5MoeForCausalLM(Qwen3NextForCausalLM):
    config_class = Qwen3_5MoeConfig
    hf_architectures = ("Qwen3_5MoeForConditionalGeneration", "Qwen3_5MoeForCausalLM")

    def state_dict_adapter(self):
        from automodel_tpu.models.qwen3_5_moe.state_dict_adapter import (
            Qwen3_5MoeStateDictAdapter,
        )

        return Qwen3_5MoeStateDictAdapter(self.config)

    @classmethod
    def from_config(cls, config, backend=None):
        if isinstance(config, dict):
            config = Qwen3_5MoeConfig.from_hf(config)
        return cls(config, backend)
