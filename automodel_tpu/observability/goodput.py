"""Goodput accounting: classify every second of wall time into buckets.

The reference ships per-step throughput summaries but never answers "where did
the wall clock go" — a 20% regression can hide in compile, host data stalls, or
checkpoint pauses and look identical in tokens/sec. ``GoodputTracker`` bills
host wall time to named buckets (compile / data_wait / device_step / eval /
checkpoint); whatever is unaccounted is idle. Goodput is the device_step share
of total wall time — the fraction of the run actually spent training.

Attribution is host-side: the jitted step is asynchronous, so ``device_step``
measures dispatch-to-sync host time, not device occupancy. Over a log window
the two converge (the host blocks on the metrics pull), and host-side is the
only attribution that also sees data stalls and checkpoint pauses.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable

__all__ = ["BUCKETS", "GoodputTracker"]

# buckets the train loop bills explicitly; the remainder is idle. ``restore``
# is checkpoint load on resume (incl. the elastic re-partition path) — billed
# via bill_preceding() because it happens before the tracker exists.
BUCKETS = ("compile", "data_wait", "device_step", "eval", "checkpoint",
           "rollback", "restore")


class GoodputTracker:
    """Cumulative wall-time bucket accounting for one training run.

    ``clock`` is injectable for tests (defaults to ``time.perf_counter``).
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._start = clock()
        self._totals: dict[str, float] = {b: 0.0 for b in BUCKETS}

    @contextlib.contextmanager
    def track(self, bucket: str):
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(bucket, self._clock() - t0)

    def add(self, bucket: str, seconds: float) -> None:
        self._totals.setdefault(bucket, 0.0)
        self._totals[bucket] += max(float(seconds), 0.0)

    def bill_preceding(self, bucket: str, seconds: float) -> None:
        """Bill time spent *before* this tracker existed (checkpoint restore on
        resume happens before observability is constructed). Rewinds the wall
        origin by the same amount so fractions still sum to 1."""
        seconds = max(float(seconds), 0.0)
        self._start -= seconds
        self.add(bucket, seconds)

    @property
    def wall_s(self) -> float:
        return max(self._clock() - self._start, 1e-9)

    def totals(self) -> dict[str, float]:
        """Per-bucket seconds including the idle remainder; sums to wall_s."""
        accounted = sum(self._totals.values())
        return {**self._totals, "idle": max(self.wall_s - accounted, 0.0)}

    def snapshot(self) -> dict[str, float]:
        """Cumulative bucket fractions + the goodput scalar, ready for a log row.

        Fractions are of total wall time and sum to 1 (idle absorbs the
        remainder); ``goodput`` is the device_step fraction.
        """
        wall = self.wall_s
        totals = self.totals()
        out = {f"goodput/{b}": round(v / wall, 4) for b, v in totals.items()}
        out["goodput"] = round(totals["device_step"] / wall, 4)
        # bare key on purpose: `goodput/` values are fractions summing to 1,
        # and the run ledger needs the absolute wall to de-normalize them
        out["goodput_wall_s"] = round(wall, 3)
        return out
