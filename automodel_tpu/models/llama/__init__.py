from automodel_tpu.models.llama.model import LlamaConfig, LlamaForCausalLM

__all__ = ["LlamaConfig", "LlamaForCausalLM"]
