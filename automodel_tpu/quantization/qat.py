"""Quantization-aware training (reference quantization/qat.py, which wraps torchao
Int8DynActInt4WeightQATQuantizer; here: straight-through fake quantization).

``fake_quant`` simulates int-N rounding in the forward pass while passing gradients
straight through (STE), so the trained weights become robust to post-training
quantization. The recipe applies it to matched param leaves after an optional
delay (reference fake_quant_after_n_steps).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

__all__ = ["QATConfig", "fake_quant", "fake_quant_params"]


@dataclasses.dataclass
class QATConfig:
    enabled: bool = True
    weight_bits: int = 4
    group_size: int = 32  # per-group absmax scaling along the last dim
    fake_quant_after_n_steps: int | None = None  # None = from step 0
    target_modules: list[str] = dataclasses.field(default_factory=lambda: ["*"])


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def fake_quant(w: jnp.ndarray, bits: int = 4, group_size: int = 32) -> jnp.ndarray:
    return _fake_quant_fwd(w, bits, group_size)[0]


def _fake_quant_fwd(w, bits, group_size):
    orig_shape = w.shape
    wf = (
        w.astype(jnp.float32).reshape(-1, group_size)
        if w.size % group_size == 0
        else w.astype(jnp.float32).reshape(1, -1)
    )
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.maximum(jnp.abs(wf).max(axis=-1, keepdims=True), 1e-12) / qmax
    q = jnp.clip(jnp.round(wf / scale), -qmax - 1, qmax)
    out = (q * scale).reshape(orig_shape).astype(w.dtype)
    return out, None


def _fake_quant_bwd(bits, group_size, _res, g):
    # straight-through: d(fake_quant)/dw ~= identity (g already has w's dtype)
    return (g,)


fake_quant.defvjp(_fake_quant_fwd, _fake_quant_bwd)


def fake_quant_params(params, paths: list[str], cfg: QATConfig):
    """Apply fake quantization to the listed leaves (inside jit, pre-forward)."""
    from automodel_tpu.peft.lora import _get_path, _set_path

    out = params
    for path in paths:
        w = _get_path(out, path)
        out = _set_path(out, path, fake_quant(w, cfg.weight_bits, cfg.group_size))
    return out
