#!/usr/bin/env python
"""Self-checking CPU chaos smoke for the resilience subsystem (docs/resilience.md).

Trains a tiny mock llama on 8 virtual CPU devices with two injected faults —
NaN-poisoned params after step 6 and a truncated checkpoint at step 8 — and
asserts the run survives both:

1. the NaN step triggers an in-process rollback to the step-4 checkpoint and
   training finishes with finite losses, the final one matching an
   uninterrupted baseline to within the skipped window;
2. with the clean tail checkpoints removed, a fresh resume rejects the
   truncated step-8 checkpoint on manifest verification and walks back to
   step 4.

Usage:  JAX_PLATFORMS=cpu python tools/chaos_smoke.py [--workdir DIR]

The same scenario runs under pytest as ``pytest -m chaos``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import textwrap

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

MAX_STEPS = 14
NAN_STEP = 6
CORRUPT_STEP = 8
CKPT_EVERY = 4

_RESILIENCE = """\
resilience:
  enabled: true
  anomaly: {window: 20, min_history: 5}
  max_skipped_updates: 0
  rollback: {max_rollbacks: 2, skip_steps: 0}
  chaos:
    enabled: true
    nan_grad_steps: [%d]
    corrupt_ckpt_steps: [%d]
""" % (NAN_STEP, CORRUPT_STEP)


def _write_cfg(root: str, name: str, *, ckpt: bool, chaos: bool,
               max_steps: int = MAX_STEPS, ckpt_every: int = CKPT_EVERY,
               async_save: bool = False, resilience: str | None = None) -> str:
    """Write the tiny-llama CPU smoke config. ``resilience`` overrides the
    default chaos block (tools/supervisor_smoke.py reuses this writer with
    kill/hang injections); the defaults reproduce the classic smoke."""
    text = textwrap.dedent(f"""\
    seed: 7
    output_dir: {root}/{name}/out
    model:
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 128
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    distributed:
      dp_shard: 4
      tp: 2
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 256
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: 1
      max_steps: {max_steps}
      num_epochs: 10
      handle_sigterm: false
      ckpt_every_steps: {ckpt_every if ckpt else 0}
    optimizer:
      lr: 1.0e-2
      weight_decay: 0.0
      max_grad_norm: 1.0
    lr_scheduler:
      lr_warmup_steps: 2
    checkpoint:
      enabled: {str(ckpt).lower()}
      checkpoint_dir: {root}/{name}/ckpt
      async_save: {str(async_save).lower()}
    """)
    if chaos:
        text += resilience if resilience is not None else _RESILIENCE
    path = os.path.join(root, f"{name}.yaml")
    os.makedirs(os.path.join(root, name), exist_ok=True)
    with open(path, "w") as f:
        f.write(text)
    return path


def _run(cfg_path: str):
    from automodel_tpu.config.loader import load_config
    from automodel_tpu.recipes.llm.train_ft import (
        TrainFinetuneRecipeForNextTokenPrediction,
    )

    cfg = load_config(cfg_path)
    recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
    recipe.run_train_validation_loop()
    return recipe


def _rows(root: str, name: str) -> list[dict]:
    with open(os.path.join(root, name, "out", "training.jsonl")) as f:
        return [json.loads(line) for line in f]


def main(workdir: str | None = None) -> int:
    owns_workdir = workdir is None
    root = workdir or tempfile.mkdtemp(prefix="chaos_smoke_")
    try:
        print(f"[chaos_smoke] workdir {root}")

        print("[chaos_smoke] 1/3 uninterrupted baseline ...")
        _run(_write_cfg(root, "base", ckpt=False, chaos=False))
        base_losses = {r["step"]: r["loss"] for r in _rows(root, "base") if "loss" in r}

        print(f"[chaos_smoke] 2/3 chaos run: NaN at step {NAN_STEP}, "
              f"checkpoint truncated at step {CORRUPT_STEP} ...")
        _run(_write_cfg(root, "chaos", ckpt=True, chaos=True))
        rows = _rows(root, "chaos")

        events = [r for r in rows if "resilience/event" in r]
        names = [r["resilience/event"] for r in events]
        assert "rollback" in names and "rollback_done" in names, f"events: {names}"
        done = next(r for r in events if r["resilience/event"] == "rollback_done")
        assert done["resilience/from_step"] == NAN_STEP, done
        assert done["resilience/to_step"] == CKPT_EVERY, done

        losses = {r["step"]: r["loss"] for r in rows if "loss" in r}
        assert NAN_STEP not in losses, "poisoned step must not log a metric row"
        bad = {s: v for s, v in losses.items() if v != v}
        assert not bad, f"non-finite losses survived recovery: {bad}"
        drift = abs(losses[MAX_STEPS] - base_losses[MAX_STEPS])
        assert drift < 0.5, (
            f"final loss {losses[MAX_STEPS]:.3f} drifted {drift:.3f} from "
            f"baseline {base_losses[MAX_STEPS]:.3f}"
        )
        print(f"[chaos_smoke]     rollback {done['resilience/from_step']} -> "
              f"{done['resilience/to_step']}, final loss {losses[MAX_STEPS]:.3f} "
              f"(baseline {base_losses[MAX_STEPS]:.3f})")

        print("[chaos_smoke] 3/3 fallback restore past the truncated checkpoint ...")
        ckpt_dir = os.path.join(root, "chaos", "ckpt")
        for d in sorted(os.listdir(ckpt_dir)):
            step_dir = os.path.join(ckpt_dir, d)
            if d.startswith("step_") and int(d.split("_")[1]) > CORRUPT_STEP:
                shutil.rmtree(step_dir)
        latest = os.path.join(ckpt_dir, "latest")
        if os.path.lexists(latest):
            os.unlink(latest)

        from automodel_tpu.config.loader import load_config
        from automodel_tpu.recipes.llm.train_ft import (
            TrainFinetuneRecipeForNextTokenPrediction,
        )

        cfg = load_config(os.path.join(root, "chaos.yaml"))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        assert recipe.step_scheduler.step == CKPT_EVERY, (
            f"resumed at step {recipe.step_scheduler.step}, expected {CKPT_EVERY} "
            f"(truncated step_{CORRUPT_STEP} should fail verification)"
        )
        print(f"[chaos_smoke]     resumed at step {recipe.step_scheduler.step}, "
              f"skipping unverifiable step_{CORRUPT_STEP}")

        print("[chaos_smoke] PASS")
        return 0
    finally:
        if owns_workdir:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None,
                        help="keep artifacts here instead of a temp dir")
    sys.exit(main(parser.parse_args().workdir))
