"""Per-model VLM collators: HF-processor patch-layout parity, media expansion,
mrope wiring (reference datasets/vlm/collate_fns.py per-processor dispatch)."""

import numpy as np
import pytest

import jax.numpy as jnp

from automodel_tpu.data.vlm.collate_fns import (
    kimi_patchify, log_mel_spectrogram, qwen_patchify, qwen_vl_collate,
)


class WordTok:
    eos_token_id = 1

    def encode(self, text, add_special_tokens=True):
        return [2 + (hash(w) % 90) for w in text.split()]


class TestQwenPatchify:
    def test_matches_hf_processor_layout(self):
        transformers = pytest.importorskip("transformers")
        from transformers.models.qwen2_vl.image_processing_qwen2_vl import (
            Qwen2VLImageProcessor,
        )

        rng = np.random.RandomState(0)
        img = (rng.rand(56, 56, 3) * 255).astype(np.uint8)
        proc = Qwen2VLImageProcessor(
            patch_size=4, merge_size=2, temporal_patch_size=2,
            min_pixels=1, max_pixels=10**9, do_resize=False,
        )
        out = proc(images=[img], return_tensors="np")
        want = out["pixel_values"]
        grid = out["image_grid_thw"][0]  # (t, h, w)
        got = qwen_patchify(
            img, patch_size=4, merge_size=2, temporal_patch_size=2,
            grid_h=int(grid[1]), grid_w=int(grid[2]),
        )
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, atol=2e-2, rtol=1e-2)

    def test_kimi_patchify_shape(self):
        img = np.random.RandomState(1).rand(28, 28, 3).astype(np.float32)
        got = kimi_patchify(img, patch_size=4, grid_h=4, grid_w=4)
        assert got.shape == (16, 3 * 16)


class TestQwenVLCollate:
    def _model(self):
        from automodel_tpu.models.auto import AutoModelForImageTextToText
        from automodel_tpu.models.common.backend import BackendConfig

        hf = {
            "architectures": ["Qwen3VLMoeForConditionalGeneration"],
            "text_config": {
                "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
                "moe_intermediate_size": 32, "num_hidden_layers": 2,
                "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
                "num_experts": 8, "num_experts_per_tok": 2, "max_position_embeddings": 128,
                "rope_scaling": {"rope_type": "default", "mrope_section": [4, 2, 2],
                                 "mrope_interleaved": True},
            },
            "vision_config": {
                "depth": 2, "hidden_size": 32, "intermediate_size": 48, "num_heads": 4,
                "patch_size": 4, "spatial_merge_size": 2, "temporal_patch_size": 2,
                "out_hidden_size": 64, "num_position_embeddings": 16,
                "deepstack_visual_indexes": [0, 1], "in_channels": 3,
            },
            "image_token_id": 120, "video_token_id": 122, "vision_start_token_id": 121,
        }
        return AutoModelForImageTextToText.from_config(
            hf, BackendConfig(dtype="float32")
        )

    def test_batch_shapes_and_forward(self):
        import jax

        model = self._model()
        rng = np.random.RandomState(0)
        exs = [
            {"prompt": "<image> describe", "answer": "a cat",
             "image": rng.rand(16, 16, 3).astype(np.float32)}
            for _ in range(2)
        ]
        batch = qwen_vl_collate(exs, WordTok(), model, seq_len=48, image_size=(4, 4))
        n_merged = 4  # (4/2)*(4/2)
        assert batch["pixel_values"].shape == (2 * 16, 3 * 2 * 16)
        assert batch["positions3"].shape == (3, 2, 48)
        assert (batch["input_ids"] == 120).sum() == 2 * n_merged
        assert batch["visual_coords_b"].shape[0] == 2 * n_merged
        # answer tokens supervised, image tokens not
        assert (batch["labels"] != -100).sum() > 0
        img_positions = batch["input_ids"] == 120
        assert (batch["labels"][img_positions] == -100).all()

        params = model.init(jax.random.key(0), jnp.float32)
        out, _ = model(
            params, jnp.asarray(batch["input_ids"]),
            pixel_values=jnp.asarray(batch["pixel_values"]),
            vision_inputs={k: jnp.asarray(v) for k, v in batch["vision_inputs"].items()},
            visual_coords=(jnp.asarray(batch["visual_coords_b"]),
                           jnp.asarray(batch["visual_coords_s"])),
            positions3=jnp.asarray(batch["positions3"]),
            segment_ids=jnp.asarray(batch["segment_ids"]),
            training=False,
        )
        assert np.isfinite(np.asarray(out)).all()


class TestLogMel:
    def test_shapes_and_finite(self):
        audio = np.sin(np.linspace(0, 100, 16000)).astype(np.float32)
        mel = log_mel_spectrogram(audio, num_mel_bins=32)
        assert mel.shape[0] == 32
        assert mel.shape[1] == 1 + (16000 - 400) // 160
        assert np.isfinite(mel).all()


class TestKimiCollateForward:
    def test_collate_and_forward(self):
        import jax

        from automodel_tpu.data.vlm.collate_fns import kimi_vl_collate
        from automodel_tpu.models.auto import AutoModelForImageTextToText
        from automodel_tpu.models.common.backend import BackendConfig

        hf = {
            "architectures": ["KimiVLForConditionalGeneration"],
            "media_placeholder_token_id": 120,
            "text_config": {
                "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
                "moe_intermediate_size": 32, "num_hidden_layers": 2,
                "num_attention_heads": 4, "q_lora_rank": None, "kv_lora_rank": 32,
                "qk_nope_head_dim": 16, "qk_rope_head_dim": 8, "v_head_dim": 16,
                "n_routed_experts": 8, "num_experts_per_tok": 2, "n_shared_experts": 1,
                "n_group": 2, "topk_group": 1, "routed_scaling_factor": 2.5,
                "norm_topk_prob": True, "first_k_dense_replace": 1,
                "max_position_embeddings": 128,
                "scoring_func": "sigmoid", "topk_method": "noaux_tc",
            },
            "vision_config": {
                "patch_size": 4, "init_pos_emb_height": 8, "init_pos_emb_width": 8,
                "num_attention_heads": 4, "num_hidden_layers": 2, "hidden_size": 32,
                "intermediate_size": 48, "merge_kernel_size": [2, 2],
            },
        }
        model = AutoModelForImageTextToText.from_config(hf, BackendConfig(dtype="float32"))
        rng = np.random.RandomState(0)
        exs = [{"prompt": "<image> what", "answer": "dog",
                "image": rng.rand(16, 16, 3).astype(np.float32)}]
        batch = kimi_vl_collate(exs, WordTok(), model, seq_len=32, image_size=(4, 4))
        assert batch["pixel_values"].shape == (16, 3 * 16)
        assert (batch["input_ids"] == 120).sum() == 4  # (4/2)*(4/2) merged tokens
        params = model.init(jax.random.key(0), jnp.float32)
        out, _ = model(
            params, jnp.asarray(batch["input_ids"]),
            pixel_values=jnp.asarray(batch["pixel_values"]),
            vision_inputs={k: jnp.asarray(v) for k, v in batch["vision_inputs"].items()},
            media_coords=(jnp.asarray(batch["media_coords_b"]),
                          jnp.asarray(batch["media_coords_s"])),
            positions=jnp.asarray(batch["positions"]),
            segment_ids=jnp.asarray(batch["segment_ids"]),
            training=False,
        )
        assert np.isfinite(np.asarray(out)).all()


class TestOmniCollateForward:
    def test_audio_collate_and_forward(self):
        import jax

        from automodel_tpu.data.vlm.collate_fns import qwen3_omni_collate
        from automodel_tpu.models.auto import AutoModelForImageTextToText
        from automodel_tpu.models.common.backend import BackendConfig

        hf = {
            "architectures": ["Qwen3OmniMoeForConditionalGeneration"],
            "audio_config": {
                "d_model": 32, "encoder_layers": 2, "encoder_attention_heads": 4,
                "encoder_ffn_dim": 48, "num_mel_bins": 32, "n_window": 8,
                "n_window_infer": 32, "downsample_hidden_size": 16, "output_dim": 64,
                "conv_chunksize": 500,
            },
            "vision_config": {
                "depth": 2, "hidden_size": 32, "intermediate_size": 48, "num_heads": 4,
                "patch_size": 4, "spatial_merge_size": 2, "temporal_patch_size": 2,
                "out_hidden_size": 64, "num_position_embeddings": 16,
                "deepstack_visual_indexes": [0, 1], "in_channels": 3,
            },
            "text_config": {
                "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
                "moe_intermediate_size": 32, "num_hidden_layers": 2,
                "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
                "num_experts": 8, "num_experts_per_tok": 2, "max_position_embeddings": 256,
                "rope_scaling": {"rope_type": "default", "mrope_section": [4, 2, 2],
                                 "mrope_interleaved": True},
            },
            "audio_token_id": 123, "image_token_id": 120, "video_token_id": 122,
            "vision_start_token_id": 121, "audio_start_token_id": 124,
        }
        model = AutoModelForImageTextToText.from_config(hf, BackendConfig(dtype="float32"))
        rng = np.random.RandomState(0)
        exs = [{"prompt": "<audio> transcribe", "answer": "hello",
                "audio_features": rng.randn(32, 24).astype(np.float32)}]
        batch = qwen3_omni_collate(exs, WordTok(), model, seq_len=64)
        n_audio_tok = int((batch["input_ids"] == 123).sum())
        assert n_audio_tok > 0
        assert batch["audio_coords_b"].shape[0] == n_audio_tok
        params = model.init(jax.random.key(0), jnp.float32)
        out, _ = model(
            params, jnp.asarray(batch["input_ids"]),
            audio_chunks=jnp.asarray(batch["audio_chunks"]),
            audio_inputs={k: jnp.asarray(v) for k, v in batch["audio_inputs"].items()},
            audio_coords=(jnp.asarray(batch["audio_coords_b"]),
                          jnp.asarray(batch["audio_coords_s"])),
            positions3=jnp.asarray(batch["positions3"]),
            segment_ids=jnp.asarray(batch["segment_ids"]),
            training=False,
        )
        assert np.isfinite(np.asarray(out)).all()


class TestLeadingMediaBOS:
    def test_bos_emitted_before_leading_media_span(self):
        """A prompt that STARTS with a media placeholder still gets BOS ahead of
        the vision tokens (HF Qwen-VL/Kimi keep sequence-start tokens before
        media; advisor r2)."""
        from automodel_tpu.data.vlm.collate_fns import _encode_with_media

        class BosTok:
            bos_token_id = 7
            eos_token_id = 1

            def encode(self, text, add_special_tokens=True):
                ids = [10 + (hash(w) % 90) for w in text.split()]
                return ([self.bos_token_id] + ids) if add_special_tokens else ids

        media_span = [100, 101, 102]
        ex = {"prompt": "<image> describe it", "answer": "a cat"}
        inp, tgt = _encode_with_media(
            BosTok(), ex, 64, {"<image>": [media_span]}
        )
        # inputs are shifted by one: inp[0] is the first token of the sequence
        assert inp[0] == 7, f"expected BOS first, got {inp[:6]}"
        assert list(inp[1:4]) == media_span

    def test_no_double_bos_with_text_prefix(self):
        from automodel_tpu.data.vlm.collate_fns import _encode_with_media

        class BosTok:
            bos_token_id = 7
            eos_token_id = 1

            def encode(self, text, add_special_tokens=True):
                ids = [10 + (hash(w) % 90) for w in text.split()]
                return ([self.bos_token_id] + ids) if add_special_tokens else ids

        ex = {"prompt": "look <image> now", "answer": "ok"}
        inp, _ = _encode_with_media(BosTok(), ex, 64, {"<image>": [[100, 101]]})
        assert list(inp).count(7) == 1
        assert inp[0] == 7


class TestPhi4MMCollate:
    def test_audio_span_sizes_and_features(self):
        from automodel_tpu.data.vlm.collate_fns import phi4_mm_collate

        rng = np.random.RandomState(0)
        exs = [
            {"prompt": "<audio> transcribe", "answer": "hello",
             "audio_features": rng.randn(80, 33).astype(np.float32)},
            {"prompt": "<audio> transcribe", "answer": "bye",
             "audio_features": rng.randn(80, 17).astype(np.float32)},
        ]
        batch = phi4_mm_collate(exs, WordTok(), seq_len=64, audio_token_id=99)
        # HF _compute_audio_embed_size: ceil(T / 8) (qformer rate 1)
        assert int((batch["input_ids"][0] == 99).sum()) == -(-33 // 8)
        assert int((batch["input_ids"][1] == 99).sum()) == -(-17 // 8)
        assert batch["audio_features"].shape == (2, 80, 33)
        assert list(batch["audio_frames"]) == [33, 17]
        n_tok = int((batch["input_ids"] == 99).sum())
        assert batch["audio_coords_b"].shape[0] == n_tok
        # audio placeholder tokens never contribute to the loss
        assert (batch["labels"][batch["input_ids"] == 99] == -100).all()

    def test_raw_waveform_path(self):
        from automodel_tpu.data.vlm.collate_fns import phi4_mm_collate

        rng = np.random.RandomState(1)
        exs = [{"prompt": "<audio> what", "answer": "x",
                "audio": rng.randn(16000).astype(np.float32)}]
        batch = phi4_mm_collate(exs, WordTok(), seq_len=64, audio_token_id=99)
        assert batch["audio_features"].shape[1] == 80
        assert int((batch["input_ids"] == 99).sum()) > 0
