"""Biencoder (retrieval embedding) training recipe (reference
recipes/biencoder/train_biencoder.py:137 TrainBiencoderRecipe).

One shared bidirectional tower embeds queries and passages; the loss is contrastive
CE over ``q @ p.T / temperature`` with each query's positive at a known row
(reference contrastive_scores_and_labels, train_biencoder.py:50). In-batch
negatives on by default; L2-normalized embeddings on by default (E5-style).

YAML contract adds:

.. code-block:: yaml

    model:
      config: {architectures: [LlamaBidirectionalModel], ...}
    biencoder:
      temperature: 0.02
      normalize: true
      in_batch_negatives: true
      query_seq_len: 64
      passage_seq_len: 128
    dataset:
      _target_: automodel_tpu.data.llm.retrieval.RetrievalDataset
      path_or_dataset_id: /data/mined.jsonl
      num_hard_negatives: 1
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.config.cli_overrides import parse_args_and_load_config
from automodel_tpu.data.llm.retrieval import retrieval_collate
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction

logger = logging.getLogger(__name__)

__all__ = ["TrainBiencoderRecipe", "main", "positive_ranks"]


def positive_ranks(scores: jax.Array, labels: jax.Array) -> jax.Array:
    """1-based rank of each query's positive within its score row.

    Deterministic under ties: rank = 1 + strictly-better columns + tied
    columns with a smaller index (torch.topk's first-occurrence convention,
    exactly). In-batch duplicates produce tied fp32 scores, and counting only
    strict wins would score every duplicate as rank 1 — inflating acc@1/MRR
    on datasets with repeated passages.
    """
    labels = labels.astype(jnp.int32)
    pos = jnp.take_along_axis(scores, labels[:, None], axis=-1)
    cols = jnp.arange(scores.shape[-1])[None, :]
    tied_before = ((scores == pos) & (cols < labels[:, None])).sum(-1)
    return 1 + (scores > pos).sum(-1) + tied_before


class TrainBiencoderRecipe(TrainFinetuneRecipeForNextTokenPrediction):
    def _wrap_dataset_and_collate(self, dataset, pad_id: int):
        bc = self.cfg.get("biencoder") or ConfigNode()
        q_len = int(bc.get("query_seq_len", self.seq_len))
        p_len = int(bc.get("passage_seq_len", self.seq_len))
        return dataset, (
            lambda exs: retrieval_collate(
                exs, tokenizer=self.tokenizer,
                query_seq_len=q_len, passage_seq_len=p_len, pad_token_id=pad_id,
            )
        )

    def _scores_and_labels(self, params, batch):
        """(scores (B, B*G) fp32 already temperature-scaled, labels (B,)) —
        the contrastive core shared by the train loss and the retrieval-metric
        validation (reference contrastive_scores_and_labels)."""
        bc = self.cfg.get("biencoder") or ConfigNode()
        temperature = float(bc.get("temperature", 0.02))
        normalize = bool(bc.get("normalize", True))
        in_batch = bool(bc.get("in_batch_negatives", True))

        q = self.model(params, batch["q_ids"], positions=batch["q_pos"],
                       segment_ids=batch["q_seg"], rules=self.rules)  # (B, D)
        p = self.model(params, batch["p_ids"], positions=batch["p_pos"],
                       segment_ids=batch["p_seg"], rules=self.rules)  # (B*G, D)
        if normalize:
            q = q / jnp.linalg.norm(q, axis=-1, keepdims=True)
            p = p / jnp.linalg.norm(p, axis=-1, keepdims=True)
        # positives derived from the GLOBAL batch shape inside jit: collate-time
        # labels would be process-local rows, mislabeling every process but 0 on
        # multi-host runs (batch["labels"] is only used for the query count)
        b = q.shape[0]
        group = p.shape[0] // b
        labels = jnp.arange(b) * group
        scores = (q @ p.T).astype(jnp.float32) / temperature  # (B, B*G)
        if not in_batch:
            # restrict each query to its own passage group (reference
            # contrastive_scores_and_labels "without in-batch negatives")
            cols = jnp.arange(b * group)[None, :]
            own = (cols // group) == jnp.arange(b)[:, None]
            scores = jnp.where(own, scores, -jnp.inf)
        return scores, labels

    def _forward_loss(self, params, batch, num_label_tokens, training=True):
        scores, labels = self._scores_and_labels(params, batch)
        logp = jax.nn.log_softmax(scores, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
        # num_label_tokens = global query count (labels are all valid)
        return nll.sum() / jnp.maximum(num_label_tokens, 1).astype(jnp.float32)

    def _run_validation(self, step: int):
        """Validation with retrieval metrics (reference _run_validation epoch,
        train_biencoder.py:408: val_loss + acc@1 + MRR; recall@k added on top):
        the positive's rank within each query's score row yields acc@1
        (recall@1), recall@k, and reciprocal rank, summed per batch in-jit and
        aggregated across hosts by the shared val logger."""
        bc = self.cfg.get("biencoder") or ConfigNode()
        recall_k = int(bc.get("recall_k", 5))
        if getattr(self, "_bi_eval_step", None) is None:

            def eval_fn(params, batch, frozen=None):
                if self.peft is not None:
                    # PEFT shape: params is the LoRA tree, frozen the base —
                    # merge exactly like the train/eval steps do
                    from automodel_tpu.peft.lora import merge_lora_params

                    params = merge_lora_params(frozen, params, self.peft)
                scores, labels = self._scores_and_labels(params, batch)
                logp = jax.nn.log_softmax(scores, axis=-1)
                nll = -jnp.take_along_axis(
                    logp, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
                rank = positive_ranks(scores, labels)
                return (nll.sum(), (rank == 1).sum(), (rank <= recall_k).sum(),
                        (1.0 / rank.astype(jnp.float32)).sum())

            self._bi_eval_step = jax.jit(eval_fn)
        loss_t = acc1_t = hitk_t = rr_t = 0.0
        nq = 0
        extra = (self.params,) if self.peft is not None else ()
        for batch in self._iter_val_batches():
            l, a1, hk, rr = self._bi_eval_step(self.train_params, batch, *extra)
            loss_t += float(l)
            acc1_t += float(a1)
            hitk_t += float(hk)
            rr_t += float(rr)
            nq += int(batch["q_ids"].shape[0])
        self._log_val_loss(step, loss_t, nq, extra_sums={
            "val_acc1": acc1_t, f"val_recall_at_{recall_k}": hitk_t,
            "val_mrr": rr_t,
        })

    def encode(self, texts: list[str], batch_size: int = 32, seq_len: int | None = None):
        """Embed texts with the current tower (mine_hard_negatives uses this)."""
        import numpy as np

        bc = self.cfg.get("biencoder") or ConfigNode()
        seq_len = seq_len or int(bc.get("passage_seq_len", self.seq_len))
        normalize = bool(bc.get("normalize", True))
        out = []
        for i in range(0, len(texts), batch_size):
            chunk = texts[i:i + batch_size]
            ids = np.zeros((len(chunk), seq_len), np.int32)
            seg = np.zeros((len(chunk), seq_len), np.int32)
            pos = np.zeros((len(chunk), seq_len), np.int32)
            for r, t in enumerate(chunk):
                toks = np.asarray(self.tokenizer.encode(t), np.int32)[:seq_len]
                ids[r, :len(toks)] = toks
                seg[r, :len(toks)] = 1
                pos[r, :len(toks)] = np.arange(len(toks))
            emb = self.model(self.params, jnp.asarray(ids), positions=jnp.asarray(pos),
                             segment_ids=jnp.asarray(seg), rules=self.rules)
            if normalize:
                emb = emb / jnp.linalg.norm(emb, axis=-1, keepdims=True)
            out.append(np.asarray(emb))
        return np.concatenate(out)


def main(cfg: ConfigNode | None = None, argv=None):
    if cfg is None:
        cfg = parse_args_and_load_config(argv)
    recipe = TrainBiencoderRecipe(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()
    return recipe


if __name__ == "__main__":
    main()
