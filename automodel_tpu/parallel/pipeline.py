"""Pipeline parallelism over the ``pp`` mesh axis (SPMD collective pipelining).

TPU-native replacement for torch.distributed.pipelining (reference AutoPipeline,
distributed/pipelining/autopipeline.py:46 + functional.py:289,490): instead of
FQN-slicing a module tree into per-rank stage graphs with explicit P2P send/recv and a
hand-built 1F1B schedule, the layer-stacked param layout makes stage slicing a
*sharding*: layer dim -> ``pp`` axis. Every rank runs the same jitted program; a
``lax.scan`` over pipeline ticks moves activations stage->stage with ``ppermute``
(neighbor ICI hops). Reverse-mode AD differentiates through the scan + ppermute,
yielding the mirrored backward pipeline automatically — no schedule code, no shape
inference, no stage graphs.

Schedule: GPipe-style (all-forward then all-backward per optimizer step) with
bubble fraction (pp-1)/(n_micro+pp-1); the reference's 1F1B/interleaved/zero-bubble
schedules trade that bubble for explicit per-microbatch scheduling — a later
optimization (interleaving = assigning non-contiguous layer blocks per rank, which
this layout also supports by reshaping the layer dim).

Composition: shard_map is manual over ``pp`` only; FSDP/TP shardings on other mesh
axes stay GSPMD-managed inside (same partial-manual pattern as moe.dispatch).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_spmd", "make_pipeline_forward", "make_dense_decoder_pp_loss", "make_moe_pp_loss"]


def pipeline_spmd(
    stage_params,  # pytree; leaves (L_local, ...) — this rank's layer slice
    x_stack,  # pytree; leaves (n_micro, ...) — stage-0 inputs (already embedded)
    layer_apply: Callable,  # (stage_params, x) -> y  or -> (y, aux) with with_aux
    *,
    axis: str = "pp",
    with_aux: bool = False,
):
    """Run the pipeline; returns an x_stack-like pytree of outputs, valid on the
    LAST stage (other ranks hold garbage — mask with axis_index == pp-1).

    ``x_stack`` may be a pytree (e.g. {"h": ..., "positions": ..., "segment_ids":
    ...}) — side inputs like positions ride along with the activation through the
    ring so each stage sees its microbatch's metadata. Call inside shard_map manual
    over ``axis``.

    ``with_aux``: ``layer_apply`` returns ``(y, aux_tree)``; aux is *summed* over
    the ticks where this stage held a real microbatch (warmup/drain ticks carry
    garbage activations and are masked out) — the per-stage accumulation MoE
    expert-load/aux-loss stats need. Returns ``(outputs, aux_sum)``.
    """
    pp = jax.lax.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    leaves = jax.tree.leaves(x_stack)
    n_micro = leaves[0].shape[0]
    steps = n_micro + pp - 1
    # stage s -> s+1; the wraparound edge (pp-1 -> 0) carries only garbage, which
    # stage 0 immediately overwrites with fresh microbatch input.
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def _apply(x):
        out = layer_apply(stage_params, x)
        return out if with_aux else (out, {})

    def tick(carry, t):
        outputs, state, aux_acc = carry
        mb = jnp.clip(t, 0, n_micro - 1)
        feed = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(a, mb, 0, keepdims=False), x_stack
        )
        x = jax.tree.map(lambda f, s: jnp.where(idx == 0, f, s), feed, state)
        y, aux = _apply(x)
        # stage idx holds microbatch t-idx at tick t: real iff 0 <= t-idx < n_micro
        valid = ((t >= idx) & (t - idx < n_micro)).astype(jnp.float32)
        aux_acc = jax.tree.map(lambda acc, a: acc + a * valid, aux_acc, aux)
        # last stage finishes microbatch t-(pp-1) at tick t; earlier ticks write
        # garbage into slot 0 which the t = pp-1 tick overwrites (writes are in
        # time order, so the final write per slot is the correct one)
        out_slot = jnp.clip(t - (pp - 1), 0, n_micro - 1)
        outputs = jax.tree.map(
            lambda o, yl: jax.lax.dynamic_update_index_in_dim(o, yl, out_slot, 0),
            outputs, y,
        )
        state = jax.tree.map(lambda yl: jax.lax.ppermute(yl, axis, perm), y)
        return (outputs, state, aux_acc), None

    # mark the carries pp-varying (the body's ppermute/axis_index make them so)
    def _vary(x):
        return jax.lax.pcast(x, (axis,), to="varying")

    outputs = jax.tree.map(lambda a: _vary(jnp.zeros_like(a)), x_stack)
    state = jax.tree.map(lambda a: _vary(jnp.zeros_like(a[0])), x_stack)
    x0 = jax.tree.map(lambda a: a[0], x_stack)
    # probe with pp-varying inputs: stage params are varying inside the manual
    # region, so layer_apply's internal scans require varying carries
    aux_shapes = jax.eval_shape(lambda x: _apply(jax.tree.map(_vary, x))[1], x0)
    zero_aux = jax.tree.map(lambda s: _vary(jnp.zeros(s.shape, s.dtype)), aux_shapes)
    (outputs, _, aux_sum), _ = jax.lax.scan(tick, (outputs, state, zero_aux), jnp.arange(steps))
    if with_aux:
        return outputs, aux_sum
    return outputs


def make_pipeline_forward(mesh: Mesh, *, pp_axis: str = "pp", with_aux: bool = False,
                          aux_out_specs=None):
    """Wrap (embed, layer_apply, head_loss) into a pp-pipelined loss function.

    Returns ``fn(layer_params, other_params, batch_stack, embed_fn, layer_apply,
    head_loss_fn)`` where:
      - ``embed_fn(params, microbatch) -> x`` (stage-0 work, cheap enough to run
        everywhere: replicated compute beats a broadcast)
      - ``layer_apply(stage_layer_params, x) -> y`` scans this rank's layer slice
        (``-> (y, aux)`` with ``with_aux``: aux sums over valid ticks per stage;
        ``aux_out_specs`` — a pytree of PartitionSpecs matching aux, typically
        ``P(pp_axis)`` so per-stage layer stats reassemble in layer order)
      - ``head_loss_fn(params, y, microbatch) -> scalar`` final-norm + head + loss
        (additive across microbatches)

    Layer params must be stacked (L, ...) with the layer dim sharded over ``pp``
    (sharding rule "layers" -> pp); all other params replicated over pp.
    """
    pp = mesh.shape[pp_axis]

    def fn(layer_params, other_params, batch_stack, embed_fn, layer_apply, head_loss_fn):
        def body(layer_params, other_params, batch_stack):
            x_stack = jax.vmap(
                lambda mb: embed_fn(other_params, mb), in_axes=0
            )(batch_stack)
            outs = pipeline_spmd(
                layer_params, x_stack, layer_apply, axis=pp_axis, with_aux=with_aux
            )
            outs, aux = outs if with_aux else (outs, None)
            is_last = jax.lax.axis_index(pp_axis) == pp - 1
            # sequential over microbatches: only one microbatch's logits live at a
            # time (vmap would materialize n_micro full logits tensors at once,
            # forfeiting exactly the peak-memory win pipelining exists for)
            losses = jax.lax.map(
                lambda ymb: head_loss_fn(other_params, ymb[0], ymb[1]),
                (outs, batch_stack),
            )
            loss = jax.lax.psum(jnp.where(is_last, losses.sum(), 0.0), pp_axis)
            return (loss, aux) if with_aux else loss

        # Replicate non-layer params (embed/head/final-norm) before entering the
        # partial-manual region: a gather whose operand carries tp shardings trips
        # XLA's SpmdPartitioner (ExpandDeviceGroupsWithIota check) when pp is
        # manual. Embed/head tp-sharding inside the pp loop is a later optimization.
        from jax.sharding import NamedSharding

        other_params = jax.lax.with_sharding_constraint(
            other_params, NamedSharding(mesh, P())
        )
        layer_specs = jax.tree.map(lambda _: P(pp_axis), layer_params)
        other_specs = jax.tree.map(lambda _: P(), other_params)
        batch_specs = jax.tree.map(lambda _: P(), batch_stack)
        out_specs = (P(), aux_out_specs) if with_aux else P()
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(layer_specs, other_specs, batch_specs),
            out_specs=out_specs,
            axis_names={pp_axis},
        )(layer_params, other_params, batch_stack)

    return fn


def _make_head_loss(cfg, dtype):
    """Final-norm + unembed + additive masked CE, shared by both pp loss builders."""
    from automodel_tpu.ops.losses import masked_cross_entropy
    from automodel_tpu.ops.norms import rms_norm

    def head_loss(other, y, mb):
        h = rms_norm(y["h"], other["final_norm"].astype(dtype), cfg.rms_norm_eps)
        unembed = other.get("lm_head")
        if unembed is None:
            unembed = other["embed"].T
        logits = jnp.einsum("bsd,dv->bsv", h, jnp.asarray(unembed).astype(dtype))
        # additive (sum/num) microbatch losses, same contract as make_train_step
        return masked_cross_entropy(logits, mb["labels"], 1.0)

    return head_loss


def make_dense_decoder_pp_loss(model, mesh: Mesh, rules=None, loss_name: str = "masked_ce"):
    """Pipelined forward+loss for Llama-lineage models (the reference's PP covers HF
    decoder LMs the same way: embed on first stage, head+loss on last,
    recipes/llm/train_ft.py:1234-1242).

    Returns ``forward_loss(params, batch_stack, num_label_tokens)`` where
    ``batch_stack`` leaves are (n_micro, ...) — the pipeline consumes all
    microbatches in one call (grad accum *is* the pipeline schedule).
    """
    from automodel_tpu.models.common.transformer import apply_layer_stack

    cfg, backend = model.config, model.backend
    dtype = backend.jnp_dtype
    pipeline = make_pipeline_forward(mesh)

    def embed_fn(other, mb):
        h = other["embed"].astype(dtype)[mb["input_ids"]]
        return {"h": h, "positions": mb["positions"], "segment_ids": mb["segment_ids"]}

    # NB: no sharding-constraint rules inside the pp-manual region —
    # with_sharding_constraint over the full mesh clashes with manual pp axes;
    # GSPMD propagates dp/tp activation shardings from the params instead.
    del rules

    def layer_apply(stage, x):
        lp, sliding = stage
        return apply_layer_stack(cfg, backend, lp, sliding, x, None)

    head_loss = _make_head_loss(cfg, dtype)

    if loss_name != "masked_ce":
        raise NotImplementedError(f"pp loss {loss_name!r} (use masked_ce)")

    def forward_loss(params, batch_stack, num_label_tokens):
        sliding = jnp.asarray(cfg.sliding_flags, jnp.int32)
        layer_params = (params["layers"], sliding)
        other = {k: v for k, v in params.items() if k != "layers"}
        total = pipeline(layer_params, other, batch_stack,
                         embed_fn, layer_apply, head_loss)
        return total / num_label_tokens

    return forward_loss


def make_moe_pp_loss(model, mesh: Mesh, *, pp_axis: str = "pp", loss_name: str = "masked_ce",
                     seq_len_hint: int = 0):
    """Pipelined forward+loss for MoE decoders: the dense prefix + embedding run
    replicated on every rank (cheap, avoids a ragged first stage), the MoE layer
    stack pipelines over ``pp``, and expert-load stats accumulate per stage with
    warmup/drain ticks masked (reference composes PP with EP/FSDP inside each stage,
    infrastructure.py:107 -> autopipeline; here the ep/fsdp axes stay GSPMD-managed
    inside the pp-manual region).

    Returns ``forward_loss(params, batch_stack, num_label_tokens) ->
    (loss, {"expert_load": (num_moe_layers, E)})`` matching the MoE train-step
    contract (gate-bias balancing consumes expert_load). ``seq_len_hint``: the
    training sequence length, needed for the sliding-window disable bound.
    """
    from automodel_tpu.models.common.moe_transformer import make_moe_layer_fns

    cfg, backend = model.config, model.backend
    if cfg.moe.aux_loss_coeff > 0:
        raise NotImplementedError(
            "pp + aux-loss balancing is not wired; use gate-bias (loss-free) balancing"
        )
    if loss_name != "masked_ce":
        raise NotImplementedError(f"pp loss {loss_name!r} (use masked_ce)")
    dtype = backend.jnp_dtype
    attention_fn = model.make_attention_fn() if hasattr(model, "make_attention_fn") else None
    dense_layer_fn, moe_layer_fn = make_moe_layer_fns(
        cfg, backend, rules=None, attention_fn=attention_fn, training=True,
        seq_len_hint=seq_len_hint,
    )
    k_dense = cfg.first_k_dense_replace
    pipeline = make_pipeline_forward(
        mesh, pp_axis=pp_axis, with_aux=True, aux_out_specs={"load": P(pp_axis)}
    )

    def embed_fn(other, mb):
        h = other["embed"].astype(dtype)[mb["input_ids"]]
        state = {
            "h": h,
            "positions": mb["positions"],
            "segment_ids": mb["segment_ids"],
            "token_mask": mb["segment_ids"] != 0,
        }
        if k_dense > 0:
            sliding = jnp.asarray(cfg.sliding_flags[:k_dense], jnp.int32)
            state, _ = jax.lax.scan(
                backend.layer_remat(dense_layer_fn), state, (other["dense_layers"], sliding)
            )
        return state

    def layer_apply(stage, state):
        lp_stack, sliding = stage
        state, (_auxs, loads) = jax.lax.scan(
            backend.layer_remat(moe_layer_fn), state, (lp_stack, sliding)
        )
        return state, {"load": loads}

    head_loss = _make_head_loss(cfg, dtype)

    def forward_loss(params, batch_stack, num_label_tokens):
        moe_sliding = jnp.asarray(cfg.sliding_flags[k_dense:], jnp.int32)
        layer_params = (params["moe_layers"], moe_sliding)
        other = {k: v for k, v in params.items() if k != "moe_layers"}
        loss, aux = pipeline(layer_params, other, batch_stack,
                             embed_fn, layer_apply, head_loss)
        return loss / num_label_tokens, {"expert_load": aux["load"]}

    return forward_loss
