"""Always-on JSONL metric streams (reference loggers/metric_logger.py:27,83).

One JSONL file per stream (``training.jsonl``, ``validation.jsonl``); each line is a
flat dict of step metrics. Main process writes; other hosts no-op.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Any, IO

import jax

__all__ = ["MetricsSample", "MetricLogger", "build_run_header"]


def _episode_from_env() -> dict[str, Any]:
    """Episode identity exported by the supervisor (resilience/supervisor.py
    EPISODE_ENV — literal duplicated here because importing the resilience
    package would pull the heavy manager into every logger user). Stamped into
    the run header and every metric row so the multi-episode training.jsonl
    segments are attributable without filename archaeology."""
    raw = os.environ.get("AUTOMODEL_EPISODE")
    if not raw:
        return {}
    try:
        doc = json.loads(raw)
    except json.JSONDecodeError:
        return {}
    if not isinstance(doc, dict):
        return {}
    out: dict[str, Any] = {}
    if isinstance(doc.get("index"), int):
        out["episode"] = doc["index"]
    if isinstance(doc.get("run_id"), str):
        out["run_id"] = doc["run_id"]
    return out


def build_run_header(cfg: Any = None, mesh: Any = None, model_id: str | None = None,
                     **extra: Any) -> dict[str, Any]:
    """The one-time run-header row: everything needed to join a training.jsonl
    to a bench baseline or another run — git sha, jax/jaxlib versions, mesh
    axis sizes, model id, and a digest of the full config. Every field is
    best-effort; a missing git checkout must not block training."""
    import hashlib
    import subprocess

    import jaxlib

    header: dict[str, Any] = {
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib.__version__,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": jax.device_count(),
        "process_count": jax.process_count(),
    }
    try:
        header["git_sha"] = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        ).stdout.strip() or None
    except Exception:
        header["git_sha"] = None
    if mesh is not None:
        header["mesh"] = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    if model_id is not None:
        header["model_id"] = str(model_id)
    if cfg is not None:
        raw = cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg)
        digest = hashlib.sha256(
            json.dumps(raw, sort_keys=True, default=str).encode()
        ).hexdigest()
        header["config_digest"] = digest[:16]
    header.update(extra)
    return header


@dataclasses.dataclass
class MetricsSample:
    step: int
    metrics: dict[str, Any]
    timestamp: float = dataclasses.field(default_factory=time.time)

    def to_json(self) -> str:
        rec = {"step": self.step, "ts": round(self.timestamp, 3)}
        for k, v in self.metrics.items():
            v, nonfinite = _jsonable(v)
            rec[k] = v
            if nonfinite:
                # a NaN loss row must stay machine-readable: the value itself
                # becomes null (bare NaN/Infinity is invalid JSON and breaks
                # every json.loads consumer) and the flag records what happened
                rec[f"{k}_nonfinite"] = True
        # allow_nan=False: any non-finite float that slips past _jsonable fails
        # loudly here instead of corrupting the stream
        return json.dumps(rec, allow_nan=False)


def _jsonable(v: Any) -> tuple[Any, bool]:
    """(json-safe value, had-nonfinite-floats) — non-finite floats become None."""
    ndim = getattr(v, "ndim", None)
    if ndim == 0:
        v = v.item()
    elif ndim is not None and hasattr(v, "tolist"):
        v = v.tolist()
    if isinstance(v, float):
        if not math.isfinite(v):
            return None, True
        return round(v, 6), False
    if isinstance(v, (list, tuple)):
        items = [_jsonable(x) for x in v]
        return [x for x, _ in items], any(nf for _, nf in items)
    return v, False


class MetricLogger:
    """Append-only JSONL writer, flushed per line so tail -f works mid-run."""

    def __init__(self, path: str | os.PathLike, main_process_only: bool = True):
        self.path = str(path)
        self._fh: IO[str] | None = None
        self._episode = _episode_from_env()
        self.enabled = not main_process_only or jax.process_index() == 0
        if self.enabled:
            os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
            self._fh = open(self.path, "a")

    def log(self, step: int, **metrics: Any) -> None:
        if not self.enabled or self._fh is None:
            return
        if "episode" in self._episode:
            metrics = {"episode": self._episode["episode"], **metrics}
        self._fh.write(MetricsSample(step=step, metrics=metrics).to_json() + "\n")
        self._fh.flush()

    def log_header(self, **fields: Any) -> None:
        """One-time run-header row (``{"run_header": true, ...}``) making the
        stream self-describing; consumers filter metric rows by the presence
        of their metric keys (or absence of ``run_header``)."""
        if not self.enabled or self._fh is None:
            return
        rec: dict[str, Any] = {"run_header": True, "ts": round(time.time(), 3),
                               **self._episode}
        for k, v in fields.items():
            rec[k] = _jsonable(v)[0] if not isinstance(v, dict) else v
        self._fh.write(json.dumps(rec, allow_nan=False, default=str) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "MetricLogger":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
