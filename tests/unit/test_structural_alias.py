"""Structural auto-aliasing of unregistered HF architectures (VERDICT r3 #3).

The reference wraps ANY HF class day-0 (_transformers/model_init.py:89); the
torch-free equivalent maps llama-delta configs onto the dense-decoder lineage
after a per-field structural check. Both directions are pinned here against
the REAL transformers implementations (baked into the image):

- architectures that alias must match transformers logits bit-close at fp32;
- architectures that diverge must fail NAMING the divergent field;
- architectures whose divergence is code-only (invisible in config fields)
  must be caught by the curated denylist.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from automodel_tpu.models.auto import AutoModelForCausalLM
from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.structural import (
    StructuralDivergence, classify_config, resolve_llama_delta,
)

TINY = dict(vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64)


def _hf_config(arch: str, **kw) -> dict:
    cls = getattr(transformers, arch)
    hf = cls.config_class(**kw).to_dict()
    hf["architectures"] = [arch]
    return hf


def _parity(arch: str, **kw) -> float:
    """Max relative logits error between the aliased jax model and transformers."""
    cls = getattr(transformers, arch)
    tcfg = cls.config_class(**kw)
    hf = tcfg.to_dict()
    hf["architectures"] = [arch]
    torch.manual_seed(0)
    tm = cls(tcfg).eval()
    sd = {k: v.float().numpy() for k, v in tm.state_dict().items()}
    am = AutoModelForCausalLM.from_config(hf, backend=BackendConfig(dtype="float32"))
    import jax

    params = jax.tree.map(np.asarray, am.state_dict_adapter().from_hf(sd, dtype=np.float32))
    ids = np.arange(1, 17)[None, :] % hf["vocab_size"]
    with torch.no_grad():
        tlog = tm(torch.tensor(ids)).logits.numpy()
    jlog = np.asarray(am(params, ids))
    return float(np.abs(tlog - jlog).max() / (np.abs(tlog).max() + 1e-9))


class TestAliasedParity:
    def test_unknown_arch_with_llama_fields_aliases_and_matches(self):
        """A brand-new arch name over pure llama fields — the day-0 case the
        feature exists for; parity vs transformers' own LlamaForCausalLM."""
        cls = transformers.LlamaForCausalLM
        tcfg = cls.config_class(**TINY, rope_theta=50000.0, tie_word_embeddings=True)
        hf = tcfg.to_dict()
        hf["architectures"] = ["BrandNewLlamaDeltaForCausalLM"]
        torch.manual_seed(0)
        tm = cls(tcfg).eval()
        sd = {k: v.float().numpy() for k, v in tm.state_dict().items()}
        am = AutoModelForCausalLM.from_config(hf, backend=BackendConfig(dtype="float32"))
        import jax

        params = jax.tree.map(np.asarray, am.state_dict_adapter().from_hf(sd, dtype=np.float32))
        ids = np.arange(1, 17)[None, :] % hf["vocab_size"]
        with torch.no_grad():
            tlog = tm(torch.tensor(ids)).logits.numpy()
        jlog = np.asarray(am(params, ids))
        err = np.abs(tlog - jlog).max() / np.abs(tlog).max()
        assert err < 2e-5, f"rel logits err {err:.2e}"

    def test_helium_aliases_with_interleaved_rope(self):
        err = _parity("HeliumForCausalLM", **TINY, head_dim=8)
        assert err < 2e-5, f"rel logits err {err:.2e}"

    def test_ernie45_aliases_with_interleaved_rope(self):
        err = _parity("Ernie4_5ForCausalLM", **TINY)
        assert err < 2e-5, f"rel logits err {err:.2e}"


class TestHonestDivergence:
    """Divergent architectures fail NAMING the structural field, never silently."""

    @pytest.mark.parametrize("arch,kw,expect", [
        # starcoder2/stablelm/olmo-v1 graduated in round 5; these still diverge
        ("ApertusForCausalLM", {}, "hidden_act"),            # xIELU
        ("StableLmForCausalLM", {"qk_layernorm": True}, "qk_layernorm"),
        ("Starcoder2ForCausalLM", {"hidden_act": "relu"}, "hidden_act"),
    ])
    def test_divergent_arch_fails_naming_field(self, arch, kw, expect):
        hf = _hf_config(arch, **TINY, **kw)
        with pytest.raises(KeyError, match=expect):
            AutoModelForCausalLM.from_config(hf)

    def test_denylist_mechanism(self, monkeypatch):
        # every real entry graduated to a family in round 4; pin the mechanism
        # itself so the next config-invisible code divergence can use it
        from automodel_tpu.models import structural

        monkeypatch.setitem(structural._DENYLIST, "WeirdBlockForCausalLM",
                            "block code differs despite llama-shaped fields")
        hf = _hf_config("LlamaForCausalLM", **TINY)
        hf["architectures"] = ["WeirdBlockForCausalLM"]
        with pytest.raises(StructuralDivergence, match="WeirdBlock"):
            resolve_llama_delta("WeirdBlockForCausalLM", hf)

    def test_unsupported_rope_scaling_variant_named(self):
        hf = _hf_config("LlamaForCausalLM", **TINY)
        hf["architectures"] = ["SomeNewForCausalLM"]
        hf["rope_scaling"] = {"rope_type": "su_exotic", "factor": 4.0}
        with pytest.raises(StructuralDivergence, match="rope_scaling"):
            resolve_llama_delta("SomeNewForCausalLM", hf)

    def test_non_causal_arch_refused(self):
        with pytest.raises(StructuralDivergence, match="ForCausalLM"):
            resolve_llama_delta("SomeBertModel", dict(TINY, rms_norm_eps=1e-5))


class TestGraduatedFamilies:
    """Families that graduated from honest-fail to registered llama-lineage
    deltas in round 4: Granite (mup scalars), SmolLM3 (NoPE layers), Olmo2/3
    (post-norm blocks + whole-projection qk-RMSNorm, Olmo3 adds sliding).
    Logits parity vs the real transformers implementations."""

    def _parity(self, arch, **kw):
        cls = getattr(transformers, arch)
        tcfg = cls.config_class(**{**TINY, "pad_token_id": 0, **kw})
        hf = tcfg.to_dict()
        hf["architectures"] = [arch]
        torch.manual_seed(0)
        tm = cls(tcfg).eval()
        sd = {k: v.float().numpy() for k, v in tm.state_dict().items()}
        am = AutoModelForCausalLM.from_config(hf, backend=BackendConfig(dtype="float32"))
        import jax

        params = jax.tree.map(np.asarray,
                              am.state_dict_adapter().from_hf(sd, dtype=np.float32))
        ids = np.arange(1, 17)[None, :] % hf["vocab_size"]
        with torch.no_grad():
            tlog = tm(torch.tensor(ids)).logits.numpy()
        jlog = np.asarray(am(params, ids))
        err = float(np.abs(tlog - jlog).max() / np.abs(tlog).max())
        assert err < 2e-5, f"{arch} rel logits err {err:.2e}"

    def test_granite_mup_scalars(self):
        # granite-3-class non-trivial values: every scalar must actually bite
        self._parity("GraniteForCausalLM", embedding_multiplier=12.0,
                     residual_multiplier=0.22, attention_multiplier=0.015625,
                     logits_scaling=8.0, tie_word_embeddings=True)

    def test_smollm3_nope_layers(self):
        self._parity("SmolLM3ForCausalLM", num_hidden_layers=4)  # layer 4 = NoPE

    def test_olmo2_post_norm_whole_qk(self):
        self._parity("Olmo2ForCausalLM", num_hidden_layers=4)

    def test_olmo3_adds_sliding(self):
        self._parity("Olmo3ForCausalLM", num_hidden_layers=4, sliding_window=8)

    def test_arcee_ungated_relu2_mlp(self):
        self._parity("ArceeForCausalLM")

    def test_glm4_sandwich_norms_partial_interleaved_rope(self):
        self._parity("Glm4ForCausalLM")  # defaults: partial_rotary 0.5, sandwich

    def test_old_glm_no_sandwich(self):
        # glm-4-9b-chat-hf lineage: same family minus the sandwich norms
        self._parity("GlmForCausalLM")

    # -- round-5 graduations (previously named-fail archs) -------------------

    def test_olmo_v1_nonparam_layernorm(self):
        # the whole point: LayerNorm with NO learnable weight/bias, eps pinned
        # in code; clip_qkv exercises the clamp branch with a biting value
        self._parity("OlmoForCausalLM", clip_qkv=0.08)

    def test_olmo_v1_without_clip(self):
        self._parity("OlmoForCausalLM")

    def test_starcoder2_ln_bias_gelu_mqa(self):
        # affine LN (weight+bias), ungated c_fc/c_proj tanh-gelu MLP, biases on
        # every linear, tied embeddings — all defaults of the real config
        self._parity("Starcoder2ForCausalLM")

    def test_starcoder2_no_bias_variant(self):
        self._parity("Starcoder2ForCausalLM", use_bias=False)

    def test_stablelm_partial_rope_ln(self):
        # partial_rotary_factor 0.25 default + affine LN + qkv bias
        self._parity("StableLmForCausalLM", use_qkv_bias=True)

    def test_stablelm_parallel_residual(self):
        # stablelm-alpha style: x + attn(ln(x)) + mlp(ln(x)) with ONE norm
        self._parity("StableLmForCausalLM", use_parallel_residual=True)

    def test_glm4_fused_gate_up_roundtrip(self):
        """to_hf re-fuses gate|up into mlp.gate_up_proj and from_hf splits it
        back — bit-exact roundtrip (the export path HF loading depends on)."""
        import jax

        from automodel_tpu.models.glm4.model import Glm4ForCausalLM

        hf = {**TINY, "architectures": ["Glm4ForCausalLM"],
              "partial_rotary_factor": 0.5, "rms_norm_eps": 1e-5}
        am = AutoModelForCausalLM.from_config(hf, backend=BackendConfig(dtype="float32"))
        assert isinstance(am, Glm4ForCausalLM)
        params = am.init(jax.random.key(0))
        adapter = am.state_dict_adapter()
        sd = adapter.to_hf(params)
        assert "model.layers.0.mlp.gate_up_proj.weight" in sd
        assert "model.layers.0.mlp.gate_proj.weight" not in sd
        assert "model.layers.0.post_self_attn_layernorm.weight" in sd
        back = adapter.from_hf(sd, dtype=np.float32)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_cohere_parallel_block_logit_scale(self):
        # mean-centered LN + parallel attn||mlp + interleaved rope + logit_scale
        self._parity("CohereForCausalLM", logit_scale=0.0625)

    def test_cohere_plus_per_head_qk_layernorm(self):
        self._parity("CohereForCausalLM", logit_scale=0.0625, use_qk_norm=True)

    def test_cohere2_sliding_pattern_nope_full_layers(self):
        # rope only on sliding layers; full-attention layers are NoPE
        self._parity("Cohere2ForCausalLM", logit_scale=0.0625,
                     num_hidden_layers=4, sliding_window=8,
                     sliding_window_pattern=4)

    def test_cohere2_raw_hub_config_format(self):
        """Original R7B config.json carries an integer sliding_window_pattern
        and NO layer_types — the derivation must mirror Cohere2Config's BC
        branch or every layer silently ropes/slides wrong."""
        import jax

        cls = transformers.Cohere2ForCausalLM
        tcfg = cls.config_class(**{**TINY, "pad_token_id": 0,
                                   "num_hidden_layers": 4, "logit_scale": 0.0625,
                                   "sliding_window": 8,
                                   "sliding_window_pattern": 4})
        hf = tcfg.to_dict()
        hf["architectures"] = ["Cohere2ForCausalLM"]
        hf.pop("layer_types", None)
        hf["sliding_window_pattern"] = 4
        torch.manual_seed(0)
        tm = cls(tcfg).eval()
        sd = {k: v.float().numpy() for k, v in tm.state_dict().items()}
        am = AutoModelForCausalLM.from_config(hf, backend=BackendConfig(dtype="float32"))
        params = jax.tree.map(np.asarray,
                              am.state_dict_adapter().from_hf(sd, dtype=np.float32))
        ids = np.arange(1, 17)[None, :] % 128
        with torch.no_grad():
            tlog = tm(torch.tensor(ids)).logits.numpy()
        jlog = np.asarray(am(params, ids))
        err = float(np.abs(tlog - jlog).max() / np.abs(tlog).max())
        assert err < 2e-5, f"raw-format cohere2 rel err {err:.2e}"


def test_registry_error_carries_alias_failure():
    """The combined error names both the registry miss and the divergent field."""
    hf = _hf_config("ApertusForCausalLM", **TINY)
    with pytest.raises(KeyError) as ei:
        AutoModelForCausalLM.from_config(hf)
    msg = str(ei.value)
    assert "not supported" in msg and "hidden_act" in msg
