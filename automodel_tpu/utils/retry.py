"""Transient-fault retry with exponential backoff + jitter.

Long-horizon TPU runs touch remote services (HF Hub, streaming datasets,
object-store checkpoints) thousands of times; each touch is a chance for a
transient network/filesystem hiccup to kill a thousand-chip job. The reference
AutoModel treats these as expected (its loaders retry hub and storage I/O);
here one decorator owns the policy so every remote touch in the tree —
``models/hub.py`` snapshot downloads, ``data/llm/iterable.py`` streaming
access, ``checkpoint/safetensors_io.py`` reads, and the Orbax save/restore
calls in ``checkpoint/checkpointing.py`` — shares the same backoff curve and
exception allowlist (docs/resilience.md).

Only *transient* failures retry: the default allowlist is connection/timeout/
OS-level errors plus a by-name set covering huggingface_hub/requests errors
without importing either. Anything else (corrupt file, auth failure, bug)
raises immediately — retrying those just delays the real traceback.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
import random
import socket
import time
import zlib
from typing import Any, Callable, Iterable, TypeVar

logger = logging.getLogger(__name__)

__all__ = ["RetryConfig", "retry", "with_retry", "is_transient",
           "host_jitter_seed"]

F = TypeVar("F", bound=Callable[..., Any])

# transient exception *names* from libraries we must not import at module
# scope (huggingface_hub, requests, aiohttp, fsspec); matched against the
# exception's MRO so subclasses count
_TRANSIENT_NAMES = frozenset({
    "ConnectionError", "Timeout", "TimeoutError", "ReadTimeout",
    "ConnectTimeout", "ChunkedEncodingError", "HfHubHTTPError",
    "LocalEntryNotFoundError", "IncompleteRead", "ProtocolError",
    "TemporaryFailure", "ServerDisconnectedError",
})


def host_jitter_seed(ident: str | None = None) -> int:
    """Deterministic per-host jitter seed.

    When every worker of a pod dies together (runtime restart, pod-wide
    preemption), module-global ``random`` gives each host a jitter drawn from
    the SAME default-seeded state only when the processes happen to diverge —
    and identical container images with identical startup paths often don't,
    so the retries land simultaneously and thundering-herd the TPU runtime.
    Seeding from the hostname decorrelates hosts *deterministically*: the same
    host replays the same delay curve across restarts (reproducible, log-
    diffable), while different hosts spread out. ``AUTOMODEL_RETRY_SEED``
    overrides the identity for tests and for multi-worker-per-host layouts.
    """
    if ident is None:
        ident = os.environ.get("AUTOMODEL_RETRY_SEED") or socket.gethostname()
    return zlib.crc32(str(ident).encode())


_host_rng = random.Random(host_jitter_seed())


@dataclasses.dataclass(frozen=True)
class RetryConfig:
    """Backoff policy: delay_n = min(base * mult**n, max_delay) * U(1-j, 1+j).

    The jitter factor is drawn from a per-host deterministically seeded RNG
    (:func:`host_jitter_seed`), so delays always stay inside the
    ``[d*(1-j), d*(1+j)]`` envelope, hosts decorrelate, and a given host's
    curve is reproducible run to run.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.25  # +/- fraction of the computed delay

    @classmethod
    def from_dict(cls, raw: Any) -> "RetryConfig":
        if raw is None:
            return cls()
        if hasattr(raw, "to_dict"):
            raw = raw.to_dict()
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in dict(raw).items() if k in known})

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        """Seconds to sleep before retry number ``attempt`` (0-based)."""
        d = min(self.base_delay_s * self.multiplier**attempt, self.max_delay_s)
        if self.jitter:
            r = rng if rng is not None else _host_rng
            d *= 1.0 + r.uniform(-self.jitter, self.jitter)
        return max(d, 0.0)


def is_transient(exc: BaseException, extra: Iterable[type] = ()) -> bool:
    """True when ``exc`` is on the transient allowlist (by type or MRO name)."""
    if isinstance(exc, (ConnectionError, TimeoutError, *tuple(extra))):
        return True
    # OSError covers EIO/ENETDOWN-style blips, but FileNotFoundError/IsADirectory
    # etc. are deterministic — retrying them only hides real bugs
    if isinstance(exc, OSError) and not isinstance(
        exc, (FileNotFoundError, NotADirectoryError, IsADirectoryError, PermissionError)
    ):
        return True
    return any(t.__name__ in _TRANSIENT_NAMES for t in type(exc).__mro__)


def with_retry(
    fn: Callable[..., Any],
    *args: Any,
    config: RetryConfig | None = None,
    retry_on: Iterable[type] = (),
    description: str | None = None,
    sleep: Callable[[float], None] = time.sleep,
    **kwargs: Any,
) -> Any:
    """Call ``fn(*args, **kwargs)``, retrying transient failures per ``config``."""
    cfg = config or RetryConfig()
    extra = tuple(retry_on)
    what = description or getattr(fn, "__qualname__", repr(fn))
    last: BaseException | None = None
    for attempt in range(max(int(cfg.max_attempts), 1)):
        try:
            return fn(*args, **kwargs)
        except BaseException as exc:  # noqa: BLE001 - filtered just below
            if not is_transient(exc, extra):
                raise
            last = exc
            if attempt + 1 >= cfg.max_attempts:
                break
            d = cfg.delay(attempt)
            logger.warning(
                "transient failure in %s (attempt %d/%d): %s — retrying in %.1fs",
                what, attempt + 1, cfg.max_attempts, exc, d,
            )
            sleep(d)
    assert last is not None
    raise last


def retry(
    config: RetryConfig | None = None,
    *,
    retry_on: Iterable[type] = (),
    sleep: Callable[[float], None] = time.sleep,
) -> Callable[[F], F]:
    """Decorator form of :func:`with_retry`.

    >>> @retry(RetryConfig(max_attempts=5))
    ... def fetch(url): ...
    """

    def deco(fn: F) -> F:
        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            return with_retry(
                fn, *args, config=config, retry_on=retry_on,
                description=getattr(fn, "__qualname__", None), sleep=sleep, **kwargs,
            )

        return wrapper  # type: ignore[return-value]

    return deco
