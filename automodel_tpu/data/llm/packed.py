"""Sequence packing (reference datasets/llm/packed_sequence.py:202 pack_dataset).

The reference carries packed batches in THD layout with ``seq_lens``/``seq_lens_padded``
metadata threaded through a custom collater and TE varlen attention
(distributed/thd_utils.py). TPU-native, the whole apparatus reduces to *segment ids*:
each pack is a fixed-length row whose tokens carry the 1-based index of the sequence
they came from (0 = padding), attention masks across segment boundaries
(ops/attention.py), RoPE positions restart per sequence, and every shape stays static
for jit. No variable-length metadata survives past the data loader.

Per-sample processing matches ``sft_collate``: the next-token shift happens *within*
each sample before concatenation, so the last token of one sample never predicts the
first token of the next (the reference gets the same guarantee from label padding).

The reference pads each sequence to a multiple of ``2 * cp_size`` for TE's THD ring
chunking (packed_sequence.py:269). Here that padding is *unnecessary*: ring attention
masks by traveling positions/segment ids (parallel/ring_attention.py), so segment
boundaries need no chunk alignment — only the pack length itself must divide the cp
shard count, which the recipe validates. Packs are materialized up front, the same
contract as the reference's pack_dataset (it also builds the full pack list in
memory); bound working set with ``max_packs`` for huge corpora.
"""

from __future__ import annotations

import logging
from typing import Any, Iterable, Mapping, Sequence

import numpy as np

from automodel_tpu.data.collate import IGNORE_INDEX, shift_example, stack_batches

logger = logging.getLogger(__name__)

__all__ = ["PackedDataset", "pack_dataset", "packed_collate"]


class PackedDataset:
    """Materialized list of fixed-length packs, each a collate-ready example dict."""

    def __init__(self, packs: list[dict[str, np.ndarray]], packed_sequence_size: int):
        self.packs = packs
        self.packed_sequence_size = packed_sequence_size

    def __len__(self) -> int:
        return len(self.packs)

    def __getitem__(self, idx: int) -> dict[str, np.ndarray]:
        return self.packs[idx]


def pack_dataset(
    dataset: Iterable[Mapping[str, Any]],
    packed_sequence_size: int,
    pad_token_id: int = 0,
    max_packs: int | None = None,
    drop_long_samples: bool = False,
    answer_only_loss: bool = True,
) -> PackedDataset:
    """Greedy first-fit packing: fill each pack until the next sample won't fit.

    Mirrors the reference's buffer loop (packed_sequence.py:202) with the same knobs;
    sequences longer than ``packed_sequence_size`` raise unless ``drop_long_samples``.
    """
    if packed_sequence_size <= 0:
        raise ValueError(f"packed_sequence_size must be positive, got {packed_sequence_size}")

    packs: list[dict[str, np.ndarray]] = []
    buf_ids: list[np.ndarray] = []
    buf_labels: list[np.ndarray] = []
    buf_pos: list[np.ndarray] = []
    buf_seg: list[np.ndarray] = []
    used = 0
    n_dropped = 0

    def flush():
        nonlocal used
        if not buf_ids or (max_packs is not None and len(packs) >= max_packs):
            return
        ids = np.concatenate(buf_ids)
        tail = packed_sequence_size - len(ids)
        pack = {
            "input_ids": np.concatenate([ids, np.full(tail, pad_token_id, np.int32)]),
            "labels": np.concatenate([np.concatenate(buf_labels), np.full(tail, IGNORE_INDEX, np.int32)]),
            "positions": np.concatenate([np.concatenate(buf_pos), np.zeros(tail, np.int32)]),
            "segment_ids": np.concatenate([np.concatenate(buf_seg), np.zeros(tail, np.int32)]),
        }
        packs.append(pack)
        buf_ids.clear(); buf_labels.clear(); buf_pos.clear(); buf_seg.clear()
        used = 0

    # map-style datasets may index modulo their length (mock datasets do); iterate
    # exactly len() items rather than relying on IndexError termination
    if hasattr(dataset, "__len__") and hasattr(dataset, "__getitem__"):
        sample_iter = (dataset[i] for i in range(len(dataset)))
    else:
        sample_iter = iter(dataset)
    for ex in sample_iter:
        if max_packs is not None and len(packs) >= max_packs:
            break
        inp, tgt = shift_example(ex, answer_only_loss)
        n = len(inp)
        if n == 0:
            continue
        if n > packed_sequence_size:
            if drop_long_samples:
                n_dropped += 1
                continue
            raise ValueError(
                f"sample is too long ({n} > packed_sequence_size {packed_sequence_size}); "
                "increase packed_sequence_size or set drop_long_samples"
            )
        if used + n > packed_sequence_size:
            flush()
        seg = len(buf_ids) + 1
        buf_ids.append(np.asarray(inp, np.int32))
        buf_labels.append(np.asarray(tgt, np.int32))
        buf_pos.append(np.arange(n, dtype=np.int32))
        buf_seg.append(np.full(n, seg, np.int32))
        used += n

    flush()
    if n_dropped:
        logger.warning("pack_dataset dropped %d over-length samples", n_dropped)
    if not packs:
        raise ValueError("pack_dataset produced no packs (empty dataset?)")
    return PackedDataset(packs, packed_sequence_size)


def packed_collate(examples: Sequence[Mapping[str, np.ndarray]]) -> dict[str, np.ndarray]:
    """Packs are pre-collated rows; a batch is just a stack."""
    return stack_batches(examples)
