from automodel_tpu.models.deepseek_v32.model import DeepseekV32Config, DeepseekV32ForCausalLM

__all__ = ["DeepseekV32Config", "DeepseekV32ForCausalLM"]
