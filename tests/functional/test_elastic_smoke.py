"""Pytest entry for the elastic smoke (tools/elastic_smoke.py,
docs/resilience.md "Elastic restore & warm restart").

Marked ``elastic`` + ``slow`` so it stays out of the tier-1 ``-m 'not slow'``
suite; run explicitly with ``pytest -m elastic``. Each training phase runs in
its own subprocess pinned to a different virtual-device count — the one
scenario the in-process coverage (tests/functional/test_elastic.py) cannot
exercise.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "tools"))


@pytest.mark.elastic
@pytest.mark.slow
def test_elastic_smoke(tmp_path):
    import elastic_smoke

    assert elastic_smoke.main(str(tmp_path)) == 0
