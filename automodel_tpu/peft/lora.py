"""LoRA / DoRA for pure-pytree models (reference components/_peft/lora.py:42,76 and
module_matcher.py ModuleMatcher).

TPU-native design: instead of wrapping ``nn.Linear`` modules, LoRA is a *second param
pytree* mirroring the subset of base weights it adapts — each matched leaf ``W``
becomes ``{"lora_a": (*stack, fan_in, r), "lora_b": (*stack, r, fan_out)}``. The
forward pass is unchanged: :func:`merge_lora_params` computes
``W + (alpha/r) * A @ B`` inside jit, XLA fuses the rank-r update into the surrounding
compute, and under a layer-``scan`` only one layer's delta is ever materialized.
Freezing the base model is not a flag on modules but simply *which tree you
differentiate*: the train step takes grads w.r.t. the LoRA tree only, so optimizer
state is rank-r sized (the reference freezes via requires_grad, lora.py:335).

Weights are matched by dot-joined pytree paths (``layers.wq``, ``moe_layers.moe.
experts.gate_up_proj``) with the reference's wildcard semantics; HF-style module
names (``q_proj`` …) are aliased so reference YAML recipes work verbatim.

DoRA (use_dora): ``W' = m * (W + ΔW) / ||W + ΔW||_col`` with the magnitude vector
``m`` initialized to column norms of ``W`` (reference lora.py:196-200).
"""

from __future__ import annotations

import dataclasses
import math
import re
import zlib
from typing import Any, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "PeftConfig",
    "wildcard_match",
    "match_lora_paths",
    "init_lora_params",
    "lora_logical_axes",
    "lora_merged_loss",
    "merge_lora_params",
    "count_lora_params",
]


def lora_merged_loss(core, get_base, cfg: "PeftConfig", use_dropout: bool):
    """Close :func:`merge_lora_params` over a loss core with the right arity.

    Every recipe's PEFT step is "merge the adapter into (a view of) the frozen
    base, then call the real loss" — and with ``cfg.dropout`` the step grows a
    trailing rng argument. This factory is the ONE place that shape lives
    (train_ft / kd / vlm, pp and not, all route through it):

    - ``core(merged, frozen, *rest)`` — the actual forward+loss;
    - ``get_base(frozen)`` — extracts the adapter's base tree from the step's
      frozen argument (the base itself, ``frozen["base"]``, ...).

    Returns ``f(lora, frozen, *rest)`` or — when ``use_dropout`` —
    ``f(lora, frozen, *rest, rng)`` matching ``make_train_step(pass_rng=True)``.
    """
    if use_dropout:
        def f(lora, frozen, *rest_and_rng):
            *rest, rng = rest_and_rng
            merged = merge_lora_params(get_base(frozen), lora, cfg, dropout_rng=rng)
            return core(merged, frozen, *rest)
    else:
        def f(lora, frozen, *rest):
            merged = merge_lora_params(get_base(frozen), lora, cfg)
            return core(merged, frozen, *rest)
    return f

# Reference YAMLs name HF modules (q_proj, ...); map them onto our leaf names so
# `target_modules: [q_proj, v_proj]` matches `layers.wq` / `layers.wv`.
_HF_NAME_ALIASES = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
    "gate_proj": "w_gate",
    "up_proj": "w_up",
    "down_proj": "w_down",
    "linear_qkv": "wq|wk|wv",
    "linear_proj": "wo",
    "linear_fc1": "w_gate|w_up",
    "linear_fc2": "w_down",
}

# Logical axes that stack independent weight matrices along a leading dim; LoRA
# factors apply per stacked element (layer scan dim, expert dim).
_STACK_AXES = ("layers", "expert")

# Leaves that are never linear projections, whatever their shape.
_NEVER_MATCH = ("embed",)


@dataclasses.dataclass
class PeftConfig:
    """Reference PeftConfig (_peft/lora.py:42) minus torch-only knobs."""

    target_modules: list[str] = dataclasses.field(
        default_factory=lambda: ["*wq", "*wk", "*wv", "*wo", "*w_gate", "*w_up", "*w_down"]
    )
    exclude_modules: list[str] = dataclasses.field(default_factory=list)
    match_all_linear: bool = False
    dim: int = 8
    alpha: int = 32
    use_dora: bool = False
    # NOTE semantic difference vs the reference: reference nn.Dropout acts per
    # activation element of x (per token, per feature, per step); here the merged-
    # delta formulation draws ONE mask over A's input-feature rows per step
    # (varying per layer-stack entry via a.shape[:-1]), shared across all tokens
    # in the step. Expectation matches, regularization is coarser — configs
    # ported from the reference may want a smaller value.
    dropout: float = 0.0
    lora_A_init: str = "xavier"  # "xavier" | "uniform" | "gaussian"
    lora_dtype: str | None = None  # None = base-weight dtype

    def __post_init__(self):
        if isinstance(self.target_modules, str):
            self.target_modules = [self.target_modules]
        if isinstance(self.exclude_modules, str):
            self.exclude_modules = [self.exclude_modules]
        if not 0.0 <= self.dropout < 1.0:
            raise ValueError(f"lora dropout must be in [0, 1), got {self.dropout}")

    @property
    def scaling(self) -> float:
        return self.alpha / self.dim

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PeftConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def wildcard_match(pattern: str, key: str | None) -> bool | None:
    """Reference wildcard_match (module_matcher.py): '*' spans any chars."""
    if key is None:
        return None
    regex = re.compile("^" + re.escape(pattern).replace(r"\*", "(.*)") + "$")
    return regex.match(key) is not None


def _normalize_patterns(patterns: Sequence[str]) -> list[str]:
    out = []
    for p in patterns:
        leafname = p.split(".")[-1]
        alias = _HF_NAME_ALIASES.get(leafname)
        if alias is not None:
            prefix = p[: len(p) - len(leafname)]
            out.extend(prefix + a for a in alias.split("|"))
        else:
            out.append(p)
        # bare module names ("q_proj") mean "anywhere in the tree"
    return [p if p.startswith("*") or "." in p else "*" + p for p in out]


def _split_point(axes: Sequence[str | None]) -> int:
    """Index separating fan-in dims from fan-out dims, after stack dims.

    Projections out of the residual stream / rank bottlenecks contract their first
    dim; attention-output projections contract (heads, head_dim).
    """
    return 2 if axes and axes[0] in ("heads", "kv_heads") else 1


def _leaf_structure(path: str, axes: tuple) -> tuple[int, int] | None:
    """(n_stack, split) for a LoRA-able leaf, or None if not a linear weight."""
    name = path.split(".")[-1]
    n_stack = 0
    while n_stack < len(axes) and axes[n_stack] in _STACK_AXES:
        n_stack += 1
    body = axes[n_stack:]
    if len(body) < 2:  # norms, sinks: not matrices
        return None
    if name in _NEVER_MATCH:
        return None
    if any(a == "norm" for a in body):
        return None
    split = _split_point(body)
    if split >= len(body):
        # no fan-out dims left: a (heads, head_dim)-shaped *bias* (bq/bk/bv), not a
        # projection — the reference never adapts biases (module_matcher matches
        # nn.Linear modules, whose bias rides along unadapted)
        return None
    return n_stack, n_stack + split


def match_lora_paths(logical_axes: Any, cfg: PeftConfig) -> dict[str, tuple[int, int]]:
    """Paths eligible for LoRA -> (n_stack_dims, split_index).

    Matching is over dot-joined param paths with the reference's wildcard semantics;
    ``match_all_linear`` matches every >=2D non-norm weight (reference
    module_matcher.py _is_linear_module).
    """
    targets = _normalize_patterns(cfg.target_modules)
    excludes = _normalize_patterns(cfg.exclude_modules)
    flat = _flatten_axes(logical_axes)
    matched: dict[str, tuple[int, int]] = {}
    for path, axes in flat:
        if axes is None:
            continue
        struct = _leaf_structure(path, axes)
        if struct is None:
            continue
        if any(wildcard_match(p, path) for p in excludes):
            continue
        if cfg.match_all_linear or any(wildcard_match(p, path) for p in targets):
            matched[path] = struct
    return matched


def _flatten_axes(axes_tree: Any, prefix: str = "") -> list[tuple[str, tuple | None]]:
    out = []
    if isinstance(axes_tree, dict):
        for k, v in axes_tree.items():
            out.extend(_flatten_axes(v, f"{prefix}{k}."))
    else:
        out.append((prefix[:-1], axes_tree))
    return out


def _get_path(tree: Any, path: str):
    node = tree
    for part in path.split("."):
        node = node[part]
    return node


def _set_path(tree: dict, path: str, value: Any) -> dict:
    """Functional nested-dict update (copies along the path only)."""
    parts = path.split(".")
    if len(parts) == 1:
        return {**tree, parts[0]: value}
    return {**tree, parts[0]: _set_path(tree[parts[0]], ".".join(parts[1:]), value)}


def init_lora_params(
    params: Any,
    logical_axes: Any,
    cfg: PeftConfig,
    key: jax.Array,
    dtype=None,
) -> dict:
    """Build the LoRA tree: nested dict of {"lora_a", "lora_b"[, "magnitude"]}.

    A is init'd per ``lora_A_init`` (reference init_lora_A, lora.py), B is zeros so
    step 0 is exactly the base model; DoRA magnitude starts at column norms of W.
    Adapter dtype: explicit ``dtype`` arg > ``cfg.lora_dtype`` > each base weight's
    own dtype (reference lora_dtype semantics, _peft/lora.py:53).
    """
    if dtype is None and cfg.lora_dtype is not None:
        dtype = jnp.dtype(cfg.lora_dtype)
    matched = match_lora_paths(logical_axes, cfg)
    if not matched:
        raise ValueError(
            f"peft matched no params; target_modules={cfg.target_modules} "
            f"available={list(p for p, _ in _flatten_axes(logical_axes))[:20]}..."
        )
    lora: dict = {}
    keys = jax.random.split(key, len(matched))
    for k_init, (path, (n_stack, split)) in zip(keys, sorted(matched.items())):
        w = _get_path(params, path)
        leaf_dtype = w.dtype if dtype is None else dtype
        stack, fan_in, fan_out = (
            w.shape[:n_stack],
            math.prod(w.shape[n_stack:split]),
            math.prod(w.shape[split:]),
        )
        r = cfg.dim
        if cfg.lora_A_init == "xavier":
            limit = math.sqrt(6.0 / (fan_in + r))
            a = jax.random.uniform(k_init, (*stack, fan_in, r), jnp.float32, -limit, limit)
        elif cfg.lora_A_init == "uniform":
            limit = 1.0 / math.sqrt(fan_in)
            a = jax.random.uniform(k_init, (*stack, fan_in, r), jnp.float32, -limit, limit)
        else:  # gaussian
            a = jax.random.normal(k_init, (*stack, fan_in, r), jnp.float32) / math.sqrt(fan_in)
        leaf = {
            "lora_a": a.astype(leaf_dtype),
            "lora_b": jnp.zeros((*stack, r, fan_out), leaf_dtype),
        }
        if cfg.use_dora:
            w2 = w.reshape(*stack, fan_in, fan_out).astype(jnp.float32)
            leaf["magnitude"] = jnp.linalg.norm(w2, axis=-2).astype(leaf_dtype)  # (*stack, fan_out)
        _insert_path(lora, path, leaf)
    return lora


def _insert_path(tree: dict, path: str, value: Any) -> dict:
    parts = path.split(".")
    node = tree
    for part in parts[:-1]:
        node = node.setdefault(part, {})
    node[parts[-1]] = value
    return tree


def lora_logical_axes(logical_axes: Any, cfg: PeftConfig) -> dict:
    """Sharding axes for the LoRA tree: stack dims keep their axes (layers -> pp,
    expert -> ep); the rank-r factors are tiny and stay replicated."""
    matched = match_lora_paths(logical_axes, cfg)
    out: dict = {}
    for path, (n_stack, _split) in sorted(matched.items()):
        axes = _get_path(logical_axes, path)
        stack_axes = tuple(axes[:n_stack])
        leaf = {
            "lora_a": stack_axes + (None, None),
            "lora_b": stack_axes + (None, None),
        }
        if cfg.use_dora:
            leaf["magnitude"] = stack_axes + (None,)
        _insert_path(out, path, leaf)
    return out


def merge_lora_params(params: Any, lora: Any, cfg: PeftConfig,
                      dropout_rng: jax.Array | None = None) -> Any:
    """W -> W + (alpha/r) A@B (DoRA: renormalized + magnitude-scaled), leaving
    unmatched leaves untouched. Pure; call inside jit so XLA fuses per-layer.

    LoRA dropout (reference _peft/lora.py:76 applies nn.Dropout on the adapter
    input x): in the merged-delta formulation ``dropout(x) @ A`` is expressible
    exactly when the mask is shared across tokens — a per-input-feature mask on
    A's rows, rescaled by 1/(1-p). Pass ``dropout_rng`` (training only) to enable;
    None keeps merging deterministic (eval / dropout=0).

    QLoRA: quantized base leaves (quantization.qlora.QuantizedTensor) are
    dequantized on the fly — matched ones before adding the delta, unmatched ones
    by the final :func:`dequantize_params` sweep — so the model always sees dense
    weights while the resident base stays int8/nf4.
    """
    from automodel_tpu.quantization.qlora import (
        dequantize_leaf, dequantize_params, is_quantized_leaf,
    )

    scaling = cfg.scaling
    any_quant = any(is_quantized_leaf(x) for x in jax.tree.leaves(
        params, is_leaf=is_quantized_leaf))

    def merge_one(path: str, leaf: dict, out_params: Any) -> Any:
        w = _get_path(out_params, path)
        if is_quantized_leaf(w):
            w = dequantize_leaf(w)  # back to the base dtype, fp32 math below
        a, b = leaf["lora_a"], leaf["lora_b"]
        if dropout_rng is not None and cfg.dropout > 0.0:
            # stable digest, NOT python hash(): the salted hash would bake a
            # different trace-time constant per process, desyncing masks across
            # SPMD hosts (same reason as training/rng.py _hash_name)
            path_digest = zlib.crc32(path.encode())
            key = jax.random.fold_in(dropout_rng, path_digest % (2**31))
            keep = jax.random.bernoulli(key, 1.0 - cfg.dropout, a.shape[:-1])
            a = a * (keep / (1.0 - cfg.dropout)).astype(a.dtype)[..., None]
        delta = jnp.einsum("...ir,...ro->...io", a.astype(jnp.float32), b.astype(jnp.float32)) * scaling
        w_flat = w.reshape(delta.shape).astype(jnp.float32)
        merged = w_flat + delta
        if cfg.use_dora:
            col_norm = jnp.linalg.norm(merged, axis=-2, keepdims=True)
            merged = leaf["magnitude"].astype(jnp.float32)[..., None, :] * merged / jnp.maximum(col_norm, 1e-6)
        return _set_path(out_params, path, merged.reshape(w.shape).astype(w.dtype))

    out = params
    for path, leaf in _flatten_lora(lora):
        out = merge_one(path, leaf, out)
    if any_quant:
        out = dequantize_params(out)
    return out


def _flatten_lora(lora: Any, prefix: str = "") -> list[tuple[str, dict]]:
    out = []
    for k, v in lora.items():
        if isinstance(v, dict) and "lora_a" in v:
            out.append((prefix + k, v))
        elif isinstance(v, dict):
            out.extend(_flatten_lora(v, prefix + k + "."))
    return out


def count_lora_params(lora: Any) -> int:
    return sum(int(math.prod(x.shape)) for x in jax.tree.leaves(lora))
