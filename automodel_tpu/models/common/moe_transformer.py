"""Shared GQA + MoE decoder machinery (reference per-family model.py Block pattern,
e.g. models/qwen3_moe/model.py, models/gpt_oss/model.py).

Same contract as models.common.transformer: pure functions over stacked param pytrees,
``lax.scan`` over layers. A model may have a *dense prefix* (DeepSeek's
first_k_dense_replace) — those layers are stacked separately and scanned first; the MoE
layers follow. Scans emit per-layer ``(aux_loss, expert_load)`` which the forward
returns as a stats dict for the recipe (aux-loss term, load-balance metrics).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.transformer import (
    DenseDecoderConfig,
    _LAYER_AXES,
    _attention_block,
    _constrain,
    _layer_shapes,
    _mlp_block,
    embed_lookup,
)
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.dispatch import make_moe_block_forward
from automodel_tpu.moe.layers import (
    cast_moe_compute_params,
    init_moe_params,
    moe_logical_axes,
)
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import apply_rope, rope_attention_scaling, rope_frequencies
from automodel_tpu.utils.tracing import scope_blocks

__all__ = [
    "MoEDecoderConfig",
    "init_moe_decoder_params",
    "moe_decoder_logical_axes",
    "moe_decoder_forward",
]


@dataclasses.dataclass
class MoEDecoderConfig(DenseDecoderConfig):
    """GQA decoder where layers >= first_k_dense_replace use an MoE block."""

    moe: MoEConfig | None = None
    first_k_dense_replace: int = 0

    def __post_init__(self):
        super().__post_init__()
        if self.moe is None:
            raise ValueError("MoEDecoderConfig requires a MoEConfig in .moe")

    @property
    def num_moe_layers(self) -> int:
        return self.num_hidden_layers - self.first_k_dense_replace


def _attn_only_shapes(cfg: MoEDecoderConfig) -> dict:
    """Attention + norms from the dense layer table, minus the dense-MLP weights."""
    shapes = _layer_shapes(cfg)
    for k in ("w_gate", "w_up", "w_down"):
        shapes.pop(k)
    return shapes


def init_moe_decoder_params(
    cfg: MoEDecoderConfig,
    key: jax.Array,
    dtype=jnp.float32,
    *,
    attn_shapes: dict | None = None,  # family override (e.g. MLA projections)
    dense_mlp_shapes: dict | None = None,
) -> dict:
    """Stacked params: [dense_layers] (attn + dense MLP) + moe_layers (attn + moe).

    Families with non-GQA attention (DeepSeek MLA) pass their own per-layer
    ``attn_shapes``; dense-prefix MLP weights default to w_gate/w_up/w_down.
    """
    std = cfg.initializer_range
    k_embed, k_dense, k_moe_attn, k_moe, k_head = jax.random.split(key, 5)
    if attn_shapes is None:
        attn_shapes = _attn_only_shapes(cfg)
    if dense_mlp_shapes is None:
        d, i = cfg.hidden_size, cfg.intermediate_size
        dense_mlp_shapes = {"w_gate": (d, i), "w_up": (d, i), "w_down": (i, d)}

    def init_layer_stack(shapes: dict, L: int, key) -> dict:
        keys = jax.random.split(key, len(shapes))
        out = {}
        for idx, (name, shape) in enumerate(shapes.items()):
            if name.endswith("norm"):
                out[name] = jnp.ones((L, *shape), dtype)
            elif name.startswith("b") or name == "sinks":
                out[name] = jnp.zeros((L, *shape), dtype)
            else:
                out[name] = (jax.random.normal(keys[idx], (L, *shape), jnp.float32) * std).astype(dtype)
        return out

    params: dict = {
        "embed": (jax.random.normal(k_embed, (cfg.vocab_size, cfg.hidden_size), jnp.float32) * std).astype(dtype),
        "final_norm": jnp.ones((cfg.hidden_size,), dtype),
    }
    if cfg.first_k_dense_replace > 0:
        params["dense_layers"] = init_layer_stack(
            attn_shapes | dense_mlp_shapes, cfg.first_k_dense_replace, k_dense
        )
    Lm = cfg.num_moe_layers
    moe_layers = init_layer_stack(attn_shapes, Lm, k_moe_attn)
    moe_layers["moe"] = jax.vmap(
        lambda k: init_moe_params(cfg.moe, k, dtype, std)
    )(jax.random.split(k_moe, Lm))
    params["moe_layers"] = moe_layers
    if not cfg.tie_word_embeddings:
        params["lm_head"] = (
            jax.random.normal(k_head, (cfg.hidden_size, cfg.vocab_size), jnp.float32) * std
        ).astype(dtype)
    return params


_DENSE_MLP_AXES = {"w_gate": ("embed", "mlp"), "w_up": ("embed", "mlp"), "w_down": ("mlp", "embed")}


def moe_decoder_logical_axes(
    cfg: MoEDecoderConfig,
    *,
    attn_axes: dict | None = None,
    attn_names: "list[str] | None" = None,
) -> dict:
    if attn_axes is None:
        attn_axes = _LAYER_AXES
    if attn_names is None:
        attn_names = list(_attn_only_shapes(cfg))
    axes: dict = {
        "embed": ("vocab", "embed"),
        "final_norm": ("norm",),
    }
    if cfg.first_k_dense_replace > 0:
        # distinct logical axis: the short dense prefix replicates across pp (it
        # runs on every pipeline rank) while moe "layers" shard over pp
        axes["dense_layers"] = {
            name: ("dense_layers",) + (attn_axes | _DENSE_MLP_AXES)[name]
            for name in attn_names + list(_DENSE_MLP_AXES)
        }
    moe_axes = {name: ("layers",) + attn_axes[name] for name in attn_names}
    moe_axes["moe"] = jax.tree.map(
        lambda t: ("layers",) + t,
        moe_logical_axes(cfg.moe),
        is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(e, (str, type(None))) for e in x),
    )
    axes["moe_layers"] = moe_axes
    if not cfg.tie_word_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def make_moe_layer_fns(
    cfg: MoEDecoderConfig,
    backend: BackendConfig,
    rules=None,
    attention_fn=None,
    training: bool = True,
    seq_len_hint: int = 0,
    ep_manual_axis: str | None = None,
):
    """State-dict layer bodies shared by moe_decoder_forward and the pp pipeline.

    Returns ``(dense_layer_fn, moe_layer_fn)`` over a carried state
    ``{"h", "positions", ["segment_ids"], ["token_mask"]}``:
    ``dense_layer_fn(state, (lp, is_sliding)) -> (state, None)``;
    ``moe_layer_fn(state, (lp, is_sliding)) -> (state, (aux, load, dropped_frac))``
    (``dropped_frac`` is a constant 0 unless ``backend.dispatcher == "a2a"``).

    ``attention_fn(lp, x, positions, segment_ids, is_sliding, rules) -> attn_out``
    overrides the default GQA block — the hook MLA-style families plug into (so the
    scan / aux / dense-prefix machinery here is the single copy).

    ``ep_manual_axis``: the caller runs these layer fns inside a manual region
    over that axis (the pp pipeline's flattened {pp, ep} region) — the a2a MoE
    block then dispatches directly over it instead of opening a nested shard_map
    (see moe.dispatch.make_moe_block_forward).
    """
    dtype = backend.jnp_dtype
    emit_aux = cfg.moe.aux_loss_coeff > 0 and training and not backend.fake_balanced_gate
    custom_attention = attention_fn is not None

    if attention_fn is None:
        inv_freq = rope_frequencies(
            cfg.head_dim, cfg.rope_theta, cfg.rope_scaling,
            partial_rotary_factor=cfg.partial_rotary_factor,
        )
        attn_scale = rope_attention_scaling(cfg.rope_scaling)
        window = jnp.int32(cfg.sliding_window or 0)
        any_sliding = any(cfg.sliding_flags)

        def attention_fn(lp, x, positions, segment_ids, is_sliding, rules, cache=None,
                         cache_meta=None):
            # "disabled" window must exceed every causal q-kv distance; under
            # cached decode that distance is bounded by the CACHE length, not
            # the (length-1) decode chunk — seq_len_hint would silently turn
            # full-attention layers into max_pos-window ones past the config
            # length (same derivation as the dense stack's layer_fn)
            kv_len = x.shape[1] if cache is None else cache[0].shape[1]
            big = jnp.int32(cfg.max_position_embeddings + max(seq_len_hint, kv_len))
            eff_window = jnp.where(is_sliding > 0, window, big) if any_sliding else None
            return _attention_block(cfg, backend, lp, x, positions, segment_ids,
                                    inv_freq, attn_scale, eff_window, rules,
                                    cache=cache, cache_meta=cache_meta)

    if custom_attention:
        import inspect

        custom_supports_cache = "cache" in inspect.signature(attention_fn).parameters

    def attn(state, lp, is_sliding, kv=None):
        h = state["h"]
        x = rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
        if kv is None:
            out, kv_out = attention_fn(lp, x, state["positions"],
                                       state.get("segment_ids"), is_sliding, rules), None
        else:
            if custom_attention and not custom_supports_cache:
                raise NotImplementedError(
                    "this model plugs in a custom attention_fn without a cache "
                    "path (hybrid recurrence) — export to HF for generation instead"
                )
            cache_meta = {"write_idx": state["write_idx"], "valid": state["valid"],
                          "positions": state["kv_positions"]}
            out, kv_out = attention_fn(lp, x, state["positions"],
                                       state.get("segment_ids"), is_sliding, rules,
                                       cache=kv, cache_meta=cache_meta)
        h = h + out
        return _constrain(h, rules, ("batch", "act_seq", "act_embed")), kv_out

    def _split(layer_inputs):
        if len(layer_inputs) == 3:
            return layer_inputs
        return (*layer_inputs, None)

    moe_block = make_moe_block_forward(cfg.moe, backend, rules, training=training,
                                       ep_manual_axis=ep_manual_axis)

    def mlp_sublayer(lp, h):
        x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
        return h + _mlp_block(cfg, backend, lp, x, rules)

    # profiler scopes on the shared MoE decoder path (autonvtx parity,
    # utils/tracing.py): attention / dense-mlp / moe regions are legible in
    # every family's trace, matching the stacks that annotate per-family
    # (nemotron_v3, qwen3_next, step3p5)
    blocks = scope_blocks({"attention": attn, "mlp": mlp_sublayer, "moe": moe_block})

    def dense_layer_fn(state, layer_inputs):
        lp, is_sliding, kv = _split(layer_inputs)
        lp = jax.tree.map(lambda a: a.astype(dtype), lp)
        h, kv_out = blocks["attention"](state, lp, is_sliding, kv)
        h = blocks["mlp"](lp, h)
        state = dict(state, h=_constrain(h, rules, ("batch", "act_seq", "act_embed")))
        return state, kv_out

    def moe_layer_fn(state, layer_inputs):
        lp, is_sliding, kv = _split(layer_inputs)
        moe_params = lp["moe"]
        lp = jax.tree.map(lambda a: a.astype(dtype), {k: v for k, v in lp.items() if k != "moe"})
        h, kv_out = blocks["attention"](state, lp, is_sliding, kv)
        x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
        moe_params = cast_moe_compute_params(moe_params, dtype)
        y, aux, load, dropped = blocks["moe"](moe_params, x, state.get("token_mask"))
        h = h + y
        h = _constrain(h, rules, ("batch", "act_seq", "act_embed"))
        # decode (kv given) swaps the aux/load ys for the updated kv cache —
        # inference never consumes balance stats
        ys = kv_out if kv is not None else (aux if emit_aux else jnp.float32(0), load, dropped)
        return dict(state, h=h), ys

    return dense_layer_fn, moe_layer_fn


def moe_decoder_forward(
    cfg: MoEDecoderConfig,
    backend: BackendConfig,
    params: dict,
    input_ids: jnp.ndarray,  # (B, S)
    positions: jnp.ndarray | None = None,
    segment_ids: jnp.ndarray | None = None,
    token_mask: jnp.ndarray | None = None,  # (B, S) True = valid (counts for routing)
    rules=None,
    return_hidden: bool = False,
    training: bool = True,
    attention_fn=None,
    inputs_embeds: jnp.ndarray | None = None,  # (B, S, D) overrides the embed lookup (VLM merge)
    cache=None,  # generation.init_kv_cache dict -> returns (logits, cache)
) -> tuple[jnp.ndarray, dict[str, Any]]:
    """Returns ``(logits_or_hidden, stats)``; stats has ``aux_loss`` (scalar or None),
    ``expert_load`` (num_moe_layers, E), and — under ``backend.dispatcher == "a2a"`` —
    ``dropped_token_frac`` (mean over MoE layers). With ``cache`` (decode path, GQA
    stacks only) returns ``(logits, cache)`` instead."""
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]), input_ids.shape)
    if cache is not None and segment_ids is None:
        raise ValueError("cache decoding requires segment_ids (1 = real token)")
    dtype = backend.jnp_dtype
    h = (inputs_embeds.astype(dtype) if inputs_embeds is not None
         else embed_lookup(params["embed"], input_ids, dtype, rules,
                           scale=getattr(cfg, "embedding_multiplier", 1.0)))
    h = _constrain(h, rules, ("batch", "act_seq", "act_embed"))

    sliding_flags = jnp.asarray(cfg.sliding_flags, dtype=jnp.int32)
    emit_aux = cfg.moe.aux_loss_coeff > 0 and training and not backend.fake_balanced_gate

    state = {"h": h, "positions": positions}
    if segment_ids is not None:
        state["segment_ids"] = segment_ids
    if token_mask is not None:
        state["token_mask"] = token_mask
    if cache is not None:
        state["kv_positions"] = cache["positions"]
        state["valid"] = cache["valid"]
        state["write_idx"] = cache["write_idx"]
    dense_layer_fn, moe_layer_fn = make_moe_layer_fns(
        cfg, backend, rules, attention_fn, training, seq_len_hint=input_ids.shape[1]
    )

    # per-layer cache slots: k/v always; "idx_k" when the model adds a third
    # slot (DSv32's indexer-key cache) — the attention fn returns the same
    # tuple shape it received, so the slot list is uniform across layers
    ckeys = [c for c in ("k", "v", "idx_k") if cache is not None and c in cache]
    k_dense = cfg.first_k_dense_replace
    dense_new = ()
    if k_dense > 0:
        body = backend.layer_remat(dense_layer_fn)
        if cache is not None:
            kv_dense = tuple(cache[c][:k_dense] for c in ckeys)
            state, dense_new = jax.lax.scan(
                body, state, (params["dense_layers"], sliding_flags[:k_dense], kv_dense)
            )
        elif backend.scan_layers:
            state, _ = jax.lax.scan(body, state, (params["dense_layers"], sliding_flags[:k_dense]))
        else:
            for i in range(k_dense):
                lp = jax.tree.map(lambda a: a[i], params["dense_layers"])
                state, _ = body(state, (lp, sliding_flags[i]))

    moe_sliding = sliding_flags[k_dense:]
    body = backend.layer_remat(moe_layer_fn)
    if cache is not None:
        kv_moe = tuple(cache[c][k_dense:] for c in ckeys)
        state, moe_new = jax.lax.scan(
            body, state, (params["moe_layers"], moe_sliding, kv_moe)
        )
        cache = dict(cache, **{
            c: (jnp.concatenate([d, m], 0) if k_dense > 0 else m)
            for c, d, m in zip(ckeys, dense_new or (None,) * len(ckeys), moe_new)
        })
    elif backend.scan_layers:
        state, (auxs, loads, droppeds) = jax.lax.scan(
            body, state, (params["moe_layers"], moe_sliding)
        )
    else:
        auxs, loads, droppeds = [], [], []
        for i in range(cfg.num_moe_layers):
            lp = jax.tree.map(lambda a: a[i], params["moe_layers"])
            state, (aux, load, dropped) = body(state, (lp, moe_sliding[i]))
            auxs.append(aux)
            loads.append(load)
            droppeds.append(dropped)
        auxs = jnp.stack(auxs)
        loads = jnp.stack(loads)
        droppeds = jnp.stack(droppeds)

    h = rms_norm(state["h"], params["final_norm"].astype(dtype), cfg.rms_norm_eps)
    if cache is not None:
        # next-token logits only (B, 1, V) — see transformer.decoder_forward
        last = jnp.maximum(segment_ids.sum(-1) - 1, 0).astype(jnp.int32)
        h = jnp.take_along_axis(h, last[:, None, None], axis=1)
        unembed = params.get("lm_head")
        if unembed is None:
            unembed = params["embed"].T
        logits = jnp.einsum("bsd,dv->bsv", h, unembed.astype(dtype))
        return logits, cache

    stats = {
        "aux_loss": auxs.sum() if emit_aux else None,
        "expert_load": loads,
    }
    if backend.dispatcher == "a2a":
        stats["dropped_token_frac"] = droppeds.mean()
    if return_hidden:
        return h, stats
    unembed = params.get("lm_head")
    if unembed is None:
        unembed = params["embed"].T
    logits = jnp.einsum("bsd,dv->bsv", h, unembed.astype(dtype))
    return logits, stats
