"""Analytic training-FLOPs formulas + MFU (reference utils/flops_utils.py:18-830).

``flops_per_token`` dispatches per architecture family the way the reference's
per-model formula table does:

- dense GQA decoders (llama lineage),
- MoE (active-expert counting, shared experts, dense prefix),
- MLA (DeepSeek lineage: low-rank q/kv projections, asymmetric qk/v head dims),
- DSv3.2 sparse attention (lightning indexer + top-k-limited score term),
- gated-DeltaNet hybrids (qwen3-next lineage: linear-attention layers cost
  state-size, not seq^2),
- Mamba2/SSD hybrids (nemotron-H lineage).

Train FLOPs = 3x forward (fwd + 2x bwd). Peak TFLOPs table carries the common
TPU generations; MFU = achieved / peak.
"""

from __future__ import annotations

from typing import Any

__all__ = ["flops_per_token", "vision_tower_flops", "mfu", "PEAK_TFLOPS"]

# bf16 dense peak per chip
PEAK_TFLOPS: dict[str, float] = {
    "tpu v4": 275.0,
    "tpu v5e": 197.0,
    "tpu v5 lite": 197.0,
    "tpu v5p": 459.0,
    "tpu v6e": 918.0,
    "h100": 989.0,
    "a100": 312.0,
}


def _getter(cfg: Any):
    if isinstance(cfg, dict):
        return lambda k, d=None: cfg.get(k, d)
    return lambda k, d=None: getattr(cfg, k, d)


def _dense_attn(get, seq_len: int) -> float:
    d = get("hidden_size")
    n = get("num_attention_heads")
    k = get("num_key_value_heads", n) or n
    h = get("head_dim") or d // n
    qkv = 2 * d * (n + 2 * k) * h
    o = 2 * n * h * d
    scores = 2 * 2 * seq_len * n * h  # QK^T + PV; full count like the reference
    return qkv + o + scores


def _mla_attn(get, seq_len: int) -> float:
    """MLA (reference flops_utils deepseek formulas): low-rank q/kv factors,
    qk_head_dim for scores, v_head_dim for values."""
    d = get("hidden_size")
    n = get("num_attention_heads")
    nope = get("qk_nope_head_dim")
    rope = get("qk_rope_head_dim")
    vh = get("v_head_dim")
    qk_hd = nope + rope
    q_rank = get("q_lora_rank")
    kv_rank = get("kv_lora_rank")
    if q_rank:
        q = 2 * d * q_rank + 2 * q_rank * n * qk_hd
    else:
        q = 2 * d * n * qk_hd
    kv = 2 * d * (kv_rank + rope) + 2 * kv_rank * n * (nope + vh)
    o = 2 * n * vh * d
    kv_len = seq_len
    topk = get("index_topk")
    if topk:
        # DSv3.2 sparse attention: scores limited to the top-k indexed keys, plus
        # the lightning indexer's own projections + full-length index scores
        kv_len = min(topk, seq_len)
        hi = get("index_n_heads") or 1
        di = get("index_head_dim") or qk_hd
        idx_proj = 2 * d * di + 2 * (q_rank or d) * hi * di + 2 * d * hi
        idx_scores = 2 * seq_len * hi * di  # the full-length scan lives HERE
        o += idx_proj + idx_scores
    # both score terms run over the (possibly top-k-limited) kv set
    scores = 2 * kv_len * n * qk_hd + 2 * kv_len * n * vh
    return q + kv + o + scores


def _linear_attn(get) -> float:
    """Gated DeltaNet layer (qwen3-next lineage): cost scales with state size
    (dk x dv per value head), not seq — the whole point of the hybrid."""
    d = get("hidden_size")
    hk = get("linear_num_key_heads")
    dk = get("linear_key_head_dim")
    hv = get("linear_num_value_heads")
    dv = get("linear_value_head_dim")
    conv = get("linear_conv_kernel_dim", 4) or 4
    proj = 2 * d * (2 * hk * dk + 2 * hv * dv)  # q,k + v,z
    ba = 2 * d * 2 * hv
    conv_f = 2 * (2 * hk * dk + hv * dv) * conv
    # delta rule per token: state decay + rank-1 update + readout over (dk, dv)
    state = 6 * hv * dk * dv
    out = 2 * hv * dv * d
    return proj + ba + conv_f + state + out


def _mamba2(get) -> float:
    """Mamba2/SSD layer (nemotron-H lineage)."""
    d = get("hidden_size")
    heads = get("mamba_num_heads") or get("n_mamba_heads") or 0
    hd = get("mamba_head_dim") or 64
    d_inner = heads * hd if heads else int((get("expand") or 2) * d)
    d_state = get("ssm_state_size") or get("state_size") or 128
    groups = get("n_groups") or get("mamba_n_groups") or 1
    d_conv = get("conv_kernel") or get("d_conv") or 4
    in_proj = 2 * d * (2 * d_inner + 2 * groups * d_state + (heads or d_inner // hd))
    conv = 2 * (d_inner + 2 * groups * d_state) * d_conv
    # SSD per token: state decay + input outer-product + readout over (hd, d_state)
    ssd = 6 * d_inner * d_state
    out_proj = 2 * d_inner * d
    return in_proj + conv + ssd + out_proj


def _layer_kinds(get, L: int) -> list[str]:
    """Per-layer kind: "attn" | "linear" | "mamba" | "mlp_only"."""
    lt = get("layer_types")
    if lt:
        kinds = []
        for t in lt:
            t = str(t)
            if "linear" in t:
                kinds.append("linear")
            elif "mamba" in t or t == "M":
                kinds.append("mamba")
            else:
                kinds.append("attn")
        return kinds
    pattern = get("hybrid_override_pattern")
    if pattern:
        # nemotron-H style: M = mamba, * = attention, - = mlp-only interleave
        kinds = []
        for ch in pattern:
            if ch == "M":
                kinds.append("mamba")
            elif ch == "*":
                kinds.append("attn")
            elif ch == "-":
                kinds.append("mlp_only")
        return kinds or ["attn"] * L
    if get("linear_num_key_heads") and get("full_attention_interval"):
        fi = int(get("full_attention_interval"))
        return ["attn" if (i + 1) % fi == 0 else "linear" for i in range(L)]
    return ["attn"] * L


def vision_tower_flops(cfg: Any) -> float:
    """Forward FLOPs for ONE image through a CLIP-style ViT tower.

    ``cfg`` is a CLIPVisionConfig-like object or HF ``vision_config`` dict.
    Patch embedding is the conv-as-matmul count (``num_patches`` projections of
    a ``3*patch^2`` pixel column); each of the ``num_hidden_layers`` encoder
    layers runs full MHA plus an UN-gated 2-matmul MLP (fc1/fc2 — not the
    3-matmul gated count dense decoders use) over ``num_patches + 1`` tokens
    (the CLS token attends too).
    """
    get = _getter(cfg)
    d = get("hidden_size")
    inter = get("intermediate_size")
    L = get("num_hidden_layers")
    patch = get("patch_size", 14) or 14
    image = get("image_size", 336) or 336
    num_patches = (image // patch) ** 2
    n_pos = num_patches + 1  # + CLS
    patch_embed = num_patches * 2 * (3 * patch * patch) * d
    per_token_attn = (2 * d * 3 * d) + (2 * d * d) + (2 * 2 * n_pos * d)
    per_token_mlp = 2 * 2 * d * inter
    return float(patch_embed + n_pos * L * (per_token_attn + per_token_mlp))


def flops_per_token(cfg: Any, seq_len: int, training: bool = True,
                    num_images: int = 1) -> float:
    """FLOPs per token for a decoder config (ours or an HF-config-like dict).

    VLM configs (llava lineage: a ``vision_config``/``text_config`` pair, or
    our LlavaConfig's ``vision``/``text``) count the decoder from the text
    config and amortize ``num_images`` vision-tower forwards over ``seq_len``
    tokens — so MFU on llava-style runs credits the vision compute instead of
    pretending the image tokens were free.
    """
    get = _getter(cfg)
    vision = get("vision_config") or get("vision")
    text = get("text_config") or get("text")
    if text is not None:
        get = _getter(text)
    d = get("hidden_size")
    L = get("num_hidden_layers")
    v = get("vocab_size")
    inter = get("intermediate_size")

    is_mla = bool(get("kv_lora_rank"))
    kinds = _layer_kinds(get, L)
    if len(kinds) != L:
        # pattern tables may describe only the repeating block; tile to L
        kinds = (kinds * (L // max(len(kinds), 1) + 1))[:L]

    def attn_flops():
        return _mla_attn(get, seq_len) if is_mla else _dense_attn(get, seq_len)

    per_kind = {
        "attn": attn_flops(),
        "linear": _linear_attn(get) if get("linear_num_key_heads") else attn_flops(),
        "mamba": _mamba2(get),
        "mlp_only": 0.0,
    }
    attn_total = sum(per_kind[k] for k in kinds)

    # MLP: dense or MoE (active experts + shared). Which layers carry an MLP is
    # family-dependent: nemotron-H-style patterns give mamba/attention layers NO
    # FFN (only the '-' slots have one), while layer_types hybrids (qwen-next,
    # gpt-oss) put an MLP in every layer.
    if get("hybrid_override_pattern"):
        n_mlp_layers = kinds.count("mlp_only")
    else:
        n_mlp_layers = L
    n_routed = get("num_experts") or get("n_routed_experts") or 0
    if n_routed:
        top_k = get("num_experts_per_tok") or get("top_k") or 1
        moe_inter = get("moe_intermediate_size") or inter
        shared = get("n_shared_experts") or 0
        dense_layers = get("first_k_dense_replace") or 0
        moe_mlp = 3 * 2 * d * moe_inter * (top_k + shared)
        dense_mlp = 3 * 2 * d * inter
        mlp_total = dense_layers * dense_mlp + (n_mlp_layers - dense_layers) * moe_mlp
    else:
        mlp_total = n_mlp_layers * 3 * 2 * d * inter

    fwd = attn_total + mlp_total + 2 * d * v
    if vision is not None:
        fwd += vision_tower_flops(vision) * max(int(num_images), 0) / float(seq_len)
    return 3.0 * fwd if training else fwd


def mfu(tokens_per_sec: float, flops_per_tok: float, device_kind: str, n_devices: int = 1) -> float:
    """Model FLOPs utilization in [0,1]; 0.0 if the device kind is unknown."""
    key = device_kind.lower()
    peak = None
    for name, tf in PEAK_TFLOPS.items():
        if name in key:
            peak = tf
            break
    if peak is None:
        return 0.0
    achieved = tokens_per_sec * flops_per_tok / 1e12
    return achieved / (peak * n_devices)
