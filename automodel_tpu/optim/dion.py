"""Dion optimizer — distributed orthonormalized updates (reference optim/utils.py
integrates the external ``dion`` package; implemented natively here as an optax
transform, per Ahn et al., "Dion: Distributed Orthonormalized Updates",
arXiv:2504.05295 Algorithm 1).

Per matrix parameter W (m, n) with momentum M and a persistent right factor
Q (n, r):

    M  += g
    P   = orthonormalize(M @ Q)          (QR, column space power iteration)
    R   = M^T @ P
    M  -= (1 - mu) * P @ R^T             (error feedback: only the applied
                                          low-rank part decays from momentum)
    Q   = column_normalize(R)
    dW  = -lr * (sqrt(m / n) * P @ Q^T + weight_decay * W)

Leading stack dims (layer scan, experts) are vmapped. Non-matrix leaves
(norms, biases) and token-dimension leaves (embeddings, lm_head) take the
reference's fallback path: plain AdamW with its own lr.

TPU notes: QR on (m, r) tall matrices maps to XLA's householder pipeline; the
whole update is jit-friendly (no data-dependent shapes) and the Q state shards
like the parameter's second axis.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

__all__ = ["dion", "build_dion_optimizer"]


class DionState(NamedTuple):
    momentum: Any  # pytree matching matrix leaves
    q: Any  # pytree of right factors


def _orthonormalize(p: jnp.ndarray) -> jnp.ndarray:
    # reduced QR (unguarded: rank-deficient columns give arbitrary-but-valid
    # orthonormal completions, which the error feedback absorbs next step)
    q, _ = jnp.linalg.qr(p.astype(jnp.float32))
    return q


def _col_normalize(r: jnp.ndarray, eps: float = 1e-8) -> jnp.ndarray:
    return r / (jnp.linalg.norm(r, axis=-2, keepdims=True) + eps)


def _dion_update_2d(g, m, q, mu: float):
    """One Dion step for a single (m, n) matrix; returns (update, m_new, q_new)."""
    g = g.astype(jnp.float32)
    m = m + g
    p = _orthonormalize(m @ q)  # (rows, r)
    r = m.T @ p  # (cols, r)
    m = m - (1.0 - mu) * (p @ r.T)
    q_new = _col_normalize(r)
    rows, cols = g.shape[-2], g.shape[-1]
    scale = jnp.sqrt(jnp.asarray(rows / cols, jnp.float32))
    # positive ascent direction; the caller applies the -lr (optax convention)
    update = scale * (p @ q_new.T)
    return update, m, q_new


def dion(
    learning_rate: optax.ScalarOrSchedule,
    mu: float = 0.95,
    rank_fraction: float = 0.25,
    min_rank: int = 1,
) -> optax.GradientTransformation:
    """Dion for matrix leaves (ndim >= 2; leading dims vmapped as stacks).

    Wrap with ``optax.masked`` / ``multi_transform`` for mixed parameter groups —
    or use :func:`build_dion_optimizer`, which applies the reference's grouping.
    """

    def rank_of(shape) -> int:
        return max(min_rank, int(min(shape[-2], shape[-1]) * rank_fraction))

    def init_fn(params):
        def init_leaf(p):
            if p.ndim < 2:
                raise ValueError("dion() only handles matrix leaves; mask others out")
            r = rank_of(p.shape)
            # deterministic per-shape init; orthonormalized on first use
            key = jax.random.key(p.ndim * 1000 + p.shape[-1])
            q = jax.random.normal(key, (*p.shape[:-2], p.shape[-1], r), jnp.float32)
            return q

        momentum = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        qs = jax.tree.map(init_leaf, params)
        return DionState(momentum=momentum, q=qs)

    def update_fn(updates, state, params=None):
        del params
        lr = learning_rate

        def leaf(g, m, q):
            fn = _dion_update_2d
            for _ in range(g.ndim - 2):
                fn = jax.vmap(fn, in_axes=(0, 0, 0, None))
            u, m2, q2 = fn(g, m, q, mu)
            # dict result (not tuple): optax.MaskedNode is a tuple subclass and must
            # pass through untouched under multi_transform
            return {"u": u, "m": m2, "q": q2}

        is_res = lambda x: isinstance(x, dict) and set(x) == {"u", "m", "q"}
        out = jax.tree.map(leaf, updates, state.momentum, state.q)
        upd = jax.tree.map(lambda o: o["u"], out, is_leaf=is_res)
        m_new = jax.tree.map(lambda o: o["m"], out, is_leaf=is_res)
        q_new = jax.tree.map(lambda o: o["q"], out, is_leaf=is_res)
        if callable(lr):
            # schedules thread through optax.scale_by_schedule (build_dion_optimizer)
            raise ValueError("pass schedules via build_dion_optimizer")
        upd = jax.tree.map(lambda u: -lr * u, upd)
        return upd, DionState(momentum=m_new, q=q_new)

    return optax.GradientTransformation(init_fn, update_fn)


def _is_matrix_path(path: tuple, leaf) -> bool:
    """Reference dion grouping (optim/utils.py:34-151): matmul weights get Dion;
    embeddings / unembeddings / norms / biases / conv kernels fall back to AdamW.

    Stacked layer params keep their leading scan dim, so the check is name-based
    (a stacked norm is (L, d) and must NOT be orthonormalized)."""
    parts = [getattr(k, "key", str(k)).lower() for k in path]
    name = "/".join(parts)
    if leaf.ndim < 2 or min(leaf.shape[-2:]) < 2:
        return False
    if any(tok in name for tok in ("embed", "lm_head", "pos_emb", "score_correction", "conv", "norm")):
        return False
    if any(pt.startswith("b_") or pt in ("bias", "sinks", "dt_bias", "a_log", "d_skip") for pt in parts):
        return False
    return True


def build_dion_optimizer(
    learning_rate: optax.ScalarOrSchedule,
    mu: float = 0.95,
    rank_fraction: float = 0.25,
    adamw_lr_scale: float = 1.0,
    weight_decay: float = 0.0,
    b1: float = 0.9,
    b2: float = 0.95,
    eps: float = 1e-8,
    max_grad_norm: float | None = None,
) -> optax.GradientTransformation:
    """Dion on matrix params + AdamW on the rest, with optional global clipping.

    Decoupled weight decay applies to BOTH groups, masked off norms/biases (the
    same no_decay_mask contract as build_optimizer's adamw path)."""
    from automodel_tpu.optim.builder import no_decay_mask as masked_decay_mask

    def label_fn(params):
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: "dion" if _is_matrix_path(path, leaf) else "adamw", params
        )

    neg_lr = (lambda c: -learning_rate(c)) if callable(learning_rate) else -learning_rate
    decay = (
        [optax.add_decayed_weights(weight_decay, mask=masked_decay_mask)]
        if weight_decay
        else []
    )
    dion_tx = optax.chain(
        # lr=-1 cancels dion()'s internal descent sign, leaving the raw ascent
        # direction for the standard optax add_decayed_weights -> scale(-lr) tail
        dion(-1.0, mu=mu, rank_fraction=rank_fraction),
        *decay,
        optax.scale_by_schedule(neg_lr) if callable(learning_rate) else optax.scale(neg_lr),
    )
    adamw_lr = (
        (lambda c: adamw_lr_scale * learning_rate(c)) if callable(learning_rate)
        else adamw_lr_scale * learning_rate
    )
    adamw_tx = optax.adamw(
        adamw_lr, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
        mask=masked_decay_mask if weight_decay else None,
    )

    tx = optax.multi_transform({"dion": dion_tx, "adamw": adamw_tx}, label_fn)
    if max_grad_norm:
        tx = optax.chain(optax.clip_by_global_norm(max_grad_norm), tx)
    return tx
