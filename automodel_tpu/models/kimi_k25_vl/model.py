"""Kimi-K2.5-VL — TPU-native (reference models/kimi_k25_vl/model.py:879).

KimiVL with the MoonViT3d temporal tower: fixed sincos time embedding per frame
(Learnable2DInterpPosEmbDividedFixed, reference :228), spatial rope repeated over
frames (Rope2DPosEmbRepeated, :271), and temporal mean-pooling in the merger
(tpool_patch_merger, :421) — all handled by the shared moonvit module's
scatter-mean path (pos_emb_time > 1). The projector may use a separate
mm_hidden_size / projector_ln_eps; text is DeepSeek-V3 MLA.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from automodel_tpu.models.deepseek_v3.model import DeepseekV3Config
from automodel_tpu.models.kimivl.model import KimiVLConfig, KimiVLForConditionalGeneration
from automodel_tpu.models.vision.moonvit import MoonViTConfig

__all__ = ["KimiK25VLConfig", "KimiK25VLForConditionalGeneration"]


@dataclasses.dataclass
class KimiK25VLConfig(KimiVLConfig):
    projector_ln_eps: float = 1e-5

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "KimiK25VLConfig":
        v = dict(hf.get("vision_config", {}))
        if v.get("init_pos_emb_time"):
            v["pos_emb_time"] = v["init_pos_emb_time"]
        return cls(
            text=DeepseekV3Config.from_hf(hf["text_config"]),
            vision=MoonViTConfig.from_hf(v),
            media_placeholder_token_id=hf.get("media_placeholder_token_id", 163605),
            projector_ln_eps=hf.get("projector_ln_eps", 1e-5),
        )


class KimiK25VLForConditionalGeneration(KimiVLForConditionalGeneration):
    config_class = KimiK25VLConfig
    hf_architectures = ("KimiK25VLForConditionalGeneration",)

    @classmethod
    def from_config(cls, config, backend=None):
        if isinstance(config, dict):
            config = KimiK25VLConfig.from_hf(config)
        return cls(config, backend)
