"""``automodel`` CLI (reference _cli/app.py:45-61, pyproject.toml:144).

Usage::

    automodel finetune llm -c examples/llm_finetune/llama3_2_1b_hellaswag.yaml [--a.b.c v ...]
    automodel pretrain llm -c cfg.yaml
    automodel benchmark llm -c cfg.yaml

Unlike the reference there is no torchrun fan-out: JAX is one process per host, so the
CLI either runs the recipe inline, or — when the config has a ``slurm:`` section —
renders an sbatch script that runs this same CLI on every node (reference
launcher/slurm/utils.py:65 behavior).
"""

from __future__ import annotations

import sys

from automodel_tpu.config.cli_overrides import parse_args_and_load_config

__all__ = ["main", "RECIPES"]

# (command, domain) -> recipe main
RECIPES: dict[tuple[str, str], str] = {
    ("finetune", "llm"): "automodel_tpu.recipes.llm.train_ft:main",
    ("pretrain", "llm"): "automodel_tpu.recipes.llm.train_ft:main",
    ("benchmark", "llm"): "automodel_tpu.recipes.llm.benchmark:main",
    ("kd", "llm"): "automodel_tpu.recipes.llm.kd:main",
    ("generate", "llm"): "automodel_tpu.recipes.llm.generate:main",
    ("finetune", "seq_cls"): "automodel_tpu.recipes.llm.train_seq_cls:main",
    ("finetune", "vlm"): "automodel_tpu.recipes.vlm.finetune:main",
    ("finetune", "biencoder"): "automodel_tpu.recipes.biencoder.train_biencoder:main",
    ("mine", "biencoder"): "automodel_tpu.recipes.biencoder.mine_hard_negatives:main",
}


def _resolve(command: str, domain: str):
    key = (command, domain)
    if key not in RECIPES:
        known = ", ".join(f"{c} {d}" for c, d in RECIPES)
        raise SystemExit(f"unknown recipe '{command} {domain}'; known: {known}")
    target = RECIPES[key]
    mod_name, fn_name = target.split(":")
    import importlib

    try:
        mod = importlib.import_module(mod_name)
    except ModuleNotFoundError as e:
        raise SystemExit(f"recipe '{command} {domain}' is not available yet ({e})")
    return getattr(mod, fn_name)


def main(argv: list[str] | None = None):
    if argv is None:
        argv = sys.argv[1:]
    if len(argv) < 2 or argv[0] in ("-h", "--help"):
        print(__doc__)
        raise SystemExit(0 if argv and argv[0] in ("-h", "--help") else 2)
    command, domain, *rest = argv
    cfg = parse_args_and_load_config(rest)
    if "slurm" in cfg:
        from automodel_tpu.launcher.slurm import submit_slurm_job

        return submit_slurm_job(cfg, command, domain)
    recipe_main = _resolve(command, domain)
    return recipe_main(cfg)


if __name__ == "__main__":
    main()
