"""Blockwise flash attention for TPU (Pallas).

Replaces the reference's TransformerEngine fused attention / flash-attn externals
(components/attention/utils.py:25, models/common/utils.py:166-171) with a single
Pallas kernel pair:

- forward: online-softmax over kv blocks; (q, k, v) stream HBM->VMEM block by block,
  the (block_q, head_dim) accumulator and row stats live in VMEM scratch across the
  innermost kv grid steps. Emits logsumexp for the backward.
- backward: recompute-based (flash-attention-2 style): one kernel accumulates dq over
  kv blocks, one accumulates dk/dv over q blocks; D = rowsum(dO*O) precomputed in XLA.

Masking is composable inside the kernel: causal, sliding window (static), and segment
ids (sequence packing — the TPU replacement for the reference's THD varlen format,
distributed/thd_utils.py). GQA reads each kv head once via grid index maps — kv is
never materialized per q head in the forward.

TPU layout notes: Mosaic requires the last two block dims to be (8k, 128k)-divisible,
so per-row vectors ride in padded layouts (the same scheme as the in-tree
jax.experimental.pallas.ops.tpu.flash_attention): q-oriented vectors (q segment ids,
logsumexp, D) are broadcast across a trailing 128-lane dim; kv-oriented vectors
(kv segment ids) across an 8-sublane dim.

Layout contract: inputs are (batch, seq, heads, head_dim) like ops.attention; the
wrapper folds (batch, heads) into the leading grid dim. Sequence lengths must divide
the block sizes; callers fall back to the XLA path otherwise.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

NEG_INF = -1e30
LANES = 128
SUBLANES = 8


def _block_mask(q_start, kv_start, block_q, block_k, *, causal, window, seg_q, seg_kv):
    """(bq, bk) bool allowed-mask; seg_q is (bq, 1), seg_kv is (1, bk)."""
    q_idx = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    kv_idx = kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    allowed = None

    def _and(a, b):
        return b if a is None else jnp.logical_and(a, b)

    if causal:
        allowed = _and(allowed, q_idx >= kv_idx)
    if window is not None:
        allowed = _and(allowed, q_idx - kv_idx < window)
    if seg_q is not None:
        allowed = _and(allowed, seg_q == seg_kv)
    return allowed


def _run_block(q_start, kv_start, block_q, block_k, *, causal, window):
    """Static/cheap predicate: does this (q block, kv block) pair do any work?"""
    run = True
    if causal:
        run = q_start + block_q - 1 >= kv_start
    if window is not None:
        run = jnp.logical_and(run, q_start - (kv_start + block_k - 1) < window)
    return run


def _soft_cap(s, cap):
    """tanh logit capping (gemma2/grok style); None -> identity."""
    return s if cap is None else jnp.tanh(s / cap) * cap


def _soft_cap_jac(s_capped, cap):
    """d(capped)/d(raw) expressed in the *capped* value: 1 - (capped/cap)^2."""
    return 1.0 - (s_capped / cap) ** 2


def _fwd_kernel(q_ref, k_ref, v_ref, sq_ref, skv_ref, sink_ref, w_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k,
                num_kv, segmented, softcap, has_sink, windowed):
    window = w_ref[0] if windowed else None
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start, kv_start = qi * block_q, ki * block_k

    @pl.when(_run_block(q_start, kv_start, block_q, block_k, causal=causal, window=window))
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # (bq, d)
        k = k_ref[0].astype(jnp.float32)  # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (bq, bk)
        s = _soft_cap(s, softcap)

        allowed = _block_mask(
            q_start, kv_start, block_q, block_k, causal=causal, window=window,
            seg_q=sq_ref[0, :, :1] if segmented else None,
            seg_kv=skv_ref[0, :1, :] if segmented else None,
        )
        if allowed is not None:
            s = jnp.where(allowed, s, NEG_INF)

        m_prev = m_ref[:, :1]  # (bq, 1)
        m_new = jnp.maximum(m_prev, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if allowed is not None:
            p = jnp.where(allowed, p, 0.0)  # fully-masked rows stay all-zero
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, :1] * alpha + p.sum(-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(
            p.astype(v_ref.dtype), v_ref[0], preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        if has_sink:
            # gpt-oss attention sinks: a per-head extra logit column absorbing
            # softmax mass. Fold it into the running (m, l) stats: the sink
            # contributes exp(sink) to the denominator and nothing to the value
            # accumulator; lse then already accounts for it, so the backward
            # kernels need no change (p = exp(s - lse) sums to < 1).
            sink = sink_ref[0, 0, 0]
            m0, l0 = m_ref[:, :1], l_ref[:, :1]
            m_eff = jnp.maximum(m0, sink)
            alpha = jnp.exp(m0 - m_eff)  # 0 for fully-masked rows (m0 = -inf)
            l = l0 * alpha + jnp.exp(sink - m_eff)
            o_ref[0] = (acc_ref[:] * alpha / l).astype(o_ref.dtype)
            lse_ref[0] = jnp.broadcast_to(m_eff + jnp.log(l), lse_ref.shape[1:])
        else:
            l = l_ref[:, :1]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
            lse = jnp.where(l == 0.0, NEG_INF, m_ref[:, :1] + jnp.log(safe_l))
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _dq_kernel(q_ref, k_ref, v_ref, sq_ref, skv_ref, w_ref, do_ref, lse_ref, delta_ref,
               dq_ref, acc_ref, *, scale, causal, block_q, block_k, num_kv,
               segmented, softcap, windowed):
    window = w_ref[0] if windowed else None
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start, kv_start = qi * block_q, ki * block_k

    @pl.when(_run_block(q_start, kv_start, block_q, block_k, causal=causal, window=window))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _soft_cap(s, softcap)
        allowed = _block_mask(
            q_start, kv_start, block_q, block_k, causal=causal, window=window,
            seg_q=sq_ref[0, :, :1] if segmented else None,
            seg_kv=skv_ref[0, :1, :] if segmented else None,
        )
        p = jnp.exp(s - lse_ref[0, :, :1])
        if allowed is not None:
            p = jnp.where(allowed, p, 0.0)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, :, :1])
        if softcap is not None:
            ds = ds * _soft_cap_jac(s, softcap)
        acc_ref[:] += jax.lax.dot(ds, k, preferred_element_type=jnp.float32) * scale

    @pl.when(ki == num_kv - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _dqdkv_kernel(q_ref, k_ref, v_ref, sq_ref, skv_ref, w_ref, do_ref, lse_ref,
                  delta_ref, dq_ref, dk_ref, dv_ref, dq_acc, dk_acc, dv_acc, *,
                  scale, causal, block_q, block_k, num_q, num_kv, segmented,
                  softcap, windowed):
    """Fused backward: dq, dk, dv off ONE s/p recompute per (q, kv) block pair.

    The split kernels each redo s = qk^T and the dq kernel redoes dp = do v^T,
    so the split backward runs 7 block matmuls per pair; sharing the recompute
    cuts that to 5 (s, dp, dq += ds k, dv += p^T do, dk += ds^T q) and halves
    the q/k/v/do HBM streaming. The price: dk/dv accumulate across the whole
    per-row grid, so they live as full-(Skv, d) f32 VMEM scratch — the wrapper
    gates this path on that footprint and falls back to the split kernels.
    """
    window = w_ref[0] if windowed else None
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(jnp.logical_and(qi == 0, ki == 0))
    def _init_kv():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(ki == 0)
    def _init_q():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start, kv_start = qi * block_q, ki * block_k

    @pl.when(_run_block(q_start, kv_start, block_q, block_k, causal=causal, window=window))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _soft_cap(s, softcap)
        allowed = _block_mask(
            q_start, kv_start, block_q, block_k, causal=causal, window=window,
            seg_q=sq_ref[0, :, :1] if segmented else None,
            seg_kv=skv_ref[0, :1, :] if segmented else None,
        )
        p = jnp.exp(s - lse_ref[0, :, :1])
        if allowed is not None:
            p = jnp.where(allowed, p, 0.0)
        do = do_ref[0].astype(jnp.float32)
        kv_rows = pl.ds(kv_start, block_k)
        dv_acc[kv_rows, :] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, :, :1])
        if softcap is not None:
            ds = ds * _soft_cap_jac(s, softcap)
        dq_acc[:] += jax.lax.dot(ds, k, preferred_element_type=jnp.float32) * scale
        dk_acc[kv_rows, :] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32) * scale

    @pl.when(ki == num_kv - 1)
    def _finalize_q():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)

    @pl.when(jnp.logical_and(qi == num_q - 1, ki == num_kv - 1))
    def _finalize_kv():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, sq_ref, skv_ref, w_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                block_q, block_k, num_q, segmented, softcap, windowed):
    window = w_ref[0] if windowed else None
    ki, qi = pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start, kv_start = qi * block_q, ki * block_k

    @pl.when(_run_block(q_start, kv_start, block_q, block_k, causal=causal, window=window))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _soft_cap(s, softcap)
        allowed = _block_mask(
            q_start, kv_start, block_q, block_k, causal=causal, window=window,
            seg_q=sq_ref[0, :, :1] if segmented else None,
            seg_kv=skv_ref[0, :1, :] if segmented else None,
        )
        p = jnp.exp(s - lse_ref[0, :, :1])
        if allowed is not None:
            p = jnp.where(allowed, p, 0.0)
        do = do_ref[0].astype(jnp.float32)
        dv_acc[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, :, :1])
        if softcap is not None:
            ds = ds * _soft_cap_jac(s, softcap)
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32) * scale

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _q_lanes(x):
    """(BN, S) -> (BN, S, LANES) broadcast along a 128-lane trailing dim."""
    return jax.lax.broadcast_in_dim(x, (*x.shape, LANES), (0, 1))


def _kv_sublanes(x):
    """(BN, S) -> (BN, SUBLANES, S) broadcast along an 8-sublane dim."""
    return jax.lax.broadcast_in_dim(x, (x.shape[0], SUBLANES, x.shape[1]), (0, 2))


def _specs(bn_map, d, block_q, block_k, segmented, has_sink=False, windowed=False):
    """(q, k, v, seg_q, seg_kv, sinks, window) block specs; bn_map maps grid b -> kv row.
    The sliding window rides as a (1,) SMEM scalar so traced per-layer windows
    (gpt-oss/gemma alternating layer types under a layer scan) stay kernel-eligible."""
    return [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (bn_map(b), j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (bn_map(b), j, 0)),
        pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0)) if segmented else None,
        pl.BlockSpec((1, SUBLANES, block_k), lambda b, i, j: (bn_map(b), 0, j)) if segmented else None,
        pl.BlockSpec((1, 1, LANES), lambda b, i, j: (b, 0, 0)) if has_sink else None,
        pl.BlockSpec(memory_space=pltpu.SMEM) if windowed else None,
    ]


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11, 12, 13))
def _flash(q, k, v, seg_q, seg_kv, sinks, warr, scale, causal, softcap,
           block_q, block_k, groups, interpret):
    o, _ = _flash_fwd_impl(q, k, v, seg_q, seg_kv, sinks, warr, scale, causal,
                           softcap, block_q, block_k, groups, interpret)
    return o


def _filter_specs(specs, args):
    keep = [(s, a) for s, a in zip(specs, args) if a is not None]
    return [s for s, _ in keep], [a for _, a in keep]


# trace counter for the fused dq+dkv path — lets tests assert the fused kernel
# actually engaged (the VMEM gate silently falls back to the split kernels)
_fused_bwd_traces = 0


def _make_entry(kernel, segmented, windowed, has_sink=False, sink_slot=False):
    """Adapter from pallas_call's flat ref list to a kernel's optional-arg
    signature (q, k, v, seg_q, seg_kv, [sink], window, *rest). `has_sink` says a
    sink ref is actually present in the flat list; `sink_slot` says the kernel's
    signature has a sink parameter at all (the fwd kernel takes one even when no
    sinks input was passed — it receives None)."""

    def entry(*refs):
        it = iter(refs)
        q_r, k_r, v_r = next(it), next(it), next(it)
        sq_r = next(it) if segmented else None
        skv_r = next(it) if segmented else None
        sink_r = next(it) if has_sink else None
        w_r = next(it) if windowed else None
        if sink_slot:
            kernel(q_r, k_r, v_r, sq_r, skv_r, sink_r, w_r, *it)
        else:
            kernel(q_r, k_r, v_r, sq_r, skv_r, w_r, *it)

    return entry


def _gqa_group_sum(dk, dv, groups, k_dtype, v_dtype):
    """Reduce per-q-head dk/dv (bn, skv, d) over the GQA group -> (bk, skv, d)."""
    if groups == 1:
        return dk, dv
    dk = dk.reshape(-1, groups, *dk.shape[1:]).sum(1).astype(k_dtype)
    dv = dv.reshape(-1, groups, *dv.shape[1:]).sum(1).astype(v_dtype)
    return dk, dv


def _flash_fwd_impl(q, k, v, seg_q, seg_kv, sinks, warr, scale, causal,
                    softcap, block_q, block_k, groups, interpret):
    """q: (BN, Sq, D); k/v: (BK, Skv, D) with BN = BK * groups.
    seg_q: (BN, Sq, LANES) or None; seg_kv: (BK, SUBLANES, Skv) or None;
    sinks: (BN, 1, LANES) f32 per-row sink logits or None;
    warr: (1,) int32 sliding window (possibly traced) or None."""
    bn, sq, d = q.shape
    _, skv, _ = k.shape
    num_q, num_kv = sq // block_q, skv // block_k
    segmented = seg_q is not None
    has_sink = sinks is not None
    windowed = warr is not None

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_kv=num_kv, segmented=segmented,
        softcap=softcap, has_sink=has_sink, windowed=windowed,
    )

    kernel_entry = _make_entry(kernel, segmented, windowed,
                               has_sink=has_sink, sink_slot=True)

    specs, args = _filter_specs(
        _specs(lambda b: b // groups, d, block_q, block_k, segmented, has_sink, windowed),
        [q, k, v, seg_q, seg_kv, sinks, warr],
    )
    o, lse = pl.pallas_call(
        kernel_entry,
        grid=(bn, num_q, num_kv),
        in_specs=specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bn, sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    return o, lse


def _flash_fwd(q, k, v, seg_q, seg_kv, sinks, warr, scale, causal, softcap,
               block_q, block_k, groups, interpret):
    o, lse = _flash_fwd_impl(q, k, v, seg_q, seg_kv, sinks, warr, scale, causal,
                             softcap, block_q, block_k, groups, interpret)
    return o, (q, k, v, seg_q, seg_kv, sinks, warr, o, lse)


def _flash_bwd(scale, causal, softcap, block_q, block_k, groups, interpret,
               residuals, do):
    q, k, v, seg_q, seg_kv, sinks, warr, o, lse = residuals
    windowed = warr is not None
    bn, sq, d = q.shape
    bk_heads, skv, _ = k.shape
    num_q, num_kv = sq // block_q, skv // block_k
    segmented = seg_q is not None
    delta = _q_lanes((o.astype(jnp.float32) * do.astype(jnp.float32)).sum(-1))

    def row_specs(index_q, bq):
        # do / lse / delta blocks, all q-oriented
        return [
            pl.BlockSpec((1, bq, d), index_q),
            pl.BlockSpec((1, bq, LANES), index_q),
            pl.BlockSpec((1, bq, LANES), index_q),
        ]

    # Fused dq+dkv path: one kernel, one s/p recompute (5 block matmuls vs the
    # split kernels' 7, and one q/k/v/do HBM stream instead of two). dk/dv ride
    # full-(Skv, d) f32 VMEM scratch PLUS full-(Skv, d) output windows, so the
    # path is gated on that whole resident footprint (f32 scratch pair + the
    # dk/dv output windows at output dtype); long-context shapes fall back to
    # the split kernels below. Block tiles / dq scratch / double-buffering are
    # roughly shape-independent here and covered by the budget's headroom to
    # the 16MB scoped-VMEM line.
    fused_kv_bytes = 2 * skv * d * (4 + k.dtype.itemsize)
    fused_budget = int(os.environ.get("AUTOMODEL_FLASH_FUSED_KV_BYTES", str(8 << 20)))
    if os.environ.get("AUTOMODEL_FLASH_FUSED_BWD", "1") != "0" and fused_kv_bytes <= fused_budget:
        block_q_f = min(block_q, int(os.environ.get("AUTOMODEL_FLASH_FUSED_Q_BLOCK", "512")))
        if sq % block_q_f:
            # the default (512, capped by block_q — itself a power of two
            # dividing sq) always divides; only an explicit override can't
            raise ValueError(
                f"AUTOMODEL_FLASH_FUSED_Q_BLOCK={block_q_f} must divide seq {sq} "
                "(a silent fallback here would benchmark the split kernels "
                "while reporting a fused config)"
            )
        global _fused_bwd_traces
        _fused_bwd_traces += 1
        num_q_f = sq // block_q_f
        fused_kernel = functools.partial(
            _dqdkv_kernel, scale=scale, causal=causal,
            block_q=block_q_f, block_k=block_k, num_q=num_q_f, num_kv=num_kv,
            segmented=segmented, softcap=softcap, windowed=windowed,
        )
        specs, args = _filter_specs(
            _specs(lambda b: b // groups, d, block_q_f, block_k, segmented, False, windowed)
            + row_specs(lambda b, i, j: (b, i, 0), block_q_f),
            [q, k, v, seg_q, seg_kv, None, warr, do, lse, delta],
        )
        dq, dk, dv = pl.pallas_call(
            _make_entry(fused_kernel, segmented, windowed),
            grid=(bn, num_q_f, num_kv),
            in_specs=specs,
            out_specs=[
                pl.BlockSpec((1, block_q_f, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, skv, d), lambda b, i, j: (b, 0, 0)),
                pl.BlockSpec((1, skv, d), lambda b, i, j: (b, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(q.shape, q.dtype),
                jax.ShapeDtypeStruct((bn, skv, d), k.dtype),
                jax.ShapeDtypeStruct((bn, skv, d), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q_f, d), jnp.float32),
                pltpu.VMEM((skv, d), jnp.float32),
                pltpu.VMEM((skv, d), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary", "arbitrary"),
            ),
            interpret=interpret,
        )(*args)
        dk, dv = _gqa_group_sum(dk, dv, groups, k.dtype, v.dtype)
        return (dq, dk, dv, None, None,
                _dsinks_from_residuals(sinks, lse, delta), None)

    dq_kernel = functools.partial(
        _dq_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_kv=num_kv, segmented=segmented,
        softcap=softcap, windowed=windowed,
    )

    specs, args = _filter_specs(
        _specs(lambda b: b // groups, d, block_q, block_k, segmented, False, windowed)
        + row_specs(lambda b, i, j: (b, i, 0), block_q),
        [q, k, v, seg_q, seg_kv, None, warr, do, lse, delta],  # None: no sink input in bwd
    )
    dq = pl.pallas_call(
        _make_entry(dq_kernel, segmented, windowed),
        grid=(bn, num_q, num_kv),
        in_specs=specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)

    # dk/dv reduce over the GQA group; expand kv per q head, sum groups after.
    kx = jnp.repeat(k, groups, axis=0) if groups > 1 else k
    vx = jnp.repeat(v, groups, axis=0) if groups > 1 else v
    skx = (
        jnp.repeat(seg_kv, groups, axis=0)
        if (segmented and groups > 1)
        else seg_kv
    )
    # the dkv kernel carries TWO f32 accumulators + the recompute tile; at
    # block_q 1024 it sits ~44KB over the 16MB scoped-VMEM line in some remat
    # contexts — cap ITS q block while dq (one accumulator) keeps the bigger one.
    # The env override exists for on-chip block sweeps (bench scripts); 512 is
    # the measured best at seq 2048 AND 4096 on v5e.
    block_q_kv = min(block_q, int(os.environ.get("AUTOMODEL_FLASH_BWD_Q_BLOCK", "512")))
    if sq % block_q_kv:
        raise ValueError(
            f"AUTOMODEL_FLASH_BWD_Q_BLOCK={block_q_kv} must divide seq {sq} "
            "(a ragged dkv grid would silently drop tail q-blocks from dk/dv)"
        )
    num_q_kv = sq // block_q_kv
    dkv_kernel = functools.partial(
        _dkv_kernel, scale=scale, causal=causal,
        block_q=block_q_kv, block_k=block_k, num_q=num_q_kv, segmented=segmented,
        softcap=softcap, windowed=windowed,
    )

    # grid order here is (bn, kv, q): q/do/lse/delta index with the LAST grid dim
    qkv_specs = [
        pl.BlockSpec((1, block_q_kv, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_q_kv, LANES), lambda b, j, i: (b, i, 0)) if segmented else None,
        pl.BlockSpec((1, SUBLANES, block_k), lambda b, j, i: (b, 0, j)) if segmented else None,
        pl.BlockSpec(memory_space=pltpu.SMEM) if windowed else None,
    ]
    specs, args = _filter_specs(
        qkv_specs + row_specs(lambda b, j, i: (b, i, 0), block_q_kv),
        [q, kx, vx, seg_q, skx, warr, do, lse, delta],
    )
    dk, dv = pl.pallas_call(
        _make_entry(dkv_kernel, segmented, windowed),
        grid=(bn, num_kv, num_q_kv),
        in_specs=specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(kx.shape, k.dtype),
            jax.ShapeDtypeStruct(vx.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    dk, dv = _gqa_group_sum(dk, dv, groups, k.dtype, v.dtype)
    dwarr = None
    return dq, dk, dv, None, None, _dsinks_from_residuals(sinks, lse, delta), dwarr


def _dsinks_from_residuals(sinks, lse, delta):
    """d loss / d sink_b = -sum_i exp(sink_b - lse_{b,i}) * Delta_{b,i}
    (the sink column's p * (dp - Delta) with dp = 0); cheap XLA reduction over
    the saved lse + delta. Gradient lands on lane 0, matching the kernel's
    sink_ref[0, 0, 0] read; the wrapper's broadcast transposes the rest away."""
    if sinks is None:
        return None
    p_sink = jnp.exp(sinks[:, 0, 0][:, None] - lse[:, :, 0])  # (bn, sq)
    dsink_rows = -(p_sink * delta[:, :, 0]).sum(-1)  # (bn,)
    return jnp.zeros_like(sinks).at[:, 0, 0].set(dsink_rows)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # (B, Sq, N, D)
    k: jnp.ndarray,  # (B, Skv, K, D)
    v: jnp.ndarray,  # (B, Skv, K, D)
    *,
    causal: bool = True,
    segment_ids_q: jnp.ndarray | None = None,  # (B, Sq)
    segment_ids_kv: jnp.ndarray | None = None,  # (B, Skv)
    sliding_window: int | None = None,
    softmax_scale: float | None = None,
    logit_soft_cap: float | None = None,
    sinks: jnp.ndarray | None = None,  # (N,) per-head sink logits (gpt-oss)
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash attention over (batch, seq, heads, head_dim); returns same shape as q."""
    b, sq, n, d = q.shape
    _, skv, nk, _ = k.shape
    if softmax_scale is None:
        softmax_scale = d**-0.5
    groups = n // nk
    # measured on v5e at (B4, S2048, H32/KV8, d64): (1024, 1024) beats (512,
    # 1024) by ~2% end-to-end and (128, 128) by ~2x fwd+bwd; (1024, 2048)+ blows
    # scoped VMEM. Fall back to the largest power-of-two block that divides the
    # sequence so the grid stays exact
    def _pick(seq, target):
        # largest power-of-two block <= target that divides seq (>= 8); if none
        # divides, return 8 so the kernel's divisibility check raises clearly
        b = 1 << (max(min(target, seq), 8).bit_length() - 1)
        while b > 8 and seq % b:
            b //= 2
        return b

    block_q = _pick(sq, block_q or 1024)
    block_k = _pick(skv, block_k or 1024)
    if sq % block_q or skv % block_k:
        raise ValueError(
            f"flash_attention needs seq lengths divisible by block sizes: "
            f"sq={sq}%{block_q}, skv={skv}%{block_k}"
        )

    # (B, S, H, D) -> (B*H, S, D); kv heads stay un-repeated (GQA via index maps)
    qf = q.transpose(0, 2, 1, 3).reshape(b * n, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * nk, skv, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * nk, skv, d)
    seg_q = seg_kv = None
    if segment_ids_q is not None or segment_ids_kv is not None:
        sq_ids = segment_ids_q if segment_ids_q is not None else segment_ids_kv
        skv_ids = segment_ids_kv if segment_ids_kv is not None else segment_ids_q
        seg_q = _q_lanes(jnp.repeat(sq_ids.astype(jnp.int32), n, axis=0))
        seg_kv = _kv_sublanes(jnp.repeat(skv_ids.astype(jnp.int32), nk, axis=0))
    sinks_rows = None
    if sinks is not None:
        # per-head scalar -> one (1, LANES) row per (batch, head) grid row; the
        # kernel reads lane 0 and AD sums the tile/broadcast back to (N,)
        sinks_rows = jnp.broadcast_to(
            jnp.tile(sinks.astype(jnp.float32), b)[:, None, None], (b * n, 1, LANES)
        )

    warr = None
    if sliding_window is not None:
        # (1,) SMEM scalar: keeps traced per-layer windows (gpt-oss/gemma layer
        # scans) kernel-eligible instead of forcing the XLA fallback
        warr = jnp.asarray(sliding_window, jnp.int32).reshape(1)
    o = _flash(qf, kf, vf, seg_q, seg_kv, sinks_rows, warr, softmax_scale, causal,
               logit_soft_cap, block_q, block_k, groups, interpret)
    return o.reshape(b, n, sq, d).transpose(0, 2, 1, 3)
