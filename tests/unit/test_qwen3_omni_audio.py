"""Qwen3-Omni audio encoder parity vs HF (chunked convs, sinusoid positions,
windowed attention, GELU head) with irregular audio lengths."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.models.audio.qwen3_omni_audio import (
    Qwen3OmniAudioConfig,
    audio_forward,
    audio_output_lengths,
    init_audio_params,
    prepare_audio_inputs,
)
from automodel_tpu.models.common.backend import BackendConfig

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from transformers.models.qwen3_omni_moe.configuration_qwen3_omni_moe import (
    Qwen3OmniMoeAudioEncoderConfig,
)
from transformers.models.qwen3_omni_moe.modeling_qwen3_omni_moe import (
    Qwen3OmniMoeAudioEncoder,
)


def tiny_cfg():
    return dict(
        d_model=32, encoder_layers=2, encoder_attention_heads=4, encoder_ffn_dim=48,
        num_mel_bins=32, n_window=8, n_window_infer=32, downsample_hidden_size=16,
        output_dim=64, conv_chunksize=500, max_source_positions=1500,
        activation_function="gelu",
    )


def _fp32_backend():
    return BackendConfig(dtype="float32", remat_policy="full")


def _load_params(hf_model, dtype=np.float32):
    sd = {k: v.numpy().astype(dtype) for k, v in hf_model.state_dict().items()}
    L = hf_model.config.encoder_layers
    stack = lambda tmpl, tf=lambda x: x: np.stack([tf(sd[tmpl.format(i)]) for i in range(L)])
    t = lambda x: np.ascontiguousarray(x.T)
    return {
        "conv1_w": sd["conv2d1.weight"], "b_conv1": sd["conv2d1.bias"],
        "conv2_w": sd["conv2d2.weight"], "b_conv2": sd["conv2d2.bias"],
        "conv3_w": sd["conv2d3.weight"], "b_conv3": sd["conv2d3.bias"],
        "conv_out_w": t(sd["conv_out.weight"]),
        "layers": {
            "attn_ln_w": stack("layers.{}.self_attn_layer_norm.weight"),
            "b_attn_ln": stack("layers.{}.self_attn_layer_norm.bias"),
            "wq": stack("layers.{}.self_attn.q_proj.weight", t),
            "b_q": stack("layers.{}.self_attn.q_proj.bias"),
            "wk": stack("layers.{}.self_attn.k_proj.weight", t),
            "b_k": stack("layers.{}.self_attn.k_proj.bias"),
            "wv": stack("layers.{}.self_attn.v_proj.weight", t),
            "b_v": stack("layers.{}.self_attn.v_proj.bias"),
            "wo": stack("layers.{}.self_attn.out_proj.weight", t),
            "b_o": stack("layers.{}.self_attn.out_proj.bias"),
            "final_ln_w": stack("layers.{}.final_layer_norm.weight"),
            "b_final_ln": stack("layers.{}.final_layer_norm.bias"),
            "fc1": stack("layers.{}.fc1.weight", t), "b_fc1": stack("layers.{}.fc1.bias"),
            "fc2": stack("layers.{}.fc2.weight", t), "b_fc2": stack("layers.{}.fc2.bias"),
        },
        "post_ln_w": sd["ln_post.weight"], "b_post_ln": sd["ln_post.bias"],
        "proj1_w": t(sd["proj1.weight"]), "b_proj1": sd["proj1.bias"],
        "proj2_w": t(sd["proj2.weight"]), "b_proj2": sd["proj2.bias"],
    }


class TestOmniAudioEncoder:
    def test_matches_hf(self):
        torch.manual_seed(0)
        hf = Qwen3OmniMoeAudioEncoder(Qwen3OmniMoeAudioEncoderConfig(**tiny_cfg())).eval()
        cfg = Qwen3OmniAudioConfig.from_hf(tiny_cfg())
        params = jax.tree.map(jnp.asarray, _load_params(hf))

        rng = np.random.RandomState(0)
        lens = [40, 23]  # irregular: full + tail chunks
        mels = [rng.randn(cfg.num_mel_bins, T).astype(np.float32) for T in lens]

        flat = np.concatenate(mels, axis=1)
        with torch.no_grad():
            theirs = hf(
                torch.tensor(flat), feature_lens=torch.tensor(lens)
            ).last_hidden_state.numpy()

        vin = prepare_audio_inputs(mels, cfg)
        ours = audio_forward(
            cfg, _fp32_backend(), params,
            jnp.asarray(vin["chunks"]), jnp.asarray(vin["gather_idx"]),
            jnp.asarray(vin["segment_ids"]),
        )
        assert ours.shape == theirs.shape
        np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4, rtol=1e-3)

    def test_output_lengths_match_prepared_tokens(self):
        cfg = Qwen3OmniAudioConfig.from_hf(tiny_cfg())
        lens = [40, 23, 16, 7]
        rng = np.random.RandomState(1)
        mels = [rng.randn(cfg.num_mel_bins, T).astype(np.float32) for T in lens]
        vin = prepare_audio_inputs(mels, cfg)
        assert vin["gather_idx"].shape[0] == int(audio_output_lengths(np.array(lens), cfg.chunk_len).sum())

    def test_grads_finite(self):
        cfg = Qwen3OmniAudioConfig.from_hf(tiny_cfg())
        params = init_audio_params(cfg, jax.random.key(0), jnp.float32)
        rng = np.random.RandomState(2)
        mels = [rng.randn(cfg.num_mel_bins, 40).astype(np.float32)]
        vin = prepare_audio_inputs(mels, cfg)

        def loss_fn(p):
            out = audio_forward(
                cfg, _fp32_backend(), p, jnp.asarray(vin["chunks"]),
                jnp.asarray(vin["gather_idx"]), jnp.asarray(vin["segment_ids"]),
            )
            return (out.astype(jnp.float32) ** 2).mean()

        grads = jax.grad(loss_fn)(params)
        assert all(np.all(np.isfinite(np.asarray(g))) for g in jax.tree.leaves(grads))
