"""KD divergence options: forward-KL (reference parity), reverse-KL, JS."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.ops.losses import kd_loss


def _rand_logits(key, shape):
    return jax.random.normal(key, shape) * 2.0


class TestKDDivergences:
    def setup_method(self):
        k1, k2 = jax.random.split(jax.random.key(0))
        self.s = _rand_logits(k1, (2, 4, 16))
        self.t = _rand_logits(k2, (2, 4, 16))
        self.labels = jnp.asarray([[1, 2, -100, 3], [4, -100, 5, 6]])

    def test_forward_kl_zero_at_equality(self):
        for div in ("forward_kl", "reverse_kl", "js"):
            v = kd_loss(self.t, self.t, self.labels, divergence=div)
            np.testing.assert_allclose(float(v), 0.0, atol=1e-5)

    def test_all_nonnegative_and_distinct(self):
        vals = {
            div: float(kd_loss(self.s, self.t, self.labels, divergence=div))
            for div in ("forward_kl", "reverse_kl", "js")
        }
        assert all(v > 0 for v in vals.values())
        # three genuinely different objectives
        assert len({round(v, 6) for v in vals.values()}) == 3
        # JS is bounded by ln(2) per token (temperature 1)
        assert vals["js"] <= np.log(2.0) + 1e-6

    def test_reverse_kl_is_mirrored_forward(self):
        fwd = float(kd_loss(self.s, self.t, self.labels, divergence="forward_kl"))
        rev = float(kd_loss(self.t, self.s, self.labels, divergence="reverse_kl"))
        np.testing.assert_allclose(fwd, rev, rtol=1e-5)

    def test_grads_flow_to_student_only_args(self):
        g = jax.grad(
            lambda s: kd_loss(s, self.t, self.labels, divergence="reverse_kl")
        )(self.s)
        assert np.isfinite(np.asarray(g)).all()
        # masked positions get no gradient
        assert np.abs(np.asarray(g)[0, 2]).max() == 0.0

    def test_unknown_divergence_raises(self):
        with pytest.raises(ValueError, match="forward_kl"):
            kd_loss(self.s, self.t, self.labels, divergence="hellinger")

    def test_temperature_scaling_matches_reference_contract(self):
        # T^2 scaling keeps gradient magnitude comparable across temperatures
        v1 = float(kd_loss(self.s, self.t, self.labels, temperature=1.0))
        v4 = float(kd_loss(self.s, self.t, self.labels, temperature=4.0))
        assert v1 > 0 and v4 > 0
