"""PEFT recipe end-to-end (analogue of reference hf_peft functional scenarios):
LoRA finetune on the virtual mesh — loss falls, checkpoints are adapter-only,
resume is exact, consolidated export merges the adapter."""

import json
import textwrap

import numpy as np
import pytest

from automodel_tpu.config.loader import load_config
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction
from automodel_tpu.utils import jax_compat

# see tests/unit/test_pipeline.py: pre-0.5 jax + XLA CPU cannot lower the
# PartitionId the pp ring's axis_index produces under partial-manual shard_map
pp_partial_manual_compiles = pytest.mark.skipif(
    jax_compat.SHIMMED,
    reason="jax<0.5 XLA CPU cannot lower PartitionId under partial-manual "
    "shard_map (pp ring axis_index)",
)


def _write_cfg(tmp_path, peft_extra="", max_steps=6, ckpt=False, consolidated=False, lr="3.0e-2"):
    cfg = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 128
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    distributed:
      dp_shard: 4
      tp: 2
    backend:
      dtype: float32
    peft:
      dim: 8
      alpha: 32
      {peft_extra}
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 256
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: 2
      max_steps: {max_steps}
      num_epochs: 10
      handle_sigterm: false
      ckpt_every_steps: {3 if ckpt else 0}
    optimizer:
      lr: {lr}
      weight_decay: 0.0
      max_grad_norm: 1.0
    lr_scheduler:
      lr_warmup_steps: 2
    checkpoint:
      enabled: {str(ckpt).lower()}
      checkpoint_dir: {tmp_path}/ckpt
      save_consolidated: {str(consolidated).lower()}
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg))
    return p


def _read_jsonl(path):
    from tests.functional.jsonl import metric_rows

    return metric_rows(path)


class TestPeftRecipeE2E:
    def test_lora_loss_decreases_and_base_frozen(self, tmp_path, cpu_devices):
        # match_all_linear covers lm_head — the mock arith task is head-dominated,
        # so attention/MLP-only adapters barely move loss in 20 steps
        cfg = load_config(_write_cfg(
            tmp_path, max_steps=20, lr="2.0e-2",
            peft_extra="dim: 16\n      match_all_linear: true",
        ))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        base_before = np.asarray(recipe.params["layers"]["wq"]).copy()
        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        losses = [r["loss"] for r in rows]
        assert losses[0] > 4.0
        assert losses[-1] < losses[0] - 0.1  # rank-8 adapter learns slower than full FT
        # base weights untouched; adapter b no longer zero
        np.testing.assert_array_equal(np.asarray(recipe.params["layers"]["wq"]), base_before)
        assert np.abs(np.asarray(recipe.train_params["layers"]["wq"]["lora_b"])).max() > 0

    def test_adapter_only_checkpoint_and_resume(self, tmp_path, cpu_devices):
        cfg = load_config(_write_cfg(tmp_path, ckpt=True))
        r1 = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        r1.run_train_validation_loop()
        rows1 = _read_jsonl(tmp_path / "out" / "training.jsonl")
        # checkpoint holds the adapter tree only: rank-r sized, no full weights
        import glob
        import os

        model_dir = tmp_path / "ckpt" / "step_3" / "model"
        assert model_dir.exists()
        sz = sum(os.path.getsize(f) for f in glob.glob(str(model_dir / "**"), recursive=True)
                 if os.path.isfile(f))
        n_full = sum(int(np.prod(p.shape)) for p in np.asarray(r1.params["layers"]["wq"])[None])
        assert sz < 4 * 1024 * 1024  # adapter is tiny; full model would be ~4MB+
        client = json.load(open(tmp_path / "ckpt" / "step_3" / "client.json"))
        assert client["peft_config"]["dim"] == 8

        import shutil

        shutil.rmtree(tmp_path / "ckpt" / "step_6")
        (tmp_path / "ckpt" / "latest").unlink()
        (tmp_path / "out" / "training.jsonl").unlink()
        cfg2 = load_config(_write_cfg(tmp_path, ckpt=True))
        r2 = TrainFinetuneRecipeForNextTokenPrediction(cfg2).setup()
        assert r2.step_scheduler.step == 3
        r2.run_train_validation_loop()
        rows2 = _read_jsonl(tmp_path / "out" / "training.jsonl")
        l1 = {r["step"]: r["loss"] for r in rows1}
        l2 = {r["step"]: r["loss"] for r in rows2}
        for s in (4, 5, 6):
            assert l2[s] == pytest.approx(l1[s], rel=1e-5), f"step {s} diverged"

    def test_qlora_int8_runs_and_base_stays_quantized(self, tmp_path, cpu_devices):
        cfg = load_config(_write_cfg(tmp_path, peft_extra="qlora: int8", max_steps=4))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        from automodel_tpu.quantization.qlora import is_quantized_leaf

        assert is_quantized_leaf(recipe.params["layers"]["wq"])
        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        assert all(np.isfinite(r["loss"]) for r in rows)
        assert is_quantized_leaf(recipe.params["layers"]["wq"])  # still int8 at rest

    def test_qat_fake_quant_runs(self, tmp_path, cpu_devices):
        # QAT without peft: fake-quantize weights in the forward, full finetune
        cfg_path = _write_cfg(tmp_path, max_steps=4)
        import re

        text = re.sub(
            r"peft:\n((?:  .*)?\n)+?(?=\S)",
            "qat:\n  enabled: true\n  weight_bits: 8\n  group_size: 16\n",
            cfg_path.read_text(),
        )
        cfg_path.write_text(text)
        cfg = load_config(cfg_path)
        assert cfg.get("peft") is None
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        assert all(np.isfinite(r["loss"]) for r in rows)
        assert rows[-1]["loss"] < rows[0]["loss"] + 0.1  # training not destabilized

    def test_dora_runs(self, tmp_path, cpu_devices):
        cfg = load_config(_write_cfg(tmp_path, peft_extra="use_dora: true", max_steps=3))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        assert all(np.isfinite(r["loss"]) for r in rows)
        assert "magnitude" in recipe.train_params["layers"]["wq"]


class TestCompositions:
    """The reference composes peft/qat/kd/pp through one sequencing path
    (infrastructure.py:303); every former fence now has a bit-exact
    pipelined-vs-unpipelined trajectory test."""

    @pp_partial_manual_compiles
    def test_peft_pp_matches_unpipelined_trajectory(self, tmp_path, cpu_devices):
        """peft + pp gradient correctness: the pp=2 LoRA training trajectory must
        reproduce the pp=1 (plain dp/tp) trajectory step for step — a far
        stronger check than loss-falls (the adapter merge happens outside the
        manual region, so schedules must not perturb grads)."""
        import json as _json

        def run(tag, dist):
            cfg_text = _write_cfg(
                tmp_path, max_steps=8, lr="2.0e-2",
                peft_extra="dim: 16\n      match_all_linear: true",
            ).read_text().replace("dp_shard: 4\n  tp: 2", dist)
            cfg_text = cfg_text.replace("num_hidden_layers: 2", "num_hidden_layers: 4")
            cfg_text = cfg_text.replace(f"output_dir: {tmp_path}/out", f"output_dir: {tmp_path}/{tag}")
            p = tmp_path / f"cfg_{tag}.yaml"
            p.write_text(cfg_text)
            r = TrainFinetuneRecipeForNextTokenPrediction(load_config(str(p)))
            r.setup()
            from automodel_tpu.peft.lora import count_lora_params

            assert count_lora_params(r.train_params) < 200_000
            r.run_train_validation_loop()
            return [row["loss"] for row in _read_jsonl(tmp_path / tag / "training.jsonl")]

        ref = run("pp1", "dp_shard: 4\n  tp: 2")
        got = run("pp2", "dp_shard: 2\n  tp: 2\n  pp: 2")
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    @pp_partial_manual_compiles
    def test_qat_pp_matches_unpipelined_trajectory(self, tmp_path, cpu_devices):
        """qat x pp (a round-2 fence): fake-quant is a param-level transform
        applied before the manual region, so the pp=2 trajectory must reproduce
        the unpipelined one step for step."""
        def run(tag, dist):
            cfg_text = _write_cfg(tmp_path, max_steps=6, lr="1.0e-2").read_text()
            cfg_text = cfg_text.replace("peft:\n  dim: 8\n  alpha: 32",
                                        "qat:\n  weight_bits: 8")
            cfg_text = cfg_text.replace("dp_shard: 4\n  tp: 2", dist)
            cfg_text = cfg_text.replace(f"output_dir: {tmp_path}/out",
                                        f"output_dir: {tmp_path}/{tag}")
            p = tmp_path / f"cfg_{tag}.yaml"
            p.write_text(cfg_text)
            r = TrainFinetuneRecipeForNextTokenPrediction(load_config(str(p)))
            r.setup()
            assert r.cfg.get("qat") is not None
            r.run_train_validation_loop()
            return [row["loss"] for row in _read_jsonl(tmp_path / tag / "training.jsonl")]

        ref = run("qat_pp1", "dp_shard: 4\n  tp: 2")
        got = run("qat_pp2", "dp_shard: 2\n  tp: 2\n  pp: 2")
        assert ref[-1] < ref[0]
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    @pp_partial_manual_compiles
    def test_qat_peft_composes_and_matches_pipelined(self, tmp_path, cpu_devices):
        """qat x peft (and x pp — the full stack of round-2 fences): the adapter
        trains in full precision over a fake-quantized base; pp=2 must match the
        unpipelined trajectory exactly."""

        def run(tag, dist):
            cfg_text = _write_cfg(
                tmp_path, max_steps=6, lr="5.0e-3",
                peft_extra="match_all_linear: true",
            ).read_text()
            cfg_text = cfg_text.replace("backend:", "qat:\n  weight_bits: 8\nbackend:")
            cfg_text = cfg_text.replace("dp_shard: 4\n  tp: 2", dist)
            cfg_text = cfg_text.replace(f"output_dir: {tmp_path}/out",
                                        f"output_dir: {tmp_path}/{tag}")
            p = tmp_path / f"cfg_{tag}.yaml"
            p.write_text(cfg_text)
            r = TrainFinetuneRecipeForNextTokenPrediction(load_config(str(p)))
            r.setup()
            assert r.peft is not None and r.cfg.get("qat") is not None
            r.run_train_validation_loop()
            return [row["loss"] for row in _read_jsonl(tmp_path / tag / "training.jsonl")]

        ref = run("qp_pp1", "dp_shard: 4\n  tp: 2")
        got = run("qp_pp2", "dp_shard: 2\n  tp: 2\n  pp: 2")
        assert np.isfinite(ref).all()
        assert ref[-1] < ref[0] + 0.1  # quantization noise: not destabilized
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    @pp_partial_manual_compiles
    def test_peft_dropout_pp_matches_unpipelined_trajectory(self, tmp_path, cpu_devices):
        """peft dropout x pp (a round-3 fence): the dropout rng threads through
        the pp step; with one microbatch per step the pp key derivation
        (split(rng, n_micro)[0]) coincides with the grad-accum path's
        per-microbatch keys, so the trajectories must match bit-exactly."""

        def run(tag, dist):
            cfg_text = _write_cfg(
                tmp_path, max_steps=8, lr="2.0e-2",
                peft_extra="dim: 16\n      match_all_linear: true\n      dropout: 0.15",
            ).read_text().replace("dp_shard: 4\n  tp: 2", dist)
            cfg_text = cfg_text.replace("num_hidden_layers: 2", "num_hidden_layers: 4")
            cfg_text = cfg_text.replace("grad_acc_steps: 2", "grad_acc_steps: 1")
            cfg_text = cfg_text.replace(f"output_dir: {tmp_path}/out",
                                        f"output_dir: {tmp_path}/{tag}")
            p = tmp_path / f"cfg_{tag}.yaml"
            p.write_text(cfg_text)
            r = TrainFinetuneRecipeForNextTokenPrediction(load_config(str(p)))
            r.setup()
            assert r.peft.dropout == 0.15 and r._step_needs_rng
            r.run_train_validation_loop()
            return [row["loss"] for row in _read_jsonl(tmp_path / tag / "training.jsonl")]

        ref = run("do_pp1", "dp_shard: 4\n  tp: 2")
        got = run("do_pp2", "dp_shard: 2\n  tp: 2\n  pp: 2")
        # dropout at lr 2e-2 makes the 8-step trajectory noisy — the parity
        # below (identical stochastic trajectories) is the actual check
        assert np.isfinite(ref).all()
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    def test_qat_peft_quantizes_base_not_adapter(self, tmp_path, cpu_devices):
        """Semantic pin: the qat x peft step-0 loss equals CE on
        merge(fake_quant(base), adapter) — quantized base, full-precision
        adapter (reference QLoRA-style QAT semantics)."""
        cfg = load_config(_write_cfg(tmp_path, max_steps=1, peft_extra="match_all_linear: true"))
        cfg["qat"] = {"weight_bits": 8}
        r = TrainFinetuneRecipeForNextTokenPrediction(cfg)
        r.setup()
        import jax

        from automodel_tpu.peft.lora import merge_lora_params

        mb = next(iter(r.dataloader))
        n = int((np.asarray(mb["labels"]) != -100).sum())
        qfn = r._qat_param_fn()
        merged_q = merge_lora_params(qfn(r.params), r.train_params, r.peft)
        want = float(r._forward_loss(merged_q, jax.tree.map(np.asarray, mb), n))
        merged_plain = merge_lora_params(r.params, r.train_params, r.peft)
        plain = float(r._forward_loss(merged_plain, jax.tree.map(np.asarray, mb), n))
        assert want != plain  # quantization must actually bite
        # the compiled step must see the quantized-base loss
        got = r._train_step(
            r.train_params, r.opt_state,
            {k: np.asarray(v)[None] for k, v in mb.items()}, r.params,
        )[2]["loss"]
        np.testing.assert_allclose(float(got), want, rtol=2e-5)
