"""Training-dynamics & numerics telemetry (observability/dynamics.py).

Hand-checked per-subtree norm math, sharded-vs-replicated equality on the
8-device mesh, nonfinite provenance, the loss-spike flight recorder's
never-raise contract, SIGUSR2 snapshot handler hygiene, dense/pp metric
key-set parity, cross-host grad-norm divergence flagging, and the layer
attribution that rides anomaly verdicts into rollback events.
"""

from __future__ import annotations

import json
import math
import signal

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from automodel_tpu.observability.dynamics import (
    DynamicsConfig,
    DynamicsStats,
    DynamicsTracker,
    SpikeFlightRecorder,
    batch_fingerprint,
    bucket_for_path,
    dynamics_tree,
    first_nonfinite_bucket,
    flatten_dynamics,
    nonfinite_provenance,
    subtree_sq_norms,
)


def _paths_of(tree):
    return {
        bucket_for_path(path)
        for path, _ in jax.tree_util.tree_flatten_with_path(tree)[0]
    }


def _toy_params():
    return {
        "embed": jnp.asarray([3.0, 4.0]),
        "layers": {
            "wq": jnp.asarray([1.0, 2.0, 2.0]),
            "w_up": jnp.asarray([2.0]),
        },
        "lm_head": jnp.asarray([6.0, 8.0]),
    }


class TestBucketTaxonomy:
    def test_top_level_modules_are_own_buckets(self):
        tree = {"embed": jnp.zeros(2), "final_norm": jnp.zeros(2),
                "lm_head": jnp.zeros(2)}
        assert _paths_of(tree) == {"embed", "final_norm", "lm_head"}

    def test_layer_leaves_follow_scope_blocks(self):
        tree = {"layers": {
            "wq": jnp.zeros(2), "wo": jnp.zeros(2), "q_norm": jnp.zeros(2),
            "w_gate": jnp.zeros(2), "w_down": jnp.zeros(2),
            "moe": {"w_gate": jnp.zeros(2)}, "router": jnp.zeros(2),
            "input_norm": jnp.zeros(2),
        }}
        got = _paths_of(tree)
        assert got == {"layers.attention", "layers.mlp", "layers.moe",
                       "layers.other"}

    def test_moe_wins_over_mlp_inside_moe_subtree(self):
        # ("layers", "moe", "w_gate"): the moe component must classify before
        # the mlp-prefix w_gate does
        tree = {"layers": {"moe": {"w_gate": jnp.zeros(2)}}}
        assert _paths_of(tree) == {"layers.moe"}

    def test_peft_tree_buckets_with_base_name(self):
        tree = {"layers": {"wq": {"lora_a": jnp.zeros(2), "lora_b": jnp.zeros(2)}}}
        assert _paths_of(tree) == {"layers.attention"}


class TestSubtreeNorms:
    def test_hand_checked_sums_of_squares(self):
        sq = subtree_sq_norms(_toy_params())
        assert float(sq["embed"]) == pytest.approx(25.0)
        assert float(sq["layers.attention"]) == pytest.approx(9.0)
        assert float(sq["layers.mlp"]) == pytest.approx(4.0)
        assert float(sq["lm_head"]) == pytest.approx(100.0)

    def test_non_float_leaves_ignored(self):
        sq = subtree_sq_norms({"embed": jnp.asarray([3.0, 4.0]),
                               "step": jnp.asarray(7, jnp.int32)})
        assert set(sq) == {"embed"}

    def test_sharded_matches_replicated_on_mesh8(self, mesh8):
        """The reductions are sharding-transparent: same scalars whether the
        leaves live sharded across the mesh or replicated, with partitionable
        threefry active (the training default)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        prev = jax.config.jax_threefry_partitionable
        jax.config.update("jax_threefry_partitionable", True)
        try:
            host = {
                "embed": np.linspace(-1.0, 1.0, 64, dtype=np.float32).reshape(8, 8),
                "layers": {"wq": np.arange(32, dtype=np.float32).reshape(8, 4)},
            }
            axis = mesh8.axis_names[0]
            sharded = jax.tree.map(
                lambda x: jax.device_put(x, NamedSharding(mesh8, P(axis))), host)
            replicated = jax.tree.map(
                lambda x: jax.device_put(x, NamedSharding(mesh8, P())), host)
            sq_s = jax.jit(subtree_sq_norms)(sharded)
            sq_r = jax.jit(subtree_sq_norms)(replicated)
            for bucket in sq_r:
                assert float(sq_s[bucket]) == pytest.approx(
                    float(sq_r[bucket]), rel=1e-6)
        finally:
            jax.config.update("jax_threefry_partitionable", prev)


class TestDynamicsTree:
    def test_hand_checked_norms_and_ratio(self):
        params = _toy_params()
        grads = jax.tree.map(lambda p: 0.1 * jnp.ones_like(p), params)
        updates = jax.tree.map(lambda p: 0.01 * jnp.ones_like(p), params)
        tree = dynamics_tree(grads, params, updates)
        emb = tree["embed"]
        assert float(emb["grad_norm"]) == pytest.approx(0.1 * math.sqrt(2))
        assert float(emb["param_norm"]) == pytest.approx(5.0)
        assert float(emb["upd_ratio"]) == pytest.approx(0.01 * math.sqrt(2) / 5.0)
        assert "moment_norm" not in emb  # no opt_state passed

    def test_moment_norm_from_adam_state(self):
        params = _toy_params()
        opt = optax.adam(1e-3)
        state = opt.init(params)
        grads = jax.tree.map(jnp.ones_like, params)
        _, state = opt.update(grads, state, params)
        tree = dynamics_tree(grads, params, grads, state)
        # adam mu after one step = (1-b1)*g; per-bucket norm follows leaf counts
        assert float(tree["embed"]["moment_norm"]) == pytest.approx(
            0.1 * math.sqrt(2), rel=1e-5)
        assert float(tree["layers.attention"]["moment_norm"]) == pytest.approx(
            0.1 * math.sqrt(3), rel=1e-5)

    def test_stateless_optimizer_omits_moment_norm(self):
        params = _toy_params()
        opt = optax.sgd(1e-2)
        state = opt.init(params)
        grads = jax.tree.map(jnp.ones_like, params)
        tree = dynamics_tree(grads, params, grads, state)
        assert all("moment_norm" not in row for b, row in tree.items() if b != "num")

    def test_numerics_bucket_hand_checked(self):
        grads = {"embed": jnp.asarray([1.0, 500.0, 1e5, jnp.inf])}
        params = {"embed": jnp.ones(4)}
        tree = dynamics_tree(grads, params, grads)
        num = tree["num"]
        assert not math.isfinite(float(num["grad_amax"]))
        assert float(num["e4m3_sat_frac"]) == pytest.approx(3 / 4)  # >= 448
        assert float(num["e5m2_sat_frac"]) == pytest.approx(2 / 4)  # >= 57344
        assert float(num["nonfinite_ct"]) == 1.0

    def test_flatten_key_contract(self):
        params = _toy_params()
        grads = jax.tree.map(jnp.ones_like, params)
        flat = flatten_dynamics(dynamics_tree(grads, params, grads))
        assert "dynamics/layers.attention/grad_norm" in flat
        assert "dynamics/layers.mlp/upd_ratio" in flat
        assert "dynamics/num/grad_amax" in flat
        assert all(isinstance(v, float) for v in flat.values())


class TestNonfiniteProvenance:
    def test_names_offending_subtree(self):
        grads = {"embed": jnp.ones(2),
                 "layers": {"wq": jnp.asarray([1.0, jnp.nan])}}
        prov = jax.jit(nonfinite_provenance)(grads, jnp.float32(1.0))
        assert first_nonfinite_bucket(jax.device_get(prov)) == "layers.attention"

    def test_loss_only_nonfinite_names_loss(self):
        grads = {"embed": jnp.ones(2)}
        prov = nonfinite_provenance(grads, jnp.float32(jnp.inf))
        assert first_nonfinite_bucket(jax.device_get(prov)) == "loss"

    def test_all_finite_returns_none(self):
        prov = nonfinite_provenance({"embed": jnp.ones(2)}, jnp.float32(1.0))
        assert first_nonfinite_bucket(jax.device_get(prov)) is None


class TestDenseVsPipelineParity:
    def test_metric_keyset_parity(self):
        """make_train_step and make_pp_train_step must emit the same dynamics
        metric contract (same top-level keys, same buckets, same per-bucket
        metrics, same nonfinite_map keys)."""
        from automodel_tpu.training.train_step import (
            make_pp_train_step, make_train_step)

        params = _toy_params()
        opt = optax.adam(1e-3)

        def fwd_micro(p, batch, n):
            return jnp.sum(p["embed"]) * jnp.mean(batch["labels"] * 0.0 + 1.0) / n

        def fwd_stack(p, stack, n):
            return jnp.sum(p["embed"]) * jnp.mean(stack["labels"] * 0.0 + 1.0) / n

        stack = {"labels": jnp.ones((2, 4), jnp.int32)}
        dense = make_train_step(fwd_micro, opt, guard_nonfinite=True, dynamics=True)
        pp = make_pp_train_step(fwd_stack, opt, guard_nonfinite=True, dynamics=True)
        *_, m_dense = jax.jit(dense)(params, opt.init(params), stack)
        *_, m_pp = jax.jit(pp)(params, opt.init(params), stack)

        assert sorted(m_dense) == sorted(m_pp)
        assert sorted(m_dense["dynamics"]) == sorted(m_pp["dynamics"])
        for bucket in m_dense["dynamics"]:
            assert sorted(m_dense["dynamics"][bucket]) == sorted(
                m_pp["dynamics"][bucket]), bucket
        assert sorted(m_dense["nonfinite_map"]) == sorted(m_pp["nonfinite_map"])

    def test_dynamics_off_adds_no_keys(self):
        from automodel_tpu.training.train_step import make_train_step

        params = _toy_params()
        opt = optax.adam(1e-3)

        def fwd(p, batch, n):
            return jnp.sum(p["embed"]) / n

        stack = {"labels": jnp.ones((2, 4), jnp.int32)}
        step = make_train_step(fwd, opt)
        *_, metrics = jax.jit(step)(params, opt.init(params), stack)
        assert "dynamics" not in metrics and "nonfinite_map" not in metrics


class TestDynamicsStats:
    def test_ema_seeds_then_smooths(self):
        stats = DynamicsStats(ema_decay=0.9)
        out = stats.update({"dynamics/embed/grad_norm": 1.0})
        assert out["dynamics/embed/grad_norm_ema"] == pytest.approx(1.0)
        out = stats.update({"dynamics/embed/grad_norm": 2.0})
        assert out["dynamics/embed/grad_norm_ema"] == pytest.approx(1.1)

    def test_suspect_names_worst_excursion(self):
        stats = DynamicsStats()
        base = {"dynamics/embed/grad_norm": 1.0,
                "dynamics/layers.mlp/grad_norm": 1.0}
        stats.update(base)
        stats.update({"dynamics/embed/grad_norm": 1.1,
                      "dynamics/layers.mlp/grad_norm": 50.0})
        layer, metric, ratio = stats.suspect()
        assert (layer, metric) == ("layers.mlp", "grad_norm")
        assert ratio == pytest.approx(50.0, rel=0.01)

    def test_param_norm_excursion_outranks_grad_norm(self):
        # corrupted lm_head weights: every upstream subtree's grad blows up
        # MORE than the fault's param norm did, but the weights only jumped
        # in lm_head — param-norm excursions localize, grad blowups propagate
        stats = DynamicsStats()
        stats.update({"dynamics/lm_head/param_norm": 2.5,
                      "dynamics/lm_head/grad_norm": 0.5,
                      "dynamics/final_norm/grad_norm": 0.05})
        stats.update({"dynamics/lm_head/param_norm": 2500.0,
                      "dynamics/lm_head/grad_norm": 1.0,
                      "dynamics/final_norm/grad_norm": 250.0})
        layer, metric, ratio = stats.suspect()
        assert (layer, metric) == ("lm_head", "param_norm")
        assert ratio == pytest.approx(1000.0, rel=0.01)

    def test_grad_norm_attributes_when_weights_are_clean(self):
        # a bad batch spikes grads without moving any param norm: grad-norm
        # attribution still works (no param excursion to outrank it)
        stats = DynamicsStats()
        stats.update({"dynamics/embed/grad_norm": 1.0,
                      "dynamics/embed/param_norm": 4.0})
        stats.update({"dynamics/embed/grad_norm": 80.0,
                      "dynamics/embed/param_norm": 4.01})
        layer, metric, _ = stats.suspect()
        assert (layer, metric) == ("embed", "grad_norm")

    def test_upd_ratio_never_attributes(self):
        # upd_ratio tracks the lr schedule; a warmup must not blame a layer
        stats = DynamicsStats()
        stats.update({"dynamics/embed/upd_ratio": 1e-6})
        stats.update({"dynamics/embed/upd_ratio": 1e-2})
        assert stats.suspect() is None

    def test_nan_sample_does_not_poison_trend(self):
        stats = DynamicsStats()
        stats.update({"dynamics/embed/grad_norm": 1.0})
        stats.update({"dynamics/embed/grad_norm": float("nan")})
        out = stats.update({"dynamics/embed/grad_norm": 1.0})
        assert math.isfinite(out["dynamics/embed/grad_norm_ema"])

    def test_num_bucket_excluded(self):
        stats = DynamicsStats()
        stats.update({"dynamics/num/grad_amax": 1.0})
        stats.update({"dynamics/num/grad_amax": 1e9})
        assert stats.suspect() is None


class TestSpikeFlightRecorder:
    def _warm(self, rec, n=16, loss=2.0):
        for i in range(n):
            assert rec.observe(i, loss + 0.001 * (i % 3)) is None

    def test_excursion_returns_zscore_and_stays_out_of_window(self, tmp_path):
        rec = SpikeFlightRecorder(str(tmp_path), zscore_threshold=6.0)
        self._warm(rec)
        z = rec.observe(16, 50.0)
        assert z is not None and z > 6.0
        # the spike never entered the window: the next baseline loss is clean
        assert rec.observe(17, 2.0) is None

    def test_nonfinite_loss_scores_inf(self, tmp_path):
        rec = SpikeFlightRecorder(str(tmp_path))
        assert rec.observe(0, float("nan")) == math.inf
        assert rec.observe(1, float("inf")) == math.inf

    def test_no_judgement_before_min_history(self, tmp_path):
        rec = SpikeFlightRecorder(str(tmp_path), min_history=8)
        for i in range(7):
            assert rec.observe(i, 1000.0 if i == 6 else 1.0) is None

    def test_dump_writes_report_with_suspect_and_batch(self, tmp_path):
        rec = SpikeFlightRecorder(str(tmp_path))
        self._warm(rec)
        rec.record_dynamics(15, {"dynamics/layers.mlp/grad_norm": 42.0})
        rec.record_row(15, {"loss": 2.0})
        path = rec.dump(16, "loss_zscore", loss=50.0, zscore=12.3,
                        suspect=("layers.mlp", "grad_norm", 40.0),
                        batch={"input_ids_shape": [2, 4]})
        doc = json.loads((tmp_path / "spike_report.json").read_text())
        assert path == str(tmp_path / "spike_report.json")
        assert doc["suspect"] == {"layer": "layers.mlp", "metric": "grad_norm",
                                  "ratio_vs_ema": 40.0}
        assert doc["batch"]["input_ids_shape"] == [2, 4]
        assert doc["dynamics_history"][-1]["dynamics/layers.mlp/grad_norm"] == 42.0
        assert len(doc["loss_window"]) == 16

    def test_dump_never_raises(self, tmp_path, monkeypatch):
        rec = SpikeFlightRecorder(str(tmp_path))

        def boom(*a, **k):
            raise OSError("disk full")

        monkeypatch.setattr("builtins.open", boom)
        assert rec.dump(5, "loss_zscore") is None  # logged, not raised

    def test_cooldown_rate_limits(self, tmp_path):
        rec = SpikeFlightRecorder(str(tmp_path), cooldown_steps=50)
        rec.dump(100, "loss_zscore")
        assert rec.in_cooldown(120)
        assert not rec.in_cooldown(151)


class TestBatchFingerprint:
    def test_shapes_and_crc(self):
        stack = {"input_ids": np.arange(8, dtype=np.int32).reshape(2, 4),
                 "labels": np.ones((2, 4), np.int32)}
        fp = batch_fingerprint(stack)
        assert fp["input_ids_shape"] == [2, 4]
        assert isinstance(fp["input_ids_crc32"], int)
        # content-sensitive: a different batch fingerprints differently
        fp2 = batch_fingerprint({"input_ids": np.zeros((2, 4), np.int32)})
        assert fp2["input_ids_crc32"] != fp["input_ids_crc32"]

    def test_never_raises(self):
        class Evil:
            def get(self, key):
                raise RuntimeError("boom")

        assert batch_fingerprint(Evil()) == {"fingerprint_error": True}


class TestDynamicsTracker:
    def _tracker(self, tmp_path, **kw):
        cfg = DynamicsConfig(enabled=True, **kw)
        return DynamicsTracker(cfg, str(tmp_path))

    def test_cadence(self, tmp_path):
        t = self._tracker(tmp_path, every_n_steps=10)
        assert t.due(0) and t.due(10) and not t.due(7)

    def test_row_folds_ema_and_amax_history(self, tmp_path):
        t = self._tracker(tmp_path)
        params = _toy_params()
        grads = jax.tree.map(jnp.ones_like, params)
        flat = t.row(0, dynamics_tree(grads, params, grads))
        assert "dynamics/embed/grad_norm_ema" in flat
        assert "dynamics/num/amax_hist_max" in flat
        assert "dynamics/num/e5m2_margin_log2" in flat
        assert len(t.recorder._dyn_rows) == 1

    def test_sigusr2_snapshot_roundtrip(self, tmp_path):
        t = self._tracker(tmp_path).start()
        try:
            assert t.maybe_snapshot(1) is None  # nothing pending
            signal.raise_signal(signal.SIGUSR2)
            path = t.maybe_snapshot(2)
            assert path is not None
            doc = json.loads((tmp_path / "dynamics_snapshot.json").read_text())
            assert doc["dynamics_snapshot"] and doc["step"] == 2
            assert t.maybe_snapshot(3) is None  # request drained
        finally:
            t.close()

    def test_handler_restore_is_sig_ign_faithful(self, tmp_path):
        prev = signal.signal(signal.SIGUSR2, signal.SIG_IGN)
        try:
            t = self._tracker(tmp_path).start()
            assert signal.getsignal(signal.SIGUSR2) == t._handle_signal
            t.close()
            assert signal.getsignal(signal.SIGUSR2) == signal.SIG_IGN
            t.close()  # idempotent
            assert signal.getsignal(signal.SIGUSR2) == signal.SIG_IGN
        finally:
            signal.signal(signal.SIGUSR2, prev)

    def test_signal_none_disables_handler(self, tmp_path):
        before = signal.getsignal(signal.SIGUSR2)
        t = DynamicsTracker(DynamicsConfig(enabled=True, snapshot_signal=None),
                            str(tmp_path)).start()
        assert signal.getsignal(signal.SIGUSR2) == before
        t.close()

    def test_config_from_dict_bool_and_dict(self):
        assert DynamicsConfig.from_dict(True).enabled
        assert not DynamicsConfig.from_dict(False).enabled
        cfg = DynamicsConfig.from_dict({"every_n_steps": 5, "spike_zscore": 4.0})
        assert cfg.enabled and cfg.every_n_steps == 5 and cfg.spike_zscore == 4.0
        assert DynamicsConfig.from_dict(
            {"snapshot_signal": "none"}).resolve_signal() is None


class TestAmaxHistory:
    def test_rolling_max_and_margin(self):
        from automodel_tpu.ops.fp8 import E5M2_MAX, AmaxHistory

        h = AmaxHistory(window=4)
        out = h.update(100.0)
        assert out["dynamics/num/amax_hist_max"] == pytest.approx(100.0)
        assert out["dynamics/num/e5m2_margin_log2"] == pytest.approx(
            math.log2(E5M2_MAX / 100.0), abs=1e-3)
        h.update(500.0)
        assert h.update(10.0)["dynamics/num/amax_hist_max"] == pytest.approx(500.0)
        for _ in range(4):  # 500 rolls out of the window
            out = h.update(10.0)
        assert out["dynamics/num/amax_hist_max"] == pytest.approx(10.0)

    def test_nonfinite_samples_skipped(self):
        from automodel_tpu.ops.fp8 import AmaxHistory

        h = AmaxHistory()
        assert h.update(float("inf")) == {}  # empty window -> no row fields
        assert h.update(2.0)["dynamics/num/amax_hist_max"] == pytest.approx(2.0)


class TestCrossHostDivergence:
    def _agg(self, rows, keys, rtol=1e-4):
        from automodel_tpu.observability.aggregate import CrossHostAggregator

        return CrossHostAggregator(
            keys=keys, allgather_fn=lambda vec: [list(r) for r in rows],
            process_count=len(rows), divergence_rtol=rtol)

    def test_host_keys_widening(self):
        from automodel_tpu.observability.aggregate import (
            DYNAMICS_HOST_KEYS, HOST_KEYS, MOE_HOST_KEYS, host_keys)

        assert host_keys() == HOST_KEYS
        assert host_keys(moe=True) == MOE_HOST_KEYS
        assert host_keys(dynamics=True) == HOST_KEYS + DYNAMICS_HOST_KEYS
        assert host_keys(moe=True, dynamics=True) == (
            MOE_HOST_KEYS + DYNAMICS_HOST_KEYS)

    def test_agreeing_replicas_not_flagged(self):
        from automodel_tpu.observability.aggregate import host_keys

        keys = host_keys(dynamics=True)
        rows = [[0.5, 0.01, 8.0, 8.0, 1.25] for _ in range(8)]
        out = self._agg(rows, keys).aggregate(
            {"step_time_s": 0.5, "grad_norm": 1.25})
        assert "divergent_host" not in out
        assert out["host/grad_norm_max"] == pytest.approx(1.25)

    def test_desynced_replica_flagged(self):
        from automodel_tpu.observability.aggregate import host_keys

        keys = host_keys(dynamics=True)
        rows = [[0.5, 0.01, 8.0, 8.0, 1.25] for _ in range(8)]
        rows[3][4] = 1.30  # 4% off the replicated scalar: desync, not noise
        out = self._agg(rows, keys).aggregate(
            {"step_time_s": 0.5, "grad_norm": 1.25})
        assert out["divergent_host"] == 3
        assert out["divergence_rel"] == pytest.approx(0.04, rel=0.05)

    def test_single_nan_host_flagged_infinite(self):
        from automodel_tpu.observability.aggregate import host_keys

        keys = host_keys(dynamics=True)
        rows = [[0.5, 0.01, 8.0, 8.0, 1.25] for _ in range(8)]
        rows[6][4] = math.nan
        out = self._agg(rows, keys).aggregate(
            {"step_time_s": 0.5, "grad_norm": 1.25})
        assert out["divergent_host"] == 6
        assert out["divergence_rel"] == math.inf

    def test_float_noise_within_rtol_ignored(self):
        from automodel_tpu.observability.aggregate import host_keys

        keys = host_keys(dynamics=True)
        rows = [[0.5, 0.01, 8.0, 8.0, 1.25 + i * 1e-8] for i in range(8)]
        out = self._agg(rows, keys).aggregate(
            {"step_time_s": 0.5, "grad_norm": 1.25})
        assert "divergent_host" not in out

    def test_legacy_wire_has_no_divergence_keys(self):
        rows = [[0.5, 0.01, 8.0, 8.0] for _ in range(8)]
        from automodel_tpu.observability.aggregate import HOST_KEYS

        out = self._agg(rows, HOST_KEYS).aggregate({"step_time_s": 0.5})
        assert "divergent_host" not in out


class TestLayerAttribution:
    def _manager(self, sink):
        from automodel_tpu.resilience.manager import ResilienceManager

        return ResilienceManager.from_config(
            {"enabled": True,
             "anomaly": {"window": 8, "min_history": 4, "zscore_threshold": 6.0},
             "max_skipped_updates": 2},
            metric_sink=sink)

    def test_nonfinite_verdict_carries_layer(self):
        events = []
        mgr = self._manager(lambda step, **f: events.append((step, f)))
        action = mgr.on_step(5, float("nan"), 1.0, nonfinite=True,
                             layer="layers.attention")
        assert action == "skip_update"
        assert mgr.last_verdict.layer == "layers.attention"
        assert events[-1][1]["resilience/layer"] == "layers.attention"

    def test_rollback_done_cites_layer_from_last_verdict(self):
        events = []
        mgr = self._manager(lambda step, **f: events.append((step, f)))
        mgr.on_step(5, float("nan"), 1.0, nonfinite=True, layer="layers.mlp")
        mgr.note_rollback(from_step=5, to_step=0, skipped_steps=5)
        done = [f for _, f in events
                if f.get("resilience/event") == "rollback_done"]
        assert done and done[0]["resilience/layer"] == "layers.mlp"

    def test_clean_step_has_no_layer(self):
        mgr = self._manager(lambda step, **f: None)
        for i in range(6):
            mgr.on_step(i, 2.0, 1.0)
        assert mgr.last_verdict.layer is None


class TestTimelineCounters:
    def test_counters_from_flat_groups_by_metric(self, tmp_path):
        from automodel_tpu.observability.events import TraceTimeline

        tl = TraceTimeline(str(tmp_path / "timeline.json"))
        tl.counters_from_flat({
            "dynamics/embed/grad_norm": 1.0,
            "dynamics/layers.mlp/grad_norm": 2.0,
            "dynamics/embed/param_norm": 3.0,
            "dynamics/num/grad_amax": 4.0,
            "not/a/dynamics-key": 5.0,
            "dynamics/two_part_only": 6.0,
        })
        counters = [e for e in tl._events if e["ph"] == "C"]
        by_name = {e["name"]: e["args"] for e in counters}
        assert by_name["dynamics/grad_norm"] == {"embed": 1.0, "layers.mlp": 2.0}
        assert by_name["dynamics/param_norm"] == {"embed": 3.0}
        assert by_name["dynamics/grad_amax"] == {"num": 4.0}
        assert "not/a/dynamics-key" not in by_name
        assert len(counters) == 3


class TestRegressionGateDynamicsRows:
    def test_matrix_key_dyn_suffix(self):
        from automodel_tpu.observability.regression import _matrix_key

        row = {"model": "dense", "seq_len": 2048, "prefetch": False}
        assert _matrix_key(row) == "matrix/dense_s2048_pfoff"
        assert _matrix_key({**row, "dynamics": True}) == "matrix/dense_s2048_pfoff_dyn"
