"""Fault-tolerant training: anomaly rollback, checkpoint integrity + fallback
restore, coordinated preemption, transient-fault retry, and a deterministic
fault-injection harness (docs/resilience.md)."""

from automodel_tpu.resilience.anomaly import AnomalyDetector, RecoveryPolicy, Verdict
from automodel_tpu.resilience.chaos import ChaosConfig, ChaosInjector, FlakyIO
from automodel_tpu.resilience.config import (
    AnomalyConfig, PreemptionConfig, ResilienceConfig, RollbackConfig,
)
from automodel_tpu.resilience.manager import ResilienceManager

__all__ = [
    "AnomalyConfig",
    "AnomalyDetector",
    "ChaosConfig",
    "ChaosInjector",
    "FlakyIO",
    "PreemptionConfig",
    "RecoveryPolicy",
    "ResilienceConfig",
    "ResilienceManager",
    "RollbackConfig",
    "Verdict",
]
