#!/usr/bin/env python
"""Render a run's run-lifetime goodput ledger (docs/observability.md
"Run-level goodput & SLOs").

    python tools/goodput_report.py RUN_DIR            # table from run_ledger.json
    python tools/goodput_report.py RUN_DIR --rebuild  # restitch from artifacts
    python tools/goodput_report.py RUN_DIR --json     # the ledger document

The supervisor keeps ``run_ledger.json`` current after every episode;
``--rebuild`` restitches it from ``training.jsonl`` + ``supervisor_report.json``
(useful for unsupervised runs, or after hand-editing artifacts in a postmortem).
Exit codes: 0 = rendered, 1 = schema problems, 2 = no ledger and nothing to
build one from.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from automodel_tpu.observability import runledger  # noqa: E402


def _fmt_table(ledger: dict) -> str:
    lines = []
    wall = ledger.get("wall_s") or 0.0
    lines.append(f"run {ledger.get('run_id') or '?'}  status={ledger.get('status')}  "
                 f"wall={wall:.1f}s  episodes={len(ledger.get('episodes') or [])}  "
                 f"restarts={ledger.get('restarts')}")
    lines.append(f"goodput_e2e {ledger.get('goodput_e2e', 0.0) * 100:6.2f}%  "
                 f"({ledger.get('goodput_s', 0.0):.1f}s device-step that stuck)")
    lines.append(f"{'badput class':<18} {'seconds':>10} {'frac':>8}")
    badput = ledger.get("badput") or {}
    fracs = ledger.get("badput_frac") or {}
    for cls in runledger.BADPUT_CLASSES:
        sec = badput.get(cls, 0.0)
        if not sec:
            continue
        lines.append(f"{cls:<18} {sec:>10.2f} {fracs.get(cls, 0.0) * 100:>7.2f}%")
    lines.append(f"wasted_steps={ledger.get('wasted_steps')}  "
                 f"productive_steps={ledger.get('productive_steps')}  "
                 f"final_step={ledger.get('final_step')}")
    rec = ledger.get("recovery") or {}
    if rec:
        lines.append(f"{'recovery class':<18} {'count':>6} {'mean_s':>9} {'max_s':>9}")
        for cls, st in rec.items():
            lines.append(f"{cls:<18} {st.get('count', 0):>6} "
                         f"{st.get('mean_s', 0.0):>9.2f} {st.get('max_s', 0.0):>9.2f}")
    for ep in ledger.get("episodes") or []:
        steps = ep.get("steps")
        span = f"steps {steps[0]}..{steps[1]}" if steps else "no steps"
        tail = f"  recovery={ep['recovery_s']:.2f}s" \
            if ep.get("recovery_s") is not None else ""
        lines.append(f"  episode {ep['index']}: {span}  "
                     f"wasted={ep.get('wasted_steps', 0)}  "
                     f"taxonomy={ep.get('taxonomy') or '-'}{tail}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="goodput_report",
                                     description=__doc__.splitlines()[0])
    parser.add_argument("run_dir", help="run directory (or a run_ledger.json path)")
    parser.add_argument("--json", action="store_true",
                        help="print the ledger document instead of the table")
    parser.add_argument("--rebuild", action="store_true",
                        help="restitch the ledger from the run's artifacts "
                             "before rendering")
    args = parser.parse_args(argv)

    path = args.run_dir
    run_dir = path if os.path.isdir(path) else os.path.dirname(path) or "."
    ledger = None
    if args.rebuild or (os.path.isdir(path) and not os.path.exists(
            os.path.join(path, runledger.LEDGER_FILENAME))):
        ledger = runledger.update_run_ledger(run_dir)
    if ledger is None:
        try:
            ledger = runledger.load_ledger(path)
        except (OSError, json.JSONDecodeError):
            ledger = runledger.update_run_ledger(run_dir)
    if ledger is None:
        print(f"goodput_report: no ledger at {path} and no artifacts to "
              f"build one from (training.jsonl / supervisor_report.json)",
              file=sys.stderr)
        return 2
    problems = runledger.validate_ledger(ledger)
    if args.json:
        print(json.dumps(ledger, indent=2, sort_keys=True))
    else:
        print(_fmt_table(ledger))
    for p in problems:
        print(f"goodput_report: SCHEMA: {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
