"""Ministral-3: yarn mscale-pair attention factor, llama-4 long-context q scaling.
(No HF implementation in this transformers version; reference mistral3/model.py is
the spec, so checks are semantic self-consistency against the plain Llama path.)"""

import numpy as np

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.llama.model import LlamaForCausalLM
from automodel_tpu.models.mistral3.model import Ministral3Config, Ministral3ForCausalLM
from automodel_tpu.ops.rope import rope_attention_scaling


def _hf_cfg(**kw):
    base = dict(
        architectures=["Ministral3ForCausalLM"], vocab_size=128, hidden_size=64,
        intermediate_size=96, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, max_position_embeddings=32,
        rope_parameters=dict(
            rope_type="yarn", rope_theta=1e6, factor=16.0, beta_fast=32.0, beta_slow=1.0,
            mscale=1.0, mscale_all_dim=1.0, original_max_position_embeddings=8,
            llama_4_scaling_beta=0.1, truncate=True,
        ),
    )
    base.update(kw)
    return base


def _fp32_backend():
    return BackendConfig(dtype="float32", remat_policy="full")


class TestYarnAttentionFactor:
    def test_mscale_pair_cancels(self):
        # transformers _compute_yarn_parameters: mscale == mscale_all_dim -> factor 1.0
        rs = dict(rope_type="yarn", factor=16.0, mscale=1.0, mscale_all_dim=1.0)
        assert rope_attention_scaling(rs) == 1.0

    def test_mscale_default_when_absent(self):
        rs = dict(rope_type="yarn", factor=16.0)
        expected = 0.1 * np.log(16.0) + 1.0
        assert abs(rope_attention_scaling(rs) - expected) < 1e-9

    def test_explicit_attention_factor_wins(self):
        rs = dict(rope_type="yarn", factor=16.0, attention_factor=1.25, mscale=2.0, mscale_all_dim=1.0)
        assert rope_attention_scaling(rs) == 1.25


class TestMinistral3:
    def test_config_mapping(self):
        cfg = Ministral3Config.from_hf(_hf_cfg())
        assert cfg.rope_theta == 1e6
        assert cfg.rope_scaling["rope_type"] == "yarn"
        assert cfg.llama4_attn_scale_beta == 0.1
        assert cfg.original_max_position_embeddings == 8

    def test_llama4_scale_only_affects_long_positions(self):
        """Positions < original_max have floor(pos/orig)=0 -> scale 1, so logits there
        must match a model with the scaling disabled; later positions must differ."""
        cfg = Ministral3Config.from_hf(_hf_cfg())
        model = Ministral3ForCausalLM(cfg, _fp32_backend())
        params = model.init(jax.random.key(0), jnp.float32)

        import dataclasses
        cfg_off = dataclasses.replace(cfg, llama4_attn_scale_beta=None)
        model_off = Ministral3ForCausalLM(cfg_off, _fp32_backend())

        ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (1, 16)))
        on = np.asarray(model(params, ids))
        off = np.asarray(model_off(params, ids))
        np.testing.assert_allclose(on[0, :8], off[0, :8], atol=1e-5)
        assert np.abs(on[0, 8:] - off[0, 8:]).max() > 1e-5

    def test_matches_llama_without_rope_params(self):
        hf = _hf_cfg()
        hf.pop("rope_parameters")
        cfg = Ministral3Config.from_hf(hf)
        model = Ministral3ForCausalLM(cfg, _fp32_backend())
        params = model.init(jax.random.key(1), jnp.float32)
        llama = LlamaForCausalLM(cfg, _fp32_backend())
        ids = jnp.asarray(np.random.RandomState(1).randint(0, 128, (2, 12)))
        np.testing.assert_allclose(
            np.asarray(model(params, ids)), np.asarray(llama(params, ids)), atol=1e-6
        )

    def test_adapter_roundtrip(self):
        cfg = Ministral3Config.from_hf(_hf_cfg())
        model = Ministral3ForCausalLM(cfg, _fp32_backend())
        params = model.init(jax.random.key(2), jnp.float32)
        adapter = model.state_dict_adapter()
        hf = adapter.to_hf(params)
        assert "model.layers.0.self_attn.q_proj.weight" in hf
        back = adapter.from_hf(hf)
        for k in ("embed", "final_norm"):
            np.testing.assert_allclose(np.asarray(params[k]), np.asarray(back[k]), atol=1e-6)
