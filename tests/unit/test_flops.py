"""Per-arch FLOPs formulas (reference utils/flops_utils.py:18-830): each family's
forward FLOPs/token must track ~2x its ACTIVE non-embedding params (the
parameter-counting identity), which the old dense-only formula violated for
MLA / DeltaNet / Mamba hybrids."""

import numpy as np

import jax
import jax.numpy as jnp

from automodel_tpu.models.auto import AutoModelForCausalLM
from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.utils.flops import flops_per_token, mfu, vision_tower_flops


def _param_count(model, exclude=("embed", "lm_head", "wte")):
    params = model.abstract_params(jnp.float32)
    total = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        keys = [getattr(p, "key", "") for p in path]
        if any(k in exclude for k in keys):
            continue
        total += int(np.prod(leaf.shape))
    return total


def _check(hf, lo=1.2, hi=3.2, seq=64, active_frac=1.0):
    model = AutoModelForCausalLM.from_config(hf, BackendConfig(dtype="float32"))
    fwd = flops_per_token(hf, seq, training=False)
    active = _param_count(model) * active_frac
    ratio = fwd / (2 * active)
    assert lo < ratio < hi, f"{hf['architectures']}: fwd/2P ratio {ratio:.2f}"
    return fwd


class TestFlopsPerArch:
    def test_dense_llama(self):
        hf = {
            "architectures": ["LlamaForCausalLM"], "vocab_size": 256,
            "hidden_size": 64, "intermediate_size": 128, "num_hidden_layers": 2,
            "num_attention_heads": 4, "num_key_value_heads": 2,
            "max_position_embeddings": 128,
        }
        _check(hf, lo=0.9, hi=2.5)

    def test_mla_counts_low_rank_projections(self):
        hf = {
            "architectures": ["DeepseekV3ForCausalLM"], "vocab_size": 256,
            "hidden_size": 64, "intermediate_size": 96, "moe_intermediate_size": 32,
            "num_hidden_layers": 3, "num_attention_heads": 4, "q_lora_rank": 24,
            "kv_lora_rank": 32, "qk_nope_head_dim": 16, "qk_rope_head_dim": 8,
            "v_head_dim": 16, "n_routed_experts": 8, "num_experts_per_tok": 2,
            "n_shared_experts": 1, "norm_topk_prob": True, "first_k_dense_replace": 1,
            "max_position_embeddings": 128,
        }
        # active params: experts are 8x but only 2+1 active -> scale expert block
        model = AutoModelForCausalLM.from_config(hf, BackendConfig(dtype="float32"))
        params = model.abstract_params(jnp.float32)
        total = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            keys = [getattr(p, "key", "") for p in path]
            if any(k in ("embed", "lm_head") for k in keys):
                continue
            n = int(np.prod(leaf.shape))
            if any(k in ("gate_up_proj", "down_proj") for k in keys):
                n = n * 2 // 8  # top-2 of 8 routed
            total += n
        fwd = flops_per_token(hf, 64, training=False)
        ratio = fwd / (2 * total)
        assert 0.8 < ratio < 2.8, f"MLA ratio {ratio:.2f}"

    def test_deltanet_hybrid_ignores_seq_quadratic_on_linear_layers(self):
        hf = {
            "architectures": ["Qwen3NextForCausalLM"], "vocab_size": 256,
            "hidden_size": 64, "intermediate_size": 96, "moe_intermediate_size": 32,
            "num_hidden_layers": 4, "num_attention_heads": 4, "num_key_value_heads": 2,
            "head_dim": 16, "num_experts": 8, "num_experts_per_tok": 2,
            "shared_expert_intermediate_size": 32, "linear_num_key_heads": 2,
            "linear_key_head_dim": 16, "linear_num_value_heads": 4,
            "linear_value_head_dim": 16, "linear_conv_kernel_dim": 4,
            "full_attention_interval": 4, "max_position_embeddings": 128,
        }
        f_short = flops_per_token(hf, 64, training=False)
        f_long = flops_per_token(hf, 4096, training=False)
        # only 1 of 4 layers is full attention: the quadratic term must be ~1/4
        # of a dense model's growth
        dense = dict(hf)
        dense.pop("linear_num_key_heads"); dense.pop("full_attention_interval")
        d_short = flops_per_token(dense, 64, training=False)
        d_long = flops_per_token(dense, 4096, training=False)
        assert (f_long - f_short) < 0.3 * (d_long - d_short)

    def test_mamba_hybrid_layer_kinds(self):
        hf = {
            "architectures": ["NemotronHForCausalLM"], "vocab_size": 256,
            "hidden_size": 64, "intermediate_size": 128, "num_hidden_layers": 4,
            "num_attention_heads": 4, "num_key_value_heads": 2,
            "mamba_num_heads": 4, "mamba_head_dim": 16, "ssm_state_size": 32,
            "n_groups": 1, "conv_kernel": 4,
            "hybrid_override_pattern": "M*M-",
            "max_position_embeddings": 128,
        }
        f = flops_per_token(hf, 64, training=False)
        assert f > 0
        # mamba layers cost no seq-quadratic term: growth comes from 1 attn layer
        f_long = flops_per_token(hf, 2048, training=False)
        per_layer_growth = (f_long - f) / (2048 - 64)
        n, h = 4, 16
        assert abs(per_layer_growth - 2 * 2 * n * h) / (2 * 2 * n * h) < 0.05

    def test_mfu_device_table(self):
        assert 0.49 < mfu(12_000, 8.2e9, "TPU v5 lite") < 0.51
        assert mfu(1000, 1e9, "unknown accelerator") == 0.0


class TestVisionTowerFlops:
    # tiny tower, every term hand-computable: 8x8 image, 4x4 patches ->
    # 4 patches + CLS = 5 positions
    VCFG = {
        "hidden_size": 8, "intermediate_size": 16, "num_hidden_layers": 2,
        "num_attention_heads": 2, "image_size": 8, "patch_size": 4,
    }

    def test_pins_hand_computed_count(self):
        d, inter, L, patch = 8, 16, 2, 4
        num_patches = (8 // 4) ** 2          # 4
        n_pos = num_patches + 1              # 5
        patch_embed = num_patches * 2 * (3 * patch * patch) * d   # 4*2*48*8 = 3072
        attn = 2 * d * 3 * d + 2 * d * d + 2 * 2 * n_pos * d      # 384+128+160 = 672
        mlp = 2 * 2 * d * inter                                   # 512
        expected = patch_embed + n_pos * L * (attn + mlp)         # 3072+5*2*1184 = 14912
        assert expected == 14912
        assert vision_tower_flops(self.VCFG) == expected

    def test_accepts_config_objects(self):
        from automodel_tpu.models.vision.clip_vit import CLIPVisionConfig

        cfg = CLIPVisionConfig(**{k: v for k, v in self.VCFG.items()
                                  if k != "num_attention_heads"},
                               num_attention_heads=2)
        assert vision_tower_flops(cfg) == vision_tower_flops(self.VCFG)

    def test_vlm_config_amortizes_vision_over_seq(self):
        text = {
            "vocab_size": 256, "hidden_size": 64, "intermediate_size": 128,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2,
        }
        vlm = {"architectures": ["LlavaForConditionalGeneration"],
               "vision_config": self.VCFG, "text_config": text}
        seq = 64
        text_only = flops_per_token(text, seq, training=False)
        with_vision = flops_per_token(vlm, seq, training=False, num_images=2)
        expected_extra = vision_tower_flops(self.VCFG) * 2 / seq
        assert with_vision - text_only == expected_extra
        # training keeps the 3x fwd multiplier over the combined count
        assert flops_per_token(vlm, seq, training=True, num_images=2) == (
            3.0 * with_vision)
