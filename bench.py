"""Single-chip SFT throughput benchmark (driver-run; prints ONE JSON line).

Benchmarks the BASELINE.json config #1 shape — Llama-3.2-1B-class SFT, mock data,
bf16 — on whatever single accelerator is attached, and reports tokens/sec/chip.

``vs_baseline`` is hardware-normalized: the reference's headline single-GPU row is
Llama3-8B LoRA on H100 at 402 TFLOPs/s/GPU = 40.6% MFU against 989 bf16 peak
(BASELINE.md / docs/performance-summary.md). We report our model-FLOPs MFU against
the attached chip's bf16 peak and define vs_baseline = our_MFU / 0.406 — comparing
compiler+framework efficiency rather than raw chips (an H100 has ~5x the FLOPs of
the v5e this runs on).
"""

from __future__ import annotations

import json
import time

import numpy as np


def llama_flops_per_token(cfg, seq_len: int) -> float:
    """Training FLOPs/token (fwd+bwd = 3x fwd) incl. attention quadratic term."""
    d, i, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    n, k, h, v = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim, cfg.vocab_size
    qkv = 2 * d * (n + 2 * k) * h
    o = 2 * n * h * d
    attn_scores = 2 * 2 * seq_len * n * h  # qk^T + av per token
    mlp = 3 * 2 * d * i
    per_layer = qkv + o + attn_scores + mlp
    embed_head = 2 * d * v
    return 3.0 * (L * per_layer + embed_head)


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from automodel_tpu.models.common.backend import BackendConfig
    from automodel_tpu.models.llama.model import LlamaConfig, LlamaForCausalLM
    from automodel_tpu.ops.losses import masked_cross_entropy
    from automodel_tpu.training.train_step import make_train_step

    # Llama-3.2-1B dims
    cfg = LlamaConfig(
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=8192,
        num_hidden_layers=16,
        num_attention_heads=32,
        num_key_value_heads=8,
        head_dim=64,
        rope_theta=500000.0,
        tie_word_embeddings=True,
        max_position_embeddings=131072,
    )
    seq_len = 2048
    micro_batch = 4
    # measured on-chip (single v5-class, seq 2048, mb 4): pallas flash with
    # (1024, 1024) fwd blocks (dkv bwd capped at 512 for scoped VMEM) + remat
    # "mlp_dots" (save gate AND up; backward replays only qkv+attention) + the
    # factored-second-moment optimizer = 12.85k tok/s. The optimizer ladder on
    # this 16GB chip: fp32-nu adamw affords only remat "none" (11.7k); bf16-nu
    # affords "mlp_gate_dot" (12.0k); factored rms (~zero nu memory) affords
    # "mlp_dots" (12.85k). "mlp_attn_dots"/"dots" still overshoot HBM by ~0.3-1G.
    backend = BackendConfig(dtype="bfloat16", remat_policy="mlp_dots", attention="flash")
    model = LlamaForCausalLM(cfg, backend)

    params = model.init(jax.random.key(0), jnp.bfloat16)
    optimizer = optax.chain(
        optax.scale_by_factored_rms(),
        optax.trace(decay=0.9, accumulator_dtype=jnp.bfloat16),
        optax.scale(-1e-5),
    )
    opt_state = jax.jit(optimizer.init)(params)

    def forward_loss(p, batch, num_label_tokens):
        logits = model(p, batch["input_ids"], positions=batch["positions"],
                       segment_ids=batch["segment_ids"])
        return masked_cross_entropy(logits, batch["labels"], num_label_tokens)

    step = jax.jit(make_train_step(forward_loss, optimizer), donate_argnums=(0, 1))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (1, micro_batch, seq_len)).astype(np.int32)
    batch = {
        "input_ids": jnp.asarray(ids),
        "labels": jnp.asarray(ids),
        "positions": jnp.broadcast_to(jnp.arange(seq_len, dtype=jnp.int32), ids.shape),
        "segment_ids": jnp.ones_like(jnp.asarray(ids)),
    }

    # warmup/compile. NB: sync via host transfer — block_until_ready does not
    # block through the remote-execution tunnel, which silently yields ~1000x
    # inflated throughput numbers.
    params, opt_state, m = step(params, opt_state, batch)
    float(m["loss"])

    n_steps = 10
    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, opt_state, m = step(params, opt_state, batch)
    float(m["loss"])
    dt = time.perf_counter() - t0

    tokens = n_steps * micro_batch * seq_len
    tps = tokens / dt
    f_model = llama_flops_per_token(cfg, seq_len)
    # reference 8B dims for the FLOPs-equivalent conversion
    cfg8b = LlamaConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
    )
    f_8b = llama_flops_per_token(cfg8b, 4096)
    tps_8b_equiv = tps * f_model / f_8b
    tflops = tps * f_model / 1e12
    device = str(jax.devices()[0])
    peaks = {"v5 lite": 197.0, "v5e": 197.0, "v5p": 459.0, "v4": 275.0, "v6": 918.0}
    peak = next((v for k, v in peaks.items() if k in device.lower()), None)
    if peak is None:
        import sys

        print(f"WARNING: unknown device {device!r}; assuming v5e 197 TFLOP peak "
              "(mfu/vs_baseline unreliable)", file=sys.stderr)
        peak = 197.0
    mfu = tflops / peak
    ref_mfu = 402.0 / 989.0  # reference Llama3-8B LoRA on H100

    print(json.dumps({
        "metric": "llama3.2-1b SFT tokens/sec/chip (bf16, seq 2048)",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / ref_mfu, 4),
        "extra": {
            "model_tflops_per_sec": round(tflops, 1),
            "mfu": round(mfu, 4),
            "assumed_peak_tflops": peak,
            "8b_equiv_tokens_per_sec": round(tps_8b_equiv, 1),
            "device": device,
        },
    }))


if __name__ == "__main__":
    main()
