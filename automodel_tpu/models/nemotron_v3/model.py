"""NemotronV3 / Nemotron-H — TPU-native hybrid Mamba2 + Attention + MLP + MoE
(reference models/nemotron_v3/model.py:36, layers.py:155 Mamba2 mixer,
layers.py:458 single-mixer pre-norm blocks).

Each layer is ONE mixer (norm -> mixer -> residual), the type given per layer by
``layers_block_type`` ("mamba" | "attention" | "mlp" | "moe"). Attention is GQA
*without* rope (NemotronH convention); MLP/experts use ReLU²; MoE routes with
DSv3-style sigmoid scores, group-limited top-k, a shared ReLU² expert and a forced
score-correction-bias buffer.

TPU-first structure: params live in four stacked per-type streams; the forward
run-length-encodes the layer pattern and ``lax.scan``s each maximal same-type run,
so compile time scales with the number of type switches, not depth. Mamba2 uses the
chunked SSD scan in ops/mamba2.py; packed sequences reset conv taps and recurrence
at document boundaries.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.transformer import _constrain
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.dispatch import make_moe_block_forward
from automodel_tpu.moe.layers import cast_moe_compute_params, init_moe_params, moe_logical_axes
from automodel_tpu.utils.tracing import scope_blocks
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.gated_delta import causal_conv1d, conv_state_from_prefill, conv_step
from automodel_tpu.ops.mamba2 import group_rms_norm_gated, mamba_chunk_scan, softplus_dt
from automodel_tpu.ops.norms import rms_norm

__all__ = ["NemotronV3Config", "NemotronHForCausalLM"]

BLOCK_TYPES = ("mamba", "attention", "mlp", "moe")


@dataclasses.dataclass
class NemotronV3Config:
    vocab_size: int = 1024
    hidden_size: int = 256
    intermediate_size: int = 512
    num_hidden_layers: int = 4
    layers_block_type: tuple[str, ...] = ("mamba", "attention", "mlp", "moe")
    layer_norm_epsilon: float = 1e-5
    # attention (no rope)
    num_attention_heads: int = 4
    num_key_value_heads: int = 2
    head_dim: int = 64
    attention_bias: bool = False
    # mamba2
    mamba_num_heads: int = 8
    mamba_head_dim: int = 32
    ssm_state_size: int = 64
    n_groups: int = 2
    chunk_size: int = 128
    conv_kernel: int = 4
    use_conv_bias: bool = True
    use_bias: bool = False  # in_proj/out_proj bias
    time_step_limit: tuple[float, float] = (0.0, float("inf"))
    # mlp
    mlp_bias: bool = False
    residual_in_fp32: bool = False
    tie_word_embeddings: bool = False
    initializer_range: float = 0.02
    moe: MoEConfig | None = None

    def __post_init__(self):
        bad = set(self.layers_block_type) - set(BLOCK_TYPES)
        if bad:
            raise ValueError(f"unknown layers_block_type entries {bad}")
        if "moe" in self.layers_block_type and self.moe is None:
            raise ValueError("moe layers present but no MoEConfig")

    @property
    def mamba_intermediate(self) -> int:
        return self.mamba_num_heads * self.mamba_head_dim

    @property
    def conv_dim(self) -> int:
        return self.mamba_intermediate + 2 * self.n_groups * self.ssm_state_size

    def type_indices(self, t: str) -> tuple[int, ...]:
        return tuple(i for i, bt in enumerate(self.layers_block_type) if bt == t)

    @property
    def runs(self) -> tuple[tuple[str, int], ...]:
        """Maximal same-type runs in execution order."""
        return tuple(
            (t, len(list(g))) for t, g in itertools.groupby(self.layers_block_type)
        )

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "NemotronV3Config":
        moe = None
        layer_types = tuple(hf["layers_block_type"])
        if "moe" in layer_types:
            moe = MoEConfig(
                n_routed_experts=hf["n_routed_experts"],
                n_activated_experts=hf["num_experts_per_tok"],
                dim=hf["hidden_size"],
                moe_inter_dim=hf["moe_intermediate_size"],
                n_shared_experts=1,
                n_expert_groups=max(hf.get("n_group") or 1, 1),
                n_limited_groups=max(hf.get("topk_group") or 1, 1),
                score_func="sigmoid",
                route_scale=hf.get("routed_scaling_factor", 1.0),
                norm_topk_prob=hf.get("norm_topk_prob", True),
                expert_bias=hf.get("mlp_bias", False),
                expert_activation="relu2",
                shared_expert_inter_dim=hf.get("moe_shared_expert_intermediate_size"),
                shared_expert_activation="relu2",
                force_score_correction_bias=True,
            )
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            layers_block_type=layer_types,
            layer_norm_epsilon=hf.get("layer_norm_epsilon", hf.get("rms_norm_eps", 1e-5)),
            num_attention_heads=hf["num_attention_heads"],
            num_key_value_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            head_dim=hf.get("head_dim", hf["hidden_size"] // hf["num_attention_heads"]),
            attention_bias=hf.get("attention_bias", False),
            mamba_num_heads=hf["mamba_num_heads"],
            mamba_head_dim=hf["mamba_head_dim"],
            ssm_state_size=hf["ssm_state_size"],
            n_groups=hf["n_groups"],
            chunk_size=hf.get("chunk_size", 128),
            conv_kernel=hf.get("conv_kernel", 4),
            use_conv_bias=hf.get("use_conv_bias", True),
            use_bias=hf.get("use_bias", False),
            time_step_limit=tuple(hf.get("time_step_limit", (0.0, float("inf")))),
            mlp_bias=hf.get("mlp_bias", False),
            residual_in_fp32=hf.get("residual_in_fp32", False),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            initializer_range=hf.get("initializer_range", 0.02),
            moe=moe,
        )


def _stream_shapes(cfg: NemotronV3Config, t: str) -> dict[str, tuple[int, ...]]:
    d = cfg.hidden_size
    shapes: dict[str, tuple[int, ...]] = {"norm": (d,)}
    if t == "mamba":
        inter, hm = cfg.mamba_intermediate, cfg.mamba_num_heads
        proj = inter + cfg.conv_dim + hm
        shapes |= {
            "in_proj": (d, proj),
            "conv_w": (cfg.conv_dim, cfg.conv_kernel),
            "dt_bias": (hm,),
            "a_log": (hm,),
            "d_skip": (hm,),
            "gated_norm": (inter,),
            "out_proj": (inter, d),
        }
        if cfg.use_conv_bias:
            shapes["b_conv"] = (cfg.conv_dim,)
        if cfg.use_bias:
            shapes["b_in"] = (proj,)
            shapes["b_out"] = (d,)
    elif t == "attention":
        h, kv, dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        shapes |= {"wq": (d, h, dh), "wk": (d, kv, dh), "wv": (d, kv, dh), "wo": (h, dh, d)}
        if cfg.attention_bias:
            shapes |= {"bq": (h, dh), "bk": (kv, dh), "bv": (kv, dh), "bo": (d,)}
    elif t == "mlp":
        shapes |= {"w_up": (d, cfg.intermediate_size), "w_down": (cfg.intermediate_size, d)}
        if cfg.mlp_bias:
            shapes |= {"b_up": (cfg.intermediate_size,), "b_down": (d,)}
    return shapes  # moe: just the norm; expert params come from init_moe_params


_STREAM_AXES = {
    "norm": ("norm",),
    "in_proj": ("embed", "mlp"),
    "conv_w": (None, None),
    "b_conv": ("mlp",),
    "b_in": ("mlp",),
    "dt_bias": ("heads",),
    "a_log": ("heads",),
    "d_skip": ("heads",),
    "gated_norm": ("norm",),
    "out_proj": ("mlp", "embed"),
    "b_out": ("norm",),
    "wq": ("embed", "heads", "head_dim"),
    "wk": ("embed", "kv_heads", "head_dim"),
    "wv": ("embed", "kv_heads", "head_dim"),
    "wo": ("heads", "head_dim", "embed"),
    "bq": ("heads", "head_dim"),
    "bk": ("kv_heads", "head_dim"),
    "bv": ("kv_heads", "head_dim"),
    "bo": ("norm",),
    "w_up": ("embed", "mlp"),
    "b_up": ("mlp",),
    "w_down": ("mlp", "embed"),
    "b_down": ("norm",),
}

_STREAM_KEY = {"mamba": "mamba_layers", "attention": "attn_layers", "mlp": "mlp_layers", "moe": "moe_layers"}


class NemotronHForCausalLM:
    """Functional model: holds config + backend, operates on param pytrees."""

    config_class = NemotronV3Config
    hf_architectures = ("NemotronHForCausalLM", "NemotronV3ForCausalLM")

    def __init__(self, config: NemotronV3Config, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()

    # ---- params ----

    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        cfg = self.config
        std = cfg.initializer_range
        keys = iter(jax.random.split(key, 8))
        params: dict = {
            "embed": (jax.random.normal(next(keys), (cfg.vocab_size, cfg.hidden_size), jnp.float32) * std).astype(dtype),
            "final_norm": jnp.ones((cfg.hidden_size,), dtype),
        }

        def init_stack(t: str, L: int, key) -> dict:
            shapes = _stream_shapes(cfg, t)
            ks = jax.random.split(key, len(shapes))
            out = {}
            for idx, (name, shape) in enumerate(shapes.items()):
                if name in ("norm", "gated_norm"):
                    out[name] = jnp.ones((L, *shape), dtype)
                elif name == "dt_bias" or name == "d_skip":
                    out[name] = jnp.ones((L, *shape), dtype)
                elif name == "a_log":
                    # A = arange(1..H) (reference layers.py:208): log stays fp32
                    a = jnp.log(jnp.arange(1, shape[0] + 1, dtype=jnp.float32))
                    out[name] = jnp.broadcast_to(a, (L, *shape)).copy()
                elif name.startswith("b"):
                    out[name] = jnp.zeros((L, *shape), dtype)
                else:
                    out[name] = (jax.random.normal(ks[idx], (L, *shape), jnp.float32) * std).astype(dtype)
            return out

        for t in BLOCK_TYPES:
            idx = cfg.type_indices(t)
            if not idx:
                continue
            stack = init_stack(t, len(idx), next(keys))
            if t == "moe":
                stack["moe"] = jax.vmap(lambda k: init_moe_params(cfg.moe, k, dtype, std))(
                    jax.random.split(next(keys), len(idx))
                )
            params[_STREAM_KEY[t]] = stack
        if not cfg.tie_word_embeddings:
            params["lm_head"] = (
                jax.random.normal(next(keys), (cfg.hidden_size, cfg.vocab_size), jnp.float32) * std
            ).astype(dtype)
        return params

    def abstract_params(self, dtype=jnp.bfloat16) -> dict:
        return jax.eval_shape(lambda k: self.init(k, dtype), jax.random.key(0))

    def logical_axes(self) -> dict:
        cfg = self.config
        axes: dict = {"embed": ("vocab", "embed"), "final_norm": ("norm",)}
        for t in BLOCK_TYPES:
            idx = cfg.type_indices(t)
            if not idx:
                continue
            stream = {name: ("layers",) + _STREAM_AXES[name] for name in _stream_shapes(cfg, t)}
            if t == "moe":
                stream["moe"] = jax.tree.map(
                    lambda tp: ("layers",) + tp,
                    moe_logical_axes(cfg.moe),
                    is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(e, (str, type(None))) for e in x),
                )
            axes[_STREAM_KEY[t]] = stream
        if not cfg.tie_word_embeddings:
            axes["lm_head"] = ("embed", "vocab")
        return axes

    # ---- forward ----

    def __call__(self, params, input_ids, positions=None, segment_ids=None, token_mask=None,
                 rules=None, return_hidden=False, training=True, cache=None):
        cfg, backend = self.config, self.backend
        dtype = backend.jnp_dtype
        B, S = input_ids.shape
        eps = cfg.layer_norm_epsilon

        if cache is not None:
            if segment_ids is None:
                raise ValueError("cache decoding requires segment_ids (1 = real token)")
            if positions is None:
                positions = jnp.broadcast_to(jnp.arange(S), (B, S))
            return self._decode_forward(params, input_ids, positions, segment_ids, cache, dtype)

        reset_mask = None
        if segment_ids is not None:
            reset_mask = jnp.concatenate(
                [jnp.zeros((B, 1), bool), segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1
            )

        def mamba_block(lp, h):
            x = rms_norm(h, lp["norm"], eps).astype(dtype)
            if token_mask is not None:
                x = x * token_mask[..., None].astype(x.dtype)
            inter, hm = cfg.mamba_intermediate, cfg.mamba_num_heads
            gns = cfg.n_groups * cfg.ssm_state_size
            proj = jnp.einsum("bsd,dp->bsp", x, lp["in_proj"])
            if "b_in" in lp:
                proj = proj + lp["b_in"]
            gate, xbc, dt_raw = jnp.split(proj, [inter, inter + cfg.conv_dim], axis=-1)
            xbc = causal_conv1d(
                xbc, lp["conv_w"], segment_ids=segment_ids, bias=lp.get("b_conv")
            )
            xi, Bm, Cm = jnp.split(xbc, [inter, inter + gns], axis=-1)
            dt = softplus_dt(dt_raw, lp["dt_bias"], cfg.time_step_limit)
            A = -jnp.exp(lp["a_log"].astype(jnp.float32))
            y, _ = mamba_chunk_scan(
                xi.reshape(B, S, hm, cfg.mamba_head_dim), dt, A,
                Bm.reshape(B, S, cfg.n_groups, cfg.ssm_state_size),
                Cm.reshape(B, S, cfg.n_groups, cfg.ssm_state_size),
                lp["d_skip"], chunk_size=cfg.chunk_size, reset_mask=reset_mask,
            )
            y = group_rms_norm_gated(
                y.reshape(B, S, inter), lp["gated_norm"], gate,
                group_size=inter // cfg.n_groups, eps=eps,
            )
            out = jnp.einsum("bsi,id->bsd", y, lp["out_proj"])
            if "b_out" in lp:
                out = out + lp["b_out"]
            return h + out, _zero_stats()

        def attn_block(lp, h):
            x = rms_norm(h, lp["norm"], eps).astype(dtype)
            q = jnp.einsum("bsd,dnh->bsnh", x, lp["wq"])
            k = jnp.einsum("bsd,dnh->bsnh", x, lp["wk"])
            v = jnp.einsum("bsd,dnh->bsnh", x, lp["wv"])
            if cfg.attention_bias:
                q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
            out = dot_product_attention(
                q, k, v, causal=True, segment_ids_q=segment_ids, backend=backend.attention,
            )
            o = jnp.einsum("bsnh,nhd->bsd", out, lp["wo"])
            if cfg.attention_bias:
                o = o + lp["bo"]
            return h + o, _zero_stats()

        def mlp_block(lp, h):
            x = rms_norm(h, lp["norm"], eps).astype(dtype)
            up = jnp.einsum("bsd,di->bsi", x, lp["w_up"])
            if "b_up" in lp:
                up = up + lp["b_up"]
            act = jnp.square(jax.nn.relu(up))
            out = jnp.einsum("bsi,id->bsd", act, lp["w_down"])
            if "b_down" in lp:
                out = out + lp["b_down"]
            return h + out, _zero_stats()

        moe_fwd = (
            make_moe_block_forward(cfg.moe, backend, rules, training=training)
            if cfg.moe is not None else None
        )

        def moe_block(lp, h):
            x = rms_norm(h, lp["norm"], eps).astype(dtype)
            moe_params = cast_moe_compute_params(lp["moe"], dtype)
            y, aux, load, dropped = moe_fwd(moe_params, x, token_mask)
            return h + y, (jnp.float32(0) if aux is None else aux, load, dropped)

        def _zero_stats():
            E = cfg.moe.n_routed_experts if cfg.moe else 1
            return jnp.float32(0), jnp.zeros((E,), jnp.float32), jnp.float32(0)

        # profiler labels per block kind (autonvtx parity): mamba runs vs
        # attention vs moe show as separate regions in the trace viewer
        block_fns = scope_blocks(
            {"mamba": mamba_block, "attention": attn_block, "mlp": mlp_block, "moe": moe_block}
        )

        h = params["embed"].astype(dtype)[input_ids]
        if cfg.residual_in_fp32:
            # reference keeps the residual stream fp32 (layers.py:555-557);
            # mixer outputs promote on add, norms read fp32 and cast back
            h = h.astype(jnp.float32)
        h = _constrain(h, rules, ("batch", "act_seq", "act_embed"))

        offsets = dict.fromkeys(BLOCK_TYPES, 0)
        auxs, loads, droppeds, load_is_moe = [], [], [], []
        for t, n in cfg.runs:
            stream = params[_STREAM_KEY[t]]
            o = offsets[t]
            run_params = jax.tree.map(lambda a: a[o : o + n], stream)
            offsets[t] = o + n
            fn = block_fns[t]

            def body(hh, lp):
                # compute-dtype cast; decay logs stay fp32, moe casts in moe_block
                lp = {
                    k: v if k in ("moe", "a_log") else jax.tree.map(lambda a: a.astype(dtype), v)
                    for k, v in lp.items()
                }
                hh, stats = fn(lp, hh)
                hh = _constrain(hh, rules, ("batch", "act_seq", "act_embed"))
                return hh, stats

            body = backend.layer_remat(body)
            if backend.scan_layers and n > 1:
                h, (aux_r, load_r, drop_r) = jax.lax.scan(body, h, run_params)
                auxs.append(aux_r)
                loads.append(load_r)
                droppeds.append(drop_r)
            else:
                for i in range(n):
                    lp = jax.tree.map(lambda a: a[i], run_params)
                    h, (aux, load, dropped) = body(h, lp)
                    auxs.append(aux[None])
                    loads.append(load[None])
                    droppeds.append(dropped[None])
            load_is_moe += [t == "moe"] * n

        aux_all = jnp.concatenate(auxs)
        load_all = jnp.concatenate(loads)
        drop_all = jnp.concatenate(droppeds)
        moe_sel = np.asarray(load_is_moe, bool)  # static layer pattern: concrete mask
        emit_aux = (
            cfg.moe is not None and cfg.moe.aux_loss_coeff > 0 and training
            and not backend.fake_balanced_gate
        )
        stats = {
            "aux_loss": aux_all.sum() if emit_aux else None,
            "expert_load": load_all[moe_sel] if cfg.moe is not None else load_all[:0],
        }
        if backend.dispatcher == "a2a" and cfg.moe is not None:
            stats["dropped_token_frac"] = drop_all[moe_sel].mean()

        h = rms_norm(h, params["final_norm"].astype(dtype), eps)
        if return_hidden:
            return h, stats
        unembed = params.get("lm_head")
        if unembed is None:
            unembed = params["embed"].T
        logits = jnp.einsum("bsd,dv->bsv", h, unembed.astype(dtype))
        return logits, stats

    # ---- decode ----

    def init_decode_cache(self, batch_size: int, max_len: int, dtype=jnp.bfloat16) -> dict:
        """Hybrid decode cache: KV for attention layers, conv taps + SSD state
        (fp32) for mamba layers (mlp/moe layers are stateless)."""
        cfg = self.config
        La = len(cfg.type_indices("attention"))
        Lm = len(cfg.type_indices("mamba"))
        return {
            "k": jnp.zeros((La, batch_size, max_len, cfg.num_key_value_heads, cfg.head_dim), dtype),
            "v": jnp.zeros((La, batch_size, max_len, cfg.num_key_value_heads, cfg.head_dim), dtype),
            "conv": jnp.zeros((Lm, batch_size, cfg.conv_kernel - 1, cfg.conv_dim), dtype),
            "rec": jnp.zeros(
                (Lm, batch_size, cfg.mamba_num_heads, cfg.mamba_head_dim, cfg.ssm_state_size),
                jnp.float32,
            ),
            "positions": jnp.zeros((batch_size, max_len), jnp.int32),
            "valid": jnp.zeros((batch_size, max_len), jnp.int32),
            "write_idx": jnp.zeros((batch_size,), jnp.int32),
        }

    def _decode_forward(self, params, input_ids, positions, segment_ids, cache, dtype):
        """Unrolled cached forward (prefill S>1, decode S=1). Right-padding is
        neutralized in the recurrence by zeroing dt (decay exp(0·A)=1, write
        dt·B·x=0) and in the conv by gathering each row's trailing VALID inputs."""
        from automodel_tpu.models.common.transformer import _cache_write

        cfg = self.config
        eps = cfg.layer_norm_epsilon
        B, S = input_ids.shape
        token_mask = segment_ids != 0
        K = cfg.conv_kernel
        h = params["embed"].astype(dtype)[input_ids]
        if cfg.residual_in_fp32:
            h = h.astype(jnp.float32)
        k_all, v_all = cache["k"], cache["v"]
        conv_all, rec_all = cache["conv"], cache["rec"]
        moe_fwd = (
            make_moe_block_forward(cfg.moe, self.backend, None, training=False)
            if cfg.moe is not None else None
        )
        offsets = dict.fromkeys(BLOCK_TYPES, 0)
        a_i = m_i = 0
        for t in cfg.layers_block_type:
            o = offsets[t]
            lp = jax.tree.map(lambda a: a[o], params[_STREAM_KEY[t]])
            offsets[t] = o + 1
            lp = {
                k_: v_ if k_ in ("moe", "a_log") else jax.tree.map(lambda a: a.astype(dtype), v_)
                for k_, v_ in lp.items()
            }
            if t == "mamba":
                x = rms_norm(h, lp["norm"], eps).astype(dtype)
                x = x * token_mask[..., None].astype(x.dtype)
                inter, hm = cfg.mamba_intermediate, cfg.mamba_num_heads
                gns = cfg.n_groups * cfg.ssm_state_size
                proj = jnp.einsum("bsd,dp->bsp", x, lp["in_proj"])
                if "b_in" in lp:
                    proj = proj + lp["b_in"]
                gate, xbc, dt_raw = jnp.split(proj, [inter, inter + cfg.conv_dim], axis=-1)
                if S == 1:
                    xbc_c, new_conv = conv_step(
                        conv_all[m_i], xbc, lp["conv_w"], bias=lp.get("b_conv")
                    )
                else:
                    xbc_c = causal_conv1d(xbc, lp["conv_w"], bias=lp.get("b_conv"))
                    new_conv = conv_state_from_prefill(xbc, token_mask.sum(-1), K)
                xi, Bm, Cm = jnp.split(xbc_c, [inter, inter + gns], axis=-1)
                dt = softplus_dt(dt_raw, lp["dt_bias"], cfg.time_step_limit)
                dt = dt * token_mask[..., None].astype(dt.dtype)
                A = -jnp.exp(lp["a_log"].astype(jnp.float32))
                y, rec = mamba_chunk_scan(
                    xi.reshape(B, S, hm, cfg.mamba_head_dim), dt, A,
                    Bm.reshape(B, S, cfg.n_groups, cfg.ssm_state_size),
                    Cm.reshape(B, S, cfg.n_groups, cfg.ssm_state_size),
                    lp["d_skip"], chunk_size=min(cfg.chunk_size, S),
                    initial_state=rec_all[m_i], output_final_state=True,
                )
                conv_all = conv_all.at[m_i].set(new_conv.astype(conv_all.dtype))
                rec_all = rec_all.at[m_i].set(rec)
                y = group_rms_norm_gated(
                    y.reshape(B, S, inter), lp["gated_norm"], gate,
                    group_size=inter // cfg.n_groups, eps=eps,
                )
                out = jnp.einsum("bsi,id->bsd", y, lp["out_proj"])
                if "b_out" in lp:
                    out = out + lp["b_out"]
                h = h + out
                m_i += 1
            elif t == "attention":
                x = rms_norm(h, lp["norm"], eps).astype(dtype)
                q = jnp.einsum("bsd,dnh->bsnh", x, lp["wq"])
                k = jnp.einsum("bsd,dnh->bsnh", x, lp["wk"])
                v = jnp.einsum("bsd,dnh->bsnh", x, lp["wv"])
                if cfg.attention_bias:
                    q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
                k_cache = _cache_write(k_all[a_i], k.astype(k_all.dtype), cache["write_idx"])
                v_cache = _cache_write(v_all[a_i], v.astype(v_all.dtype), cache["write_idx"])
                out = dot_product_attention(
                    q, k_cache.astype(q.dtype), v_cache.astype(q.dtype),
                    causal=True, segment_ids_q=segment_ids,
                    segment_ids_kv=cache["valid"],
                    positions_q=positions,
                    positions_kv=cache["positions"],
                    backend="xla",
                )
                k_all = k_all.at[a_i].set(k_cache)
                v_all = v_all.at[a_i].set(v_cache)
                o = jnp.einsum("bsnh,nhd->bsd", out, lp["wo"])
                if cfg.attention_bias:
                    o = o + lp["bo"]
                h = h + o
                a_i += 1
            elif t == "mlp":
                x = rms_norm(h, lp["norm"], eps).astype(dtype)
                up = jnp.einsum("bsd,di->bsi", x, lp["w_up"])
                if "b_up" in lp:
                    up = up + lp["b_up"]
                act = jnp.square(jax.nn.relu(up))
                out = jnp.einsum("bsi,id->bsd", act, lp["w_down"])
                if "b_down" in lp:
                    out = out + lp["b_down"]
                h = h + out
            else:  # moe
                x = rms_norm(h, lp["norm"], eps).astype(dtype)
                moe_params = cast_moe_compute_params(lp["moe"], dtype)
                y, _, _, _ = moe_fwd(moe_params, x, token_mask)
                h = h + y
        h = rms_norm(h, params["final_norm"].astype(dtype), eps)
        last = jnp.maximum(segment_ids.sum(-1) - 1, 0).astype(jnp.int32)
        h = jnp.take_along_axis(h, last[:, None, None], axis=1)
        unembed = params.get("lm_head")
        if unembed is None:
            unembed = params["embed"].T
        logits = jnp.einsum("bsd,dv->bsv", h, unembed.astype(dtype))
        return logits, dict(cache, k=k_all, v=v_all, conv=conv_all, rec=rec_all)

    def generate(self, params, input_ids, **kw):
        """Sample with the hybrid conv+SSD+KV cache (automodel_tpu.generation)."""
        from automodel_tpu.generation import generate

        return generate(self, params, input_ids, **kw)

    # ---- interop ----

    def state_dict_adapter(self):
        from automodel_tpu.models.nemotron_v3.state_dict_adapter import NemotronV3StateDictAdapter

        return NemotronV3StateDictAdapter(self.config)

    @classmethod
    def from_config(cls, config, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = NemotronV3Config.from_hf(config)
        return cls(config, backend)
