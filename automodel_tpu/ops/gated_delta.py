"""Gated DeltaNet linear attention (Qwen3-Next) — TPU-native chunked form.

Implements the chunked gated delta rule used by Qwen3-Next's ``linear_attention``
layers (reference models/qwen3_next/model.py:39 delegates to HF/flash-linear-attention;
math mirrored from transformers torch_chunk_gated_delta_rule,
modeling_qwen3_next.py:442-517). Design is TPU-first rather than a translation:

- the intra-chunk "UT transform" — the reference builds the inverse of the unit
  lower-triangular matrix ``(I - tril(kᵝ·kᵀ ⊙ decay))`` with a Python loop over rows —
  is a batched ``solve_triangular`` here (one fused MXU-friendly op, differentiable);
- the inter-chunk recurrence is a ``lax.scan`` over chunks carrying the (dk, dv)
  state, so XLA sees a compact loop with static shapes;
- everything runs in fp32 (the decays ``exp(g)`` underflow in bf16), cast back at the
  end, matching the reference kernel's fp32 accumulation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_P = jax.lax.Precision.HIGHEST  # delta-rule recurrence compounds matmul error; keep fp32 MXU passes

__all__ = [
    "l2norm", "causal_conv1d", "conv_state_from_prefill", "conv_step",
    "gated_rms_norm", "chunk_gated_delta_rule",
]


def conv_state_from_prefill(x: jnp.ndarray, lens: jnp.ndarray, kernel: int) -> jnp.ndarray:
    """Trailing ``kernel-1`` VALID pre-conv inputs per row — the decode conv state
    after a right-padded prefill. ``x`` (B, S, C), ``lens`` (B,) valid lengths
    (valid region contiguous from 0). Short prompts left-fill with zeros, matching
    the causal conv's implicit left padding."""
    padded = jnp.pad(x, ((0, 0), (kernel - 1, 0), (0, 0)))
    return jax.vmap(
        lambda p, n: jax.lax.dynamic_slice(p, (n, 0), (kernel - 1, p.shape[-1]))
    )(padded, lens.astype(jnp.int32))


def conv_step(
    state: jnp.ndarray,  # (B, K-1, C) trailing pre-conv inputs
    x: jnp.ndarray,  # (B, s, C) new pre-conv inputs (decode: s = 1)
    weight: jnp.ndarray,  # (C, K)
    bias: jnp.ndarray | None = None,
    activation: str = "silu",
):
    """Continue a causal depthwise conv from carried state: returns
    ``(out (B, s, C), new_state (B, K-1, C))``."""
    kernel = weight.shape[-1]
    full = jnp.concatenate([state.astype(x.dtype), x], axis=1)
    out = causal_conv1d(full, weight, activation=activation, bias=bias)[:, kernel - 1:]
    return out, full[:, full.shape[1] - (kernel - 1):]


def l2norm(x: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """FLA-style L2 normalization over the last dim (modeling_qwen3_next.py:436)."""
    return x * jax.lax.rsqrt(jnp.sum(x * x, axis=-1, keepdims=True) + eps)


def causal_conv1d(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    activation: str = "silu",
    segment_ids: jnp.ndarray | None = None,
    bias: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Depthwise causal conv over the sequence dim.

    x: (B, S, C), weight: (C, K). Left-pads K-1 so output[t] only sees inputs <= t
    (HF causal_conv1d_fn semantics, conv state = trailing K-1 inputs). With
    ``segment_ids`` (B, S), taps from other packed documents are zeroed — K explicit
    shifted adds (K is 4; cheaper than a masked conv and fuses into one XLA loop).
    """
    if segment_ids is not None:
        K = weight.shape[-1]
        y = x * weight[:, K - 1]
        for j in range(1, K):
            shifted = jnp.pad(x, ((0, 0), (j, 0), (0, 0)))[:, : x.shape[1]]
            seg_shift = jnp.pad(segment_ids, ((0, 0), (j, 0)))[:, : x.shape[1]]
            same = (seg_shift == segment_ids)[..., None].astype(x.dtype)
            y = y + shifted * same * weight[:, K - 1 - j]
        if bias is not None:
            y = y + bias
        if activation == "silu":
            y = jax.nn.silu(y)
        return y
    ch = x.shape[-1]
    lhs = x.swapaxes(1, 2)  # (B, C, S)
    rhs = weight[:, None, :]  # (C, 1, K) = (out, in/groups, K)
    y = jax.lax.conv_general_dilated(
        lhs, rhs,
        window_strides=(1,),
        padding=[(weight.shape[-1] - 1, 0)],
        dimension_numbers=("NCH", "OIH", "NCH"),
        feature_group_count=ch,
    )
    y = y.swapaxes(1, 2)
    if bias is not None:
        y = y + bias
    if activation == "silu":
        y = jax.nn.silu(y)
    elif activation is not None and activation != "none":
        raise NotImplementedError(f"conv activation {activation!r}")
    return y


def gated_rms_norm(x: jnp.ndarray, weight: jnp.ndarray, gate: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """RMSNorm(x) * w, gated by silu(gate) — Qwen3NextRMSNormGated
    (modeling_qwen3_next.py:68-83; norm before gate)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    xn = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    out = weight.astype(jnp.float32) * xn
    out = out * jax.nn.silu(gate.astype(jnp.float32))
    return out.astype(dtype)


def chunk_gated_delta_rule(
    query: jnp.ndarray,  # (B, S, H, dk)
    key: jnp.ndarray,  # (B, S, H, dk)
    value: jnp.ndarray,  # (B, S, H, dv)
    g: jnp.ndarray,  # (B, S, H) log-decay (<= 0)
    beta: jnp.ndarray,  # (B, S, H) write strength in (0, 1)
    *,
    chunk_size: int = 64,
    initial_state: jnp.ndarray | None = None,  # (B, H, dk, dv)
    output_final_state: bool = False,
    use_qk_l2norm: bool = True,
):
    """Chunked gated delta rule: S_t = S_{t-1}·exp(g_t)·(I − β_t k_t k_tᵀ) + β_t k_t v_tᵀ,
    o_t = q_tᵀ S_t. Returns (out (B, S, H, dv), final_state | None)."""
    out_dtype = query.dtype
    B, S, H, dk = query.shape
    dv = value.shape[-1]

    if use_qk_l2norm:
        query = l2norm(query.astype(jnp.float32))
        key = l2norm(key.astype(jnp.float32))

    # (B, H, S, d) fp32
    q = query.astype(jnp.float32).transpose(0, 2, 1, 3) * (dk**-0.5)
    k = key.astype(jnp.float32).transpose(0, 2, 1, 3)
    v = value.astype(jnp.float32).transpose(0, 2, 1, 3)
    gf = g.astype(jnp.float32).transpose(0, 2, 1)
    bf = beta.astype(jnp.float32).transpose(0, 2, 1)

    C = chunk_size
    pad = (-S) % C
    if pad:
        q, k, v = (jnp.pad(t, ((0, 0), (0, 0), (0, pad), (0, 0))) for t in (q, k, v))
        gf, bf = (jnp.pad(t, ((0, 0), (0, 0), (0, pad))) for t in (gf, bf))
    N = (S + pad) // C

    # chunked views (B, H, N, C, d)
    q, k, v = (t.reshape(B, H, N, C, -1) for t in (q, k, v))
    gf = gf.reshape(B, H, N, C)
    bf = bf.reshape(B, H, N, C)

    k_beta = k * bf[..., None]
    v_beta = v * bf[..., None]

    gcs = jnp.cumsum(gf, axis=-1)  # within-chunk cumulative log decay
    # decay[i, j] = exp(gcs_i - gcs_j) for j <= i (lower incl diag), else 0.
    # Mask the exp *argument*, not its result: upper-triangle arguments are positive
    # and overflow, and where(mask, inf, 0) still propagates NaN cotangents.
    tril = jnp.tril(jnp.ones((C, C), bool))
    strict = jnp.tril(jnp.ones((C, C), bool), -1)
    log_decay = jnp.where(tril, gcs[..., :, None] - gcs[..., None, :], -jnp.inf)
    decay = jnp.exp(log_decay)

    # intra-chunk UT transform: T = (I + A)^-1, A = strict_tril(kᵝ kᵀ ⊙ decay)
    # (the reference builds this inverse with a Python loop over rows, :486-490)
    A = jnp.where(strict, jnp.einsum("bhncd,bhnmd->bhncm", k_beta, k, precision=_P) * decay, 0.0)
    eye = jnp.eye(C, dtype=jnp.float32)
    T = jax.scipy.linalg.solve_triangular(eye + A, jnp.broadcast_to(eye, A.shape), lower=True)

    v_new_c = jnp.einsum("bhncm,bhnmd->bhncd", T, v_beta, precision=_P)
    k_cumdecay = jnp.einsum("bhncm,bhnmd->bhncd", T, k_beta * jnp.exp(gcs)[..., None], precision=_P)

    # inter-chunk recurrence over N chunks
    if initial_state is None:
        state0 = jnp.zeros((B, H, dk, dv), jnp.float32)
    else:
        state0 = initial_state.astype(jnp.float32)

    # local (within-chunk) attention, lower-triangular incl diag
    attn_local = jnp.where(tril, jnp.einsum("bhncd,bhnmd->bhncm", q, k, precision=_P) * decay, 0.0)

    def step(state, xs):
        q_i, k_i, vn_i, kcd_i, al_i, gcs_i = xs
        v_prime = jnp.einsum("bhcd,bhde->bhce", kcd_i, state, precision=_P)
        v_new = vn_i - v_prime
        inter = jnp.einsum("bhcd,bhde->bhce", q_i * jnp.exp(gcs_i)[..., None], state, precision=_P)
        out_i = inter + jnp.einsum("bhcm,bhme->bhce", al_i, v_new, precision=_P)
        g_last = gcs_i[..., -1]
        k_scaled = k_i * jnp.exp(g_last[..., None] - gcs_i)[..., None]
        state = state * jnp.exp(g_last)[..., None, None] + jnp.einsum(
            "bhcd,bhce->bhde", k_scaled, v_new, precision=_P
        )
        return state, out_i

    xs = tuple(
        t.transpose(2, 0, 1, *range(3, t.ndim))  # chunk axis to front for scan
        for t in (q, k, v_new_c, k_cumdecay, attn_local, gcs)
    )
    final_state, outs = jax.lax.scan(step, state0, xs)
    out = outs.transpose(1, 2, 0, 3, 4).reshape(B, H, N * C, dv)[:, :, :S]
    out = out.transpose(0, 2, 1, 3).astype(out_dtype)  # (B, S, H, dv)
    return out, (final_state if output_final_state else None)
