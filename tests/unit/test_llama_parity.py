"""Golden parity vs HF transformers (torch CPU) — the loss-curve-parity foundation.

Reference analogue: functional tests against tiny local model fixtures
(tests/functional_tests/, SURVEY.md §4). Here we build tiny random HF models in-process,
save safetensors, load through our adapter, and require logit agreement.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from automodel_tpu.models.auto import AutoModelForCausalLM
from automodel_tpu.models.common.backend import BackendConfig

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _fp32_backend(**kw):
    return BackendConfig(dtype="float32", remat_policy="full", **kw)


def _save_hf(model, tmp_path):
    d = str(tmp_path / "hf")
    model.save_pretrained(d, safe_serialization=True)
    return d


def _compare(hf_model, d, tmp_path, atol=3e-4, seq=16):
    hf_model.eval()
    model, params = AutoModelForCausalLM.from_pretrained(d, dtype=jnp.float32, backend=_fp32_backend())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, hf_model.config.vocab_size, (2, seq))
    ours = np.asarray(model(params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, atol=atol, rtol=1e-3)
    return model, params


class TestLlamaParity:
    def test_llama_logits_match_hf(self, tmp_path):
        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=128,
        )
        torch.manual_seed(0)
        hf = transformers.LlamaForCausalLM(cfg)
        _compare(hf, _save_hf(hf, tmp_path), tmp_path)

    def test_llama3_rope_scaling(self, tmp_path):
        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
            rope_scaling={
                "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
                "high_freq_factor": 4.0, "original_max_position_embeddings": 64,
            },
        )
        torch.manual_seed(1)
        hf = transformers.LlamaForCausalLM(cfg)
        _compare(hf, _save_hf(hf, tmp_path), tmp_path, seq=48)

    def test_tied_embeddings(self, tmp_path):
        cfg = transformers.LlamaConfig(
            vocab_size=96, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=4, tie_word_embeddings=True,
        )
        torch.manual_seed(2)
        hf = transformers.LlamaForCausalLM(cfg)
        _compare(hf, _save_hf(hf, tmp_path), tmp_path)

    def test_qwen2_bias(self, tmp_path):
        cfg = transformers.Qwen2Config(
            vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=128,
        )
        torch.manual_seed(3)
        hf = transformers.Qwen2ForCausalLM(cfg)
        _compare(hf, _save_hf(hf, tmp_path), tmp_path)

    def test_qwen3_qk_norm(self, tmp_path):
        cfg = transformers.Qwen3Config(
            vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        )
        torch.manual_seed(4)
        hf = transformers.Qwen3ForCausalLM(cfg)
        _compare(hf, _save_hf(hf, tmp_path), tmp_path)


class TestStateDictRoundtrip:
    def test_to_hf_from_hf_roundtrip(self, tmp_path):
        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
        )
        torch.manual_seed(5)
        hf = transformers.LlamaForCausalLM(cfg)
        d = _save_hf(hf, tmp_path)
        model, params = AutoModelForCausalLM.from_pretrained(d, dtype=jnp.float32, backend=_fp32_backend())
        adapter = model.state_dict_adapter()
        hf_dict = adapter.to_hf(params)
        params2 = adapter.from_hf(hf_dict)
        import jax

        jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)), params, params2)

    def test_hf_keys_complete(self, tmp_path):
        cfg = transformers.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
        )
        hf = transformers.LlamaForCausalLM(cfg)
        d = _save_hf(hf, tmp_path)
        model, params = AutoModelForCausalLM.from_pretrained(d, dtype=jnp.float32, backend=_fp32_backend())
        ours = set(model.state_dict_adapter().to_hf(params).keys())
        theirs = {k for k in hf.state_dict().keys() if "rotary_emb" not in k}
        assert ours == theirs


class TestShardedLoad:
    def test_from_pretrained_with_rules(self, tmp_path, mesh8):
        from automodel_tpu.parallel.mesh import default_sharding_rules

        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
        )
        hf = transformers.LlamaForCausalLM(cfg)
        d = _save_hf(hf, tmp_path)
        rules = default_sharding_rules().with_mesh(mesh8)
        model, params = AutoModelForCausalLM.from_pretrained(
            d, dtype=jnp.float32, backend=_fp32_backend(), rules=rules
        )
        wq = params["layers"]["wq"]
        # (L, D, N, H): embed dim sharded over dp_shard*cp = 4, heads over tp = 2
        assert wq.sharding.shard_shape(wq.shape) == (2, 16, 2, 16)


class TestPhi3Parity:
    def _tiny_cfg(self, **kw):
        base = dict(
            vocab_size=128, hidden_size=64, intermediate_size=96,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, sliding_window=16, rope_scaling=None,
            pad_token_id=0, bos_token_id=1, eos_token_id=2,
        )
        base.update(kw)
        return transformers.Phi3Config(**base)

    def test_logits_match_hf(self, tmp_path):
        """Fused qkv/gate_up split + llama stack reproduce HF Phi-3 logits."""
        torch.manual_seed(7)
        hf = transformers.Phi3ForCausalLM(self._tiny_cfg())
        hf.eval()
        d = str(tmp_path / "phi3")
        hf.save_pretrained(d, safe_serialization=True)
        model, params = AutoModelForCausalLM.from_pretrained(
            d, dtype=jnp.float32,
            backend=BackendConfig(dtype="float32", remat_policy="full"),
        )
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (2, 12))
        ours = model(params, jnp.asarray(ids))
        with torch.no_grad():
            theirs = hf(torch.tensor(ids)).logits.float().numpy()
        # noise floor at hidden=64 on CPU XLA-vs-torch is ~2e-3 max (an identical
        # tiny-LLAMA control shows the same magnitude), so 5e-3 here
        np.testing.assert_allclose(np.asarray(ours), theirs, atol=5e-3, rtol=1e-3)

    def test_fused_roundtrip_and_lazy_export(self, tmp_path):
        torch.manual_seed(8)
        hf = transformers.Phi3ForCausalLM(self._tiny_cfg())
        d = str(tmp_path / "phi3")
        hf.save_pretrained(d, safe_serialization=True)
        model, params = AutoModelForCausalLM.from_pretrained(
            d, dtype=jnp.float32,
            backend=BackendConfig(dtype="float32", remat_policy="full"),
        )
        adapter = model.state_dict_adapter()
        hf_dict = adapter.to_hf(params)
        theirs = {k for k in hf.state_dict() if "rotary_emb" not in k}
        assert set(hf_dict) == theirs
        # the streaming-export lazy path fuses qkv/gate_up identically
        lazy = adapter.to_hf_lazy(params)
        assert set(lazy) == theirs
        for k in ("model.layers.0.self_attn.qkv_proj.weight",
                  "model.layers.1.mlp.gate_up_proj.weight"):
            np.testing.assert_array_equal(lazy[k].materialize(), hf_dict[k])
        import jax

        params2 = adapter.from_hf(hf_dict)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params, jax.tree.map(jnp.asarray, params2),
        )
