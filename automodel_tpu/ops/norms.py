"""Normalization ops.

The reference reaches for TransformerEngine's fused RMSNorm (models/common/utils.py:166);
on TPU a plain jnp expression is the right call — XLA fuses the reduction+scale into
neighbouring ops, and the accumulation is forced to fp32 regardless of activation dtype.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["layer_norm", "rms_norm"]


def layer_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """LayerNorm with fp32 accumulation (GPT-2, CLIP towers, DSv3.2 indexer k-norm)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-6, offset: float = 0.0) -> jnp.ndarray:
    """RMSNorm with fp32 accumulation; ``offset=1.0`` gives the (1+scale) Gemma variant."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    y = x32 * lax.rsqrt(var + eps)
    return (y * (offset + scale.astype(jnp.float32))).astype(dtype)
