"""HF Hub id resolution (models/hub.py) — fully offline: the download itself is
monkeypatched; what's under test is id-vs-path routing and the process-0-first
multi-host protocol (reference pre-downloads on rank 0, model_init.py:194)."""

import json
import os

import numpy as np
import pytest

import automodel_tpu.models.hub as hub
from automodel_tpu.models.hub import looks_like_repo_id, resolve_pretrained_path


class TestRepoIdDetection:
    def test_org_name_is_repo_id(self):
        assert looks_like_repo_id("meta-llama/Llama-3.2-1B")
        assert looks_like_repo_id("gpt2")

    def test_paths_are_not(self, tmp_path):
        assert not looks_like_repo_id(str(tmp_path))  # exists
        assert not looks_like_repo_id("/abs/missing/dir")
        assert not looks_like_repo_id("a/b/c")
        assert not looks_like_repo_id("./rel")

    def test_existing_dir_wins_over_id_shape(self, tmp_path):
        # a directory literally named like a repo id resolves as the directory
        d = tmp_path / "org" / "name"
        d.mkdir(parents=True)
        old = os.getcwd()
        os.chdir(tmp_path)
        try:
            assert not looks_like_repo_id("org/name")
            assert resolve_pretrained_path("org/name") == "org/name"
        finally:
            os.chdir(old)


class TestResolution:
    def test_local_dir_passthrough_no_download(self, tmp_path, monkeypatch):
        def boom(*a, **k):
            raise AssertionError("must not download for a local dir")

        monkeypatch.setattr(hub, "_download", boom)
        assert resolve_pretrained_path(str(tmp_path)) == str(tmp_path)

    def test_repo_id_downloads(self, tmp_path, monkeypatch):
        calls = []

        def fake_snapshot(repo_id, revision=None, allow_patterns=None):
            calls.append((repo_id, revision, tuple(allow_patterns)))
            return str(tmp_path / "snap")

        monkeypatch.setattr(hub, "_snapshot_download", fake_snapshot)
        got = resolve_pretrained_path("org/model-x", revision="abc123")
        assert got == str(tmp_path / "snap")
        assert calls == [("org/model-x", "abc123", hub._DEFAULT_PATTERNS)]

    def test_garbage_raises(self):
        with pytest.raises(FileNotFoundError, match="neither a local"):
            resolve_pretrained_path("/no/such/dir")
        with pytest.raises(FileNotFoundError):
            resolve_pretrained_path("too/many/segments")

    def test_ambiguous_id_shaped_path_raises_naming_both(self, tmp_path,
                                                         monkeypatch):
        """'checkpoints/model' where checkpoints/ exists is almost always a
        typo'd local path — refuse with both readings instead of a hub 404."""
        (tmp_path / "checkpoints").mkdir()
        monkeypatch.chdir(tmp_path)

        def boom(*a, **k):
            raise AssertionError("ambiguous input must not hit the hub")

        monkeypatch.setattr(hub, "_download", boom)
        with pytest.raises(FileNotFoundError, match="ambiguous") as exc:
            resolve_pretrained_path("checkpoints/model")
        msg = str(exc.value)
        assert "hub repo id" in msg and "local directory" in msg

    def test_unambiguous_org_still_downloads(self, tmp_path, monkeypatch):
        # no local 'org' directory: plain hub id, resolves normally
        monkeypatch.chdir(tmp_path)
        monkeypatch.setattr(hub, "_snapshot_download",
                            lambda *a, **k: "/cache/snap")
        assert resolve_pretrained_path("org/model") == "/cache/snap"


class TestProcessZeroGating:
    """The download rides parallel.init.main_process_first; fake the process
    topology at that layer and record the barrier/download interleaving."""

    def _run(self, monkeypatch, idx, n):
        import jax

        import automodel_tpu.parallel.init as dist_init

        events = []
        monkeypatch.setattr(jax, "process_index", lambda: idx)
        monkeypatch.setattr(jax, "process_count", lambda: n)
        monkeypatch.setattr(dist_init, "barrier",
                            lambda name="barrier": events.append("barrier"))
        monkeypatch.setattr(
            hub, "_snapshot_download",
            lambda *a, **k: (events.append("download"), "/cache/snap")[1],
        )
        out = resolve_pretrained_path("org/m")
        assert out == "/cache/snap"
        return events

    def test_single_process_no_barrier(self, monkeypatch):
        assert self._run(monkeypatch, 0, 1) == ["download"]

    def test_process_zero_downloads_then_barriers(self, monkeypatch):
        assert self._run(monkeypatch, 0, 4) == ["download", "barrier"]

    def test_other_processes_barrier_then_resolve(self, monkeypatch):
        assert self._run(monkeypatch, 3, 4) == ["barrier", "download"]


class TestFromPretrainedWithHubId(object):
    def test_auto_model_loads_via_fake_cache(self, tmp_path, monkeypatch):
        """End-to-end: a hub id resolves to a (fake) snapshot dir and the
        normal local from_pretrained path loads it."""
        import jax.numpy as jnp
        import ml_dtypes
        import safetensors.numpy

        from automodel_tpu.models.auto import AutoModelForCausalLM
        from automodel_tpu.models.common.backend import BackendConfig

        cfg = {
            "architectures": ["LlamaForCausalLM"],
            "vocab_size": 64, "hidden_size": 16, "intermediate_size": 32,
            "num_hidden_layers": 1, "num_attention_heads": 2,
            "num_key_value_heads": 2, "max_position_embeddings": 32,
            "tie_word_embeddings": False,
        }
        snap = tmp_path / "models--org--tiny" / "snapshots" / "rev"
        snap.mkdir(parents=True)
        (snap / "config.json").write_text(json.dumps(cfg))
        model = AutoModelForCausalLM.from_config(cfg, BackendConfig(dtype="float32"))
        params = model.init(__import__("jax").random.key(0), jnp.float32)
        tensors = model.state_dict_adapter().to_hf(params)
        safetensors.numpy.save_file(
            {k: np.asarray(v) for k, v in tensors.items()},
            str(snap / "model.safetensors"),
        )
        monkeypatch.setattr(hub, "_snapshot_download", lambda *a, **k: str(snap))

        model2, params2 = AutoModelForCausalLM.from_pretrained(
            "org/tiny", BackendConfig(dtype="float32"), dtype=jnp.float32
        )
        np.testing.assert_array_equal(
            np.asarray(params2["embed"]), np.asarray(params["embed"])
        )
