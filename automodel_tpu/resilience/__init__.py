"""Fault-tolerant training: anomaly rollback, checkpoint integrity + fallback
restore, coordinated preemption, elastic topology (mesh-shape-agnostic resume),
transient-fault retry, and a deterministic fault-injection harness
(docs/resilience.md)."""

from automodel_tpu.resilience.anomaly import AnomalyDetector, RecoveryPolicy, Verdict
from automodel_tpu.resilience.chaos import ChaosConfig, ChaosInjector, FlakyIO
from automodel_tpu.resilience.config import (
    AnomalyConfig, ElasticConfig, PreemptionConfig, ResilienceConfig,
    RollbackConfig,
)
from automodel_tpu.resilience.elastic import (
    ElasticTopologyChange, merge_host_states, plan_warmup_micro_counts,
    repartition_dataloader_state,
)
from automodel_tpu.resilience.manager import ResilienceManager

__all__ = [
    "AnomalyConfig",
    "AnomalyDetector",
    "ChaosConfig",
    "ChaosInjector",
    "ElasticConfig",
    "ElasticTopologyChange",
    "FlakyIO",
    "PreemptionConfig",
    "RecoveryPolicy",
    "ResilienceConfig",
    "ResilienceManager",
    "RollbackConfig",
    "Verdict",
    "merge_host_states",
    "plan_warmup_micro_counts",
    "repartition_dataloader_state",
]
