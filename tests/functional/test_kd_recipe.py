"""KD recipe end-to-end (reference llm_pretrain_and_kd scenario): student distills
from a teacher; loss falls and pure-CE validation is finite."""

import json
import textwrap

import numpy as np
import pytest

from automodel_tpu.config.loader import load_config
from automodel_tpu.utils import jax_compat
from tests.functional.jsonl import losses as jl_losses, metric_rows
from automodel_tpu.recipes.llm.kd import KnowledgeDistillationRecipe

# see tests/unit/test_pipeline.py: pre-0.5 jax + XLA CPU cannot lower the
# PartitionId the pp ring's axis_index produces under partial-manual shard_map
pp_partial_manual_compiles = pytest.mark.skipif(
    jax_compat.SHIMMED,
    reason="jax<0.5 XLA CPU cannot lower PartitionId under partial-manual "
    "shard_map (pp ring axis_index)",
)


def test_kd_loss_decreases(tmp_path, cpu_devices):
    student = """
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 32
        intermediate_size: 64
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    """
    teacher = student.replace("hidden_size: 32", "hidden_size: 64").replace(
        "intermediate_size: 64", "intermediate_size: 128"
    )
    cfg_text = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
{textwrap.indent(textwrap.dedent(student), "        ")}
    teacher_model:
      config:
{textwrap.indent(textwrap.dedent(teacher), "        ")}
    kd:
      temperature: 2.0
      kd_ratio: 0.5
    distributed:
      dp_shard: 4
      tp: 2
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 256
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: 2
      max_steps: 6
      num_epochs: 10
      handle_sigterm: false
    optimizer:
      lr: 1.0e-2
      max_grad_norm: 1.0
    lr_scheduler:
      lr_warmup_steps: 2
    checkpoint:
      enabled: false
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg_text))
    recipe = KnowledgeDistillationRecipe(load_config(p)).setup()
    recipe.run_train_validation_loop()
    rows = metric_rows(tmp_path / "out" / "training.jsonl")
    losses = [r["loss"] for r in rows]
    assert np.isfinite(losses).all()
    # blended objective: CE falls toward data + KL toward (random) teacher; the
    # CE component dominates direction on learnable data
    assert losses[-1] < losses[0]
    # teacher params were never touched by the optimizer
    assert recipe.teacher_params is not None


def test_kd_peft_adapter_trains(tmp_path, cpu_devices):
    """kd + peft (a round-1 fence): the frozen slot carries teacher AND lora
    base; only the adapter gets optimizer state, and the blended loss falls."""
    student = """
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 32
        intermediate_size: 64
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    """
    cfg_text = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
{textwrap.indent(textwrap.dedent(student), "        ")}
    teacher_model:
      config:
{textwrap.indent(textwrap.dedent(student), "        ")}
    kd:
      temperature: 2.0
      kd_ratio: 0.2
    peft:
      dim: 8
      alpha: 32
      match_all_linear: true
    distributed:
      dp_shard: 4
      tp: 2
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 256
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: 2
      max_steps: 20
      num_epochs: 10
      handle_sigterm: false
    optimizer:
      lr: 1.0e-2
      max_grad_norm: 1.0
    lr_scheduler:
      lr_warmup_steps: 2
    checkpoint:
      enabled: false
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg_text))
    recipe = KnowledgeDistillationRecipe(load_config(p)).setup()
    assert recipe.peft is not None
    from automodel_tpu.peft.lora import count_lora_params

    assert count_lora_params(recipe.train_params) < 100_000
    base_before = np.asarray(recipe.params["layers"]["wq"]).copy()
    adapter_before = np.asarray(recipe.train_params["layers"]["wq"]["lora_b"]).copy()
    recipe.run_train_validation_loop()
    rows = metric_rows(tmp_path / "out" / "training.jsonl")
    losses = [r["loss"] for r in rows]
    assert np.isfinite(losses).all()
    # the blended objective (CE + KL to a random teacher) conflicts at rank-8
    # capacity, so assert the mechanism: adapter trains, base frozen, loss improves
    assert min(losses) < losses[0] - 0.05, f"kd+peft must improve at some point: {losses}"
    assert not np.allclose(np.asarray(recipe.train_params["layers"]["wq"]["lora_b"]), adapter_before)
    np.testing.assert_array_equal(np.asarray(recipe.params["layers"]["wq"]), base_before)


@pp_partial_manual_compiles
def test_kd_pp_matches_unpipelined_trajectory(tmp_path, cpu_devices):
    """kd x pp (a round-2 fence): the student pipelines to hidden states, the
    student head + teacher forward + blended loss close outside the manual
    region — the pp=2 trajectory must reproduce the unpipelined one exactly."""
    student = """
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 32
        intermediate_size: 64
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    """

    def run(tag, dist):
        cfg_text = f"""
        seed: 7
        output_dir: {tmp_path}/{tag}
        model:
          config:
{textwrap.indent(textwrap.dedent(student), "            ")}
        teacher_model:
          config:
{textwrap.indent(textwrap.dedent(student), "            ")}
        distributed: {dist}
        backend: {{dtype: float32}}
        kd: {{temperature: 2.0, kd_ratio: 0.5}}
        dataset:
          _target_: automodel_tpu.data.llm.mock.MockSFTDataset
          vocab_size: 128
          seq_len: 32
          num_samples: 128
          seed: 0
          pattern: arith
        micro_batch_size: 8
        seq_len: 32
        step_scheduler: {{grad_acc_steps: 2, max_steps: 6, handle_sigterm: false}}
        optimizer: {{lr: 1.0e-2, weight_decay: 0.0, max_grad_norm: 1.0}}
        lr_scheduler: {{lr_warmup_steps: 2}}
        checkpoint: {{enabled: false}}
        """
        p = tmp_path / f"cfg_{tag}.yaml"
        p.write_text(textwrap.dedent(cfg_text))
        r = KnowledgeDistillationRecipe(load_config(p))
        r.setup()
        r.run_train_validation_loop()
        return jl_losses(tmp_path / tag / "training.jsonl")

    ref = run("kd_pp1", "{dp_shard: 4, tp: 2}")
    got = run("kd_pp2", "{dp_shard: 2, tp: 2, pp: 2}")
    assert np.isfinite(ref).all() and ref[-1] < ref[0]
    np.testing.assert_allclose(got, ref, rtol=1e-4)


@pp_partial_manual_compiles
def test_kd_moe_student_pp_matches_unpipelined_trajectory(tmp_path, cpu_devices):
    """kd x pp for MoE students (a round-3 fence): the student rides the same
    pipelined hidden-state path as train_ft's MoE pp loss; the pp=2 trajectory
    must reproduce the unpipelined one, expert_load metrics included."""
    student = """
        architectures: [Qwen3MoeForCausalLM]
        vocab_size: 128
        hidden_size: 32
        intermediate_size: 48
        moe_intermediate_size: 24
        num_hidden_layers: 4
        num_attention_heads: 4
        num_key_value_heads: 2
        head_dim: 8
        max_position_embeddings: 128
        num_experts: 8
        num_experts_per_tok: 2
        norm_topk_prob: true
        router_aux_loss_coef: 0.0
    """
    teacher = """
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 32
        intermediate_size: 64
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    """

    def run(tag, dist):
        cfg_text = f"""
        seed: 7
        output_dir: {tmp_path}/{tag}
        model:
          config:
{textwrap.indent(textwrap.dedent(student), "            ")}
        teacher_model:
          config:
{textwrap.indent(textwrap.dedent(teacher), "            ")}
        distributed: {dist}
        backend: {{dtype: float32}}
        kd: {{temperature: 2.0, kd_ratio: 0.5}}
        dataset:
          _target_: automodel_tpu.data.llm.mock.MockSFTDataset
          vocab_size: 128
          seq_len: 32
          num_samples: 128
          seed: 0
          pattern: arith
        micro_batch_size: 8
        seq_len: 32
        step_scheduler: {{grad_acc_steps: 2, max_steps: 6, handle_sigterm: false}}
        optimizer: {{lr: 1.0e-2, weight_decay: 0.0, max_grad_norm: 1.0}}
        lr_scheduler: {{lr_warmup_steps: 2}}
        checkpoint: {{enabled: false}}
        """
        p = tmp_path / f"cfg_{tag}.yaml"
        p.write_text(textwrap.dedent(cfg_text))
        r = KnowledgeDistillationRecipe(load_config(p))
        r.setup()
        r.run_train_validation_loop()
        rows = metric_rows(tmp_path / tag / "training.jsonl")
        assert "moe_load/max_util_mean" in rows[0]
        return [row["loss"] for row in rows]

    ref = run("kdm_pp1", "{dp_shard: 4, ep: 2}")
    got = run("kdm_pp2", "{dp_shard: 2, ep: 2, pp: 2}")
    assert np.isfinite(ref).all() and ref[-1] < ref[0]
    np.testing.assert_allclose(got, ref, rtol=1e-4)


@pp_partial_manual_compiles
def test_kd_pp_moe_teacher_runs(tmp_path, cpu_devices):
    """kd x pp with an MoE TEACHER: the pp path must unpack the teacher's
    (logits, stats) tuple and thread token_mask, like the non-pp path."""
    student = """
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 32
        intermediate_size: 64
        num_hidden_layers: 4
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    """
    teacher = """
        architectures: [Qwen3MoeForCausalLM]
        vocab_size: 128
        hidden_size: 32
        intermediate_size: 48
        moe_intermediate_size: 24
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        head_dim: 8
        max_position_embeddings: 128
        num_experts: 8
        num_experts_per_tok: 2
        norm_topk_prob: true
    """
    cfg_text = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
{textwrap.indent(textwrap.dedent(student), "        ")}
    teacher_model:
      config:
{textwrap.indent(textwrap.dedent(teacher), "        ")}
    kd: {{temperature: 2.0, kd_ratio: 0.5}}
    distributed: {{dp_shard: 2, ep: 2, pp: 2}}
    backend: {{dtype: float32}}
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 64
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler: {{grad_acc_steps: 2, max_steps: 2, handle_sigterm: false}}
    optimizer: {{lr: 1.0e-2, max_grad_norm: 1.0}}
    lr_scheduler: {{lr_warmup_steps: 2}}
    checkpoint: {{enabled: false}}
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg_text))
    recipe = KnowledgeDistillationRecipe(load_config(p)).setup()
    recipe.run_train_validation_loop()
    losses = jl_losses(tmp_path / "out" / "training.jsonl")
    assert np.isfinite(losses).all() and len(losses) == 2


def test_kd_peft_dropout_runs(tmp_path, cpu_devices):
    """kd + lora dropout (a round-3 fence): the KD step threads a dropout rng;
    the run is finite and deterministic under the seeded rng stream."""
    student = """
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 32
        intermediate_size: 64
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    """
    cfg_text = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
{textwrap.indent(textwrap.dedent(student), "        ")}
    teacher_model:
      config:
{textwrap.indent(textwrap.dedent(student), "        ")}
    kd: {{temperature: 2.0, kd_ratio: 0.2}}
    peft:
      dim: 8
      alpha: 32
      match_all_linear: true
      dropout: 0.1
    distributed: {{dp_shard: 4, tp: 2}}
    backend: {{dtype: float32}}
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 128
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler: {{grad_acc_steps: 2, max_steps: 4, handle_sigterm: false}}
    optimizer: {{lr: 1.0e-2, max_grad_norm: 1.0}}
    lr_scheduler: {{lr_warmup_steps: 2}}
    checkpoint: {{enabled: false}}
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg_text))
    recipe = KnowledgeDistillationRecipe(load_config(p)).setup()
    assert recipe._step_needs_rng
    adapter_before = np.asarray(recipe.train_params["layers"]["wq"]["lora_b"]).copy()
    recipe.run_train_validation_loop()
    losses = jl_losses(tmp_path / "out" / "training.jsonl")
    assert np.isfinite(losses).all()
    assert not np.allclose(
        np.asarray(recipe.train_params["layers"]["wq"]["lora_b"]), adapter_before
    )
