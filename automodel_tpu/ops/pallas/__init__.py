"""Pallas TPU kernels: the hot-op layer (reference L3 kernel layer — TE fused
attention, Triton CE/LoRA — rebuilt TPU-native per SURVEY.md §2.1)."""
