"""End-to-end recipe runs on the virtual 8-device mesh — the analogue of the
reference's 2-GPU L2 functional tests (SURVEY.md §4): tiny model, few steps, real
SPMD semantics, loss must fall, checkpoints must resume exactly."""

import json
import textwrap

import numpy as np
import pytest

from automodel_tpu.config.loader import load_config
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction
from automodel_tpu.utils import jax_compat

# see tests/unit/test_ring_attention.py: pre-0.5 jax + XLA CPU CHECK-aborts
# (process-killing) compiling the ring kernel under partial-manual shard_map
ring_cp_compiles = pytest.mark.skipif(
    jax_compat.SHIMMED,
    reason="jax<0.5 XLA CPU hard-aborts compiling partial-manual ring "
    "attention (interpret-mode pallas under shard_map over cp)",
)

# see tests/unit/test_pipeline.py: pre-0.5 jax + XLA CPU cannot lower the
# PartitionId the pp ring's axis_index produces under partial-manual shard_map
pp_partial_manual_compiles = pytest.mark.skipif(
    jax_compat.SHIMMED,
    reason="jax<0.5 XLA CPU cannot lower PartitionId under partial-manual "
    "shard_map (pp ring axis_index)",
)


def _write_cfg(tmp_path, extra="", dp_shard=4, tp=2, pp=1, n_layers=2, max_steps=6,
               grad_acc=2, ckpt=False):
    cfg = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 128
        num_hidden_layers: {n_layers}
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    distributed:
      dp_shard: {dp_shard}
      tp: {tp}
      pp: {pp}
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 256
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: {grad_acc}
      max_steps: {max_steps}
      num_epochs: 10
      handle_sigterm: false
      ckpt_every_steps: {3 if ckpt else 0}
    optimizer:
      lr: 1.0e-2
      weight_decay: 0.0
      max_grad_norm: 1.0
    lr_scheduler:
      lr_warmup_steps: 2
    checkpoint:
      enabled: {str(ckpt).lower()}
      checkpoint_dir: {tmp_path}/ckpt
    {extra}
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg))
    return p


def _read_jsonl(path):
    rows = [json.loads(line) for line in open(path)]
    # run-header and compile-accounting rows are stream metadata; resilience
    # event rows stay — TestResilience asserts on them
    return [r for r in rows
            if "run_header" not in r
            and r.get("event") not in ("compile_costs", "compile_summary")]


@pytest.fixture(scope="module")
def base_run(tmp_path_factory, cpu_devices):
    """The canonical dense run (dp_shard=4 x tp=2, ckpt at 3 and 6), compiled
    once and shared by the loss/observability/resume assertions — the compile
    dominates these tests' wall time. Artifacts are captured eagerly;
    test_resume_exact may mutate the directory afterwards."""
    tmp = tmp_path_factory.mktemp("base_run")
    cfg = load_config(_write_cfg(tmp, ckpt=True))
    recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
    recipe.run_train_validation_loop()
    raw = [json.loads(line) for line in open(tmp / "out" / "training.jsonl")]
    timeline = json.load(open(tmp / "out" / "timeline.json"))
    return {
        "tmp": tmp,
        "raw": raw,
        "rows": _read_jsonl(tmp / "out" / "training.jsonl"),
        "timeline": timeline,
    }


class TestTrainRecipeE2E:
    def test_loss_decreases_sharded(self, base_run):
        rows = base_run["rows"]
        assert len(rows) == 6
        losses = [r["loss"] for r in rows]
        # 128-vocab: initial loss ~ln(128)=4.85; learnable data must drop w/ lr=1e-2
        assert losses[0] > 4.0
        assert losses[-1] < losses[0] - 0.3
        assert all(np.isfinite(r["grad_norm"]) for r in rows)
        # observability: every row carries compile time, goodput fractions, and
        # mfu (0.0 on CPU — the device kind has no peak-TFLOPs entry)
        for r in rows:
            assert r["compile_time_s"] > 0.0
            assert 0.0 <= r["goodput"] <= 1.0
            for bucket in ("compile", "data_wait", "device_step", "idle"):
                assert 0.0 <= r[f"goodput/{bucket}"] <= 1.0
        # mfu is null on the compile-only first window, 0.0 on CPU afterwards
        # (the device kind has no peak-TFLOPs entry)
        assert rows[0]["mfu"] is None
        assert all(r["mfu"] == 0.0 for r in rows[1:])
        # the first log window holds only the compile step: throughput is null,
        # never inf/0-division garbage
        assert rows[0]["tps"] is None
        assert all(r["tps"] > 0 for r in rows[1:])

    def test_run_header_compile_costs_and_timeline(self, base_run):
        """The perf-observability artifacts of one training run: the one-time
        run-header row, the per-compile analytic cost/roofline row, per-step
        bound diagnosis, and a Perfetto-loadable timeline.json."""
        raw = base_run["raw"]

        headers = [r for r in raw if r.get("run_header")]
        assert len(headers) == 1
        h = headers[0]
        assert h["jax_version"] and h["jaxlib_version"]
        assert h["n_devices"] == 8 and h["process_count"] == 1
        assert h["mesh"]["dp_shard"] == 4 and h["mesh"]["tp"] == 2
        assert h["model_id"] == "LlamaForCausalLM"
        assert "git_sha" in h and len(h["config_digest"]) == 16
        # XLA compile-cache counters ride the header (written pre-compile, so
        # they cover model-init dispatches; run totals land in compile_summary)
        cc = h["compile_cache"]
        assert cc["listener"] is True and "persistent_enabled" in cc

        compiles = [r for r in raw if r.get("event") == "compile_costs"]
        assert len(compiles) == 1
        c = compiles[0]
        assert c["hlo_flops"] > 0
        assert c["hlo_bytes_accessed"] > 0
        assert c["comm_bytes_total"] > 0  # dp=4 x tp=2 sharding emits collectives
        assert c["roofline_step_time_s"] > 0
        assert c["roofline_bound"] in ("compute", "memory", "comms")

        metric = [r for r in raw if "loss" in r]
        assert len(metric) == 6
        # per-row diagnosis on every post-compile row (row 0 has no step time)
        for r in metric[1:]:
            assert r["bound"] in ("compute", "memory", "comms", "input")
            assert r["roofline_frac"] > 0

        summaries = [r for r in raw if r.get("event") == "compile_summary"]
        assert len(summaries) == 1
        assert summaries[0]["compile_aot"] >= 1
        assert summaries[0]["compile_jit_fallback"] == 0

        doc = base_run["timeline"]
        assert doc["displayTimeUnit"] == "ms"
        for e in doc["traceEvents"]:
            assert {"name", "cat", "ph", "ts", "pid", "tid"} <= set(e)
        names = {e["name"] for e in doc["traceEvents"]}
        assert {"compile", "compile_costs", "step", "checkpoint"} <= names
        steps = [e for e in doc["traceEvents"] if e["name"] == "step"]
        assert len(steps) == 6
        assert all(e["ph"] == "X" and e["dur"] > 0 for e in steps)

    def test_memory_plan_rides_header_and_reconciles(self, base_run):
        """The memory pillar's two halves on a real run: the analytic
        ``mem_plan/*`` budget in the run_header (written BEFORE the first
        compile), and the compile_costs row carrying XLA's measured ``mem/*``
        attribution reconciled against it within the documented tolerance."""
        from automodel_tpu.observability.memory_plan import RECON_TOLERANCE

        raw = base_run["raw"]
        h = [r for r in raw if r.get("run_header")][0]
        assert h["mem_plan/params_gib"] > 0
        assert h["mem_plan/opt_gib"] > 0
        assert h["mem_plan/batch_gib"] > 0
        assert h["mem_plan/act_est_gib"] > 0
        assert h["mem_plan/total_gib"] == pytest.approx(
            h["mem_plan/params_gib"] + h["mem_plan/opt_gib"]
            + h["mem_plan/batch_gib"] + h["mem_plan/act_est_gib"], abs=5e-6)
        # CPU: no allocator bytes_limit and no override => no verdict keys
        assert "mem_plan/fits" not in h

        c = [r for r in raw if r.get("event") == "compile_costs"][0]
        assert c["mem/args_gib"] > 0 and c["mem/peak_est_gib"] > 0
        # XLA's identity: peak = args + out + temp + code - alias
        assert c["mem/peak_est_gib"] == pytest.approx(
            c["mem/args_gib"] + c["mem/out_gib"] + c["mem/temp_gib"]
            + c["mem/code_gib"] - c["mem/alias_gib"], abs=5e-6)
        # the acceptance bar: analytic args (params+opt+batch) within the
        # documented tolerance of what the compiled program actually takes
        assert c["mem_plan/recon_rel_err"] <= RECON_TOLERANCE
        # the hbm_plan_gib counter landed on the timeline at compile time
        counters = [e for e in base_run["timeline"]["traceEvents"]
                    if e["ph"] == "C" and e["name"] == "hbm_plan_gib"]
        assert len(counters) == 1
        assert counters[0]["args"]["params"] == h["mem_plan/params_gib"]

    def test_hsdp_matches_fsdp_trajectory(self, tmp_path, cpu_devices):
        """HSDP (dp_replicate=2 x dp_shard=2 x tp=2 — reference
        mesh_utils.py:173-190) end-to-end: params replicate across the replica
        axis, the global batch still shards 4 ways, so the trajectory must
        reproduce the pure-fsdp dp_shard=4 run step for step."""

        def run(tag, dist):
            cfg_text = _write_cfg(tmp_path).read_text()
            cfg_text = cfg_text.replace("dp_shard: 4\n  tp: 2", dist)
            cfg_text = cfg_text.replace(f"output_dir: {tmp_path}/out",
                                        f"output_dir: {tmp_path}/{tag}")
            p = tmp_path / f"cfg_{tag}.yaml"
            p.write_text(cfg_text)
            r = TrainFinetuneRecipeForNextTokenPrediction(load_config(str(p)))
            r.setup()
            if tag == "hsdp":
                assert r.mesh.shape["dp_replicate"] == 2
                # model params actually replicate over dp_replicate and shard
                # over dp_shard: local shard = L/1 x rows/(dp_shard) x ...
                wq = r.params["layers"]["wq"]
                spec = wq.sharding.spec
                flat = [a for ax in spec if ax is not None
                        for a in ((ax,) if isinstance(ax, str) else ax)]
                assert "dp_replicate" not in flat, spec
                assert "dp_shard" in flat, spec
            r.run_train_validation_loop()
            return [row["loss"] for row in _read_jsonl(tmp_path / tag / "training.jsonl")]

        ref = run("fsdp", "dp_shard: 4\n  tp: 2")
        got = run("hsdp", "dp_replicate: 2\n  dp_shard: 2\n  tp: 2")
        assert np.isfinite(ref).all() and ref[-1] < ref[0]
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    @pp_partial_manual_compiles
    def test_granite_pp_matches_unpipelined_trajectory(self, tmp_path, cpu_devices):
        """Granite's mup scalars under pp: the pipeline embeds OUTSIDE
        decoder_forward, so embedding_multiplier must ride embed_lookup itself
        (a review-caught silent-wrong-math bug) — pp=2 must reproduce the
        unpipelined trajectory exactly with non-trivial scalars."""

        def run(tag, dist):
            cfg_text = _write_cfg(tmp_path, n_layers=4).read_text()
            cfg_text = cfg_text.replace("architectures: [LlamaForCausalLM]",
                                        "architectures: [GraniteForCausalLM]")
            cfg_text = cfg_text.replace(
                "max_position_embeddings: 128",
                "max_position_embeddings: 128\n    embedding_multiplier: 6.0\n"
                "    residual_multiplier: 0.25\n"
                "    attention_multiplier: 0.0883883\n"
                "    logits_scaling: 4.0")
            cfg_text = cfg_text.replace("dp_shard: 4\n  tp: 2\n  pp: 1", dist)
            cfg_text = cfg_text.replace(f"output_dir: {tmp_path}/out",
                                        f"output_dir: {tmp_path}/{tag}")
            p = tmp_path / f"cfg_{tag}.yaml"
            p.write_text(cfg_text)
            r = TrainFinetuneRecipeForNextTokenPrediction(load_config(str(p)))
            r.setup()
            assert r.model.config.embedding_multiplier == 6.0
            r.run_train_validation_loop()
            return [row["loss"] for row in _read_jsonl(tmp_path / tag / "training.jsonl")]

        ref = run("gr_pp1", "dp_shard: 4\n  tp: 2\n  pp: 1")
        got = run("gr_pp2", "dp_shard: 2\n  tp: 2\n  pp: 2")
        assert np.isfinite(ref).all() and ref[-1] < ref[0]
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    def test_resume_exact(self, base_run):
        # run 1 is the shared fixture: 6 steps with ckpt at 3 and final at 6
        tmp_path = base_run["tmp"]
        rows1 = base_run["rows"]

        # run 2: resume from step 3 checkpoint by removing later ckpts
        import shutil

        shutil.rmtree(tmp_path / "ckpt" / "step_6")
        (tmp_path / "ckpt" / "latest").unlink()
        (tmp_path / "out" / "training.jsonl").unlink()
        cfg2 = load_config(_write_cfg(tmp_path, ckpt=True))
        r2 = TrainFinetuneRecipeForNextTokenPrediction(cfg2).setup()
        assert r2.step_scheduler.step == 3
        r2.run_train_validation_loop()
        rows2 = _read_jsonl(tmp_path / "out" / "training.jsonl")
        # steps 4..6 must reproduce run 1 exactly (same data order, same params)
        l1 = {r["step"]: r["loss"] for r in rows1}
        l2 = {r["step"]: r["loss"] for r in rows2}
        for s in (4, 5, 6):
            assert l2[s] == pytest.approx(l1[s], rel=1e-5), f"step {s} diverged"

    @pp_partial_manual_compiles
    def test_pipeline_parallel_loss_decreases(self, tmp_path, cpu_devices):
        cfg = load_config(_write_cfg(tmp_path, dp_shard=2, tp=2, pp=2, n_layers=4, grad_acc=4))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        losses = [r["loss"] for r in rows]
        assert losses[0] > 4.0
        assert losses[-1] < losses[0] - 0.3
        # layer params actually pp-sharded: 4 layers over pp=2 -> 2 local
        wq = recipe.params["layers"]["wq"]
        assert wq.sharding.shard_shape(wq.shape)[0] == 2

    def test_packed_sequence_loss_decreases(self, tmp_path, cpu_devices):
        extra = textwrap.dedent("""\
        packed_sequence:
          packed_sequence_size: 64
        """).replace("\n", "\n    ")
        cfg = load_config(_write_cfg(tmp_path, extra=extra))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        assert recipe.seq_len == 64  # packs override seq_len
        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        losses = [r["loss"] for r in rows]
        assert losses[0] > 4.0
        assert losses[-1] < losses[0] - 0.3

    def test_packed_sequence_with_cp(self, tmp_path, cpu_devices):
        extra = textwrap.dedent("""\
        packed_sequence:
          packed_sequence_size: 64
        """).replace("\n", "\n    ")
        cfg = load_config(_write_cfg(tmp_path, extra=extra, dp_shard=2, tp=2, max_steps=3))
        cfg.set_by_path("distributed.cp", 2)
        cfg.set_by_path("distributed.tp", 1)
        cfg.set_by_path("distributed.dp_shard", 4)
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        assert all(np.isfinite(r["loss"]) for r in rows)

    def test_linear_ce_loss_matches(self, tmp_path, cpu_devices):
        cfg = load_config(_write_cfg(tmp_path, extra="loss:\n      name: linear_ce", max_steps=2))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        assert rows[0]["loss"] > 4.0  # sane CE for random data


class TestResilience:
    """Chaos-driven recovery end-to-end on the mock recipe (docs/resilience.md):
    an injected NaN step must roll back to the last checkpoint and finish with
    a loss matching the uninterrupted baseline to within the skipped window,
    and a truncated latest checkpoint must fall back to an older verifiable one
    at resume."""

    _resilience = textwrap.dedent("""\
    resilience:
      enabled: true
      anomaly: {window: 20, min_history: 5}
      max_skipped_updates: 0
      rollback: {max_rollbacks: 2, skip_steps: 0}
      chaos:
        enabled: true
        nan_grad_steps: [6]
        corrupt_ckpt_steps: [8]
    """).replace("\n", "\n    ")

    def test_chaos_rollback_recovers_and_falls_back_on_resume(self, tmp_path, cpu_devices):
        # uninterrupted baseline: same seed/data, no faults
        base_dir = tmp_path / "base"
        base_dir.mkdir()
        cfg = load_config(_write_cfg(base_dir, ckpt=False, max_steps=10, grad_acc=1))
        TrainFinetuneRecipeForNextTokenPrediction(cfg).setup().run_train_validation_loop()
        base_rows = _read_jsonl(base_dir / "out" / "training.jsonl")

        # chaos run: NaN-poisoned params at step 6, checkpoint truncated at 8
        cfg = load_config(_write_cfg(tmp_path, extra=self._resilience, ckpt=True,
                                     max_steps=10, grad_acc=1))
        cfg["step_scheduler"]["ckpt_every_steps"] = 4
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")

        events = [r["resilience/event"] for r in rows if "resilience/event" in r]
        assert "rollback" in events and "rollback_done" in events
        done = next(r for r in rows if r.get("resilience/event") == "rollback_done")
        assert done["resilience/from_step"] == 6
        assert done["resilience/to_step"] == 4

        losses = {r["step"]: r["loss"] for r in rows if "loss" in r}
        assert 6 not in losses  # the poisoned step never logs a metric row
        assert all(np.isfinite(v) for v in losses.values())
        base_losses = {r["step"]: r["loss"] for r in base_rows}
        # rollback dropped the step-5..6 updates, so trajectories differ by the
        # skipped window only — the final loss must land close to the baseline
        assert losses[10] == pytest.approx(base_losses[10], abs=0.35)

        # the rollback must also land on the unified timeline as an instant
        tl = json.load(open(tmp_path / "out" / "timeline.json"))
        tl_names = {e["name"] for e in tl["traceEvents"]}
        assert "rollback" in tl_names

        # resume leg: drop the clean tail checkpoints so the truncated step_8
        # is newest — setup must reject it and walk back to step_4
        import shutil

        for d in ("step_10", "step_12"):
            if (tmp_path / "ckpt" / d).exists():
                shutil.rmtree(tmp_path / "ckpt" / d)
        (tmp_path / "ckpt" / "latest").unlink()
        cfg2 = load_config(_write_cfg(tmp_path, extra=self._resilience, ckpt=True,
                                      max_steps=10, grad_acc=1))
        r2 = TrainFinetuneRecipeForNextTokenPrediction(cfg2).setup()
        assert r2.step_scheduler.step == 4

    def test_resilience_abort_when_budget_exhausted(self, tmp_path, cpu_devices):
        # no checkpoints at all: a rollback request has nothing to restore and
        # must abort loudly rather than loop on poisoned params
        extra = textwrap.dedent("""\
        resilience:
          enabled: true
          anomaly: {min_history: 5}
          max_skipped_updates: 0
          chaos:
            enabled: true
            nan_grad_steps: [3]
        """).replace("\n", "\n    ")
        cfg = load_config(_write_cfg(tmp_path, extra=extra, ckpt=False, max_steps=6))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        with pytest.raises(RuntimeError, match="unrecoverable"):
            recipe.run_train_validation_loop()


class TestNanGuard:
    def test_nonfinite_grad_raises(self, tmp_path, cpu_devices):
        """distributed.check_for_nan_in_grad stops loudly on a non-finite signal
        (reference check_for_nan_in_grad, distributed/config.py:129) — forced here
        with an absurd lr that overflows bf16 within a few steps."""
        import pytest

        from automodel_tpu.config.loader import load_config
        from automodel_tpu.recipes.llm.train_ft import (
            TrainFinetuneRecipeForNextTokenPrediction,
        )

        cfg = load_config(_write_cfg(tmp_path))
        cfg["optimizer"]["lr"] = 1.0e12
        cfg["optimizer"]["max_grad_norm"] = None
        cfg["distributed"]["check_for_nan_in_grad"] = True
        cfg["step_scheduler"]["max_steps"] = 10
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        with pytest.raises(RuntimeError, match="non-finite"):
            recipe.run_train_validation_loop()


class TestContextParallelRing:
    @ring_cp_compiles
    def test_cp_ring_recipe_loss_decreases(self, tmp_path, cpu_devices):
        """cp=4 ring attention end-to-end through the recipe: loss must decrease,
        and a cp-sharded forward must match the single-device forward."""
        from automodel_tpu.config.loader import load_config
        from automodel_tpu.recipes.llm.train_ft import (
            TrainFinetuneRecipeForNextTokenPrediction,
        )

        import jax
        import jax.numpy as jnp

        cfg = load_config(_write_cfg(tmp_path, dp_shard=2, tp=1))
        cfg["distributed"]["cp"] = 4
        cfg["distributed"]["dp_shard"] = 2
        cfg["backend"]["context_parallel"] = "ring"
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()

        # parity: the cp-ring forward must match the plain xla forward exactly
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (4, 32)))
        with jax.sharding.set_mesh(recipe.mesh):
            ring_logits = recipe.model(recipe.params, ids, rules=recipe.rules)
        import dataclasses as _dc

        plain_backend = _dc.replace(recipe.backend, context_parallel="allgather")
        plain_model = type(recipe.model)(recipe.model.config, plain_backend)
        plain_logits = plain_model(recipe.params, ids)
        np.testing.assert_allclose(
            np.asarray(ring_logits), np.asarray(plain_logits), atol=2e-5
        )

        recipe.run_train_validation_loop()
        rows = _read_jsonl(tmp_path / "out" / "training.jsonl")
        losses = [r["loss"] for r in rows]
        assert losses[-1] < losses[0] * 0.95, losses
