"""EP-dispatch microbench: dense GSPMD path vs explicit a2a (VERDICT r3 #6).

Default (no args): single TPU chip, ep=1 degenerate mesh — the all_to_all is a
self-copy, so the delta between the two dispatchers is exactly the a2a path's
bucketing overhead (one-hot-cumsum queue positions + (ep, cap, D) scatter
layout) with zero real ICI traffic in either. Measured on v5e: a2a 2.25x
slower (577ms vs 257ms/step).

``--ep 4 --devices 8`` (run under JAX_PLATFORMS=cpu with
--xla_force_host_platform_device_count=8): the multi-rank comparison on the
virtual mesh, where routing actually crosses ranks — measured a2a ~2.05x
FASTER than dense (1.77s vs 3.63s/step at the scaled-down shape the flag
selects). Prints one JSON line per dispatcher.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def measure(dispatcher: str, *, ep=1, devices=1, seq_len=2048, micro_batch=4,
            n_steps=10):
    import jax
    import jax.numpy as jnp
    import optax

    from automodel_tpu.models.auto import AutoModelForCausalLM
    from automodel_tpu.models.common.backend import BackendConfig
    from automodel_tpu.ops.losses import masked_cross_entropy
    from automodel_tpu.parallel.mesh import MeshContext, default_sharding_rules
    from automodel_tpu.training.train_step import make_train_step

    ctx = MeshContext(ep=ep, dp_shard=devices // ep, world_size=devices)
    mesh = ctx.build_mesh(jax.devices()[:devices])
    rules = default_sharding_rules().with_mesh(mesh)
    if devices == 1:
        # qwen3-moe-A3B-ish proxy scaled to one 16GB chip
        hf_cfg = {
            "architectures": ["Qwen3MoeForCausalLM"],
            "vocab_size": 32000, "hidden_size": 1024, "intermediate_size": 3072,
            "moe_intermediate_size": 384, "num_hidden_layers": 12,
            "num_attention_heads": 16, "num_key_value_heads": 4, "head_dim": 64,
            "num_experts": 32, "num_experts_per_tok": 4, "norm_topk_prob": True,
            "max_position_embeddings": seq_len,
        }
        backend = BackendConfig(dtype="bfloat16", attention="flash",
                                remat_policy="mlp_attn_dots", dispatcher=dispatcher)
    else:
        # virtual-CPU-mesh shape (fp32, xla attention — CPU has no pallas/bf16 win)
        seq_len, micro_batch = 256, 8
        hf_cfg = {
            "architectures": ["Qwen3MoeForCausalLM"],
            "vocab_size": 512, "hidden_size": 128, "intermediate_size": 256,
            "moe_intermediate_size": 64, "num_hidden_layers": 4,
            "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 32,
            "num_experts": 16, "num_experts_per_tok": 4, "norm_topk_prob": True,
            "max_position_embeddings": seq_len,
        }
        backend = BackendConfig(dtype="float32", dispatcher=dispatcher)
    model = AutoModelForCausalLM.from_config(hf_cfg, backend)
    with mesh:
        params = model.init(jax.random.key(0), jnp.bfloat16)
        optimizer = optax.chain(optax.scale_by_factored_rms(), optax.scale(-1e-5))
        opt_state = jax.jit(optimizer.init)(params)

        def forward_loss(p, batch, n):
            # rules passed in BOTH modes (a2a needs the mesh; keeping the dense
            # path identical makes the comparison constraint-for-constraint fair)
            out, stats = model(
                p, batch["input_ids"], positions=batch["positions"],
                segment_ids=batch["segment_ids"],
                token_mask=batch["segment_ids"] != 0,
                rules=rules, training=True,
            )
            return masked_cross_entropy(out, batch["labels"], n), {
                "expert_load": stats["expert_load"]}

        step = jax.jit(make_train_step(forward_loss, optimizer), donate_argnums=(0, 1))
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 32000, (1, micro_batch, seq_len)).astype(np.int32)
        batch = {
            "input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids),
            "positions": jnp.broadcast_to(jnp.arange(seq_len, dtype=jnp.int32), ids.shape),
            "segment_ids": jnp.ones_like(jnp.asarray(ids)),
        }
        for _ in range(3):  # warmup + compile
            params, opt_state, m = step(params, opt_state, batch)
        float(m["loss"])  # sync through the tunnel (block_until_ready doesn't)
        t0 = time.perf_counter()
        for _ in range(n_steps):
            params, opt_state, m = step(params, opt_state, batch)
        float(m["loss"])
        dt = (time.perf_counter() - t0) / n_steps
    tokens = micro_batch * seq_len
    return {"dispatcher": dispatcher, "ep": ep, "devices": devices,
            "seq_len": seq_len, "step_time_ms": round(dt * 1e3, 2),
            "tokens_per_sec": round(tokens / dt, 1)}


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--ep", type=int, default=1)
    ap.add_argument("--devices", type=int, default=1)
    args = ap.parse_args()
    for disp in ("dense", "a2a"):
        print(json.dumps(measure(disp, ep=args.ep, devices=args.devices)))
