"""Mixtral family (Mixtral-8x7B / 8x22B) — TPU-native.

The reference serves Mixtral through its generic HF factory
(_transformers/model_init.py:89); here it rides the shared MoE decoder stack:
Mixtral is llama-lineage GQA attention (no qk-norm) + every-layer top-2 MoE.
HF's "topk logits then softmax" routing is mathematically identical to
"softmax all, topk, renormalize" (softmax is monotonic and the renormalized
selected probabilities equal the softmax over the selected logits), which is
the stack's softmax_before_topk + norm_topk_prob path — the full-softmax
scores also feed the aux load-balancing loss exactly as HF's router does.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.moe_transformer import (
    MoEDecoderConfig,
    init_moe_decoder_params,
    moe_decoder_forward,
    moe_decoder_logical_axes,
)
from automodel_tpu.moe.config import MoEConfig

__all__ = ["MixtralConfig", "MixtralForCausalLM"]


@dataclasses.dataclass
class MixtralConfig(MoEDecoderConfig):
    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "MixtralConfig":
        moe = MoEConfig(
            n_routed_experts=hf["num_local_experts"],
            n_activated_experts=hf.get("num_experts_per_tok", 2),
            dim=hf["hidden_size"],
            moe_inter_dim=hf["intermediate_size"],
            score_func="softmax",
            softmax_before_topk=True,
            norm_topk_prob=True,
            aux_loss_coeff=hf.get("router_aux_loss_coef", 0.02),
        )
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            num_attention_heads=hf["num_attention_heads"],
            num_key_value_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            head_dim=hf.get("head_dim"),
            max_position_embeddings=hf.get("max_position_embeddings", 32768),
            rope_theta=hf.get("rope_theta", 1e6),
            rope_scaling=hf.get("rope_scaling"),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            sliding_window=hf.get("sliding_window"),
            initializer_range=hf.get("initializer_range", 0.02),
            moe=moe,
            first_k_dense_replace=0,
        )


class MixtralForCausalLM:
    """Functional model: holds config + backend, operates on param pytrees."""

    config_class = MixtralConfig
    hf_architectures = ("MixtralForCausalLM",)

    def __init__(self, config: MixtralConfig, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()

    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        return init_moe_decoder_params(self.config, key, dtype)

    def logical_axes(self) -> dict:
        return moe_decoder_logical_axes(self.config)

    def abstract_params(self, dtype=jnp.bfloat16) -> dict:
        return jax.eval_shape(lambda k: self.init(k, dtype), jax.random.key(0))

    def __call__(self, params, input_ids, positions=None, segment_ids=None, token_mask=None,
                 rules=None, return_hidden=False, training=True, cache=None):
        return moe_decoder_forward(
            self.config, self.backend, params, input_ids,
            positions=positions, segment_ids=segment_ids, token_mask=token_mask,
            rules=rules, return_hidden=return_hidden, training=training, cache=cache,
        )

    def generate(self, params, input_ids, **kw):
        """Sample with a KV cache (see :func:`automodel_tpu.generation.generate`)."""
        from automodel_tpu.generation import generate

        return generate(self, params, input_ids, **kw)

    def state_dict_adapter(self):
        from automodel_tpu.models.mixtral.state_dict_adapter import MixtralStateDictAdapter

        return MixtralStateDictAdapter(self.config)

    @classmethod
    def from_config(cls, config, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = MixtralConfig.from_hf(config)
        return cls(config, backend)
