#!/usr/bin/env python
"""Self-checking CPU smoke for elastic topology (docs/resilience.md).

Simulates a slice resize the only way a single box can: the XLA host-platform
device count is fixed per process, so each phase runs in its own interpreter
with a different ``--xla_force_host_platform_device_count``. Four phases:

1. baseline: 8 virtual devices, ``dp_shard=8``, trains uninterrupted;
2. phase A: same mesh, checkpoints every 3 steps, stops at step 6;
3. phase B: 4 virtual devices, ``dp_shard=4``, resumes from phase A's
   checkpoint directory — the elastic restore path;
4. warm restart: two identical fresh runs sharing a persistent XLA compile
   cache — the second must report zero cache misses and zero jit demotions
   in its ``compile_summary`` row.

Asserts phase B classified the restore as elastic (an ``elastic_restore``
event naming the dp_shard 8->4 delta), re-partitioned the dataloader cursor
(an ``elastic_data_repartition`` event with zero re-fed examples — the global
batch size is process-count-bound and did not change), and finished with a
final loss matching the uninterrupted baseline (same data order, so the
trajectory continues rather than restarts).

Usage:  python tools/elastic_smoke.py [--workdir DIR]

The same scenario runs under pytest as ``pytest -m elastic``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap

MAX_STEPS = 16
SWITCH_STEP = 6
CKPT_EVERY = 3
LOSS_TOL = 0.5


def _write_cfg(root: str, name: str, *, dp_shard: int, ckpt_dir: str | None,
               max_steps: int, cache_dir: str | None = None) -> str:
    text = textwrap.dedent(f"""\
    seed: 11
    output_dir: {root}/{name}/out
    model:
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 128
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    distributed:
      dp_shard: {dp_shard}
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 256
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: 1
      max_steps: {max_steps}
      num_epochs: 10
      handle_sigterm: false
      ckpt_every_steps: {CKPT_EVERY if ckpt_dir else 0}
    optimizer:
      lr: 1.0e-2
      weight_decay: 0.0
      max_grad_norm: 1.0
    lr_scheduler:
      lr_warmup_steps: 2
    checkpoint:
      enabled: {str(ckpt_dir is not None).lower()}
      checkpoint_dir: {ckpt_dir or f"{root}/{name}/ckpt"}
    resilience:
      enabled: true
      anomaly: {{enabled: false}}
      elastic: {{enabled: true, allow_joiners: true}}
    """)
    if cache_dir:
        text += textwrap.dedent(f"""\
        compile_cache:
          dir: {cache_dir}
          min_entry_size_bytes: 0
          min_compile_time_secs: 0
        """)
    path = os.path.join(root, f"{name}.yaml")
    os.makedirs(os.path.join(root, name), exist_ok=True)
    with open(path, "w") as f:
        f.write(text)
    return path


def _run_phase(cfg_path: str, devices: int) -> None:
    """One training phase in a fresh interpreter pinned to ``devices`` virtual
    CPU devices (the whole point: device count is per-process)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--run", cfg_path],
        env=env, timeout=900,
    )
    if proc.returncode != 0:
        raise RuntimeError(f"phase {cfg_path} failed with rc={proc.returncode}")


def _run_child(cfg_path: str) -> int:
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from automodel_tpu.config.loader import load_config
    from automodel_tpu.recipes.llm.train_ft import (
        TrainFinetuneRecipeForNextTokenPrediction,
    )

    cfg = load_config(cfg_path)
    recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
    recipe.run_train_validation_loop()
    return 0


def _rows(root: str, name: str) -> list[dict]:
    with open(os.path.join(root, name, "out", "training.jsonl")) as f:
        return [json.loads(line) for line in f]


def main(workdir: str | None = None) -> int:
    owns_workdir = workdir is None
    root = workdir or tempfile.mkdtemp(prefix="elastic_smoke_")
    try:
        print(f"[elastic_smoke] workdir {root}")

        print("[elastic_smoke] 1/3 uninterrupted baseline on 8 devices ...")
        _run_phase(_write_cfg(root, "base", dp_shard=8, ckpt_dir=None,
                              max_steps=MAX_STEPS), devices=8)
        base_losses = {r["step"]: r["loss"] for r in _rows(root, "base") if "loss" in r}

        ckpt_dir = os.path.join(root, "shared_ckpt")
        print(f"[elastic_smoke] 2/3 phase A: dp_shard=8, checkpoint every "
              f"{CKPT_EVERY}, stop at step {SWITCH_STEP} ...")
        _run_phase(_write_cfg(root, "phase_a", dp_shard=8, ckpt_dir=ckpt_dir,
                              max_steps=SWITCH_STEP), devices=8)

        print("[elastic_smoke] 3/3 phase B: resume on 4 devices, dp_shard=4 ...")
        _run_phase(_write_cfg(root, "phase_b", dp_shard=4, ckpt_dir=ckpt_dir,
                              max_steps=MAX_STEPS), devices=4)
        rows = _rows(root, "phase_b")

        events = [r.get("resilience/event") for r in rows if "resilience/event" in r]
        assert "elastic_restore" in events, f"no elastic_restore event; saw {events}"
        restore = next(r for r in rows
                       if r.get("resilience/event") == "elastic_restore")
        assert "dp_shard 8->4" in restore["resilience/delta"], restore

        repart = next((r for r in rows
                       if r.get("event") == "elastic_data_repartition"), None)
        assert repart is not None, "dataloader state was not re-partitioned"
        # single-process smoke: the global batch size is process-count-bound,
        # so the reshape must be example-exact — nothing re-fed
        assert "refed_examples" not in repart, repart
        assert repart["new_cursor"] * repart["new_batch_size"] == \
            repart["consumed_examples"], repart

        losses = {r["step"]: r["loss"] for r in rows if "loss" in r}
        assert min(losses) == SWITCH_STEP + 1, (
            f"phase B first step {min(losses)}, expected {SWITCH_STEP + 1}"
        )
        bad = {s: v for s, v in losses.items() if v != v}
        assert not bad, f"non-finite losses after elastic resume: {bad}"
        drift = abs(losses[MAX_STEPS] - base_losses[MAX_STEPS])
        assert drift < LOSS_TOL, (
            f"final loss {losses[MAX_STEPS]:.3f} drifted {drift:.3f} from "
            f"baseline {base_losses[MAX_STEPS]:.3f}: the trajectory restarted "
            "instead of continuing"
        )
        print(f"[elastic_smoke]     resumed {SWITCH_STEP}->{min(losses)}, "
              f"delta '{restore['resilience/delta']}', final loss "
              f"{losses[MAX_STEPS]:.3f} (baseline {base_losses[MAX_STEPS]:.3f})")

        # --- warm restart: two identical fresh runs sharing a persistent XLA
        # cache; the second must deserialize every compile (the other half of
        # "instant warm restart" — the elastic half is asserted above)
        cache_dir = os.path.join(root, "xla_cache")
        print("[elastic_smoke] 4/4 warm restart: cold run then warm run "
              "sharing a persistent compile cache ...")
        _run_phase(_write_cfg(root, "cold", dp_shard=8, ckpt_dir=None,
                              max_steps=4, cache_dir=cache_dir), devices=8)
        _run_phase(_write_cfg(root, "warm", dp_shard=8, ckpt_dir=None,
                              max_steps=4, cache_dir=cache_dir), devices=8)
        cold = next(r for r in _rows(root, "cold")
                    if r.get("event") == "compile_summary")
        warm = next(r for r in _rows(root, "warm")
                    if r.get("event") == "compile_summary")
        assert cold["compile_cache_misses"] > 0, cold  # cache was actually live
        assert warm["compile_cache_misses"] == 0, (
            f"warm restart recompiled: {warm}"
        )
        assert warm["compile_cache_hits"] > 0, warm
        # and nothing fell off the AOT path mid-run
        assert warm["compile_aot_demoted"] == 0, warm
        assert warm["compile_jit_fallback"] == 0, warm
        print(f"[elastic_smoke]     warm run: {warm['compile_cache_hits']} "
              "cache hits, 0 misses, 0 demotions")
        print("[elastic_smoke] PASS")
        return 0
    finally:
        if owns_workdir:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None,
                        help="keep artifacts here instead of a temp dir")
    parser.add_argument("--run", default=None, help=argparse.SUPPRESS)
    args = parser.parse_args()
    if args.run:
        sys.exit(_run_child(args.run))
    sys.exit(main(args.workdir))
