"""Mock VLM dataset: the answer token is determined by image brightness, so a
working vision path is *required* to fit it (text-only models plateau)."""

from __future__ import annotations

import numpy as np

__all__ = ["MockVLMDataset"]


class MockVLMDataset:
    def __init__(self, num_samples: int = 128, image_hw: int = 28, num_classes: int = 4,
                 vocab_size: int = 128, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.rows = []
        for _ in range(num_samples):
            cls = int(rng.integers(0, num_classes))
            # brightness encodes the class; noise keeps it non-trivial
            base = (cls + 0.5) / num_classes
            img = np.clip(
                base + rng.normal(0, 0.05, size=(image_hw, image_hw, 3)), 0, 1
            ).astype(np.float32)
            self.rows.append(
                {
                    "prompt": "what class",
                    "answer": f"class{cls}",
                    "image": img,
                    "label": cls,
                }
            )

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, i: int):
        return self.rows[i]
