"""Kimi-VL / MoonViT: bicubic pos-emb taps vs torch F.interpolate, 2D rope math,
native-resolution packing, composition self-consistency, adapter round-trip.
(No HF kimi_vl in this transformers version; the reference kimivl/model.py is the
spec — the numerically risky pieces are pinned against torch ops directly.)"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.kimivl.model import KimiVLConfig, KimiVLForConditionalGeneration
from automodel_tpu.models.vision.moonvit import (
    MoonViTConfig,
    _cubic_taps,
    prepare_moonvit_inputs,
)

torch = pytest.importorskip("torch")


def _fp32_backend():
    return BackendConfig(dtype="float32", remat_policy="full")


def _hf_cfg(**kw):
    base = dict(
        architectures=["KimiVLForConditionalGeneration"],
        media_placeholder_token_id=120,
        text_config=dict(
            vocab_size=128, hidden_size=64, intermediate_size=96, moe_intermediate_size=32,
            num_hidden_layers=2, num_attention_heads=4, q_lora_rank=None, kv_lora_rank=32,
            qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
            n_routed_experts=8, num_experts_per_tok=2, n_shared_experts=1,
            n_group=2, topk_group=1, routed_scaling_factor=2.5, norm_topk_prob=True,
            first_k_dense_replace=1, max_position_embeddings=128,
            scoring_func="sigmoid", topk_method="noaux_tc",
        ),
        vision_config=dict(
            patch_size=4, init_pos_emb_height=8, init_pos_emb_width=8,
            num_attention_heads=4, num_hidden_layers=2, hidden_size=32,
            intermediate_size=48, merge_kernel_size=[2, 2],
        ),
    )
    base.update(kw)
    return base


class TestBicubicTaps:
    @pytest.mark.parametrize("dst,src", [(8, 8), (6, 8), (12, 8), (3, 8)])
    def test_matches_torch_interpolate(self, dst, src):
        rng = np.random.RandomState(0)
        table = rng.randn(src, src, 5).astype(np.float32)
        ref = (
            torch.nn.functional.interpolate(
                torch.tensor(table).permute(2, 0, 1).unsqueeze(0),
                size=(dst, dst), mode="bicubic",
            )
            .squeeze(0).permute(1, 2, 0).numpy()
        )
        iy, wy = _cubic_taps(dst, src)
        ix, wx = _cubic_taps(dst, src)
        flat = table.reshape(-1, 5)
        idx = (iy[:, None, :, None] * src + ix[None, :, None, :]).reshape(dst * dst, 16)
        wts = (wy[:, None, :, None] * wx[None, :, None, :]).reshape(dst * dst, 16)
        ours = (flat[idx] * wts[..., None]).sum(1).reshape(dst, dst, 5)
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_identity_at_native_size(self):
        idx, wts = _cubic_taps(8, 8)
        # weights collapse onto the center tap
        np.testing.assert_allclose(wts[:, 1], np.ones(8), atol=1e-12)
        np.testing.assert_array_equal(idx[np.arange(8), 1], np.arange(8))


class TestMoonViTRope:
    def test_angles_match_reference_polar_math(self):
        """Reference Rope2DPosEmb: freqs over arange(0,dh,4)/dh; per position
        interleaved (x_cis, y_cis) complex pairs (kimivl/model.py:189-217)."""
        cfg = MoonViTConfig(patch_size=4, num_attention_heads=2, hidden_size=16,
                            num_hidden_layers=1, intermediate_size=16)
        dh = cfg.head_dim  # 8
        vin = prepare_moonvit_inputs(np.array([[2, 4]]), cfg)
        ang = vin["rope_angles"]  # (8, dh/2=4)
        freqs = 1.0 / (10000.0 ** (np.arange(0, dh, 4)[: dh // 4] / dh))
        # token at (y=1, x=2) is row-major index 1*4+2=6
        expect = np.stack([2 * freqs, 1 * freqs], axis=-1).reshape(-1)
        np.testing.assert_allclose(ang[6], expect, rtol=1e-6)

    def test_merge_scatter_groups_2x2(self):
        cfg = MoonViTConfig(patch_size=4, num_attention_heads=2, hidden_size=16,
                            num_hidden_layers=1, intermediate_size=16)
        vin = prepare_moonvit_inputs(np.array([[4, 4]]), cfg)
        # first merge unit = row-major positions (0,0),(0,1),(1,0),(1,1) = 0,1,4,5
        np.testing.assert_array_equal(vin["out_idx"][[0, 1, 4, 5]], [0, 1, 2, 3])
        np.testing.assert_allclose(vin["out_w"], np.ones(16))

    def test_temporal_mean_pooling(self):
        """t=2 frames mean-pool into the same merged slots with weight 1/2, and the
        fixed sincos time embedding distinguishes frames."""
        cfg = MoonViTConfig(patch_size=4, num_attention_heads=2, hidden_size=16,
                            num_hidden_layers=1, intermediate_size=16, pos_emb_time=4)
        vin = prepare_moonvit_inputs(np.array([[2, 2, 2]]), cfg)
        assert vin["out_idx"].shape == (8,)
        np.testing.assert_array_equal(vin["out_idx"][:4], vin["out_idx"][4:])
        np.testing.assert_allclose(vin["out_w"], np.full(8, 0.5))
        assert int(vin["out_idx"].max()) + 1 == 4
        # frame 0 gets time_table[0]=[sin(0)|cos(0)] = [0..0, 1..1]; frame 1 differs
        assert np.abs(vin["time_emb"][:4] - vin["time_emb"][4:]).max() > 0.1
        np.testing.assert_allclose(vin["time_emb"][0, 8:], np.ones(8), atol=1e-6)
        # rope repeats spatially across frames
        np.testing.assert_allclose(vin["rope_angles"][:4], vin["rope_angles"][4:])


class TestKimiVL:
    def _batch(self, model, rng, grids, seq=24):
        cfg = model.config
        tot_patches = sum(h * w for h, w in grids)
        tot_merged = sum((h // 2) * (w // 2) for h, w in grids)
        ids = rng.randint(0, 100, (1, seq))
        ids[0, 2 : 2 + tot_merged] = cfg.media_placeholder_token_id
        pixels = rng.randn(tot_patches, cfg.vision.patch_dim).astype(np.float32)
        grid = np.array(grids)
        vin = {k: jnp.asarray(v) for k, v in model.prepare_vision_inputs(grid).items()}
        coords = tuple(jnp.asarray(c) for c in model.media_token_coords(ids))
        return jnp.asarray(ids), jnp.asarray(pixels), vin, coords

    def test_forward_finite(self):
        model = KimiVLForConditionalGeneration.from_config(_hf_cfg(), _fp32_backend())
        params = model.init(jax.random.key(0), jnp.float32)
        rng = np.random.RandomState(0)
        ids, pixels, vin, coords = self._batch(model, rng, [(4, 4), (2, 6)])
        logits, stats = model(params, ids, pixel_values=pixels, vision_inputs=vin,
                              media_coords=coords, training=False)
        assert logits.shape == (1, 24, 128)
        assert np.all(np.isfinite(np.asarray(logits)))

    def test_images_are_isolated_by_segments(self):
        """Perturbing image 2's pixels must not change image 1's merged features'
        effect: check logits at positions before image-2 tokens stay put."""
        model = KimiVLForConditionalGeneration.from_config(_hf_cfg(), _fp32_backend())
        params = model.init(jax.random.key(1), jnp.float32)
        rng = np.random.RandomState(1)
        ids, pixels, vin, coords = self._batch(model, rng, [(4, 4), (4, 4)])
        out1, _ = model(params, ids, pixel_values=pixels, vision_inputs=vin,
                        media_coords=coords, training=False)
        pixels2 = pixels.at[16:].set(pixels[16:] + 1.0)  # image 2 patches only
        out2, _ = model(params, ids, pixel_values=pixels2, vision_inputs=vin,
                        media_coords=coords, training=False)
        # first image occupies merged slots 2..6; positions 0..5 see only image 1
        np.testing.assert_allclose(np.asarray(out1[0, :6]), np.asarray(out2[0, :6]), atol=1e-5)
        assert np.abs(np.asarray(out1[0, 6:]) - np.asarray(out2[0, 6:])).max() > 1e-6

    def test_text_only_matches_dsv3(self):
        from automodel_tpu.models.deepseek_v3.model import DeepseekV3ForCausalLM

        model = KimiVLForConditionalGeneration.from_config(_hf_cfg(), _fp32_backend())
        params = model.init(jax.random.key(2), jnp.float32)
        ids = jnp.asarray(np.random.RandomState(2).randint(0, 100, (2, 12)))
        a, _ = model(params, ids, training=False)
        text = DeepseekV3ForCausalLM(model.config.text, _fp32_backend())
        text_params = {k: v for k, v in params.items() if k not in ("visual", "projector")}
        b, _ = text(text_params, ids, training=False)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_adapter_roundtrip(self):
        model = KimiVLForConditionalGeneration.from_config(_hf_cfg(), _fp32_backend())
        params = model.init(jax.random.key(3), jnp.float32)
        adapter = model.state_dict_adapter()
        hf = adapter.to_hf(params)
        for k in (
            "language_model.model.embed_tokens.weight",
            "language_model.model.layers.1.mlp.gate.weight",
            "language_model.lm_head.weight",
            "vision_tower.patch_embed.pos_emb.weight",
            "vision_tower.encoder.blocks.0.wqkv.weight",
            "multi_modal_projector.linear_2.bias",
        ):
            assert k in hf, k
        back = adapter.from_hf(hf)
        flat_a, flat_b = jax.tree.leaves(params), jax.tree.leaves(back)
        assert len(flat_a) == len(flat_b)
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_grads_finite(self):
        model = KimiVLForConditionalGeneration.from_config(_hf_cfg(), _fp32_backend())
        params = model.init(jax.random.key(4), jnp.float32)
        rng = np.random.RandomState(4)
        ids, pixels, vin, coords = self._batch(model, rng, [(4, 4)], seq=16)

        def loss_fn(p):
            logits, _ = model(p, ids[:, :-1], pixel_values=pixels, vision_inputs=vin,
                              media_coords=coords, training=True)
            ll = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            return -jnp.take_along_axis(ll, ids[:, 1:, None], -1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        assert all(np.all(np.isfinite(np.asarray(g))) for g in jax.tree.leaves(grads))
        # the learned pos-emb table must receive gradient through the bicubic gather
        assert np.abs(np.asarray(grads["visual"]["pos_emb"])).max() > 0


class TestKimiK25VL:
    def test_video_forward_and_grads(self):
        from automodel_tpu.models.kimi_k25_vl.model import KimiK25VLForConditionalGeneration

        hf = _hf_cfg()
        hf["architectures"] = ["KimiK25VLForConditionalGeneration"]
        hf["vision_config"]["init_pos_emb_time"] = 4
        model = KimiK25VLForConditionalGeneration.from_config(
            hf, BackendConfig(dtype="float32", remat_policy="full")
        )
        assert model.config.vision.pos_emb_time == 4
        params = model.init(jax.random.key(0), jnp.float32)
        rng = np.random.RandomState(0)
        # one 2-frame 4x4 video -> 4 merged tokens (mean over frames)
        grid = np.array([[2, 4, 4]])
        ids = rng.randint(0, 100, (1, 16))
        ids[0, 2:6] = model.config.media_placeholder_token_id
        pixels = jnp.asarray(rng.randn(32, model.config.vision.patch_dim).astype(np.float32))
        vin = {k: jnp.asarray(v) for k, v in model.prepare_vision_inputs(grid).items()}
        coords = tuple(jnp.asarray(c) for c in model.media_token_coords(ids))
        jids = jnp.asarray(ids)
        logits, _ = model(params, jids, pixel_values=pixels, vision_inputs=vin,
                          media_coords=coords, training=False)
        assert np.all(np.isfinite(np.asarray(logits)))

        def loss_fn(p):
            out, _ = model(p, jids, pixel_values=pixels, vision_inputs=vin,
                           media_coords=coords, training=True)
            return (out.astype(jnp.float32) ** 2).mean()

        grads = jax.grad(loss_fn)(params)
        assert np.abs(np.asarray(grads["visual"]["pos_emb"])).max() > 0
