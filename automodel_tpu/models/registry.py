"""HF architecture-name -> model family registry (reference _transformers/registry.py:33).

The reference scans components/models/*/model.py for classes; here registration is
explicit and lazy (import strings) so importing the registry stays cheap.
"""

from __future__ import annotations

import importlib

__all__ = ["MODEL_REGISTRY", "resolve_model_class", "register_model"]

# architecture name (HF config.json "architectures"[0]) -> "module:Class"
MODEL_REGISTRY: dict[str, str] = {
    "LlamaForCausalLM": "automodel_tpu.models.llama.model:LlamaForCausalLM",
    "Qwen2ForCausalLM": "automodel_tpu.models.llama.model:LlamaForCausalLM",
    "Qwen3ForCausalLM": "automodel_tpu.models.llama.model:LlamaForCausalLM",
    "MistralForCausalLM": "automodel_tpu.models.llama.model:LlamaForCausalLM",
    # Granite = llama + four mup-style static scalars, read straight from config
    # (embedding/residual/attention multipliers + logits_scaling)
    "GraniteForCausalLM": "automodel_tpu.models.llama.model:LlamaForCausalLM",
    # SmolLM3 = llama + per-layer NoPE (no_rope_layers via layer_flags bit 1)
    "SmolLM3ForCausalLM": "automodel_tpu.models.llama.model:LlamaForCausalLM",
    # Olmo2/3 = llama + post-sublayer norms + whole-projection qk-RMSNorm
    # (norm_placement="post", qk_norm_whole; Olmo3 adds per-layer sliding via
    # layer_types, which the lineage already carries)
    "Olmo2ForCausalLM": "automodel_tpu.models.llama.model:LlamaForCausalLM",
    "Olmo3ForCausalLM": "automodel_tpu.models.llama.model:LlamaForCausalLM",
    # Cohere (Command R) = llama + mean-centered LN + parallel attn||mlp block
    # + interleaved rope + multiplicative logit_scale (+ per-head qk-LN on R+)
    "CohereForCausalLM": "automodel_tpu.models.llama.model:LlamaForCausalLM",
    # Cohere2 (Command R7B) adds the 3:1 sliding pattern with rope ONLY on
    # sliding layers (NoPE full-attention layers via no_rope_layers)
    "Cohere2ForCausalLM": "automodel_tpu.models.llama.model:LlamaForCausalLM",
    # Arcee (AFM) = llama + ungated relu^2 MLP
    "ArceeForCausalLM": "automodel_tpu.models.llama.model:LlamaForCausalLM",
    # GLM-4 dense = llama + sandwich norms + interleaved partial rope + fused
    # gate_up checkpoints (split by its adapter); old GLM (glm-4-9b-chat-hf) is
    # the same minus the sandwich norms and rides the same adapter
    "Glm4ForCausalLM": "automodel_tpu.models.glm4.model:Glm4ForCausalLM",
    "GlmForCausalLM": "automodel_tpu.models.glm4.model:Glm4ForCausalLM",
    "MixtralForCausalLM": "automodel_tpu.models.mixtral.model:MixtralForCausalLM",
    # Phi-3 lineage is llama-shaped with fused checkpoint tensors + longrope
    "Phi3ForCausalLM": "automodel_tpu.models.phi3.model:Phi3ForCausalLM",
    "Gemma2ForCausalLM": "automodel_tpu.models.gemma.model:GemmaForCausalLM",
    "Gemma3ForCausalLM": "automodel_tpu.models.gemma.model:GemmaForCausalLM",
    "Gemma3ForConditionalGeneration": "automodel_tpu.models.gemma.model:GemmaForCausalLM",
    "Ministral3ForCausalLM": "automodel_tpu.models.mistral3.model:Ministral3ForCausalLM",
    "Qwen3MoeForCausalLM": "automodel_tpu.models.qwen3_moe.model:Qwen3MoeForCausalLM",
    "GptOssForCausalLM": "automodel_tpu.models.gpt_oss.model:GptOssForCausalLM",
    "DeepseekV3ForCausalLM": "automodel_tpu.models.deepseek_v3.model:DeepseekV3ForCausalLM",
    "DeepseekV2ForCausalLM": "automodel_tpu.models.deepseek_v3.model:DeepseekV3ForCausalLM",
    "DeepseekV32ForCausalLM": "automodel_tpu.models.deepseek_v32.model:DeepseekV32ForCausalLM",
    # Kimi-K2 ships DeepseekV3 architecture in its config.json (reference kimi support)
    "KimiK2ForCausalLM": "automodel_tpu.models.deepseek_v3.model:DeepseekV3ForCausalLM",
    # GLM4-MoE-Lite is MLA attention + GLM gating — same param/weight surface as DSv3
    "Glm4MoeLiteForCausalLM": "automodel_tpu.models.deepseek_v3.model:DeepseekV3ForCausalLM",
    "Glm4MoeForCausalLM": "automodel_tpu.models.glm4_moe.model:Glm4MoeForCausalLM",
    "MiniMaxM2ForCausalLM": "automodel_tpu.models.minimax_m2.model:MiniMaxM2ForCausalLM",
    "Qwen3NextForCausalLM": "automodel_tpu.models.qwen3_next.model:Qwen3NextForCausalLM",
    "Qwen3_5MoeForConditionalGeneration": "automodel_tpu.models.qwen3_5_moe.model:Qwen3_5MoeForCausalLM",
    "Qwen3_5MoeForCausalLM": "automodel_tpu.models.qwen3_5_moe.model:Qwen3_5MoeForCausalLM",
    "GPT2LMHeadModel": "automodel_tpu.models.gpt2.model:GPT2LMHeadModel",
    "NemotronHForCausalLM": "automodel_tpu.models.nemotron_v3.model:NemotronHForCausalLM",
    "Step3p5ForCausalLM": "automodel_tpu.models.step3p5.model:Step3p5ForCausalLM",
    "NemotronV3ForCausalLM": "automodel_tpu.models.nemotron_v3.model:NemotronHForCausalLM",
    "LlavaForConditionalGeneration": "automodel_tpu.models.llava.model:LlavaForConditionalGeneration",
    "Qwen3VLMoeForConditionalGeneration": "automodel_tpu.models.qwen3_vl_moe.model:Qwen3VLMoeForConditionalGeneration",
    "KimiVLForConditionalGeneration": "automodel_tpu.models.kimivl.model:KimiVLForConditionalGeneration",
    "KimiK25VLForConditionalGeneration": "automodel_tpu.models.kimi_k25_vl.model:KimiK25VLForConditionalGeneration",
    "NemotronParseForConditionalGeneration": "automodel_tpu.models.nemotron_parse.model:NemotronParseForConditionalGeneration",
    "Qwen3OmniMoeThinkerForConditionalGeneration": "automodel_tpu.models.qwen3_omni_moe.model:Qwen3OmniMoeThinkerForConditionalGeneration",
    "Qwen3OmniMoeForConditionalGeneration": "automodel_tpu.models.qwen3_omni_moe.model:Qwen3OmniMoeThinkerForConditionalGeneration",
    "LlamaBidirectionalModel": "automodel_tpu.models.llama_bidirectional.model:LlamaBidirectionalModel",
}


def register_model(architecture: str, target: str) -> None:
    MODEL_REGISTRY[architecture] = target


def resolve_model_class(architecture: str):
    target = MODEL_REGISTRY.get(architecture)
    if target is None:
        import difflib

        near = difflib.get_close_matches(architecture, MODEL_REGISTRY, n=3, cutoff=0.5)
        hint = (
            f" Closest supported: {near} — if the architecture is a config-level "
            "variant of one of these, register an alias with "
            "automodel_tpu.models.registry.register_model(arch, target)."
            if near
            else ""
        )
        raise KeyError(
            f"architecture {architecture!r} is not supported; known: "
            f"{sorted(MODEL_REGISTRY)}.{hint}"
        )
    mod_name, cls_name = target.split(":")
    return getattr(importlib.import_module(mod_name), cls_name)
