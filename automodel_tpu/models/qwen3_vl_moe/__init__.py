from automodel_tpu.models.qwen3_vl_moe.model import (
    Qwen3VLMoeConfig,
    Qwen3VLMoeForConditionalGeneration,
)

__all__ = ["Qwen3VLMoeConfig", "Qwen3VLMoeForConditionalGeneration"]
