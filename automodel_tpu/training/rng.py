"""Deterministic RNG threading (reference components/training/rng.py:83,115).

The torch ``StatefulRNG`` (capturing python/numpy/torch/cuda states) collapses to
``jax.random.key`` + ``fold_in``: determinism is structural, not captured state. The
stateful wrapper below exists so recipes can checkpoint/restore the stream position and
scope named substreams exactly like the reference's ``ScopedRNG``.
"""

from __future__ import annotations

import random
from typing import Any, Iterator
from contextlib import contextmanager

import jax
import numpy as np

__all__ = ["StatefulRNG", "ScopedRNG"]


def _hash_name(name: str) -> int:
    # Stable across processes (python hash() is salted); fold scope names into keys.
    h = 2166136261
    for b in name.encode():
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


class StatefulRNG:
    """A named, checkpointable PRNG stream.

    ``key(name)`` derives a per-call subkey: fold_in(seed_key, hash(name), counter).
    Also seeds python/numpy so host-side shuffles (dataloaders) are deterministic,
    matching the reference's intent of seeding every build phase (train_ft.py:171,439).
    """

    def __init__(self, seed: int = 42, ranked: bool = False):
        self.seed = int(seed)
        self.ranked = bool(ranked)
        offset = jax.process_index() if ranked else 0
        self._base = jax.random.key(self.seed + offset)
        self._counters: dict[str, int] = {}
        random.seed(self.seed + offset)
        np.random.seed((self.seed + offset) % (2**32))

    def key(self, name: str = "default") -> jax.Array:
        """Next subkey in the named stream; advances the stream counter."""
        count = self._counters.get(name, 0)
        self._counters[name] = count + 1
        return jax.random.fold_in(jax.random.fold_in(self._base, _hash_name(name)), count)

    def peek(self, name: str = "default") -> jax.Array:
        count = self._counters.get(name, 0)
        return jax.random.fold_in(jax.random.fold_in(self._base, _hash_name(name)), count)

    # -- checkpointable state (JSON-safe so client.json can hold it) --------
    def state_dict(self) -> dict[str, Any]:
        pr = random.getstate()
        ns = np.random.get_state()
        return {
            "seed": self.seed,
            "ranked": self.ranked,
            "counters": dict(self._counters),
            "python_random": [pr[0], list(pr[1]), pr[2]],
            "numpy_random": [ns[0], np.asarray(ns[1]).tolist(), int(ns[2]), int(ns[3]), float(ns[4])],
        }

    def load_state_dict(self, state: dict[str, Any]) -> None:
        self.seed = state["seed"]
        self.ranked = state["ranked"]
        offset = jax.process_index() if self.ranked else 0
        self._base = jax.random.key(self.seed + offset)
        self._counters = dict(state["counters"])
        pr = state.get("python_random")
        if pr is not None:
            random.setstate(_to_random_state(pr))
        nr = state.get("numpy_random")
        if nr is not None:
            np.random.set_state(_to_numpy_state(nr))


def _to_random_state(state: Any) -> Any:
    # random.getstate() is (version, tuple_of_ints, gauss_next); orbax/json round-trips
    # may turn tuples into lists.
    if isinstance(state, (list, tuple)):
        v, ints, g = state
        return (v, tuple(int(i) for i in ints), g)
    return state


def _to_numpy_state(state: Any) -> Any:
    if isinstance(state, (list, tuple)) and len(state) == 5:
        name, keys, pos, has_gauss, cached = state
        return (name, np.asarray(keys, dtype=np.uint32), int(pos), int(has_gauss), float(cached))
    return state


class ScopedRNG:
    """Context manager giving a scope-local stream (reference rng.py:115).

    >>> rng = StatefulRNG(seed=0)
    >>> with ScopedRNG(rng, "model_init") as r:
    ...     k = r.key()
    """

    def __init__(self, rng: StatefulRNG, scope: str):
        self.rng = rng
        self.scope = scope

    def key(self, name: str = "default") -> jax.Array:
        return self.rng.key(f"{self.scope}/{name}")

    def __enter__(self) -> "ScopedRNG":
        return self

    def __exit__(self, *exc: Any) -> None:
        return None


@contextmanager
def scoped_rng(rng: StatefulRNG, scope: str) -> Iterator[ScopedRNG]:
    yield ScopedRNG(rng, scope)
