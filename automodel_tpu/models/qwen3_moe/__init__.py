from automodel_tpu.models.qwen3_moe.model import Qwen3MoeConfig, Qwen3MoeForCausalLM

__all__ = ["Qwen3MoeConfig", "Qwen3MoeForCausalLM"]
