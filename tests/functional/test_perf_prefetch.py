"""Overlapped-input-pipeline perf smoke (CPU backend, ``pytest -m perf``).

The mock dataset's ``item_delay_s`` stands in for real host-side input cost
(tokenize/augment/pack). Synchronously that cost lands in the ``data_wait``
goodput bucket every step; with the prefetch pipeline the worker thread pays it
while the device computes, so the consumed fraction must drop measurably.
"""

import json
import textwrap

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.perf]

# 8 items/step x 10ms = ~80ms of host input cost per step, against a
# sub-10ms device step: synchronously data_wait dominates the loop.
ITEM_DELAY_S = 0.010

PREFETCH = textwrap.dedent("""\
dataloader:
  prefetch:
    enabled: true
    host_depth: 3
    device_depth: 2
""").replace("\n", "\n    ")


def _write_cfg(tmp_path, extra=""):
    cfg = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 128
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    distributed:
      dp_shard: 4
      tp: 2
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 256
      seed: 0
      pattern: arith
      item_delay_s: {ITEM_DELAY_S}
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: 1
      max_steps: 10
      num_epochs: 10
      handle_sigterm: false
    optimizer:
      lr: 1.0e-2
    checkpoint:
      enabled: false
    {extra}
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg))
    return p


def _final_row(tmp_path):
    rows = [json.loads(line) for line in open(tmp_path / "out" / "training.jsonl")]
    rows = [r for r in rows if "goodput/data_wait" in r]
    assert rows, "no goodput rows logged"
    return rows[-1]


def _run(tmp_path, extra=""):
    from automodel_tpu.config.loader import load_config
    from automodel_tpu.recipes.llm.train_ft import (
        TrainFinetuneRecipeForNextTokenPrediction,
    )

    cfg = load_config(_write_cfg(tmp_path, extra=extra))
    TrainFinetuneRecipeForNextTokenPrediction(cfg).setup().run_train_validation_loop()
    return _final_row(tmp_path)


def test_prefetch_hides_host_input_cost(tmp_path, cpu_devices):
    sync_dir = tmp_path / "sync"
    sync_dir.mkdir()
    sync = _run(sync_dir)

    pf_dir = tmp_path / "prefetch"
    pf_dir.mkdir()
    pf = _run(pf_dir, extra=PREFETCH)

    # both runs completed the same schedule
    assert pf["step"] == sync["step"] == 10

    sync_wait = sync["goodput/data_wait"]
    pf_wait = pf["goodput/data_wait"]
    # the injected delay must actually register synchronously — otherwise the
    # comparison below is vacuous
    assert sync_wait > 0.03, f"sync data_wait fraction suspiciously low: {sync_wait}"
    # overlapping strictly reduces consumed data_wait: the worker pays the
    # per-item cost during device compute, and fills the queue during compile
    assert pf_wait < sync_wait, (pf_wait, sync_wait)
    assert sync_wait - pf_wait > 0.02, (
        f"prefetch did not measurably reduce data_wait: {sync_wait} -> {pf_wait}"
    )
    # the goodput (device_step share) must not regress with the pipeline on
    assert pf["goodput"] >= sync["goodput"]
