"""Ring attention over the ``cp`` mesh axis — long-context context parallelism.

TPU-native replacement for the reference's two CP mechanisms (SURVEY.md §5): torch
DTensor experimental ``context_parallel`` ring SDPA (distributed/cp_utils.py:68) and
TransformerEngine p2p ring attention (moe/parallelizer.py:267-285). Here: q/k/v arrive
sequence-sharded over ``cp``; k/v (+ their positions/segment ids) rotate around the
ring via ``lax.ppermute`` while each shard accumulates online-softmax partials in
fp32. ppermute rides ICI neighbor links, and XLA overlaps the permute with the
current chunk's attention math.

Causality is enforced by *global* positions (each shard's token positions travel with
it), so any seq-dim layout works — including the load-balanced interleave the
reference gets from THD round-robin sharding (cp_utils.py:296-321). Differentiable
end-to-end (ppermute has a transpose rule), so no custom VJP is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention_local", "make_ring_attention"]

NEG_INF = -1e30


def _partial_attention(q, k, v, allowed, scale):
    """Unnormalized blockwise attention; returns (acc, m, l) in fp32.

    q/k (B, S, N|K, D); v (B, Sk, K, Dv) — Dv may differ from D (MLA's v_head_dim,
    moe/parallelizer.py:267-285 runs ring CP through TE for MLA the same way);
    allowed (B, Sq, Sk) bool or None. acc (B, K, G, Sq, Dv), m/l (B, K, G, Sq).
    """
    b, sq, n, d = q.shape
    kh = k.shape[2]
    g = n // kh
    qf = q.astype(jnp.float32).reshape(b, sq, kh, g, d)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qf, k.astype(jnp.float32)) * scale
    if allowed is not None:
        logits = jnp.where(allowed[:, None, None], logits, NEG_INF)
    m = logits.max(-1)  # (b, kh, g, sq)
    p = jnp.exp(logits - m[..., None])
    if allowed is not None:
        # fully-masked rows would otherwise contribute exp(0)=1 per masked entry
        p = jnp.where(allowed[:, None, None], p, 0.0)
    l = p.sum(-1)
    acc = jnp.einsum("bkgqs,bskd->bkgqd", p, v.astype(jnp.float32))
    return acc, m, l


def ring_attention_local(
    q: jnp.ndarray,  # (B, Sq_local, N, D)
    k: jnp.ndarray,  # (B, Skv_local, K, D)
    v: jnp.ndarray,
    positions_q: jnp.ndarray,  # (B, Sq_local) global positions
    positions_kv: jnp.ndarray,  # (B, Skv_local)
    segment_ids_q: jnp.ndarray | None = None,  # (B, Sq_local)
    segment_ids_kv: jnp.ndarray | None = None,
    *,
    axis: str = "cp",
    causal: bool = True,
    sliding_window: int | None = None,
    softmax_scale: float | None = None,
) -> jnp.ndarray:
    """The per-shard body — call inside shard_map manual over ``axis``."""
    cp = jax.lax.axis_size(axis)
    b, sq, n, d = q.shape
    dv = v.shape[-1]
    kh = k.shape[2]
    g = n // kh
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    perm = [(j, (j + 1) % cp) for j in range(cp)]

    acc = jnp.zeros((b, kh, g, sq, dv), jnp.float32)
    m = jnp.full((b, kh, g, sq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, kh, g, sq), jnp.float32)
    kv = (k, v, positions_kv, segment_ids_kv)

    for step in range(cp):
        k_i, v_i, pos_kv, seg_kv = kv
        allowed = None

        def _and(a, b):
            return b if a is None else jnp.logical_and(a, b)

        if causal:
            allowed = _and(allowed, positions_q[:, :, None] >= pos_kv[:, None, :])
        if sliding_window is not None:
            allowed = _and(
                allowed, positions_q[:, :, None] - pos_kv[:, None, :] < sliding_window
            )
        if segment_ids_q is not None:
            allowed = _and(
                allowed, segment_ids_q[:, :, None] == seg_kv[:, None, :]
            )

        acc_i, m_i, l_i = _partial_attention(q, k_i, v_i, allowed, scale)
        m_new = jnp.maximum(m, m_i)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_i - m_new)
        acc = acc * alpha[..., None] + acc_i * beta[..., None]
        l = l * alpha + l_i * beta
        m = m_new

        if step < cp - 1:
            kv = jax.tree.map(
                lambda x: jax.lax.ppermute(x, axis, perm) if x is not None else None,
                kv, is_leaf=lambda x: x is None,
            )

    out = acc / jnp.where(l == 0.0, 1.0, l)[..., None]  # (b, kh, g, sq, dv)
    return out.transpose(0, 3, 1, 2, 4).reshape(b, sq, n, dv).astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    *,
    cp_axis: str = "cp",
    causal: bool = True,
    sliding_window: int | None = None,
    softmax_scale: float | None = None,
):
    """Wrap :func:`ring_attention_local` in a partial-manual shard_map over ``cp``.

    Inputs are global arrays with the seq dim sharded over ``cp`` (other axes stay
    GSPMD-managed). Returns ``fn(q, k, v, positions, segment_ids=None) -> out``.
    """

    def fn(q, k, v, positions, segment_ids=None):
        seq_spec = P(None, cp_axis)

        def body(q, k, v, positions, segment_ids):
            return ring_attention_local(
                q, k, v, positions, positions,
                segment_ids, segment_ids,
                axis=cp_axis, causal=causal,
                sliding_window=sliding_window, softmax_scale=softmax_scale,
            )

        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(
                P(None, cp_axis, None, None),
                P(None, cp_axis, None, None),
                P(None, cp_axis, None, None),
                seq_spec,
                None if segment_ids is None else seq_spec,
            ),
            out_specs=P(None, cp_axis, None, None),
            axis_names={cp_axis},
        )(q, k, v, positions, segment_ids)

    return fn
