from automodel_tpu.data.llm.megatron.blended import BlendedDataset, normalize_weights, parse_blend
from automodel_tpu.data.llm.megatron.gpt_dataset import GPTDataset
from automodel_tpu.data.llm.megatron.indexed_dataset import (
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
)
from automodel_tpu.data.llm.megatron.megatron_dataset import MegatronPretraining

__all__ = [
    "BlendedDataset",
    "GPTDataset",
    "MMapIndexedDataset",
    "MMapIndexedDatasetBuilder",
    "MegatronPretraining",
    "normalize_weights",
    "parse_blend",
]
