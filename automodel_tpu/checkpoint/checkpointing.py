"""Distributed checkpointing (reference components/checkpoint/checkpointing.py:100,142).

Orbax replaces torch DCP: sharded jax arrays save/restore in parallel across hosts with
no gloo side-channels, and restore reads directly into the target sharding (the
reference's shard-then-load rules collapse into Orbax restore_args). The reference's
dual-format guarantee is kept: every model checkpoint can also be consolidated to
HF-layout safetensors so any step is ``transformers``-loadable (SURVEY.md §3.4).

Layout per save (mirrors the reference's epoch/step dirs + ``latest`` symlink,
base_recipe.py:241,383):

    <root>/step_{N}/model/        orbax pytree (sharded)
    <root>/step_{N}/optim/        orbax pytree (sharded)
    <root>/step_{N}/client.json   rng/step-scheduler/dataloader state_dicts
    <root>/step_{N}/hf/           consolidated safetensors (optional)
    <root>/latest -> step_{N}
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
from typing import Any, Callable, Mapping

import jax
import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["CheckpointingConfig", "Checkpointer"]


@dataclasses.dataclass
class CheckpointingConfig:
    enabled: bool = True
    checkpoint_dir: str = "checkpoints"
    save_consolidated: bool = False  # also write HF safetensors per save
    keep_last_k: int | None = None  # prune old step dirs
    async_save: bool = False


class Checkpointer:
    """Save/restore model params, optimizer state, and client (host) states."""

    def __init__(self, config: CheckpointingConfig, state_dict_adapter=None, hf_config: dict | None = None):
        self.config = config
        # orbax requires absolute paths; make relative dirs cwd-anchored up front
        self.config.checkpoint_dir = os.path.abspath(config.checkpoint_dir)
        self.state_dict_adapter = state_dict_adapter  # for consolidated HF export
        self.hf_config = hf_config
        self._ckptr = None
        self._pending = None

    # lazily create so importing this module never touches orbax/devices
    @property
    def ckptr(self):
        if self._ckptr is None:
            import orbax.checkpoint as ocp

            if self.config.async_save:
                self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
            else:
                self._ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
        return self._ckptr

    # -- paths --------------------------------------------------------------
    def step_dir(self, step: int) -> str:
        return os.path.join(self.config.checkpoint_dir, f"step_{step}")

    def latest_step(self) -> int | None:
        root = self.config.checkpoint_dir
        link = os.path.join(root, "latest")
        if os.path.islink(link):
            target = os.readlink(link)
            if target.startswith("step_"):
                return int(target.split("_")[1])
        if not os.path.isdir(root):
            return None
        steps = [
            int(d.split("_")[1])
            for d in os.listdir(root)
            if d.startswith("step_") and os.path.isdir(os.path.join(root, d))
            and self._step_complete(os.path.join(root, d))
        ]
        return max(steps) if steps else None

    @staticmethod
    def _step_complete(d: str) -> bool:
        """True when the step's arrays committed. Orbax renames its tmp dir onto
        the final name only at finalize, so a crash between an async ``save``
        and ``wait`` leaves tmp residue and/or no ``model`` tree — such a dir
        must never win the no-symlink fallback (the symlink itself is only
        written post-finalize, checkpointing.wait)."""
        if not os.path.isdir(os.path.join(d, "model")):
            return False
        return not any(".orbax-checkpoint-tmp" in name for name in os.listdir(d))

    # -- save ---------------------------------------------------------------
    def save(
        self,
        step: int,
        params: Any,
        opt_state: Any = None,
        client_states: Mapping[str, Any] | None = None,
        hf_params: Any = None,
    ) -> str:
        """``hf_params`` overrides what the consolidated HF export writes — used by
        PEFT to export merged base+adapter weights while ``params`` stays
        adapter-only (reference checkpoint/addons.py)."""
        if not self.config.enabled:
            return ""
        self.wait()  # finalize any in-flight async save (writes its latest symlink)
        d = self.step_dir(step)
        os.makedirs(d, exist_ok=True)
        self.ckptr.save(os.path.join(d, "model"), params, force=True)
        if opt_state is not None:
            self.ckptr.save(os.path.join(d, "optim"), opt_state, force=True)
        if jax.process_index() == 0 and client_states:
            with open(os.path.join(d, "client.json"), "w") as f:
                json.dump({k: _jsonify(v.state_dict() if hasattr(v, "state_dict") else v)
                           for k, v in client_states.items()}, f)
        if jax.process_index() == 0:
            with open(os.path.join(d, "signature.json"), "w") as f:
                json.dump(_model_signature(params), f)
        if self.config.save_consolidated and self.state_dict_adapter is not None:
            self.save_hf(os.path.join(d, "hf"), params if hf_params is None else hf_params)
        # async: the array write may still be in flight — defer the latest symlink
        # to wait() so a crash mid-write can't leave latest -> incomplete step
        self._pending = step
        if not self.config.async_save:
            self.wait()
        self._prune()
        logger.info("saved checkpoint step=%d -> %s", step, d)
        return d

    def save_hf(self, out_dir: str, params: Any) -> None:
        """Consolidated HF-layout safetensors export (any rank count -> one HF dir).

        STREAMING: the adapter yields lazy per-tensor views (to_hf_lazy), so each
        layer/expert slice is gathered to host, transformed, written, and dropped
        one at a time — peak host memory is one <=5GB shard on the writing rank
        and one tensor elsewhere, never the model (the r2 design pulled the full
        tree to host first, capping exports at one host's RAM; the reference
        ships an 858-LoC consolidation engine for the same reason,
        consolidate_hf_safetensors.py:1). Every process walks the tensors in the
        SAME order because the per-slice gathers are collectives; only rank 0
        writes."""
        from automodel_tpu.checkpoint.safetensors_io import save_safetensors

        lazy = self.state_dict_adapter.to_hf_lazy(params, host_fn=_full_host_array)
        is_writer = jax.process_index() == 0
        save_safetensors(lazy, out_dir, write=is_writer)
        if is_writer and self.hf_config is not None:
            with open(os.path.join(out_dir, "config.json"), "w") as f:
                json.dump(self.hf_config, f, indent=2)

    def wait(self) -> None:
        """Block until an in-flight async save lands, then commit its ``latest``
        symlink (reference maybe_wait_for_staging, train_ft.py:1336)."""
        if self._ckptr is not None and hasattr(self._ckptr, "wait_until_finished"):
            self._ckptr.wait_until_finished()
        if self._pending is not None:
            if jax.process_index() == 0:
                self._update_latest(self._pending)
            self._pending = None

    # -- load ---------------------------------------------------------------
    def load(
        self,
        params_template: Any,
        opt_state_template: Any = None,
        step: int | None = None,
    ) -> tuple[Any, Any, dict[str, Any]]:
        """Restore into the shardings/dtypes of the provided templates."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.config.checkpoint_dir!r}")
        import orbax.checkpoint as ocp

        d = self.step_dir(step)
        # model-signature compat check (reference base_recipe.py:768-846): fail
        # with a diff instead of orbax's opaque tree-mismatch errors when the
        # config changed between save and resume
        sig_path = os.path.join(d, "signature.json")
        if os.path.exists(sig_path):
            with open(sig_path) as f:
                saved = json.load(f)
            current = _model_signature(params_template)
            if saved != current:
                missing = sorted(set(saved) - set(current))[:5]
                added = sorted(set(current) - set(saved))[:5]
                changed = sorted(
                    k for k in set(saved) & set(current) if saved[k] != current[k]
                )[:5]
                raise ValueError(
                    f"checkpoint at {d!r} was saved from a different model signature: "
                    f"missing={missing} added={added} changed={changed} "
                    f"(first 5 each; did the model config change between save and resume?)"
                )

        def _resharded(restored, template):
            # orbax can land scalars/small leaves on a single device; force every
            # leaf back onto the template's sharding so jit sees consistent placement
            def put(r, t):
                if hasattr(t, "sharding"):
                    return jax.device_put(r, t.sharding)
                return r

            return jax.tree.map(put, restored, template)

        params = _resharded(
            self.ckptr.restore(os.path.join(d, "model"), args=ocp.args.StandardRestore(params_template)),
            params_template,
        )
        opt_state = None
        if opt_state_template is not None and os.path.isdir(os.path.join(d, "optim")):
            opt_state = _resharded(
                self.ckptr.restore(os.path.join(d, "optim"), args=ocp.args.StandardRestore(opt_state_template)),
                opt_state_template,
            )
        client: dict[str, Any] = {}
        cj = os.path.join(d, "client.json")
        if os.path.exists(cj):
            with open(cj) as f:
                client = json.load(f)
        return params, opt_state, client

    # -- best tracking -------------------------------------------------------
    def _read_best(self) -> dict | None:
        best_path = os.path.join(self.config.checkpoint_dir, "best.json")
        if not os.path.exists(best_path):
            return None
        try:
            with open(best_path) as f:
                return json.load(f)
        except (ValueError, OSError):
            # a crash mid-write left a truncated file; treat as no record
            logger.warning("unreadable best.json at %s; ignoring", best_path)
            return None

    def is_best(self, val_loss: float) -> bool:
        """Would this validation loss improve on the recorded best? (read-only.
        On multi-host runs decide on process 0 and broadcast — filesystem
        visibility can skew across hosts.)"""
        best = self._read_best()
        return best is None or float(val_loss) < best["val_loss"]

    def mark_best(self, step: int, val_loss: float) -> bool:
        """Record a validation result; when it improves on the best so far,
        persist it and point the ``best`` symlink at the step's directory
        (reference base_recipe.py:383-425 best-checkpoint tracking). Returns
        True when this step became the new best. Call after the step is saved."""
        if not self.config.enabled or not self.is_best(val_loss):
            return False
        if jax.process_index() == 0:
            root = self.config.checkpoint_dir
            os.makedirs(root, exist_ok=True)
            best_path = os.path.join(root, "best.json")
            tmp_json = best_path + ".tmp"
            with open(tmp_json, "w") as f:
                json.dump({"step": step, "val_loss": float(val_loss)}, f)
            os.replace(tmp_json, best_path)
            link = os.path.join(root, "best")
            tmp = link + ".tmp"
            if os.path.islink(tmp) or os.path.exists(tmp):
                os.remove(tmp)
            os.symlink(f"step_{step}", tmp)
            os.replace(tmp, link)
            logger.info("new best checkpoint: step=%d val_loss=%.6f", step, val_loss)
        return True

    def best_step(self) -> int | None:
        best = self._read_best()
        return None if best is None else int(best["step"])

    # -- internals ----------------------------------------------------------
    def _update_latest(self, step: int) -> None:
        link = os.path.join(self.config.checkpoint_dir, "latest")
        tmp = link + ".tmp"
        if os.path.islink(tmp) or os.path.exists(tmp):
            os.remove(tmp)
        os.symlink(f"step_{step}", tmp)
        os.replace(tmp, link)

    def _prune(self) -> None:
        k = self.config.keep_last_k
        if not k or jax.process_index() != 0:
            return
        root = self.config.checkpoint_dir
        steps = sorted(
            int(d.split("_")[1])
            for d in os.listdir(root)
            if d.startswith("step_") and os.path.isdir(os.path.join(root, d))
        )
        best = self.best_step()
        for s in steps[:-k]:
            if s == best:
                continue  # the best checkpoint survives pruning (reference contract)
            shutil.rmtree(self.step_dir(s), ignore_errors=True)


def _model_signature(params: Any) -> dict[str, str]:
    """path -> "shape/dtype" for every param leaf (sharding-independent)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    return {
        jax.tree_util.keystr(path): f"{tuple(leaf.shape)}/{np.dtype(leaf.dtype).name}"
        for path, leaf in flat
    }


def _full_host_array(a: Any) -> np.ndarray:
    """Device/sharded array -> full host array, gathering across hosts if needed."""
    if hasattr(a, "is_fully_addressable") and not a.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(a, tiled=True))
    return np.asarray(a)


def _jsonify(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {k: _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return {"__ndarray__": obj.tolist(), "dtype": str(obj.dtype)}
    if isinstance(obj, (np.integer, np.floating)):
        return obj.item()
    return obj
