"""Llama-lineage HF key/layout mapping (reference models/llama/state_dict_adapter.py).

HF linear weights are (out_features, in_features); our layout is (in, out) — or
(in, heads, head_dim) / (heads, head_dim, out) for attention — so every projection
transposes + reshapes on the way in and back out.
"""

from __future__ import annotations

import numpy as np

from automodel_tpu.models.common.state_dict import Entry, MappingAdapter
from automodel_tpu.models.common.transformer import DenseDecoderConfig

__all__ = ["LlamaStateDictAdapter"]


def _proj_in(heads: int, head_dim: int):
    """HF (heads*head_dim, D) -> ours (D, heads, head_dim)."""

    def f(w: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(w.T).reshape(w.shape[1], heads, head_dim)

    return f


def _proj_out(heads: int, head_dim: int):
    def f(w: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(w.reshape(w.shape[0], heads * head_dim).T)

    return f


def _o_in(heads: int, head_dim: int):
    """HF o_proj (D, heads*head_dim) -> ours (heads, head_dim, D)."""

    def f(w: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(w.T).reshape(heads, head_dim, w.shape[0])

    return f


def _o_out(heads: int, head_dim: int):
    def f(w: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(w.reshape(heads * head_dim, w.shape[2]).T)

    return f


def _bias_in(heads: int, head_dim: int):
    def f(b: np.ndarray) -> np.ndarray:
        return b.reshape(heads, head_dim)

    return f


def _bias_out(heads: int, head_dim: int):
    def f(b: np.ndarray) -> np.ndarray:
        return b.reshape(heads * head_dim)

    return f


def _t(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w.T)


class LlamaStateDictAdapter(MappingAdapter):
    def __init__(self, cfg: DenseDecoderConfig, scan_layers: bool = True):
        n, k, h = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        post = getattr(cfg, "norm_placement", "pre") == "post"
        gated = getattr(cfg, "mlp_gated", True)
        # ungated families may rename the two MLP projections (starcoder2: c_fc/c_proj)
        up_name, down_name = getattr(cfg, "hf_mlp_names", None) or ("up_proj", "down_proj")
        attn_norm_key = ("model.layers.{i}.post_attention_layernorm"
                         if post else "model.layers.{i}.input_layernorm")
        mlp_norm_key = ("model.layers.{i}.post_feedforward_layernorm"
                        if post else "model.layers.{i}.post_attention_layernorm")
        has_mlp_norm = not getattr(cfg, "parallel_block", False)
        entries = [
            Entry("model.embed_tokens.weight", "embed"),
            # olmo-v1 (norm_param=False): LayerNorms carry NO weights at all
            *([Entry("model.norm.weight", "final_norm"),
               Entry(attn_norm_key + ".weight", "layers.attn_norm"),
               *([Entry(mlp_norm_key + ".weight", "layers.mlp_norm")]
                 if has_mlp_norm else [])]
              if getattr(cfg, "norm_param", True) else []),
            Entry("model.layers.{i}.self_attn.q_proj.weight", "layers.wq", _proj_in(n, h), _proj_out(n, h)),
            Entry("model.layers.{i}.self_attn.k_proj.weight", "layers.wk", _proj_in(k, h), _proj_out(k, h)),
            Entry("model.layers.{i}.self_attn.v_proj.weight", "layers.wv", _proj_in(k, h), _proj_out(k, h)),
            Entry("model.layers.{i}.self_attn.o_proj.weight", "layers.wo", _o_in(n, h), _o_out(n, h)),
            *([] if not gated else [
                Entry("model.layers.{i}.mlp.gate_proj.weight", "layers.w_gate", _t, _t)]),
            Entry(f"model.layers.{{i}}.mlp.{up_name}.weight", "layers.w_up", _t, _t),
            Entry(f"model.layers.{{i}}.mlp.{down_name}.weight", "layers.w_down", _t, _t),
        ]
        if getattr(cfg, "norm_bias", False):
            entries += [
                Entry("model.norm.bias", "final_norm_b"),
                Entry(attn_norm_key + ".bias", "layers.attn_norm_b"),
                *([Entry(mlp_norm_key + ".bias", "layers.mlp_norm_b")]
                  if has_mlp_norm else []),
            ]
        if getattr(cfg, "mlp_bias", False):
            entries += [
                *([] if not gated else [
                    Entry("model.layers.{i}.mlp.gate_proj.bias", "layers.b_gate")]),
                Entry(f"model.layers.{{i}}.mlp.{up_name}.bias", "layers.b_up"),
                Entry(f"model.layers.{{i}}.mlp.{down_name}.bias", "layers.b_down"),
            ]
        if getattr(cfg, "attention_out_bias", False):
            entries.append(Entry("model.layers.{i}.self_attn.o_proj.bias", "layers.bo"))
        if getattr(cfg, "norm_placement", "pre") == "sandwich":
            entries += [
                Entry("model.layers.{i}.post_self_attn_layernorm.weight",
                      "layers.attn_post_norm"),
                Entry("model.layers.{i}.post_mlp_layernorm.weight",
                      "layers.mlp_post_norm"),
            ]
        if cfg.attention_bias:
            entries += [
                Entry("model.layers.{i}.self_attn.q_proj.bias", "layers.bq", _bias_in(n, h), _bias_out(n, h)),
                Entry("model.layers.{i}.self_attn.k_proj.bias", "layers.bk", _bias_in(k, h), _bias_out(k, h)),
                Entry("model.layers.{i}.self_attn.v_proj.bias", "layers.bv", _bias_in(k, h), _bias_out(k, h)),
            ]
        if getattr(cfg, "qk_norm_whole", False):
            # olmo2: flat (n*h,) HF weights <-> our (n, h) / (k, h) layout
            entries += [
                Entry("model.layers.{i}.self_attn.q_norm.weight", "layers.q_norm",
                      lambda a, n=n, h=h: a.reshape(n, h),
                      lambda a: np.ascontiguousarray(a.reshape(-1))),
                Entry("model.layers.{i}.self_attn.k_norm.weight", "layers.k_norm",
                      lambda a, k=k, h=h: a.reshape(k, h),
                      lambda a: np.ascontiguousarray(a.reshape(-1))),
            ]
        elif cfg.qk_norm:
            entries += [
                Entry("model.layers.{i}.self_attn.q_norm.weight", "layers.q_norm"),
                Entry("model.layers.{i}.self_attn.k_norm.weight", "layers.k_norm"),
            ]
        if not cfg.tie_word_embeddings:
            entries.append(Entry("lm_head.weight", "lm_head", _t, _t))
        super().__init__(entries, cfg.num_hidden_layers, scan_layers)
