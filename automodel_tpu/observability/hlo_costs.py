"""Analytic cost extraction from a compiled step + roofline accounting.

One XLA compile already knows almost everything a performance investigation
needs: the model FLOPs per step, the bytes the program touches, and — after
GSPMD partitioning — the exact collective instructions and their shapes. This
module pulls those numbers out of a ``jax.stages.Compiled`` once per compile
and turns them, together with the attached chip's peak specs, into a
roofline-expected step time and a per-row ``bound`` diagnosis
(compute/memory/comms/input-bound).

The per-collective byte accounting here is the single source of truth: the
driver's MULTICHIP dryrun (``__graft_entry__.py``) imports
:func:`collective_bytes` rather than carrying its own copy.

Convention: "bytes" = sum of each collective instruction's OUTPUT shape in the
per-device program (all-gather counts the gathered tensor, reduce-scatter the
scattered shard). Costs are per-device-program numbers — under SPMD every
device runs the same module, so per-chip rates compare directly.
"""

from __future__ import annotations

import dataclasses
import logging
import re
from typing import Any

logger = logging.getLogger(__name__)

__all__ = [
    "COLLECTIVE_OPS",
    "DTYPE_BYTES",
    "MOE_DISPATCH_SCOPES",
    "DeviceSpec",
    "collective_bytes",
    "collective_bytes_by_axis",
    "scope_output_bytes",
    "device_specs",
    "device_peak_tflops",
    "compiled_cost_metrics",
    "roofline_metrics",
    "diagnose_bound",
]

COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                  "collective-permute")
DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16,
}

_OP_RE = re.compile(
    r"=\s+((?:\([^)]*\))|(?:\S+))\s+(" + "|".join(COLLECTIVE_OPS) + r")(-start)?\("
)
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# any instruction's result shape(s): `%name = f32[8,16]{1,0} op(...)` or a tuple
_RESULT_RE = re.compile(r"=\s+((?:\([^)]*\))|(?:\S+))\s+[\w\-]+\(")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
# `replica_groups={{0,1},{2,3}}` — explicit groups; group size = first group len
_GROUPS_RE = re.compile(r"replica_groups=\{\{([\d,\s]*)\}")
# `replica_groups=[4,2]<=[8]` — iota form: 4 groups of size 2
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")

# named scopes that mark MoE dispatch/combine comms in the optimized HLO:
# the explicit-EP a2a path (moe/dispatch.py) and the GSPMD dense path
# (moe/experts.py) both label their reshard/exchange regions with these
MOE_DISPATCH_SCOPES = ("ep_dispatch", "ep_combine", "moe_dispatch", "moe_combine")


def _shapes_total_bytes(shapes_token: str, is_start: str | None = None) -> int:
    found = _SHAPE_RE.findall(shapes_token)
    if is_start and len(found) > 1:
        # async form: the -start tuple is (operand alias, ..., result) —
        # count only the result or the operand would double the volume
        found = found[-1:]
    total = 0
    for dt, dims in found:
        nbytes = DTYPE_BYTES.get(dt)
        if nbytes is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * nbytes
    return total


def collective_bytes(hlo: str) -> dict:
    """Sum output bytes per collective op kind in an optimized HLO module."""
    out = {}
    for line in hlo.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        shapes, op, is_start = m.group(1), m.group(2), m.group(3)
        total = _shapes_total_bytes(shapes, is_start)
        out[op] = out.get(op, 0) + total
    return out


def _group_size(line: str) -> int | None:
    """Participant count of a collective's replica groups, if parseable."""
    m = _GROUPS_RE.search(line)
    if m:
        ids = [x for x in m.group(1).split(",") if x.strip()]
        return len(ids) or None
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return int(m.group(2)) or None
    return None


def collective_bytes_by_axis(hlo: str, mesh_axes: dict | None = None) -> dict:
    """Attribute collective output bytes to mesh axes (ep vs dp vs tp vs pp).

    Two signals, in priority order:

    1. **Scope**: a collective whose ``op_name`` metadata lies inside one of
       the :data:`MOE_DISPATCH_SCOPES` is MoE dispatch/combine traffic — it
       counts toward the ``ep`` axis AND the ``moe_a2a`` bucket (the category
       the roofline ``bound`` diagnosis reports when expert exchange dominates;
       ``moe_a2a`` is a subset view, not an extra axis).
    2. **Group size**: a collective over groups of size g belongs to the
       unique mesh axis of size g (> 1). Equal-sized axes are genuinely
       ambiguous from the HLO alone and land in ``unattributed`` — honest
       beats guessed for a diagnosis people act on.

    Returns ``{axis: bytes, ..., "moe_a2a": bytes, "unattributed": bytes}``
    with zero-byte axes omitted (``moe_a2a`` is always present when any MoE
    dispatch scope appears in the module, even at 0 bytes, so its absence
    means "not an MoE program" rather than "no traffic").
    """
    axes = {str(k): int(v) for k, v in (mesh_axes or {}).items()}
    out: dict[str, int] = {}
    saw_moe_scope = any(scope in hlo for scope in MOE_DISPATCH_SCOPES)
    for line in hlo.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        shapes, op, is_start = m.group(1), m.group(2), m.group(3)
        nbytes = _shapes_total_bytes(shapes, is_start)
        if not nbytes:
            continue
        m_name = _OPNAME_RE.search(line)
        op_name = m_name.group(1) if m_name else ""
        in_moe_scope = any(scope in op_name for scope in MOE_DISPATCH_SCOPES)
        if in_moe_scope:
            out["moe_a2a"] = out.get("moe_a2a", 0) + nbytes
            if "ep" in axes:
                out["ep"] = out.get("ep", 0) + nbytes
                continue
        g = _group_size(line)
        candidates = [ax for ax, size in axes.items() if size == g and size > 1]
        if len(candidates) == 1:
            ax = candidates[0]
            out[ax] = out.get(ax, 0) + nbytes
            if ax == "ep" and op == "all-to-all" and not in_moe_scope:
                out["moe_a2a"] = out.get("moe_a2a", 0) + nbytes
        elif not in_moe_scope:
            out["unattributed"] = out.get("unattributed", 0) + nbytes
    if saw_moe_scope:
        out.setdefault("moe_a2a", 0)
    return out


def scope_output_bytes(hlo: str, scopes: tuple[str, ...]) -> dict:
    """Per-scope analytic volume: sum of instruction output bytes (and the
    collective subset) for instructions whose ``op_name`` metadata falls under
    one of ``scopes``. This is what lets the timeline carry analytic
    dispatch/combine/expert-compute spans without a device profiler — the
    optimized HLO already says how many bytes each labeled region produces.

    Returns ``{scope: {"bytes": int, "comm_bytes": int}}`` for scopes present.
    """
    out: dict[str, dict[str, int]] = {}
    for line in hlo.splitlines():
        m_name = _OPNAME_RE.search(line)
        if not m_name:
            continue
        op_name = m_name.group(1)
        # innermost wins: scopes nest (".../moe_experts/moe_combine/mul" is
        # combine work, not expert compute), so take the rightmost match
        matches = [(op_name.rfind(s), s) for s in scopes if s in op_name]
        if not matches:
            continue
        scope = max(matches)[1]
        m = _RESULT_RE.search(line)
        if not m:
            continue
        nbytes = _shapes_total_bytes(m.group(1))
        if not nbytes:
            continue
        bucket = out.setdefault(scope, {"bytes": 0, "comm_bytes": 0})
        bucket["bytes"] += nbytes
        cm = _OP_RE.search(line)
        if cm:
            bucket["comm_bytes"] += _shapes_total_bytes(cm.group(1), cm.group(3))
    return out


# ---------------------------------------------------------------------- specs
@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """Peak numbers for roofline math (per chip, public datasheet figures)."""

    name: str
    peak_bf16_tflops: float
    hbm_gbps: float  # HBM bandwidth, GB/s
    ici_gbps: float  # aggregate interchip-interconnect bandwidth, GB/s
    known: bool = True
    hbm_gib: float = 0.0  # per-chip HBM capacity, GiB (0 = unknown)


# matched by substring against the lowercased device kind, first hit wins;
# "v5 lite" before "v5p" keeps the v5e tunnel string from matching v5p
_DEVICE_SPECS = (
    ("v5 lite", DeviceSpec("v5e", 197.0, 819.0, 200.0, hbm_gib=16.0)),
    ("v5e", DeviceSpec("v5e", 197.0, 819.0, 200.0, hbm_gib=16.0)),
    ("v5p", DeviceSpec("v5p", 459.0, 2765.0, 600.0, hbm_gib=95.0)),
    ("v4", DeviceSpec("v4", 275.0, 1228.0, 300.0, hbm_gib=32.0)),
    ("v6", DeviceSpec("v6e", 918.0, 1640.0, 448.0, hbm_gib=32.0)),
)
_FALLBACK = DeviceSpec("v5e (assumed)", 197.0, 819.0, 200.0, known=False, hbm_gib=16.0)


def device_specs(device_kind: str) -> DeviceSpec:
    """Spec table lookup; unknown kinds assume v5e with ``known=False``."""
    kind = str(device_kind).lower()
    for key, spec in _DEVICE_SPECS:
        if key in kind:
            return spec
    return _FALLBACK


def device_peak_tflops(device: str) -> float:
    """bf16 peak for MFU math; warns and assumes v5e on unknown devices
    (shared by bench.py and the tools/ bench scripts)."""
    spec = device_specs(device)
    if not spec.known:
        import sys

        print(f"WARNING: unknown device {device!r}; assuming v5e 197 TFLOP peak "
              "(mfu/vs_baseline unreliable)", file=sys.stderr)
    return spec.peak_bf16_tflops


# ------------------------------------------------------------------ extraction
def compiled_cost_metrics(compiled: Any, mesh_axes: dict | None = None,
                          hlo_text: str | None = None) -> dict[str, int]:
    """Analytic costs of one compiled step, as flat log-row-ready ints.

    Returns ``hlo_flops`` / ``hlo_bytes_accessed`` (XLA's own cost analysis of
    the optimized module) plus ``comm_bytes_<kind>`` per collective kind and
    ``comm_bytes_total`` (regex accounting over the optimized HLO text). With
    ``mesh_axes`` (``{axis: size}``), collective bytes are also attributed per
    mesh axis as ``comm_bytes_axis_<axis>`` with the MoE dispatch/combine
    subset surfaced as ``comm_bytes_moe_a2a`` (see
    :func:`collective_bytes_by_axis`). Any unavailable source contributes
    nothing rather than raising — diagnostics must never take the run down.
    ``hlo_text``: pass the module text if the caller already extracted it
    (``as_text()`` is not free on big programs).
    """
    out: dict[str, int] = {}
    try:
        cost = compiled.cost_analysis()
        # list-of-dicts on some backends (one per computation), dict on others
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if cost:
            if cost.get("flops") is not None:
                out["hlo_flops"] = int(cost["flops"])
            if cost.get("bytes accessed") is not None:
                out["hlo_bytes_accessed"] = int(cost["bytes accessed"])
    except Exception:
        logger.debug("cost_analysis unavailable on this backend", exc_info=True)
    try:
        hlo = hlo_text if hlo_text is not None else compiled.as_text()
        comm = collective_bytes(hlo)
        for op, nbytes in sorted(comm.items()):
            out[f"comm_bytes_{op.replace('-', '_')}"] = int(nbytes)
        out["comm_bytes_total"] = int(sum(comm.values()))
        by_axis = collective_bytes_by_axis(hlo, mesh_axes)
        moe_a2a = by_axis.pop("moe_a2a", None)
        for ax, nbytes in sorted(by_axis.items()):
            out[f"comm_bytes_axis_{ax}"] = int(nbytes)
        if moe_a2a is not None:
            out["comm_bytes_moe_a2a"] = int(moe_a2a)
    except Exception:
        logger.debug("optimized HLO text unavailable", exc_info=True)
    return out


# -------------------------------------------------------------------- roofline
def roofline_metrics(costs: dict[str, int], spec: DeviceSpec) -> dict[str, float]:
    """Roofline-expected step time from analytic costs + chip peaks.

    Each resource is an independent floor: the step can go no faster than its
    FLOPs at peak compute, its bytes at peak HBM bandwidth, or its collective
    bytes at peak ICI bandwidth. The expected time is the max of the three and
    ``roofline_bound`` names the binding resource.
    """
    t_compute = costs.get("hlo_flops", 0) / (spec.peak_bf16_tflops * 1e12)
    t_memory = costs.get("hlo_bytes_accessed", 0) / (spec.hbm_gbps * 1e9)
    comm_total = costs.get("comm_bytes_total", 0)
    t_comm = comm_total / (spec.ici_gbps * 1e9)
    components = {"compute": t_compute, "memory": t_memory, "comms": t_comm}
    if max(components.values()) <= 0:
        return {}  # no analytic costs -> no roofline (an all-zero one misleads)
    bound = max(components, key=components.get)
    out = {
        "roofline_t_compute_s": t_compute,
        "roofline_t_memory_s": t_memory,
        "roofline_t_comm_s": t_comm,
        "roofline_step_time_s": max(components.values()),
        "roofline_bound": bound,
        "roofline_spec": spec.name,
    }
    moe_a2a = costs.get("comm_bytes_moe_a2a")
    if moe_a2a is not None:
        t_moe = moe_a2a / (spec.ici_gbps * 1e9)
        out["roofline_t_moe_a2a_s"] = t_moe
        # comms-bound and mostly dispatch/combine traffic -> the MoE a2a is the
        # wall, not generic gradient/activation collectives.
        if bound == "comms" and comm_total > 0 and moe_a2a > 0.5 * comm_total:
            out["roofline_bound"] = "moe_a2a"
    return out


def diagnose_bound(step_time_s: float | None, roofline: dict[str, Any],
                   data_wait_frac: float = 0.0,
                   input_bound_frac: float = 0.25) -> str | None:
    """Per-row bound diagnosis: achieved step time vs the roofline expectation.

    When the host spends more than ``input_bound_frac`` of wall time waiting on
    data, the step is input-bound regardless of what the device program looks
    like; otherwise the binding roofline resource is the diagnosis.
    """
    if not roofline or step_time_s is None:
        return None
    if data_wait_frac > input_bound_frac:
        return "input"
    return roofline.get("roofline_bound")
