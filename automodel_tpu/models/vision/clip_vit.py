"""CLIP vision tower — TPU-native ViT (the vision half of the reference's VLM
support; the reference reuses HF towers directly, e.g. recipes/vlm/finetune.py
freeze_config vision handling).

Standard CLIP ViT: bias-free patch conv, class token, learned absolute positions,
pre-LN encoder with quick-GELU MLPs, attention with biases. ``feature_layer``
selects which encoder layer's output to return (LLaVA uses -2, skipping the last
layer and the post-layernorm) — matching HF ``vision_feature_layer`` semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.ops.norms import layer_norm

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.ops.attention import dot_product_attention

__all__ = ["CLIPVisionConfig", "CLIPVisionTower"]


@dataclasses.dataclass
class CLIPVisionConfig:
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    image_size: int = 336
    patch_size: int = 14
    layer_norm_eps: float = 1e-5
    hidden_act: str = "quick_gelu"
    initializer_range: float = 0.02

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "CLIPVisionConfig":
        return cls(
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            num_attention_heads=hf["num_attention_heads"],
            image_size=hf.get("image_size", 336),
            patch_size=hf.get("patch_size", 14),
            layer_norm_eps=hf.get("layer_norm_eps", 1e-5),
            hidden_act=hf.get("hidden_act", "quick_gelu"),
            initializer_range=hf.get("initializer_range", 0.02),
        )

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def num_positions(self) -> int:
        return self.num_patches + 1  # + class token

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def _act(name: str, x):
    if name == "quick_gelu":
        return x * jax.nn.sigmoid(1.702 * x)
    if name in ("gelu", "gelu_new", "gelu_pytorch_tanh"):
        return jax.nn.gelu(x, approximate=name != "gelu")
    raise ValueError(f"unknown activation {name!r}")


class CLIPVisionTower:
    def __init__(self, config: CLIPVisionConfig, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()

    # -- params -------------------------------------------------------------
    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        cfg = self.config
        d, i, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
        std = cfg.initializer_range
        ks = iter(jax.random.split(key, 10))

        def w(k, shape, scale=std):
            return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

        layers = {
            "ln1_w": jnp.ones((L, d), dtype), "ln1_b": jnp.zeros((L, d), dtype),
            "wq": w(next(ks), (L, d, d)), "bq": jnp.zeros((L, d), dtype),
            "wk": w(next(ks), (L, d, d)), "bk": jnp.zeros((L, d), dtype),
            "wv": w(next(ks), (L, d, d)), "bv": jnp.zeros((L, d), dtype),
            "wo": w(next(ks), (L, d, d)), "bo": jnp.zeros((L, d), dtype),
            "ln2_w": jnp.ones((L, d), dtype), "ln2_b": jnp.zeros((L, d), dtype),
            "fc1": w(next(ks), (L, d, i)), "fc1_b": jnp.zeros((L, i), dtype),
            "fc2": w(next(ks), (L, i, d)), "fc2_b": jnp.zeros((L, d), dtype),
        }
        return {
            "patch_embed": w(next(ks), (cfg.patch_size, cfg.patch_size, 3, d)),
            "class_embed": w(next(ks), (d,)),
            "pos_embed": w(next(ks), (cfg.num_positions, d)),
            "pre_ln_w": jnp.ones((d,), dtype), "pre_ln_b": jnp.zeros((d,), dtype),
            "layers": layers,
            "post_ln_w": jnp.ones((d,), dtype), "post_ln_b": jnp.zeros((d,), dtype),
        }

    def logical_axes(self) -> dict:
        d2 = ("embed", None)
        layers = {
            "ln1_w": ("layers", "norm"), "ln1_b": ("layers", "norm"),
            "wq": ("layers", *d2), "bq": ("layers", None),
            "wk": ("layers", *d2), "bk": ("layers", None),
            "wv": ("layers", *d2), "bv": ("layers", None),
            "wo": ("layers", *d2), "bo": ("layers", None),
            "ln2_w": ("layers", "norm"), "ln2_b": ("layers", "norm"),
            "fc1": ("layers", "embed", "mlp"), "fc1_b": ("layers", "mlp"),
            "fc2": ("layers", "mlp", "embed"), "fc2_b": ("layers", None),
        }
        return {
            "patch_embed": (None, None, None, "embed"),
            "class_embed": ("embed",),
            "pos_embed": (None, "embed"),
            "pre_ln_w": ("norm",), "pre_ln_b": ("norm",),
            "layers": layers,
            "post_ln_w": ("norm",), "post_ln_b": ("norm",),
        }

    # -- forward ------------------------------------------------------------
    def __call__(self, params, pixel_values: jnp.ndarray, feature_layer: int | None = None):
        """pixel_values (B, 3, H, W) -> features (B, 1+P, D).

        ``feature_layer`` follows HF ``hidden_states`` indexing: index k (or L+1+k
        for negative k) = output after k encoder layers, never post-layernormed —
        LLaVA reads hidden_states[-2]. ``None`` = the full tower's pooled-style
        output: all layers + post-LN (HF last_hidden_state).
        """
        cfg = self.config
        dtype = self.backend.jnp_dtype
        eps = cfg.layer_norm_eps
        x = jnp.transpose(pixel_values, (0, 2, 3, 1)).astype(dtype)  # BHWC
        patches = jax.lax.conv_general_dilated(
            x, params["patch_embed"].astype(dtype),
            window_strides=(cfg.patch_size, cfg.patch_size), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        b = patches.shape[0]
        patches = patches.reshape(b, -1, cfg.hidden_size)
        cls_tok = jnp.broadcast_to(params["class_embed"].astype(dtype), (b, 1, cfg.hidden_size))
        h = jnp.concatenate([cls_tok, patches], axis=1) + params["pos_embed"].astype(dtype)
        h = layer_norm(h, params["pre_ln_w"], params["pre_ln_b"], eps)

        L = cfg.num_hidden_layers
        if feature_layer is None:
            stop_at = L
        else:
            stop_at = L + 1 + feature_layer if feature_layer < 0 else feature_layer
            if not 0 <= stop_at <= L:
                raise ValueError(
                    f"vision_feature_layer {feature_layer} out of range for {L}-layer tower"
                )

        def layer_fn(h, lp):
            lp = jax.tree.map(lambda a: a.astype(dtype), lp)
            x = layer_norm(h, lp["ln1_w"], lp["ln1_b"], eps)
            shape = (b, x.shape[1], cfg.num_attention_heads, cfg.head_dim)
            q = (x @ lp["wq"] + lp["bq"]).reshape(shape)
            k = (x @ lp["wk"] + lp["bk"]).reshape(shape)
            v = (x @ lp["wv"] + lp["bv"]).reshape(shape)
            out = dot_product_attention(q, k, v, causal=False, backend=self.backend.attention)
            h = h + (out.reshape(b, x.shape[1], -1) @ lp["wo"] + lp["bo"])
            x = layer_norm(h, lp["ln2_w"], lp["ln2_b"], eps)
            h = h + (_act(cfg.hidden_act, x @ lp["fc1"] + lp["fc1_b"]) @ lp["fc2"] + lp["fc2_b"])
            return h

        # unrolled loop: feature_layer selection needs per-layer outputs; vision
        # towers are shallow (24 layers) so compile cost is fine
        for li in range(stop_at):
            lp = jax.tree.map(lambda a: a[li], params["layers"])
            h = layer_fn(h, lp)
        if feature_layer is None:
            h = layer_norm(h, params["post_ln_w"], params["post_ln_b"], eps)
        return h
