"""Qwen3-VL-MoE — TPU-native (reference models/qwen3_vl_moe/model.py:317; the
reference keeps HF's vision tower and swaps the text stack — here both are native).

Composition: vision tower (models/vision/qwen3_vl_vit.py) -> merged visual embeds
scattered into the token embedding at image-token slots, plus *deepstack* features
added into the hidden states of the first N text layers (DeepStack,
arXiv:2406.04334). Text decoder = Qwen3-MoE blocks with interleaved mrope (3D t/h/w
position ids, transformers Qwen3VLMoeTextRotaryEmbedding).

TPU-first contract: everything data-dependent (3D rope index construction from
vision token spans, scatter coordinates of visual tokens) is host-side numpy
(``get_mrope_positions``/``visual_token_coords``); the jitted forward takes only
static-shaped arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.moe_transformer import (
    MoEDecoderConfig,
    init_moe_decoder_params,
    make_moe_layer_fns,
    moe_decoder_logical_axes,
)
from automodel_tpu.models.common.transformer import _constrain
from automodel_tpu.models.vision.qwen3_vl_vit import (
    Qwen3VLVisionConfig,
    init_vision_params,
    prepare_vision_inputs,
    vision_forward,
    vision_logical_axes,
)
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import (
    apply_rope_angles,
    mrope_angles,
    rope_attention_scaling,
    rope_frequencies,
)

__all__ = ["Qwen3VLMoeConfig", "Qwen3VLMoeForConditionalGeneration"]


@dataclasses.dataclass
class Qwen3VLMoeConfig:
    text: MoEDecoderConfig = None
    vision: Qwen3VLVisionConfig = None
    mrope_section: tuple[int, int, int] = (24, 20, 20)
    image_token_id: int = 151655
    video_token_id: int = 151656
    vision_start_token_id: int = 151652

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "Qwen3VLMoeConfig":
        t = hf.get("text_config", hf)
        rope_scaling = t.get("rope_scaling") or {}
        moe = MoEConfig(
            n_routed_experts=t["num_experts"],
            n_activated_experts=t["num_experts_per_tok"],
            dim=t["hidden_size"],
            moe_inter_dim=t["moe_intermediate_size"],
            score_func="softmax",
            softmax_before_topk=True,
            norm_topk_prob=True,  # HF hardcodes renorm for this family
            aux_loss_coeff=t.get("router_aux_loss_coef", 0.0),
        )
        text = MoEDecoderConfig(
            vocab_size=t["vocab_size"],
            hidden_size=t["hidden_size"],
            intermediate_size=t.get("intermediate_size", 0),
            num_hidden_layers=t["num_hidden_layers"],
            num_attention_heads=t["num_attention_heads"],
            num_key_value_heads=t.get("num_key_value_heads", t["num_attention_heads"]),
            head_dim=t.get("head_dim"),
            max_position_embeddings=t.get("max_position_embeddings", 4096),
            rope_theta=t.get("rope_theta", 10000.0),
            rope_scaling=rope_scaling or None,  # mrope keys are ignored by rope_frequencies
            rms_norm_eps=t.get("rms_norm_eps", 1e-6),
            tie_word_embeddings=hf.get("tie_word_embeddings", t.get("tie_word_embeddings", False)),
            attention_bias=t.get("attention_bias", False),
            qk_norm=True,
            initializer_range=t.get("initializer_range", 0.02),
            moe=moe,
            first_k_dense_replace=0,
        )
        return cls(
            text=text,
            vision=Qwen3VLVisionConfig.from_hf(hf.get("vision_config", {})),
            mrope_section=tuple(rope_scaling.get("mrope_section", (24, 20, 20))),
            image_token_id=hf.get("image_token_id", 151655),
            video_token_id=hf.get("video_token_id", 151656),
            vision_start_token_id=hf.get("vision_start_token_id", 151652),
        )


class Qwen3VLMoeForConditionalGeneration:
    """Functional model: holds config + backend, operates on param pytrees."""

    config_class = Qwen3VLMoeConfig
    hf_architectures = ("Qwen3VLMoeForConditionalGeneration",)

    def __init__(self, config: Qwen3VLMoeConfig, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()

    # ---- params ----

    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        k_text, k_vis = jax.random.split(key)
        params = init_moe_decoder_params(self.config.text, k_text, dtype)
        params["visual"] = init_vision_params(self.config.vision, k_vis, dtype)
        return params

    def abstract_params(self, dtype=jnp.bfloat16) -> dict:
        return jax.eval_shape(lambda k: self.init(k, dtype), jax.random.key(0))

    def logical_axes(self) -> dict:
        axes = moe_decoder_logical_axes(self.config.text)
        axes["visual"] = vision_logical_axes(self.config.vision)
        return axes

    # ---- host-side bookkeeping (collator/test helpers) ----

    def prepare_vision_inputs(self, grid_thw: np.ndarray) -> dict[str, np.ndarray]:
        return prepare_vision_inputs(grid_thw, self.config.vision)

    def visual_token_coords(self, input_ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(batch_idx, seq_idx) of image/video placeholder tokens, in scan order —
        matches the order merged vision tokens come out of the tower for batches
        whose images appear in reading order."""
        mask = (input_ids == self.config.image_token_id) | (
            input_ids == self.config.video_token_id
        )
        b, s = np.where(mask)
        return b.astype(np.int32), s.astype(np.int32)

    def get_mrope_positions(
        self,
        input_ids: np.ndarray,  # (B, S)
        grid_thw: np.ndarray | None,  # image grids, (n_images, 3), reading order
        attention_mask: np.ndarray | None = None,
        video_grid_thw: np.ndarray | None = None,  # (n_videos, 3)
    ) -> np.ndarray:
        """3D (t, h, w) position ids, (3, B, S) — numpy mirror of HF get_rope_index
        (modeling_qwen3_vl_moe.py:1082): text tokens advance all three axes together;
        a vision span of (t, h, w) patches gets grid coordinates offset after the
        preceding text, and the following text resumes from max+1. Video grids are
        split into per-frame t=1 spans (Qwen3-VL timestamp encoding — frames are
        separate placeholder runs separated by timestamp text, :1091-1094)."""
        cfg = self.config
        B, S = input_ids.shape
        ms = cfg.vision.spatial_merge_size
        if video_grid_thw is not None:
            v = np.asarray(video_grid_thw)
            v = np.repeat(v, v[:, 0], axis=0)
            v[:, 0] = 1
            video_grid_thw = v
        pos = np.zeros((3, B, S), dtype=np.int64)
        img_idx, vid_idx = 0, 0
        for b in range(B):
            valid = np.ones((S,), bool) if attention_mask is None else attention_mask[b].astype(bool)
            ids = input_ids[b][valid]
            out = np.zeros((3, len(ids)), dtype=np.int64)
            st = 0
            cursor = 0
            is_vis = (ids == cfg.image_token_id) | (ids == cfg.video_token_id)
            while st < len(ids):
                if not is_vis[st]:
                    out[:, st] = cursor
                    cursor += 1
                    st += 1
                    continue
                if ids[st] == cfg.video_token_id:
                    t, h, w = (int(x) for x in video_grid_thw[vid_idx])
                    vid_idx += 1
                else:
                    t, h, w = (int(x) for x in grid_thw[img_idx])
                    img_idx += 1
                gh, gw = h // ms, w // ms
                n = t * gh * gw
                ti = np.repeat(np.arange(t), gh * gw)
                hi = np.tile(np.repeat(np.arange(gh), gw), t)
                wi = np.tile(np.arange(gw), t * gh)
                out[0, st : st + n] = ti + cursor
                out[1, st : st + n] = hi + cursor
                out[2, st : st + n] = wi + cursor
                cursor = int(out[:, st : st + n].max()) + 1
                st += n
            pos[:, b, valid] = out
        return pos

    # ---- forward ----

    def embed_with_vision(self, params, input_ids, pixel_values=None,
                          vision_inputs=None, visual_coords=None, extra_embeds=None):
        """Token embedding with visual tokens scattered in at image-token slots.
        Returns ``(h, ds)`` — ds is the (n_ds, Tm, D) deepstack feature stack
        (None without pixels). Shared by __call__ and the pp hidden path."""
        dtype = self.backend.jnp_dtype
        h = params["embed"].astype(dtype)[input_ids]
        ds = None
        if pixel_values is not None:
            vis, ds = vision_forward(
                self.config.vision, self.backend, params["visual"],
                pixel_values, vision_inputs["pos_pairs"], vision_inputs["pos_idx"],
                vision_inputs["pos_w"], vision_inputs["segment_ids"],
            )
            b_idx, s_idx = visual_coords
            h = h.at[b_idx, s_idx].set(vis.astype(dtype))
        if extra_embeds is not None:
            (eb_idx, es_idx), toks = extra_embeds
            h = h.at[eb_idx, es_idx].set(toks.astype(dtype))
        return h, ds

    # vlm x pp capability flag for the recipe's _check_pp_support
    pp_hidden_supported = True

    def _pp_extra_embeds(self, params, mb):
        """Hook for subclasses with extra scatter modalities (omni audio): maps
        a microbatch to ``((b_idx, s_idx), tokens)`` for embed_with_vision, or
        None. The base family has none."""
        del params, mb
        return None

    def make_pp_hidden(self, mesh, rules=None, *, seq_len_hint: int = 0,
                       circular_repeats: int = 1):
        """Pipelined text stack -> FINAL HIDDEN STATES for vlm x pp (VERDICT r3
        #5; the reference pipelines the wrapped VLM module by FQN slicing,
        distributed/pipelining/functional.py:289).

        Per microbatch OUTSIDE the manual region (plain GSPMD): vision tower,
        embed scatter, mrope angles. INSIDE, the per-layer deepstack features
        ride the ring as a dense (n_ds, B, S, D) addend next to the activation
        — side-riders over pipeline_spmd's pytree ring — and are injected at
        their GLOBAL layer index by whichever stage owns it, so the deepstack
        window may even straddle a stage boundary.

        Returns ``hidden_fn(params, batch_stack, num_label_tokens) ->
        (h_stack, aux_loss, {"expert_load": (L, E)})`` — the same contract as
        :func:`parallel.pipeline.make_moe_pp_hidden`.
        """
        from jax.sharding import PartitionSpec as P

        from automodel_tpu.parallel.pipeline import make_pipeline_forward

        if circular_repeats > 1:
            raise NotImplementedError(
                "qwen3-vl deepstack pp is wired for V=1 (circular rounds need a "
                "round-major layer-index remap for the deepstack injection)"
            )
        cfg, backend = self.config.text, self.backend
        if backend.dispatcher == "a2a":
            # same fence as make_moe_pp_loss (parallel/pipeline.py): the a2a
            # dispatch is its own shard_map and cannot nest in the pp region
            raise ValueError(
                "dispatcher='a2a' cannot run inside the pp manual region (nested "
                "shard_map over ep); use the default GSPMD dispatcher under pp"
            )
        pp = mesh.shape["pp"]
        L = cfg.num_hidden_layers
        if L % pp:
            raise ValueError(f"num_hidden_layers {L} % pp {pp} != 0")
        Lb = L // pp
        n_ds = len(self.config.vision.deepstack_visual_indexes)
        inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
        attn_scale = rope_attention_scaling(cfg.rope_scaling)
        emit_aux = cfg.moe.aux_loss_coeff > 0 and not backend.fake_balanced_gate
        mrope_section = self.config.mrope_section

        def attention_fn(lp, x, angles, seg, is_sliding, rules_):
            # the state's ``positions`` slot carries the per-microbatch mrope
            # ANGLES through the ring (moe_layer_fn just forwards it here)
            del is_sliding, rules_
            q = jnp.einsum("bsd,dnh->bsnh", x, lp["wq"])
            k = jnp.einsum("bsd,dnh->bsnh", x, lp["wk"])
            v = jnp.einsum("bsd,dnh->bsnh", x, lp["wv"])
            q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
            k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
            q = apply_rope_angles(q, angles, attn_scale)
            k = apply_rope_angles(k, angles, attn_scale)
            out = dot_product_attention(
                q, k, v, causal=True, segment_ids_q=seg, backend=backend.attention,
            )
            return jnp.einsum("bsnh,nhd->bsd", out, lp["wo"])

        # rules=None: no sharding constraints inside the pp-manual region (the
        # same contract as make_moe_pp_loss)
        _, moe_layer_fn = make_moe_layer_fns(
            cfg, backend, None, attention_fn, True, seq_len_hint=seq_len_hint
        )
        body = backend.layer_remat(moe_layer_fn)
        aux_specs = {"load": P("pp")}
        if emit_aux:
            aux_specs["aux"] = P("pp")
        pipeline = make_pipeline_forward(mesh, with_aux=True, aux_out_specs=aux_specs)

        def layer_apply(lp_stack, x):
            state = {"h": x["h"], "positions": x["angles"],
                     "segment_ids": x["segment_ids"],
                     "token_mask": x["segment_ids"] != 0}
            base = jax.lax.axis_index("pp") * Lb

            def scan_body(st, inp):
                lp, j = inp
                st, (aux, load, dropped) = body(st, (lp, jnp.int32(0)))
                if n_ds:
                    gi = base + j
                    inj = jnp.where(
                        gi < n_ds,
                        x["ds"][jnp.clip(gi, 0, n_ds - 1)].astype(st["h"].dtype),
                        jnp.zeros_like(st["h"]),
                    )
                    st = dict(st, h=st["h"] + inj)
                return st, (aux, load, dropped)

            state, (auxs, loads, _dropped) = jax.lax.scan(
                scan_body, state, (lp_stack, jnp.arange(Lb))
            )
            out = {"load": loads}
            if emit_aux:
                out["aux"] = (auxs.sum() * x["aux_weight"])[None]
            return dict(x, h=state["h"]), out

        def hidden_fn(params, batch_stack, num_label_tokens):
            def embed_mb(mb):
                h, ds = self.embed_with_vision(
                    params, mb["input_ids"], mb.get("pixel_values"),
                    mb.get("vision_inputs"),
                    (mb["visual_coords_b"], mb["visual_coords_s"])
                    if "visual_coords_b" in mb else None,
                    extra_embeds=self._pp_extra_embeds(params, mb),
                )
                pos3 = mb.get("positions3")
                if pos3 is None:
                    B, S = mb["input_ids"].shape
                    pos3 = jnp.broadcast_to(jnp.arange(S), (3, B, S))
                entry = {
                    "h": h,
                    "angles": mrope_angles(pos3, inv_freq, mrope_section),
                    "segment_ids": mb["segment_ids"],
                }
                if n_ds:
                    dsd = jnp.zeros((n_ds, *h.shape), h.dtype)
                    if ds is not None:
                        b_idx, s_idx = mb["visual_coords_b"], mb["visual_coords_s"]
                        dsd = dsd.at[:, b_idx, s_idx].add(ds.astype(h.dtype))
                    entry["ds"] = dsd
                return entry

            x_stack = jax.lax.map(embed_mb, batch_stack)
            if emit_aux:
                mb_tokens = (batch_stack["labels"] != -100).sum(axis=tuple(
                    range(1, batch_stack["labels"].ndim))).astype(jnp.float32)
                x_stack["aux_weight"] = mb_tokens / jnp.asarray(
                    num_label_tokens, jnp.float32)
            h_stack, aux = pipeline(
                params["moe_layers"], None, x_stack, None, layer_apply, None
            )
            aux_loss = (cfg.moe.aux_loss_coeff * aux["aux"].sum()) if emit_aux else 0.0
            return h_stack, aux_loss, {"expert_load": aux["load"]}

        return hidden_fn

    def __call__(
        self,
        params,
        input_ids,  # (B, S)
        pixel_values=None,  # (Tv, patch_dim)
        vision_inputs=None,  # dict from prepare_vision_inputs (jnp arrays ok)
        visual_coords=None,  # (b_idx (Tm,), s_idx (Tm,)) from visual_token_coords
        positions3=None,  # (3, B, S) from get_mrope_positions; None = text-only arange
        extra_embeds=None,  # ((b_idx, s_idx), tokens): extra modality scatter (omni audio)
        segment_ids=None,
        token_mask=None,
        rules=None,
        return_hidden=False,
        training=True,
    ):
        cfg, backend = self.config.text, self.backend
        dtype = backend.jnp_dtype
        B, S = input_ids.shape

        if positions3 is None:
            positions3 = jnp.broadcast_to(jnp.arange(S), (3, B, S))
        inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
        attn_scale = rope_attention_scaling(cfg.rope_scaling)
        angles = mrope_angles(positions3, inv_freq, self.config.mrope_section)

        h, ds = self.embed_with_vision(
            params, input_ids, pixel_values, vision_inputs, visual_coords, extra_embeds
        )
        h = _constrain(h, rules, ("batch", "act_seq", "act_embed"))
        emit_aux = cfg.moe.aux_loss_coeff > 0 and training and not backend.fake_balanced_gate

        def attention_fn(lp, x, positions, seg, is_sliding, rules_):
            del positions, is_sliding
            q = jnp.einsum("bsd,dnh->bsnh", x, lp["wq"])
            k = jnp.einsum("bsd,dnh->bsnh", x, lp["wk"])
            v = jnp.einsum("bsd,dnh->bsnh", x, lp["wv"])
            q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
            k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
            q = apply_rope_angles(q, angles, attn_scale)
            k = apply_rope_angles(k, angles, attn_scale)
            q = _constrain(q, rules_, ("batch", "act_attn_seq", "act_heads", None))
            k = _constrain(k, rules_, ("batch", "act_attn_seq", "act_heads", None))
            out = dot_product_attention(
                q, k, v, causal=True, segment_ids_q=seg, backend=backend.attention,
            )
            return jnp.einsum("bsnh,nhd->bsd", out, lp["wo"])

        _, moe_layer_fn = make_moe_layer_fns(
            cfg, backend, rules, attention_fn, training, seq_len_hint=S
        )
        body = backend.layer_remat(moe_layer_fn)

        state = {"h": h, "positions": positions3[0]}
        if segment_ids is not None:
            state["segment_ids"] = segment_ids
        if token_mask is not None:
            state["token_mask"] = token_mask

        sliding = jnp.zeros((cfg.num_hidden_layers,), jnp.int32)
        n_ds = 0 if ds is None else ds.shape[0]
        auxs, loads, droppeds = [], [], []
        # deepstack: unrolled first n_ds layers, each followed by a visual-feature add
        for i in range(n_ds):
            lp = jax.tree.map(lambda a: a[i], params["moe_layers"])
            state, (aux, load, dropped) = body(state, (lp, sliding[i]))
            b_idx, s_idx = visual_coords
            state["h"] = state["h"].at[b_idx, s_idx].add(ds[i].astype(dtype))
            auxs.append(aux)
            loads.append(load)
            droppeds.append(dropped)
        rest = jax.tree.map(lambda a: a[n_ds:], params["moe_layers"])
        if backend.scan_layers:
            state, (aux_s, load_s, drop_s) = jax.lax.scan(body, state, (rest, sliding[n_ds:]))
        else:
            aux_l, load_l, drop_l = [], [], []
            for i in range(cfg.num_hidden_layers - n_ds):
                lp = jax.tree.map(lambda a: a[i], rest)
                state, (aux, load, dropped) = body(state, (lp, sliding[n_ds + i]))
                aux_l.append(aux)
                load_l.append(load)
                drop_l.append(dropped)
            aux_s, load_s, drop_s = jnp.stack(aux_l), jnp.stack(load_l), jnp.stack(drop_l)
        if auxs:
            aux_s = jnp.concatenate([jnp.stack(auxs), aux_s])
            load_s = jnp.concatenate([jnp.stack(loads), load_s])
            drop_s = jnp.concatenate([jnp.stack(droppeds), drop_s])

        stats = {"aux_loss": aux_s.sum() if emit_aux else None, "expert_load": load_s}
        if backend.dispatcher == "a2a":
            stats["dropped_token_frac"] = drop_s.mean()

        h = rms_norm(state["h"], params["final_norm"].astype(dtype), cfg.rms_norm_eps)
        if return_hidden:
            return h, stats
        unembed = params.get("lm_head")
        if unembed is None:
            unembed = params["embed"].T
        logits = jnp.einsum("bsd,dv->bsv", h, unembed.astype(dtype))
        return logits, stats

    # ---- interop ----

    def state_dict_adapter(self):
        from automodel_tpu.models.qwen3_vl_moe.state_dict_adapter import (
            Qwen3VLMoeStateDictAdapter,
        )

        return Qwen3VLMoeStateDictAdapter(self.config)

    @classmethod
    def from_config(cls, config, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = Qwen3VLMoeConfig.from_hf(config)
        return cls(config, backend)
