"""Bench-matrix smoke: `bench.py --matrix --cpu` rows parse and gate correctly.

Marked ``perf`` (and ``slow``, out of tier-1): run with ``pytest -m perf``.
Runs the real matrix in a subprocess the way the driver would, checks the
one-JSON-line-per-row contract (dense AND moe cells, with routed-throughput
and a2a-share fields on the moe rows), then drives tools/bench_gate.py over
the capture: exit 0 against a matching baseline, exit 1 on a synthetic
per-cell regression, exit 2 on a broken artifact.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.perf]

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
GATE = os.path.join(REPO, "tools", "bench_gate.py")


def _gate(*args):
    return subprocess.run([sys.executable, GATE, *args],
                          capture_output=True, text=True, timeout=120)


@pytest.fixture(scope="module")
def matrix_run(tmp_path_factory):
    """One CPU matrix run shared by every scenario (the cells dominate time)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = ""  # the --cpu path re-pins jax_platforms itself
    env.pop("XLA_FLAGS", None)  # 8 virtual devices would slow the tiny cells
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--matrix", "--cpu"],
        capture_output=True, text=True, timeout=840, env=env,
    )
    assert result.returncode == 0, result.stderr[-2000:]
    path = tmp_path_factory.mktemp("matrix") / "matrix.jsonl"
    path.write_text(result.stdout)
    return path


def _rows_and_summary(path):
    docs = [json.loads(ln) for ln in path.read_text().splitlines() if ln.strip()]
    rows = [d for d in docs if d.get("matrix_row")]
    return rows, docs[-1]


def test_matrix_emits_one_parseable_row_per_cell(matrix_run):
    rows, summary = _rows_and_summary(matrix_run)
    # {dense, moe} x 3 seq lens x {off, on}, plus the two a2a hot-path
    # cells (moe_a2a, moe_a2a_pallas) at the headline seq x {off, on}
    assert len(rows) == 16
    cells = {(r["model"], r["seq_len"], r["prefetch"]) for r in rows}
    assert len(cells) == 16
    for r in rows:
        assert r["tokens_per_sec_per_chip"] > 0
        if r["model"].startswith("moe"):
            assert r["moe/tokens_per_sec_per_chip"] > 0
            assert 0.0 <= r["a2a_byte_share"] <= 1.0
        else:
            assert "moe/tokens_per_sec_per_chip" not in r
    # the a2a cells run the explicit ep dispatch: real all_to_alls in the
    # HLO (nonzero byte share) and a profiled step on the prefetch-on row
    for kind in ("moe_a2a", "moe_a2a_pallas"):
        on = next(r for r in rows if r["model"] == kind and r["prefetch"])
        assert on["a2a_byte_share"] > 0
        assert "dropped_token_frac" in on
        if "overlap_frac" in on:  # profiled step is best-effort decoration
            assert 0.0 <= on["overlap_frac"] <= 1.0
    assert summary["ok"] is True
    assert summary["value"] > 0  # headline: dense s2048 prefetch-on
    assert len(summary["matrix"]) == 16


def test_gate_exit_codes_on_matrix_artifact(matrix_run, tmp_path):
    baseline = str(tmp_path / "baseline.json")

    wrote = _gate("--run", str(matrix_run), "--baseline", baseline, "--write-baseline")
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    base = json.load(open(baseline))
    assert "matrix/dense_s2048_pfon/tps" in base["metrics"]
    assert "matrix/moe_s4096_pfoff/moe_tps" in base["metrics"]

    same = _gate("--run", str(matrix_run), "--baseline", baseline)
    assert same.returncode == 0, same.stdout + same.stderr
    assert "[gate] PASS" in same.stdout

    # synthetic regression in ONE cell: the gate must name it, not average it away
    rows, summary = _rows_and_summary(matrix_run)
    regressed = tmp_path / "regressed.jsonl"
    with open(regressed, "w") as f:
        for r in rows:
            if r["model"] == "moe" and r["seq_len"] == 8192 and r["prefetch"]:
                r = dict(r, **{"tokens_per_sec_per_chip":
                               r["tokens_per_sec_per_chip"] * 0.4})
            f.write(json.dumps(r) + "\n")
    bad = _gate("--run", str(regressed), "--baseline", baseline,
                "--tolerance", "default=0.3")
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "REGRESSION" in bad.stdout
    assert "matrix/moe_s8192_pfon/tps" in bad.stdout

    # a broken artifact is a usage error (2), not a silent pass
    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert _gate("--run", str(empty), "--baseline", baseline).returncode == 2


def test_committed_baseline_gates_a_fresh_run(matrix_run):
    """BASELINE.json's metrics key is a live gate target for the matrix."""
    committed = os.path.join(REPO, "BASELINE.json")
    doc = json.load(open(committed))
    assert any(k.startswith("matrix/") for k in doc["metrics"])
    # wide default tolerance: CPU-fallback cells jitter run to run
    res = _gate("--run", str(matrix_run), "--baseline", committed,
                "--tolerance", "default=0.9")
    assert res.returncode in (0, 1), res.stdout + res.stderr
    assert "[gate]" in res.stdout
