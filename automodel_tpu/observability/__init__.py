"""Unified training observability: goodput accounting, HBM + compile telemetry,
a stall watchdog, and on-demand profiling (docs/observability.md)."""

from automodel_tpu.observability.goodput import BUCKETS, GoodputTracker
from automodel_tpu.observability.manager import Observability, ObservabilityConfig
from automodel_tpu.observability.memory import device_memory_stats
from automodel_tpu.observability.profiling import OnDemandProfiler
from automodel_tpu.observability.watchdog import StallWatchdog

__all__ = [
    "BUCKETS",
    "GoodputTracker",
    "Observability",
    "ObservabilityConfig",
    "OnDemandProfiler",
    "StallWatchdog",
    "device_memory_stats",
]
