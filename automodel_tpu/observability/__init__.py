"""Unified training observability: goodput accounting, HBM + compile telemetry,
a stall watchdog, on-demand profiling, HLO cost/roofline accounting, cross-host
metric aggregation, a unified trace timeline, and a perf-regression gate
(docs/observability.md)."""

from automodel_tpu.observability.aggregate import CrossHostAggregator
from automodel_tpu.observability.events import TraceTimeline
from automodel_tpu.observability.goodput import BUCKETS, GoodputTracker
from automodel_tpu.observability.hlo_costs import (
    collective_bytes,
    compiled_cost_metrics,
    device_specs,
    diagnose_bound,
    roofline_metrics,
)
from automodel_tpu.observability.manager import Observability, ObservabilityConfig
from automodel_tpu.observability.memory import device_memory_stats
from automodel_tpu.observability.profiling import OnDemandProfiler
from automodel_tpu.observability.watchdog import StallWatchdog

__all__ = [
    "BUCKETS",
    "CrossHostAggregator",
    "GoodputTracker",
    "Observability",
    "ObservabilityConfig",
    "OnDemandProfiler",
    "StallWatchdog",
    "TraceTimeline",
    "collective_bytes",
    "compiled_cost_metrics",
    "device_memory_stats",
    "device_specs",
    "diagnose_bound",
    "roofline_metrics",
]
