from automodel_tpu.diffusers.auto_diffusion_pipeline import AutoDiffusionPipeline

__all__ = ["AutoDiffusionPipeline"]
