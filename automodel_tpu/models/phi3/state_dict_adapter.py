"""Phi-3 HF key/layout mapping: llama table + fused-tensor split/merge.

HF Phi-3 packs q|k|v into ``self_attn.qkv_proj.weight`` and gate|up into
``mlp.gate_up_proj.weight`` (transformers Phi3Attention/Phi3MLP); the shared
FusedTensorMixin splits them into the llama-table's virtual q/k/v/gate/up keys
on the way in and re-fuses on the way out, so the model tree stays identical
to llama's.
"""

from __future__ import annotations

from automodel_tpu.models.common.state_dict import FusedTensorMixin
from automodel_tpu.models.common.transformer import DenseDecoderConfig
from automodel_tpu.models.llama.state_dict_adapter import LlamaStateDictAdapter

__all__ = ["Phi3StateDictAdapter"]


class Phi3StateDictAdapter(FusedTensorMixin, LlamaStateDictAdapter):
    _fused = [
        ("self_attn.qkv_proj.weight",
         ["self_attn.q_proj.weight", "self_attn.k_proj.weight", "self_attn.v_proj.weight"]),
        ("mlp.gate_up_proj.weight", ["mlp.gate_proj.weight", "mlp.up_proj.weight"]),
    ]

    def __init__(self, cfg: DenseDecoderConfig, scan_layers: bool = True):
        super().__init__(cfg, scan_layers)
        q = cfg.num_attention_heads * cfg.head_dim
        kv = cfg.num_key_value_heads * cfg.head_dim
        # split offsets along HF's out_features dim 0
        self._fused_splits = {"self_attn.qkv_proj.weight": [q, q + kv],
                              "mlp.gate_up_proj.weight": [cfg.intermediate_size]}
