"""Single-chip SFT throughput benchmark (driver-run; prints ONE JSON line).

Benchmarks the BASELINE.json config #1 shape — Llama-3.2-1B-class SFT, mock data,
bf16 — on whatever single accelerator is attached, and reports tokens/sec/chip at
seq 2048 (primary, continuity with earlier rounds) AND seq 4096 (the reference's
own measurement condition, BASELINE.md) in extra.

``vs_baseline`` is hardware-normalized: the reference's headline single-GPU row is
Llama3-8B LoRA on H100 at 402 TFLOPs/s/GPU = 40.6% MFU against 989 bf16 peak
(BASELINE.md / docs/performance-summary.md). We report our model-FLOPs MFU against
the attached chip's bf16 peak and define vs_baseline = our_MFU / 0.406 — comparing
compiler+framework efficiency rather than raw chips (an H100 has ~5x the FLOPs of
the v5e this runs on).

Failure contract: the LAST stdout line is ALWAYS machine-parseable JSON — the
``__main__`` guard catches BaseException and flushes stderr before the final
print, so no traceback can displace or interleave with it. When the TPU/axon
backend cannot initialize — or inits but dies at the FIRST dispatch (a trivial
jitted canary probes this; round 5 lost its data point to exactly that) — the
bench retries in a subprocess on the CPU platform with a tiny config (marked
``extra.fallback: "cpu"``, exit 0) so the bench trajectory never goes dark; an
unrecoverable failure prints ``{"ok": false, "error": ...}`` and exits
non-zero. ``extra.input_pipeline`` reports seconds/step for the same loop with
the overlapped input pipeline off vs on.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def device_peak_tflops(device: str) -> float:
    """bf16 peak for MFU math; warns and assumes v5e on unknown devices
    (shared by bench.py and the tools/ bench scripts). Delegates to the
    observability spec table — one source of truth with the roofline math."""
    from automodel_tpu.observability.hlo_costs import device_peak_tflops as _peak

    return _peak(device)


def llama_flops_per_token(cfg, seq_len: int) -> float:
    """Training FLOPs/token (fwd+bwd = 3x fwd) incl. attention quadratic term."""
    d, i, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    n, k, h, v = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim, cfg.vocab_size
    qkv = 2 * d * (n + 2 * k) * h
    o = 2 * n * h * d
    attn_scores = 2 * 2 * seq_len * n * h  # qk^T + av per token
    mlp = 3 * 2 * d * i
    per_layer = qkv + o + attn_scores + mlp
    embed_head = 2 * d * v
    return 3.0 * (L * per_layer + embed_head)


def _measure(cfg, seq_len: int, micro_batch: int, n_steps: int, backend=None,
             dynamics: bool = False):
    import jax
    import jax.numpy as jnp
    import optax

    from automodel_tpu.models.common.backend import BackendConfig
    from automodel_tpu.models.llama.model import LlamaForCausalLM
    from automodel_tpu.ops.losses import masked_cross_entropy
    from automodel_tpu.training.train_step import make_train_step

    # measured on-chip (single v5-class): pallas flash (1024, 1024) blocks +
    # remat "mlp_attn_dots" (save gate/up/k/v/attn-out; backward replays only the
    # q projection + elementwise) + momentum-free factored-rms (pure Adafactor,
    # the T5/PaLM optimizer — its ~zero state is what affords that remat policy
    # on a 16GB chip) + attention_segments=False (mock SFT batches are unpacked
    # and full-length: causal masking already isolates pads, so the kernels skip
    # the segment loads/selects — clean-run-to-clean-run +4.1% at 2048, +5.5%
    # at 4096) = 13.68k tok/s / 57.1% MFU at 2048, 11.89k / 54.5% at 4096
    # (stable over repeats). The ladder: fp32-nu adamw -> remat "none" 11.7k;
    # bf16-nu -> "mlp_gate_dot" 12.0k; factored+bf16 trace -> "mlp_dots"
    # 12.87k; momentum-free -> "mlp_attn_dots" 13.14k; segment-free attention
    # -> 13.68k; round-5 fused dq+dkv backward (one s/p recompute feeding all
    # three grads, 5 bwd block-matmuls instead of 7) -> 14.38k @2048 / 12.78k
    # @4096 (60.0% / 58.5% MFU). Fused q-block sweep: 512 best (256: -2%,
    # 1024: scoped-VMEM OOM at 19.6M/16M). Round-4 dead ends at 4096
    # (tools/bench_seq4096_sweep.py): saving q too in remat (-1.3pt, bandwidth),
    # dkv q-block 256 (-2.1pt) or 1024 (+-0), fwd blocks (2048,1024) and
    # micro_batch 3/4 (OOM even with linear-CE — the mlp saved tensors dominate).
    if backend is None:
        backend = BackendConfig(dtype="bfloat16", remat_policy="mlp_attn_dots",
                                attention="flash", attention_segments=False)
    model = LlamaForCausalLM(cfg, backend)

    params = model.init(jax.random.key(0), jnp.dtype(backend.dtype))
    optimizer = optax.chain(
        optax.scale_by_factored_rms(),
        optax.scale(-1e-5),
    )
    opt_state = jax.jit(optimizer.init)(params)

    def forward_loss(p, batch, num_label_tokens):
        logits = model(p, batch["input_ids"], positions=batch["positions"],
                       segment_ids=batch["segment_ids"])
        return masked_cross_entropy(logits, batch["labels"], num_label_tokens)

    # --dynamics: the per-subtree telemetry reductions ride in-graph (the
    # overhead the gate tolerance must absorb, docs/observability.md)
    step = jax.jit(make_train_step(forward_loss, optimizer, dynamics=dynamics),
                   donate_argnums=(0, 1))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (1, micro_batch, seq_len)).astype(np.int32)
    batch = {
        "input_ids": jnp.asarray(ids),
        "labels": jnp.asarray(ids),
        "positions": jnp.broadcast_to(jnp.arange(seq_len, dtype=jnp.int32), ids.shape),
        "segment_ids": jnp.ones_like(jnp.asarray(ids)),
    }

    # warmup/compile. NB: sync via host transfer — block_until_ready does not
    # block through the remote-execution tunnel, which silently yields ~1000x
    # inflated throughput numbers.
    params, opt_state, m = step(params, opt_state, batch)
    float(m["loss"])

    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, opt_state, m = step(params, opt_state, batch)
    float(m["loss"])
    dt = time.perf_counter() - t0
    return n_steps * micro_batch * seq_len / dt


def _prefetch_probe(n_steps: int = 8, item_delay_s: float = 0.004) -> dict:
    """Input-pipeline overlap measurement: seconds/step for an identical tiny
    loop with the loader synchronous vs overlapped (host prefetch thread +
    device double-buffering). ``item_delay_s`` stands in for real host-side
    tokenize/pack cost; the overlapped path hides it behind device compute."""
    import jax
    import jax.numpy as jnp

    from automodel_tpu.data.collate import stack_batches
    from automodel_tpu.data.llm.mock import MockSFTDataset
    from automodel_tpu.data.loader import DataLoader
    from automodel_tpu.data.prefetch import InputPipeline, PrefetchConfig
    from automodel_tpu.training.step_scheduler import StepScheduler

    def collate(samples):
        return {"x": np.asarray([s["input_ids"] for s in samples], np.int32)}

    def f_impl(x):
        # device work of the same magnitude as the host-side cost — overlap is
        # only visible when there is compute to hide the input latency behind
        v = x.reshape(-1).astype(jnp.float32)[:512]
        a = jnp.outer(v, v) / 512.0
        for _ in range(12):
            a = jnp.tanh(a @ a)
        return jnp.sum(a)

    f = jax.jit(f_impl)

    def run(enabled: bool) -> float:
        ds = MockSFTDataset(vocab_size=512, seq_len=128,
                            num_samples=8 * (n_steps + 2), seed=0,
                            item_delay_s=item_delay_s)
        dl = DataLoader(ds, batch_size=8, collate_fn=collate, seed=0)
        sched = StepScheduler(grad_acc_steps=1, num_epochs=1,
                              max_steps=n_steps + 1, dataloader=dl,
                              handle_sigterm=False)
        pipe = InputPipeline(scheduler=sched, dataloader=dl,
                             stack_fn=stack_batches, put_fn=jax.device_put,
                             config=PrefetchConfig(enabled=enabled))
        try:
            # first step covers compile + queue spin-up; timed steps follow
            first = pipe.get()
            f(first.stack["x"]).block_until_ready()
            done = 0
            t0 = time.perf_counter()
            while done < n_steps:
                item = pipe.get()
                if item is None:
                    break
                f(item.stack["x"]).block_until_ready()
                done += 1
            dt = time.perf_counter() - t0
        finally:
            pipe.close()
        return dt / max(done, 1)

    sync = run(False)
    overlapped = run(True)
    return {
        "sync_s_per_step": round(sync, 5),
        "prefetch_s_per_step": round(overlapped, 5),
        "overlap_speedup": round(sync / overlapped, 3) if overlapped > 0 else None,
    }


def _attach_prefetch_probe(doc: dict) -> dict:
    """Best-effort: the overlap numbers ride along, they never fail the bench."""
    try:
        doc["extra"]["input_pipeline"] = _prefetch_probe()
    except Exception as exc:  # noqa: BLE001
        doc["extra"]["input_pipeline"] = {"error": repr(exc)}
    return doc


def _full_bench(dynamics: bool = False) -> dict:
    import jax

    from automodel_tpu.models.llama.model import LlamaConfig

    # Llama-3.2-1B dims
    cfg = LlamaConfig(
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=8192,
        num_hidden_layers=16,
        num_attention_heads=32,
        num_key_value_heads=8,
        head_dim=64,
        rope_theta=500000.0,
        tie_word_embeddings=True,
        max_position_embeddings=131072,
    )
    tps = _measure(cfg, seq_len=2048, micro_batch=4, n_steps=20, dynamics=dynamics)
    tps_4k = _measure(cfg, seq_len=4096, micro_batch=2, n_steps=10, dynamics=dynamics)

    device = str(jax.devices()[0])
    peak = device_peak_tflops(device)

    f_2k = llama_flops_per_token(cfg, 2048)
    f_4k = llama_flops_per_token(cfg, 4096)
    # reference 8B dims for the FLOPs-equivalent conversion
    cfg8b = LlamaConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
    )
    f_8b = llama_flops_per_token(cfg8b, 4096)
    mfu = tps * f_2k / 1e12 / peak
    mfu_4k = tps_4k * f_4k / 1e12 / peak
    ref_mfu = 402.0 / 989.0  # reference Llama3-8B LoRA on H100, seq 4096

    return _attach_prefetch_probe({
        "ok": True,
        "metric": "llama3.2-1b SFT tokens/sec/chip (bf16, seq 2048)",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / ref_mfu, 4),
        "extra": {
            "model_tflops_per_sec": round(tps * f_2k / 1e12, 1),
            "mfu": round(mfu, 4),
            "seq4096_tokens_per_sec": round(tps_4k, 1),
            "seq4096_mfu": round(mfu_4k, 4),
            "seq4096_vs_baseline": round(mfu_4k / ref_mfu, 4),
            "assumed_peak_tflops": peak,
            "8b_equiv_tokens_per_sec": round(tps_4k * f_4k / f_8b, 1),
            "device": device,
            "dynamics": dynamics,
        },
    })


def _cpu_fallback_bench(dynamics: bool = False) -> dict:
    """Tiny-config CPU measurement: keeps the trajectory numeric (and the JSON
    contract intact) on a TPU-less host. NOT comparable to chip numbers —
    marked ``extra.fallback: "cpu"`` and vs_baseline null."""
    import jax

    from automodel_tpu.models.common.backend import BackendConfig
    from automodel_tpu.models.llama.model import LlamaConfig

    cfg = LlamaConfig(
        vocab_size=2048, hidden_size=256, intermediate_size=1024,
        num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
        head_dim=32, max_position_embeddings=512,
    )
    tps = _measure(cfg, seq_len=256, micro_batch=2, n_steps=3,
                   backend=BackendConfig(dtype="float32"), dynamics=dynamics)
    return _attach_prefetch_probe({
        "ok": True,
        "metric": "llama3.2-1b SFT tokens/sec/chip (bf16, seq 2048)",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": None,
        "extra": {
            "fallback": "cpu",
            "fallback_config": "tiny (4L/256d, seq 256, fp32, xla attention)",
            "device": str(jax.devices()[0]),
            "dynamics": dynamics,
        },
    })


# ---------------------------------------------------------------- matrix mode
MATRIX_SEQ_LENS = (2048, 4096, 8192)


def _matrix_dense_model(cpu: bool):
    from automodel_tpu.models.common.backend import BackendConfig
    from automodel_tpu.models.llama.model import LlamaForCausalLM

    cfg = _tune_model_config(cpu)
    if cpu:
        backend = BackendConfig(dtype="float32")
    else:
        # the tuned single-chip backend (see _measure)
        backend = BackendConfig(dtype="bfloat16", remat_policy="mlp_attn_dots",
                                attention="flash", attention_segments=False)
    return LlamaForCausalLM(cfg, backend), cfg.vocab_size


def _matrix_moe_model(cpu: bool, dispatcher: str = "dense",
                      experts_backend: str = "ragged_dot", a2a_chunks: int = 1):
    from automodel_tpu.models.common.backend import BackendConfig
    from automodel_tpu.models.qwen3_moe.model import Qwen3MoeForCausalLM

    moe_knobs = dict(dispatcher=dispatcher, experts_backend=experts_backend,
                     a2a_chunks=a2a_chunks)
    if cpu:
        hf = dict(
            vocab_size=2048, hidden_size=256, intermediate_size=512,
            moe_intermediate_size=128, num_hidden_layers=4,
            num_attention_heads=8, num_key_value_heads=4, head_dim=32,
            max_position_embeddings=512, num_experts=8, num_experts_per_tok=2,
            norm_topk_prob=True, router_aux_loss_coef=0.01,
        )
        backend = BackendConfig(dtype="float32", **moe_knobs)
    else:
        # 1B-class MoE: same token FLOPs ballpark as the dense row so the
        # dense-vs-moe tokens/s gap in one matrix is the dispatch overhead
        hf = dict(
            vocab_size=128256, hidden_size=2048, intermediate_size=4096,
            moe_intermediate_size=1024, num_hidden_layers=16,
            num_attention_heads=32, num_key_value_heads=8, head_dim=64,
            max_position_embeddings=131072, num_experts=16,
            num_experts_per_tok=2, norm_topk_prob=True,
            router_aux_loss_coef=0.01,
        )
        backend = BackendConfig(dtype="bfloat16", remat_policy="mlp_attn_dots",
                                attention="flash", attention_segments=False,
                                **moe_knobs)
    return Qwen3MoeForCausalLM.from_config(hf, backend), hf["vocab_size"]


# the moe_a2a cells exercise the explicit EP dispatch hot path: dispatcher=a2a
# over an ep mesh spanning every device, chunked so expert GEMMs overlap the
# next chunk's all_to_all, with both grouped-GEMM backends. One seq point is
# enough — the dispatch/overlap story does not need the seq sweep.
MATRIX_A2A_KINDS = ("moe_a2a", "moe_a2a_pallas")


def _matrix_cells() -> list[tuple[str, int]]:
    """Every (kind, nominal_seq) cell in the matrix: dense/moe across
    MATRIX_SEQ_LENS plus the a2a hot-path variants at the headline seq."""
    cells = [(kind, nominal) for kind in ("dense", "moe")
             for nominal in MATRIX_SEQ_LENS]
    cells += [(kind, MATRIX_SEQ_LENS[0]) for kind in MATRIX_A2A_KINDS]
    return cells


def _matrix_cell(kind: str, nominal_seq: int, cpu: bool,
                 dynamics: bool = False,
                 profile: bool = False) -> tuple[list[dict], dict | None]:
    """One {model} x {seq} cell: AOT-compile once, run prefetch off then on.

    Returns ``(rows, signals_cell)``. CPU rows keep the nominal seq as the row
    label (so baselines line up across hosts) and record the actually
    measured ``measured_seq_len``; MoE rows add routed tokens/s/chip and the
    a2a share of collective bytes from the compiled HLO. With ``profile``,
    one extra step runs under a ``jax.profiler`` trace after the timed loops
    and the measured category breakdown (``measured_*`` + ``overlap_frac``,
    observability/trace_analysis.py) lands on the prefetch-on row — the
    production config — plus a schema-shaped signals cell (signals.py) for
    the summary doc; without it ``signals_cell`` is None.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from automodel_tpu.data.collate import stack_batches
    from automodel_tpu.data.llm.mock import MockSFTDataset
    from automodel_tpu.data.loader import DataLoader
    from automodel_tpu.data.prefetch import InputPipeline, PrefetchConfig
    from automodel_tpu.observability.hlo_costs import (
        collective_bytes,
        collective_bytes_by_axis,
    )
    from automodel_tpu.observability.memory import device_memory_stats
    from automodel_tpu.observability.memory_plan import compiled_memory_attribution
    from automodel_tpu.ops.losses import masked_cross_entropy
    from automodel_tpu.training.step_scheduler import StepScheduler
    from automodel_tpu.training.train_step import make_train_step

    a2a = kind in MATRIX_A2A_KINDS
    is_moe = kind == "moe" or a2a
    rules = None
    if a2a:
        from automodel_tpu.parallel.mesh import MeshContext, default_sharding_rules

        # an ep mesh over every device: the explicit dispatch path degrades
        # gracefully at ep=1 (single-host runs without forced devices), and
        # a2a cells always carry overlap_frac — the a2a/compute overlap IS
        # the metric these cells exist to gate, so the one profiled step is
        # not optional here
        mesh = MeshContext(ep=jax.device_count()).build_mesh()
        rules = default_sharding_rules().with_mesh(mesh)
        model, vocab = _matrix_moe_model(
            cpu, dispatcher="a2a", a2a_chunks=2,
            experts_backend="pallas" if kind == "moe_a2a_pallas"
            else "ragged_dot")
        profile = True
    else:
        model, vocab = (_matrix_moe_model(cpu) if is_moe
                        else _matrix_dense_model(cpu))
    seq_len = min(nominal_seq, 128) if cpu else nominal_seq
    micro_batch = 2 if cpu else {2048: 4, 4096: 2, 8192: 1}[nominal_seq]
    n_steps = 3 if cpu else 10
    devices = jax.device_count()
    if a2a:
        # the dispatch shard_map splits the batch dim over ep: round the
        # microbatch up to a whole multiple of the mesh
        micro_batch = -(-micro_batch // devices) * devices

    def forward_loss(p, batch, num_label_tokens):
        if is_moe:
            out, stats = model(
                p, batch["input_ids"], positions=batch["positions"],
                segment_ids=batch["segment_ids"],
                token_mask=batch["segment_ids"] != 0, training=True,
                rules=rules,
            )
            loss = masked_cross_entropy(out, batch["labels"], num_label_tokens)
            aux = {"expert_load": stats["expert_load"]}
            if a2a:
                aux["dropped_frac"] = stats["dropped_token_frac"]
            return loss, aux
        logits = model(p, batch["input_ids"], positions=batch["positions"],
                       segment_ids=batch["segment_ids"])
        return masked_cross_entropy(logits, batch["labels"], num_label_tokens)

    optimizer = optax.chain(optax.scale_by_factored_rms(), optax.scale(-1e-5))
    step_fn = make_train_step(forward_loss, optimizer, dynamics=dynamics)

    if a2a:
        # sharded init: expert weights land distributed over the ep axis, so
        # the lowered step is the real multi-device dispatch program
        shardings = rules.tree_sharding(model.logical_axes())
        from automodel_tpu.parallel.sharding_utils import make_sharded_init

        params = jax.jit(
            lambda k: model.init(k, jnp.dtype(model.backend.dtype)),
            out_shardings=shardings)(jax.random.key(0))
        opt_state = make_sharded_init(optimizer, params, mesh)(params)
        # pin the carry outputs to the carry input shardings — XLA is
        # otherwise free to re-lay the donated params between steps, which
        # the AOT-compiled call rejects on the next invocation
        step = jax.jit(
            step_fn, donate_argnums=(0, 1),
            out_shardings=(jax.tree.map(lambda a: a.sharding, params),
                           jax.tree.map(lambda a: a.sharding, opt_state),
                           None))
    else:
        step = jax.jit(step_fn, donate_argnums=(0, 1))
        params = model.init(jax.random.key(0), jnp.dtype(model.backend.dtype))
        opt_state = jax.jit(optimizer.init)(params)

    # AOT compile from a synthetic stack of the pipeline's exact shapes; the
    # optimized HLO also yields the a2a byte share
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (1, micro_batch, seq_len)).astype(np.int32)
    sample_stack = {
        "input_ids": ids, "labels": ids.copy(),
        "positions": np.ascontiguousarray(np.broadcast_to(
            np.arange(seq_len, dtype=np.int32), ids.shape)),
        "segment_ids": np.ones_like(ids),
    }
    compiled = step.lower(params, opt_state, sample_stack).compile()
    a2a_share = 0.0
    hlo = None
    try:
        hlo = compiled.as_text()
        total = sum(collective_bytes(hlo).values())
        moe_a2a = collective_bytes_by_axis(hlo).get("moe_a2a", 0)
        a2a_share = round(moe_a2a / total, 4) if total else 0.0
    except Exception:  # noqa: BLE001 — a2a share is best-effort decoration
        pass
    # memory-analysis peak: XLA's own args+out+temp-alias attribution of the
    # compiled step — available on every backend, deterministic for a given
    # (model, seq, batch), and the CPU fallback for the hbm_gib_peak gate key
    # where no allocator counters exist
    attribution = compiled_memory_attribution(compiled)
    compiled_peak_gib = (round(attribution["peak_est"] / 2**30, 4)
                         if attribution else None)

    def collate(samples):
        # MockSFTDataset emits seq_len + 1 ids (next-token shift headroom);
        # trim to the AOT-compiled width so shapes match the lowered step
        arr = np.asarray([s["input_ids"] for s in samples], np.int32)[:, :seq_len]
        return {
            "input_ids": arr, "labels": arr.copy(),
            "positions": np.ascontiguousarray(np.broadcast_to(
                np.arange(arr.shape[-1], dtype=np.int32), arr.shape)),
            "segment_ids": np.ones_like(arr),
        }

    def make_pipeline(prefetch: bool) -> InputPipeline:
        ds = MockSFTDataset(vocab_size=vocab, seq_len=seq_len,
                            num_samples=micro_batch * (n_steps + 3), seed=0,
                            item_delay_s=0.002)
        dl = DataLoader(ds, batch_size=micro_batch, collate_fn=collate, seed=0)
        sched = StepScheduler(grad_acc_steps=1, num_epochs=1,
                              max_steps=n_steps + 1, dataloader=dl,
                              handle_sigterm=False)
        return InputPipeline(scheduler=sched, dataloader=dl,
                             stack_fn=stack_batches, put_fn=jax.device_put,
                             config=PrefetchConfig(enabled=prefetch))

    rows = []
    for prefetch in (False, True):
        pipe = make_pipeline(prefetch)
        try:
            first = pipe.get()
            params, opt_state, m = compiled(params, opt_state, first.stack)
            float(m["loss"])  # host sync: flush warmup before the clock starts
            done = 0
            t0 = time.perf_counter()
            while done < n_steps:
                item = pipe.get()
                if item is None:
                    break
                params, opt_state, m = compiled(params, opt_state, item.stack)
                done += 1
            float(m["loss"])  # host sync closes the timed window
            dt = time.perf_counter() - t0
        finally:
            pipe.close()
        row = {
            "matrix_row": True, "model": kind, "seq_len": nominal_seq,
            "prefetch": prefetch, "steps": max(done, 1),
            "tokens_per_sec_per_chip": round(
                done * micro_batch * seq_len / dt / devices, 1),
        }
        if dynamics:
            # condition marker: a dynamics-on row must not be compared against
            # a dynamics-off baseline without knowing it
            row["dynamics"] = True
        # gate key: measured allocator high-water where the platform has one
        # (TPU), else the compiled-step estimate — the source rides along so
        # a baseline from one never silently gates a run from the other
        mem_stats = device_memory_stats()
        if mem_stats.get("hbm_gib_peak") is not None:
            row["hbm_gib_peak"] = mem_stats["hbm_gib_peak"]
            row["hbm_source"] = "device"
        elif compiled_peak_gib is not None:
            row["hbm_gib_peak"] = compiled_peak_gib
            row["hbm_source"] = "compiled"
        if cpu:
            row["fallback"] = "cpu"
            row["measured_seq_len"] = seq_len
            row["micro_batch"] = micro_batch
        if is_moe:
            # routed token copies through the expert GEMMs — the volume a
            # grouped-GEMM / fused-dispatch optimization has to move
            routed_per_step = float(np.asarray(m["expert_load"]).sum())
            row["moe/tokens_per_sec_per_chip"] = round(
                routed_per_step * done / dt / devices, 1)
            row["a2a_byte_share"] = a2a_share
            if a2a:
                row["dropped_token_frac"] = round(float(m["dropped_frac"]), 4)
        rows.append(row)
    signals_cell = None
    if profile:
        # one profiled step AFTER the timed loops: params/opt_state are warm
        # and nothing downstream needs them (donation deletes the inputs)
        measured, signals_cell = _profile_cell_step(
            compiled, params, opt_state, sample_stack, hlo,
            cell={"model": kind, "seq_len": nominal_seq})
        rows[-1].update(measured)  # the prefetch-on (production) row
    return rows, signals_cell


def _profile_cell_step(compiled, params, opt_state, sample_stack, hlo,
                       cell) -> tuple[dict, dict | None]:
    """One step under a jax.profiler trace -> measured row keys + signals cell.

    Best-effort decoration like the a2a share: any failure returns empty and
    the bench rows stand on their timed numbers alone.
    """
    import shutil
    import tempfile

    import jax

    from automodel_tpu.observability import signals as sig
    from automodel_tpu.observability import trace_analysis as ta
    from automodel_tpu.observability.hlo_costs import (
        compiled_cost_metrics,
        device_specs,
        roofline_metrics,
    )

    td = tempfile.mkdtemp(prefix="bench_trace_")
    try:
        try:
            batch = jax.device_put(sample_stack)
            jax.profiler.start_trace(td)
            try:
                _p, _o, m = compiled(params, opt_state, batch)
                float(m["loss"])  # host sync: the trace must hold the whole step
            finally:
                jax.profiler.stop_trace()
            report = ta.analyze_trace(td, hlo_text=hlo, steps_hint=1)
        finally:
            shutil.rmtree(td, ignore_errors=True)
        if report is None:
            return {}, None
        costs = compiled_cost_metrics(compiled, hlo_text=hlo)
        roof = roofline_metrics(costs, device_specs(jax.devices()[0].device_kind))
        summary = report.summary_row()
        summary.update(ta.reconcile_with_roofline(report, roof))
        measured = {k: summary[k] for k in
                    ("measured_step_time_s", "measured_t_compute_s",
                     "measured_t_comm_s", "measured_t_moe_a2a_s",
                     "measured_t_host_s", "measured_frac_compute",
                     "measured_frac_comm", "measured_frac_moe_a2a",
                     "measured_frac_host", "overlap_frac", "measured_bound")
                    if k in summary}
        signals_cell = sig.build_cell(cell=cell, roofline=roof or None,
                                      costs=costs, trace_summary=summary)
        return measured, signals_cell
    except Exception as exc:  # noqa: BLE001 — profiling must not kill the bench
        print(f"bench: profiled step failed ({exc!r}); rows carry no "
              "measured_* keys", file=sys.stderr)
        return {}, None


def _matrix_bench_inline(cpu: bool, dynamics: bool = False,
                         profile: bool = False) -> dict:
    """``--no-isolate``: every cell in THIS process (the pre-r05 monolith —
    one dead cell still kills the rest). Kept for debugging a single
    interpreter; the default path is the per-cell subprocess harness below."""
    import jax

    rows: list[dict] = []
    signal_cells: list[dict] = []
    for kind, nominal in _matrix_cells():
        cell_rows, signals_cell = _matrix_cell(
            kind, nominal, cpu, dynamics=dynamics, profile=profile)
        for row in cell_rows:
            print(json.dumps(row), flush=True)
            rows.append(row)
        if signals_cell is not None:
            signal_cells.append(signals_cell)
    headline = next(
        (r["tokens_per_sec_per_chip"] for r in rows
         if r["model"] == "dense" and r["seq_len"] == 2048 and r["prefetch"]),
        None,
    )
    doc = {
        "ok": True,
        "metric": "bench matrix: {dense,moe} x seq x prefetch tokens/s/chip",
        "value": headline,
        "unit": "tokens/s/chip",
        "vs_baseline": None,
        "matrix": rows,
        "extra": {"device": str(jax.devices()[0]), "rows": len(rows)},
    }
    if signal_cells:
        from automodel_tpu.observability.signals import build_signals

        doc["signals"] = build_signals(signal_cells)
    if cpu:
        doc["extra"]["fallback"] = "cpu"
    return doc


def _cell_argv(spec: dict, script: str | None = None) -> list[str]:
    """The child invocation for one cell: same interpreter, same script,
    ``--cell kind:seq`` plus the run's mode flags."""
    import os

    argv = [sys.executable, script or os.path.abspath(__file__),
            "--cell", f"{spec['kind']}:{spec['seq_len']}"]
    for flag in ("cpu", "dynamics", "profile"):
        if spec.get(flag):
            argv.append(f"--{flag}")
    return argv


def _bench_chaos_hook(cell_id: str) -> None:
    """CI fault injection for the harness itself: ``AUTOMODEL_BENCH_CHAOS``
    (JSON: ``{"fail": [cell ids], "hang": [cell ids], "hang_s": n}``) forces
    a named cell to die or to wedge past its timeout — proving a poisoned
    cell costs one cell, never the artifact. Resume without the env var
    re-runs only the poisoned cells."""
    import os

    raw = os.environ.get("AUTOMODEL_BENCH_CHAOS")
    if not raw:
        return
    spec = json.loads(raw)
    if cell_id in (spec.get("fail") or ()):
        raise RuntimeError(f"bench chaos: forced failure in cell {cell_id}")
    if cell_id in (spec.get("hang") or ()):
        hold = float(spec.get("hang_s", 3600.0))
        print(f"bench chaos: hanging cell {cell_id} for {hold:.0f}s",
              file=sys.stderr)
        time.sleep(hold)


def _cell_main(cell: str, cpu: bool, dynamics: bool = False,
               profile: bool = False) -> dict:
    """``--cell kind:seq`` child mode: one isolated cell, rows as JSON lines,
    then a final doc the harness records (``{"ok", "cell", "rows", "signals"}``
    — the rows ride the doc so the ledger can replay them on resume)."""
    kind, _, seq = cell.partition(":")
    cell_id = f"{kind}_s{seq}"
    _bench_chaos_hook(cell_id)
    rows, signals_cell = _matrix_cell(kind, int(seq), cpu,
                                      dynamics=dynamics, profile=profile)
    for row in rows:
        print(json.dumps(row), flush=True)
    return {"ok": True, "cell": cell_id, "rows": rows, "signals": signals_cell}


def _matrix_bench(cpu: bool, dynamics: bool = False, profile: bool = False,
                  out_dir: str = "bench_matrix", resume: bool = False,
                  cell_timeout_s: float = 900.0, cell_retries: int = 1) -> dict:
    """{dense, moe} x seq {2048,4096,8192} plus the moe_a2a hot-path cells
    at the headline seq (_matrix_cells), each cell in an isolated
    subprocess with a wall budget (resilience/harness.py). One JSON line per
    row as it lands; completed cells recorded in the resumable
    ``<out_dir>/matrix_ledger.json``; a failed cell becomes a taxonomy-labeled
    ledger entry instead of killing the matrix (BENCH_r05). The summary doc
    keeps the gate contract (``matrix`` rows + headline) and adds per-cell
    status (``cells``) plus the preflight verdict; ``ok`` is False when any
    cell did not run. ``--resume`` re-runs only the incomplete cells,
    byte-identically preserving completed entries."""
    import os

    from automodel_tpu.resilience.harness import (
        CellLedger, run_cells, run_isolated,
    )

    os.makedirs(out_dir, exist_ok=True)
    ledger_path = os.path.join(out_dir, "matrix_ledger.json")
    if not resume and os.path.exists(ledger_path):
        # a fresh run must not silently inherit a stale ledger's completions
        os.unlink(ledger_path)
    ledger = CellLedger(ledger_path)

    # preflight health rung in its own subprocess: a wedged backend poisons
    # one probe, and the verdict is stamped into the artifact header
    script = os.path.abspath(__file__)
    pf_argv = [sys.executable, script, "--preflight"] + (["--cpu"] if cpu else [])
    pf = run_isolated(pf_argv, timeout_s=min(cell_timeout_s, 300.0))
    pf_doc = next((d for d in reversed(pf["docs"]) if "ok" in d), None) or {
        "ok": False,
        "error": ("preflight timed out" if pf["timed_out"]
                  else f"preflight rc={pf['returncode']} with no JSON line"),
        "tail": pf["stderr_tail"][-2000:],
    }
    ledger.set_header({"preflight": pf_doc, "mode": {
        "cpu": cpu, "dynamics": dynamics, "profile": profile}})
    if not pf_doc.get("ok"):
        return {
            "ok": False,
            "metric": "bench matrix: {dense,moe} x seq x prefetch tokens/s/chip",
            "value": None, "unit": "tokens/s/chip", "vs_baseline": None,
            "error": f"preflight failed: {pf_doc.get('error')}",
            "matrix": [], "cells": [],
            "extra": {"preflight": pf_doc, "ledger": ledger_path},
        }

    specs = [
        {"id": f"{kind}_s{nominal}", "kind": kind, "seq_len": nominal,
         "cpu": cpu, "dynamics": dynamics, "profile": profile}
        for kind, nominal in _matrix_cells()
    ]

    def emit(entry: dict, replayed: bool) -> None:
        outcome = entry["outcome"]
        if outcome["status"] == "ran":
            for row in outcome.get("rows") or []:
                print(json.dumps(row), flush=True)
        else:
            print(f"bench: cell {entry['id']} {outcome['status']} "
                  f"({outcome.get('taxonomy')})", file=sys.stderr)

    counts = run_cells(
        specs, argv_for=_cell_argv, ledger=ledger,
        timeout_s=cell_timeout_s, retries=cell_retries, on_entry=emit)

    rows: list[dict] = []
    signal_cells: list[dict] = []
    cells_status: list[dict] = []
    for e in ledger.doc["cells"]:
        outcome = e["outcome"]
        status = {"id": e["id"], "status": outcome["status"]}
        if outcome["status"] == "ran":
            rows.extend(outcome.get("rows") or [])
            if outcome.get("signals"):
                signal_cells.append(outcome["signals"])
        else:
            status["taxonomy"] = outcome.get("taxonomy")
            status["tail"] = (outcome.get("tail") or "")[-500:]
        cells_status.append(status)
    incomplete = [c["id"] for c in cells_status if c["status"] != "ran"]
    headline = next(
        (r["tokens_per_sec_per_chip"] for r in rows
         if r["model"] == "dense" and r["seq_len"] == 2048 and r["prefetch"]),
        None,
    )
    doc = {
        "ok": not incomplete,
        "metric": "bench matrix: {dense,moe} x seq x prefetch tokens/s/chip",
        "value": headline,
        "unit": "tokens/s/chip",
        "vs_baseline": None,
        "matrix": rows,
        "cells": cells_status,
        "incomplete_cells": incomplete,
        "extra": {"rows": len(rows), "ledger": ledger_path,
                  "preflight": pf_doc, "counts": counts,
                  "device": pf_doc.get("device")},
    }
    if incomplete:
        doc["error"] = (f"{len(incomplete)} cell(s) did not run: "
                        + ", ".join(incomplete))
    if signal_cells:
        from automodel_tpu.observability.signals import build_signals

        doc["signals"] = build_signals(signal_cells)
    if cpu:
        doc["extra"]["fallback"] = "cpu"
    return doc


# ------------------------------------------------------------------ tune mode
def _tune_measure_factory(cpu: bool, nominal_seq: int, plan_cache: dict):
    """Build the per-trial measure() the tuner runner calls: model with the
    trial's backend knobs, AOT compile, a short timed window through the
    overlapped input pipeline at the trial's prefetch depths. Returns raw
    metrics plus a signals-cell snapshot for the ledger."""
    import jax
    import jax.numpy as jnp
    import optax

    from automodel_tpu.data.collate import stack_batches
    from automodel_tpu.data.llm.mock import MockSFTDataset
    from automodel_tpu.data.loader import DataLoader
    from automodel_tpu.data.prefetch import InputPipeline, PrefetchConfig
    from automodel_tpu.models.common.backend import BackendConfig
    from automodel_tpu.models.llama.model import LlamaForCausalLM
    from automodel_tpu.observability import signals as sig
    from automodel_tpu.observability.hlo_costs import (
        compiled_cost_metrics,
        device_specs,
        roofline_metrics,
    )
    from automodel_tpu.observability.memory_plan import compiled_memory_attribution
    from automodel_tpu.ops.losses import masked_cross_entropy
    from automodel_tpu.training.step_scheduler import StepScheduler
    from automodel_tpu.training.train_step import make_train_step

    cfg = _tune_model_config(cpu)
    seq_len = min(nominal_seq, 128) if cpu else nominal_seq
    n_steps = 3 if cpu else 10
    devices = jax.device_count()

    def backend_for(trial) -> BackendConfig:
        kw = dict(dtype="float32") if cpu else dict(
            dtype="bfloat16", attention="flash", attention_segments=False)
        kw["remat_policy"] = trial.remat_policy
        if trial.layout is not None:
            kw["scan_layers"] = trial.layout == "scan"
        if trial.dispatcher is not None:
            kw["dispatcher"] = trial.dispatcher
        return BackendConfig(**kw)

    def measure(trial) -> dict:
        backend = backend_for(trial)
        model = LlamaForCausalLM(cfg, backend)
        micro_batch = int(trial.micro_batch_size or (2 if cpu else 4))

        def forward_loss(p, batch, num_label_tokens):
            logits = model(p, batch["input_ids"], positions=batch["positions"],
                           segment_ids=batch["segment_ids"])
            return masked_cross_entropy(logits, batch["labels"], num_label_tokens)

        optimizer = optax.chain(optax.scale_by_factored_rms(), optax.scale(-1e-5))
        step = jax.jit(make_train_step(forward_loss, optimizer),
                       donate_argnums=(0, 1))
        params = model.init(jax.random.key(0), jnp.dtype(backend.dtype))
        opt_state = jax.jit(optimizer.init)(params)

        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (1, micro_batch, seq_len)).astype(np.int32)
        sample_stack = {
            "input_ids": ids, "labels": ids.copy(),
            "positions": np.ascontiguousarray(np.broadcast_to(
                np.arange(seq_len, dtype=np.int32), ids.shape)),
            "segment_ids": np.ones_like(ids),
        }
        compiled = step.lower(params, opt_state, sample_stack).compile()
        hlo = None
        try:
            hlo = compiled.as_text()
        except Exception:  # noqa: BLE001 — costs/roofline degrade gracefully
            pass
        costs = compiled_cost_metrics(compiled, hlo_text=hlo)
        roof = roofline_metrics(costs, device_specs(jax.devices()[0].device_kind))
        attribution = compiled_memory_attribution(compiled)
        peak_gib = (round(attribution["peak_est"] / 2**30, 4)
                    if attribution else None)

        def collate(samples):
            arr = np.asarray([s["input_ids"] for s in samples], np.int32)[:, :seq_len]
            return {
                "input_ids": arr, "labels": arr.copy(),
                "positions": np.ascontiguousarray(np.broadcast_to(
                    np.arange(arr.shape[-1], dtype=np.int32), arr.shape)),
                "segment_ids": np.ones_like(arr),
            }

        ds = MockSFTDataset(vocab_size=cfg.vocab_size, seq_len=seq_len,
                            num_samples=micro_batch * (n_steps + 3), seed=0,
                            item_delay_s=0.002)
        dl = DataLoader(ds, batch_size=micro_batch, collate_fn=collate, seed=0)
        sched = StepScheduler(grad_acc_steps=1, num_epochs=1,
                              max_steps=n_steps + 1, dataloader=dl,
                              handle_sigterm=False)
        pipe = InputPipeline(
            scheduler=sched, dataloader=dl, stack_fn=stack_batches,
            put_fn=jax.device_put,
            config=PrefetchConfig(
                enabled=trial.prefetch_host_depth is not None,
                host_depth=int(trial.prefetch_host_depth or 2),
                device_depth=int(trial.prefetch_device_depth or 2)))
        try:
            first = pipe.get()
            params, opt_state, m = compiled(params, opt_state, first.stack)
            float(m["loss"])  # host sync before the clock starts
            done = 0
            t0 = time.perf_counter()
            while done < n_steps:
                item = pipe.get()
                if item is None:
                    break
                params, opt_state, m = compiled(params, opt_state, item.stack)
                done += 1
            float(m["loss"])
            dt = time.perf_counter() - t0
        finally:
            pipe.close()
        tps = round(done * micro_batch * seq_len / dt / devices, 1)
        out = {"tps": tps,
               "signals": sig.build_cell(
                   cell={"model": "dense", "seq_len": nominal_seq},
                   roofline=roof or None, costs=costs,
                   memory_plan=plan_cache.get(trial.digest()))}
        if peak_gib is not None:
            out["hbm_gib_peak"] = peak_gib
        return out

    return measure


def _tune_model_config(cpu: bool):
    """The dense cell's dims, shared by the matrix bench and the tuner (the
    tuner rebuilds the model per trial with the trial's backend knobs)."""
    from automodel_tpu.models.llama.model import LlamaConfig

    if cpu:
        return LlamaConfig(
            vocab_size=2048, hidden_size=256, intermediate_size=1024,
            num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
            head_dim=32, max_position_embeddings=512,
        )
    # Llama-3.2-1B dims
    return LlamaConfig(
        vocab_size=128256, hidden_size=2048, intermediate_size=8192,
        num_hidden_layers=16, num_attention_heads=32, num_key_value_heads=8,
        head_dim=64, rope_theta=500000.0, tie_word_embeddings=True,
        max_position_embeddings=131072,
    )


def _tune_bench(cpu: bool, out_dir: str = "tuned",
                baseline_path: str | None = None) -> dict:
    """``--tune``: a pruned, signal-ordered search over the dense smoke cell.

    Emits one ``tuner/*`` JSON row per trial as it lands (the matrix-row
    contract), an atomic resumable ``<out_dir>/tuner_report.json`` ledger, a
    ``tuner_timeline.json`` with one span per trial, the winning trial as
    ``<out_dir>/<cell>.yaml`` (loadable via the recipe's ``tuned_config``
    key), and — when ``baseline_path`` exists — merges the winning cell's
    ``tuned/<cell>/*`` metrics into it through regression.write_baseline so
    the perf gate enforces tuned numbers from then on.
    """
    import os

    import jax
    import jax.numpy as jnp
    import optax

    from automodel_tpu.observability import regression
    from automodel_tpu.observability.events import TraceTimeline
    from automodel_tpu.observability.memory_plan import build_memory_plan
    from automodel_tpu.tuning import SearchSpace, TrialLedger, run_search
    from automodel_tpu.tuning.runner import write_tuned_config

    nominal_seq = 2048
    seq_len = min(nominal_seq, 128) if cpu else nominal_seq
    devices = jax.device_count()
    mesh_name = f"{jax.devices()[0].platform}{devices}"
    cell_name = f"dense_s{nominal_seq}_{mesh_name}"

    space = (SearchSpace.smoke(micro_batch=2) if cpu else SearchSpace(
        microbatch_splits=((4, 1), (2, 2), (1, 4)),
        prefetch_depths=((2, 2), (4, 2), (4, 4)),
        layouts=("scan", "unrolled"),
    ))
    trials = space.enumerate()
    baseline_trial = trials[0]

    # pre-compile memory plans: abstract params/opt-state shapes only — a trial
    # the plan rejects never compiles. The synthetic HBM line sits at 3x the
    # baseline trial's footprint, so the deliberately oversized microbatch
    # split in the smoke space is pruned, not compiled.
    from automodel_tpu.models.common.backend import BackendConfig
    from automodel_tpu.models.llama.model import LlamaForCausalLM

    cfg = _tune_model_config(cpu)
    optimizer = optax.chain(optax.scale_by_factored_rms(), optax.scale(-1e-5))
    plan_cache: dict = {}

    def plan_for(trial, limit_gib):
        backend = BackendConfig(dtype="float32" if cpu else "bfloat16",
                                remat_policy=trial.remat_policy)
        model = LlamaForCausalLM(cfg, backend)
        aparams = model.abstract_params(jnp.dtype(backend.dtype))
        aopt = jax.eval_shape(optimizer.init, aparams)
        return build_memory_plan(
            aparams, aopt,
            micro_batch_size=int(trial.micro_batch_size or (2 if cpu else 4)),
            seq_len=seq_len,
            grad_acc_steps=int(trial.grad_acc_steps or 1),
            model_config=cfg,
            hbm_limit_override_gib=limit_gib,
        )

    base_plan = plan_for(baseline_trial, None)
    limit_gib = round(base_plan.total_bytes * 3 / 2**30, 6)

    def plan_fn(trial):
        plan = plan_for(trial, limit_gib)
        plan_cache[trial.digest()] = plan
        return plan

    # exploration order comes from the cell's analytic bound: one baseline
    # measure (compile + costs + roofline) before the search proper
    measure = _tune_measure_factory(cpu, nominal_seq, plan_cache)
    plan_cache[baseline_trial.digest()] = base_plan
    probe = measure(baseline_trial)
    bound = ((probe.get("signals") or {}).get("analytic") or {}).get("roofline_bound")

    os.makedirs(out_dir, exist_ok=True)
    report_path = os.path.join(out_dir, "tuner_report.json")
    ledger = TrialLedger(report_path,
                         cell={"model": "dense", "seq_len": nominal_seq,
                               "mesh": mesh_name},
                         bound=bound)
    timeline = TraceTimeline(os.path.join(out_dir, "tuner_timeline.json"))

    def metric_sink(row):
        print(json.dumps({"tuner_row": True, **row}), flush=True)

    result = run_search(trials, measure=measure, ledger=ledger,
                        plan_fn=plan_fn, bound=bound, baseline=baseline_trial,
                        timeline=timeline, metric_sink=metric_sink)
    timeline.close()

    winner = result["winner"]
    doc = {
        "ok": True,
        "metric": f"bench tune: pruned search over {cell_name}",
        "value": (winner["outcome"]["metrics"].get("tuner/tps")
                  if winner else None),
        "unit": "tokens/s/chip",
        "vs_baseline": None,
        "tuner": {
            "cell": cell_name,
            "bound": bound,
            "counts": result["counts"],
            "report": report_path,
            "winner": winner["digest"] if winner else None,
            "attribution": (result["attribution"] or {}).get("line"),
        },
        "extra": {"device": str(jax.devices()[0])},
    }
    if cpu:
        doc["extra"]["fallback"] = "cpu"
        doc["extra"]["measured_seq_len"] = seq_len
    if winner is None:
        doc["ok"] = False
        doc["error"] = "no trial ran to completion"
        return doc

    tuned_path = os.path.join(out_dir, f"{cell_name}.yaml")
    write_tuned_config(tuned_path, cell_name=cell_name, entry=winner,
                       attribution=result["attribution"])
    doc["tuner"]["tuned_config"] = tuned_path

    tuned_metrics = {
        f"tuned/{cell_name}/{k.rsplit('/', 1)[-1]}": v
        for k, v in winner["outcome"]["metrics"].items()
        if k in ("tuner/tps", "tuner/hbm_gib_peak")
    }
    # gate-ready form: load_run_metrics lifts these so the same stdout capture
    # that announced the winner can be gated against the merged baseline
    doc["tuner"]["metrics"] = tuned_metrics
    if baseline_path and os.path.exists(baseline_path):
        regression.write_baseline(
            baseline_path, tuned_metrics, merge=True,
            meta={"source": "bench.py --tune", "cell": cell_name,
                  "winner": winner["digest"],
                  "attribution": (result["attribution"] or {}).get("line")})
        comps = regression.compare(
            tuned_metrics,
            {k: v for k, v in regression.load_baseline(baseline_path).items()
             if k in tuned_metrics})
        doc["tuner"]["baseline"] = baseline_path
        doc["tuner"]["gate"] = ("PASS" if all(c.ok for c in comps)
                                else "FAIL")
    return doc


def _flag_value(argv: list[str], flag: str) -> str | None:
    if flag in argv:
        i = argv.index(flag)
        if i + 1 < len(argv):
            return argv[i + 1]
    return None


def _classify(text: str) -> tuple[str, bool]:
    """``(taxonomy, transient)`` for an error message / traceback tail.

    Delegates to the supervisor's classifier (resilience/supervisor.py), which
    fixes the r05 misclassification: the old substring set here matched
    "UNAVAILABLE"/"initialize backend" anywhere, so a *lowering* error whose
    message merely contained init-looking text (BENCH_r05's
    ``convert_element_type`` failure) retried and fell back to CPU as if the
    chip were absent. The classifier's non-transient markers (setup/compile
    error, lowering frames) override init-looking text — only genuinely
    transient init errors may retry or fall back."""
    from automodel_tpu.resilience.supervisor import classify_error_text

    return classify_error_text(text)


def _transient_backend_error(exc: BaseException) -> bool:
    taxonomy, transient = _classify(repr(exc))
    return transient and taxonomy in ("backend-init", "preemption")


def _init_backend(max_attempts: int = 3) -> str:
    """Attach the JAX backend with bounded retry + exponential backoff
    (``utils/retry.py`` policy curve). A TPU attach can fail transiently while
    a previous holder releases the chips ("Device or resource busy",
    UNAVAILABLE) — sleeping through the handoff beats falling straight to the
    tiny CPU bench. Only errors the taxonomy classifier marks transient retry;
    anything else is a code/compiler bug and raises immediately. On exhaustion
    the LAST named init error raises, and main() routes it into the guaranteed
    final JSON line (``fallback_reason`` on the CPU-fallback doc, or the
    ``error`` field when even that fails)."""
    from automodel_tpu.utils.retry import RetryConfig

    policy = RetryConfig(max_attempts=max_attempts, base_delay_s=1.0,
                         max_delay_s=15.0)
    last: Exception | None = None
    for attempt in range(max(int(max_attempts), 1)):
        try:
            import jax

            return jax.default_backend()  # first real backend touch
        except Exception as exc:  # noqa: BLE001 — filtered just below
            if not _transient_backend_error(exc):
                raise
            last = exc
            if attempt + 1 >= max_attempts:
                break
            d = policy.delay(attempt)
            print(
                f"bench: backend init failed (attempt {attempt + 1}/"
                f"{max_attempts}): {exc!r} — retrying in {d:.1f}s",
                file=sys.stderr,
            )
            time.sleep(d)
    assert last is not None
    raise RuntimeError(
        f"backend init failed after {max_attempts} attempts: {last!r}"
    ) from last


def _canary_dispatch() -> None:
    """One trivial jitted op through the attached backend. A backend that
    initializes but cannot execute (driver/libtpu mismatch, wedged chip) fails
    HERE — unambiguously a backend fault, whatever the exception says — instead
    of deep inside the 1B bench where it is indistinguishable from a code bug."""
    import jax
    import jax.numpy as jnp

    jax.jit(lambda x: x + 1)(jnp.arange(8)).block_until_ready()


def _spawn_cpu_fallback(reason: str, extra_args: tuple[str, ...] = ()) -> int:
    """Re-run this script with ``--cpu`` in a clean interpreter: the failed
    backend init poisoned this process's JAX state, and the axon sitecustomize
    pins jax_platforms at startup — the child both clears JAX_PLATFORMS and
    re-updates the config (the _spawn_cpu_dryrun pattern). ``extra_args``
    carries mode flags through (``--matrix``); the child's matrix rows are
    re-emitted ahead of its summary line so the parent's stdout keeps the
    one-line-per-row contract."""
    import os
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = ""
    try:
        result = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu", *extra_args],
            env=env, capture_output=True, text=True, timeout=1800,
        )
    except subprocess.TimeoutExpired:
        sys.stderr.flush()
        print(json.dumps({"ok": False, "error": f"cpu fallback timed out; primary: {reason}"}),
              flush=True)
        return 1
    sys.stderr.write(result.stderr)
    sys.stderr.flush()
    docs = []
    for line in result.stdout.splitlines():
        try:
            doc = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(doc, dict):
            docs.append(doc)
    final = next((d for d in reversed(docs) if "ok" in d), None)
    for doc in docs:
        if doc is not final:
            print(json.dumps(doc), flush=True)
    if final is not None:
        final.setdefault("extra", {})["fallback_reason"] = reason
        print(json.dumps(final), flush=True)
        return 0 if final.get("ok") else 1
    print(json.dumps({
        "ok": False,
        "error": f"cpu fallback rc={result.returncode} with no JSON line; primary: {reason}",
    }), flush=True)
    return 1


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    matrix = "--matrix" in argv
    # --dynamics: build the measured step with the per-subtree telemetry
    # reductions in-graph, proving the overhead stays inside the gate
    # tolerance instead of asserting it (docs/observability.md)
    dynamics = "--dynamics" in argv
    # --profile: one traced step per matrix cell -> measured_* gate keys +
    # the signals bundle on the summary doc (matrix mode only)
    profile = "--profile" in argv
    # --tune: the perf-lab loop closes — pruned, signal-ordered search over
    # the dense smoke cell with an auditable resumable ledger (tuning/)
    tune = "--tune" in argv
    tune_dir = _flag_value(argv, "--tune-dir") or "tuned"
    tune_baseline = _flag_value(argv, "--tune-baseline")
    # --ledger RUN_DIR|run_ledger.json: merge the run-lifetime goodput ledger
    # (observability/runledger.py) into the summary doc as gate-able
    # goodput_e2e / badput/* / wasted_steps / recovery_s keys, so one capture
    # gates throughput AND recovery cost (docs/observability.md)
    ledger_path = _flag_value(argv, "--ledger")

    def _emit_doc(doc: dict) -> None:
        if ledger_path:
            try:
                from automodel_tpu.observability import runledger

                doc["ledger"] = runledger.gate_metrics(
                    runledger.load_ledger(ledger_path))
            except Exception as exc:  # noqa: BLE001 — a bad ledger must not
                # sink the bench line; the error is named instead
                doc.setdefault("extra", {})["ledger_error"] = repr(exc)
        print(json.dumps(doc), flush=True)
    # matrix isolation knobs (resilience/harness.py)
    matrix_dir = _flag_value(argv, "--matrix-dir") or "bench_matrix"
    resume = "--resume" in argv
    cell_timeout_s = float(_flag_value(argv, "--cell-timeout") or 900.0)
    cell_retries = int(_flag_value(argv, "--cell-retries") or 1)
    isolate = "--no-isolate" not in argv
    mode_args = (("--matrix",) if matrix else ()) + (
        ("--dynamics",) if dynamics else ()) + (
        ("--profile",) if profile else ()) + (
        ("--tune", "--tune-dir", tune_dir) if tune else ()) + (
        ("--tune-baseline", tune_baseline) if tune and tune_baseline else ()) + (
        # isolation knobs forward only when explicitly given — the fallback
        # child keeps its own defaults otherwise
        tuple(f for f in ("--resume", "--no-isolate") if f in argv)) + (
        ("--matrix-dir", matrix_dir)
        if _flag_value(argv, "--matrix-dir") else ()) + (
        ("--cell-timeout", str(cell_timeout_s))
        if _flag_value(argv, "--cell-timeout") else ())
    cell = _flag_value(argv, "--cell")
    if "--preflight" in argv or cell:
        # child modes for the per-cell harness: run in THIS process (the
        # harness already isolated us), keep the one-JSON-line contract
        try:
            if "--cpu" in argv:
                import jax

                jax.config.update("jax_platforms", "cpu")
            if "--preflight" in argv:
                from automodel_tpu.resilience.harness import preflight_probe

                doc = preflight_probe()
            else:
                doc = _cell_main(cell, cpu="--cpu" in argv,
                                 dynamics=dynamics, profile=profile)
            print(json.dumps(doc), flush=True)
            return 0 if doc.get("ok") else 1
        except Exception as exc:  # noqa: BLE001 — taxonomy-labeled final line
            import traceback

            tail = traceback.format_exc()[-2000:]
            taxonomy, transient = _classify(repr(exc) + "\n" + tail)
            sys.stderr.write(tail)
            sys.stderr.flush()
            print(json.dumps({"ok": False, "error": repr(exc),
                              "taxonomy": taxonomy, "transient": transient,
                              "tail": tail}), flush=True)
            return 1

    def _matrix(cpu: bool) -> dict:
        if not isolate:
            return _matrix_bench_inline(cpu=cpu, dynamics=dynamics,
                                        profile=profile)
        return _matrix_bench(cpu=cpu, dynamics=dynamics, profile=profile,
                             out_dir=matrix_dir, resume=resume,
                             cell_timeout_s=cell_timeout_s,
                             cell_retries=cell_retries)

    if "--cpu" in argv:
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
            if tune:
                doc = _tune_bench(cpu=True, out_dir=tune_dir,
                                  baseline_path=tune_baseline)
            else:
                doc = (_matrix(cpu=True)
                       if matrix else _cpu_fallback_bench(dynamics=dynamics))
            _emit_doc(doc)
            return 0 if doc.get("ok") else 1
        except Exception as exc:  # noqa: BLE001 — the JSON contract is the point
            sys.stderr.flush()
            print(json.dumps({"ok": False, "error": repr(exc)}), flush=True)
            return 1
    try:
        # retried attach: a transient init failure (chip handoff, UNAVAILABLE)
        # gets backoff before the exception routes to the CPU fallback below
        backend = _init_backend()
        import jax

        if backend == "cpu":
            # TPU-less host with a working CPU backend: the full 1B bench
            # would grind for hours — go straight to the tiny fallback.
            print("bench: no accelerator attached; running tiny CPU fallback",
                  file=sys.stderr)
            if tune:
                doc = _tune_bench(cpu=True, out_dir=tune_dir,
                                  baseline_path=tune_baseline)
            else:
                doc = (_matrix(cpu=True)
                       if matrix else _cpu_fallback_bench(dynamics=dynamics))
            doc.setdefault("extra", {})["fallback_reason"] = "default backend is cpu"
            _emit_doc(doc)
            return 0 if doc.get("ok") else 1
        try:
            _canary_dispatch()
        except Exception as exc:  # noqa: BLE001 — any canary failure is a backend fault
            reason = f"first-dispatch canary failed: {exc!r}"
            print(f"bench: {reason}; retrying on CPU", file=sys.stderr)
            return _spawn_cpu_fallback(reason, extra_args=mode_args)
        if tune:
            doc = _tune_bench(cpu=False, out_dir=tune_dir,
                              baseline_path=tune_baseline)
        else:
            doc = (_matrix(cpu=False)
                   if matrix else _full_bench(dynamics=dynamics))
        _emit_doc(doc)
        return 0 if doc.get("ok") else 1
    except Exception as exc:  # noqa: BLE001
        import traceback

        reason = repr(exc)
        taxonomy, transient = _classify(
            reason + "\n" + traceback.format_exc()[-2000:])
        if transient and taxonomy in ("backend-init", "preemption"):
            print(f"bench: backend unavailable ({reason}); retrying on CPU",
                  file=sys.stderr)
            return _spawn_cpu_fallback(reason, extra_args=mode_args)
        sys.stderr.flush()
        # satellite contract (BENCH_r05): the final line names the failure
        # class and carries the real traceback tail, not just the repr
        print(json.dumps({"ok": False, "error": reason, "taxonomy": taxonomy,
                          "tail": traceback.format_exc()[-2000:]}), flush=True)
        return 1


def run_cli(argv: list[str] | None = None) -> int:
    """main() inside the last line of defense for the JSON contract: whatever
    escapes — KeyboardInterrupt, SystemExit from a library, MemoryError —
    still ends stdout with one parseable line instead of a bare traceback
    (BENCH_r05). Split from ``__main__`` so tests can drive the guard
    in-process."""
    try:
        return main(argv)
    except BaseException as exc:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        sys.stderr.flush()
        print(json.dumps({"ok": False, "error": repr(exc)}), flush=True)
        return 1


if __name__ == "__main__":
    sys.exit(run_cli())
