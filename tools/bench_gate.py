#!/usr/bin/env python
"""Perf-regression gate CLI (docs/observability.md "Perf-regression gate").

Compares a run artifact — a recipe ``training.jsonl``, a ``benchmark.json``,
or the single JSON line ``bench.py`` prints — against a committed baseline
with per-metric tolerances, and exits non-zero on regression::

    python tools/bench_gate.py --run out/training.jsonl --baseline baselines/v5e.json
    python tools/bench_gate.py --run out/training.jsonl --baseline b.json --write-baseline

Thin wrapper over :mod:`automodel_tpu.observability.regression` so the gate is
importable in tests and callable from CI without a package install.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from automodel_tpu.observability.regression import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
