"""Optimizer construction (reference recipes/llm/train_ft.py:275 build_optimizer).

Params stay fp32 (the master copy); the model casts to bf16 at use. optax keeps
moments in fp32 alongside — the same mixed-precision contract as the reference's
FSDP2 mp_policy (bf16 compute / fp32 params+grads, distributed/config.py:74-81) with
none of the wrapping ceremony.

Weight decay is masked off 1-D params (norm scales, biases) matching standard HF
finetune behavior.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import optax

__all__ = ["build_optimizer", "first_moment_tree", "no_decay_mask"]


def first_moment_tree(opt_state: Any) -> Any:
    """First first-moment accumulator in an optax state tree, or None.

    The dynamics pillar (observability/dynamics.py) reports a per-subtree
    ``moment_norm``, which needs the optimizer's own view of the gradient
    trend: walk the chain's state tuples breadth-first for a pytree-valued
    field named ``mu`` (the adam families, including
    :func:`low_mem_scale_by_adam`'s bf16 state) or ``trace`` (momentum SGD,
    :func:`int8_trace`). Optimizers without a moment (adafactor, plain sgd)
    return None and the telemetry row simply omits the metric. Works inside
    jit — it only rearranges tree references, no value ops.
    """
    stack = [opt_state]
    while stack:
        node = stack.pop(0)
        for field in ("mu", "trace"):
            sub = getattr(node, field, None)
            if sub is not None and not hasattr(sub, "dtype"):
                return sub
        if isinstance(node, (tuple, list)):
            stack.extend(node)
    return None


def no_decay_mask(params: Any) -> Any:
    """True where weight decay applies (rank >= 2 tensors only).

    Layer-stacked params have a leading L dim, so the cutoff is rank >= 3 for
    stacked leaves; top-level embed/lm_head are rank 2; norms/biases stacked are
    rank 2 or 1 — decide by trailing dims instead: decay iff the *per-layer* rank
    (total rank minus the stack dim for leaves under "layers") is >= 2.
    """

    def mask_tree(tree, under_layers=False):
        out = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                out[k] = mask_tree(v, under_layers or k == "layers")
            else:
                # getattr: robust under optax multi_transform MaskedNode leaves
                rank = getattr(v, "ndim", 0) - (1 if under_layers else 0)
                out[k] = rank >= 2
        return out

    return mask_tree(params)


def low_mem_scale_by_adam(
    b1: float, b2: float, eps: float,
    mu_dtype=jax.numpy.bfloat16, nu_dtype=jax.numpy.bfloat16,
) -> optax.GradientTransformation:
    """Adam moment tracking with reduced-precision state (bf16 mu AND nu).

    optax.scale_by_adam only casts mu; the fp32 nu is the single largest
    optimizer tensor (4 bytes/param). Storing both moments bf16 halves+ the
    optimizer footprint; the update math runs in fp32 (moments are decayed
    running averages — bf16's ~3 significant digits cost far less than the
    gradient noise they smooth). The freed HBM buys lighter remat policies,
    which is where the throughput actually comes from."""
    import jax.numpy as jnp

    def init(params):
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(lambda p: jnp.zeros_like(p, dtype=mu_dtype), params),
            nu=jax.tree.map(lambda p: jnp.zeros_like(p, dtype=nu_dtype), params),
        )

    def update(grads, state, params=None):
        del params
        count = state.count + 1
        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** count.astype(jnp.float32)

        def moments(g, mu, nu):
            g32 = g.astype(jnp.float32)
            mu32 = b1 * mu.astype(jnp.float32) + (1 - b1) * g32
            nu32 = b2 * nu.astype(jnp.float32) + (1 - b2) * g32 * g32
            upd = (mu32 / bc1) / (jnp.sqrt(nu32 / bc2) + eps)
            return {"u": upd.astype(g.dtype), "mu": mu32.astype(mu_dtype), "nu": nu32.astype(nu_dtype)}

        out = jax.tree.map(moments, grads, state.mu, state.nu)
        is_res = lambda x: isinstance(x, dict) and set(x) == {"u", "mu", "nu"}
        pick = lambda k: jax.tree.map(lambda o: o[k], out, is_leaf=is_res)
        return pick("u"), optax.ScaleByAdamState(count=count, mu=pick("mu"), nu=pick("nu"))

    return optax.GradientTransformation(init, update)


def int8_trace(decay: float, block: int = 256) -> optax.GradientTransformation:
    """Momentum with an int8 blockwise-quantized accumulator (the 8-bit-optimizer
    recipe: per-``block`` absmax scales keep quantization error local, reference
    gets the same from bitsandbytes-backed torch optimizers).

    Halves the bf16 ``optax.trace`` footprint to ~1 byte/param; on a 16GB chip
    that is the difference between remat policies — worth far more throughput
    than the momentum LSBs (the accumulator already smooths gradient noise much
    larger than the ~0.4% blockwise rounding)."""
    import jax.numpy as jnp

    def _quant(x):
        flat = x.reshape(-1).astype(jnp.float32)
        pad = (-flat.size) % block
        blocks = jnp.pad(flat, (0, pad)).reshape(-1, block)
        scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 127.0
        q = jnp.round(blocks / jnp.maximum(scale, 1e-20)).astype(jnp.int8)
        return {"q": q, "scale": scale}

    def _dequant(s, shape):
        flat = (s["q"].astype(jnp.float32) * s["scale"]).reshape(-1)
        size = 1
        for d in shape:
            size *= d
        return flat[:size].reshape(shape)

    def init(params):
        return jax.tree.map(lambda p: _quant(jnp.zeros_like(p, jnp.float32)), params)

    def update(updates, state, params=None):
        del params
        # state slots are {"q","scale"} dicts (a deeper structure than updates),
        # so pair them via flatten_up_to rather than tree.map
        flat_u, treedef = jax.tree.flatten(updates)
        flat_s = treedef.flatten_up_to(state)
        mom = [decay * _dequant(s, u.shape) + u.astype(jnp.float32)
               for u, s in zip(flat_u, flat_s)]
        new_state = treedef.unflatten([_quant(m) for m in mom])
        out = treedef.unflatten([m.astype(u.dtype) for m, u in zip(mom, flat_u)])
        return out, new_state

    return optax.GradientTransformation(init, update)


def build_optimizer(
    lr: float | Callable[[int], float],
    weight_decay: float = 0.0,
    betas: tuple[float, float] = (0.9, 0.95),
    eps: float = 1e-8,
    max_grad_norm: float | None = None,
    optimizer: str = "adamw",
    **optimizer_kwargs,
) -> optax.GradientTransformation:
    """AdamW (or SGD/adafactor/low-mem AdamW) with decay masking and global-norm clip.

    Note: when grads are pre-normalized by global num_label_tokens (the recipe's
    contract), clipping here operates on that normalized gradient, matching the
    reference's scale-then-clip order (training/utils.py:276).
    """
    chain = []
    if max_grad_norm is not None and max_grad_norm > 0:
        chain.append(optax.clip_by_global_norm(max_grad_norm))
    if optimizer == "adamw_lowmem":
        chain.append(low_mem_scale_by_adam(b1=betas[0], b2=betas[1], eps=eps))
        if weight_decay:
            chain.append(optax.add_decayed_weights(weight_decay, mask=no_decay_mask))
        chain.append(optax.scale_by_learning_rate(lr))
    elif optimizer == "adafactor_momentum":
        # factored second moment (rows+cols instead of a full tensor: ~zero HBM)
        # + bf16 momentum — the lightest stateful optimizer here. The ~2.5GB it
        # frees vs even bf16-nu adam buys remat_policy "mlp_dots" on memory-tight
        # configs, which is worth far more throughput than the moment precision.
        # betas -> (momentum decay, second-moment decay); eps is NOT wired: the
        # factored-rms epsilon (1e-30 inside the rms) has different semantics
        # than adam's denominator eps and its default is the right one
        chain.append(optax.scale_by_factored_rms(decay_rate=betas[1]))
        chain.append(optax.trace(decay=betas[0], accumulator_dtype=jax.numpy.bfloat16))
        if weight_decay:
            chain.append(optax.add_decayed_weights(weight_decay, mask=no_decay_mask))
        chain.append(optax.scale_by_learning_rate(lr))
    elif optimizer == "adafactor_nomom":
        # momentum-free factored rms — pure Adafactor a la T5/PaLM. ~Zero
        # optimizer state: on a 16GB chip this affords remat "mlp_attn_dots"
        # (bench.py: 13.2k tok/s / 55% MFU on the 1B SFT shape)
        chain.append(optax.scale_by_factored_rms(decay_rate=betas[1]))
        if weight_decay:
            chain.append(optax.add_decayed_weights(weight_decay, mask=no_decay_mask))
        chain.append(optax.scale_by_learning_rate(lr))
    elif optimizer == "adafactor_momentum8":
        # adafactor_momentum with the momentum itself int8-blockwise quantized:
        # the lightest optimizer state here (~1 byte/param total)
        chain.append(optax.scale_by_factored_rms(decay_rate=betas[1]))
        chain.append(int8_trace(decay=betas[0]))
        if weight_decay:
            chain.append(optax.add_decayed_weights(weight_decay, mask=no_decay_mask))
        chain.append(optax.scale_by_learning_rate(lr))
    elif optimizer == "adamw":
        chain.append(
            optax.adamw(
                learning_rate=lr, b1=betas[0], b2=betas[1], eps=eps,
                weight_decay=weight_decay,
                mask=no_decay_mask if weight_decay else None,
            )
        )
    elif optimizer == "adam":
        chain.append(optax.adam(learning_rate=lr, b1=betas[0], b2=betas[1], eps=eps))
    elif optimizer == "sgd":
        chain.append(optax.sgd(learning_rate=lr, momentum=betas[0]))
    elif optimizer == "adafactor":
        chain.append(optax.adafactor(learning_rate=lr))
    elif optimizer == "dion":
        from automodel_tpu.optim.dion import build_dion_optimizer

        # clipping is handled inside (before the split transform); extra YAML keys
        # (mu, rank_fraction, adamw_lr_scale) pass straight through
        return build_dion_optimizer(
            lr, weight_decay=weight_decay, b1=betas[0], b2=betas[1], eps=eps,
            max_grad_norm=max_grad_norm, **optimizer_kwargs,
        )
    else:
        raise ValueError(f"unknown optimizer {optimizer!r}")
    if optimizer_kwargs:
        raise ValueError(f"unknown optimizer kwargs for {optimizer!r}: {sorted(optimizer_kwargs)}")
    return optax.chain(*chain)
