from automodel_tpu.models.step3p5.model import Step3p5Config, Step3p5ForCausalLM

__all__ = ["Step3p5Config", "Step3p5ForCausalLM"]
