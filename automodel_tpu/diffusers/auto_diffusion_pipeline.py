"""Diffusion pipeline stub (reference _diffusers/auto_diffusion_pipeline.py:79).

The reference exposes a minimal ``NeMoAutoDiffusionPipeline.from_pretrained`` that
loads a Hugging Face diffusers pipeline with device/dtype placement and nothing
else; diffusion *training* is out of scope there too. This mirrors that surface:
a thin loader that defers to ``diffusers`` when installed (it is not part of the
baked TPU image) and otherwise fails with a clear message.
"""

from __future__ import annotations

from typing import Any

__all__ = ["AutoDiffusionPipeline"]


class AutoDiffusionPipeline:
    """Minimal diffusers loader (reference NeMoAutoDiffusionPipeline)."""

    @staticmethod
    def from_pretrained(
        pretrained_model_name_or_path: str,
        dtype: Any = None,
        device: Any = None,
        **kwargs,
    ):
        try:
            import diffusers  # noqa: PLC0415
        except ModuleNotFoundError as e:  # pragma: no cover - env without diffusers
            raise ModuleNotFoundError(
                "AutoDiffusionPipeline requires the `diffusers` package, which is "
                "not part of the TPU image; install it to load diffusion pipelines"
            ) from e
        pipe = diffusers.DiffusionPipeline.from_pretrained(
            pretrained_model_name_or_path, torch_dtype=dtype, **kwargs
        )
        if device is not None:
            pipe = pipe.to(device)
        return pipe
