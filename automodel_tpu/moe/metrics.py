"""MoE expert-load-balance metrics (reference components/moe/load_balance_metrics.py).

The reference hooks Gate modules to stash per-layer loads and all-reduces them over dp;
here :func:`moe_forward` already returns per-layer ``expert_load`` arrays (globally
summed under pjit), so metrics are pure post-processing of a stacked (L, E) array.
"""

from __future__ import annotations

import numpy as np

__all__ = ["compute_load_balance_metrics"]


def compute_load_balance_metrics(
    expert_loads: np.ndarray,  # (L, E) tokens routed per expert per MoE layer
    *,
    mode: str = "brief",
    top_k_experts: int = 5,
    prefix: str = "moe_load",
) -> dict[str, float]:
    """Scalar metrics dict for the metric logger / wandb.

    Utilization ratio = load / ideal (ideal = mean over experts); 1.0 is perfect
    balance, > 1 overloaded (reference _compute_expert_utilization semantics).
    ``brief`` emits aggregates + global top/bottom-k; ``detailed`` adds per-layer stats.
    """
    loads = np.asarray(expert_loads, np.float64)
    if loads.ndim == 1:
        loads = loads[None]
    L, E = loads.shape
    ideal = loads.mean(axis=1, keepdims=True)  # (L, 1)
    util = np.divide(loads, ideal, out=np.ones_like(loads), where=ideal > 0)

    per_layer_max = util.max(axis=1)
    per_layer_min = util.min(axis=1)
    per_layer_std = util.std(axis=1)
    zero_frac = (loads == 0).mean(axis=1)

    metrics = {
        f"{prefix}/max_util_mean": float(per_layer_max.mean()),
        f"{prefix}/max_util_max": float(per_layer_max.max()),
        f"{prefix}/min_util_mean": float(per_layer_min.mean()),
        f"{prefix}/util_std_mean": float(per_layer_std.mean()),
        f"{prefix}/zero_expert_frac": float(zero_frac.mean()),
    }

    mean_util = util.mean(axis=0)  # (E,) average across layers
    order = np.argsort(mean_util)
    k = min(top_k_experts, E)
    for rank, e in enumerate(order[::-1][:k]):
        metrics[f"{prefix}/top{rank}_expert{e}_util"] = float(mean_util[e])
    for rank, e in enumerate(order[:k]):
        metrics[f"{prefix}/bottom{rank}_expert{e}_util"] = float(mean_util[e])

    if mode == "detailed":
        for layer in range(L):
            metrics[f"{prefix}/layer{layer}/max_util"] = float(per_layer_max[layer])
            metrics[f"{prefix}/layer{layer}/min_util"] = float(per_layer_min[layer])
            metrics[f"{prefix}/layer{layer}/util_std"] = float(per_layer_std[layer])
    return metrics
