"""Generation recipe end-to-end: finetune-to-sample without leaving the
framework — `automodel generate llm -c cfg.yaml` over an HF checkpoint dir."""

import json
import textwrap

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

# heavyweight torch-parity leg: HF checkpoint round-trips + sampling loops.
# Out of the tier-1 budget; CI's functional job opts back in with -m ""
pytestmark = pytest.mark.slow


class IntTokenizer:
    """Whitespace integer tokenizer: encode('5 9') == [5, 9]."""

    eos_token_id = 1
    bos_token_id = None
    pad_token_id = 0

    def encode(self, text, add_special_tokens=True):
        return [int(t) for t in text.split()]

    def decode(self, ids):
        return " ".join(str(int(i)) for i in ids)


@pytest.fixture(scope="module")
def tiny_hf_dir(tmp_path_factory):
    cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(cfg).eval()
    d = tmp_path_factory.mktemp("hf_model")
    hf.save_pretrained(str(d), safe_serialization=True)
    return str(d), hf


def test_generate_recipe_end_to_end(tmp_path, tiny_hf_dir, cpu_devices):
    d, hf = tiny_hf_dir
    cfg_text = f"""
    model:
      pretrained_model_name_or_path: {d}
    backend:
      dtype: float32
    tokenizer:
      _target_: tests.functional.test_generate_recipe.IntTokenizer
    generation:
      max_new_tokens: 6
      temperature: 0.0
      cache_dtype: float32
    prompts:
      - "5 9 11 40"
      - "17 3"
    output_file: {tmp_path}/completions.jsonl
    """
    p = tmp_path / "gen.yaml"
    p.write_text(textwrap.dedent(cfg_text))

    from automodel_tpu.cli.app import main as cli_main

    results = cli_main(["generate", "llm", "-c", str(p)])
    assert len(results) == 2 and all(r["completion"] for r in results)

    # greedy parity vs HF generate for the first (longest) prompt
    with torch.no_grad():
        theirs = hf.generate(
            input_ids=torch.tensor([[5, 9, 11, 40]]), max_new_tokens=6,
            do_sample=False, pad_token_id=0, eos_token_id=1,
        )[0, 4:].numpy()
    n = len(results[0]["completion"].split())
    ours = np.asarray([int(t) for t in results[0]["completion"].split()])
    np.testing.assert_array_equal(ours, theirs[:n])

    rows = [json.loads(l) for l in open(tmp_path / "completions.jsonl")]
    assert rows[0]["prompt"] == "5 9 11 40"
    assert rows[0]["new_tokens"] == n


def test_generate_recipe_prompts_file(tmp_path, tiny_hf_dir, cpu_devices):
    d, _ = tiny_hf_dir
    pf = tmp_path / "prompts.txt"
    pf.write_text("4 4 4\n8 8\n")
    cfg_text = f"""
    model:
      pretrained_model_name_or_path: {d}
    backend: {{dtype: float32}}
    tokenizer:
      _target_: tests.functional.test_generate_recipe.IntTokenizer
    generation: {{max_new_tokens: 3, temperature: 0.0, cache_dtype: float32}}
    prompts_file: {pf}
    """
    p = tmp_path / "gen.yaml"
    p.write_text(textwrap.dedent(cfg_text))
    from automodel_tpu.recipes.llm.generate import main

    results = main(argv=["-c", str(p)])
    assert len(results) == 2
