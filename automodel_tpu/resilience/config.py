"""Fault-tolerance configuration (docs/resilience.md).

One ``resilience:`` YAML section drives the whole subsystem — anomaly
detection thresholds, the skip→rollback→abort escalation budget, preemption
grace deadlines, transient-I/O retry tuning, and the fault-injection harness.
Absent section = subsystem off (the seed's crash-on-first-NaN behavior is
preserved for configs that never opt in).

.. code-block:: yaml

    resilience:
      enabled: true
      anomaly:
        window: 50              # rolling loss/grad-norm window
        min_history: 12         # observations before z-scores fire
        zscore_threshold: 6.0   # loss z-score that triggers recovery
        grad_norm_threshold: null   # optional absolute grad-norm ceiling
      rollback:
        max_rollbacks: 3        # within budget_steps; then abort
        budget_steps: 200       # clean steps that reset the rollback count
        skip_steps: 1           # extra optimizer steps of data skipped past the anomaly
      max_skipped_updates: 3    # consecutive guarded skips before rollback
      preemption:
        grace_period_s: 300     # what the platform grants after SIGTERM
        export_min_grace_s: 60  # skip consolidated HF export when remaining < this
      retry: {max_attempts: 3, base_delay_s: 0.5}
      chaos: {enabled: false}   # fault injection (resilience/chaos.py)
"""

from __future__ import annotations

import dataclasses
from typing import Any

from automodel_tpu.utils.retry import RetryConfig

__all__ = ["AnomalyConfig", "ElasticConfig", "RollbackConfig", "PreemptionConfig",
           "ResilienceConfig"]


def _sub(raw: Any) -> dict:
    if raw is None:
        return {}
    if hasattr(raw, "to_dict"):
        raw = raw.to_dict()
    return dict(raw)


def _known(cls, d: dict) -> dict:
    names = {f.name for f in dataclasses.fields(cls)}
    return {k: v for k, v in d.items() if k in names}


@dataclasses.dataclass
class AnomalyConfig:
    enabled: bool = True
    window: int = 50
    min_history: int = 12
    zscore_threshold: float = 6.0
    grad_norm_threshold: float | None = None


@dataclasses.dataclass
class RollbackConfig:
    enabled: bool = True
    max_rollbacks: int = 3
    budget_steps: int = 200
    skip_steps: int = 1  # extra optimizer steps of data skipped past the anomaly


@dataclasses.dataclass
class PreemptionConfig:
    grace_period_s: float = 300.0
    export_min_grace_s: float = 60.0


@dataclasses.dataclass
class ElasticConfig:
    """Mesh-shape-agnostic restore (docs/resilience.md "Elastic restore").

    ``enabled`` gates the elastic resume path in the recipe (topology-aware
    checkpoints are always written — they cost one JSON key); ``allow_joiners``
    lets a host with no local checkpoint view abstain from the pod-agreed
    restore step instead of forcing a fresh run (join/leave)."""

    enabled: bool = True
    allow_joiners: bool = True


@dataclasses.dataclass
class ResilienceConfig:
    enabled: bool = True
    anomaly: AnomalyConfig = dataclasses.field(default_factory=AnomalyConfig)
    rollback: RollbackConfig = dataclasses.field(default_factory=RollbackConfig)
    preemption: PreemptionConfig = dataclasses.field(default_factory=PreemptionConfig)
    retry: RetryConfig = dataclasses.field(default_factory=RetryConfig)
    elastic: ElasticConfig = dataclasses.field(default_factory=ElasticConfig)
    max_skipped_updates: int = 3
    chaos: dict = dataclasses.field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Any) -> "ResilienceConfig":
        """``resilience:`` YAML section -> config; ``None`` -> disabled."""
        if raw is None:
            return cls(enabled=False)
        d = _sub(raw)
        return cls(
            enabled=bool(d.get("enabled", True)),
            anomaly=AnomalyConfig(**_known(AnomalyConfig, _sub(d.get("anomaly")))),
            rollback=RollbackConfig(**_known(RollbackConfig, _sub(d.get("rollback")))),
            preemption=PreemptionConfig(**_known(PreemptionConfig, _sub(d.get("preemption")))),
            retry=RetryConfig.from_dict(d.get("retry")),
            elastic=ElasticConfig(**_known(ElasticConfig, _sub(d.get("elastic")))),
            max_skipped_updates=int(d.get("max_skipped_updates", 3)),
            chaos=_sub(d.get("chaos")),
        )
