"""OpenAI-format chat dataset (reference datasets/llm/chat_dataset.py ChatDataset).

Rows hold a ``messages`` list (`[{"role": ..., "content": ...}, ...]`); tokenization
goes through the tokenizer's chat template with loss restricted to assistant spans
(data/llm/formatting.py). Accepts local json/jsonl files or HF dataset ids.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

from automodel_tpu.data.llm.column_mapped import _load_rows
from automodel_tpu.data.llm.formatting import format_chat_messages

__all__ = ["ChatDataset"]

_VALID_ROLES = {"system", "user", "assistant", "tool"}


def _normalize_messages(messages: Sequence[Mapping[str, Any]]) -> list[dict[str, Any]]:
    out = []
    for m in messages:
        role = m.get("role")
        if role not in _VALID_ROLES:
            raise ValueError(f"invalid chat role {role!r}")
        msg = dict(m)
        if role in ("system", "user", "assistant") and not isinstance(m.get("content"), str):
            msg["content"] = "" if m.get("content") is None else str(m["content"])
        out.append(msg)
    return out


class ChatDataset:
    def __init__(
        self,
        path_or_dataset_id: str,
        tokenizer=None,
        split: str | None = None,
        messages_column: str = "messages",
        limit_dataset_samples: int | None = None,
        answer_only_loss: bool = True,
    ):
        self.rows = _load_rows(path_or_dataset_id, split)
        if limit_dataset_samples:
            self.rows = self.rows[:limit_dataset_samples]
        self.tokenizer = tokenizer
        self.messages_column = messages_column
        self.answer_only = answer_only_loss

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, i: int) -> dict[str, Any]:
        if self.tokenizer is None:
            raise ValueError("tokenizer required to materialize chat examples")
        messages = _normalize_messages(self.rows[i][self.messages_column])
        return format_chat_messages(self.tokenizer, messages, self.answer_only)
